"""Scalable spanning collectives: the scheduled inter-process
algorithms of ``coll/hier_schedules.py`` and their integration in
``coll/hier.py``.

Three layers:

1. The LOCKSTEP SIMULATOR (``ompi_release_tpu.testing.lockstep`` —
   first-class since the fleet-sim PR; the fleet-scale harness in
   ``testing/fleet_sim.py`` shares its adapter contract) drives the
   pure schedules with P threads and per-(src, dst) FIFO queues — the
   exact transport contract the real ``_XchgAdapter`` provides — so
   the bitwise-parity matrix runs the whole (P, op, dtype, algorithm)
   cross product in milliseconds, device- and process-free.
2. Selection-unit tests for ``pick`` (forcing > rules > fixed
   constants, the non-commutative downgrades) and the pair-op payload
   packing.
3. Real 3-process ``tpurun`` Job tests per schedule family, a
   leader-tier job over a faked two-host topology, and a
   hang-injection job proving the watchdog postmortem names the
   stalled round, its algorithm, and the awaited ring neighbor.

Parity discipline: every schedule's combine order is fixed and
process-index-derived, so results are bitwise-identical to the linear
path for every order-invariant case (integer dtypes; MIN/MAX/BAND on
any dtype; ``recursive_doubling`` and ``linear`` ALWAYS, including
non-commutative ops — they fold once, in index order). ``ring`` /
``rabenseifner`` re-associate float sums by construction (rotated /
halving chunk folds), so float32 SUM under them is compared to tight
tolerance; everything else in the matrix is bitwise.
"""

import json
import os
import sys
import textwrap

import numpy as np
import pytest

from ompi_release_tpu import ops
import ompi_release_tpu.coll.components  # noqa: F401  (registers the
# coll_tuned_* cvars and the plain rule namespaces the shipped rules
# file also uses)
from ompi_release_tpu.coll import hier_schedules as hs
from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.runtime.state import JobState
from ompi_release_tpu.testing.lockstep import simulate
from ompi_release_tpu.tools.tpurun import Job
from ompi_release_tpu.utils.errors import MPIError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _linear_fold(parts, op):
    acc = parts[0]
    for nxt in parts[1:]:
        acc = np.asarray(op(acc, nxt))
    return acc


PROC_SETS = ([3, 9], [0, 1, 5], [2, 4, 6, 8], [1, 2, 3, 5, 7],
             list(range(8)))


class TestAllreduceParityMatrix:
    """Every allreduce schedule vs the linear process-index fold."""

    OPS = [(ops.SUM, "sum"), (ops.PROD, "prod"), (ops.MAX, "max"),
           (ops.MIN, "min"), (ops.BAND, "band")]

    @pytest.mark.parametrize("procs", PROC_SETS,
                             ids=lambda p: f"P{len(p)}")
    @pytest.mark.parametrize("dtype", [np.int32, np.float32])
    def test_matrix(self, procs, dtype):
        rng = np.random.RandomState(len(procs))
        P = len(procs)
        for op, opname in self.OPS:
            if opname == "band" and dtype is np.float32:
                continue
            if opname == "prod":
                data = {p: rng.randint(1, 4, 13).astype(dtype)
                        for p in procs}
            else:
                data = {p: (rng.randint(1, 100, 13)).astype(dtype)
                        for p in procs}
            want = _linear_fold([data[p] for p in procs], op)
            npop = lambda a, b: np.asarray(op(a, b))  # noqa: E731
            ident = op.identity_for(dtype)

            for alg in ("ring", "rabenseifner"):
                fn = (hs.allreduce_ring if alg == "ring"
                      else hs.allreduce_rabenseifner)
                out = simulate(procs, lambda x, p: fn(
                    x, procs, p, data[p], npop, ident))
                for p in procs:
                    got = np.asarray(out[p]).astype(dtype)
                    if dtype is np.float32 and opname in ("sum", "prod"):
                        np.testing.assert_allclose(got, want, rtol=1e-6)
                    else:  # order-invariant: bitwise
                        np.testing.assert_array_equal(
                            got, want, err_msg=f"{alg}/{opname}/P={P}")

            # recursive_doubling folds ONCE in index order: bitwise vs
            # linear for every op — including this non-commutative one
            out = simulate(procs, lambda x, p: _linear_fold(
                hs.allgather_bruck(x, procs, p, data[p], [13] * P), op))
            for p in procs:
                np.testing.assert_array_equal(
                    np.asarray(out[p]).astype(dtype), want,
                    err_msg=f"recursive_doubling/{opname}/P={P}")

    @pytest.mark.parametrize("procs", PROC_SETS,
                             ids=lambda p: f"P{len(p)}")
    def test_noncommutative_exact_via_recursive_doubling(self, procs):
        """a - b is non-commutative AND non-associative; the
        doubling-allgather + ordered local fold must still match the
        linear fold bitwise (the exact-order fallback path)."""
        sub = ops.user_op("sub_t", lambda a, b: a - b, commute=False)
        rng = np.random.RandomState(7)
        data = {p: rng.randint(0, 50, 9).astype(np.int64)
                for p in procs}
        want = _linear_fold([data[p] for p in procs], sub)
        P = len(procs)
        out = simulate(procs, lambda x, p: _linear_fold(
            hs.allgather_bruck(x, procs, p, data[p], [9] * P), sub))
        for p in procs:
            np.testing.assert_array_equal(np.asarray(out[p]), want)


class TestMovementSchedules:
    @pytest.mark.parametrize("procs", PROC_SETS,
                             ids=lambda p: f"P{len(p)}")
    def test_bcast_binomial_every_root(self, procs):
        rng = np.random.RandomState(1)
        val = rng.randint(0, 99, (4, 3)).astype(np.int32)
        for root in procs:
            out = simulate(procs, lambda x, p: hs.bcast_binomial(
                x, procs, p, root, val if p == root else None))
            for p in procs:
                np.testing.assert_array_equal(np.asarray(out[p]), val)

    @pytest.mark.parametrize("procs", PROC_SETS,
                             ids=lambda p: f"P{len(p)}")
    def test_gather_scatter_binomial(self, procs):
        P = len(procs)
        rng = np.random.RandomState(2)
        counts = [(i % 3) + 1 for i in range(P)]
        data = {p: rng.randint(0, 99, counts[i] * 4).astype(np.int32)
                for i, p in enumerate(procs)}
        for root in (procs[0], procs[-1], procs[P // 2]):
            out = simulate(procs, lambda x, p: hs.gather_binomial(
                x, procs, p, root, data[p],
                [c * 4 for c in counts]))
            for i, p in enumerate(procs):
                if p == root:
                    for j, q in enumerate(procs):
                        np.testing.assert_array_equal(out[p][j], data[q])
                else:
                    assert out[p] is None
            sc = simulate(procs, lambda x, p: hs.scatter_binomial(
                x, procs, p, root,
                [data[q] for q in procs] if p == root else None,
                counts, np.asarray([4], np.int64) if p == root else None))
            for i, p in enumerate(procs):
                flat, meta = sc[p]
                np.testing.assert_array_equal(flat, data[p])
                assert list(meta) == [4]

    @pytest.mark.parametrize("procs", PROC_SETS,
                             ids=lambda p: f"P{len(p)}")
    def test_allgather_bruck_and_ring_heterogeneous(self, procs):
        P = len(procs)
        rng = np.random.RandomState(3)
        blocks = {p: rng.randint(0, 99, ((i % 2) + 1, 5)).astype(np.int32)
                  for i, p in enumerate(procs)}
        counts = [blocks[p].size for p in procs]
        out = simulate(procs, lambda x, p: hs.allgather_bruck(
            x, procs, p, blocks[p].ravel(), counts))
        for p in procs:
            for i, q in enumerate(procs):
                np.testing.assert_array_equal(
                    out[p][i], blocks[q].ravel())
        out = simulate(procs, lambda x, p: hs.allgather_ring(
            x, procs, p, blocks[p]))
        for p in procs:
            for i, q in enumerate(procs):
                np.testing.assert_array_equal(out[p][i], blocks[q])

    @pytest.mark.parametrize("procs", PROC_SETS,
                             ids=lambda p: f"P{len(p)}")
    def test_alltoall_bruck_and_pairwise(self, procs):
        P = len(procs)
        rng = np.random.RandomState(4)
        mlen = [(i % 2) + 1 for i in range(P)]
        cf = 3
        pc = [[mlen[o] * mlen[j] * cf for j in range(P)]
              for o in range(P)]
        send = {p: [rng.randint(0, 99, pc[i][j]).astype(np.int32)
                    for j in range(P)]
                for i, p in enumerate(procs)}
        out = simulate(procs, lambda x, p: hs.alltoall_bruck(
            x, procs, p, send[p], pc))
        for i, p in enumerate(procs):
            for j, q in enumerate(procs):
                if q == p:
                    assert out[p][j] is None
                else:
                    np.testing.assert_array_equal(out[p][j], send[q][i])
        if P > 1:
            payloads = {p: {q: send[p][j]
                            for j, q in enumerate(procs) if q != p}
                        for p in procs}
            out = simulate(procs, lambda x, p: hs.alltoall_pairwise(
                x, procs, p, payloads[p]))
            for i, p in enumerate(procs):
                for j, q in enumerate(procs):
                    if q != p:
                        np.testing.assert_array_equal(
                            out[p][q], send[q][i])


# ---------------------------------------------------------------------------
# selection + packing units
# ---------------------------------------------------------------------------

class TestSelection:
    def test_fixed_constants(self):
        assert hs.pick("allreduce", 4, 1024) == "recursive_doubling"
        assert hs.pick("allreduce", 4, 1 << 20) == "rabenseifner"
        assert hs.pick("allreduce", 3, 1 << 20) == "ring"
        # non-commutative / identity-less large messages keep the
        # exact-order schedule
        assert hs.pick("allreduce", 4, 1 << 20,
                       commutative=False) == "recursive_doubling"
        assert hs.pick("allreduce", 4, 1 << 20,
                       has_identity=False) == "recursive_doubling"
        assert hs.pick("bcast", 8, 1 << 20) == "binomial"
        assert hs.pick("reduce", 8, 1024) == "binomial"
        assert hs.pick("reduce", 8, 1 << 20) == "linear"
        assert hs.pick("allgather", 8, 1024) == "bruck"
        assert hs.pick("allgather", 8, 1 << 20) == "linear"
        assert hs.pick("alltoall", 8, 1024) == "bruck"
        assert hs.pick("alltoall", 8, 1 << 20) == "pairwise"

    def test_forcing(self):
        mca_var.set_value("hier_inter_algorithm", "ring")
        try:
            assert hs.pick("allreduce", 4, 64) == "ring"
            # forcing an order-waiving schedule for a non-commutative
            # op is an ERROR (mirrors coll/tuned), not a silent downgrade
            with pytest.raises(MPIError):
                hs.pick("allreduce", 4, 64, commutative=False)
            # collectives with no 'ring'... bcast has no ring variant:
            # auto selection applies rather than a crash
            assert hs.pick("bcast", 4, 64) == "binomial"
        finally:
            mca_var.VARS.unset("hier_inter_algorithm")

    def test_dynamic_rules_and_noncommutative_downgrade(self, tmp_path):
        # the coll_tuned_* cvars register at framework open (runtime
        # init); this device-free test opens just the tuned component
        from ompi_release_tpu.coll.base import COLL_FRAMEWORK

        COLL_FRAMEWORK.lookup("tuned").register_vars()
        rules = tmp_path / "hier.conf"
        rules.write_text(textwrap.dedent("""
            hier_allreduce  0  0       linear
            hier_allreduce  0  4096    ring
            hier_bcast      0  0       linear
        """))
        mca_var.set_value("coll_tuned_use_dynamic_rules", True)
        mca_var.set_value("coll_tuned_dynamic_rules_filename",
                          str(rules))
        try:
            assert hs.pick("allreduce", 4, 100) == "linear"
            assert hs.pick("allreduce", 4, 8192) == "ring"
            # the rule file cannot waive MPI semantics
            assert hs.pick("allreduce", 4, 8192,
                           commutative=False) == "recursive_doubling"
            assert hs.pick("bcast", 4, 8192) == "linear"
            # no hier_alltoall rule: fixed constants apply
            assert hs.pick("alltoall", 4, 100) == "bruck"
        finally:
            mca_var.VARS.unset("coll_tuned_use_dynamic_rules")
            mca_var.VARS.unset("coll_tuned_dynamic_rules_filename")

    def test_shipped_rules_file_parses_with_hier_lines(self):
        from ompi_release_tpu.coll import dynamic_rules

        rules = dynamic_rules.load_rules(
            os.path.join(REPO, "tuning", "cpu8_rules.conf"))
        assert any(k.startswith("hier_") for k in rules), rules.keys()


class TestPairPacking:
    @pytest.mark.parametrize("vdt,idt", [(np.float32, np.int32),
                                         (np.float32, np.int64),
                                         (np.float64, np.int32)])
    def test_roundtrip(self, vdt, idt):
        from ompi_release_tpu.coll.hier import _HierModule

        rng = np.random.RandomState(0)
        pv = rng.randn(3, 5).astype(vdt)
        pi = rng.randint(0, 99, (3, 5)).astype(idt)
        buf = _HierModule._pack_pair(pv, pi)
        assert buf.dtype == np.uint8
        assert buf.nbytes == pv.nbytes + pi.nbytes  # ONE payload
        v, i = _HierModule._unpack_pair(buf, pv, pi)
        np.testing.assert_array_equal(v, pv)
        np.testing.assert_array_equal(i, pi)

    def test_roundtrip_odd_offset(self):
        """A value block whose byte length is not a multiple of the
        index itemsize still splits correctly (the unaligned-view
        path)."""
        from ompi_release_tpu.coll.hier import _HierModule

        pv = np.arange(3, dtype=np.float32)      # 12 bytes
        pi = np.arange(3, dtype=np.int64)        # 8-byte items at +12
        buf = _HierModule._pack_pair(pv, pi)
        v, i = _HierModule._unpack_pair(buf, pv, pi)
        np.testing.assert_array_equal(v, pv)
        np.testing.assert_array_equal(i, pi)


# ---------------------------------------------------------------------------
# real tpurun jobs: one per schedule family + leader tier + hang
# ---------------------------------------------------------------------------

APP_PRELUDE = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, %r)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import ompi_release_tpu as mpi
    from ompi_release_tpu import ops as _ops
    from ompi_release_tpu.mca import pvar, var as mca_var
    from ompi_release_tpu.runtime.runtime import Runtime

    def _pv(name):
        p = pvar.PVARS.lookup(name)
        return float(p.read()) if p is not None else 0.0

    def force(alg):
        mca_var.set_value("hier_inter_algorithm", alg)
""" % REPO)


def _run(tmp_path, capfd, body, n=3, timeout=240, mca=()):
    app = tmp_path / "app.py"
    app.write_text(APP_PRELUDE + textwrap.dedent(body))
    job = Job(n, [sys.executable, str(app)], list(mca),
              heartbeat_s=0.5, miss_limit=8)
    rc = job.run(timeout_s=timeout)
    out = capfd.readouterr()
    assert rc == 0, out.out + out.err
    assert job.job_state.visited(JobState.TERMINATED)
    return out.out


class TestScheduleJobs:
    def test_allreduce_family_job(self, tmp_path, capfd):
        """linear/recursive_doubling/ring/rabenseifner forced in turn
        on a 3-process 6-rank world: numpy parity (bitwise for int32),
        pair-op parity through the packed payload, the split message
        pvars consistent with their alias, and ring's inter bytes
        strictly below linear's."""
        out = _run(tmp_path, capfd, """
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            n = world.size
            x = np.stack([np.arange(64, dtype=np.int32) * (off + i + 1)
                          for i in range(2)])
            want = sum(np.arange(64, dtype=np.int32) * (r + 1)
                       for r in range(n))
            xf = x.astype(np.float32) * 0.125
            wantf = want.astype(np.float32) * 0.125
            bytes_by_alg = {}
            for alg in ("linear", "recursive_doubling", "ring",
                        "rabenseifner"):
                force(alg)
                b0 = _pv("hier_inter_bytes")
                got = np.asarray(world.allreduce(x))
                bytes_by_alg[alg] = _pv("hier_inter_bytes") - b0
                for i in range(2):
                    np.testing.assert_array_equal(got[i], want)
                gf = np.asarray(world.allreduce(xf))
                np.testing.assert_allclose(gf[0], wantf, rtol=1e-6)
                # pair op rides ONE packed message per peer per step
                pv_ = np.asarray([3., 1., 7., 2., 9., 0.],
                                 np.float32).reshape(n, 1)
                pi_ = np.arange(n, dtype=np.int32).reshape(n, 1)
                rv, ri = world.allreduce(
                    (pv_[off:off+2], pi_[off:off+2]), _ops.MAXLOC)
                assert float(np.asarray(rv)[0, 0]) == 9.0
                assert int(np.asarray(ri)[0, 0]) == 4
            # ring reduce-scatter+allgather ships ~2n*(P-1)/P, linear
            # (P-1)*n: at P=3 that is 4/3 n vs 2n per process
            assert bytes_by_alg["ring"] < bytes_by_alg["linear"], \\
                bytes_by_alg
            # the alias pvar stays the sum of the split counters
            assert _pv("hier_inter_msgs") == \\
                _pv("hier_inter_msgs_sent") + _pv("hier_inter_msgs_recvd")
            world.barrier()
            print(f"ALLREDUCE-FAM-OK {off}")
            mpi.finalize()
        """)
        for off in (0, 2, 4):
            assert f"ALLREDUCE-FAM-OK {off}" in out

    def test_tree_family_job(self, tmp_path, capfd):
        """Binomial bcast/reduce/gather/scatter on 3 processes: parity
        vs numpy, and the root's bcast send count drops from P-1 to
        ceil(log2 P) — the auditable O(log P) claim."""
        out = _run(tmp_path, capfd, """
            import math
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            n = world.size
            full = np.stack([np.arange(8, dtype=np.int32) + 10 * r
                             for r in range(n)])
            mine = full[off:off + 2]
            P = 3
            sent_by_alg = {}
            for alg in ("linear", "binomial"):
                force(alg)
                s0 = _pv("hier_inter_msgs_sent")
                got = np.asarray(world.bcast(mine, root=5))
                sent_by_alg[alg] = _pv("hier_inter_msgs_sent") - s0
                for i in range(2):
                    np.testing.assert_array_equal(got[i], full[5])
            if off == 4:  # root's owner
                assert sent_by_alg["linear"] == P - 1, sent_by_alg
                assert sent_by_alg["binomial"] == math.ceil(
                    math.log2(P)), sent_by_alg

            for alg in ("linear", "binomial"):
                force(alg)
                red = np.asarray(world.reduce(mine, root=2))
                if off == 2:
                    np.testing.assert_array_equal(red[0], full.sum(0))
                else:
                    assert (red == 0).all()
                # non-commutative reduce keeps the documented fold
                # order: members fold within their process, process
                # partials fold in process-index order (MPI ops are
                # associative, so this regrouping is legal; the order
                # itself must be exact and deterministic)
                sub = _ops.user_op("sub_j", lambda a, b: a - b,
                                   commute=False)
                sred = np.asarray(world.reduce(mine, sub, root=2))
                parts = [full[2 * q] - full[2 * q + 1]
                         for q in range(3)]
                wsub = (parts[0] - parts[1]) - parts[2]
                if off == 2:
                    np.testing.assert_array_equal(sred[0], wsub)
                # MINLOC pair reduce through the packed gather
                apv = np.asarray([3., 1., 7., 2., 9., 0.],
                                 np.float32).reshape(n, 1)
                api = np.arange(n, dtype=np.int32).reshape(n, 1)
                rv, ri = world.reduce(
                    (apv[off:off+2], api[off:off+2]), _ops.MINLOC,
                    root=3)
                if off == 2:
                    assert float(np.asarray(rv)[1, 0]) == 0.0
                    assert int(np.asarray(ri)[1, 0]) == 5

                g = np.asarray(world.gather(mine, root=4))
                if off == 4:
                    np.testing.assert_array_equal(
                        g[0], full.reshape(-1))
                else:
                    assert (g == 0).all()

                sc_full = np.arange(n * 3, dtype=np.int32) * 7
                sc_in = np.stack([sc_full, sc_full])
                sc = np.asarray(world.scatter(sc_in, root=1))
                for i in range(2):
                    np.testing.assert_array_equal(
                        sc[i], sc_full[(off + i) * 3:(off + i + 1) * 3])
            world.barrier()
            print(f"TREE-FAM-OK {off}")
            mpi.finalize()
        """)
        for off in (0, 2, 4):
            assert f"TREE-FAM-OK {off}" in out

    def test_exchange_family_job(self, tmp_path, capfd):
        """Bruck/ring allgather and bruck/pairwise alltoall forced on
        3 processes, bitwise parity vs the linear baseline results."""
        out = _run(tmp_path, capfd, """
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            n = world.size
            full = np.stack([np.arange(6, dtype=np.int32) + 100 * r
                             for r in range(n)])
            mine = full[off:off + 2]
            a2a_in = np.stack([
                np.asarray([(off + i) * 100 + j for j in range(n)],
                           dtype=np.int32)
                for i in range(2)])
            for alg in ("linear", "bruck", "ring"):
                force(alg)
                ag = np.asarray(world.allgather(mine))
                np.testing.assert_array_equal(ag[0], full.reshape(-1))
                # scans ride the same row-exchange schedule
                sc = np.asarray(world.scan(mine))
                for i in range(2):
                    np.testing.assert_array_equal(
                        sc[i], full[:off + i + 1].sum(0))
            for alg in ("linear", "bruck", "pairwise"):
                force(alg)
                a2a = np.asarray(world.alltoall(a2a_in))
                for i in range(2):
                    want = np.asarray(
                        [s * 100 + (off + i) for s in range(n)],
                        dtype=np.int32)
                    np.testing.assert_array_equal(a2a[i], want)
            world.barrier()
            print(f"XCHG-FAM-OK {off}")
            mpi.finalize()
        """)
        for off in (0, 2, 4):
            assert f"XCHG-FAM-OK {off}" in out

    def test_leader_tier_job(self, tmp_path, capfd):
        """Fake two-host topology (procs 0,1 on one host, proc 2 on
        another): allreduce/bcast parity holds, the leader performs
        the cross-host combine (hier_leader_combines), and the
        non-leader's inter traffic collapses to its shm pair with the
        leader (one packed send per combine)."""
        out = _run(tmp_path, capfd, """
            import os
            nid = int(os.environ["OMPITPU_NODE_ID"])
            os.environ["OMPITPU_HOST_ID"] = (
                "hostA" if nid <= 2 else "hostB")
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            me = rt.bootstrap["process_index"]
            n = world.size
            x = np.stack([np.arange(32, dtype=np.int32) * (off + i + 1)
                          for i in range(2)])
            want = sum(np.arange(32, dtype=np.int32) * (r + 1)
                       for r in range(n))
            s0 = _pv("hier_inter_msgs_sent")
            got = np.asarray(world.allreduce(x))
            d_sent = _pv("hier_inter_msgs_sent") - s0
            np.testing.assert_array_equal(got[0], want)
            lc = _pv("hier_leader_combines")
            if me == 0:
                assert lc >= 1, lc        # hostA's leader combined
            if me == 1:
                assert lc == 0 and d_sent == 1, (lc, d_sent)
            # bcast through the leader fan-out, remote root
            full = np.stack([np.arange(8, dtype=np.int32) + 10 * r
                             for r in range(n)])
            got = np.asarray(world.bcast(full[off:off+2], root=5))
            np.testing.assert_array_equal(got[0], full[5])
            # float parity within tolerance (per-host regrouped fold)
            xf = x.astype(np.float32) * 0.5
            gf = np.asarray(world.allreduce(xf))
            np.testing.assert_allclose(
                gf[0], want.astype(np.float32) * 0.5, rtol=1e-6)
            # opt-out restores the flat schedule
            mca_var.set_value("hier_leader_tier", False)
            l0 = _pv("hier_leader_combines")
            got = np.asarray(world.allreduce(x))
            np.testing.assert_array_equal(got[0], want)
            assert _pv("hier_leader_combines") == l0
            world.barrier()
            print(f"LEADER-OK {me}")
            mpi.finalize()
        """)
        for me in (0, 1, 2):
            assert f"LEADER-OK {me}" in out

    def test_hang_postmortem_names_ring_neighbor(self, tmp_path, capfd):
        """Hang injection under a FORCED ring schedule: process 1
        sleeps before the allreduce; the stalled peers' postmortems
        must name the stuck round (op + algorithm) and the specific
        ring neighbor being awaited — proc 0 waits on proc 2 (its ring
        predecessor), NOT on the sleeping proc directly, which is
        exactly the who-waits-on-whom chain tpu-doctor reconstructs."""
        pm_dir = tmp_path / "pm"
        out = _run(tmp_path, capfd, """
            import time
            world = mpi.init()
            rt = Runtime.current()
            me = rt.bootstrap["process_index"]
            off = rt.local_rank_offset
            n = world.size
            if me == 1:
                time.sleep(4.0)
            x = np.stack([np.full(8192, off + i + 1, np.float32)
                          for i in range(2)])
            got = np.asarray(world.allreduce(x))
            want = float(sum(r + 1 for r in range(n)))
            assert got[0][0] == want, got[0][0]
            world.barrier()
            print(f"HANG-RING-OK {me}")
            mpi.finalize()
        """, mca=[("hier_inter_algorithm", "ring"),
                  ("obs_enable", "1"),
                  ("obs_stall_timeout", "1.2"),
                  ("obs_postmortem_dir", str(pm_dir))])
        for me in (0, 1, 2):
            assert f"HANG-RING-OK {me}" in out
        pms = sorted(pm_dir.glob("postmortem-*-stall-*.json"))
        assert pms, f"no stall postmortem in {pm_dir}"
        named = []
        for p in pms:
            pm = json.loads(p.read_text())
            rounds = pm.get("hier_rounds", {})
            ring_round = any(
                st.get("op") == "allreduce" and st.get("alg") == "ring"
                for st in rounds.values())
            for st in pm.get("stalled", []):
                info = st.get("info") or {}
                if st.get("op") == "allreduce" and ring_round:
                    named.append(tuple(info.get("awaiting_procs") or ()))
        assert named, pms
        # the awaited process is a specific ring predecessor (proc 2
        # waits on the sleeper; proc 0 waits on proc 2 downstream)
        assert any(t in ((1,), (2,)) for t in named), named
