"""Simulated-fleet scale harness: the real schedule/FT/sentinel stack
at P=256-4096 over the virtual wire.

Five layers:

1. Fabric units: link classes over host topologies, slow-NIC
   stragglers, deterministic loss retransmit penalties, partition
   windows (healing and black-hole).
2. Metrology/virtual-clock units at small P: inter-host byte
   accounting, straggler makespan impact, clock monotonicity.
3. SCALING CURVES at P in {256, 1024, 4096} (P >= 1024 @slow): the
   unmodified ``hier_schedules`` round code must show bcast root
   sends = ceil(log2 P), recursive-doubling rounds = ceil(log2 P),
   and Rabenseifner inter-process send bytes/rank = exactly
   2n(P-1)/P (every simulated rank is one process, so bytes_sent IS
   the hier_inter_bytes quantity; inter_bytes_sent is the
   host-crossing subset) — the O(log P)/O(n) claims, asserted at
   the scale they were made for.
4. ULFM + sentinel at scale: a 256-rank multi-failure chaos episode
   whose typed-error cascade, epoch agreement, ft_cid rebuild, and
   verified rerun all drive the real ``ft/ulfm.py`` state machines;
   a 256-rank sentinel desync whose journals feed the real
   ``tpu-doctor contracts`` / ``report`` forensics.
5. Determinism: the seeded P=64 chaos smoke scenario (tier-1) replays
   with bit-identical event logs — chaos as reproducible evidence.
"""

import json

import numpy as np
import pytest

from ompi_release_tpu.coll import hier_schedules as hs
from ompi_release_tpu.ft.ulfm import FT_CID_BASE
from ompi_release_tpu.obs import doctor as doctor_mod
from ompi_release_tpu.testing import fleet_sim as fs
from ompi_release_tpu.testing import scenarios as sc
from ompi_release_tpu.utils.errors import ErrorCode

slow = pytest.mark.slow

#: the acceptance grid: P=256 in tier-1, the fleet sizes @slow
SCALING_P = [256,
             pytest.param(1024, marks=slow),
             pytest.param(4096, marks=slow)]


# ---------------------------------------------------------------------------
# fabric units
# ---------------------------------------------------------------------------


class TestFabric:
    def test_host_grouping_and_link_classes(self):
        f = fs.Fabric(16, hosts_per=4)
        assert f.host(0) == f.host(3) == "h0"
        assert f.host(4) == "h1"
        assert not f.crosses_host(0, 3)
        assert f.crosses_host(3, 4)
        assert sorted(f.hosts()) == ["h0", "h1", "h2", "h3"]
        lat_i, bps_i, _ = f.link(0, 1)
        lat_x, bps_x, _ = f.link(0, 5)
        assert lat_x > lat_i and bps_x < bps_i

    def test_delivery_latency_plus_bandwidth(self):
        f = fs.Fabric(4, hosts_per=4)   # all intra
        lat, bps, _ = f.link(0, 1)
        arr, retx = f.delivery(0, 1, 1 << 20, 2.0, 0)
        assert retx == 0
        assert arr == pytest.approx(2.0 + lat + (1 << 20) / bps)

    def test_slow_nic_straggler_shapes_both_directions(self):
        f = fs.Fabric(4, hosts_per=4)
        base = f.delivery(0, 1, 4096, 0.0, 0)[0]
        f.slow_nic(1, 4.0)
        assert f.delivery(0, 1, 4096, 0.0, 0)[0] > base
        assert f.delivery(1, 2, 4096, 0.0, 0)[0] > base
        assert f.delivery(2, 3, 4096, 0.0, 0)[0] == base

    def test_loss_penalty_is_deterministic(self):
        mk = lambda: fs.Fabric(  # noqa: E731
            4, hosts_per=4, seed=9,
            intra=fs.LinkSpec(1e-6, 100.0, loss=0.5))
        a, b = mk(), mk()
        outs_a = [a.delivery(0, 1, 64, 0.0, k) for k in range(64)]
        outs_b = [b.delivery(0, 1, 64, 0.0, k) for k in range(64)]
        assert outs_a == outs_b
        retxs = [r for (_, r) in outs_a]
        assert any(r > 0 for r in retxs), "50% loss never retransmitted?"
        # every retransmit costs the rto on top of the lossless time
        clean = fs.Fabric(4, hosts_per=4).delivery(0, 1, 64, 0.0, 0)[0]
        for (arr, r) in outs_a:
            assert arr == pytest.approx(clean + r * a.rto_s)

    def test_partition_heals_and_blackholes(self):
        f = fs.Fabric(4, hosts_per=2)
        f.partition([0, 1], [2, 3], t0=1.0, t1=2.0)
        lat, bps, _ = f.link(0, 2)
        # inside the window: held in the switch until the heal
        arr, _ = f.delivery(0, 2, 64, 1.5, 0)
        assert arr >= 2.0 + lat
        # after the heal / not crossing: undisturbed delivery math
        assert f.delivery(0, 2, 64, 2.5, 0)[0] \
            == pytest.approx(2.5 + lat + 64 / bps)
        assert f.delivery(0, 1, 64, 1.5, 0)[0] < 2.0
        f.partition([0], [3], t0=0.0, t1=None)  # severed forever
        assert f.delivery(0, 3, 64, 0.5, 0)[0] is None


# ---------------------------------------------------------------------------
# virtual clock + metrology at small P
# ---------------------------------------------------------------------------


class TestMetrology:
    def test_ring_allgather_rounds_and_clock(self):
        P = 8
        fleet = fs.FleetSim(P, hosts_per=4)
        procs = fleet.procs
        blocks = {p: np.full(4, p, np.int32) for p in procs}
        rep = fleet.run(
            lambda x, p: hs.allgather_ring(x, procs, p, blocks[p]),
            label="allgather")
        assert rep.ok() == procs
        assert rep.min_rounds() == rep.max_rounds() == P - 1
        assert rep.makespan > 0.0
        for i, got in enumerate(rep.value(3)):
            np.testing.assert_array_equal(got, blocks[i])

    def test_inter_host_bytes_counted_only_across_hosts(self):
        # ring over hosts of 2: rank p sends everything to (p+1)%4,
        # so odd ranks cross hosts (1->2, 3->0), even ranks stay shm
        fleet = fs.FleetSim(4, hosts_per=2)
        procs = fleet.procs
        rep = fleet.run(
            lambda x, p: hs.allgather_ring(
                x, procs, p, np.full(8, p, np.int64)),
            label="allgather")
        for p in (0, 2):
            assert rep.inter_bytes_sent[p] == 0, rep.inter_bytes_sent
        for p in (1, 3):
            assert rep.inter_bytes_sent[p] == rep.bytes_sent[p] > 0

    def test_straggler_stretches_makespan(self):
        def makespan(straggle):
            fleet = fs.FleetSim(16, hosts_per=4)
            if straggle:
                fleet.fabric.slow_nic(5, 8.0)
            procs = fleet.procs
            rep = fleet.run(
                lambda x, p: hs.allgather_ring(
                    x, procs, p, np.full(1024, p, np.int64)),
                label="allgather")
            return rep.makespan

        assert makespan(True) > makespan(False)

    def test_lossy_link_costs_retransmit_time(self):
        def run(loss):
            fleet = fs.FleetSim(
                8, fabric=fs.Fabric(
                    8, hosts_per=8, seed=5,
                    intra=fs.LinkSpec(1e-6, 100.0, loss=loss)))
            procs = fleet.procs
            rep = fleet.run(
                lambda x, p: hs.allgather_ring(
                    x, procs, p, np.full(16, p, np.int32)),
                label="allgather")
            return rep

        clean, lossy = run(0.0), run(0.4)
        assert sum(lossy.loss_retx.values()) > 0
        assert sum(clean.loss_retx.values()) == 0
        assert lossy.makespan > clean.makespan


# ---------------------------------------------------------------------------
# the scaling curves (the acceptance grid)
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("P", SCALING_P)
class TestScalingCurves:
    def test_bcast_root_sends_ceil_log2(self, P):
        fleet = fs.FleetSim(P, hosts_per=8, real_timeout_s=240)
        procs = fleet.procs
        val = np.arange(16, dtype=np.int32)
        rep = fleet.run(
            lambda x, p: hs.bcast_binomial(
                x, procs, p, 0, val if p == 0 else None),
            label="bcast", timeout_s=400)
        assert len(rep.ok()) == P
        # THE O(log P) fan-out claim, at the scale it was made for:
        # the root sends exactly ceil(log2 P) messages, not P-1
        assert rep.msgs_sent[0] == fs.log2_rounds(P)
        assert rep.rounds[0] == 1
        for p in (1, P // 2, P - 1):
            np.testing.assert_array_equal(np.asarray(rep.value(p)), val)
        # the binomial tree is O(log P) deep in virtual time too: far
        # below P serialized inter-latency hops
        lat = fleet.fabric.inter.latency_s
        assert rep.makespan < 4 * fs.log2_rounds(P) * 10 * lat

    def test_recursive_doubling_rounds_ceil_log2(self, P):
        fleet = fs.FleetSim(P, hosts_per=8, real_timeout_s=240)
        procs = fleet.procs
        data = {p: np.full(2, p + 1, np.int64) for p in procs}
        rep = fleet.run(
            lambda x, p: np.sum(
                np.stack(hs.allgather_bruck(x, procs, p, data[p],
                                            [2] * P)), axis=0),
            label="allreduce_rd", timeout_s=400)
        assert len(rep.ok()) == P
        # the doubling-distance partial exchange behind the
        # recursive_doubling allreduce: ceil(log2 P) rounds on EVERY
        # rank, regardless of P
        assert rep.min_rounds() == rep.max_rounds() \
            == fs.log2_rounds(P)
        want = np.full(2, P * (P + 1) // 2, np.int64)
        np.testing.assert_array_equal(np.asarray(rep.value(P // 3)),
                                      want)

    def test_rabenseifner_inter_bytes_2n(self, P):
        fleet = fs.FleetSim(P, hosts_per=8, real_timeout_s=240)
        procs = fleet.procs
        n = 2 * P
        data = {p: np.arange(n, dtype=np.float32) * ((p % 7) + 1)
                for p in procs}
        rep = fleet.run(
            lambda x, p: hs.allreduce_rabenseifner(
                x, procs, p, data[p], np.add, 0.0),
            label="allreduce_rab", timeout_s=400)
        assert len(rep.ok()) == P
        nbytes = n * 4
        want_bytes = fs.rabenseifner_bytes_per_rank(n, 4, P)
        # EXACT: (P-1) chunks out in the halving reduce-scatter plus
        # (P-1) back in the doubling allgather = 2n(P-1)/P per rank.
        # bytes_sent IS the inter-process (hier_inter_bytes) quantity
        # here: one simulated rank = one process...
        assert set(rep.bytes_sent.values()) == {want_bytes}
        assert want_bytes <= 2 * nbytes
        # ...which is O(n), not the linear path's O(P n): at fleet
        # scale the gap is what makes the schedule usable at all
        assert want_bytes * 64 < (P - 1) * nbytes
        # and 2*ceil(log2 P) rounds per rank
        assert rep.min_rounds() == rep.max_rounds() \
            == 2 * fs.log2_rounds(P)
        want = sum(np.arange(n, dtype=np.float32) * ((p % 7) + 1)
                   for p in procs)
        np.testing.assert_allclose(np.asarray(rep.value(5)), want,
                                   rtol=1e-4)


# ---------------------------------------------------------------------------
# ULFM at scale: cascades, typed errors, recovery
# ---------------------------------------------------------------------------


class TestUlfmAtScale:
    def test_death_cascades_into_typed_errors(self):
        """One staged death mid-ring: the direct waiter raises
        ERR_PROC_FAILED through the REAL check_wait, the revoke storm
        propagates, and downstream waiters raise ERR_REVOKED —
        exactly the PR 9 semantics, process-free."""
        P = 16
        fleet = fs.FleetSim(P, hosts_per=8)
        procs = fleet.procs
        fleet.kill(6, at_round=3)
        rep = fleet.run(
            lambda x, p: hs.allgather_ring(
                x, procs, p, np.full(4, p, np.int32)),
            label="allgather")
        assert rep.killed() == [6]
        assert not fleet.ranks[6].alive
        errs = {p: rep.outcomes[p][1] for p in rep.errored()}
        assert errs, "no rank detected the death"
        codes = {e.code for e in errs.values()}
        assert ErrorCode.ERR_PROC_FAILED in codes
        # the failure wave travels the ring one hop per round: rank
        # 7 (the direct waiter) fails at round 3, rank 7+d at round
        # 3+d — so exactly the ranks within P-1-3 hops error, and
        # the two furthest-downstream ranks (4, 5) legally finish
        # all 15 rounds first. Downstream detectors saw the revoke
        # storm, not the raw death.
        assert rep.ok() == [4, 5]
        assert len(rep.errored()) == P - 3
        assert ErrorCode.ERR_REVOKED in codes
        # every detector's OWN FtState carries the failure picture
        for p in rep.errored():
            st = fleet.ranks[p].ft
            assert 6 in st.failed_at
            assert st.is_revoked(1) or st.dead_for([6])

    def test_same_cid_rerun_after_error_is_refused(self):
        """An errored rank's exit markers (and undrained payloads)
        still sit on the failed cid's queues, so replaying survivors
        on the SAME cid would fail spuriously — run() enforces the
        production ULFM rule: rebuild on a fresh cid."""
        fleet = fs.FleetSim(8, hosts_per=8)
        procs = fleet.procs
        fleet.kill(3, at_round=2)
        rep = fleet.run(
            lambda x, p: hs.allgather_ring(
                x, procs, p, np.full(4, p, np.int32)),
            label="allgather")
        survivors = [p for p in procs if fleet.ranks[p].alive]
        assert rep.errored()
        with pytest.raises(ValueError, match="fresh cid"):
            fleet.run(lambda x, p: None, ranks=survivors, cid=1)
        # the rebuild shape works: fresh cid, clean run
        rep2 = fleet.run(
            lambda x, p: hs.allgather_ring(
                x, survivors, p, np.full(4, p, np.int32)),
            ranks=survivors, cid=2)
        assert rep2.ok() == survivors

    def test_blackhole_partition_raises_unreachable(self):
        fleet = fs.FleetSim(8, hosts_per=4)
        fleet.fabric.partition(range(4), range(4, 8), t0=0.0, t1=None)
        procs = fleet.procs
        rep = fleet.run(
            lambda x, p: hs.allgather_ring(
                x, procs, p, np.full(4, p, np.int32)),
            label="allgather")
        errs = [rep.outcomes[p][1] for p in rep.errored()]
        assert errs
        assert any(e.code == ErrorCode.ERR_UNREACH for e in errs)

    def test_multi_failure_episode_256(self):
        """The satellite scenario: a 256-rank, 3-death cascade with a
        healing partition and a straggler, recovered through the real
        epoch agreement + ft_cid rebuild, rerun verified."""
        res = sc.cascading_failure(P=256, seed=7, deaths=3)
        assert len(res.victims) == 3
        assert len(res.survivors) == 256 - 3
        assert res.agreed_epoch == 3
        # every survivor derived the SAME rebuilt cid from its own
        # state (asserted inside the scenario) in the wire FT band
        assert FT_CID_BASE <= res.new_cid < (1 << 20)
        assert res.phase1.killed() == res.victims
        # phase 2 completed on every survivor (verified numerically
        # inside the scenario)
        assert res.phase2.ok() == res.survivors

    def test_forensics_incident_timeline_names_culprits_256(
            self, tmp_path):
        """Dump the 256-rank episode's per-rank journals and make the
        REAL tpu-doctor report name the story: which ranks died, that
        the comm was revoked, that recovery landed on the rebuilt
        cid — forensics past 8 ranks for the first time."""
        res = sc.cascading_failure(P=256, seed=7, deaths=3)
        d = tmp_path / "dumps"
        assert res.fleet.write_journals(str(d)) == 256
        dumps = doctor_mod.load_dir(str(d))
        text, data = doctor_mod.skew_report(dumps)
        incidents = data["incidents"]
        failed = sorted({e["failed_pidx"] for e in incidents
                         if e["op"] == "ft_failure"})
        assert failed == res.victims
        revoked_cids = {e["cid"] for e in incidents
                        if e["op"] == "ft_revoke"}
        assert 1 in revoked_cids
        recs = [e for e in incidents if e["op"] == "ft_recovery"]
        assert recs and recs[0]["new_cid"] == res.new_cid
        assert "incident timeline" in text
        for v in res.victims:
            assert f"process {v} FAILED" in text


# ---------------------------------------------------------------------------
# sentinel at scale: 256-rank desync through the real doctor
# ---------------------------------------------------------------------------


class TestSentinelAtScale:
    def test_contracts_names_the_divergent_rank_of_256(self, tmp_path):
        fleet = sc.sentinel_desync(P=256, divergent_rank=137,
                                   divergent_seq=2)
        d = tmp_path / "dumps"
        assert fleet.write_journals(str(d)) == 256
        dumps = doctor_mod.load_dir(str(d))
        text, data = doctor_mod.contract_report(dumps,
                                                directory=str(d))
        assert data["divergences"] == 1
        div = data["comms"]["1"]["divergence"]
        assert div["kind"] == "signature_mismatch"
        assert div["seq"] == 2 and div["divergent"] == 137
        assert div["expected"]["canon"] \
            == "allreduce|sum|float32|1024|-1"
        assert div["actual"]["canon"] == "bcast|-|float32|1024|0"
        assert "proc 137 posted bcast" in text
        assert "restore.py:88" in text and "trainer.py:203" in text

    def test_doctor_cli_exit_code_on_the_sim_dump(self, tmp_path,
                                                  capsys):
        from ompi_release_tpu.tools import tpu_doctor

        fleet = sc.sentinel_desync(P=64, divergent_rank=33,
                                   divergent_seq=1)
        d = tmp_path / "dumps"
        fleet.write_journals(str(d))
        rc = tpu_doctor.main(["contracts", str(d)])
        out = capsys.readouterr().out
        assert rc == 3
        assert "proc 33" in out and "DESYNC at seq 1" in out

    def test_healthy_fleet_chains_agree(self):
        """No divergence injected: 256 production CallSig chains fold
        to ONE value — the cross-rank determinism the sentinel's
        whole design rests on, at 256 ranks."""
        fleet = sc.sentinel_desync(P=256, divergent_rank=-1,
                                   divergent_seq=2)  # never fires
        chains = {fleet.chain_of(p, 1) for p in fleet.procs}
        assert len(chains) == 1 and 0 not in chains


# ---------------------------------------------------------------------------
# determinism: seeded chaos replays bit-identically (tier-1 smoke)
# ---------------------------------------------------------------------------


class TestDeterminism:
    def test_p64_smoke_chaos_replays_identically(self):
        """THE tier-1 smoke scenario: P=64, cascading deaths, healing
        partition, slow-NIC straggler — full episode (chaos -> typed
        errors -> agreement -> ft_cid rebuild -> verified rerun)
        twice, with bit-identical event logs."""
        r1 = sc.cascading_failure(P=64, seed=3)
        r2 = sc.cascading_failure(P=64, seed=3)
        assert r1.event_log_json == r2.event_log_json
        ev = json.loads(r1.event_log_json)
        kinds = {e["kind"] for e in ev}
        assert {"died", "error", "revoke", "learned_failure", "done",
                "recovered"} <= kinds
        assert r1.victims == r2.victims
        assert r1.new_cid == r2.new_cid
        # the chaos actually happened: both typed error classes
        codes = {r1.phase1.outcomes[p][1].code
                 for p in r1.phase1.errored()}
        assert codes & {ErrorCode.ERR_PROC_FAILED,
                        ErrorCode.ERR_REVOKED}

    def test_different_seed_different_story(self):
        r1 = sc.cascading_failure(P=64, seed=3)
        r2 = sc.cascading_failure(P=64, seed=4)
        assert r1.event_log_json != r2.event_log_json

    @slow
    def test_p256_chaos_replays_identically(self):
        r1 = sc.cascading_failure(P=256, seed=11, deaths=4)
        r2 = sc.cascading_failure(P=256, seed=11, deaths=4)
        assert r1.event_log_json == r2.event_log_json


# ---------------------------------------------------------------------------
# bench wiring: the fleet_scaling suite and its gate contract
# ---------------------------------------------------------------------------


class TestBenchWiring:
    def test_fleet_suite_lines_are_sim_tier_and_gateable(self):
        import bench
        from ompi_release_tpu.tools import tpu_bench_gate as gate

        lines = bench._fleet_micro_suite(sizes=(64,))
        assert lines
        for ln in lines:
            # sim_* = closed-form observables (lower-better), topo_* =
            # topology-aware speedup ratios over the flat ring
            # (higher-better)
            assert ln["metric"].startswith(("sim_", "topo_"))
            # satellite: distinct tier label so the gate NEVER fits
            # sim numbers against loopback-cpu/tpu history
            assert ln["tier_label"] == "sim"
            assert gate.line_tier(ln) == "sim"
            assert gate.gateable(ln)
            want = 1 if ln["metric"].startswith("topo_") else -1
            assert gate._direction(ln.get("unit"), ln["metric"]) == want
        metrics = {ln["metric"] for ln in lines}
        assert "sim_bcast_root_sends_p64" in metrics
        assert "sim_rab_bytes_per_rank_p64" in metrics
        # the emitted observables match the closed-form laws
        by = {ln["metric"]: ln for ln in lines}
        assert by["sim_bcast_root_sends_p64"]["value"] == 6
        assert by["sim_rd_rounds_p64"]["value"] == 6
        assert by["sim_rab_bytes_per_rank_p64"]["value"] \
            == fs.rabenseifner_bytes_per_rank(128, 4, 64)

    def test_suite_makespan_shrinks_vs_flat_wire(self):
        """The fabric model is doing real work: the same binomial
        bcast over an 8-per-host topology beats an all-DCN wire."""
        import bench  # noqa: F401  (suite helper exercised above)

        def makespan(hosts_per):
            fleet = fs.FleetSim(64, hosts_per=hosts_per)
            procs = fleet.procs
            val = np.arange(16, dtype=np.int32)
            rep = fleet.run(
                lambda x, p: hs.bcast_binomial(
                    x, procs, p, 0, val if p == 0 else None),
                label="bcast")
            return rep.makespan

        assert makespan(8) < makespan(1)


# ---------------------------------------------------------------------------
# multi-tenant: N tenants x small fleets over one shared fabric
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mt_result():
    """ONE P=256 multi_tenant scenario run (solo + contended-QoS with
    a staged bulk-rank kill + contended-FIFO legs) shared by the
    fairness and FT-isolation assertions — the kill is staged in the
    bulk tenant only, so the latency tenant's virtual clocks are
    identical to a kill-free run (contention is the deterministic
    bandwidth-share model, not the bulk schedule's fate)."""
    return sc.multi_tenant(P=256, seed=1, kill_bulk=True)


class TestMultiTenant:
    def test_tenant_cid_banding_units(self):
        from ompi_release_tpu.ft import ulfm

        lo0, hi0 = ulfm.tenant_band(0)
        lo1, hi1 = ulfm.tenant_band(1)
        assert hi0 == lo1 and hi0 - lo0 == ulfm.TENANT_CID_SLOT
        assert ulfm.tenant_band(ulfm.MAX_TENANTS - 1)[1] == FT_CID_BASE
        # app cids and tenant-scoped rebuild cids stay in-band
        c = ulfm.tenant_cid(3, 7)
        assert ulfm.tenant_of_cid(c) == 3
        r = ulfm.ft_cid(5, c, tenant=3)
        assert ulfm.tenant_of_cid(r) == 3
        assert r != c
        # distinct tenants recovering at one epoch never collide
        assert ulfm.ft_cid(5, c, tenant=3) != ulfm.ft_cid(5, c, tenant=4)
        # legacy (tenant-less) rebuilds stay in the FT band
        assert ulfm.ft_cid(5, c) >= FT_CID_BASE
        assert ulfm.tenant_of_cid(ulfm.ft_cid(5, c)) == -1
        assert ulfm.tenant_of_cid(17) == -1
        with pytest.raises(Exception):
            ulfm.tenant_band(ulfm.MAX_TENANTS)

    def test_bandwidth_share_scales_only_bandwidth(self):
        fab = fs.Fabric(4, hosts_per=2)
        lat0, bps0, _ = fab.link(0, 2)
        fab.bandwidth_share(0, 0.25)
        lat1, bps1, _ = fab.link(0, 2)
        assert lat1 == lat0                    # latency untouched
        assert bps1 == pytest.approx(bps0 * 0.25)
        # receiver-side share does not apply (sender egress model)
        assert fab.link(2, 1)[1] == pytest.approx(bps0)

    def test_fairness_bound_at_p256(self, mt_result):
        """Bulk tenant saturating the wire leaves the latency
        tenant's virtual-clock makespan within the weighted-fair
        bound of its solo run — while the FIFO (no-QoS) model of the
        same contention blows far past it."""
        r = mt_result
        assert len(r.lat_ranks) == 32 and len(r.bulk_ranks) == 224
        bound = r.solo_makespan / r.share_lat * 1.10
        assert r.qos_makespan <= bound
        assert r.p99(r.qos_durations) <= \
            r.p99(r.solo_durations) / r.share_lat * 1.10
        # the QoS win over head-of-line FIFO is large and measurable
        assert r.fifo_makespan > 2.0 * r.qos_makespan

    def test_ft_isolation_at_p256(self, mt_result):
        """SIGKILLing a bulk-tenant rank mid-allreduce revokes ONLY
        the bulk tenant's band cids: every latency rank finishes ok,
        every bulk survivor raises a typed ULFM error, and no
        latency-rank FtState ever saw a revocation."""
        from ompi_release_tpu.ft import ulfm

        r = mt_result
        assert r.killed_rank in r.bulk_ranks
        assert all(k == "ok" for k, _ in r.outcomes_lat.values())
        kinds = {}
        for p, (k, v) in r.outcomes_bulk.items():
            kinds.setdefault(k, []).append(p)
            if k == "error":
                assert v.code in (ErrorCode.ERR_PROC_FAILED,
                                  ErrorCode.ERR_REVOKED)
        assert kinds["killed"] == [r.killed_rank]
        assert len(kinds["error"]) == len(r.bulk_ranks) - 1
        # revocations confined to the bulk tenant's band
        for p in r.bulk_ranks:
            for c in r.qos_fleet.ranks[p].ft.revoked:
                assert ulfm.tenant_of_cid(c) == 1
        for p in r.lat_ranks:
            assert not r.qos_fleet.ranks[p].ft.revoked

    def test_band_revoke_poisons_future_cids_and_clear_band_heals(self):
        from ompi_release_tpu.ft import ulfm
        from ompi_release_tpu.utils.errors import MPIError

        st = ulfm.FtState()
        lo, hi = ulfm.tenant_band(2)
        st.revoke_band(lo, hi)
        assert st.is_revoked(ulfm.tenant_cid(2, 9))  # never minted
        with pytest.raises(MPIError) as ei:
            st.check_wait(ulfm.tenant_cid(2, 9), (), "wait")
        assert ei.value.code == ErrorCode.ERR_REVOKED
        assert "tenant 2" in str(ei.value)
        # the neighbor band is untouched
        st.check_wait(ulfm.tenant_cid(3, 9), (), "wait")
        assert [lo, hi] in st.snapshot()["revoked_bands"]
        st.clear_band(lo, hi)
        st.check_wait(ulfm.tenant_cid(2, 9), (), "wait")

    def test_per_rank_cid_scopes_exit_markers_small(self):
        """Two tenants in ONE run at small P: a death in tenant B's
        cid never wakes tenant A's queues (the cid(p) callable run
        shape, fast version of the P=256 episode)."""
        from ompi_release_tpu.ft import ulfm

        fleet = fs.FleetSim(8, hosts_per=4, seed=0)
        a_ranks, b_ranks = [0, 2, 4, 6], [1, 3, 5, 7]
        a_cid, b_cid = ulfm.tenant_cid(0, 0), ulfm.tenant_cid(1, 0)
        fleet.kill(3, at_round=1)
        data = {p: np.full(4, p + 1, np.int64) for p in range(8)}

        def fn(x, p):
            grp = a_ranks if p in a_ranks else b_ranks
            return hs.allgather_bruck(x, grp, p, data[p], [4] * 4)

        rep = fleet.run(fn, cid=lambda p: a_cid if p in a_ranks
                        else b_cid, label="mt")
        assert all(rep.outcomes[p][0] == "ok" for p in a_ranks)
        assert rep.outcomes[3][0] == "killed"
        assert all(rep.outcomes[p][0] == "error" for p in b_ranks
                   if p != 3)
