"""Flagship model tests: sharded-vs-single-device parity.

The simulator-backend strategy of SURVEY §4: the same SPMD program runs
on a 1-device mesh (every axis size 1 — the dense reference) and on
real multi-device layouts; losses and post-step losses must agree.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from ompi_release_tpu.models import transformer as tfm
from ompi_release_tpu.parallel.mesh_axes import build_parallel_mesh

CFG = dict(
    vocab=32, d_model=16, n_layers=2, n_heads=4, head_dim=4, d_ff=32,
    max_seq=16, dtype=jnp.float32,
)


def make_batch(rng, b, s, vocab):
    tokens = rng.randint(0, vocab, size=(b, s)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1)
    return tokens, targets


def run_loss(cfg, mesh, params, tokens, targets):
    fwd = tfm.make_forward(cfg, mesh)
    p = tfm.shard_params(params, cfg, mesh)
    sh = tfm.make_batch_sharding(mesh)
    return float(fwd(p, jax.device_put(tokens, sh),
                     jax.device_put(targets, sh)))


def run_step(cfg, mesh, params, tokens, targets, lr=0.1):
    opt = optax.sgd(lr)
    step = tfm.make_train_step(cfg, mesh, opt)
    p = tfm.shard_params(params, cfg, mesh)
    opt_state = jax.jit(opt.init)(p)
    sh = tfm.make_batch_sharding(mesh)
    tok = jax.device_put(tokens, sh)
    tgt = jax.device_put(targets, sh)
    p, opt_state, loss0 = step(p, opt_state, tok, tgt)
    _, _, loss1 = step(p, opt_state, tok, tgt)
    return float(loss0), float(loss1)


@pytest.fixture(scope="module")
def setup():
    cfg = tfm.ModelConfig(**CFG)
    params = jax.device_get(
        tfm.init_params(jax.random.PRNGKey(0), cfg)
    )
    rng = np.random.RandomState(0)
    tokens, targets = make_batch(rng, 8, 16, cfg.vocab)
    mesh1 = build_parallel_mesh(devices=jax.devices()[:1])
    ref_loss = run_loss(cfg, mesh1, params, tokens, targets)
    return cfg, params, tokens, targets, mesh1, ref_loss


def test_loss_is_finite_and_reasonable(setup):
    cfg, params, tokens, targets, mesh1, ref = setup
    assert np.isfinite(ref)
    # random init ~ uniform over vocab
    assert abs(ref - np.log(cfg.vocab)) < 1.0


@pytest.mark.parametrize(
    "axes",
    [
        dict(dp=2), dict(tp=2), dict(sp=2), dict(dp=2, tp=2),
        dict(dp=2, sp=2, tp=2), dict(dp=2, pp=2, tp=2),
        dict(pp=2, sp=2, tp=2),
    ],
    ids=lambda a: "x".join(f"{k}{v}" for k, v in a.items()),
)
def test_sharded_loss_matches_single_device(setup, axes):
    cfg, params, tokens, targets, mesh1, ref = setup
    n = int(np.prod(list(axes.values())))
    if "pp" in axes:
        cfg = tfm.ModelConfig(**{**CFG, "microbatches": 4})
        ref = run_loss(cfg, mesh1, params, tokens, targets)
    mesh = build_parallel_mesh(devices=jax.devices()[:n], **axes)
    got = run_loss(cfg, mesh, params, tokens, targets)
    assert got == pytest.approx(ref, rel=1e-4, abs=1e-5)


def test_train_step_parity_dp_sp_tp(setup):
    cfg, params, tokens, targets, mesh1, _ = setup
    ref0, ref1 = run_step(cfg, mesh1, params, tokens, targets)
    mesh = build_parallel_mesh(devices=jax.devices(), dp=2, sp=2, tp=2)
    got0, got1 = run_step(cfg, mesh, params, tokens, targets)
    assert got0 == pytest.approx(ref0, rel=1e-4)
    assert got1 == pytest.approx(ref1, rel=1e-3, abs=1e-4)
    assert ref1 < ref0  # it actually learns


def test_train_step_parity_full_mesh_pp(setup):
    cfg, params, tokens, targets, mesh1, _ = setup
    cfg = tfm.ModelConfig(**{**CFG, "microbatches": 2})
    ref0, ref1 = run_step(cfg, mesh1, params, tokens, targets)
    mesh = build_parallel_mesh(devices=jax.devices(), dp=2, pp=2, tp=2)
    got0, got1 = run_step(cfg, mesh, params, tokens, targets)
    assert got0 == pytest.approx(ref0, rel=1e-4)
    assert got1 == pytest.approx(ref1, rel=1e-3, abs=1e-4)


class TestMoE:
    def test_moe_loss_parity_ep2(self):
        cfg = tfm.ModelConfig(**{**CFG, "n_experts": 4,
                                 "capacity_factor": 4.0})
        params = jax.device_get(
            tfm.init_params(jax.random.PRNGKey(1), cfg)
        )
        rng = np.random.RandomState(1)
        tokens, targets = make_batch(rng, 8, 16, cfg.vocab)
        mesh1 = build_parallel_mesh(devices=jax.devices()[:1])
        ref = run_loss(cfg, mesh1, params, tokens, targets)
        mesh = build_parallel_mesh(devices=jax.devices()[:4], ep=2, tp=2)
        got = run_loss(cfg, mesh, params, tokens, targets)
        assert got == pytest.approx(ref, rel=1e-4, abs=1e-5)

    def test_moe_train_step_runs(self):
        cfg = tfm.ModelConfig(**{**CFG, "n_experts": 4,
                                 "capacity_factor": 4.0})
        params = jax.device_get(
            tfm.init_params(jax.random.PRNGKey(2), cfg)
        )
        rng = np.random.RandomState(2)
        tokens, targets = make_batch(rng, 8, 16, cfg.vocab)
        mesh = build_parallel_mesh(devices=jax.devices(), dp=2, ep=2, tp=2)
        l0, l1 = run_step(cfg, mesh, params, tokens, targets)
        assert np.isfinite(l0) and np.isfinite(l1)
        assert l1 < l0


def test_flash_attention_path_matches_ring(setup):
    """Forcing the Pallas flash path must agree with ring attention.
    (Off-TPU this runs the interpret-mode kernels with the vma checker
    gated off in _loss_spmd — the jax HLO interpreter's dynamic_slice
    vma check rejects valid interpret-mode pallas; see _loss_spmd.)"""
    cfg_ring, params, tokens, targets, mesh1, ref = setup
    cfg_flash = tfm.ModelConfig(**{**CFG, "attn_impl": "flash"})
    got = run_loss(cfg_flash, mesh1, params, tokens, targets)
    assert got == pytest.approx(ref, rel=1e-4, abs=1e-5)


def test_remat_train_step_matches_plain():
    """cfg.remat=True must not change the training math (loss parity
    with the plain config on one step)."""
    cfg_a = tfm.ModelConfig(**{**CFG, "microbatches": 2})
    cfg_b = tfm.ModelConfig(**{**CFG, "microbatches": 2, "remat": True})
    params = jax.device_get(tfm.init_params(jax.random.PRNGKey(3), cfg_a))
    rng = np.random.RandomState(3)
    tokens, targets = make_batch(rng, 8, 16, cfg_a.vocab)
    mesh = build_parallel_mesh(devices=jax.devices()[:4], pp=2, tp=2)
    la = run_loss(cfg_a, mesh, params, tokens, targets)
    lb = run_loss(cfg_b, mesh, params, tokens, targets)
    assert la == pytest.approx(lb, rel=1e-5)
    l0, l1 = run_step(cfg_b, mesh, params, tokens, targets)
    assert np.isfinite(l0) and l1 < l0
