"""tpu-tune — measured algorithm selection closing tuned's loop.

The reference reads operator-written dynamic rule files
(``coll_tuned_dynamic_file.c``) but ships nothing that GENERATES one;
tpu-tune measures every legal algorithm per (op, size) on the live
mesh and emits the file. These tests run the measure→emit→load→apply
cycle on the 8-device CPU mesh and pin the committed artifact
(tuning/cpu8_rules.conf).
"""

import os

import numpy as np
import pytest

import ompi_release_tpu as mpi
from ompi_release_tpu.coll import dynamic_rules
from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.tools import tpu_tune

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def world():
    yield mpi.init()


class TestTpuTune:
    def test_measure_emit_load_apply(self, world, tmp_path):
        results = tpu_tune.measure(
            world, ["allreduce", "alltoall"], [1024, 262144], repeats=2
        )
        assert results["allreduce"] and results["alltoall"]
        for rows in results.values():
            for row in rows:
                assert row["winner"] in row["times"]
                assert min(row["times"].values()) == \
                    row["times"][row["winner"]]

        text = tpu_tune.emit(world, results)
        p = tmp_path / "rules.conf"
        p.write_text(text)
        rules = dynamic_rules.load_rules(str(p))  # parses cleanly
        assert rules.get("allreduce")

        mca_var.set_value("coll_tuned_use_dynamic_rules", True)
        mca_var.set_value("coll_tuned_dynamic_rules_filename", str(p))
        try:
            # the rule table answers with the measured winner...
            first = results["allreduce"][0]
            got = dynamic_rules.lookup("allreduce", world.size,
                                       first["unit_bytes"])
            assert got == first["winner"], (got, first)
            # ...and the collective still computes the right thing
            # with the generated rules active
            x = np.ones((world.size, 64), np.float32)
            out = np.asarray(world.allreduce(x))
            assert (out == world.size).all()
        finally:
            mca_var.set_value("coll_tuned_use_dynamic_rules", False)
            mca_var.set_value("coll_tuned_dynamic_rules_filename", "")

    def test_checked_in_rules_parse_and_differ_from_fixed(self, world):
        """The committed artifact (generated on the 8-dev CPU mesh)
        loads, and at least one of its rules differs from the fixed
        decision constants — with the measurement justifying it in
        the adjacent comment (the VERDICT r4 item 8 'done' bar)."""
        path = os.path.join(REPO, "tuning", "cpu8_rules.conf")
        rules = dynamic_rules.load_rules(path)
        assert any(rules.values())
        text = open(path).read()
        assert "[differs from fixed constants" in text
        # every rule line's collective/algorithm already validated by
        # load_rules; check the justification comments carry timings
        assert "us" in text and "@" in text


class TestHierSweep:
    def test_emit_hier_rules_shape_and_parse(self, tmp_path):
        """emit_hier_rules renders last-match-wins threshold lines in
        the hier_* namespaces, justified by measurement comments, and
        the output parses through the real rule loader."""
        sweep = {"nprocs": 4, "results": {"allreduce": [
            {"size": 1024, "unit_bytes": 1024,
             "times": {"linear": 1e-3, "recursive_doubling": 5e-4},
             "winner": "recursive_doubling"},
            {"size": 1 << 20, "unit_bytes": 1 << 20,
             "times": {"ring": 1e-3, "recursive_doubling": 2e-3},
             "winner": "ring"},
        ], "bcast": [
            {"size": 1024, "unit_bytes": 1024,
             "times": {"binomial": 1e-4}, "winner": "binomial"},
        ]}}
        text = tpu_tune.emit_hier_rules(sweep)
        assert "hier_allreduce  0  0  recursive_doubling" in text
        assert "hier_allreduce  0  1048576  ring" in text
        assert "hier_bcast  0  0  binomial" in text
        assert "us" in text  # measurement justification comments
        p = tmp_path / "hier_rules.conf"
        p.write_text(text)
        rules = dynamic_rules.load_rules(str(p))
        assert len(rules["hier_allreduce"]) == 2

    def test_sweep_hier_loopback_job(self, tmp_path):
        """The real 2-process loopback sweep: every timed algorithm is
        a legal hier_allreduce rule name and the emitted file loads."""
        sweep = tpu_tune.sweep_hier(2, ["allreduce"], [4096], repeats=1)
        assert sweep is not None and sweep["nprocs"] == 2
        rows = sweep["results"]["allreduce"]
        assert rows, sweep
        legal = set(dynamic_rules.RULE_COLLECTIVES["hier_allreduce"])
        for row in rows:
            assert row["winner"] in row["times"]
            assert set(row["times"]) <= legal
        p = tmp_path / "swept.conf"
        p.write_text(tpu_tune.emit_hier_rules(sweep))
        assert dynamic_rules.load_rules(str(p))["hier_allreduce"]
