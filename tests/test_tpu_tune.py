"""tpu-tune — measured algorithm selection closing tuned's loop.

The reference reads operator-written dynamic rule files
(``coll_tuned_dynamic_file.c``) but ships nothing that GENERATES one;
tpu-tune measures every legal algorithm per (op, size) on the live
mesh and emits the file. These tests run the measure→emit→load→apply
cycle on the 8-device CPU mesh and pin the committed artifact
(tuning/cpu8_rules.conf).
"""

import os

import numpy as np
import pytest

import ompi_release_tpu as mpi
from ompi_release_tpu.coll import dynamic_rules
from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.tools import tpu_tune

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def world():
    yield mpi.init()


class TestTpuTune:
    def test_measure_emit_load_apply(self, world, tmp_path):
        results = tpu_tune.measure(
            world, ["allreduce", "alltoall"], [1024, 262144], repeats=2
        )
        assert results["allreduce"] and results["alltoall"]
        for rows in results.values():
            for row in rows:
                assert row["winner"] in row["times"]
                assert min(row["times"].values()) == \
                    row["times"][row["winner"]]

        text = tpu_tune.emit(world, results)
        p = tmp_path / "rules.conf"
        p.write_text(text)
        rules = dynamic_rules.load_rules(str(p))  # parses cleanly
        assert rules.get("allreduce")

        mca_var.set_value("coll_tuned_use_dynamic_rules", True)
        mca_var.set_value("coll_tuned_dynamic_rules_filename", str(p))
        try:
            # the rule table answers with the measured winner...
            first = results["allreduce"][0]
            got = dynamic_rules.lookup("allreduce", world.size,
                                       first["unit_bytes"])
            assert got == first["winner"], (got, first)
            # ...and the collective still computes the right thing
            # with the generated rules active
            x = np.ones((world.size, 64), np.float32)
            out = np.asarray(world.allreduce(x))
            assert (out == world.size).all()
        finally:
            mca_var.set_value("coll_tuned_use_dynamic_rules", False)
            mca_var.set_value("coll_tuned_dynamic_rules_filename", "")

    def test_checked_in_rules_parse_and_differ_from_fixed(self, world):
        """The committed artifact (generated on the 8-dev CPU mesh)
        loads, and at least one of its rules differs from the fixed
        decision constants — with the measurement justifying it in
        the adjacent comment (the VERDICT r4 item 8 'done' bar)."""
        path = os.path.join(REPO, "tuning", "cpu8_rules.conf")
        rules = dynamic_rules.load_rules(path)
        assert any(rules.values())
        text = open(path).read()
        assert "[differs from fixed constants" in text
        # every rule line's collective/algorithm already validated by
        # load_rules; check the justification comments carry timings
        assert "us" in text and "@" in text


class TestHierSweep:
    def test_emit_hier_rules_shape_and_parse(self, tmp_path):
        """emit_hier_rules renders last-match-wins threshold lines in
        the hier_* namespaces, justified by measurement comments, and
        the output parses through the real rule loader."""
        sweep = {"nprocs": 4, "results": {"allreduce": [
            {"size": 1024, "unit_bytes": 1024,
             "times": {"linear": 1e-3, "recursive_doubling": 5e-4},
             "winner": "recursive_doubling"},
            {"size": 1 << 20, "unit_bytes": 1 << 20,
             "times": {"ring": 1e-3, "recursive_doubling": 2e-3},
             "winner": "ring"},
        ], "bcast": [
            {"size": 1024, "unit_bytes": 1024,
             "times": {"binomial": 1e-4}, "winner": "binomial"},
        ]}}
        text = tpu_tune.emit_hier_rules(sweep)
        assert "hier_allreduce  0  0  recursive_doubling" in text
        assert "hier_allreduce  0  1048576  ring" in text
        assert "hier_bcast  0  0  binomial" in text
        assert "us" in text  # measurement justification comments
        p = tmp_path / "hier_rules.conf"
        p.write_text(text)
        rules = dynamic_rules.load_rules(str(p))
        assert len(rules["hier_allreduce"]) == 2

    def test_sweep_hier_loopback_job(self, tmp_path):
        """The real 2-process loopback sweep: every timed algorithm is
        a legal hier_allreduce rule name and the emitted file loads."""
        sweep = tpu_tune.sweep_hier(2, ["allreduce"], [4096], repeats=1)
        assert sweep is not None and sweep["nprocs"] == 2
        rows = sweep["results"]["allreduce"]
        assert rows, sweep
        legal = set(dynamic_rules.RULE_COLLECTIVES["hier_allreduce"])
        for row in rows:
            assert row["winner"] in row["times"]
            assert set(row["times"]) <= legal
        p = tmp_path / "swept.conf"
        p.write_text(tpu_tune.emit_hier_rules(sweep))
        assert dynamic_rules.load_rules(str(p))["hier_allreduce"]


class TestFingerprintStamping:
    def test_measured_fingerprint_shapes(self):
        from ompi_release_tpu.tuning import db as tdb

        # no hier sweep: the single-process in-process mesh
        assert tpu_tune.measured_fingerprint() == tdb.LOCAL
        # a hier sweep without host grouping: one fake host per proc
        fp = tpu_tune.measured_fingerprint(4, 0)
        assert fp == tdb.Fingerprint(1, 4, ("shm",), 4)
        # grouped into hosts of 2: a spanning shm+dcn layout
        fp = tpu_tune.measured_fingerprint(4, 2)
        assert fp == tdb.Fingerprint(2, 2, ("shm", "dcn"), 4)
        # ragged grouping pins ppn to 0
        fp = tpu_tune.measured_fingerprint(5, 2)
        assert fp.procs_per_host == 0 and fp.hosts == 3

    def test_stamped_rules_round_trip_through_the_db(self, tmp_path):
        """What `tpu-tune --db DIR` does: stamp the emitted text with
        the measured fingerprint, register it as a versioned entry,
        and the entry both loads and is selected for that topology."""
        from ompi_release_tpu.tuning import db as tdb

        fp = tpu_tune.measured_fingerprint(4, 2)
        text = tdb.stamp("hier_allreduce  0  0  multiring\n", fp)
        assert text.startswith("# fingerprint: " + fp.canon())
        path = tdb.TuningDb(str(tmp_path)).register(text, fp)
        got_fp, version = tdb.read_header(path)
        assert got_fp == fp and version == 1
        assert dynamic_rules.load_rules(path)["hier_allreduce"] \
            == [(0, 0, "multiring", None)]
        assert tdb.TuningDb(str(tmp_path)).best_match(fp) == path

    def test_hier_sweep_menu_includes_the_topo_family(self):
        """The --hier-procs sweep times whatever ALGORITHMS lists, so
        the topology-aware variants are swept (and legal rule names)."""
        from ompi_release_tpu.coll import hier_schedules as hs

        assert {"multiring", "torus2d"} \
            <= set(hs.ALGORITHMS["allreduce"])
        assert "torus2d" in hs.ALGORITHMS["bcast"]
        assert "torus2d" in hs.ALGORITHMS["allgather"]
        legal = set(dynamic_rules.RULE_COLLECTIVES["hier_allreduce"])
        assert {"multiring", "torus2d"} <= legal
        # the worker app literally iterates ALGORITHMS and the
        # hosts-per grouping knob reaches it via the env plumbing
        assert "ALGORITHMS[op]" in tpu_tune._HIER_TUNE_APP
        assert "OMPITPU_HIER_TUNE_HOSTS_PER" in tpu_tune._HIER_TUNE_APP

    def test_sweep_hier_hosts_per_times_topo_schedules(self, tmp_path):
        """A real 4-process sweep grouped into fake hosts of 2: the
        multiring/torus2d schedules run over an actual shm/DCN split
        and land in the timed menu."""
        sweep = tpu_tune.sweep_hier(4, ["allreduce"], [262144],
                                    repeats=1, hosts_per=2)
        assert sweep is not None and sweep["hosts_per"] == 2
        rows = sweep["results"]["allreduce"]
        assert rows, sweep
        timed = set().union(*(row["times"] for row in rows))
        assert {"multiring", "torus2d"} <= timed, timed
        # ...and the torus family ACTUALLY ran: a ragged fake-host
        # grouping (e.g. the 1-based NODE_ID taken as 0-based) would
        # degrade torus2d to the flat ring while still "timing" it
        assert sweep.get("topo_runs", 0) > 0, sweep
