"""Native plan executor: ``coll/native_exec.py`` + ``native/planexec.cc``.

Four layers:

1. DEVICE-FREE units — the descriptor blob round-trips through the C
   parser (``build_blob`` -> ``PlanExec``), the byte-provenance
   matcher (``_match_payload``) decomposes payloads over an arena and
   refuses every ambiguous case loudly, and ``try_compile`` withdraws
   gracefully (returns None, touches nothing) when the cvar is off or
   the .so lacks the symbols.
2. SATELLITE units — ``PlannedXchg.exchange``'s per-fire fast path
   never calls ``np.asarray`` for inputs that already are ndarrays
   (monkeypatch-counted), and the striper's frame-count discipline
   gates bursts at their real cost while dropping drained streams
   without buying window for them.
3. REAL 3-process jobs — the executor engages on a recursive-doubling
   allreduce (``plan_native_fires`` advances, zero fallback copies,
   bitwise-stable results), and a mixed fleet (one rank opted out via
   the ``coll_plan_native`` cvar) interoperates frame-for-frame: the
   wire bytes are the contract, so results stay bitwise identical.
4. FAULT TOLERANCE — a SIGKILL mid-plan-fire surfaces as the typed
   ERR_PROC_FAILED naming the dead process within the detection
   interval (the C slice loop re-checks the FT epoch between 100 ms
   slices; it never turns into an untyped 30 s timeout).
"""

import os
import sys
import textwrap

import numpy as np
import pytest

from ompi_release_tpu.coll import native_exec as nx
from ompi_release_tpu.coll import plan as cplan
from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.runtime.state import JobState
from ompi_release_tpu.runtime.wire import WireRouter
from ompi_release_tpu.tools.tpurun import Job
from ompi_release_tpu.utils.errors import ErrorCode, MPIError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

needs_native = pytest.mark.skipif(
    not nx.available(), reason="planexec symbols not in the loaded .so")


# ---------------------------------------------------------------------------
# 1. descriptor blob round-trip (device-free)
# ---------------------------------------------------------------------------

class TestBlob:
    @needs_native
    def test_round_trip_through_c_parser(self):
        """A hand-built two-round descriptor table parses: counts and
        the 8-aligned pool layout come back through the handle."""
        from ompi_release_tpu.native.bindings import PlanExec

        rounds = [
            {"depth": 2,
             "streams": [(0, [(b"PRE0", b"MID0", 64, 0, 64,
                               ((0, 0, 0, 64),))])],
             "rsrcs": [(1, [(0, 24, 0, 24, b"PRE1", b"MID1")])]},
            {"depth": 2,
             "streams": [(1, [(b"PRE2", b"MID2", 24, 0, 24,
                               ((1, 0, 0, 24),))])],
             "rsrcs": [(0, [(1, 64, 0, 64, b"PRE3", b"MID3")])]},
        ]
        blob = nx.build_blob(7, [64], [24, 64], [3, 5], rounds)
        px = PlanExec(blob)
        try:
            assert px.round_count == 2
            assert px.input_count == 1
            assert px.pool_count == 2
            # 24 is already 8-aligned, so the layout is 0 / 24 / 88
            assert px.pool_total == 88
        finally:
            px.close()

    @needs_native
    def test_garbage_blob_is_rejected(self):
        from ompi_release_tpu.native.bindings import PlanExec

        with pytest.raises(Exception):
            PlanExec(b"not a descriptor table at all")

    def test_align8(self):
        assert [nx._align8(v) for v in (0, 1, 7, 8, 9)] == \
            [0, 8, 8, 8, 16]


# ---------------------------------------------------------------------------
# 1b. byte-provenance matcher (device-free)
# ---------------------------------------------------------------------------

def _arena_of(*regions):
    """Build an arena the way the prober does: random separators
    around every region; returns (arena, a_arr, bounds)."""
    rng = np.random.default_rng(0xBEEF)
    arrs = [np.frombuffer(r, dtype=np.uint8) for r in regions]
    arena, bounds = nx._build_arena(
        rng, arrs, [])  # all regions as "inputs"
    return arena, np.frombuffer(arena, dtype=np.uint8), bounds


class TestMatchPayload:
    def test_whole_region_and_slice(self):
        rng = np.random.default_rng(1)
        r0 = rng.bytes(64)
        arena, a_arr, bounds = _arena_of(r0)
        segs = nx._match_payload(r0, arena, a_arr, bounds)
        assert segs == ((0, 0, 0, 64),)
        segs = nx._match_payload(r0[16:48], arena, a_arr, bounds)
        assert segs == ((0, 0, 16, 32),)

    def test_concatenation_across_regions(self):
        """A payload stitched from two source regions decomposes into
        two segs — the scatter-gather form the C executor emits."""
        rng = np.random.default_rng(2)
        r0, r1 = rng.bytes(64), rng.bytes(64)
        arena, a_arr, bounds = _arena_of(r0, r1)
        segs = nx._match_payload(r0[:32] + r1[32:], arena, a_arr,
                                 bounds)
        assert segs == ((0, 0, 0, 32), (0, 1, 32, 32))

    def test_adjacent_spans_merge(self):
        rng = np.random.default_rng(3)
        r0 = rng.bytes(256)
        arena, a_arr, bounds = _arena_of(r0)
        # one contiguous source span must come back as ONE seg even
        # though matching proceeds window by window
        segs = nx._match_payload(r0, arena, a_arr, bounds)
        assert len(segs) == 1

    def test_duplicate_regions_resolve_deterministically(self):
        """Bytes appearing in two regions (a round-0 send aliasing an
        argument) resolve to a FIXED pick — longest span, then lowest
        arena offset — so both probe seeds infer the same map and the
        cross-probe equality proof stays meaningful."""
        rng = np.random.default_rng(4)
        dup = rng.bytes(32)
        arena, a_arr, bounds = _arena_of(dup, dup)
        assert nx._match_payload(dup, arena, a_arr, bounds) == \
            ((0, 0, 0, 32),)
        # the longer candidate wins even when it sits later
        rng = np.random.default_rng(7)
        tail = rng.bytes(32)
        arena, a_arr, bounds = _arena_of(dup, dup + tail)
        assert nx._match_payload(dup + tail, arena, a_arr, bounds) \
            == ((0, 1, 0, 64),)

    def test_foreign_bytes_fail(self):
        rng = np.random.default_rng(5)
        arena, a_arr, bounds = _arena_of(rng.bytes(64))
        with pytest.raises(nx._ProbeFail):
            nx._match_payload(rng.bytes(32), arena, a_arr, bounds)

    def test_tiny_payload_fails(self):
        rng = np.random.default_rng(6)
        r0 = rng.bytes(64)
        arena, a_arr, bounds = _arena_of(r0)
        with pytest.raises(nx._ProbeFail):
            nx._match_payload(r0[:8], arena, a_arr, bounds)


# ---------------------------------------------------------------------------
# 1c. graceful withdrawal
# ---------------------------------------------------------------------------

class _Plan:
    def __init__(self):
        rnd = cplan.WireRound(((1, (((4,), "int32"),)),), ((1, 1),),
                              ((1, (None,)),), 9, 2)
        self.rounds = [rnd]
        self.gen = 0
        self.cid = 1
        self.timeout_ms = 1000


class _State:
    def __init__(self):
        self.plan = _Plan()


class TestWithdrawal:
    def test_cvar_off_withdraws(self):
        old = mca_var.get("coll_plan_native", True)
        mca_var.set_value("coll_plan_native", 0)
        try:
            # m is never touched once the cvar says no
            assert nx.try_compile(_State(), object(), None, (), {}) \
                is None
        finally:
            mca_var.set_value("coll_plan_native", old)

    def test_missing_symbols_withdraw(self, monkeypatch):
        monkeypatch.setattr(nx, "available", lambda: False)
        assert nx.try_compile(_State(), object(), None, (), {}) is None

    def test_inline_sentinel_withdraws(self):
        # obs_sentinel=2 interleaves ctl frames with the planned
        # rounds — the C reap would stash them mid-fire, so the
        # executor must leave inline-checked comms to PlannedXchg
        # (the gate once read a nonexistent cvar name and engaged
        # anyway, derailing the sentinel's posting seq)
        old = mca_var.get("obs_sentinel", 0)
        mca_var.set_value("obs_sentinel", 2)
        try:
            assert nx.try_compile(_State(), object(), None, (), {}) \
                is None
        finally:
            mca_var.set_value("obs_sentinel", old)

    def test_try_compile_never_raises(self):
        # a state with no plan, then one whose module explodes on
        # attribute access: both are selection outcomes, not errors
        class _NoPlan:
            plan = None

        assert nx.try_compile(_NoPlan(), object(), None, (), {}) is None

        class _Hostile:
            def __getattr__(self, k):
                raise RuntimeError("boom")

        assert nx.try_compile(_State(), _Hostile(), None, (), {}) \
            is None


# ---------------------------------------------------------------------------
# 2a. satellite: PlannedXchg per-fire asarray skip
# ---------------------------------------------------------------------------

class _FakeModule:
    """Minimal stand-in honoring the slice of the hier-module contract
    PlannedXchg uses: planned sends and arrival-order reaping."""

    def __init__(self, arrivals):
        self.arrivals = arrivals
        self.sent = []

        class _C:
            name = "fake_comm"

        self.comm = _C()

    def _send_all_planned(self, rnd, sends):
        self.sent.append((rnd, sends))

    def _reap(self, recvs, cb, timeout_ms, record=True):
        for src, cnt in sorted(recvs.items()):
            for k in range(cnt):
                cb(src, self.arrivals[src][k])


def _one_round_plan(peer=1, src=2, shape=(8,), dtype="int32"):
    rnd = cplan.WireRound(
        ((peer, ((shape, dtype),)),), ((src, 1),),
        ((peer, (None,)),), 11, 2)
    return cplan.WirePlan(0, 1, [rnd], 1000)


class TestAsarraySkip:
    def test_as_nd_is_identity_for_ndarrays(self, monkeypatch):
        calls = []
        real = np.asarray
        monkeypatch.setattr(
            cplan, "_np_asarray",
            lambda a, *k, **kw: calls.append(1) or real(a, *k, **kw))
        a = np.arange(4, dtype=np.int32)
        assert cplan._as_nd(a) is a
        assert not calls
        assert cplan._as_nd([1, 2]).tolist() == [1, 2]
        assert len(calls) == 1

    def test_round_meta_skips_converted_inputs(self, monkeypatch):
        calls = []
        real = np.asarray
        monkeypatch.setattr(
            cplan, "_np_asarray",
            lambda a, *k, **kw: calls.append(1) or real(a, *k, **kw))
        a = np.arange(8, dtype=np.int32)
        meta = cplan._round_meta({1: [a, a]})
        assert meta == ((1, (((8,), "int32"), ((8,), "int32"))),)
        assert not calls

    def test_planned_exchange_zero_asarray_for_ndarrays(
            self, monkeypatch):
        """The per-fire fast path: ndarray sends ride straight into
        the comparison tuple — zero conversions per exchange."""
        arr = np.arange(8, dtype=np.int32)
        m = _FakeModule({2: [np.ones(3, np.int32)]})
        px = cplan.PlannedXchg(m, _one_round_plan())
        calls = []
        real = np.asarray
        monkeypatch.setattr(
            cplan, "_np_asarray",
            lambda a, *k, **kw: calls.append(1) or real(a, *k, **kw))
        got = px.exchange({1: [arr]}, {2: 1})
        assert not calls
        assert got[2][0].tolist() == [1, 1, 1]
        # the planned send saw the SAME array object — no copy
        assert m.sent[0][1][1][0] is arr

    def test_planned_exchange_divergence_is_typed(self):
        m = _FakeModule({2: [np.ones(3, np.int32)]})
        px = cplan.PlannedXchg(m, _one_round_plan())
        with pytest.raises(MPIError) as ei:
            px.exchange({1: [np.zeros((9, 9), np.float64)]}, {2: 1})
        assert ei.value.code == ErrorCode.ERR_INTERN
        assert "diverged" in str(ei.value)


# ---------------------------------------------------------------------------
# 2b. satellite: frame-count-exact stripe gating
# ---------------------------------------------------------------------------

class _Arb:
    def __init__(self):
        self.events = []

    def enter(self, cls):
        self.events.append(("enter", cls))

    def gate(self, cls, cost=1):
        self.events.append(("gate", cls, cost))

    def leave(self, cls):
        self.events.append(("leave", cls))


def _gen(log, label, n):
    for k in range(n):
        log.append((label, k))
        yield


class TestStripeCounts:
    def test_partial_tail_gates_at_real_cost(self):
        """counts=(5, 2), depth=3: stream B's single burst costs 2,
        stream A's tail burst costs 2 — never the full depth."""
        log, arb = [], _Arb()
        WireRouter._stripe([_gen(log, "a", 5), _gen(log, "b", 2)], 3,
                           arbiter=arb, cls="bulk", counts=(5, 2))
        gates = [e for e in arb.events if e[0] == "gate"]
        assert gates == [("gate", "bulk", 3), ("gate", "bulk", 2),
                         ("gate", "bulk", 2)]
        assert len([e for e in log if e[0] == "a"]) == 5
        assert len([e for e in log if e[0] == "b"]) == 2
        assert arb.events[0] == ("enter", "bulk")
        assert arb.events[-1] == ("leave", "bulk")

    def test_drained_stream_is_dropped_without_gating(self):
        """A zero-count stream must not pass the gate NOR be pulled:
        window bought for frames that never exist starves the other
        classes for nothing."""
        log, arb = [], _Arb()
        WireRouter._stripe([_gen(log, "a", 4), _gen(log, "dead", 9)],
                           2, arbiter=arb, cls="lat", counts=(4, 0))
        gates = [e for e in arb.events if e[0] == "gate"]
        assert gates == [("gate", "lat", 2), ("gate", "lat", 2)]
        assert not [e for e in log if e[0] == "dead"]

    def test_legacy_no_counts_gates_full_depth(self):
        """Without counts (interpreted path) behavior is unchanged:
        every live stream's burst is gated at the full depth."""
        log, arb = [], _Arb()
        WireRouter._stripe([_gen(log, "a", 4)], 3,
                           arbiter=arb, cls="c", counts=None)
        gates = [e for e in arb.events if e[0] == "gate"]
        assert gates == [("gate", "c", 3), ("gate", "c", 3)]
        assert len(log) == 4

    def test_no_arbiter_counts_still_bound_pulls(self):
        log = []
        g = _gen(log, "a", 9)
        WireRouter._stripe([g], 4, counts=(6,))
        # exactly the counted frames were pulled, none past the plan
        assert len(log) == 6


# ---------------------------------------------------------------------------
# 3 + 4. real 3-process jobs
# ---------------------------------------------------------------------------

APP_PRELUDE = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, %r)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import ompi_release_tpu as mpi
    from ompi_release_tpu.mca import pvar, var as mca_var
    from ompi_release_tpu.runtime.runtime import Runtime

    def _pv(name):
        p = pvar.PVARS.lookup(name)
        return float(p.read()) if p is not None else 0.0

    world = mpi.init()
    rt = Runtime.current()
    me = rt.bootstrap["process_index"]
    off = rt.local_rank_offset
    n = world.size
    mca_var.set_value("hier_inter_algorithm", "recursive_doubling")
""" % REPO)


def _run_job(tmp_path, capfd, body, n=3, timeout=240, job_kw=None):
    app = tmp_path / "app.py"
    app.write_text(APP_PRELUDE + textwrap.dedent(body))
    job = Job(n, [sys.executable, str(app)], [],
              heartbeat_s=0.5, miss_limit=8, **(job_kw or {}))
    rc = job.run(timeout_s=timeout)
    out = capfd.readouterr()
    return rc, out.out + out.err, job


class TestNativeJobs:
    def test_native_engages_bitwise_stable(self, tmp_path, capfd):
        """Recursive-doubling allreduce on 3 processes: the plan
        freezes on fire 1, compiles natively, and every later fire
        runs the whole frozen schedule C-side — fires counted, zero
        per-fire fallbacks, zero contiguous-path copies, and the
        results bitwise-identical to the recorded (interpreted)
        fire."""
        rc, out, _ = _run_job(tmp_path, capfd, """
            x = np.stack([np.arange(256, dtype=np.int32)
                          * (off + i + 1) for i in range(2)])
            want = sum(np.arange(256, dtype=np.int32) * (r + 1)
                       for r in range(n))
            first = None
            for it in range(5):
                got = np.asarray(world.allreduce(x))
                np.testing.assert_array_equal(got[0], want)
                if first is None:
                    first = got.copy()
                np.testing.assert_array_equal(got, first)  # BITWISE
            fires = _pv("plan_native_fires")
            assert fires >= 3, fires
            assert _pv("plan_native_fallbacks") == 0
            assert _pv("plan_pool_hits") >= fires
            assert _pv("plan_pool_bytes") > 0
            assert _pv("wire_native_fallback_copies") == 0
            world.barrier()
            print(f"NATIVE-OK {me} fires={fires}", flush=True)
            mpi.finalize()
        """)
        assert rc == 0, out
        for me in range(3):
            assert f"NATIVE-OK {me} " in out

    def test_mixed_fleet_bitwise_parity(self, tmp_path, capfd):
        """One rank opts out (cvar off — same wire position as a rank
        whose .so lacks the symbols): its fires stay interpreted,
        the others go native, and because the wire bytes are the
        contract the results are STILL bitwise identical on every
        rank."""
        rc, out, _ = _run_job(tmp_path, capfd, """
            if me == 2:
                mca_var.set_value("coll_plan_native", 0)
            x = np.stack([np.arange(128, dtype=np.int32)
                          * (off + i + 1) for i in range(2)])
            want = sum(np.arange(128, dtype=np.int32) * (r + 1)
                       for r in range(n))
            for it in range(4):
                got = np.asarray(world.allreduce(x))
                np.testing.assert_array_equal(got[0], want)  # BITWISE
            fires = _pv("plan_native_fires")
            if me == 2:
                assert fires == 0, fires
            else:
                assert fires >= 2, fires
            world.barrier()
            print(f"MIXED-OK {me} fires={fires}", flush=True)
            mpi.finalize()
        """)
        assert rc == 0, out
        for me in range(3):
            assert f"MIXED-OK {me} " in out

    def test_sigkill_mid_plan_fire_is_typed_and_fast(
            self, tmp_path, capfd):
        """FT contract: rank 1 dies between native fires; the
        survivors' next fire surfaces ERR_PROC_FAILED (or the revoke
        that follows) naming the dead process well inside the
        detection interval — the C slice loop re-checks the FT word
        every ~100 ms, so death never becomes a silent hang."""
        rc, out, _ = _run_job(tmp_path, capfd, """
            x = np.stack([np.arange(64, dtype=np.int32)
                          * (off + i + 1) for i in range(2)])
            for it in range(3):  # freeze + native fires
                world.allreduce(x)
            assert me == 2 or _pv("plan_native_fires") >= 1
            world.barrier()
            if me == 1:
                time.sleep(0.5)
                import signal
                os.kill(os.getpid(), signal.SIGKILL)
            t0 = time.monotonic()
            try:
                for it in range(50):
                    world.allreduce(x)
                raise AssertionError("collective with dead peer ran")
            except mpi.MPIError as e:
                dt = time.monotonic() - t0
                assert e.code in (mpi.ErrorCode.ERR_PROC_FAILED,
                                  mpi.ErrorCode.ERR_REVOKED), e
                assert dt < 20, f"typed error took {dt:.1f}s"
                if e.code == mpi.ErrorCode.ERR_PROC_FAILED:
                    assert "1" in str(e)  # names the dead process
            print(f"FT-NATIVE-OK {me}", flush=True)
            mpi.finalize()
        """, job_kw={"on_failure": "continue"})
        assert rc == 0, out
        assert "FT-NATIVE-OK 0" in out
        assert "FT-NATIVE-OK 2" in out
