"""Observability plane: journal, HISTOGRAM/AGGREGATE pvars, MPI_T
sessions, skew metrics, exporters, metrics RPC, tracer fixes.

Fast tier-1 coverage for `ompi_release_tpu/obs/` plus the pvar-session
semantics the MPI_T shim promises (session-relative deltas, reset).
The trace-overhead guard is @slow (excluded from tier-1).
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import ompi_release_tpu as mpi
from ompi_release_tpu import obs
from ompi_release_tpu.mca import mpit, pvar as pvar_mod
from ompi_release_tpu.obs import export as obs_export
from ompi_release_tpu.obs.journal import Journal
from ompi_release_tpu.obs import skew as obs_skew
from ompi_release_tpu.utils.errors import MPIError


@pytest.fixture(scope="module")
def world():
    yield mpi.init()


@pytest.fixture()
def obs_on():
    """Observability enabled for the test, always restored after."""
    obs.journal.clear()
    obs.enable()
    try:
        yield obs
    finally:
        obs.disable()
        obs.journal.clear()


# ---------------------------------------------------------------------------
# pvar classes
# ---------------------------------------------------------------------------

class TestPvarClasses:
    def test_histogram_log2_buckets(self, fresh_mca):
        h = pvar_mod.histogram("lat", "latency")
        for v in (0.0, 0.75, 1.0, 1.5, 2.0, 7.0):
            h.observe(v)
        snap = h.read()
        assert snap["count"] == 6
        assert snap["min"] == 0.0 and snap["max"] == 7.0
        assert snap["sum"] == pytest.approx(12.25)
        b = snap["buckets"]
        # 0.0 -> the 0-bound bucket; 0.75/1.0 -> le 1; 1.5/2.0 -> le 2;
        # 7.0 -> le 8 (exact powers of two file in their own bound)
        assert b[0.0] == 1 and b[1.0] == 2 and b[2.0] == 2 and b[8.0] == 1
        assert sum(b.values()) == 6
        h.reset()
        assert h.read()["count"] == 0 and h.read()["buckets"] == {}

    def test_aggregate(self, fresh_mca):
        a = pvar_mod.aggregate("agg", "spread")
        a.observe(3.0)
        a.observe(-1.0)
        assert a.read() == {"count": 2, "sum": 2.0, "min": -1.0, "max": 3.0}
        a.reset()
        assert a.read()["count"] == 0

    def test_registry_dispatches_classes(self, fresh_mca):
        h = pvar_mod.PVARS.register("h", pvar_mod.PvarClass.HISTOGRAM)
        a = pvar_mod.PVARS.register("a", pvar_mod.PvarClass.AGGREGATE)
        assert isinstance(h, pvar_mod.Histogram)
        assert isinstance(a, pvar_mod.Aggregate)
        # generic .add() records an observation on both
        h.add(4.0)
        a.add(4.0)
        assert h.read()["count"] == 1 and a.read()["count"] == 1


# ---------------------------------------------------------------------------
# MPI_T pvar sessions (session-relative deltas, reset semantics)
# ---------------------------------------------------------------------------

class TestPvarSessions:
    def test_counter_session_delta_and_reset(self, fresh_mca):
        c = pvar_mod.counter("hits")
        c.add(5)
        sess = mpit.Mpit().pvar_session()
        h = sess.handle("hits")
        assert h.read() == 5.0          # absolute before start
        h.start()
        c.add(2)
        assert h.read() == 2.0          # session-relative
        h.reset()                       # rebase within the session
        assert h.read() == 0.0
        c.add(1)
        assert h.read() == 1.0
        h.stop()
        assert h.read() == 8.0          # absolute again after stop
        sess.free()
        with pytest.raises(MPIError):
            sess.handle("hits")         # closed session refuses handles

    def test_histogram_session_delta(self, fresh_mca):
        hist = pvar_mod.histogram("lat")
        hist.observe(1.0)
        hist.observe(2.0)
        sess = mpit.Mpit().pvar_session()
        h = sess.handle("lat")
        h.start()
        hist.observe(4.0)
        d = h.read()
        assert d["count"] == 1.0 and d["sum"] == 4.0
        assert sum(d["buckets"].values()) == 1.0
        # extrema are not invertible over a window: current passes thru
        assert d["max"] == 4.0
        sess.free()

    def test_aggregate_session_delta(self, fresh_mca):
        agg = pvar_mod.aggregate("skew")
        agg.observe(10.0)
        sess = mpit.Mpit().pvar_session()
        h = sess.handle("skew")
        h.start()
        agg.observe(2.0)
        agg.observe(6.0)
        d = h.read()
        assert d["count"] == 2.0 and d["sum"] == 8.0

    def test_unknown_pvar_raises(self, fresh_mca):
        sess = mpit.Mpit().pvar_session()
        with pytest.raises(MPIError):
            sess.handle("no_such_pvar")


# ---------------------------------------------------------------------------
# journal ring buffer
# ---------------------------------------------------------------------------

class TestJournal:
    def test_ring_wrap_keeps_newest(self):
        j = Journal(size=8)
        for i in range(20):
            j.record(f"op{i}", "t", float(i), 0.001, nbytes=i)
        spans = j.snapshot()
        assert len(spans) == 8
        assert [s.op for s in spans] == [f"op{i}" for i in range(12, 20)]
        assert spans[0].seq == 12 and spans[-1].seq == 19  # monotonic
        assert j.total_recorded == 20 and j.dropped == 12

    def test_resize_preserves_newest(self):
        j = Journal(size=4)
        for i in range(6):
            j.record(f"op{i}", "t", float(i), 0.0)
        j.resize(2)
        assert [s.op for s in j.snapshot()] == ["op4", "op5"]
        j.resize(16)
        assert [s.op for s in j.snapshot()] == ["op4", "op5"]
        j.record("op6", "t", 6.0, 0.0)
        assert j.snapshot()[-1].seq == 6  # seq continuity across resize

    def test_clear_keeps_seq_monotonic(self):
        j = Journal(size=4)
        j.record("a", "t", 0.0, 0.0)
        j.clear()
        assert len(j) == 0
        sp = j.record("b", "t", 1.0, 0.0)
        assert sp.seq == 1

    def test_enable_applies_cvar_size(self):
        from ompi_release_tpu.mca import var as mca_var

        old = obs.journal.size
        try:
            mca_var.set_value("obs_journal_size", 32)
            obs.enable()
            assert obs.journal.size == 32
        finally:
            mca_var.VARS.unset("obs_journal_size")
            obs.disable()
            obs.journal.resize(old)


# ---------------------------------------------------------------------------
# end-to-end: instrumented collectives -> journal + pvars + exporters
# ---------------------------------------------------------------------------

class TestEndToEnd:
    def test_allreduce_alltoall_populate_plane(self, world, obs_on,
                                               tmp_path):
        n = world.size
        x = np.random.RandomState(0).randn(n, 64).astype(np.float32)
        world.allreduce(x)
        world.alltoall(np.arange(n * n, dtype=np.float32).reshape(n, n))

        # (a) journal has coll-layer spans for both ops
        ops_seen = {s.op for s in obs.journal.snapshot()
                    if s.layer == "coll"}
        assert {"allreduce", "alltoall"} <= ops_seen

        # (b) latency histograms have non-empty buckets, skew pvars
        # exist and counted — all readable through MPI_T handles
        sess = mpit.Mpit().pvar_session()
        lat = sess.handle("coll_allreduce_latency").read()
        assert lat["count"] >= 1 and sum(lat["buckets"].values()) >= 1
        for op in ("allreduce", "alltoall"):
            skew = sess.handle(f"coll_{op}_skew_seconds").read()
            assert skew["count"] >= 1 and skew["max"] >= 0.0
            size_h = sess.handle(f"coll_{op}_msg_bytes").read()
            assert size_h["count"] >= 1
        sess.free()

        # (c) Perfetto trace round-trips as valid trace_event JSON
        path = obs_export.dump_chrome_trace(str(tmp_path / "trace.json"))
        with open(path) as f:
            doc = json.load(f)
        evs = [e for e in doc["traceEvents"] if e.get("ph") != "M"]
        assert evs and all(
            {"name", "ph", "ts", "pid", "tid"} <= set(e) for e in evs
        )
        assert any(e["cat"] == "coll" for e in evs)
        # thread_name metadata names each layer row
        meta = [e for e in doc["traceEvents"] if e.get("ph") == "M"]
        assert any(e["args"].get("name") == "coll" for e in meta)

        # JSONL dump mirrors the snapshot
        jl = obs_export.dump_jsonl(str(tmp_path / "j.jsonl"))
        with open(jl) as f:
            lines = [json.loads(ln) for ln in f]
        assert len(lines) == len(obs.journal.snapshot())

        # prometheus page lists the per-op histograms with buckets
        page = obs_export.prometheus_text()
        assert "ompitpu_coll_allreduce_latency_bucket" in page
        assert "ompitpu_coll_allreduce_skew_seconds_count" in page

    def test_p2p_and_wait_spans(self, world, obs_on):
        req = world.isend(np.arange(8, dtype=np.float32), 1, tag=7,
                          rank=0)
        world.recv(source=0, tag=7, rank=1)
        req.wait()
        layers = {s.layer for s in obs.journal.snapshot()}
        assert {"pml", "request", "peruse"} <= layers
        ops = {s.op for s in obs.journal.snapshot() if s.layer == "pml"}
        assert {"isend", "deliver"} <= ops

    def test_disabled_records_nothing(self, world):
        obs.disable()
        obs.journal.clear()
        n = world.size
        world.allreduce(np.ones((n, 8), np.float32))
        assert len(obs.journal) == 0


# ---------------------------------------------------------------------------
# tracer satellite: kwargs payloads + per-event flush + journal feed
# ---------------------------------------------------------------------------

class _FakeComm:
    def allreduce(self, *args, **kw):
        vals = list(args) + list(kw.values())
        return vals[0]


class TestTracer:
    def test_kwargs_payload_counted(self):
        from ompi_release_tpu.tools import trace

        tc = trace.wrap(_FakeComm())
        tc.allreduce(x=np.ones(16, np.float32))
        assert tc.events[0].nbytes == 64  # keyword buffers count too

    def test_sink_flushed_per_event(self, tmp_path):
        from ompi_release_tpu.tools import trace

        sink = str(tmp_path / "trace.jsonl")
        tc = trace.wrap(_FakeComm(), sink_path=sink)
        tc.allreduce(np.ones(4, np.float32))
        # WITHOUT close(): a crashed run must already see the line
        with open(sink) as f:
            lines = [json.loads(ln) for ln in f]
        assert len(lines) == 1 and lines[0]["op"] == "allreduce"
        assert lines[0]["bytes"] == 16
        tc.close()

    def test_tracer_feeds_journal(self, obs_on):
        from ompi_release_tpu.tools import trace

        tc = trace.wrap(_FakeComm())
        tc.allreduce(np.ones(4, np.float32))
        pmpi = [s for s in obs.journal.snapshot() if s.layer == "pmpi"]
        assert pmpi and pmpi[-1].op == "allreduce"
        assert pmpi[-1].nbytes == 16


# ---------------------------------------------------------------------------
# metrics RPC (tpu_server) + selftest entry point
# ---------------------------------------------------------------------------

class TestMetricsRpc:
    def test_server_serves_prometheus_page(self):
        from ompi_release_tpu.tools.tpu_server import (NameClient,
                                                       NameServer)

        srv = NameServer()
        client = NameClient("127.0.0.1", srv.port)
        try:
            # name service still works alongside the metrics RPC
            client.publish("obs-metrics-test", "4242")
            assert client.lookup("obs-metrics-test") == "4242"
            page = client.metrics()
            assert "ompitpu_obs_journal_events" in page
            # every registered pvar appears (spot-check a framework one)
            assert "ompitpu_requests_created" in page
            assert "# TYPE ompitpu_requests_created counter" in page
        finally:
            client.close()
            srv.shutdown()


# ---------------------------------------------------------------------------
# plan-relative flight recorder (obs/ledger)
# ---------------------------------------------------------------------------


class TestLedger:
    """Device-free coverage of the compiled-fire flight recorder: the
    fixed-size binary record round-trips, device and spanning records
    expand into journal-shaped synthetic spans with the interpreted
    path's flow-id derivation, the ring wraps (dropping oldest, pvar-
    counted), and the watchdog postmortem contributor is wired."""

    @pytest.fixture(autouse=True)
    def fresh_ledger(self):
        from ompi_release_tpu.obs import ledger
        ledger._reset_for_tests()
        yield ledger
        ledger._reset_for_tests()

    def test_record_roundtrip_fixed_size(self, fresh_ledger):
        led = fresh_ledger
        pid = led.register_device_plan(7, "allreduce", 4096, "sig")
        seq = led.record_fire(led.KIND_DEVICE, pid, 7, 1.0, 2.5)
        assert seq == 0
        recs = led.records()
        assert len(recs) == 1
        r = recs[0]
        assert r == {"kind": led.KIND_DEVICE, "cid": 7, "plan": pid,
                     "seq": 0, "round0": 0, "t_start": 1.0,
                     "t_end": 2.5, "round_ts": []}
        # the raw slot really is one fixed-size bytes record
        raw = next(b for b in led._ring if b is not None)
        assert isinstance(raw, bytes) and len(raw) == led._HDR.size
        # spanning records grow exactly 8 bytes per timed wire round
        seq2 = led.record_fire(led.KIND_SPANNING, pid, 7, 1.0, 2.0,
                               round0=3, round_ts=(1.25, 1.75))
        assert seq2 == 1
        r2 = led.records(since_seq=0)[0]
        assert r2["round_ts"] == [1.25, 1.75] and r2["round0"] == 3

    def test_device_record_expands_to_coll_span(self, fresh_ledger):
        led = fresh_ledger
        pid = led.register_device_plan(5, "bcast", 1 << 20)
        led.record_fire(led.KIND_DEVICE, pid, 5, 10.0, 10.5)
        doc = led.snapshot()
        spans = led.expand_dump(doc)
        assert len(spans) == 1
        s = spans[0]
        assert s["op"] == "bcast" and s["layer"] == "coll"
        assert s["comm"] == 5 and s["bytes"] == 1 << 20
        assert s["t"] == 10.0 and s["dt"] == 0.5
        assert s["ledger"] is True

    def test_spanning_flow_ids_pair_across_ranks(self, fresh_ledger):
        """Sender and receiver re-derive flow ids independently from
        COMPLEMENTARY frozen structures — the ids must meet."""
        from types import SimpleNamespace
        led = fresh_ledger
        arrs = [((4,), "float32"), ((4,), "float32")]
        # rank 0 sends two messages to rank 1 in round 0
        rnd0 = SimpleNamespace(sends_meta=[(1, arrs)], recvs_t=[])
        p0 = led.register_spanning_plan(9, "allreduce", 0, [rnd0])
        # rank 1 receives two messages from rank 0 in round 0
        rnd1 = SimpleNamespace(sends_meta=[], recvs_t=[(0, 2)])
        p1 = led.register_spanning_plan(9, "allreduce", 1, [rnd1])
        plan_docs = {str(k): v for k, v in led.plans().items()}
        rec0 = {"kind": led.KIND_SPANNING, "cid": 9, "plan": p0,
                "seq": 0, "round0": 4, "t_start": 0.0, "t_end": 1.0,
                "round_ts": [1.0]}
        rec1 = dict(rec0, plan=p1)
        s0 = led.expand_record(rec0, plan_docs)
        s1 = led.expand_record(rec1, plan_docs)
        sends = [s for s in s0 if s.get("fs") == "s"]
        recvs = [s for s in s1 if s.get("fs") == "t"]
        assert len(sends) == len(recvs) == 2
        assert [s["flow"] for s in sends] == [r["flow"] for r in recvs]
        assert len(set(s["flow"] for s in sends)) == 2  # distinct k
        # the per-round span names the compiled collective round and
        # carries the frozen send bytes
        rnd_span = next(s for s in s0
                        if s["op"] == "allreduce_wire_round0")
        assert rnd_span["bytes"] == 2 * 16 and rnd_span["layer"] == "hier"

    def test_ring_wraps_dropping_oldest(self, fresh_ledger):
        from ompi_release_tpu.mca import pvar as pv
        led = fresh_ledger
        pid = led.register_device_plan(1, "x", 0)
        led.resize(4)
        d0 = pv.PVARS.lookup("ledger_dropped").read()
        for i in range(6):
            led.record_fire(led.KIND_DEVICE, pid, 1, float(i),
                            float(i) + 0.5)
        recs = led.records()
        assert [r["seq"] for r in recs] == [2, 3, 4, 5]  # newest 4
        assert pv.PVARS.lookup("ledger_dropped").read() - d0 == 2
        led.resize(2)  # shrink keeps the newest records
        assert [r["seq"] for r in led.records()] == [4, 5]

    def test_watchdog_contributor_carries_the_tail(self, fresh_ledger):
        from ompi_release_tpu.obs import watchdog
        led = fresh_ledger
        fn = watchdog._contributors.get("ledger_tail")
        assert fn is not None, "ledger tail not wired into postmortems"
        pid = led.register_device_plan(3, "gather", 64)
        led.record_fire(led.KIND_DEVICE, pid, 3, 0.0, 1.0)
        doc = fn()
        assert doc["records"][-1]["cid"] == 3
        assert doc["plans"][str(pid)]["name"] == "gather"
        assert doc["total"] >= 1

    def test_dump_loads_back_and_doctor_attaches(self, fresh_ledger,
                                                 tmp_path):
        from ompi_release_tpu.obs import doctor
        led = fresh_ledger
        pid = led.register_device_plan(2, "allgather", 128)
        led.record_fire(led.KIND_DEVICE, pid, 2, 0.0, 0.25)
        path = led.dump(str(tmp_path / "ledger-p0.json"))
        doc = doctor.load_ledger_dump(path)
        assert doc["format"] == led.FORMAT
        dumps = doctor.load_dir(str(tmp_path))  # ledger-only dir
        assert len(dumps) == 1
        assert any(s.get("ledger") for s in dumps[0]["spans"])


def test_selftest_entry_point():
    """`python -m ompi_release_tpu.obs --selftest` is tier-1 runnable."""
    from conftest import subprocess_env

    proc = subprocess.run(
        [sys.executable, "-m", "ompi_release_tpu.obs", "--selftest"],
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        env=subprocess_env(), capture_output=True, text=True, timeout=120,
    )
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "obs selftest: ok" in proc.stdout


# ---------------------------------------------------------------------------
# trace-overhead guard (journal disabled => <5% on a small allreduce)
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_disabled_overhead_guard(world):
    obs.disable()
    n = world.size
    x = np.ones((n, 256), np.float32)
    world.allreduce(x)  # compile/warm

    def loop(k):
        t0 = time.perf_counter()
        for _ in range(k):
            world.allreduce(x)
        return time.perf_counter() - t0

    per_call = min(loop(50) for _ in range(3)) / 50

    # the plane's entire disabled-mode cost is its emit gates: measure
    # the gate directly and bound a generous 16-gates-per-call budget
    # against 5% of the measured op time
    K = 200_000
    t0 = time.perf_counter()
    for _ in range(K):
        if obs.enabled:
            pass  # pragma: no cover
    gate = (time.perf_counter() - t0) / K
    assert gate * 16 < 0.05 * per_call, (
        f"emit gates cost {gate * 16:.3e}s/call vs "
        f"{0.05 * per_call:.3e}s budget"
    )

    # sanity: enabling the full plane stays the same order of magnitude
    obs.journal.clear()
    obs.enable()
    try:
        t_on = min(loop(50) for _ in range(3)) / 50
    finally:
        obs.disable()
        obs.journal.clear()
    assert t_on < per_call * 3, (per_call, t_on)
