"""btl/nativewire — the zero-copy native datapath.

Three layers of proof:

- **byte identity**: the native frame stream's scatter-gather lists
  join byte-identical to the portable staged frames across a
  segsize x lane matrix (same ``FrameTemplate`` authority, same xfer
  counter), and the two framings INTEROPERATE on real sockets in both
  directions (portable sender -> native receiver and back), CRC
  enforced end to end.
- **selection / graceful degradation**: the MCA component withdraws
  when the capability is absent (env kill-switch, cvar, missing
  symbols); per-peer eligibility is both-ended and card-driven, so a
  peer that never advertised falls back to the portable path.
- **real jobs**: 3-process loopback worlds run collective families
  bitwise-parity over the shm-ring mode AND the forced cross-host
  vectored-socket mode; a mixed fleet (one rank opted out) proves the
  per-peer fallback; a SIGKILLed sender mid-transfer surfaces as the
  typed ERR_PROC_FAILED through the shm ring's dead-producer check.
"""

import itertools
import os
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

from ompi_release_tpu.btl import components as btl_comps
from ompi_release_tpu.btl import nativewire as nw
from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.runtime.state import JobState
from ompi_release_tpu.tools.tpurun import Job
from ompi_release_tpu.utils.errors import ErrorCode, MPIError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

try:
    from ompi_release_tpu.native import wire_symbols_available
    _NATIVE = bool(wire_symbols_available())
except Exception:
    _NATIVE = False

needs_native = pytest.mark.skipif(
    not _NATIVE, reason="native wire symbols unavailable (no "
    "toolchain); the portable staged path is covered elsewhere")

#: the wire p2p tag base + lane stride (QoS lanes differ above bit 17)
USER_TAG = 1 << 20
LANE_STRIDE = 1 << 17


def _cards(hosts, capable=None, pids=None):
    """Modex cards for a fake fleet: ``hosts[i]`` is pidx i's host,
    ``capable`` the set of pidxs advertising the native datapath."""
    capable = set(range(len(hosts))) if capable is None else capable
    out = []
    for i, h in enumerate(hosts):
        card = {"host": h,
                "pid": (pids or {}).get(i, os.getpid()),
                "node_id": i + 1}
        if i in capable:
            card[nw.CARD_KEY] = f"tok{i}:4:{8 << 20}"
        out.append(card)
    return out


class _CaptureEp:
    """OOB stand-in recording every frame as its joined wire bytes —
    sendv MUST be byte-equivalent to send(b"".join(parts))."""

    def __init__(self):
        self.frames = []

    def send(self, nid, tag, data):
        self.frames.append(bytes(data))

    def sendv(self, nid, tag, parts):
        self.frames.append(b"".join(bytes(p) for p in parts))


@pytest.fixture
def seg(request):
    mca_var.set_value("wire_pipeline_segsize", str(request.param))
    try:
        yield int(request.param)
    finally:
        mca_var.VARS.unset("wire_pipeline_segsize")


class TestByteIdentity:
    """The native stream is the SAME framing, not a compatible one."""

    @pytest.mark.parametrize("seg", [256, 1000, 64 * 1024],
                             indirect=True)
    @pytest.mark.parametrize("lane", [0, 1, 3])
    @pytest.mark.parametrize(
        "dtype,n", [(np.float32, 7321), (np.int16, 4096),
                    (np.uint8, 1)])
    def test_matrix_native_frames_equal_staged_frames(
            self, seg, lane, dtype, n):
        """segsize x lane x dtype: b''.join of every native
        scatter-gather list == the portable staged frame, including
        the ragged tail fragment and the header."""
        cards = _cards(["hostA", "hostB"])  # distinct: sendv path
        mod = nw.NativeWireBtl()
        mod.bind(cards, 0)
        x = (np.arange(n) % 251).astype(dtype)
        tag = USER_TAG + lane * LANE_STRIDE + 5
        saved = btl_comps._xfer_ids
        try:
            btl_comps._xfer_ids = itertools.count(9000)
            ep = _CaptureEp()
            for _ in mod.frame_stream(ep, 1, tag, x):
                pass
            btl_comps._xfer_ids = itertools.count(9000)
            ref = list(btl_comps.DcnBtl().staged_frames(x, segsize=seg))
        finally:
            btl_comps._xfer_ids = saved
        assert len(ep.frames) == len(ref)
        assert ep.frames == ref

    @pytest.mark.parametrize("seg", [256], indirect=True)
    def test_planned_template_same_identity(self, seg):
        """The frozen-template (compiled-plan) leg of the native
        stream matches planned_frames bit for bit."""
        cards = _cards(["hostA", "hostB"])
        mod = nw.NativeWireBtl()
        mod.bind(cards, 0)
        x = np.arange(600, dtype=np.float64)
        tpl = btl_comps.plan_frame_template(x.shape, x.dtype, seg)
        saved = btl_comps._xfer_ids
        try:
            btl_comps._xfer_ids = itertools.count(77)
            ep = _CaptureEp()
            for _ in mod.frame_stream(ep, 1, USER_TAG + 9, x, tpl=tpl):
                pass
            btl_comps._xfer_ids = itertools.count(77)
            ref = list(btl_comps.DcnBtl().planned_frames(x, tpl))
        finally:
            btl_comps._xfer_ids = saved
        assert ep.frames == ref

    @pytest.mark.parametrize("seg", [256], indirect=True)
    def test_template_mismatch_is_loud(self, seg):
        cards = _cards(["hostA", "hostB"])
        mod = nw.NativeWireBtl()
        mod.bind(cards, 0)
        tpl = btl_comps.plan_frame_template((8,), np.float32, seg)
        with pytest.raises(MPIError) as ei:
            for _ in mod.frame_stream(_CaptureEp(), 1, USER_TAG + 1,
                                      np.zeros(9, np.float32), tpl=tpl):
                pass
        assert ei.value.code == ErrorCode.ERR_INTERN


@needs_native
class TestSocketInterop:
    """Both framings on REAL sockets, mixed directions: the native
    receiver reassembles a portable sender's frames and vice versa —
    the wire contract that makes per-peer fallback safe mid-fleet."""

    def _pair(self):
        from ompi_release_tpu.native import OobEndpoint

        a, b = OobEndpoint(1), OobEndpoint(2)
        b.connect(1, "127.0.0.1", a.port)
        return a, b

    @pytest.mark.parametrize("seg", [1 << 16], indirect=True)
    def test_native_sender_portable_receiver(self, seg):
        a, b = self._pair()
        try:
            cards = _cards(["hostA", "hostB"])
            mod = nw.NativeWireBtl()
            mod.bind(cards, 1)  # sender is pidx 1 -> sendv to nid 1
            x = np.arange(300_000, dtype=np.float32)
            mod.send_staged(b, 1, USER_TAG + 3, x)
            got = btl_comps.DcnBtl().recv_staged(a, USER_TAG + 3)
            np.testing.assert_array_equal(np.asarray(got), x)
        finally:
            a.close()
            b.close()

    @pytest.mark.parametrize("seg", [1 << 16], indirect=True)
    def test_portable_sender_native_receiver(self, seg):
        a, b = self._pair()
        try:
            cards = _cards(["hostA", "hostB"])
            mod = nw.NativeWireBtl()
            mod.bind(cards, 0)  # receiver is pidx 0; sender pidx 1
            before = nw._native_bytes.read()
            x = np.arange(123_457, dtype=np.int32)
            btl_comps.DcnBtl().send_staged(b, 1, USER_TAG + 4, x)
            got = mod.recv_staged(a, USER_TAG + 4)
            np.testing.assert_array_equal(np.asarray(got), x)
            assert nw._native_bytes.read() - before == x.nbytes
        finally:
            a.close()
            b.close()

    @pytest.mark.parametrize("seg", [1 << 14], indirect=True)
    def test_crc_catches_corruption(self, seg):
        """A corrupted fragment payload fails the transfer CRC with
        the typed ERR_TRUNCATE — never silently wrong data."""
        a, b = self._pair()
        try:
            cards = _cards(["hostA", "hostB"])
            mod = nw.NativeWireBtl()
            mod.bind(cards, 0)
            x = np.arange(20_000, dtype=np.int32)
            frames = list(btl_comps.DcnBtl().staged_frames(
                x, segsize=seg))
            bad = bytearray(frames[-1])
            bad[-1] ^= 0xFF
            frames[-1] = bytes(bad)
            for fr in frames:
                b.send(1, USER_TAG + 6, fr)
            with pytest.raises(MPIError) as ei:
                mod.recv_staged(a, USER_TAG + 6, timeout_ms=10_000)
            assert ei.value.code == ErrorCode.ERR_TRUNCATE
        finally:
            a.close()
            b.close()

    @pytest.mark.parametrize("seg", [1 << 15], indirect=True)
    def test_shm_ring_loopback_same_process(self, seg):
        """Co-hosted mode in one process: fragments cross a real
        /dev/shm ring while the header rides the OOB; the zero-copy
        witness stays ~0 for a clean transfer."""
        a, b = self._pair()
        try:
            cards = _cards(["hostX", "hostX"])  # SAME host: ring mode
            tx = nw.NativeWireBtl()
            tx.bind(cards, 1)
            rx = nw.NativeWireBtl()
            rx.bind(cards, 0)
            x = np.arange(500_000, dtype=np.float32)
            b_fb = nw._fallback_copies.read()
            b_nb = nw._native_bytes.read()
            err = []

            def _send():
                try:
                    tx.send_staged(b, 1, USER_TAG + 8, x)
                except Exception as e:  # surfaced by the main thread
                    err.append(e)

            th = threading.Thread(target=_send, daemon=True)
            th.start()
            got = rx.recv_staged(a, USER_TAG + 8, timeout_ms=60_000)
            th.join(timeout=60)
            assert not err, err
            np.testing.assert_array_equal(np.asarray(got), x)
            assert nw._native_bytes.read() - b_nb == 2 * x.nbytes
            # clean same-tag transfer: no forced host copies at all
            assert nw._fallback_copies.read() == b_fb
        finally:
            a.close()
            b.close()
            # unlink any ring this test left mapped
            for mod in (locals().get("tx"), locals().get("rx")):
                if isinstance(mod, nw.NativeWireBtl):
                    mod._shutdown_rings()

    def test_shutdown_waits_for_unattached_consumer(self):
        """A completed send whose receiver hasn't attached yet must
        survive producer exit — the socket path parks such bytes in
        kernel buffers, so the ring path may not lose them either.
        ``_shutdown_rings`` holds the unlink until a consumer maps the
        ring, then finishes promptly (the mapping outlives the name)."""
        from ompi_release_tpu.native import ShmRing

        cards = _cards(["hostX", "hostX"])
        tx = nw.NativeWireBtl()
        tx.bind(cards, 1)
        ring, _lk = tx._tx_ring(0, 3)
        payload = np.arange(4096, dtype=np.int32).tobytes()
        assert ring.writev(77, [payload], 2000) == 0
        name = nw._ring_name(tx._cap(0)[0], 1, 3)
        th = threading.Thread(target=tx._shutdown_rings, daemon=True)
        th.start()
        time.sleep(0.25)
        assert th.is_alive(), \
            "shutdown unlinked a ring still holding undelivered bytes"
        late = ShmRing.attach(name, os.getpid())
        assert late is not None, "ring name vanished before attach"
        try:
            th.join(timeout=10)
            assert not th.is_alive(), "shutdown ignored the attach"
            buf = bytearray(len(payload))
            rc, tag = late.read_into(buf, 2000)
            assert rc >= 0 and tag == 77
            assert bytes(buf) == payload
        finally:
            late.close()
            ShmRing.unlink(name)


class TestSelectionAndFallback:
    """Graceful degradation is structural: MCA withdrawal + per-peer
    card checks, never a runtime surprise."""

    def test_component_registered_at_package_import(self):
        """Importing the btl package alone registers the component —
        a user listing the framework pre-init sees nativewire in the
        help banner even when query() would withdraw it."""
        out = subprocess.check_output(
            [sys.executable, "-c", textwrap.dedent(f"""
                import sys; sys.path.insert(0, {REPO!r})
                from ompi_release_tpu.btl import BTL_FRAMEWORK
                print([c.NAME for c in BTL_FRAMEWORK.components()])
            """)],
        )
        assert b"nativewire" in out

    def test_env_killswitch_withdraws_component(self, monkeypatch):
        monkeypatch.setenv("OMPITPU_NATIVEWIRE", "0")
        assert not nw.nativewire_ready()
        assert nw.modex_entry() == {}
        assert nw.NativeWireComponent().query() is None
        assert nw.module_for(_cards(["h", "h"]), 0) is None

    def test_cvar_killswitch_withdraws_component(self):
        mca_var.set_value("btl_nativewire_enable", "false")
        try:
            assert not nw.nativewire_ready()
            assert nw.NativeWireComponent().query() is None
        finally:
            mca_var.VARS.unset("btl_nativewire_enable")

    @needs_native
    def test_component_selected_when_ready(self):
        got = nw.NativeWireComponent().query()
        assert got is not None
        prio, mod = got
        assert prio == 45 and mod.NAME == "nativewire"
        # ranked between the shm handoff (50) and dcn (40)
        from ompi_release_tpu.btl import base as btl_base

        names = [c.NAME for c in btl_base.BTL_FRAMEWORK.components()]
        assert "nativewire" in names

    @needs_native
    def test_modex_card_roundtrip(self):
        entry = nw.modex_entry()
        assert set(entry) == {nw.CARD_KEY}
        token, slots, ring = nw._parse_card(entry[nw.CARD_KEY])
        assert token == nw._local_token()
        assert slots >= 1 and ring >= 1 << 16

    def test_parse_card_malformed_means_not_capable(self):
        assert nw._parse_card("garbage") is None
        assert nw._parse_card(None) is None
        assert nw._parse_card("") is None
        # floors: zero slots / tiny ring are clamped, not trusted
        token, slots, ring = nw._parse_card("t:0:1")
        assert slots == 1 and ring == 1 << 16

    def test_peer_capable_is_both_ended_and_live(self):
        cards = _cards(["h", "h", "h"], capable={0, 1})
        mod = nw.NativeWireBtl()
        mod.bind(cards, 0)
        assert mod.peer_capable(1)
        assert not mod.peer_capable(2)   # peer never advertised
        assert not mod.peer_capable(0)   # self is never a wire peer
        # respawn: the card is refreshed IN PLACE with a new token —
        # the verdict and parsed geometry must follow the live entry
        cards[1][nw.CARD_KEY] = f"fresh:2:{1 << 20}"
        assert mod.peer_capable(1)
        assert mod._cap(1)[0] == "fresh"
        del cards[1][nw.CARD_KEY]
        assert not mod.peer_capable(1)

    def test_peer_capable_needs_own_card(self):
        cards = _cards(["h", "h"], capable={1})
        mod = nw.NativeWireBtl()
        mod.bind(cards, 0)  # we never advertised: ring geometry absent
        assert not mod.peer_capable(1)

    def test_slot_hash_spreads_lanes(self):
        """QoS lanes (tag stride 1<<17) land on distinct rings instead
        of re-coupling head-of-line behind one FIFO."""
        slots = {nw._slot_of(USER_TAG + lane * LANE_STRIDE + 5, 4)
                 for lane in range(4)}
        assert len(slots) > 1
        for t in (USER_TAG, USER_TAG + 123456):
            assert nw._slot_of(t, 4) == nw._slot_of(t, 4)
            assert 0 <= nw._slot_of(t, 4) < 4
        assert nw._slot_of(USER_TAG, 1) == 0

    def test_host_array_copy_accounting(self):
        arr = np.arange(32, dtype=np.float32)
        out, copied = nw._host_array(arr)
        assert out is arr and not copied
        out, copied = nw._host_array(arr[::2])  # non-contiguous
        assert copied and out.flags["C_CONTIGUOUS"]
        out, copied = nw._host_array([1, 2, 3])  # no buffer protocol
        assert copied

    @pytest.mark.parametrize("seg", [1024], indirect=True)
    def test_incapable_peer_rides_portable_framing(self, seg):
        """frame_stream to a peer WITHOUT the card must produce the
        portable staged frames (single-yield, DcnBtl.send_staged)."""
        cards = _cards(["hostA", "hostB"], capable={0})
        mod = nw.NativeWireBtl()
        mod.bind(cards, 0)
        x = np.arange(2048, dtype=np.int16)
        saved = btl_comps._xfer_ids
        try:
            btl_comps._xfer_ids = itertools.count(31)
            ep = _CaptureEp()
            for _ in mod.frame_stream(ep, 1, USER_TAG + 2, x):
                pass
            btl_comps._xfer_ids = itertools.count(31)
            ref = list(btl_comps.DcnBtl().staged_frames(
                x, segsize=seg))
        finally:
            btl_comps._xfer_ids = saved
        assert ep.frames == ref


# ---------------------------------------------------------------------------
# real multi-process jobs
# ---------------------------------------------------------------------------

APP_PRELUDE = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, %r)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import ompi_release_tpu as mpi
    from ompi_release_tpu.runtime.runtime import Runtime
""" % REPO)


def _run_job(tmp_path, capfd, body, *, n=3, timeout=180, job_kw=None,
             mca=None):
    app = tmp_path / "nw_app.py"
    app.write_text(APP_PRELUDE + textwrap.dedent(body))
    kw = {"heartbeat_s": 0.5, "miss_limit": 8, **(job_kw or {})}
    job = Job(n, [sys.executable, str(app)], list(mca or ()), **kw)
    rc = job.run(timeout_s=timeout)
    out = capfd.readouterr()
    return rc, out.out + out.err, job


PARITY_BODY = """
    world = mpi.init()
    rt = Runtime.current()
    me = rt.bootstrap["process_index"]
    n = world.size
    off = rt.local_rank_offset
    nw = rt.wire._nw
    assert nw is not None, "native datapath did not come up"
    for p in range(3):
        if p != me:
            assert rt.wire._btl_for(p).NAME == "nativewire", \\
                rt.wire._btl_for(p).NAME
    from ompi_release_tpu.mca import pvar
    nb = pvar.PVARS.lookup("wire_native_bytes")

    # allreduce: bitwise int32 parity against the numpy total
    x = np.stack([np.arange(64, dtype=np.int32) * (off + i + 1)
                  for i in range(2)])
    got = np.asarray(world.allreduce(x))
    want = sum(np.arange(64, dtype=np.int32) * (r + 1)
               for r in range(n))
    for i in range(2):
        np.testing.assert_array_equal(got[i], want)

    # bcast from a cross-process root
    bx = (np.stack([np.arange(1024, dtype=np.int32) * 3] * 2)
          if off == 0 else np.zeros((2, 1024), np.int32))
    bgot = np.asarray(world.bcast(bx, root=0))
    np.testing.assert_array_equal(
        bgot[0], np.arange(1024, dtype=np.int32) * 3)

    # reduce_scatter + allgather round-trip
    rs = np.stack([np.arange(n * 8, dtype=np.int32) + 10 * (off + i)
                   for i in range(2)])
    rgot = np.asarray(world.reduce_scatter_block(rs))
    want_full = sum(np.arange(n * 8, dtype=np.int32) + 10 * r
                    for r in range(n))
    for i in range(2):
        r = off + i
        np.testing.assert_array_equal(
            rgot[i], want_full[r * 8:(r + 1) * 8])

    ag = np.asarray(world.allgather(
        np.stack([np.full(4, off + i, np.int32) for i in range(2)])))
    np.testing.assert_array_equal(
        ag[0].reshape(n, 4)[:, 0], np.arange(n, dtype=np.int32))

    # p2p across the process boundary
    if me == 0:
        world.send(np.arange(50_000, dtype=np.float32), n - 1,
                   tag=21, rank=0)
    elif me == 2:
        val, st = world.recv(source=0, tag=21, rank=n - 1)
        np.testing.assert_array_equal(
            np.asarray(val), np.arange(50_000, dtype=np.float32))
    world.barrier()
    assert float(nb.read()) > 0, "no bytes rode the native datapath"
    print(f"NW_PARITY_OK {me} native_bytes={float(nb.read()):.0f}",
          flush=True)
    mpi.finalize()
"""


@needs_native
class TestNativeJobs:
    def test_shm_ring_collectives_parity_3proc(self, tmp_path, capfd):
        """3 co-hosted processes: every cross-process byte rides the
        shm-ring mode; collective families parity-check bitwise and
        the native byte counter proves the path was really taken."""
        rc, out, job = _run_job(tmp_path, capfd, PARITY_BODY)
        assert rc == 0, out
        for me in range(3):
            assert f"NW_PARITY_OK {me}" in out, out
        assert job.job_state.visited(JobState.TERMINATED)

    def test_tcp_vectored_collectives_parity_3proc(self, tmp_path,
                                                   capfd):
        """Same families, forced cross-host (distinct OMPITPU_HOST_ID
        per worker): fragments ride the vectored-socket path."""
        body = """
    import os
    os.environ["OMPITPU_HOST_ID"] = (
        "nwhost-" + os.environ["OMPITPU_NODE_ID"])
""" + PARITY_BODY
        rc, out, job = _run_job(tmp_path, capfd, body)
        assert rc == 0, out
        for me in range(3):
            assert f"NW_PARITY_OK {me}" in out, out

    def test_mixed_fleet_per_peer_fallback(self, tmp_path, capfd):
        """One rank opts out (OMPITPU_NATIVEWIRE=0): capable pairs
        keep the native path, pairs touching the opted-out rank fall
        back per peer, and the whole world still parity-checks."""
        rc, out, _job = _run_job(tmp_path, capfd, """
    import os
    if os.environ["OMPITPU_NODE_ID"] == "3":
        os.environ["OMPITPU_NATIVEWIRE"] = "0"
    world = mpi.init()
    rt = Runtime.current()
    me = rt.bootstrap["process_index"]
    n = world.size
    off = rt.local_rank_offset
    if me == 2:
        assert rt.wire._nw is None
        for p in (0, 1):
            assert rt.wire._btl_for(p).NAME in ("shm", "dcn")
    else:
        nw = rt.wire._nw
        assert nw is not None
        other = 1 - me
        assert nw.peer_capable(other)
        assert not nw.peer_capable(2), "opted-out peer looked capable"
        assert rt.wire._btl_for(other).NAME == "nativewire"
        assert rt.wire._btl_for(2).NAME in ("shm", "dcn")
    x = np.stack([np.arange(32, dtype=np.int32) * (off + i + 1)
                  for i in range(2)])
    got = np.asarray(world.allreduce(x))
    want = sum(np.arange(32, dtype=np.int32) * (r + 1)
               for r in range(n))
    np.testing.assert_array_equal(got[0], want)
    if me == 0:
        world.send(np.arange(9999, dtype=np.int32), n - 1, tag=23,
                   rank=0)
    elif me == 2:
        val, st = world.recv(source=0, tag=23, rank=n - 1)
        np.testing.assert_array_equal(
            np.asarray(val), np.arange(9999, dtype=np.int32))
    world.barrier()
    print(f"NW_MIXED_OK {me}", flush=True)
    mpi.finalize()
""")
        assert rc == 0, out
        for me in range(3):
            assert f"NW_MIXED_OK {me}" in out, out

    def test_sigkill_mid_transfer_raises_proc_failed(self, tmp_path,
                                                     capfd):
        """A sender SIGKILLed mid-transfer (header sent, ring partly
        drained) surfaces as the typed ERR_PROC_FAILED through the shm
        ring's dead-producer check — fast, never the generic 30s
        ERR_PENDING timeout."""
        rc, out, _job = _run_job(tmp_path, capfd, """
    import signal, threading
    world = mpi.init()
    rt = Runtime.current()
    me = rt.bootstrap["process_index"]
    if me == 1:
        big = np.zeros(48 << 20, np.uint8)  # 48 MiB >> the 8 MiB ring

        def _s():
            world.send(big, 0, tag=25, rank=rt.local_rank_offset)

        threading.Thread(target=_s, daemon=True).start()
        time.sleep(1.0)  # header out, ring full, writev blocked
        os.kill(os.getpid(), signal.SIGKILL)
    time.sleep(2.0)  # let the sender die mid-stream first
    t0 = time.monotonic()
    try:
        world.recv(source=rt.local_size, tag=25, rank=0)
        raise AssertionError("recv from killed sender returned")
    except mpi.MPIError as e:
        dt = time.monotonic() - t0
        assert e.code == mpi.ErrorCode.ERR_PROC_FAILED, e
        assert dt < 20, f"typed error took {dt:.1f}s"
    print(f"NW_KILL_OK {me}", flush=True)
    mpi.finalize()
""", n=2, timeout=120, job_kw={"on_failure": "continue"})
        assert rc == 0, out
        assert "NW_KILL_OK 0" in out, out

    def test_sigstop_consumer_postmortem_names_ring(self, tmp_path,
                                                    capfd):
        """A consumer SIGSTOPped mid-drain leaves the sender blocked
        in the armed ``nw_ring_put`` wait; the stall watchdog's
        postmortem names the blocked ring token (the ``/onw-`` shm
        name), the frozen peer's pid, the direction, and the live
        occupancy — and the ``native_rings`` contributor carries every
        ring's counter block. A third rank SIGCONTs the consumer so
        the job still finishes clean (the typed-error contract for a
        DEAD peer is the previous test; a stopped peer is a stall, not
        a failure)."""
        import json

        pm_dir = tmp_path / "pm"
        pidf = tmp_path / "consumer.pid"
        rc, out, _job = _run_job(tmp_path, capfd, """
    import signal
    world = mpi.init()
    rt = Runtime.current()
    me = rt.bootstrap["process_index"]
    off = rt.local_rank_offset
    pidf = %(pidf)r
    big = np.zeros(48 << 20, np.uint8)  # 48 MiB >> the 8 MiB ring
    warm = np.ones(2 << 20, np.uint8)   # rides the native rings
    world.barrier()
    if me == 1:
        # warm transfer first: the consumer ATTACHES the rx ring
        # (stamping its pid into the shared header) before freezing
        world.send(warm, 0, tag=25, rank=off)
        world.recv(source=0, tag=27, rank=off)  # consumer frozen now
        world.send(big, 0, tag=25, rank=off)  # jams in nw_ring_put
        v, _st = world.recv(source=0, tag=26, rank=off)
        assert int(np.asarray(v)[0]) == 7
    elif me == 0:
        world.recv(source=rt.local_size, tag=25, rank=0)  # attach
        world.send(np.full(4, 9, np.int32), rt.local_size, tag=27,
                   rank=0)
        with open(pidf + ".tmp", "w") as f:
            f.write(str(os.getpid()))
        os.replace(pidf + ".tmp", pidf)  # rank 2's SIGCONT cue
        os.kill(os.getpid(), signal.SIGSTOP)  # freeze mid-transfer
        got, _st = world.recv(source=rt.local_size, tag=25, rank=0)
        assert np.asarray(got).nbytes == big.nbytes
        world.send(np.full(4, 7, np.int32), rt.local_size, tag=26,
                   rank=0)
    else:
        while not os.path.exists(pidf):
            time.sleep(0.05)
        time.sleep(5.0)  # stall timeout 1.5s: postmortem is on disk
        os.kill(int(open(pidf).read()), signal.SIGCONT)
    world.barrier()
    print(f"NW_STALL_OK {me}", flush=True)
    mpi.finalize()
""" % {"pidf": str(pidf)}, n=3, timeout=180,
            mca=[("obs_enable", "1"), ("obs_stall_timeout", "1.5"),
                 ("obs_postmortem_dir", str(pm_dir))],
            job_kw={"miss_limit": 40})
        assert rc == 0, out
        for me in range(3):
            assert f"NW_STALL_OK {me}" in out, out
        consumer_pid = int(pidf.read_text())
        pms = sorted(pm_dir.glob("postmortem-*.json"))
        assert pms, f"no postmortems in {pm_dir}: {out}"
        stalls = []
        rings_doc = None
        for p in pms:
            with open(p) as f:
                doc = json.load(f)
            if isinstance(doc.get("native_rings"), dict):
                rings_doc = doc["native_rings"]
            for w in doc.get("stalled") or []:
                if w.get("op") == "nw_ring_put":
                    stalls.append((doc["rank"], w))
        assert stalls, f"no nw_ring_put stall in {pms}"
        (rank, w), = stalls[:1]
        assert int(rank["pidx"]) == 1, stalls
        info = w["info"]
        assert info["ring"].startswith("/onw-"), info
        assert info["dir"] == "send", info
        assert int(info["peer_pid"]) == consumer_pid, (
            info, consumer_pid)
        assert info["occupancy"] > 0.5, info  # ring jammed full
        assert info["pending"] > 0 and info["capacity"] > 0, info
        # the fleet-wide ring table rode along in the same dump
        assert rings_doc is not None, pms
        assert rings_doc["tx"], rings_doc
        tx0 = rings_doc["tx"][0]
        assert tx0["name"].startswith("/onw-"), tx0
        assert tx0["stats"]["w_stalls"] >= 1, tx0
