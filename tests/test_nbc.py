"""Nonblocking & persistent collectives on the async progress engine —
the libnbc/opal_progress analogue.

Four layers:

1. The original dispatch properties (``coll/libnbc``'s contract,
   ``ompi/mca/coll/libnbc/nbc.c``): i-collectives RETURN before
   completion — dispatch never blocks (no ``block_until_ready``), and
   two i-collectives on DISJOINT communicators are concurrently in
   flight.
2. A PARITY MATRIX: every i-collective family × dtypes (including
   non-commutative exactness) against the blocking result, and
   MPI-4-style persistent ``*_init`` requests fired twice with buffer
   reuse — the plan is built once, start() re-reads the bound buffer.
3. Device-free units for ``runtime/progress.py``: posting-order drain
   in polling mode, off-caller execution + ``nbc_hidden_seconds``
   under the dedicated progress thread, error-on-progress, and the
   shared progress hook one ``wait_all`` tick drives.
4. Real 3-process ``tpurun`` jobs: the spanning-comm NBC path end to
   end (deferred dispatch, posting-order drain by a blocking
   collective, six-family parity, persistent restarts, two
   overlapping i-allreduces on disjoint communicators under the
   progress thread), and a hang-injection job proving the watchdog
   postmortem names the stuck NBC schedule.
"""

import json
import os
import sys
import textwrap
import time

import numpy as np
import pytest

import jax

import ompi_release_tpu as mpi
from ompi_release_tpu import ops
from ompi_release_tpu.mca import pvar
from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.request import request as req_mod
from ompi_release_tpu.request.request import Request
from ompi_release_tpu.runtime import progress as progress_mod
from ompi_release_tpu.runtime.state import JobState
from ompi_release_tpu.tools.tpurun import Job

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def world():
    return mpi.init()


@pytest.fixture(scope="module")
def halves(world):
    lo = world.create(world.group.incl([0, 1, 2, 3]), name="lo")
    hi = world.create(world.group.incl([4, 5, 6, 7]), name="hi")
    return lo, hi


def test_ibarrier_returns_before_completion(world, monkeypatch):
    """ibarrier must not block: its dispatch path may not call
    block_until_ready (round-1/2 regression: ibarrier ran the full
    blocking barrier before returning a completed request)."""
    world.barrier()  # warm the compiled program

    calls = []
    real = jax.block_until_ready

    def spy(x):
        calls.append(1)
        return real(x)

    monkeypatch.setattr(jax, "block_until_ready", spy)
    req = world.ibarrier()
    dispatch_blocked = len(calls)
    monkeypatch.undo()
    assert isinstance(req, Request)
    assert dispatch_blocked == 0, "ibarrier blocked during dispatch"
    req.wait()
    assert req.test()[0]


def test_iallreduce_dispatch_faster_than_completion(halves):
    """Dispatch of a large iallreduce returns well before the result
    is ready to fetch — XLA async dispatch is the progress engine."""
    lo, _ = halves
    x = np.ones((4, 4 << 20), np.float32)  # 64 MiB total
    np.asarray(lo.allreduce(x, ops.SUM))  # warm up + compile

    t0 = time.perf_counter()
    req = lo.iallreduce(x, ops.SUM)
    t_dispatch = time.perf_counter() - t0
    req.wait()
    out = np.asarray(req.value)
    t_total = time.perf_counter() - t0
    np.testing.assert_allclose(out[0], x.sum(0) / 1, rtol=1e-6)
    # dispatch must be a small fraction of end-to-end completion
    assert t_dispatch < 0.5 * t_total, (
        f"dispatch {t_dispatch:.4f}s vs total {t_total:.4f}s — "
        "iallreduce appears to block on dispatch"
    )


def test_disjoint_icollectives_both_in_flight(halves):
    """Two i-allreduces on disjoint comms are simultaneously in
    flight: the second dispatch returns while the first is still
    incomplete, and both are pending at once.

    Measured design note (the VERDICT-r2 #3 alternative): wall-clock
    overlap speedup is NOT observable on the CPU simulator by
    construction — the 8 virtual devices are threads on the same
    physical cores, so the "serial" baseline already saturates the
    machine (measured here: overlapped 0.33s vs serial 0.28s for
    2x64 MiB — contention, not serialization). XLA does NOT serialize
    the dispatches: both programs are enqueued asynchronously and are
    pending concurrently, which is the property that turns into
    wall-clock overlap on TPU where disjoint device sets are disjoint
    hardware."""
    lo, hi = halves
    x = np.ones((4, 4 << 20), np.float32)

    # warm both compiled programs
    jax.block_until_ready(lo.allreduce(x, ops.SUM))
    jax.block_until_ready(hi.allreduce(x, ops.SUM))

    ra = lo.iallreduce(x, ops.SUM)
    rb = hi.iallreduce(x, ops.SUM)
    # both dispatched, neither complete: concurrently in flight
    a_pending = not ra.test()[0]
    b_pending = not rb.test()[0]
    ra.wait()
    rb.wait()
    assert a_pending and b_pending, (
        f"a_pending={a_pending} b_pending={b_pending} — the second "
        "dispatch did not happen while the first was in flight"
    )


def test_icollectives_complete_with_values(world):
    """Every i-variant completes and yields the blocking result."""
    n = world.size
    x = np.arange(n * 8, dtype=np.float32).reshape(n, 8)
    reqs = {
        "iallreduce": world.iallreduce(x, ops.SUM),
        "ibcast": world.ibcast(x, root=2),
        "iallgather": world.iallgather(x),
        "ialltoall": world.ialltoall(x),
    }
    for name, req in reqs.items():
        req.wait()
        assert req.test()[0], name
    np.testing.assert_allclose(
        np.asarray(reqs["iallreduce"].value)[3], x.sum(0)
    )
    np.testing.assert_array_equal(np.asarray(reqs["ibcast"].value)[5], x[2])


# ---------------------------------------------------------------------------
# parity matrix: every i-family × dtypes vs the blocking result
# ---------------------------------------------------------------------------


class TestParityMatrix:
    def test_icoll_parity_matrix(self, world):
        """Nonblocking results are BITWISE the blocking results: the
        i-path runs the identical collective (same compiled program /
        same schedule) — only later. Covers all six families × int32
        and float32."""
        n = world.size
        counts = [2] * n
        for dtype in (np.int32, np.float32):
            x = np.arange(n * 8, dtype=dtype).reshape(n, 8)
            xrs = np.arange(n * 2 * n, dtype=dtype).reshape(n, 2 * n)
            xa2a = np.arange(n * n, dtype=dtype).reshape(n, n)
            cases = [
                ("iallreduce", world.iallreduce, (x, ops.SUM),
                 world.allreduce, (x, ops.SUM)),
                ("ibcast", world.ibcast, (x, 2), world.bcast, (x, 2)),
                ("iallgather", world.iallgather, (x,),
                 world.allgather, (x,)),
                ("ireduce_scatter", world.ireduce_scatter,
                 (xrs, counts), world.reduce_scatter, (xrs, counts)),
                ("ialltoall", world.ialltoall, (xa2a,),
                 world.alltoall, (xa2a,)),
            ]
            for name, ifn, iargs, bfn, bargs in cases:
                want = bfn(*bargs)
                req = ifn(*iargs)
                req.wait()
                assert req.test()[0], name
                got = req.value
                if isinstance(want, list):
                    for a, b in zip(got, want):
                        np.testing.assert_array_equal(
                            np.asarray(a), np.asarray(b),
                            err_msg=f"{name} {dtype}")
                else:
                    np.testing.assert_array_equal(
                        np.asarray(got), np.asarray(want),
                        err_msg=f"{name} {dtype}")
        rb = world.ibarrier()
        rb.wait()
        assert rb.test()[0]

    def test_icoll_noncommutative_exact(self, world):
        """Non-commutative ops keep the exact fold order through the
        nonblocking path — bitwise vs blocking (the same order-exact
        schedule runs either way)."""
        n = world.size
        sub = ops.user_op("nbc_sub", lambda a, b: a - b, commute=False)
        x = (np.arange(n * 6, dtype=np.float32).reshape(n, 6) + 1.0) \
            * 0.37
        want = np.asarray(world.allreduce(x, sub))
        req = world.iallreduce(x, sub)
        req.wait()
        np.testing.assert_array_equal(np.asarray(req.value), want)

    def test_persistent_families_fire_twice(self, world):
        """Every persistent family: inactive until start (MPI: an
        inactive request tests complete/empty), fires nonblocking, and
        a SECOND start re-reads the bound buffer (MPI persistent
        buffer reuse) after in-place mutation."""
        n = world.size
        x = np.arange(n * 8, dtype=np.int32).reshape(n, 8)
        xrs = np.arange(n * 2 * n, dtype=np.int32).reshape(n, 2 * n)
        xa2a = np.arange(n * n, dtype=np.int32).reshape(n, n)
        counts = [2] * n
        cases = [
            (world.allreduce_init(x), x,
             lambda: world.allreduce(x)),
            (world.bcast_init(x, root=1), x,
             lambda: world.bcast(x, root=1)),
            (world.allgather_init(x), x,
             lambda: world.allgather(x)),
            (world.reduce_scatter_init(xrs, counts), xrs,
             lambda: world.reduce_scatter(xrs, counts)),
            (world.alltoall_init(xa2a), xa2a,
             lambda: world.alltoall(xa2a)),
        ]
        for req, buf, blocking in cases:
            assert req.test() == (True, None)  # inactive
            for _ in range(2):
                want = blocking()
                req.start()
                req.wait()
                got = req.value
                if isinstance(want, list):
                    for a, b in zip(got, want):
                        np.testing.assert_array_equal(
                            np.asarray(a), np.asarray(b))
                else:
                    np.testing.assert_array_equal(
                        np.asarray(got), np.asarray(want))
                buf += 1  # in-place: start() must see the new bytes
        br = world.barrier_init()
        for _ in range(2):
            br.start()
            br.wait()
            assert br.test()[0]

    def test_persistent_start_on_active_raises(self, world):
        from ompi_release_tpu.utils.errors import MPIError

        with pytest.raises(MPIError):
            Request().start()  # non-persistent
        req = world.barrier_init()
        req.start()
        # double-start while ACTIVE must raise (MPI_Start on an active
        # persistent request is erroneous — allowing it would orphan a
        # schedule the peers still participate in)
        with pytest.raises(MPIError, match="active"):
            req.start()
        req.wait()
        req.start()  # complete -> restartable
        req.wait()


# ---------------------------------------------------------------------------
# progress-engine units (device-free)
# ---------------------------------------------------------------------------


class TestProgressEngine:
    def test_polling_drains_in_posting_order(self):
        """Polling mode: nothing runs at post; waiting a LATER op
        first completes every earlier op this thread posted — the
        program-order drain that keeps cross-process posting order."""
        eng = progress_mod.engine()
        order = []
        op1 = progress_mod.ScheduledOp(("t-order", 1), "a",
                                       lambda: order.append("a"))
        op2 = progress_mod.ScheduledOp(("t-order", 1), "b",
                                       lambda: order.append("b"))
        eng.post(op1)
        eng.post(op2)
        assert not op1.done.is_set() and not op2.done.is_set()
        eng.wait(op2)
        assert order == ["a", "b"]
        assert op1.done.is_set()

    def test_error_surfaces_at_wait(self):
        eng = progress_mod.engine()

        def boom():
            raise RuntimeError("schedule died")

        op = progress_mod.ScheduledOp(("t-err", 1), "boom", boom)
        eng.post(op)
        with pytest.raises(RuntimeError, match="schedule died"):
            eng.wait(op)

    def test_thread_mode_runs_off_caller_and_hides_time(self):
        """progress_thread on: a posted schedule completes with NO
        wait from the caller, and its run time lands in the
        nbc_hidden_seconds pvar (it overlapped 'caller compute')."""
        eng = progress_mod.engine()
        hidden = pvar.PVARS.lookup("nbc_hidden_seconds")
        h0 = float(hidden.read())
        mca_var.set_value("progress_thread", True)
        try:
            op = progress_mod.ScheduledOp(
                ("t-thread", 1), "bg", lambda: time.sleep(0.03) or 7)
            eng.post(op)
            assert op.done.wait(5.0), "progress thread never ran it"
            assert eng.wait(op) == 7
            assert float(hidden.read()) - h0 >= 0.02
        finally:
            mca_var.VARS.unset("progress_thread")

    def test_polling_wait_exposes_time(self):
        """Polling mode: the schedule runs INSIDE wait(), so none of
        its time is hidden — the pvar must not grow."""
        eng = progress_mod.engine()
        hidden = pvar.PVARS.lookup("nbc_hidden_seconds")
        op = progress_mod.ScheduledOp(
            ("t-expose", 1), "fg", lambda: time.sleep(0.02) or 1)
        eng.post(op)
        h0 = float(hidden.read())
        assert eng.wait(op) == 1
        assert float(hidden.read()) - h0 == pytest.approx(0.0, abs=1e-9)

    def test_wait_all_drives_shared_hook_once_per_pass(self, monkeypatch):
        """wait_all/test_all tick the SHARED progress hook — one tick
        advances all pending requests — instead of spinning blind."""
        ticks = []
        monkeypatch.setattr(req_mod, "_progress_hooks",
                            list(req_mod._progress_hooks))
        req_mod.register_progress_hook(lambda: ticks.append(1) or 0)
        done_reqs = []
        for _ in range(3):
            r = Request()
            r.complete(value=1)
            done_reqs.append(r)
        req_mod.wait_all(done_reqs)
        assert len(ticks) == 1  # one pass, one tick
        ticks.clear()
        ok, _ = req_mod.test_all(done_reqs)
        assert ok and len(ticks) == 1

    def test_from_future_wait_drives_hook(self, monkeypatch):
        from concurrent.futures import ThreadPoolExecutor

        ticks = []
        monkeypatch.setattr(req_mod, "_progress_hooks",
                            list(req_mod._progress_hooks))
        req_mod.register_progress_hook(lambda: ticks.append(1) or 0)
        with ThreadPoolExecutor(1) as pool:
            fut = pool.submit(lambda: time.sleep(0.05) or "v")
            req = req_mod.from_future(fut)
            st = req.wait()
            assert st is not None
            assert req.value == "v"
        assert ticks, "bare wait() never ticked the progress hook"

    def test_advance_toward_kicks_background_drainer(self):
        """Polling mode: a test()-POLL-LOOP on a queued schedule must
        complete it WITHOUT a wait() (the MPI_Test progress rule — the
        kick drainer replaces the deleted per-comm worker). The FIRST
        advance must NOT spawn a drainer: Request.wait() performs one
        internal test before blocking, and wait-only users must never
        see a thread (nor pollute the polling-mode hidden-seconds
        witness)."""
        eng = progress_mod.engine()
        op = progress_mod.ScheduledOp(("t-kick", 1), "k", lambda: 5)
        eng.post(op)
        eng.advance_toward(op)  # wait()'s single internal test
        time.sleep(0.05)
        assert not op.done.is_set(), "first test alone must not kick"
        eng.advance_toward(op)  # second consecutive poll = a real loop
        assert op.done.wait(5.0), "kick drainer never ran the schedule"
        assert eng.wait(op) == 5

    def test_inflight_pvar_tracks_registry(self):
        eng = progress_mod.engine()
        level = pvar.PVARS.lookup("nbc_schedules_inflight")
        base = int(level.read())
        op = progress_mod.ScheduledOp(("t-level", 1), "x", lambda: 0)
        eng.post(op)
        assert int(level.read()) == base + 1
        eng.wait(op)
        assert int(level.read()) == base


# ---------------------------------------------------------------------------
# real tpurun jobs: the spanning-comm NBC path + hang injection
# ---------------------------------------------------------------------------

APP_PRELUDE = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, %r)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import ompi_release_tpu as mpi
    from ompi_release_tpu import ops as _ops
    from ompi_release_tpu.mca import pvar, var as mca_var
    from ompi_release_tpu.request import request as req_mod
    from ompi_release_tpu.runtime.runtime import Runtime

    def _pv(name):
        p = pvar.PVARS.lookup(name)
        return float(p.read()) if p is not None else 0.0
""" % REPO)


def _run(tmp_path, capfd, body, n=3, timeout=240, mca=()):
    app = tmp_path / "app.py"
    app.write_text(APP_PRELUDE + textwrap.dedent(body))
    job = Job(n, [sys.executable, str(app)], list(mca),
              heartbeat_s=0.5, miss_limit=8)
    rc = job.run(timeout_s=timeout)
    out = capfd.readouterr()
    assert rc == 0, out.out + out.err
    assert job.job_state.visited(JobState.TERMINATED)
    return out.out


class TestNbcJobs:
    def test_nbc_spanning_job(self, tmp_path, capfd):
        """The whole spanning NBC story on one 3-process 6-rank world:
        dispatch performs NO block_until_ready and stays pending
        (polling mode defers execution); a blocking collective posted
        after drains the earlier i-op first (posting order); all six
        families wait to bitwise parity; a persistent request restarts
        against mutated buffers; and with the progress thread enabled
        two i-allreduces on DISJOINT spanning communicators complete
        with no wait() from the caller, hiding their comm time
        (nbc_hidden_seconds > 0)."""
        out = _run(tmp_path, capfd, """
            world = mpi.init()
            rt = Runtime.current()
            me = rt.bootstrap["process_index"]
            off = rt.local_rank_offset
            n = world.size
            x = np.stack([np.arange(16, dtype=np.int32) * (off + i + 1)
                          for i in range(2)])
            want = sum(np.arange(16, dtype=np.int32) * (r + 1)
                       for r in range(n))

            # dispatch: pure enqueue — no device sync, stays pending
            calls = []
            real = jax.block_until_ready
            jax.block_until_ready = (
                lambda v: (calls.append(1), real(v))[1])
            r1 = world.iallreduce(x)
            dispatched = len(calls)
            jax.block_until_ready = real
            assert dispatched == 0, dispatched
            # posting order: the blocking barrier drains r1 FIRST
            world.barrier()
            assert r1.test()[0], "barrier did not drain the iallreduce"
            np.testing.assert_array_equal(np.asarray(r1.value)[0], want)

            # a test()-only polling loop completes (MPI_Test progress
            # rule: the first test kicks a background drainer)
            r2 = world.iallreduce(x)
            deadline = time.time() + 60
            while not r2.test()[0]:
                assert time.time() < deadline, "test() never completed"
                time.sleep(0.005)
            np.testing.assert_array_equal(np.asarray(r2.value)[0], want)

            # six families, blocking-vs-nonblocking bitwise parity
            xb = np.stack([np.arange(8, dtype=np.int32)
                           + 10 * (off + i) for i in range(2)])
            xa2a = np.stack([np.arange(n, dtype=np.int32)
                             + 100 * (off + i) for i in range(2)])
            xrs = np.stack([np.full(n * 2, off + i + 1, np.int32)
                            for i in range(2)])
            counts = [2] * n
            exp = {
                "bcast": np.asarray(world.bcast(xb, root=3)),
                "allgather": np.asarray(world.allgather(xb)),
                "alltoall": np.asarray(world.alltoall(xa2a)),
                "rs": [np.asarray(a) for a in
                       world.reduce_scatter(xrs, counts)],
            }
            reqs = [world.iallreduce(x), world.ibcast(xb, root=3),
                    world.iallgather(xb),
                    world.ireduce_scatter(xrs, counts),
                    world.ialltoall(xa2a), world.ibarrier()]
            req_mod.wait_all(reqs)
            np.testing.assert_array_equal(
                np.asarray(reqs[0].value)[0], want)
            np.testing.assert_array_equal(
                np.asarray(reqs[1].value), exp["bcast"])
            np.testing.assert_array_equal(
                np.asarray(reqs[2].value), exp["allgather"])
            for a, b in zip(reqs[3].value, exp["rs"]):
                np.testing.assert_array_equal(np.asarray(a), b)
            np.testing.assert_array_equal(
                np.asarray(reqs[4].value), exp["alltoall"])

            # persistent: plan once, fire twice, buffer reuse
            pr = world.allreduce_init(x)
            assert pr.test() == (True, None)
            pr.start(); pr.wait()
            np.testing.assert_array_equal(
                np.asarray(pr.value)[0], want)
            x[:] *= 2
            pr.start(); pr.wait()
            np.testing.assert_array_equal(
                np.asarray(pr.value)[0], want * 2)
            starts = _pv("nbc_persistent_starts")
            assert starts >= 2, starts

            # disjoint comms under the dedicated progress thread:
            # both complete with NO wait from the caller
            A = world.create(world.group.incl([0, 2, 4]), name="A")
            B = world.create(world.group.incl([1, 3, 5]), name="B")
            mca_var.set_value("progress_thread", True)
            xa = np.ones((1, 2048), np.float32) * (me + 1)
            h0 = _pv("nbc_hidden_seconds")
            ra = A.iallreduce(xa)
            rb = B.iallreduce(xa)
            deadline = time.time() + 60
            while not (ra.test()[0] and rb.test()[0]):
                assert time.time() < deadline, "engine never ran them"
                time.sleep(0.01)
            np.testing.assert_allclose(
                np.asarray(ra.value)[0], np.full(2048, 6.0), rtol=1e-6)
            np.testing.assert_allclose(
                np.asarray(rb.value)[0], np.full(2048, 6.0), rtol=1e-6)
            assert _pv("nbc_hidden_seconds") > h0
            mca_var.VARS.unset("progress_thread")
            world.barrier()
            print(f"NBC-JOB-OK {me}")
            mpi.finalize()
        """)
        for me in (0, 1, 2):
            assert f"NBC-JOB-OK {me}" in out

    def test_hang_postmortem_names_nbc_schedule(self, tmp_path, capfd):
        """Hang injection: process 1 sleeps before the i-allreduce;
        the stalled peers' flight-recorder postmortems carry the
        engine's nbc_inflight table naming the stuck schedule (op,
        comm, state=running) next to the hier round state naming the
        awaited processes — the watchdog contract of the issue."""
        pm_dir = tmp_path / "pm"
        out = _run(tmp_path, capfd, """
            world = mpi.init()
            rt = Runtime.current()
            me = rt.bootstrap["process_index"]
            off = rt.local_rank_offset
            n = world.size
            if me == 1:
                time.sleep(4.0)
            x = np.stack([np.full(4096, off + i + 1, np.float32)
                          for i in range(2)])
            req = world.iallreduce(x)
            req.wait()
            want = float(sum(r + 1 for r in range(n)))
            assert float(np.asarray(req.value)[0][0]) == want
            world.barrier()
            print(f"NBC-HANG-OK {me}")
            mpi.finalize()
        """, mca=[("obs_enable", "1"),
                  ("obs_stall_timeout", "1.2"),
                  ("obs_postmortem_dir", str(pm_dir))])
        for me in (0, 1, 2):
            assert f"NBC-HANG-OK {me}" in out
        pms = sorted(pm_dir.glob("postmortem-*-stall-*.json"))
        assert pms, f"no stall postmortem in {pm_dir}"
        named = []
        for p in pms:
            pm = json.loads(p.read_text())
            for entry in pm.get("nbc_inflight", []) or []:
                if isinstance(entry, dict) \
                        and entry.get("name") == "allreduce" \
                        and entry.get("state") == "running":
                    named.append((p.name, entry.get("cid")))
        assert named, (
            f"no postmortem named the running allreduce schedule: "
            f"{pms}")
