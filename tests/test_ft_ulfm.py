"""ULFM-style elastic fault tolerance (PR 9).

Layers under test:

- ``utils/errors``: the new ``ERR_PROC_FAILED`` / ``ERR_REVOKED``
  classes.
- ``ft/ulfm.py``: the process-local failure picture — epoch
  monotonicity, per-incarnation failure permanence (``dead_for``),
  revocation, the deterministic epoch-derived cid.
- ``runtime/coordinator.py``: the heartbeat monitor's promotion path
  (miss-limit, recovered-in-time beats, errmgr callback ordering),
  ``promote_failed`` idempotence, TAG_PROC_FAILED notices, the TAG_FT
  state/agreement responder.
- ``runtime/progress.py``: ``fail_queued`` (revoke interrupts queued
  schedules without running them).
- ``ft/sensor.py``: seeded/deterministic/armed-kill FtTester modes.
- ``comm/dpm.py``: FT-aware rendezvous (dead-port fast fail, stale
  epoch fence, mid-wait revocation).
- ``tools/tpurun.py``: ``--ft-inject`` / ``--ft-continue`` plumbing.
- ``tools/tpu_bench_gate.py``: ft metrics gate lower-better.
- end-to-end: two REAL 3-process recovery jobs — a SIGKILLed rank
  mid-allreduce recovered by revoke+shrink (degraded world, exact
  loss) and by respawn+rebuild (full-size world, exact loss).
"""

import os
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

from ompi_release_tpu.ft import ulfm
from ompi_release_tpu.ft.sensor import FtTester, InjectedFault
from ompi_release_tpu.mca import pvar, var as mca_var
from ompi_release_tpu.runtime import coordinator as coord
from ompi_release_tpu.runtime import progress as progress_mod
from ompi_release_tpu.runtime.state import JobState
from ompi_release_tpu.tools.tpurun import Job
from ompi_release_tpu.utils.errors import ErrorCode, MPIError


@pytest.fixture
def ft_state():
    """A clean process-local failure picture per test."""
    st = ulfm.state()
    st.reset()
    yield st
    st.reset()


# ---------------------------------------------------------------------------
# error classes + state machine
# ---------------------------------------------------------------------------

class TestFtState:
    def test_error_classes_distinct(self):
        assert ErrorCode.ERR_PROC_FAILED != ErrorCode.ERR_REVOKED
        assert ErrorCode.ERR_PROC_FAILED not in (
            ErrorCode.ERR_PENDING, ErrorCode.ERR_UNREACH)

    def test_notice_updates_and_counts_once(self, ft_state):
        base = float(pvar.PVARS.lookup("ft_failures_detected").read())
        ft_state.apply_notice({"epoch": 1, "failed": [2]})
        ft_state.apply_notice({"epoch": 2, "failed": [2]})  # same pidx
        assert ft_state.epoch == 2
        assert ft_state.failed == {2}
        got = float(pvar.PVARS.lookup("ft_failures_detected").read())
        assert got == base + 1  # one failure, counted once

    def test_stale_epoch_ignored(self, ft_state):
        ft_state.apply_notice({"epoch": 5, "failed": [1]})
        ft_state.apply_notice({"epoch": 3, "failed": []})
        assert ft_state.epoch == 5 and ft_state.failed == {1}

    def test_check_wait_raises_proc_failed(self, ft_state):
        ft_state.apply_notice({"epoch": 1, "failed": [2]})
        with pytest.raises(MPIError) as ei:
            ft_state.check_wait(0, [1, 2], "reap")
        assert ei.value.code == ErrorCode.ERR_PROC_FAILED
        ft_state.check_wait(0, [0, 1], "reap")  # survivors: no raise

    def test_failure_permanence_per_comm_epoch(self, ft_state):
        """ULFM permanence: a pidx that failed at epoch 1 stays dead
        for comms born at epoch 0 even after its replacement rejoins
        (failed set empties), while a comm born at the recovery epoch
        sees the new incarnation as alive."""
        ft_state.apply_notice({"epoch": 1, "failed": [2]})
        ft_state.apply_notice({"epoch": 2, "failed": [],
                               "restarted": [2]})
        ft_state.apply_notice({"epoch": 3, "failed": [],
                               "restarted": [2], "rejoined": [2]})
        assert ft_state.dead_for([0, 1, 2], epoch0=0) == [2]
        with pytest.raises(MPIError) as ei:
            ft_state.check_wait(0, [2], "reap", epoch0=0)
        assert ei.value.code == ErrorCode.ERR_PROC_FAILED
        # a comm built at the recovery epoch talks to the replacement
        assert ft_state.dead_for([0, 1, 2], epoch0=3) == []
        ft_state.check_wait(900, [2], "reap", epoch0=3)
        # a SECOND death kills it for the rebuild comm too
        ft_state.apply_notice({"epoch": 4, "failed": [2]})
        assert ft_state.dead_for([2], epoch0=3) == [2]

    def test_revoke_marks_and_raises(self, ft_state):
        base = float(pvar.PVARS.lookup("ft_revokes").read())
        assert ft_state.apply_revoke(7, 1) is True
        assert ft_state.apply_revoke(7, 1) is False  # idempotent
        assert ft_state.is_revoked(7)
        with pytest.raises(MPIError) as ei:
            ft_state.check_wait(7, [0], "reap")
        assert ei.value.code == ErrorCode.ERR_REVOKED
        assert float(pvar.PVARS.lookup("ft_revokes").read()) == base + 1

    def test_ft_cid_deterministic_and_bounded(self):
        a = ulfm.ft_cid(3, 0)
        assert a == ulfm.ft_cid(3, 0)
        assert a != ulfm.ft_cid(4, 0) and a != ulfm.ft_cid(3, 1)
        assert ulfm.FT_CID_BASE <= a < (1 << 20)

    def test_ft_cid_distinct_per_parent_at_one_epoch(self):
        """The shrink-every-comm recovery pattern: distinct parent
        cids at ONE epoch must mint distinct rebuild cids (the old
        mod-64 parent slot collided cid 2 with cid 66)."""
        minted = {ulfm.ft_cid(5, c) for c in range(200)}
        assert len(minted) == 200
        assert ulfm.ft_cid(5, 2) != ulfm.ft_cid(5, 66)

    def test_rebuild_evicts_revoked_slot_occupant(self):
        """An epoch-wrapped ft cid landing on this lineage's OLD
        revoked comm evicts it instead of failing the recovery; a
        LIVE occupant stays a loud error."""
        import ompi_release_tpu as mpi
        from ompi_release_tpu.comm.communicator import (
            Communicator, _comm_registry,
        )
        from ompi_release_tpu.comm.group import Group

        world = mpi.init()
        slot = ulfm.ft_cid(1, 0)
        old = Communicator(world.runtime, Group([0, 1]), name="old",
                           cid=slot)
        with pytest.raises(MPIError):  # live occupant: loud error
            Communicator(world.runtime, Group([0, 1]), cid=slot)
        old._revoked = True  # poisoned ancestor: evictable
        ulfm.state().apply_revoke(slot, 1)  # its wire-level poison
        new = Communicator(world.runtime, Group([0, 1]), name="new",
                           cid=slot)
        assert _comm_registry[slot] is new and old._freed
        # the ancestor's revocation record must not poison the fresh
        # comm minted at the wrapped slot
        assert not ulfm.state().is_revoked(slot)
        new.free()
        # ...including when the ancestor was revoked-then-FREED long
        # ago (no registry occupant left at the slot): the stale
        # record is cleared unconditionally on the explicit-cid path
        ulfm.state().apply_revoke(slot, 2)
        again = Communicator(world.runtime, Group([0, 1]),
                             name="again", cid=slot)
        assert not ulfm.state().is_revoked(slot)
        again.free()

    def test_lineage_anchor_survives_rebuild_chain(self):
        """Recovery agreements/cids key on the LINEAGE: a rebuild's
        rebuild still anchors to the original comm, matching what a
        fresh replacement (holding only its world) derives."""
        import ompi_release_tpu as mpi
        from ompi_release_tpu.comm.communicator import Communicator
        from ompi_release_tpu.comm.group import Group

        world = mpi.init()
        base = Communicator(world.runtime, Group([0, 1]), name="base")
        r1 = Communicator(world.runtime, Group([0, 1]), parent=base,
                          cid=ulfm.ft_cid(1, base._ft_lineage))
        r2 = Communicator(world.runtime, Group([0, 1]), parent=r1,
                          cid=ulfm.ft_cid(2, r1._ft_lineage))
        assert base._ft_lineage == base.cid
        assert r1._ft_lineage == base.cid
        assert r2._ft_lineage == base.cid
        # survivors on r1 and a fresh process on base mint the SAME
        # recovery cid for the next epoch
        assert ulfm.ft_cid(3, r1._ft_lineage) == \
            ulfm.ft_cid(3, base._ft_lineage)
        for c in (r2, r1, base):
            c.free()

    def test_watchdog_contributor_snapshot(self, ft_state):
        from ompi_release_tpu.obs import watchdog
        ft_state.apply_notice({"epoch": 2, "failed": [1]})
        snap = dict(watchdog._contributors)["ft_state"]()
        assert snap["failed"] == [1] and snap["epoch"] == 2

    def test_postmortem_awaiting_names_known_failed(self, ft_state):
        """The watchdog info split: a known-failed peer is NAMED as
        failed in postmortems, not listed as merely 'awaiting'."""
        from ompi_release_tpu.runtime.wire import _ft_split_awaiting

        ft_state.apply_notice({"epoch": 1, "failed": [2]})
        info = _ft_split_awaiting([1, 2, 3])
        assert info == {"awaiting_procs": [1, 3],
                        "known_failed_procs": [2]}


# ---------------------------------------------------------------------------
# progress engine: revoke interrupts queued schedules
# ---------------------------------------------------------------------------

class TestFailQueued:
    def test_queued_ops_complete_in_error_without_running(self):
        eng = progress_mod.ProgressEngine()
        ran = []
        blocker = progress_mod.ScheduledOp(
            ("comm", 42), "blocker", lambda: ran.append("b"))
        victim = progress_mod.ScheduledOp(
            ("comm", 42), "victim", lambda: ran.append("v"))
        eng.post(blocker)
        eng.post(victim)
        n = eng.fail_queued(
            ("comm", 42),
            lambda: MPIError(ErrorCode.ERR_REVOKED, "revoked"))
        assert n == 2 and not ran
        assert victim.done.is_set() and victim.error.code == \
            ErrorCode.ERR_REVOKED
        with pytest.raises(MPIError):
            eng.wait(victim)
        assert eng.inflight_count() == 0

    def test_running_op_untouched(self):
        eng = progress_mod.ProgressEngine()
        started = threading.Event()
        release = threading.Event()

        def slow():
            started.set()
            release.wait(5)
            return "done"

        op = progress_mod.ScheduledOp(("comm", 43), "slow", slow)
        eng.post(op)
        t = threading.Thread(target=eng.wait, args=(op,), daemon=True)
        t.start()
        assert started.wait(5)
        assert eng.fail_queued(("comm", 43), lambda: MPIError(
            ErrorCode.ERR_REVOKED, "r")) == 0
        release.set()
        t.join(5)
        assert op.error is None and op.result == "done"


# ---------------------------------------------------------------------------
# sensor: seeded / every-N / armed-kill injection
# ---------------------------------------------------------------------------

class TestFtTester:
    def test_seed_reproducible(self):
        a = FtTester(fail_prob=0.5, seed=1234)
        b = FtTester(fail_prob=0.5, seed=1234)

        def trace(t):
            out = []
            for _ in range(50):
                try:
                    t.maybe_fail()
                    out.append(0)
                except InjectedFault:
                    out.append(1)
            return out

        ta, tb = trace(a), trace(b)
        assert ta == tb and sum(ta) > 0
        assert trace(FtTester(fail_prob=0.5, seed=99)) != ta

    def test_seed_cvar_feeds_default(self, monkeypatch):
        monkeypatch.setenv("OMPITPU_MCA_sensor_ft_seed", "777")
        mca_var.VARS.refresh_from_env()
        try:
            a, b = FtTester(fail_prob=0.5), FtTester(fail_prob=0.5)
            ra = [a._rng.random() for _ in range(8)]
            rb = [b._rng.random() for _ in range(8)]
            assert ra == rb  # both seeded from the cvar
        finally:
            monkeypatch.delenv("OMPITPU_MCA_sensor_ft_seed")
            mca_var.VARS.refresh_from_env()

    def test_every_n_deterministic(self):
        t = FtTester(fail_prob=0.0, every_n=3)
        fired = []
        for s in range(10):
            try:
                t.step()
            except InjectedFault:
                fired.append(s)
        assert fired == [3, 6, 9]

    def test_kill_armed_at_step(self, monkeypatch):
        killed = []
        monkeypatch.setattr(os, "kill",
                            lambda pid, sig: killed.append((pid, sig)))
        t = FtTester(fail_prob=0.0, kill_step=2)
        t.step(); t.step()
        assert not killed
        t.step()  # step index 2: fires
        assert killed and killed[0][0] == os.getpid()

    def test_from_cvars_rank_scoping(self, monkeypatch):
        monkeypatch.setenv("OMPITPU_MCA_sensor_ft_kill_step", "5")
        monkeypatch.setenv("OMPITPU_MCA_sensor_ft_kill_rank", "1")
        mca_var.VARS.refresh_from_env()
        try:
            assert FtTester.from_cvars(process_index=1).kill_step == 5
            assert FtTester.from_cvars(process_index=0).kill_step == -1
        finally:
            monkeypatch.delenv("OMPITPU_MCA_sensor_ft_kill_step")
            monkeypatch.delenv("OMPITPU_MCA_sensor_ft_kill_rank")
            mca_var.VARS.refresh_from_env()


# ---------------------------------------------------------------------------
# heartbeat monitor + ULFM coordinator plane (satellite: direct tests)
# ---------------------------------------------------------------------------

class TestHeartbeatMonitor:
    def _pair(self, n_workers=1):
        hnp = coord.HnpCoordinator(n_workers + 1)
        agents = []
        threads = []

        def mk(nid):
            a = coord.WorkerAgent(nid, "127.0.0.1", hnp.port)
            a.run_modex({"node_id": nid})
            agents.append(a)

        for nid in range(1, n_workers + 1):
            t = threading.Thread(target=mk, args=(nid,))
            t.start()
            threads.append(t)
        hnp.run_modex(None)
        for t in threads:
            t.join(10)
        agents.sort(key=lambda a: a.node_id)
        return hnp, agents

    def test_miss_limit_promotes_once(self, ft_state):
        """A worker silent for miss_limit intervals is reported
        exactly once, the job epoch bumps, and a live peer's watcher
        receives the TAG_PROC_FAILED notice."""
        hnp, (w1, w2) = self._pair(2)
        try:
            fired = []
            notices = []
            w1.start_ft_watcher(lambda d: notices.append(d))
            hnp.start_heartbeat_monitor(fired.append,
                                        interval_s=0.1, miss_limit=2)
            deadline = time.monotonic() + 5
            while not fired and time.monotonic() < deadline:
                w1.heartbeat()  # only w1 beats; w2 goes silent
                time.sleep(0.05)
            # keep w1 alive through the would-be-duplicate window
            end = time.monotonic() + 0.5
            while time.monotonic() < end:
                w1.heartbeat()
                time.sleep(0.05)
            assert fired == [2]
            doc = hnp._ft_doc()
            assert doc["epoch"] >= 1 and doc["failed"] == [1]
            assert any(n.get("failed") == [1] for n in notices)
        finally:
            hnp.shutdown()
            for a in (w1, w2):
                a.close()

    def test_recovered_in_time_beat_does_not_fire(self):
        """Beats arriving inside the miss window must never promote —
        today's gap in coverage: start_heartbeat_monitor had no direct
        tests at all."""
        hnp, (w,) = self._pair(1)
        try:
            fired = []
            hnp.start_heartbeat_monitor(fired.append,
                                        interval_s=0.1, miss_limit=3)
            end = time.monotonic() + 1.2  # 4x the miss window
            while time.monotonic() < end:
                w.heartbeat()
                time.sleep(0.1)  # inside interval*miss_limit = 0.3s
            assert fired == []
            assert hnp._ft_doc()["failed"] == []
        finally:
            hnp.shutdown()
            w.close()

    def test_failure_callback_orders_with_errmgr_handle(self):
        """The promotion sequence an errmgr policy observes: epoch
        bump BEFORE the on_failure callback, so a policy that consults
        the ft doc inside its handler already sees the failure; and
        ErrMgr.handle dispatches the typed error to its registrants."""
        from ompi_release_tpu.ft.errmgr import ErrMgr

        hnp, (w1, w2) = self._pair(2)
        try:
            order = []
            mgr = ErrMgr()
            mgr.register(MPIError,
                         lambda e: order.append(("handler", e.code)))

            def on_failure(nid):
                # the epoch must already record the failure HERE
                order.append(("cb", nid,
                              tuple(hnp._ft_doc()["failed"])))
                claimed = mgr.handle(MPIError(
                    ErrorCode.ERR_PROC_FAILED, f"worker {nid}"))
                order.append(("handled", claimed))

            hnp.start_heartbeat_monitor(on_failure,
                                        interval_s=0.1, miss_limit=2)
            deadline = time.monotonic() + 5
            while len(order) < 3 and time.monotonic() < deadline:
                w1.heartbeat()
                time.sleep(0.05)
            assert order[0] == ("cb", 2, (1,))
            assert order[1] == ("handler", ErrorCode.ERR_PROC_FAILED)
            assert order[2] == ("handled", True)
        finally:
            hnp.shutdown()
            for a in (w1, w2):
                a.close()

    def test_restart_grace_excuses_cold_startup_silence(self):
        """A respawned worker's first beat is gated on full process
        startup (cold jax import can exceed the whole heartbeat
        window); note_restarted must grant a startup grace so the
        monitor does not re-promote the replacement before it could
        possibly beat — observed as a real flake of the respawn
        acceptance job on cold runs."""
        hnp, (w,) = self._pair(1)
        try:
            fired = []
            hnp.start_heartbeat_monitor(fired.append,
                                        interval_s=0.05, miss_limit=2)
            time.sleep(0.3)  # w never beats: promoted normally
            assert fired == [1]
            hnp.note_restarted(1)
            time.sleep(0.5)  # 5x the window, still inside the grace
            assert fired == [1], "replacement re-promoted during boot"
            w.heartbeat()  # first beat ends the grace
            time.sleep(0.2)
            with hnp._hb_lock:
                assert 1 not in hnp._hb_restart_grace
            time.sleep(0.4)  # silent AFTER the first beat: normal rules
            assert fired == [1, 1]
        finally:
            hnp.shutdown()
            w.close()

    def test_promote_failed_idempotent_and_skips_finished(self):
        hnp, (w,) = self._pair(1)
        try:
            hnp.start_ft_responder()
            assert hnp.promote_failed(1) is True
            assert hnp.promote_failed(1) is False  # already failed
            assert w.ft_query()["failed"] == [0]
            hnp.note_restarted(1)
            doc = w.ft_query()
            assert doc["failed"] == [] and doc["restarted"] == [0]
            # a cleanly-finished worker is never promoted
            hnp.note_finished(1)
            assert hnp.promote_failed(1) is False
        finally:
            hnp.shutdown()
            w.close()

    def test_ft_agreement_excuses_failed_and_ands_flags(self):
        """MPIX_Comm_agree at the HNP: parked until every LIVE
        participant contributed, failed participants excused, reply =
        AND of flags + one consistent snapshot."""
        hnp, (w1, w2) = self._pair(2)
        try:
            hnp.start_ft_responder()
            hnp.promote_failed(2)  # pidx 1 is dead
            out = {}

            def contribute(agent, flag):
                out[agent.node_id] = agent.ft_agree(
                    5, 1, flag, [0, 1], timeout_ms=10_000)

            t = threading.Thread(target=contribute, args=(w1, 0))
            t.start()
            t.join(10)
            assert not t.is_alive(), "agreement never completed"
            doc = out[1]
            assert doc["flag"] == 0 and doc["failed"] == [1]
        finally:
            hnp.shutdown()
            for a in (w1, w2):
                a.close()


# ---------------------------------------------------------------------------
# errmgr: respawn-readiness predicate + dead-for-comm mapping
# ---------------------------------------------------------------------------

class TestRespawnReadiness:
    def test_stale_cumulative_rejoined_not_ready(self):
        """Second-recovery regression: restarted/rejoined are
        cumulative, so a NEW failure whose respawn was just granted
        (failed empty, old survivor still in rejoined) must NOT look
        ready — only once the new replacement's rejoin lands."""
        from ompi_release_tpu.ft.errmgr import respawn_ready

        assert not respawn_ready(None)
        assert not respawn_ready({"epoch": 0})
        # recovery #1 complete
        assert respawn_ready({"epoch": 3, "failed": [],
                              "restarted": [2], "rejoined": [2]})
        # failure #2 detected
        assert not respawn_ready({"epoch": 4, "failed": [1],
                                  "restarted": [2], "rejoined": [2]})
        # respawn of pidx 1 granted but NOT yet rejoined
        assert not respawn_ready({"epoch": 5, "failed": [],
                                  "restarted": [1, 2],
                                  "rejoined": [2]})
        # replacement wired: ready
        assert respawn_ready({"epoch": 6, "failed": [],
                              "restarted": [1, 2],
                              "rejoined": [1, 2]})

    def test_finish_checked_respects_comm_epoch(self, ft_state):
        """A rejoined replacement's flaky transfer on a POST-recovery
        comm must stay a flake (original error), not be escalated to
        ERR_PROC_FAILED by its old failure episode."""
        import ompi_release_tpu as mpi
        from ompi_release_tpu.runtime.wire import WireRouter

        mpi.init()
        ft_state.apply_notice({"epoch": 1, "failed": [2]})
        ft_state.apply_notice({"epoch": 3, "failed": [],
                               "restarted": [2], "rejoined": [2]})
        rt = type("R", (), {})()

        def boom(self, *a, **k):
            raise MPIError(ErrorCode.ERR_TRUNCATE, "flaky tail")

        router = WireRouter.__new__(WireRouter)
        router._finish_transfer = boom.__get__(router)
        # pre-failure comm: escalated to the typed process failure
        with pytest.raises(MPIError) as ei:
            router._finish_checked(2, 0, b"", 0.0, epoch0=0)
        assert ei.value.code == ErrorCode.ERR_PROC_FAILED
        # post-recovery comm: the flake surfaces as itself
        with pytest.raises(MPIError) as ei:
            router._finish_checked(2, 0, b"", 0.0, epoch0=3)
        assert ei.value.code == ErrorCode.ERR_TRUNCATE


# ---------------------------------------------------------------------------
# dpm: FT-aware rendezvous + lookup (satellite)
# ---------------------------------------------------------------------------

class TestDpmFt:
    @pytest.fixture
    def world(self):
        import ompi_release_tpu as mpi

        return mpi.init()

    def test_connect_to_revoked_acceptor_fast_fails(self, world,
                                                    ft_state):
        """A connect against a parked acceptor whose comm is revoked
        returns the typed error IMMEDIATELY (no timeout burn)."""
        from ompi_release_tpu.comm.dpm import (
            close_port, comm_accept, comm_connect, open_port,
        )
        from ompi_release_tpu.comm.group import Group
        from ompi_release_tpu.comm.communicator import Communicator

        a = Communicator(world.runtime, Group([0, 1]), name="dpm-a")
        b = Communicator(world.runtime, Group([2, 3]), name="dpm-b")
        port = open_port()
        errs = {}

        def accept():
            try:
                comm_accept(a, port, timeout_s=15)
            except MPIError as e:
                errs["accept"] = e

        t = threading.Thread(target=accept, daemon=True)
        t.start()
        time.sleep(0.3)  # acceptor parked
        a._revoked = True  # poison the parked side
        t0 = time.monotonic()
        with pytest.raises(MPIError) as ei:
            comm_connect(b, port, timeout_s=15)
        assert time.monotonic() - t0 < 5  # not the full timeout
        assert ei.value.code == ErrorCode.ERR_REVOKED
        t.join(5)
        close_port(port)
        a._revoked = False
        a.free()
        b.free()

    def test_accept_rejects_stale_epoch_joiner(self, world, ft_state):
        from ompi_release_tpu.comm.dpm import (
            close_port, comm_connect, open_port,
        )
        from ompi_release_tpu.comm.group import Group
        from ompi_release_tpu.comm.communicator import Communicator

        ft_state.apply_notice({"epoch": 4, "failed": []})
        port = open_port()  # opened at epoch 4
        b = Communicator(world.runtime, Group([2, 3]), name="dpm-c")
        with pytest.raises(MPIError) as ei:
            comm_connect(b, port, timeout_s=5, epoch=2)  # stale view
        assert ei.value.code == ErrorCode.ERR_REVOKED
        assert "stale" in str(ei.value)
        close_port(port)
        b.free()

    def test_lookup_of_closed_port_raises_proc_failed(self, world,
                                                      ft_state):
        from ompi_release_tpu.comm.dpm import (
            close_port, lookup_name, open_port, publish_name,
            unpublish_name,
        )

        port = open_port()
        publish_name("dead-svc", port)
        close_port(port)  # publisher died without unpublishing
        t0 = time.monotonic()
        with pytest.raises(MPIError) as ei:
            lookup_name("dead-svc", timeout_s=10)
        assert ei.value.code == ErrorCode.ERR_PROC_FAILED
        assert time.monotonic() - t0 < 5
        unpublish_name("dead-svc")


# ---------------------------------------------------------------------------
# tpurun plumbing + bench gate directions (satellites)
# ---------------------------------------------------------------------------

class TestTpurunFtFlags:
    def test_ft_inject_arms_only_chosen_child_first_incarnation(self):
        job = Job(3, ["true"], [], ft_inject=(1, 7))
        job.hnp = type("H", (), {"port": 1})()
        job.hnp_host = "127.0.0.1"
        envs = {n: job._ompitpu_env(n) for n in (1, 2, 3)}
        key = "OMPITPU_MCA_sensor_ft_kill_step"
        assert envs[2][key] == "7"
        assert key not in envs[1] and key not in envs[3]
        # a respawned incarnation is NOT re-armed (one failure)
        job._restarts[2] = 1
        assert key not in job._ompitpu_env(2)

    def test_ft_inject_validation(self):
        with pytest.raises(MPIError):
            Job(2, ["true"], [], ft_inject=(5, 0))
        with pytest.raises(MPIError):
            Job(2, ["true"], [], ft_inject=(0, -1))

    def test_cli_parses_ft_flags(self, capsys):
        from ompi_release_tpu.tools import tpurun as tpurun_mod

        with pytest.raises(SystemExit):
            tpurun_mod.main(["--help"])
        out = capsys.readouterr().out
        assert "--ft-inject" in out and "--ft-continue" in out
        with pytest.raises(SystemExit):
            tpurun_mod.main(["-n", "2", "--ft-inject", "bogus", "true"])
        with pytest.raises(SystemExit):
            tpurun_mod.main(["-n", "2", "--enable-recovery",
                             "--ft-continue", "true"])

    def test_continue_policy_accepted(self):
        job = Job(2, ["true"], [], on_failure="continue")
        assert job.on_failure == "continue"
        with pytest.raises(MPIError):
            Job(2, ["true"], [], on_failure="bogus")

    def test_continue_policy_aborts_on_bringup_failure(self):
        """A child that dies during LAUNCH must abort the job loudly
        — the degraded-world policy only applies once RUNNING, or
        survivors would park in wire-up masking the real startup
        error."""
        import subprocess as _sp

        from ompi_release_tpu.runtime.state import ProcState

        job = Job(2, ["true"], [], on_failure="continue")
        job.hnp = coord.HnpCoordinator(3)
        job.job_state.activate(JobState.INIT)
        job.job_state.activate(JobState.LAUNCH_DAEMONS)  # NOT running
        try:
            job._on_worker_failure(1, ProcState.ABORTED)
            assert job._failed.is_set()  # aborted, not "continued"
            assert not job._ft_failed_ranks
        finally:
            job.hnp.shutdown()

    def test_sendrecv_refuses_revoked_comm(self):
        import ompi_release_tpu as mpi

        world = mpi.init()
        c = world.dup("sr-revoked")
        c._revoked = True
        with pytest.raises(MPIError) as ei:
            c.sendrecv([np.zeros(2)] * c.size, list(range(c.size)))
        assert ei.value.code == ErrorCode.ERR_REVOKED
        c._revoked = False
        c.free()

    def test_failed_at_of_parses_wire_map(self):
        assert ulfm.failed_at_of(None) == {}
        assert ulfm.failed_at_of({"failed_at": {"2": 5, "bad": "x",
                                                "1": "3"}}) \
            == {2: 5, 1: 3}


class TestBenchGateFtDirections:
    def test_ft_metrics_gate_lower_better(self):
        from ompi_release_tpu.tools.tpu_bench_gate import _direction

        assert _direction("s", "ft_recovery_seconds") == -1
        assert _direction("steps", "ft_steps_lost") == -1
        assert _direction(None, "ft_steps_lost") == -1  # prefix rule

    def test_gate_flags_recovery_regression(self):
        from ompi_release_tpu.tools.tpu_bench_gate import evaluate

        hist = [[{"metric": "ft_recovery_seconds", "value": v,
                  "unit": "s", "tier_label": "loopback-cpu"}]
                for v in (0.20, 0.22, 0.21, 0.19)]
        bad = [{"metric": "ft_recovery_seconds", "value": 2.5,
                "unit": "s", "tier_label": "loopback-cpu"}]
        ok = [{"metric": "ft_recovery_seconds", "value": 0.21,
               "unit": "s", "tier_label": "loopback-cpu"}]
        assert any(r["metric"] == "ft_recovery_seconds"
                   for r in evaluate(hist, bad)["regressions"])
        assert not evaluate(hist, ok)["regressions"]


# ---------------------------------------------------------------------------
# ElasticStep in-process: injected-fault rollback (no job needed)
# ---------------------------------------------------------------------------

class TestElasticStepLocal:
    def test_injected_fault_rolls_back_to_committed_step(self,
                                                         tmp_path):
        import ompi_release_tpu as mpi
        from ompi_release_tpu.ft.checkpoint import Checkpointer
        from ompi_release_tpu.parallel.elastic import ElasticStep

        world = mpi.init()
        ck = Checkpointer(str(tmp_path / "ck"))
        calls = []

        def step_fn(step, state, comm):
            calls.append(step)
            return np.asarray(state) + np.float32(step + 1)

        es = ElasticStep(world, step_fn, ck, policy="shrink",
                         checkpoint_every=1,
                         tester=FtTester(fail_prob=0.0, every_n=4))
        state, stats = es.run(np.zeros((), np.float32), 6)
        # every-4 fires at tester-steps 4 and (after rollback resumes
        # counting) 8; each rolls back to the last committed step
        assert stats["injected_rollbacks"] >= 1
        assert float(np.asarray(state)) == float(sum(range(1, 7)))
        assert stats["steps_lost"] == 0  # checkpoint_every=1

    def test_unseeded_probabilistic_injection_refused_spanning(self):
        """Unseeded random injection on a spanning comm would
        desynchronize the collective schedule (one rank rolls back,
        peers post the step) — refused loudly at construction; a
        SEEDED tester (same step sequence fleet-wide) is accepted."""
        import ompi_release_tpu as mpi
        from ompi_release_tpu.ft.checkpoint import Checkpointer
        from ompi_release_tpu.parallel.elastic import ElasticStep

        world = mpi.init()
        fake = type("C", (), {"spans_processes": True,
                              "runtime": world.runtime})()
        with pytest.raises(MPIError) as ei:
            ElasticStep(fake, lambda s, st, c: st,
                        Checkpointer("/tmp/_es_refuse"),
                        tester=FtTester(fail_prob=0.1))
        assert "sensor_ft_seed" in str(ei.value)
        # a programmatically SEEDED tester is accepted as-is (no cvar
        # involved): the tester's own seed is what makes it replayable
        ElasticStep(fake, lambda s, st, c: st,
                    Checkpointer("/tmp/_es_refuse"),
                    tester=FtTester(fail_prob=0.1, seed=42))

    def test_unconfirmed_suspect_error_reraises(self, tmp_path,
                                                ft_state):
        import ompi_release_tpu as mpi
        from ompi_release_tpu.ft.checkpoint import Checkpointer
        from ompi_release_tpu.parallel.elastic import ElasticStep

        world = mpi.init()
        ck = Checkpointer(str(tmp_path / "ck2"))

        def step_fn(step, state, comm):
            raise MPIError(ErrorCode.ERR_TRUNCATE, "flaky transfer")

        es = ElasticStep(world, step_fn, ck, confirm_timeout_s=0.3)
        with pytest.raises(MPIError) as ei:
            es.run(np.zeros((), np.float32), 2)
        assert ei.value.code == ErrorCode.ERR_TRUNCATE  # not swallowed


# ---------------------------------------------------------------------------
# end-to-end recovery jobs (the acceptance criterion)
# ---------------------------------------------------------------------------

APP_PRELUDE = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, %r)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import ompi_release_tpu as mpi
    from ompi_release_tpu.mca import pvar
    from ompi_release_tpu.ft.checkpoint import Checkpointer
    from ompi_release_tpu.ft.sensor import FtTester
    from ompi_release_tpu.parallel.elastic import ElasticStep
    from ompi_release_tpu.runtime.runtime import Runtime

    def _pv(name):
        p = pvar.PVARS.lookup(name)
        return float(p.read()) if p is not None else 0.0

    world = mpi.init()
    rt = Runtime.current()
    me = rt.bootstrap["process_index"]
    STEPS = 8

    def step_fn(step, state, comm):
        wrs = [comm.group.world_rank(i) for i in comm.local_comm_ranks]
        contrib = np.stack(
            [np.full(4, (step + 1) * (wr + 1), np.float32)
             for wr in wrs])
        got = np.asarray(comm.allreduce(contrib))
        return np.asarray(state) + got[:1]
""" % REPO)


def _run_ft_job(tmp_path, capfd, body, *, n=3, timeout=240,
                job_kw=None):
    app = tmp_path / "ft_app.py"
    app.write_text(APP_PRELUDE + textwrap.dedent(body))
    os.environ["OMPITPU_FT_TEST_DIR"] = str(tmp_path / "ftdir")
    try:
        job = Job(n, [sys.executable, str(app)], [],
                  heartbeat_s=0.3, miss_limit=3, **(job_kw or {}))
        rc = job.run(timeout_s=timeout)
    finally:
        os.environ.pop("OMPITPU_FT_TEST_DIR", None)
    out = capfd.readouterr()
    return rc, out.out + out.err, job


class TestRecoveryJobs:
    def test_kill_mid_allreduce_shrink_recovers_exact_loss(
            self, tmp_path, capfd):
        """THE acceptance criterion, shrink leg: a 3-process job has
        rank 2 SIGKILLed at step 3 (survivors are inside that step's
        allreduce); survivors detect via the heartbeat/waitpid epoch
        bump (ERR_PROC_FAILED from the bounded reap, NOT a watchdog
        timeout), revoke() the world, shrink() to a working 4-rank
        communicator, restore the last committed checkpoint, and
        finish with the exact degraded loss — with the ft_* pvars
        witnessing exactly one failure and one recovery."""
        rc, out, job = _run_ft_job(tmp_path, capfd, """
            ckpt = Checkpointer(os.path.join(
                os.environ["OMPITPU_FT_TEST_DIR"], f"rank{me}"))
            es = ElasticStep(world, step_fn, ckpt, policy="shrink",
                             checkpoint_every=1,
                             tester=FtTester.from_cvars(me))
            state, stats = es.run(np.zeros((1, 4), np.float32), STEPS)

            # exact replay math: steps 0-2 on the 6-rank world
            # (sum(wr+1) = 21), steps 3-7 on the 4 survivors (10)
            exp = (sum((s + 1) * 21 for s in range(0, 3))
                   + sum((s + 1) * 10 for s in range(3, 8)))
            got = np.asarray(state)
            assert np.array_equal(
                got, np.full((1, 4), float(exp), np.float32)), \\
                (got, exp)
            assert stats["recoveries"] == 1, stats
            fail = stats["failures"][0][1]
            assert ("ERR_PROC_FAILED" in fail
                    or "ERR_REVOKED" in fail), fail
            assert es.comm.size == 4
            assert not es.comm.spans_processes or \\
                len(es.comm.local_comm_ranks) == 2
            assert _pv("ft_failures_detected") == 1.0
            assert _pv("ft_recoveries") == 1.0
            assert _pv("ft_revokes") >= 1.0
            assert _pv("ft_recovery_seconds") > 0.0
            # the old world is poisoned: new collectives refuse fast
            try:
                world.allreduce(np.zeros((2, 2), np.float32))
                raise AssertionError("revoked world still worked")
            except mpi.MPIError as e:
                assert e.code in (
                    mpi.ErrorCode.ERR_REVOKED,
                    mpi.ErrorCode.ERR_PROC_FAILED), e
            print(f"FT_SHRINK_OK rank{me} final={float(got[0][0])}",
                  flush=True)
            mpi.finalize()
        """, job_kw={"on_failure": "continue", "ft_inject": (2, 3)})
        assert rc == 0, out
        assert out.count("FT_SHRINK_OK") == 2, out  # both survivors
        assert "FT_SHRINK_OK rank2" not in out
        assert job.job_state.visited(JobState.TERMINATED)
        assert job._ft_failed_ranks == {3}  # node id of pidx 2

    def test_p2p_recv_on_dead_peer_raises_typed_error(self, tmp_path,
                                                      capfd):
        """A blocking p2p recv whose sender process dies raises
        ERR_PROC_FAILED within the detection interval — not a generic
        ERR_PENDING after the full 30s pml_wire_timeout."""
        rc, out, _job = _run_ft_job(tmp_path, capfd, """
            if me == 1:
                time.sleep(1.0)
                import signal
                os.kill(os.getpid(), signal.SIGKILL)
            t0 = time.monotonic()
            try:
                world.recv(source=2, rank=0)  # rank 2 lives on pidx 1
                raise AssertionError("recv from dead peer returned")
            except mpi.MPIError as e:
                dt = time.monotonic() - t0
                assert e.code == mpi.ErrorCode.ERR_PROC_FAILED, e
                assert dt < 15, f"typed error took {dt:.1f}s"
            print(f"FT_P2P_OK rank{me}", flush=True)
            mpi.finalize()
        """, n=2, timeout=120, job_kw={"on_failure": "continue"})
        assert rc == 0, out
        assert "FT_P2P_OK rank0" in out

    def test_exit_zero_without_fin_is_promoted(self, tmp_path, capfd):
        """A worker that exits 0 WITHOUT sending FIN (os._exit mid-
        run) is lifeline-lost, not cleanly finished: it must still be
        promoted through the job epoch so survivors' waits raise the
        typed error — note_finished may only fire on a confirmed
        FIN."""
        rc, out, _job = _run_ft_job(tmp_path, capfd, """
            if me == 1:
                time.sleep(1.0)
                os._exit(0)  # exit 0, no FIN, no finalize
            t0 = time.monotonic()
            try:
                step_fn(0, np.zeros((1, 4), np.float32), world)
                raise AssertionError("collective with dead peer ran")
            except mpi.MPIError as e:
                dt = time.monotonic() - t0
                assert e.code in (mpi.ErrorCode.ERR_PROC_FAILED,
                                  mpi.ErrorCode.ERR_REVOKED), e
                assert dt < 20, f"typed error took {dt:.1f}s"
            print(f"FT_NOFIN_OK rank{me}", flush=True)
            mpi.finalize()
        """, n=2, timeout=120, job_kw={"on_failure": "continue"})
        assert rc == 0, out
        assert "FT_NOFIN_OK rank0" in out

    def test_kill_then_respawn_rebuilds_full_world_exact_loss(
            self, tmp_path, capfd):
        """The acceptance criterion's second leg: same kill, but under
        tpurun --enable-recovery the launcher respawns the rank; the
        replacement re-wires through the rejoin service at the new
        epoch, survivors re-dial it, and errmgr.recover('respawn')
        rebuilds a FULL-SIZE communicator (epoch-derived cid minted
        identically by survivors and the restarted process) whose
        allreduce is bitwise-correct; everyone resumes from the agreed
        checkpoint and reaches the no-failure loss."""
        rc, out, job = _run_ft_job(tmp_path, capfd, """
            ckpt = Checkpointer(os.path.join(
                os.environ["OMPITPU_FT_TEST_DIR"], f"rank{me}"))
            es = ElasticStep(world, step_fn, ckpt, policy="respawn",
                             checkpoint_every=1, recover_timeout_s=120,
                             tester=FtTester.from_cvars(me))
            state, stats = es.run(np.zeros((1, 4), np.float32), STEPS)

            # full-size recovery: every step sums over all 6 ranks
            exp = sum((s + 1) * 21 for s in range(STEPS))
            got = np.asarray(state)
            assert np.array_equal(
                got, np.full((1, 4), float(exp), np.float32)), \\
                (got, exp)
            assert es.comm.size == 6
            assert es.comm.name.startswith("rebuild")
            assert _pv("ft_recoveries") == 1.0
            print(f"FT_RESPAWN_OK rank{me} final={float(got[0][0])}",
                  flush=True)
            mpi.finalize()
        """, timeout=300,
            job_kw={"on_failure": "restart", "max_restarts": 2,
                    "ft_inject": (2, 3)})
        assert rc == 0, out
        # all three FINAL incarnations finish, replacement included
        for r in range(3):
            assert f"FT_RESPAWN_OK rank{r}" in out, out
        assert job._restarts.get(3) == 1  # exactly one respawn
        assert job.job_state.visited(JobState.TERMINATED)

    def test_two_sequential_failures_both_respawned(self, tmp_path,
                                                    capfd):
        """Multi-recovery: a SECOND rank dies after the first
        recovery completed. The lineage anchor is what makes this
        work — the second rebuild's agreement/cid pair a survivor
        holding rebuild#1 with a fresh replacement holding only its
        world — and the exact full-size loss proves both rollbacks
        replayed correctly."""
        rc, out, job = _run_ft_job(tmp_path, capfd, """
            ckpt = Checkpointer(os.path.join(
                os.environ["OMPITPU_FT_TEST_DIR"], f"rank{me}"))
            tester = FtTester.from_cvars(me)
            if me == 1 and not os.environ.get("OMPITPU_INCARNATION"):
                # the SECOND failure: rank 1's first incarnation dies
                # a few steps after recovery #1 completes
                tester.kill_step = 6
            es = ElasticStep(world, step_fn, ckpt, policy="respawn",
                             checkpoint_every=1, recover_timeout_s=120,
                             tester=tester)
            state, stats = es.run(np.zeros((1, 4), np.float32), STEPS)
            exp = sum((s + 1) * 21 for s in range(STEPS))
            got = np.asarray(state)
            assert np.array_equal(
                got, np.full((1, 4), float(exp), np.float32)), \\
                (got, exp)
            assert es.comm.size == 6
            print(f"FT_TWOFAIL_OK rank{me}", flush=True)
            mpi.finalize()
        """, timeout=300,
            job_kw={"on_failure": "restart", "max_restarts": 2,
                    "ft_inject": (2, 3)})
        assert rc == 0, out
        for r in range(3):
            assert f"FT_TWOFAIL_OK rank{r}" in out, out
        assert job._restarts.get(3) == 1  # rank 2's respawn
        assert job._restarts.get(2) == 1  # rank 1's respawn
