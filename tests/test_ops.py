"""Reduction op tests — analogue of the op_base_functions.c kernel table."""

import numpy as np
import pytest

import jax.numpy as jnp

from ompi_release_tpu import ops


@pytest.mark.parametrize("name,expect", [
    ("sum", 10), ("prod", 24), ("max", 4), ("min", 1),
])
def test_arith_ops(name, expect):
    op = ops.PREDEFINED_OPS[name]
    vals = [jnp.array(v, jnp.float32) for v in [1, 2, 3, 4]]
    acc = vals[0]
    for v in vals[1:]:
        acc = op(acc, v)
    assert float(acc) == expect


def test_logical_ops():
    t, f = jnp.array(True), jnp.array(False)
    assert bool(ops.LAND(t, f)) is False
    assert bool(ops.LOR(t, f)) is True
    assert bool(ops.LXOR(t, t)) is False


def test_bitwise_ops():
    a, b = jnp.array(0b1100, jnp.int32), jnp.array(0b1010, jnp.int32)
    assert int(ops.BAND(a, b)) == 0b1000
    assert int(ops.BOR(a, b)) == 0b1110
    assert int(ops.BXOR(a, b)) == 0b0110


def test_identities():
    assert ops.SUM.identity_for(np.float32) == 0
    assert ops.PROD.identity_for(np.int32) == 1
    assert ops.MIN.identity_for(np.int32) == np.iinfo(np.int32).max
    assert float(ops.MAX.identity_for(np.float32)) == -np.inf
    assert int(ops.BAND.identity_for(np.uint8)) == 0xFF


def test_maxloc_minloc_tie_lower_index():
    v = jnp.array([3.0, 5.0]), jnp.array([0, 1])
    w = jnp.array([3.0, 5.0]), jnp.array([2, 0])
    mv, mi = ops.MAXLOC(v, w)
    np.testing.assert_array_equal(np.asarray(mv), [3.0, 5.0])
    np.testing.assert_array_equal(np.asarray(mi), [0, 0])  # ties -> lower idx
    nv, ni = ops.MINLOC(v, w)
    np.testing.assert_array_equal(np.asarray(ni), [0, 0])


def test_replace_noop():
    a, b = jnp.array(1.0), jnp.array(2.0)
    assert float(ops.REPLACE(a, b)) == 2.0
    assert float(ops.NO_OP(a, b)) == 1.0


def test_user_op():
    op = ops.user_op("avg2", lambda a, b: (a + b) / 2, commute=True)
    assert float(op(jnp.array(2.0), jnp.array(4.0))) == 3.0
    assert op.commutative


def test_op_framework_selection():
    mod = ops.OP_FRAMEWORK.select()
    assert mod.lookup("sum") is ops.SUM


def test_non_commutative_flag():
    assert not ops.REPLACE.commutative
    assert ops.SUM.commutative


class TestPallasOpKernels:
    """Streaming Pallas reduction kernels (interpret mode on CPU)."""

    def test_axpy_matches_reference(self):
        from ompi_release_tpu.ops import pallas_op

        rng = np.random.RandomState(0)
        # non-multiple of the block size: exercises padding
        a = rng.randn(3000).astype(np.float32)
        acc = rng.randn(3000).astype(np.float32)
        out = pallas_op.axpy(jnp.asarray(a), jnp.asarray(acc), 0.5)
        np.testing.assert_allclose(
            np.asarray(out), acc * 0.5 + a, rtol=1e-6
        )

    def test_scale_matches_reference(self):
        from ompi_release_tpu.ops import pallas_op

        rng = np.random.RandomState(1)
        x = rng.randn(17, 33).astype(np.float32)
        out = pallas_op.scale(jnp.asarray(x), 2.0)
        np.testing.assert_allclose(np.asarray(out), x * 2.0, rtol=1e-6)

    def test_bench_loops_run(self):
        from ompi_release_tpu.ops import pallas_op

        rows, cols = pallas_op.AXPY_BLOCK[0], pallas_op.AXPY_BLOCK[1]
        loop = pallas_op.make_axpy_loop(rows, cols)
        v = loop(jnp.ones((rows, cols), jnp.float32), 3)
        assert np.isfinite(float(v))
        rows, cols = pallas_op.SCALE_BLOCK
        loop = pallas_op.make_scale_loop(rows, cols)
        v = loop(jnp.ones((rows, cols), jnp.float32), 3)
        assert np.isfinite(float(v))


def test_bench_end_to_end_on_simulator_mesh():
    """bench.py's full multi-device path (the scoreboard the driver
    runs) must execute on the 8-device simulator mesh and emit valid
    JSON metric lines with the headline LAST — a crash here would
    silence the round's BENCH file."""
    import json
    import os
    import subprocess
    import sys

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                        + " --xla_force_host_platform_device_count=8")
    r = subprocess.run(
        [sys.executable, "bench.py"], cwd="/root/repo", env=env,
        capture_output=True, text=True, timeout=900,
    )
    assert r.returncode == 0, r.stderr[-2000:]
    lines = [json.loads(ln) for ln in r.stdout.strip().splitlines()
             if ln.startswith("{")]
    assert len(lines) >= 5, lines
    for ln in lines:
        assert "metric" in ln and "value" in ln and "unit" in ln
        if ln.get("vs_baseline") is not None:
            assert ln["vs_baseline"] <= 1.0 + 1e-9  # by construction
    headline = lines[-1]
    assert "allreduce" in headline["metric"] or "op_sum" in \
        headline["metric"]
