"""Compiled whole-schedule collectives (coll/plan): frozen device
programs, frozen wire rounds, plan-time frame precomposition, and the
hot-path cvar caching satellites.

Five layers:

1. Device-free plan metadata: signatures (stable across identical
   calls, distinct across shapes, None for ragged/unplannable calls),
   frozen frame templates whose byte stream is IDENTICAL to the
   interpreted ``staged_frames`` path, and the wire-tuning snapshot
   (resolve once; a mid-job cvar write takes effect at the NEXT
   snapshot/plan, never mid-schedule).
2. Device-free wire-plan record/replay over fakes: structure
   verification, loud divergence errors, generation invalidation.
3. In-process compiled plans on the real 8-device world: steady-state
   blocking fires, the MPI-4 persistent 10x re-fire satellite
   (exactly one compile, ``coll_compiled_cache_hits`` == 9, bitwise
   parity vs the interpreted leg on every fire, progress thread on),
   obs fallback, and the compiled whole-tree pass.
4. Fleet-scale determinism: the recorded round schedule of a P=256
   recursive-doubling allreduce replays bit-identically against the
   interpreted ``hier_schedules`` rounds on the simulators.
5. One real 3-process job: spanning persistent allreduce records a
   wire plan at first start and replays precomposed frames after,
   bitwise-equal, with the pvar witnesses.
"""

import itertools
import os
import sys
import textwrap
import threading
import time
from types import SimpleNamespace

import numpy as np
import pytest

import ompi_release_tpu as mpi
from ompi_release_tpu import ops
from ompi_release_tpu.btl import components as btlc
from ompi_release_tpu.coll import plan
from ompi_release_tpu.mca import pvar
from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.native import DssBuffer
from ompi_release_tpu.runtime import wire
from ompi_release_tpu.runtime.state import JobState
from ompi_release_tpu.testing import lockstep
from ompi_release_tpu.tools.tpurun import Job
from ompi_release_tpu.utils.errors import ErrorCode, MPIError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pv(name):
    p = pvar.PVARS.lookup(name)
    assert p is not None, name
    return p.read()


@pytest.fixture(scope="module")
def world():
    return mpi.init()


# ---------------------------------------------------------------------------
# 1. device-free plan metadata
# ---------------------------------------------------------------------------


class TestSignatures:
    def test_identical_calls_share_a_signature(self):
        a = np.zeros((8, 16), np.float32)
        b = np.zeros((8, 16), np.float32)
        s1 = plan.signature_of("allreduce", (a, ops.SUM), {})
        s2 = plan.signature_of("allreduce", (b, ops.SUM), {})
        assert s1 == s2 and s1 is not None

    def test_shape_dtype_root_op_distinguish(self):
        a = np.zeros((8, 16), np.float32)
        base = plan.signature_of("bcast", (a, 0), {})
        assert base != plan.signature_of("bcast", (a, 1), {})
        assert base != plan.signature_of(
            "bcast", (np.zeros((8, 17), np.float32), 0), {})
        assert base != plan.signature_of(
            "bcast", (a.astype(np.int32), 0), {})
        # two distinct Op OBJECTS must not share a plan (a user op
        # named "sum" with a different fn would corrupt results)
        s_sum = plan.signature_of("allreduce", (a, ops.SUM), {})
        s_max = plan.signature_of("allreduce", (a, ops.MAX), {})
        assert s_sum != s_max

    def test_unplannable_calls_return_none(self):
        # ragged buffer lists (v-variants) and pair-op tuples carry
        # data-dependent structure
        assert plan.signature_of(
            "allgatherv", ([np.zeros(3)],), {}) is None
        vals = np.zeros((8, 4), np.float32)
        idxs = np.zeros((8, 4), np.int32)
        assert plan.signature_of(
            "allreduce", ((vals, idxs), ops.MINLOC), {}) is None

    def test_scalar_sequences_are_plannable(self):
        x = np.zeros((8, 16), np.float32)
        s = plan.signature_of("reduce_scatter", (x, [2] * 8, ops.SUM),
                              {})
        assert s is not None
        assert s != plan.signature_of(
            "reduce_scatter", (x, [4] * 4, ops.SUM), {})


class TestFrameTemplates:
    def test_planned_frames_byte_identical_to_staged(self):
        """The frozen-template send path must put the EXACT bytes of
        the interpreted ``staged_frames`` path on the wire — the
        receiver is unchanged, so byte identity IS the parity proof."""
        b = btlc.DcnBtl()
        arr = np.arange(3000, dtype=np.float32).reshape(30, 100)
        saved = btlc._xfer_ids
        try:
            btlc._xfer_ids = itertools.count(42)
            legacy = [bytes(f) for f in b.staged_frames(arr,
                                                        segsize=1024)]
            btlc._xfer_ids = itertools.count(42)
            tpl = btlc.plan_frame_template(arr.shape, arr.dtype, 1024)
            planned = [bytes(f) for f in b.planned_frames(arr, tpl)]
        finally:
            btlc._xfer_ids = saved
        assert planned == legacy
        assert len(planned) == tpl.nchunks + 1  # header + fragments

    def test_template_header_parses(self):
        tpl = btlc.plan_frame_template((4, 4), "int32", 32)
        hdr = DssBuffer(tpl.header(xfer=7, crc=99))
        assert hdr.unpack_string() == "SGH2"
        assert hdr.unpack_int64() == [7]
        assert np.dtype(hdr.unpack_string()) == np.dtype("int32")
        assert hdr.unpack_string() == "4,4"
        assert hdr.unpack_int64(2) == [tpl.nchunks, tpl.chunk]
        assert hdr.unpack_int64() == [99]

    def test_template_mismatch_raises(self):
        b = btlc.DcnBtl()
        tpl = btlc.plan_frame_template((8,), "float32", 16)
        with pytest.raises(MPIError) as ei:
            list(b.planned_frames(np.zeros(9, np.float32), tpl))
        assert "frozen frame template" in str(ei.value)


class TestWireTuning:
    def test_snapshot_resolves_and_freezes(self):
        mca_var.set_value("wire_p2p_lanes", 2)
        mca_var.set_value("wire_coll_timeout_ms", 1234)
        try:
            t = wire.WireTuning()
            assert t.lanes == 2 and t.coll_timeout_ms == 1234
            # a later write does NOT change the frozen snapshot (a
            # plan holding it never sees mid-schedule changes)...
            mca_var.set_value("wire_p2p_lanes", 3)
            assert t.lanes == 2
            # ...but a FRESH snapshot (the next plan) picks it up
            assert wire.WireTuning().lanes == 3
        finally:
            mca_var.VARS.unset("wire_p2p_lanes")
            mca_var.VARS.unset("wire_coll_timeout_ms")

    def test_router_tuning_is_generation_cached(self, monkeypatch):
        r = wire.WireRouter.__new__(wire.WireRouter)
        r._tuning = wire.WireTuning()
        first = r.tuning()
        assert r.tuning() is first  # no write -> same snapshot object
        calls = []
        real_get = mca_var.VARS.get
        monkeypatch.setattr(mca_var.VARS, "get",
                            lambda *a, **k: (calls.append(a),
                                             real_get(*a, **k))[1])
        for _ in range(50):
            r.tuning()
        assert not calls, "steady-state tuning() must not hit the " \
                          "registry"
        mca_var.set_value("wire_pipeline_depth", 7)
        try:
            t2 = r.tuning()
            assert t2 is not first and t2.depth == 7
        finally:
            mca_var.VARS.unset("wire_pipeline_depth")

    def test_coll_timeout_cvar_bounds_waits(self, monkeypatch):
        """Satellite: the hard-coded 60 s collective/ctl wait default
        is now the ``wire_coll_timeout_ms`` cvar."""
        r = wire.WireRouter.__new__(wire.WireRouter)
        r._tuning = wire.WireTuning()
        r._coll_early = {}
        r._coll_early_lock = threading.Lock()
        r._chan_locks = {}
        r._chan_guard = threading.Lock()
        captured = {}

        def fake_sliced(want_src, tag, deadline, comm, peers_fn, what,
                        msg):
            captured["deadline"] = deadline
            raise MPIError(ErrorCode.ERR_PENDING, msg)

        monkeypatch.setattr(r, "_sliced_recv", fake_sliced)
        comm = SimpleNamespace(cid=5, name="c", _ft_epoch0=0)
        mca_var.set_value("wire_coll_timeout_ms", 1500)
        try:
            for fn in (lambda: r.coll_recv(comm, 1),
                       lambda: r.ctl_recv(comm, 1)):
                t0 = time.monotonic()
                with pytest.raises(MPIError):
                    fn()
                waited = captured.pop("deadline") - t0
                assert 1.2 < waited < 1.8, waited
        finally:
            mca_var.VARS.unset("wire_coll_timeout_ms")

    def test_dcn_segsize_is_generation_cached(self, monkeypatch):
        b = btlc.DcnBtl()
        mca_var.set_value("wire_pipeline_segsize", 4096)
        try:
            assert b.pipeline_segsize() == 4096
            calls = []
            real_get = mca_var.VARS.get
            monkeypatch.setattr(mca_var.VARS, "get",
                                lambda *a, **k: (calls.append(a),
                                                 real_get(*a, **k))[1])
            for _ in range(50):
                assert b.pipeline_segsize() == 4096
            assert not calls, "per-message segsize reads must be " \
                              "generation-cached"
            mca_var.set_value("wire_pipeline_segsize", 8192)
            assert b.pipeline_segsize() == 8192
        finally:
            mca_var.VARS.unset("wire_pipeline_segsize")


# ---------------------------------------------------------------------------
# 2. device-free wire-plan record/replay
# ---------------------------------------------------------------------------


class _FakeInner:
    def __init__(self):
        self.rounds = 0

    def exchange(self, sends, recvs):
        self.rounds += 1
        return {p: [np.zeros(4, np.float32)] * int(c)
                for p, c in recvs.items() if int(c) > 0}


class _FakeModule:
    my_pidx = 0   # the observed replay path registers the
    _round = 0    # spanning plan (pidx) and stamps round0

    def __init__(self, comm):
        self.comm = comm
        self._xchg = _FakeInner()
        self.planned_rounds = []
        self.reap_timeouts = []

    def _send_all_planned(self, rnd, sends):
        self.planned_rounds.append(rnd)

    def _reap(self, pending, on_arrival, timeout_ms=None, record=True):
        self.reap_timeouts.append(timeout_ms)
        for p, c in pending.items():
            for _ in range(c):
                on_arrival(p, np.zeros(4, np.float32))


def _fake_comm(cid=900):
    comm = SimpleNamespace(cid=cid, name=f"fake{cid}",
                           runtime=SimpleNamespace(wire=None))
    comm._hier_module = _FakeModule(comm)
    return comm


def _schedule(m, payload):
    """A two-round fixed schedule driven through m._xchg."""
    got1 = m._xchg.exchange({1: [payload]}, {1: 1})
    got2 = m._xchg.exchange({2: [payload, payload]}, {2: 2})
    return got1[1][0] + got2[2][0]


def _manual_plan(recorded, gen, cid):
    rounds = [plan.WireRound(meta, rec, tuple(
        (p, tuple(None for _ in arrs)) for p, arrs in meta),
        tag=0, depth=1) for meta, rec in recorded]
    return plan.WirePlan(gen, cid, rounds, 60_000)


class TestWirePlanReplay:
    def test_record_then_replay_uses_planned_sends(self, monkeypatch):
        comm = _fake_comm()
        m = comm._hier_module
        state = plan.SpanningPlanState(comm, "allreduce")
        monkeypatch.setattr(
            plan, "freeze_wire_plan",
            lambda c, rec, gen: _manual_plan(rec, gen, c.cid))
        payload = np.ones(4, np.float32)
        h0 = _pv("coll_compiled_cache_hits")
        state.run(lambda: _schedule(m, payload), (), {})  # records
        assert state.plan is not None
        assert len(state.plan.rounds) == 2
        assert m._xchg.rounds == 2 and not m.planned_rounds
        state.run(lambda: _schedule(m, payload), (), {})  # replays
        assert m._xchg.rounds == 2, "replay must not use the " \
                                    "interpreted transport"
        assert len(m.planned_rounds) == 2
        # replay waits are bounded by the PLAN-TIME timeout snapshot
        assert m.reap_timeouts == [60_000, 60_000]
        h1 = _pv("coll_compiled_cache_hits")
        assert h1["sum"] - h0["sum"] == 1
        assert h1["count"] - h0["count"] == 2

    def test_divergence_is_a_loud_typed_error(self, monkeypatch):
        comm = _fake_comm(901)
        m = comm._hier_module
        state = plan.SpanningPlanState(comm, "allreduce")
        monkeypatch.setattr(
            plan, "freeze_wire_plan",
            lambda c, rec, gen: _manual_plan(rec, gen, c.cid))
        state.run(lambda: _schedule(m, np.ones(4, np.float32)), (), {})
        with pytest.raises(MPIError) as ei:
            state.run(lambda: _schedule(m, np.ones(5, np.float32)),
                      (), {})
        assert ei.value.code == ErrorCode.ERR_INTERN
        assert "diverged" in str(ei.value)
        # the raise must DROP the stale plan so the error's own advice
        # ("re-issue the collective") works: the next fire re-records
        # instead of replaying the same frozen rounds forever
        assert state.plan is None
        state.run(lambda: _schedule(m, np.ones(5, np.float32)), (), {})
        assert state.plan is not None
        assert state.plan.rounds[0].sends_meta[0][1][0][0] == (5,)

    def test_overlap_opt_out_stays_interpreted(self, monkeypatch):
        """wire_overlap_exchange=False serializes sends in the
        interpreted adapter; the planned replay path is striped by
        construction, so the opt-out must bypass planning entirely."""
        comm = _fake_comm(903)
        m = comm._hier_module
        state = plan.SpanningPlanState(comm, "allreduce")
        monkeypatch.setattr(
            plan, "freeze_wire_plan",
            lambda c, rec, gen: _manual_plan(rec, gen, c.cid))
        pay = np.ones(4, np.float32)
        mca_var.set_value("wire_overlap_exchange", False)
        try:
            state.run(lambda: _schedule(m, pay), (), {})
            state.run(lambda: _schedule(m, pay), (), {})
            assert state.plan is None, \
                "overlap opt-out must not freeze a striped plan"
            assert m._xchg.rounds == 4 and not m.planned_rounds
        finally:
            mca_var.VARS.unset("wire_overlap_exchange")
        state.run(lambda: _schedule(m, pay), (), {})  # re-enabled
        assert state.plan is not None

    def test_cvar_write_takes_effect_at_next_plan(self, monkeypatch):
        """Satellite: a mid-job cvar write re-plans at the NEXT fire
        — the stale frozen plan is dropped, never half-applied."""
        comm = _fake_comm(902)
        m = comm._hier_module
        state = plan.SpanningPlanState(comm, "allreduce")
        monkeypatch.setattr(
            plan, "freeze_wire_plan",
            lambda c, rec, gen: _manual_plan(rec, gen, c.cid))
        pay = np.ones(4, np.float32)
        state.run(lambda: _schedule(m, pay), (), {})
        frozen = state.plan
        state.run(lambda: _schedule(m, pay), (), {})  # replay
        assert state.plan is frozen
        mca_var.set_value("wire_pipeline_depth", 9)  # generation bump
        try:
            state.run(lambda: _schedule(m, pay), (), {})
            assert state.plan is not frozen, \
                "cvar write must re-plan at the next fire"
            # 2 recorded + 0 replayed + 2 re-recorded interpreted
            assert m._xchg.rounds == 4
        finally:
            mca_var.VARS.unset("wire_pipeline_depth")

    def test_watchdog_contributor_names_active_replay(self,
                                                      monkeypatch):
        """Satellite: a rank stuck mid-``PlannedXchg`` gets a
        postmortem that names the frozen plan it is inside — ledger
        plan id, collective, signature, round index — via the
        watchdog's ``frozen_plans`` contributor, and the entry clears
        once the fire completes."""
        import ompi_release_tpu.obs as obs

        comm = _fake_comm(911)
        m = comm._hier_module
        state = plan.SpanningPlanState(comm, "allreduce")
        monkeypatch.setattr(
            plan, "freeze_wire_plan",
            lambda c, rec, gen: _manual_plan(rec, gen, c.cid))
        pay = np.ones(4, np.float32)
        state.run(lambda: _schedule(m, pay), (), {})  # record
        seen = {}
        orig = _FakeModule._reap

        def spy(self, pending, on_arrival, timeout_ms=None,
                record=True):
            # what the watchdog would dump while we wait in _reap
            seen.setdefault("snap", plan._frozen_plans_snapshot())
            return orig(self, pending, on_arrival, timeout_ms, record)

        monkeypatch.setattr(_FakeModule, "_reap", spy)
        obs.enable()
        try:
            state.run(lambda: _schedule(m, pay), (), {})  # replay
        finally:
            obs.disable()
        snap = seen["snap"]
        active = [a for a in snap["active_replays"]
                  if a["cid"] == 911]
        assert active, snap
        a = active[0]
        assert a["name"] == "allreduce"
        assert a["rounds_total"] == 2 and 1 <= a["round"] <= 2
        assert a["plan"] is not None, "ledger plan id not registered"
        assert "fires" in snap and "hits" in snap  # cache stats ride
        # ...and the live entry clears when the fire completes
        after = plan._frozen_plans_snapshot()["active_replays"]
        assert not any(x["cid"] == 911 for x in after)


# ---------------------------------------------------------------------------
# 3. in-process compiled plans (real 8-device world)
# ---------------------------------------------------------------------------


class TestDevicePlans:
    def test_steady_state_blocking_fires_frozen_program(self, world):
        x = np.arange(world.size * 32,
                      dtype=np.float32).reshape(world.size, 32)
        comm = world.dup(name="plan_blk")
        try:
            first = np.asarray(comm.allreduce(x))  # capture
            h0 = _pv("coll_compiled_cache_hits")
            c0 = _pv("coll_programs_compiled")
            for _ in range(3):
                np.testing.assert_array_equal(
                    np.asarray(comm.allreduce(x)), first)
            h1 = _pv("coll_compiled_cache_hits")
            assert h1["sum"] - h0["sum"] == 3
            assert h1["count"] - h0["count"] == 3
            assert _pv("coll_programs_compiled") == c0
        finally:
            comm.free()

    def test_persistent_ten_fires_one_compile(self, world):
        """THE satellite: a compiled ``allreduce_init`` request fired
        10x with mutated buffers compiles exactly once
        (``coll_compiled_cache_hits`` == 9 over the 10 fires) and is
        bitwise-identical to the interpreted leg on EVERY fire —
        under the dedicated progress thread."""
        base = np.arange(world.size * 64,
                         dtype=np.float32).reshape(world.size, 64)
        # interpreted references for all ten buffer states, computed
        # with the plan layer OFF (the interpreted leg)
        mca_var.set_value("coll_compiled", 0)
        try:
            refs = [np.asarray(world.allreduce(base + k))
                    for k in range(10)]
        finally:
            mca_var.VARS.unset("coll_compiled")
        mca_var.set_value("progress_thread", 1)
        comm = world.dup(name="plan_pers")
        try:
            buf = base.copy()
            req = comm.allreduce_init(buf)
            h0 = _pv("coll_compiled_cache_hits")
            c0 = _pv("coll_programs_compiled")
            for k in range(10):
                req.start()
                req.wait()
                np.testing.assert_array_equal(
                    np.asarray(req.value), refs[k])  # BITWISE
                buf += 1  # start() must read the CURRENT bytes
            h1 = _pv("coll_compiled_cache_hits")
            assert h1["count"] - h0["count"] == 10
            assert h1["sum"] - h0["sum"] == 9, \
                "exactly the first start may capture"
            assert _pv("coll_programs_compiled") - c0 == 1, \
                "exactly one compile across ten fires"
        finally:
            mca_var.VARS.unset("progress_thread")
            comm.free()

    def test_cvar_write_invalidates_device_plan(self, world):
        x = np.ones((world.size, 16), np.float32)
        comm = world.dup(name="plan_inval")
        try:
            comm.allreduce(x)  # capture
            h0 = _pv("coll_compiled_cache_hits")
            comm.allreduce(x)  # hit
            mca_var.set_value("coll_pipeline_segsize", 4096)
            try:
                comm.allreduce(x)  # generation moved: re-capture
            finally:
                mca_var.VARS.unset("coll_pipeline_segsize")
            h1 = _pv("coll_compiled_cache_hits")
            assert h1["count"] - h0["count"] == 2
            assert h1["sum"] - h0["sum"] == 1
        finally:
            comm.free()

    def test_obs_on_rides_the_compiled_plan(self, world):
        """Observability is a property of the steady state: enabling
        obs must NOT bounce frozen plans back to the interpreted
        path.  An observed fire replays the compiled program (hit
        counter advances), stays bitwise-identical, and appends a
        fixed-size flight-recorder record to the obs ledger."""
        import ompi_release_tpu.obs as obs_pkg
        from ompi_release_tpu.obs import ledger as obs_ledger

        x = np.ones((world.size, 8), np.float32)
        comm = world.dup(name="plan_obs")
        try:
            want = np.asarray(comm.allreduce(x))  # capture (obs off)
            was = obs_pkg.enabled
            obs_pkg.enable()
            try:
                h0 = _pv("coll_compiled_cache_hits")
                r0 = len(obs_ledger.records())
                got = np.asarray(comm.allreduce(x))
                h1 = _pv("coll_compiled_cache_hits")
                recs = obs_ledger.records()
            finally:
                if not was:
                    obs_pkg.disable()
            np.testing.assert_array_equal(got, want)
            assert h1["count"] == h0["count"] + 1
            assert h1["sum"] == h0["sum"] + 1, \
                "the observed fire must replay the frozen plan"
            new = recs[r0:]
            assert any(r["cid"] == comm.cid for r in new), \
                "observed compiled fire must land in the ledger"
            pid = [r["plan"] for r in new if r["cid"] == comm.cid][-1]
            meta = obs_ledger.plans()[pid]
            assert meta["kind"] == "device"
            assert meta["name"] == "allreduce"
        finally:
            comm.free()

    def test_same_named_ops_get_distinct_programs(self, world):
        """Program caches (driver AND plan layer) key reductions by
        the op OBJECT: two user ops sharing a name but carrying
        different combiners must never share a compiled program —
        name keying silently fired the first op's baked-in combiner
        for the second (wrong numbers, no error)."""
        import jax.numpy as jnp

        from ompi_release_tpu.ops import Op

        op_add = Op("custom", lambda a, b: a + b, commutative=True)
        op_max = Op("custom", lambda a, b: jnp.maximum(a, b),
                    commutative=True)
        x = np.random.default_rng(7).standard_normal(
            (world.size, 32)).astype(np.float32)
        comm = world.dup(name="plan_opkey")
        try:
            ra = np.asarray(comm.allreduce(x, op=op_add))
            rb = np.asarray(comm.allreduce(x, op=op_max))
            np.testing.assert_allclose(ra[0], x.sum(axis=0), rtol=1e-5)
            np.testing.assert_array_equal(rb[0], x.max(axis=0))
            # steady state: each op replays ITS own frozen program
            np.testing.assert_array_equal(
                np.asarray(comm.allreduce(x, op=op_add)), ra)
            np.testing.assert_array_equal(
                np.asarray(comm.allreduce(x, op=op_max)), rb)
        finally:
            comm.free()

    def test_plan_cache_cleared_on_comm_free(self, world):
        x = np.ones((world.size, 8), np.float32)
        comm = world.dup(name="plan_free")
        cid = comm.cid
        comm.allreduce(x)
        assert any(k[0] == cid for k in plan._device_plans)
        comm.free()
        assert not any(k[0] == cid for k in plan._device_plans)

    def test_compiled_whole_tree_pass(self, world):
        """One jitted program for a whole planned tree pass, parity
        vs the per-leaf blocking collectives, cached per signature."""
        from ompi_release_tpu.parallel import tree

        n = world.size
        t = {"w": np.arange(n * 48,
                            dtype=np.float32).reshape(n, 48) * 0.5,
             "b": np.ones((n, 5), np.float32),
             "i": np.arange(n * 6, dtype=np.int32).reshape(n, 6)}
        out = tree.run_tree_pass(world, t, kind="allreduce",
                                 bucket_bytes=1 << 20)
        for k in t:
            np.testing.assert_array_equal(
                np.asarray(out[k]), np.asarray(world.allreduce(t[k])))
        c0 = _pv("coll_programs_compiled")
        tree.run_tree_pass(world, t, kind="allreduce",
                           bucket_bytes=1 << 20)
        assert _pv("coll_programs_compiled") == c0  # cached program
        with pytest.raises(ValueError):
            tree.run_tree_pass(world, t, kind="alltoall")


# ---------------------------------------------------------------------------
# 4. fleet-scale determinism (P=256, simulator)
# ---------------------------------------------------------------------------


class TestFleetDeterminism:
    def test_recorded_schedule_replays_bit_identically_p256(self):
        """Satellite: the frozen plan's round schedule is a pure
        function of (procs, me, sizes) — two recordings of the P=256
        recursive-doubling allreduce are IDENTICAL per rank, and a
        replay that verifies every round against the recorded plan
        (the PlannedXchg check) reproduces the interpreted result
        bit-for-bit."""
        from ompi_release_tpu.coll import hier_schedules as hs

        P = 256
        procs = list(range(P))
        data = {p: np.arange(8, dtype=np.int64) * (p + 1)
                for p in procs}

        def run_recorded(rounds_by_rank):
            def fn(x, p):
                rec = plan.RoundRecorder(x)
                flats = hs.allgather_bruck(rec, procs, p, data[p],
                                           [8] * P)
                rounds_by_rank[p] = tuple(rec.rounds)
                return np.sum(np.stack(flats), axis=0)
            return lockstep.simulate(procs, fn, timeout=120)

        r1, r2 = {}, {}
        out1 = run_recorded(r1)
        out2 = run_recorded(r2)
        assert r1 == r2, "round schedule must be deterministic"
        want = sum(np.arange(8, dtype=np.int64) * (p + 1)
                   for p in procs)
        for p in (0, 41, 137, P - 1):
            np.testing.assert_array_equal(out1[p], want)
            np.testing.assert_array_equal(out2[p], want)
            assert len(r1[p]) == 8  # ceil(log2 256) rounds

        class Verify:
            def __init__(self, inner, rounds):
                self.inner, self.rounds, self.i = inner, rounds, 0

            def exchange(self, sends, recvs):
                meta = plan._round_meta(
                    {q: [np.asarray(a) for a in arrs]
                     for q, arrs in sends.items() if arrs})
                rec = tuple(sorted((int(q), int(c))
                            for q, c in recvs.items() if int(c) > 0))
                assert (meta, rec) == self.rounds[self.i], \
                    f"round {self.i} diverged from the frozen plan"
                self.i += 1
                return self.inner.exchange(sends, recvs)

        def replay(x, p):
            v = Verify(x, r1[p])
            flats = hs.allgather_bruck(v, procs, p, data[p], [8] * P)
            assert v.i == len(r1[p])
            return np.sum(np.stack(flats), axis=0)

        out3 = lockstep.simulate(procs, replay, timeout=120)
        for p in procs:
            np.testing.assert_array_equal(out3[p], out1[p])  # BITWISE

    def test_fleet_sim_records_identically(self):
        """Same determinism through the PR 12 fleet simulator's
        virtual wire (fabric latencies must not perturb structure)."""
        from ompi_release_tpu.coll import hier_schedules as hs
        from ompi_release_tpu.testing import fleet_sim as fs

        P = 64
        stories = []
        for _ in range(2):
            fleet = fs.FleetSim(P, hosts_per=8)
            procs = fleet.procs
            data = {p: np.full(4, p + 1, np.int64) for p in procs}
            rounds_by_rank = {}

            def fn(x, p):
                rec = plan.RoundRecorder(x)
                flats = hs.allgather_bruck(rec, procs, p, data[p],
                                           [4] * P)
                rounds_by_rank[p] = tuple(rec.rounds)
                return np.sum(np.stack(flats), axis=0)

            rep = fleet.run(fn, timeout_s=120)
            assert len(rep.ok()) == P
            stories.append(dict(rounds_by_rank))
        assert stories[0] == stories[1]


# ---------------------------------------------------------------------------
# 5. the real 3-process job
# ---------------------------------------------------------------------------


APP_PRELUDE = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, %r)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import ompi_release_tpu as mpi
    from ompi_release_tpu.mca import pvar, var as mca_var
    from ompi_release_tpu.runtime.runtime import Runtime

    def _pv(name):
        p = pvar.PVARS.lookup(name)
        return p.read() if p is not None else None
""" % REPO)


class TestCompiledPlanJob:
    def test_spanning_persistent_replays_frozen_wire_plan(
            self, tmp_path, capfd):
        """3-process world: the first blocking allreduce records and
        freezes the wire plan (round structure + frame headers);
        later blocking fires AND persistent start()s replay it —
        bitwise-equal results, ``coll_compiled_cache_hits`` counting
        every replay, ``coll_wire_rounds_frozen`` counting the frozen
        rounds — and a mid-job cvar write re-plans at the next fire
        instead of corrupting the running schedule."""
        app = tmp_path / "app.py"
        app.write_text(APP_PRELUDE + textwrap.dedent("""
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            n = world.size
            x = np.stack([np.arange(512, dtype=np.float32)
                          * (off + i + 1) for i in range(2)])
            want = sum(np.arange(512, dtype=np.float32) * (r + 1)
                       for r in range(n))
            first = np.asarray(world.allreduce(x))  # record + freeze
            np.testing.assert_array_equal(first[0], want)
            assert _pv("coll_wire_rounds_frozen") >= 1
            st0 = _pv("coll_compiled_cache_hits")
            for _ in range(3):
                got = np.asarray(world.allreduce(x))  # replay
                np.testing.assert_array_equal(got, first)  # BITWISE
            st1 = _pv("coll_compiled_cache_hits")
            assert st1["sum"] - st0["sum"] == 3, (st0, st1)

            pr = world.allreduce_init(x)
            st0 = _pv("coll_compiled_cache_hits")
            for k in range(3):
                pr.start(); pr.wait()
                np.testing.assert_array_equal(
                    np.asarray(pr.value), first)
                # same (cid, signature) as the blocking fires: every
                # start replays the already-frozen plan
            st1 = _pv("coll_compiled_cache_hits")
            assert st1["sum"] - st0["sum"] == 3, (st0, st1)

            # a cvar write re-plans at the NEXT fire (one capturing
            # run, then replays resume) — never mid-schedule
            mca_var.set_value("wire_pipeline_depth", 2)
            st0 = _pv("coll_compiled_cache_hits")
            got = np.asarray(world.allreduce(x))
            np.testing.assert_array_equal(got, first)
            st1 = _pv("coll_compiled_cache_hits")
            assert st1["sum"] - st0["sum"] == 0, (st0, st1)
            assert st1["count"] - st0["count"] == 1, (st0, st1)
            got = np.asarray(world.allreduce(x))
            np.testing.assert_array_equal(got, first)
            st2 = _pv("coll_compiled_cache_hits")
            assert st2["sum"] - st1["sum"] == 1, (st1, st2)
            print("PLAN-JOB-OK", flush=True)
            mpi.finalize()
        """))
        job = Job(3, [sys.executable, str(app)], [],
                  heartbeat_s=0.5, miss_limit=8)
        rc = job.run(timeout_s=240)
        out = capfd.readouterr()
        assert rc == 0, out.out + out.err
        assert job.job_state.visited(JobState.TERMINATED)
        assert out.out.count("PLAN-JOB-OK") == 3

    def test_obs_on_job_matches_obs_off_and_reconstructs_flows(
            self, tmp_path, capfd):
        """THE regression satellite: a 3-process job fires the same
        spanning allreduce with obs OFF then ON. The obs-ON fires must
        replay the SAME frozen wire plan (identical
        ``coll_compiled_cache_hits`` deltas — observability no longer
        bounces plans to the interpreted path), stay bitwise-identical,
        and land fixed-size flight-recorder records whose doctor
        expansion reconstructs cross-process flow arrows in the merged
        trace."""
        dump_dir = tmp_path / "obs"
        dump_dir.mkdir()
        app = tmp_path / "app.py"
        app.write_text(APP_PRELUDE + textwrap.dedent("""
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            n = world.size
            # set BEFORE the first freeze: a cvar write bumps the
            # tuning generation and would re-plan the next fire
            mca_var.set_value("obs_dump_dir", DUMP_DIR)
            x = np.stack([np.arange(512, dtype=np.float32)
                          * (off + i + 1) for i in range(2)])
            first = np.asarray(world.allreduce(x))  # record + freeze

            h0 = _pv("coll_compiled_cache_hits")
            for _ in range(3):
                got = np.asarray(world.allreduce(x))   # obs OFF
                np.testing.assert_array_equal(got, first)  # BITWISE
            h1 = _pv("coll_compiled_cache_hits")
            d_off = (h1["sum"] - h0["sum"], h1["count"] - h0["count"])

            import ompi_release_tpu.obs as obs
            from ompi_release_tpu.obs import ledger
            obs.enable()
            h0 = _pv("coll_compiled_cache_hits")
            for _ in range(3):
                got = np.asarray(world.allreduce(x))   # obs ON
                np.testing.assert_array_equal(got, first)  # BITWISE
            h1 = _pv("coll_compiled_cache_hits")
            d_on = (h1["sum"] - h0["sum"], h1["count"] - h0["count"])
            assert d_on == d_off == (3, 3), (d_off, d_on)

            recs = [r for r in ledger.records()
                    if r["kind"] == ledger.KIND_SPANNING]
            assert len(recs) == 3, recs
            assert all(len(r["round_ts"]) >= 1 for r in recs)
            meta = ledger.plans()[recs[0]["plan"]]
            assert meta["name"] == "allreduce"
            assert len(meta["rounds"]) == len(recs[0]["round_ts"])
            print("OBS-PLAN-JOB-OK", flush=True)
            mpi.finalize()  # dumps journal-p*.json + ledger-p*.json
        """).replace("DUMP_DIR", repr(str(dump_dir))))
        job = Job(3, [sys.executable, str(app)], [],
                  heartbeat_s=0.5, miss_limit=8)
        rc = job.run(timeout_s=240)
        out = capfd.readouterr()
        assert rc == 0, out.out + out.err
        assert out.out.count("OBS-PLAN-JOB-OK") == 3

        # every rank dumped its flight-recorder ring at finalize...
        from ompi_release_tpu.obs import doctor
        ledgers = sorted(dump_dir.glob("ledger-p*.json"))
        assert len(ledgers) == 3, list(dump_dir.iterdir())
        # ...and the doctor merge expands them into synthetic spans
        # that name the compiled collective's wire rounds and pair
        # into cross-process flow arrows
        dumps = doctor.load_dir(str(dump_dir))
        led = [s for d in dumps for s in d["spans"] if s.get("ledger")]
        assert led, "no ledger-reconstructed spans in the merge"
        assert any(str(s["op"]).startswith("allreduce_wire_round")
                   for s in led)
        pairs = [p for p in doctor.flow_pairs(dumps)
                 if p["src"].get("ledger") and p["cross_process"]]
        assert pairs, "ledger flows did not pair into arrows"
        trace = doctor.merge(dumps)
        assert trace["otherData"]["cross_process_flows"] > 0
        assert any(e.get("cat") == "flow" for e in
                   trace["traceEvents"]), "merged trace lost the flows"


# ---------------------------------------------------------------------------
# cache stats / selftest surface
# ---------------------------------------------------------------------------


def test_cache_stats_shape():
    st = plan.cache_stats()
    assert set(st) == {"device_plans", "spanning_plans", "fires",
                       "hits"}
    assert st["fires"] >= st["hits"] >= 0
