"""Topology-aware torus/multiring schedules, the persistent
per-topology tuning database, and online re-tuning.

Four planes, mirroring the PR's layers:

1. SCHEDULE CORRECTNESS — the lockstep simulator runs the real
   multiring / 2D-torus round code; every op/dtype must match the
   ``recursive_doubling`` reference BITWISE (the data is integer-
   valued, so every fold order is exact even in float32 — parity is
   bit-for-bit, not within-tolerance).

2. FLEET SCALING — the PR 12 simulator at P ∈ {256, 1024} on a
   ``hosts_per=8`` topology: measured host-crossing bytes equal the
   closed forms exactly, and the 2D torus moves STRICTLY fewer total
   inter-host bytes (and ~d0× fewer per NIC) than the flat ring.

3. TUNING DATABASE — fingerprint round-trips, the optional
   ``# fingerprint:`` header stanza (legacy files pinned unchanged),
   versioned register/select, nearest-match rules, and the
   dynamic-rules precedence: forcing > explicit file > DB entry >
   fixed constants.

4. ONLINE RE-TUNING — a seeded slow-NIC straggler degrades the
   per-comm MB/s series; the sustained-slow detector triggers a
   bounded fleet-sim micro-probe whose verdict registers a NEW db
   version and lands via the cvar write that bumps the MCA write
   generation — so PR 13 frozen plans provably re-freeze at the next
   fire (unit + real-job test).
"""

import os
import sys
import textwrap

import numpy as np
import pytest

from ompi_release_tpu import ops
import ompi_release_tpu.coll.components  # noqa: F401  (registers the
# coll_tuned_* cvars and the plain rule namespaces)
from ompi_release_tpu.coll import dynamic_rules
from ompi_release_tpu.coll import hier_schedules as hs
from ompi_release_tpu.coll import topo_schedules as ts
from ompi_release_tpu.coll.base import COLL_FRAMEWORK
from ompi_release_tpu.mca import pvar
from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.runtime.state import JobState
from ompi_release_tpu.testing import fleet_sim as fs
from ompi_release_tpu.testing.lockstep import simulate
from ompi_release_tpu.tools.tpurun import Job
from ompi_release_tpu.tuning import db as tdb
from ompi_release_tpu.tuning import retune
from ompi_release_tpu.utils.errors import MPIError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
slow = pytest.mark.slow

COLL_FRAMEWORK.lookup("tuned").register_vars()  # device-free cvar reg


def _host_of(procs, per):
    """Uniform fake host map: ``per`` consecutive procs per host."""
    return {p: f"h{i // per}" for i, p in enumerate(procs)}


def _linear_fold(parts, op):
    acc = parts[0]
    for nxt in parts[1:]:
        acc = np.asarray(op(acc, nxt))
    return acc


@pytest.fixture(autouse=True)
def _clean_tuning_state():
    yield
    for v in ("coll_tuned_use_dynamic_rules",
              "coll_tuned_dynamic_rules_filename",
              "coll_tuning_db_dir", "hier_topo_schedules",
              "hier_multiring_k", "hier_inter_algorithm",
              "tune_online", "tune_online_window",
              "tune_online_sustain", "tune_online_slow_factor",
              "tune_online_cooldown_s"):
        mca_var.VARS.unset(v)
    tdb._reset_for_tests()
    retune._reset_for_tests()


# ---------------------------------------------------------------------------
# 1. grids, strides, closed forms
# ---------------------------------------------------------------------------

class TestGridsAndClosedForms:
    def test_uniform_grid(self):
        procs = [3, 1, 4, 1 + 8, 5 + 8, 9 + 16]  # deliberately unsorted
        procs = [1, 3, 4, 9, 13, 25]
        host_of = {1: "a", 3: "a", 4: "b", 9: "b", 13: "c", 25: "c"}
        d0, d1, groups = ts.torus_grid(procs, host_of)
        assert (d0, d1) == (2, 3)
        # groups ordered by lowest member, members sorted
        assert groups == [[1, 3], [4, 9], [13, 25]]
        assert ts.grid_dims(procs, host_of) == (2, 3)

    def test_ragged_and_single_host_are_none(self):
        procs = [0, 1, 2]
        assert ts.torus_grid(procs, {0: "a", 1: "a", 2: "b"}) is None
        assert ts.torus_grid(procs, {0: "a", 1: "a", 2: "a"}) is None
        # missing host entries degrade to per-proc pseudo-hosts
        assert ts.grid_dims([0, 1], {}) == (1, 2)

    def test_ring_strides_are_coprime_and_distinct(self):
        for P in (4, 6, 8, 12, 16, 7):
            strides = ts.ring_strides(P, 4)
            assert strides[0] == 1
            assert len(set(strides)) == len(strides)
            import math
            for s in strides:
                assert math.gcd(s, P) == 1
            # distinct strides => pairwise-distinct successors
            for me in range(P):
                succ = [(me + s) % P for s in strides]
                assert len(set(succ)) == len(strides)

    def test_closed_forms(self):
        assert ts.torus_rounds(8, 32) == 2 * 7 + 2 * 31
        # n=2048 f32 over d0=8,d1=32: per0=256, per1=8 elems
        assert ts.torus_inter_bytes_per_rank(2048, 4, 8, 32) \
            == 2 * 31 * 8 * 4
        assert ts.torus_inter_bytes_total(2048, 4, 8, 32) \
            == 256 * 2 * 31 * 8 * 4
        assert ts.flat_ring_inter_bytes_total(2048, 4, 256, 32) \
            == 32 * 2 * 255 * 8 * 4


# ---------------------------------------------------------------------------
# 2. lockstep parity: bitwise vs the recursive_doubling reference
# ---------------------------------------------------------------------------

GRIDS = [(4, 2), (8, 2), (8, 4), (12, 4), (6, 3)]


class TestTopoParityMatrix:
    """multiring/torus2d vs recursive_doubling, bitwise for EVERY
    op/dtype: integer-valued data keeps every f32 fold order exact."""

    OPS = [(ops.SUM, "sum"), (ops.PROD, "prod"), (ops.MAX, "max"),
           (ops.MIN, "min"), (ops.BAND, "band")]

    @pytest.mark.parametrize("P,per", GRIDS,
                             ids=lambda g: "x".join(map(str, g))
                             if isinstance(g, tuple) else str(g))
    @pytest.mark.parametrize("dtype", [np.int32, np.float32])
    def test_allreduce_bitwise(self, P, per, dtype):
        procs = [3 * i + 1 for i in range(P)]
        host_of = _host_of(procs, per)
        rng = np.random.RandomState(P * per)
        for op, opname in self.OPS:
            if opname == "band" and dtype is np.float32:
                continue
            lo, hi = (1, 3) if opname == "prod" else (1, 50)
            data = {p: rng.randint(lo, hi, 13).astype(dtype)
                    for p in procs}
            npop = lambda a, b: np.asarray(op(a, b))  # noqa: E731
            ident = op.identity_for(dtype)
            # the recursive_doubling reference: doubling allgather +
            # the ordered index fold (the exact-order schedule)
            ref = simulate(procs, lambda x, p: _linear_fold(
                hs.allgather_bruck(x, procs, p, data[p], [13] * P),
                op))
            want = np.asarray(ref[procs[0]])
            out = simulate(procs, lambda x, p: ts.allreduce_torus2d(
                x, procs, p, data[p], npop, ident, host_of))
            for p in procs:
                np.testing.assert_array_equal(
                    np.asarray(out[p]).ravel().astype(dtype), want,
                    err_msg=f"torus2d/{opname}/{P}x{per}")
            for k in (2, 4):
                out = simulate(
                    procs, lambda x, p: ts.allreduce_multiring(
                        x, procs, p, data[p], npop, ident, k))
                for p in procs:
                    np.testing.assert_array_equal(
                        np.asarray(out[p]).ravel().astype(dtype),
                        want,
                        err_msg=f"multiring(k={k})/{opname}/{P}")

    def test_ragged_layout_falls_back_and_stays_correct(self):
        procs = list(range(5))
        host_of = {0: "a", 1: "a", 2: "a", 3: "b", 4: "b"}  # ragged
        data = {p: np.arange(11, dtype=np.int64) * (p + 1)
                for p in procs}
        want = sum(data[p] for p in procs)
        out = simulate(procs, lambda x, p: ts.allreduce_torus2d(
            x, procs, p, data[p], np.add, 0, host_of))
        for p in procs:
            np.testing.assert_array_equal(
                np.asarray(out[p]).ravel(), want)

    def test_multiring_degrades_to_single_ring_when_p_small(self):
        procs = [0, 1]  # only stride 1 is coprime: single ring
        data = {0: np.arange(7, dtype=np.int32),
                1: np.arange(7, dtype=np.int32) * 3}
        out = simulate(procs, lambda x, p: ts.allreduce_multiring(
            x, procs, p, data[p], np.add, 0, 8))
        for p in procs:
            np.testing.assert_array_equal(
                np.asarray(out[p]).ravel(), data[0] + data[1])

    @pytest.mark.parametrize("P,per", GRIDS,
                             ids=lambda g: "x".join(map(str, g))
                             if isinstance(g, tuple) else str(g))
    def test_allgather_torus_heterogeneous_blocks(self, P, per):
        procs = [2 * i + 1 for i in range(P)]
        host_of = _host_of(procs, per)
        rng = np.random.RandomState(P + per)
        blocks = {p: rng.randint(0, 99, ((i % 2) + 1, 5))
                  .astype(np.int32) for i, p in enumerate(procs)}
        out = simulate(procs, lambda x, p: ts.allgather_torus2d(
            x, procs, p, blocks[p], host_of))
        for p in procs:
            for i, q in enumerate(procs):
                np.testing.assert_array_equal(out[p][i], blocks[q])

    @pytest.mark.parametrize("P,per", GRIDS,
                             ids=lambda g: "x".join(map(str, g))
                             if isinstance(g, tuple) else str(g))
    def test_bcast_torus_every_root(self, P, per):
        procs = [2 * i for i in range(P)]
        host_of = _host_of(procs, per)
        rng = np.random.RandomState(P)
        val = rng.randint(0, 99, (4, 3)).astype(np.int32)
        for root in (procs[0], procs[-1], procs[P // 2]):
            out = simulate(procs, lambda x, p: ts.bcast_torus2d(
                x, procs, p, root, val if p == root else None,
                host_of))
            for p in procs:
                np.testing.assert_array_equal(np.asarray(out[p]), val)

    def test_torus_bcast_dcn_copies_are_d1_minus_1(self):
        """The torus bcast's inter-host traffic is exactly d1-1
        copies — counted on the fleet fabric."""
        P, per = 16, 4
        val = np.arange(64, dtype=np.int32)
        fleet = fs.FleetSim(P, hosts_per=per, seed=2)
        procs = fleet.procs
        host_of = fleet.fabric.host_of
        rep = fleet.run(lambda x, p: ts.bcast_torus2d(
            x, procs, p, 0, val if p == 0 else None, host_of),
            label="bcast_torus")
        d1 = P // per
        total_inter = sum(rep.inter_bytes_sent.values())
        assert total_inter == (d1 - 1) * val.nbytes
        for p in procs:
            np.testing.assert_array_equal(np.asarray(rep.value(p)),
                                          val)


# ---------------------------------------------------------------------------
# 3. fleet scaling: closed-form inter-host bytes at P ∈ {256, 1024}
# ---------------------------------------------------------------------------

def _torus_run(P, hosts_per=8):
    procs = list(range(P))
    n = 8 * P  # divisible by P, d0 and d1: the closed forms are exact
    data = {p: np.arange(n, dtype=np.float32) * ((p % 5) + 1)
            for p in procs}
    fleet = fs.FleetSim(P, hosts_per=hosts_per, seed=1)
    host_of = fleet.fabric.host_of
    rep = fleet.run(lambda x, p: ts.allreduce_torus2d(
        x, procs, p, data[p], np.add, 0.0, host_of),
        label="allreduce_torus")
    want = np.arange(n, dtype=np.float32) * sum(
        (p % 5) + 1 for p in procs)
    return rep, n, want


class TestFleetScaling:
    @pytest.mark.parametrize("P", [256, 1024])
    def test_torus_closed_form_and_strictly_fewer_inter_bytes(self, P):
        d0, d1 = 8, P // 8
        rep, n, want = _torus_run(P)
        assert len(rep.ok()) == P
        # measured host-crossing bytes == the closed form, EVERY rank
        per_rank = ts.torus_inter_bytes_per_rank(n, 4, d0, d1)
        assert set(rep.inter_bytes_sent.values()) == {per_rank}
        assert rep.max_rounds() == ts.torus_rounds(d0, d1)
        # strictly fewer TOTAL inter-host bytes than the flat ring...
        torus_total = sum(rep.inter_bytes_sent.values())
        flat_total = ts.flat_ring_inter_bytes_total(n, 4, P, d1)
        assert torus_total < flat_total
        # ...and a ~d0× cut at the busiest NIC (the flat ring's
        # boundary ranks each ship every chunk across DCN)
        flat_per_nic = flat_total // d1
        assert flat_per_nic >= (d0 - 1) * per_rank
        # results are right at scale, not just cheap
        np.testing.assert_allclose(
            np.asarray(rep.value(0)).ravel(), want, rtol=1e-5)
        np.testing.assert_array_equal(
            np.asarray(rep.value(0)), np.asarray(rep.value(P - 1)))

    def test_flat_ring_baseline_closed_form_is_measured(self):
        """flat_ring_inter_bytes_total is not a guess: the actual
        flat ring on the same fabric measures exactly it."""
        P, hosts = 64, 8
        procs = list(range(P))
        n = 8 * P
        data = {p: np.arange(n, dtype=np.float32) for p in procs}
        fleet = fs.FleetSim(P, hosts_per=P // hosts, seed=1)
        rep = fleet.run(lambda x, p: hs.allreduce_ring(
            x, procs, p, data[p], np.add, 0.0), label="ring")
        assert sum(rep.inter_bytes_sent.values()) \
            == ts.flat_ring_inter_bytes_total(n, 4, P, hosts)

    def test_torus_beats_flat_ring_makespan(self):
        """On the hierarchical fabric the torus's virtual makespan
        beats the flat ring's (the topo_torus_makespan_x bench line's
        law, pinned here at P=64)."""
        P = 64
        procs = list(range(P))
        n = 8 * P
        data = {p: np.arange(n, dtype=np.float32) * ((p % 5) + 1)
                for p in procs}

        def run(fn, label):
            fleet = fs.FleetSim(P, hosts_per=8, seed=1)
            host_of = fleet.fabric.host_of
            return fleet.run(
                lambda x, p: fn(x, p, host_of), label=label)

        rep_t = run(lambda x, p, h: ts.allreduce_torus2d(
            x, procs, p, data[p], np.add, 0.0, h), "torus")
        rep_r = run(lambda x, p, h: hs.allreduce_ring(
            x, procs, p, data[p], np.add, 0.0), "ring")
        assert rep_t.makespan < rep_r.makespan
        np.testing.assert_array_equal(
            np.asarray(rep_t.value(3)), np.asarray(rep_r.value(3)))

    def test_multiring_beats_single_ring_on_bandwidth_bound_wire(self):
        """k disjoint rings driven in parallel on a uniform
        bandwidth-bound fabric: the k× ring-bandwidth claim."""
        P = 32
        procs = list(range(P))
        n = 16 * P
        data = {p: np.arange(n, dtype=np.float32) for p in procs}

        def run(fn, label):
            fleet = fs.FleetSim(P, fabric=fs.Fabric(
                P, hosts_per=P, intra=fs.LinkSpec(1e-7, 0.1), seed=1))
            return fleet.run(fn, label=label)

        rep_m = run(lambda x, p: ts.allreduce_multiring(
            x, procs, p, data[p], np.add, 0.0, 4), "multiring")
        rep_r = run(lambda x, p: hs.allreduce_ring(
            x, procs, p, data[p], np.add, 0.0), "ring")
        assert rep_m.makespan < rep_r.makespan


# ---------------------------------------------------------------------------
# 4. selection: fixed constants, gating, forcing, rules
# ---------------------------------------------------------------------------

class TestTopoSelection:
    def test_fixed_decision_prefers_torus_on_a_grid(self):
        # large commutative allreduce on a uniform grid: torus2d
        assert hs.pick("allreduce", 64, 1 << 20,
                       topo=(8, 8)) == "torus2d"
        # no grid (flat/ragged/single-host): the flat decisions hold
        assert hs.pick("allreduce", 64, 1 << 20) == "rabenseifner"
        assert hs.pick("allreduce", 64, 1 << 20,
                       topo=(1, 64)) == "rabenseifner"
        # small messages keep the latency-optimal flat schedule
        assert hs.pick("allreduce", 64, 64,
                       topo=(8, 8)) == "recursive_doubling"
        # non-commutative ops never get an order-waiving schedule
        assert hs.pick("allreduce", 64, 1 << 20, topo=(8, 8),
                       commutative=False) == "recursive_doubling"
        assert hs.pick("bcast", 64, 1 << 20, topo=(8, 8)) == "torus2d"
        assert hs.pick("allgather", 64, 1 << 20,
                       topo=(8, 8)) == "torus2d"
        # small allgather stays bruck even on a grid
        assert hs.pick("allgather", 64, 1024, topo=(8, 8)) == "bruck"

    def test_operator_opt_out_restores_flat_decisions(self):
        mca_var.set_value("hier_topo_schedules", False)
        assert hs.pick("allreduce", 64, 1 << 20,
                       topo=(8, 8)) == "rabenseifner"
        assert hs.pick("bcast", 64, 1 << 20, topo=(8, 8)) == "binomial"
        assert hs.pick("allgather", 64, 1 << 20,
                       topo=(8, 8)) == "linear"

    def test_forcing_and_noncommutative_guard(self):
        mca_var.set_value("hier_inter_algorithm", "multiring")
        assert hs.pick("allreduce", 8, 64) == "multiring"
        # forcing an order-waiving schedule for a non-commutative op
        # is an ERROR, exactly like ring/rabenseifner
        with pytest.raises(MPIError):
            hs.pick("allreduce", 8, 64, commutative=False)
        mca_var.set_value("hier_inter_algorithm", "torus2d")
        assert hs.pick("allreduce", 8, 64) == "torus2d"
        with pytest.raises(MPIError):
            hs.pick("allreduce", 8, 64, has_identity=False)
        # bcast has a torus2d variant; reduce does not -> auto
        assert hs.pick("bcast", 8, 64) == "torus2d"
        assert hs.pick("reduce", 8, 64) == "binomial"

    def test_dynamic_rule_names_the_topo_variants(self, tmp_path):
        rules = tmp_path / "topo.conf"
        rules.write_text(textwrap.dedent("""
            hier_allreduce  0  0      multiring
            hier_allreduce  0  65536  torus2d
        """))
        mca_var.set_value("coll_tuned_use_dynamic_rules", True)
        mca_var.set_value("coll_tuned_dynamic_rules_filename",
                          str(rules))
        assert hs.pick("allreduce", 8, 100) == "multiring"
        assert hs.pick("allreduce", 8, 1 << 20) == "torus2d"
        # rules cannot waive MPI semantics: silent downgrade
        assert hs.pick("allreduce", 8, 1 << 20,
                       commutative=False) == "recursive_doubling"

    def test_order_waiving_covers_the_topo_family(self):
        assert "multiring" in hs.ORDER_WAIVING
        assert "torus2d" in hs.ORDER_WAIVING
        for alg in ts.TOPO_ALGS:
            assert alg in hs.ALGORITHMS["allreduce"]


# ---------------------------------------------------------------------------
# 5. fingerprints, the header stanza, legacy files pinned
# ---------------------------------------------------------------------------

class TestFingerprint:
    def test_canon_round_trip(self):
        fp = tdb.Fingerprint(hosts=8, procs_per_host=8,
                             link_classes=("shm", "dcn"), P=64)
        assert fp.canon() == "hosts=8;ppn=8;links=shm+dcn;P=64"
        assert tdb.Fingerprint.parse(fp.canon()) == fp
        assert tdb.Fingerprint.parse(tdb.LOCAL.canon()) == tdb.LOCAL

    def test_malformed_raises(self):
        for bad in ("hosts=8;ppn=8", "8/8/64", "",
                    "hosts=x;ppn=1;links=shm;P=4"):
            with pytest.raises(ValueError):
                tdb.Fingerprint.parse(bad)

    def test_fingerprint_for_layouts(self):
        fp = tdb.fingerprint_for({0: "a", 1: "a", 2: "b", 3: "b"}, 4)
        assert fp == tdb.Fingerprint(2, 2, ("shm", "dcn"), 4)
        # ragged: ppn pins to 0 so it never exact-matches uniform
        fp = tdb.fingerprint_for({0: "a", 1: "a", 2: "b"}, 3)
        assert fp.procs_per_host == 0 and fp.hosts == 2
        fp = tdb.fingerprint_for({0: "a", 1: "a"}, 2)
        assert fp.link_classes == ("shm",)

    def test_stamp_and_read_header(self, tmp_path):
        fp = tdb.Fingerprint(4, 2, ("shm", "dcn"), 8)
        text = tdb.stamp("hier_allreduce  0  0  ring\n", fp, version=3)
        p = tmp_path / "x.conf"
        p.write_text(text)
        got_fp, got_v = tdb.read_header(str(p))
        assert got_fp == fp and got_v == 3
        # re-stamping replaces, never duplicates
        text2 = tdb.stamp(text, tdb.LOCAL, version=1)
        assert text2.count("# fingerprint:") == 1

    def test_legacy_file_reads_none(self, tmp_path):
        p = tmp_path / "legacy.conf"
        p.write_text("allreduce  0  0  ring\n")
        assert tdb.read_header(str(p)) == (None, None)


class TestHeaderStanzaInRules:
    def test_stanza_is_parsed_not_skipped(self, tmp_path):
        p = tmp_path / "fp.conf"
        p.write_text("# fingerprint: hosts=2;ppn=4;links=shm+dcn;P=8\n"
                     "# version: 2\n"
                     "hier_allreduce  0  0  torus2d\n")
        rules, meta = dynamic_rules.load_rules_doc(str(p))
        assert meta["fingerprint"] == "hosts=2;ppn=4;links=shm+dcn;P=8"
        assert meta["version"] == 2
        assert rules["hier_allreduce"] == [(0, 0, "torus2d", None)]

    def test_malformed_stanza_fails_at_load_with_lineno(self, tmp_path):
        p = tmp_path / "bad.conf"
        p.write_text("# fingerprint: hosts=two;ppn=1;links=shm;P=4\n"
                     "allreduce  0  0  ring\n")
        with pytest.raises(MPIError) as ei:
            dynamic_rules.load_rules_doc(str(p))
        assert "bad.conf:1" in str(ei.value)

    def test_shipped_cpu8_rules_load_unchanged(self):
        """The satellite pin: tuning/cpu8_rules.conf (no stanza) keeps
        the exact legacy semantics — same tables through both entry
        points, no fingerprint, and the known entries intact."""
        path = os.path.join(REPO, "tuning", "cpu8_rules.conf")
        rules, meta = dynamic_rules.load_rules_doc(path)
        assert meta == {"fingerprint": None, "version": None}
        assert dynamic_rules.load_rules(path) == rules
        # exact legacy entries, pinned
        assert rules["allreduce"][0] == (0, 0, "nonoverlapping", None)
        assert (0, 65536, "segmented_ring", None) in rules["allreduce"]
        assert rules["tree_buckets"] == [(0, 0, "fused", 1048576)]
        assert (4, 65536, "rabenseifner", None) \
            in rules["hier_allreduce"]


# ---------------------------------------------------------------------------
# 6. the tuning database: register / version / select
# ---------------------------------------------------------------------------

FP_A = tdb.Fingerprint(8, 8, ("shm", "dcn"), 64)
FP_B = tdb.Fingerprint(16, 8, ("shm", "dcn"), 128)
FP_L = tdb.Fingerprint(1, 4, ("shm",), 4)


class TestTuningDb:
    def test_register_versions_and_never_overwrites(self, tmp_path):
        db = tdb.TuningDb(str(tmp_path))
        p1 = db.register("hier_allreduce  0  0  ring\n", FP_A)
        p2 = db.register("hier_allreduce  0  0  torus2d\n", FP_A)
        assert p1 != p2 and os.path.exists(p1) and os.path.exists(p2)
        assert tdb.read_header(p1)[1] == 1
        assert tdb.read_header(p2)[1] == 2
        # highest version wins the exact match
        assert db.best_match(FP_A) == p2
        assert dynamic_rules.load_rules(p2)["hier_allreduce"] \
            == [(0, 0, "torus2d", None)]

    def test_register_validates_through_the_real_loader(self, tmp_path):
        db = tdb.TuningDb(str(tmp_path))
        with pytest.raises(MPIError):
            db.register("hier_allreduce  0  0  no_such_alg\n", FP_A)
        # nothing published, not even a temp file
        assert [f for f in os.listdir(tmp_path)] == []

    def test_nearest_match_and_link_class_isolation(self, tmp_path):
        db = tdb.TuningDb(str(tmp_path))
        pa = db.register("hier_allreduce  0  0  torus2d\n", FP_A)
        db.register("allreduce  0  0  ring\n", FP_L)
        # no spanning entry for FP_B: the nearest same-link entry
        assert db.best_match(FP_B) == pa
        # ...but a local table must NEVER steer a spanning job and
        # vice versa
        assert db.best_match(
            tdb.Fingerprint(1, 2, ("shm",), 2)) is not None
        only_spanning = tdb.TuningDb(str(tmp_path / "sub"))
        only_spanning.register("hier_allreduce  0  0  ring\n", FP_A)
        assert only_spanning.best_match(tdb.LOCAL) is None

    def test_select_cache_invalidated_by_register(self, tmp_path):
        tdb.set_active(FP_A)
        db = tdb.TuningDb(str(tmp_path))
        p1 = db.register("hier_allreduce  0  0  ring\n", FP_A)
        assert tdb.select_rules_path(str(tmp_path), FP_A) == p1
        p2 = db.register("hier_allreduce  0  0  torus2d\n", FP_A)
        # register created a NEW file -> dir mtime moved -> re-resolve
        assert tdb.select_rules_path(str(tmp_path), FP_A) == p2


class TestDbAutoSelection:
    def test_db_serves_rules_when_no_file_is_pointed(self, tmp_path):
        tdb.set_active(FP_A)
        tdb.TuningDb(str(tmp_path)).register(
            "hier_allreduce  0  0  torus2d\n", FP_A)
        mca_var.set_value("coll_tuned_use_dynamic_rules", True)
        mca_var.set_value("coll_tuning_db_dir", str(tmp_path))
        assert dynamic_rules.lookup("hier_allreduce", 64, 1 << 20) \
            == "torus2d"
        src = dynamic_rules.rules_source()
        assert src["mode"] == "db"
        assert src["fingerprint"] == FP_A.canon()

    def test_explicit_file_outranks_the_db(self, tmp_path):
        tdb.set_active(FP_A)
        tdb.TuningDb(str(tmp_path / "db")).register(
            "hier_allreduce  0  0  torus2d\n", FP_A)
        pinned = tmp_path / "pinned.conf"
        pinned.write_text("hier_allreduce  0  0  ring\n")
        mca_var.set_value("coll_tuned_use_dynamic_rules", True)
        mca_var.set_value("coll_tuning_db_dir", str(tmp_path / "db"))
        mca_var.set_value("coll_tuned_dynamic_rules_filename",
                          str(pinned))
        assert dynamic_rules.lookup("hier_allreduce", 64, 1 << 20) \
            == "ring"
        assert dynamic_rules.rules_source()["mode"] == "file"

    def test_no_matching_entry_falls_to_fixed_constants(self, tmp_path):
        tdb.set_active(FP_A)  # spanning job, but the db only has LOCAL
        tdb.TuningDb(str(tmp_path)).register(
            "allreduce  0  0  ring\n", FP_L)
        mca_var.set_value("coll_tuned_use_dynamic_rules", True)
        mca_var.set_value("coll_tuning_db_dir", str(tmp_path))
        assert dynamic_rules.lookup("hier_allreduce", 64, 1 << 20) \
            is None
        assert dynamic_rules.rules_source()["mode"] == "off"
        # and pick() falls through to the fixed decision
        assert hs.pick("allreduce", 64, 1 << 20) == "rabenseifner"


# ---------------------------------------------------------------------------
# 7. online re-tuning
# ---------------------------------------------------------------------------

class TestOnlineRetuneDetector:
    def _cfg(self):
        mca_var.set_value("tune_online_window", 4)
        mca_var.set_value("tune_online_sustain", 2)
        mca_var.set_value("tune_online_slow_factor", 2.0)
        mca_var.set_value("tune_online_cooldown_s", 0.0)

    def test_sustained_slow_triggers_one_hiccup_does_not(self):
        self._cfg()
        clk = [0.0]
        rt = retune.OnlineRetuner(clock=lambda: clk[0])
        for _ in range(4):
            assert not rt.observe_rate(1, 100.0)
        # one hiccup: below threshold once, then recovery — no trigger
        assert not rt.observe_rate(1, 10.0)
        assert not rt.observe_rate(1, 100.0)
        # sustained: two consecutive slow ticks -> trigger
        assert not rt.observe_rate(1, 10.0)
        assert rt.observe_rate(1, 10.0)

    def test_cooldown_blocks_a_probe_storm(self):
        self._cfg()
        mca_var.set_value("tune_online_cooldown_s", 100.0)
        clk = [0.0]
        rt = retune.OnlineRetuner(clock=lambda: clk[0])
        for _ in range(4):
            rt.observe_rate(1, 100.0)
        rt.observe_rate(1, 10.0)
        assert rt.observe_rate(1, 10.0)
        rt._last_apply[1] = clk[0]  # an apply happened "now"
        rt.observe_rate(1, 10.0)
        assert not rt.observe_rate(1, 10.0)  # cooled down: suppressed
        clk[0] = 200.0  # cooldown expired; baseline recovers...
        for _ in range(4):
            assert not rt.observe_rate(1, 100.0)
        rt.observe_rate(1, 10.0)  # ...then the link goes slow again
        assert rt.observe_rate(1, 10.0)

    def test_observe_points_folds_bytes_over_seconds(self):
        self._cfg()
        rt = retune.OnlineRetuner()

        def tick(t, cid, mbps):
            return [{"name": "coll_bytes", "t": t, "cid": cid,
                     "v": mbps * 1e6},
                    {"name": "coll_seconds", "t": t, "cid": cid,
                     "v": 1.0}]

        pts = []
        for k in range(4):
            pts += tick(float(k), 7, 100.0)
        assert rt.observe_points(pts) == []
        pts = tick(4.0, 7, 10.0) + tick(5.0, 7, 10.0)
        assert rt.observe_points(pts) == [7]

    def test_maybe_start_is_gated(self):
        # tune_online off (default): nothing arms
        assert retune.maybe_start() is False
        assert retune.RETUNER is None

    def test_default_probe_mirrors_the_active_fingerprint(self):
        """A production arm (no injected probe) must still close the
        loop: the built-in probe mirrors the active fingerprint, and
        declines layouts the fleet model cannot mirror."""
        tdb.set_active(tdb.LOCAL)
        assert retune.default_probe(1) is None  # single process
        tdb.set_active(tdb.Fingerprint(2, 0, ("shm", "dcn"), 5))
        assert retune.default_probe(1) is None  # ragged: no mirror
        tdb.set_active(tdb.Fingerprint(4, 4, ("shm", "dcn"), 16))
        text = retune.default_probe(1)
        assert text and "hier_allreduce" in text
        assert "P=16, hosts_per=4" in text

    def test_widest_comm_owns_the_active_fingerprint(self):
        """Comm construction publishes with force=False: a narrower
        subcomm built after the world must NOT steer the process-
        global DB selection away from the world's rules."""
        world_fp = tdb.Fingerprint(16, 8, ("shm", "dcn"), 128)
        sub_fp = tdb.Fingerprint(2, 8, ("shm", "dcn"), 16)
        tdb.set_active(world_fp, force=False)
        tdb.set_active(sub_fp, force=False)  # the subcomm: ignored
        assert tdb.active() == world_fp
        tdb.set_active(sub_fp)  # force (tests/operator): replaces
        assert tdb.active() == sub_fp


class TestRetuneApply:
    def test_apply_registers_new_version_and_bumps_generation(
            self, tmp_path):
        """THE re-freeze contract, unit leg: the cvar write that
        applies a re-tuned rule moves VARS.generation, which is what
        every frozen PR 13 SchedulePlan is stamped with — the next
        fire re-plans."""
        tdb.set_active(FP_A)
        mca_var.set_value("coll_tuning_db_dir", str(tmp_path))
        rt = retune.OnlineRetuner()
        g0 = mca_var.VARS.generation
        path = rt.apply("hier_allreduce  0  0  ring\n", cid=5)
        assert mca_var.VARS.generation > g0
        assert mca_var.get("coll_tuned_dynamic_rules_filename", "") \
            == path
        assert mca_var.get("coll_tuned_use_dynamic_rules", False)
        assert tdb.read_header(path)[0] == FP_A
        assert rt.applied and rt.applied[-1]["cid"] == 5
        assert dynamic_rules.lookup("hier_allreduce", 64, 1 << 20) \
            == "ring"

    def test_apply_without_a_db_is_a_loud_error(self):
        with pytest.raises(ValueError):
            retune.OnlineRetuner().apply("hier_allreduce 0 0 ring\n")

    def test_slow_nic_straggler_ends_with_a_retuned_rule(
            self, tmp_path):
        """The seeded end-to-end scenario: a job running the torus
        schedule (the clean-fabric winner) sees a 10× slow NIC; the
        sustained-slow streak triggers the bounded fleet-sim
        micro-probe over a straggler mirror, and the re-tuned rule —
        the straggler flips the winner back to the flat ring, whose
        DCN edges avoid the sick NIC — registers as v2 and is
        selected at the next plan."""
        P, hosts_per = 64, 8
        fp = tdb.Fingerprint(P // hosts_per, hosts_per,
                             ("shm", "dcn"), P)
        tdb.set_active(fp)
        db = tdb.TuningDb(str(tmp_path))
        db.register("hier_allreduce  0  0  torus2d\n", fp)
        mca_var.set_value("coll_tuned_use_dynamic_rules", True)
        mca_var.set_value("coll_tuning_db_dir", str(tmp_path))
        assert dynamic_rules.lookup("hier_allreduce", P, 1 << 20) \
            == "torus2d"  # the baseline the fleet tuned in

        def straggler_fabric():
            f = fs.Fabric(P, hosts_per=hosts_per, seed=3)
            f.slow_nic(5, 10.0)
            return f

        # sanity: the clean-fabric probe keeps torus2d; only the
        # straggler flips it (the probe really reads the fabric)
        clean = retune.fleet_probe(P, hosts_per, n_elems=512, seed=3)
        assert clean.splitlines()[-1].split()[-1] == "torus2d"

        mca_var.set_value("tune_online_window", 4)
        mca_var.set_value("tune_online_sustain", 2)
        mca_var.set_value("tune_online_cooldown_s", 0.0)
        rt = retune.OnlineRetuner(
            probe=lambda cid: retune.fleet_probe(
                P, hosts_per, n_elems=512, seed=3,
                fabric_factory=straggler_fabric),
            clock=lambda: 0.0)
        cid = 3
        for _ in range(4):
            assert not rt.observe_rate(cid, 120.0)  # healthy baseline
        rt.observe_rate(cid, 11.0)      # the NIC went slow...
        assert rt.observe_rate(cid, 9.0)  # ...and stayed slow
        path = rt.retune(cid)
        assert path is not None and tdb.read_header(path)[1] == 2
        # the applied rule IS selected now — and it names the flat
        # ring, away from the straggler-poisoned torus column
        assert dynamic_rules.lookup("hier_allreduce", P, 1 << 20) \
            == "ring"
        src = dynamic_rules.rules_source()
        assert src["mode"] == "file" and src["path"] == path

    def test_fleet_probe_output_loads_and_is_bounded(self, tmp_path):
        text = retune.fleet_probe(16, 4, n_elems=256, seed=1)
        p = tmp_path / "probe.conf"
        p.write_text(text)
        rules = dynamic_rules.load_rules(str(p))
        assert len(rules["hier_allreduce"]) == 1
        alg = rules["hier_allreduce"][0][2]
        assert alg in ("ring", "multiring", "torus2d")

    def test_tick_hook_never_kills_the_sampler(self):
        from ompi_release_tpu.obs import sampler as _sampler

        mca_var.set_value("tune_online", True)
        rt = retune.OnlineRetuner()
        rt.tick()  # drains an empty ring: no points, no crash
        assert rt._cursor >= 0
        # a broken hook is swallowed by the sampler's dispatch loop
        _sampler.TICK_HOOKS.append(lambda: 1 / 0)
        try:
            for hook in tuple(_sampler.TICK_HOOKS):
                try:
                    hook()
                except Exception:
                    pass
        finally:
            del _sampler.TICK_HOOKS[-1]


# ---------------------------------------------------------------------------
# 8. the re-freeze, in-process: compiled plans re-capture after apply
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def world():
    import ompi_release_tpu as mpi

    return mpi.init()


def _hits():
    p = pvar.PVARS.lookup("coll_compiled_cache_hits")
    return p.read()


class TestPlanRefreeze:
    def test_retune_apply_refreezes_the_plan_at_the_next_fire(
            self, world, tmp_path):
        """Unit leg of the acceptance criterion: capture, hit, APPLY
        (the cvar write), then the next fire is a re-capture — never
        a stale frozen plan, never a mid-schedule switch."""
        x = np.ones((world.size, 16), np.float32)
        comm = world.dup(name="retune_refreeze")
        tdb.set_active(tdb.LOCAL)
        try:
            comm.allreduce(x)           # capture
            h0 = _hits()
            comm.allreduce(x)           # frozen-plan hit
            rt = retune.OnlineRetuner(db_dir=str(tmp_path))
            rt.apply("hier_allreduce  0  0  recursive_doubling\n",
                     cid=int(comm.cid))
            comm.allreduce(x)           # generation moved: re-capture
            comm.allreduce(x)           # ...and freezes again
            h1 = _hits()
            assert h1["count"] - h0["count"] == 3
            assert h1["sum"] - h0["sum"] == 2
        finally:
            comm.free()
            for v in ("coll_tuned_use_dynamic_rules",
                      "coll_tuned_dynamic_rules_filename"):
                mca_var.VARS.unset(v)


# ---------------------------------------------------------------------------
# 9. the re-freeze + topo schedules in a REAL job
# ---------------------------------------------------------------------------

APP_PRELUDE = textwrap.dedent("""
    import os, sys, tempfile
    sys.path.insert(0, %r)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    # fake 2x2 grid: procs {0,1} on one host, {2,3} on the other
    # (OMPITPU_NODE_ID is 1-based)
    nid = int(os.environ["OMPITPU_NODE_ID"])
    os.environ["OMPITPU_HOST_ID"] = "hostA" if nid <= 2 else "hostB"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import ompi_release_tpu as mpi
    from ompi_release_tpu.mca import pvar, var as mca_var
    from ompi_release_tpu.runtime.runtime import Runtime
    from ompi_release_tpu.tuning import retune as _retune

    def _pv(name):
        p = pvar.PVARS.lookup(name)
        return float(p.read()) if p is not None else 0.0

    def _agg(name):
        return pvar.PVARS.lookup(name).read()
""" % REPO)


def _run(tmp_path, capfd, body, n=4, timeout=240, mca=()):
    app = tmp_path / "app.py"
    app.write_text(APP_PRELUDE + textwrap.dedent(body))
    job = Job(n, [sys.executable, str(app)], list(mca),
              heartbeat_s=0.5, miss_limit=8)
    rc = job.run(timeout_s=timeout)
    out = capfd.readouterr()
    assert rc == 0, out.out + out.err
    assert job.job_state.visited(JobState.TERMINATED)
    return out.out


class TestRetuneJob:
    def test_torus_runs_then_retune_flips_it_at_the_next_fire(
            self, tmp_path, capfd):
        """A 4-process 2-host job: the fixed decision picks torus2d on
        the uniform grid (hier_topo_schedule_runs bumps, parity
        holds); a cvar-applied re-tune (the retuner's apply path, per
        process) flips the rule to ring — the NEXT fire re-plans and
        runs ring (no more topo runs), and parity still holds. The
        job-test leg of the acceptance criterion."""
        out = _run(tmp_path, capfd, """
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            me = rt.bootstrap["process_index"]
            n = world.size
            # past hier_small_message (64 KiB partials), so the fixed
            # decision leaves the small-message regime: torus2d on
            # the 2x2 grid
            x = np.stack([np.arange(32768, dtype=np.int32)
                          * (off + i + 1) for i in range(2)])
            want = sum(np.arange(32768, dtype=np.int32) * (r + 1)
                       for r in range(n))
            t0 = _pv("hier_topo_schedule_runs")
            got = np.asarray(world.allreduce(x))
            np.testing.assert_array_equal(got[0], want)
            d1 = _pv("hier_topo_schedule_runs") - t0
            assert d1 >= 1, d1   # the torus family actually engaged

            # cvar-applied re-tune on every process (the cvar plane
            # is per-process; same rule text everywhere keeps the
            # selection consistent across ranks)
            g0 = mca_var.VARS.generation
            td = tempfile.mkdtemp(prefix="tunedb-")
            _retune.OnlineRetuner(db_dir=td).apply(
                "hier_allreduce  0  0  ring\\n", cid=1)
            assert mca_var.VARS.generation > g0

            t1 = _pv("hier_topo_schedule_runs")
            got = np.asarray(world.allreduce(x))
            np.testing.assert_array_equal(got[0], want)
            # the re-tuned rule took effect AT THE NEXT FIRE: the
            # ring schedule ran, the torus family did not
            assert _pv("hier_topo_schedule_runs") == t1
            world.barrier()
            print(f"RETUNE-JOB-OK {me}")
            mpi.finalize()
        """)
        for me in range(4):
            assert f"RETUNE-JOB-OK {me}" in out
