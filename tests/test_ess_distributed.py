"""ess/distributed — the jax.distributed multi-controller bootstrap
(``orte/mca/ess/pmi`` analogue): two REAL OS processes form one jax
runtime through the coordination service, mpi.init() selects the
distributed ESS from the OMPITPU_* env contract, and collectives run
through the SPMD driver path (per-process local shards in, one
compiled program across controllers, local shards out).
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _cpu_multiprocess_gap() -> str:
    """Empty when this jax build can run cross-controller collectives
    on the CPU backend; otherwise the missing capability, named.

    The multi-controller bootstrap itself always works (it is our own
    coordination service), but the compiled collective needs the CPU
    client to be built WITH a cross-process collectives implementation
    (gloo/mpi) and jax to expose the config knob that selects it —
    without both, XLA raises "Multiprocess computations aren't
    implemented on the CPU backend" at the first collective."""
    try:
        import jax
    except Exception as e:  # pragma: no cover - jax is a hard dep
        return f"jax unavailable ({e})"
    if not hasattr(jax.config, "jax_cpu_collectives_implementation"):
        return ("jax %s has no jax_cpu_collectives_implementation "
                "option: the CPU backend cannot run multiprocess "
                "computations" % jax.__version__)
    try:
        from jaxlib import xla_extension as _xe
    except Exception as e:
        return f"jaxlib xla_extension unavailable ({e})"
    if not hasattr(_xe, "make_gloo_tcp_collectives"):
        return ("jaxlib built without gloo CPU collectives: the CPU "
                "backend cannot run multiprocess computations")
    return ""


_GAP = _cpu_multiprocess_gap()

#: evaluated at collection time: stock containers ship a jaxlib whose
#: CPU backend cannot run cross-controller collectives — skip with the
#: capability named instead of failing tier-1
pytestmark = pytest.mark.skipif(
    bool(_GAP), reason=f"ess/distributed needs multiprocess CPU "
                       f"collectives: {_GAP}")

WORKER = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, %r)
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    if hasattr(jax.config, "jax_cpu_collectives_implementation"):
        # the capability this test is gated on: cross-controller CPU
        # collectives need an explicit implementation selected
        jax.config.update("jax_cpu_collectives_implementation", "gloo")
    import numpy as np
    import ompi_release_tpu as mpi
    from ompi_release_tpu.runtime.runtime import Runtime
    from ompi_release_tpu.runtime import ess as ess_mod

    # the distributed ESS must be the selected component (env contract)
    sel = ess_mod.ESS_FRAMEWORK.select()
    assert sel.NAME == "distributed", sel.NAME

    world = mpi.init()
    rt = Runtime.current()
    pid = jax.process_index()
    assert rt.bootstrap["process_count"] == 2
    assert world.size == 8, world.size  # 2 controllers x 4 devices
    # endpoints carry each device's OWNING controller
    owners = sorted({e.process_index for e in rt.endpoints})
    assert owners == [0, 1], owners

    # SPMD collective path: this controller passes ITS 4 ranks' slices
    my_ranks = [e.rank for e in rt.endpoints if e.process_index == pid]
    x = np.stack([np.arange(8, dtype=np.int32) + r for r in my_ranks])
    out = world.allreduce(x)
    want = sum(np.arange(8, dtype=np.int32) + r for r in range(8))
    out = np.asarray(out)
    assert out.shape == (4, 8), out.shape
    for row in out:
        np.testing.assert_array_equal(row, want)

    # a second op on the same comm reuses the compiled program
    out2 = np.asarray(world.allreduce(2 * x))
    np.testing.assert_array_equal(out2[0], 2 * want)
    print(f"DIST-OK {pid}")
    mpi.finalize()
""" % REPO)


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_two_process_jax_distributed_bootstrap(tmp_path):
    app = tmp_path / "dist_worker.py"
    app.write_text(WORKER)
    port = _free_port()
    procs = []
    for pid in range(2):
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        env.update({
            "OMPITPU_COORDINATOR": f"127.0.0.1:{port}",
            "OMPITPU_PROCESS_ID": str(pid),
            "OMPITPU_NUM_PROCESSES": "2",
        })
        procs.append(subprocess.Popen(
            [sys.executable, str(app)], env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        ))
    outs = []
    for pid, p in enumerate(procs):
        try:
            out, err = p.communicate(timeout=150)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            raise
        assert p.returncode == 0, f"process {pid}:\n{err[-3000:]}"
        outs.append(out)
    assert "DIST-OK 0" in outs[0]
    assert "DIST-OK 1" in outs[1]
