"""Frozen RMA access plans (osc/plan): the one-sided analogue of the
compiled collective plans.

Layers:

1. Parity matrix: put/accumulate/get/get_accumulate across dtypes and
   every sync flavor (fence, passive lock, PSCW) — the planned close
   must be BITWISE identical to the interpreted close, because the
   fused program dispatches through the very same branch lambdas.
2. Steady state: a 10-epoch passive-target loop compiles exactly ONE
   fused program and replays it 9 times (``osc_plan_programs`` /
   ``osc_plan_cache_hits`` witnesses).
3. Lifecycle: a cvar write re-plans at the next close (generation
   witness), replay divergence drops the plan loudly and falls back
   interpreted, ``win.free()`` evicts every plan and template.
4. Wire frames: the frozen ``BatchTemplate`` renders bytes IDENTICAL
   to ``_pack_batch`` (pinned, round-tripped through
   ``_unpack_batch``), and packing is time-deterministic.
5. Hot-path cvar caching: steady-state closes and request timeouts hit
   the MCA registry ZERO times (the ``OscTuning`` snapshot + the
   generation-cached plan conf), and same-NAMED user ops can neither
   alias a predefined program locally nor ship over the wire.
6. One real 3-process job: the wire window's home-side epochs replay
   frozen plans with parity over the wire.
"""

import os
import sys
import textwrap

import numpy as np
import pytest

import jax.numpy as jnp

import ompi_release_tpu as mpi
from ompi_release_tpu import ops
from ompi_release_tpu.mca import pvar
from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.ops.op import Op
from ompi_release_tpu.osc import LOCK_EXCLUSIVE, win_allocate
from ompi_release_tpu.osc import plan as osc_plan
from ompi_release_tpu.osc.wire_win import (
    OscTuning, _pack_batch, _savez_bytes, _unpack_batch,
)
from ompi_release_tpu.osc.window import _PendingOp
from ompi_release_tpu.runtime.state import JobState
from ompi_release_tpu.tools.tpurun import Job
from ompi_release_tpu.utils.errors import MPIError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _pv(name):
    p = pvar.PVARS.lookup(name)
    assert p is not None, name
    return p.read()


@pytest.fixture(scope="module")
def world():
    yield mpi.init()


@pytest.fixture()
def win(world):
    w = win_allocate(world, (4,), jnp.float32)
    yield w
    if not w._freed:
        w.free()


def _interpreted(fn):
    """Run fn with access plans off (the interpreted twin)."""
    mca_var.set_value("osc_compiled", 0)
    try:
        return fn()
    finally:
        mca_var.VARS.unset("osc_compiled")


# ---------------------------------------------------------------------------
# 1. parity matrix
# ---------------------------------------------------------------------------


class TestPlannedParity:
    """Planned and interpreted closes share ``Window._branch_fn``
    lambdas, so parity is a structural identity being spot-checked —
    any mismatch means the fused unrolling diverged from the scan."""

    @pytest.mark.parametrize("dtype", [jnp.float32, jnp.int32])
    @pytest.mark.parametrize("sync", ["fence", "lock", "pscw"])
    def test_epoch_matrix_bitwise(self, world, dtype, sync):
        def epoch(w):
            pay = np.arange(4).astype(w.dtype)
            acc = np.full(4, 3, w.dtype)
            if sync == "fence":
                w.fence()
            elif sync == "lock":
                w.lock(1, LOCK_EXCLUSIVE)
            else:
                w.post(world.group)
                w.start(world.group)
            w.put(pay, target=1)
            w.accumulate(acc, target=1, op=ops.SUM)
            g = w.get(target=1)
            ga = w.get_accumulate(acc, target=1, op=ops.MAX)
            if sync == "fence":
                w.fence_end()
            elif sync == "lock":
                w.unlock(1)
            else:
                w.complete()
                w.wait()
            return (np.asarray(g.value), np.asarray(ga.value),
                    np.asarray(w.read()))

        def run(w):
            outs = [epoch(w) for _ in range(3)]  # capture + replays
            return outs

        wi = win_allocate(world, (4,), dtype)
        wc = win_allocate(world, (4,), dtype)
        try:
            want = _interpreted(lambda: run(wi))
            h0 = _pv("osc_plan_cache_hits")
            got = run(wc)
            h1 = _pv("osc_plan_cache_hits")
            for (gg, gga, gdata), (wg, wga, wdata) in zip(got, want):
                np.testing.assert_array_equal(gg, wg)
                np.testing.assert_array_equal(gga, wga)
                np.testing.assert_array_equal(gdata, wdata)
            # epoch 1 captures (observe 0), 2..3 replay (observe 1)
            assert h1["sum"] - h0["sum"] == 2, (h0, h1)
        finally:
            wi.free()
            wc.free()

    def test_indexed_cas_and_fetch_parity(self, world):
        def run(w):
            w.fence()
            w.put(np.arange(6, dtype=np.float32), target=1)
            w.fence_end()
            w.lock(1, LOCK_EXCLUSIVE)
            old = w.compare_and_swap(
                np.float32(99.0), np.float32(3.0), target=1, index=3)
            fetched = w.fetch_and_op(
                np.float32(10.0), target=1, op=ops.SUM, index=0)
            w.unlock(1)
            return (np.asarray(old.value), np.asarray(fetched.value),
                    np.asarray(w.read()))

        wi = win_allocate(world, (6,), jnp.float32)
        wc = win_allocate(world, (6,), jnp.float32)
        try:
            want = _interpreted(lambda: run(wi))
            got = run(wc)   # capture
            got2 = run(wc)  # replay fires the fused program
            want2 = _interpreted(lambda: run(wi))
            for g, w_ in zip(got + got2, want + want2):
                np.testing.assert_array_equal(g, w_)
        finally:
            wi.free()
            wc.free()


# ---------------------------------------------------------------------------
# 2. steady state: exactly one compile
# ---------------------------------------------------------------------------


class TestSteadyState:
    def test_ten_epochs_one_program(self, world):
        w = win_allocate(world, (4,), jnp.float32)
        try:
            mca_var.set_value("osc_plan_max_ops", 128)  # pin generation
            try:
                pay = np.full(4, 2.0, np.float32)
                h0 = _pv("osc_plan_cache_hits")
                p0 = _pv("osc_plan_programs")
                f0 = _pv("osc_plans_frozen")
                for _ in range(10):
                    w.lock(1, LOCK_EXCLUSIVE)
                    w.put(pay, target=1)
                    w.accumulate(pay, target=1, op=ops.SUM)
                    w.unlock(1)
                h1 = _pv("osc_plan_cache_hits")
                assert h1["count"] - h0["count"] == 10, (h0, h1)
                assert h1["sum"] - h0["sum"] == 9, (h0, h1)
                # exactly ONE plan frozen, ONE fused program compiled
                # (at the first replay), across all ten closes
                assert _pv("osc_plans_frozen") - f0 == 1
                assert _pv("osc_plan_programs") - p0 == 1
                assert len(w._access_plans) == 1
            finally:
                mca_var.VARS.unset("osc_plan_max_ops")
        finally:
            w.free()

    def test_orchestration_timer_feeds_both_paths(self, world, win):
        def one(w):
            w.fence()
            w.put(np.ones(4, np.float32), target=0)
            w.fence_end()

        o0 = _pv("osc_orchestration_seconds")
        _interpreted(lambda: one(win))
        o1 = _pv("osc_orchestration_seconds")
        assert o1 > o0  # interpreted close reported its span
        one(win)  # capture
        one(win)  # replay
        assert _pv("osc_orchestration_seconds") > o1

    def test_oversized_epoch_stays_interpreted(self, world, win):
        mca_var.set_value("osc_plan_max_ops", 2)
        try:
            h0 = _pv("osc_plan_cache_hits")
            win.fence()
            for _ in range(3):
                win.put(np.ones(4, np.float32), target=1)
            win.fence_end()
            h1 = _pv("osc_plan_cache_hits")
            assert h1["count"] == h0["count"]  # not even counted
            assert not win._access_plans
        finally:
            mca_var.VARS.unset("osc_plan_max_ops")


# ---------------------------------------------------------------------------
# 3. lifecycle: generation, divergence, eviction
# ---------------------------------------------------------------------------


class TestPlanLifecycle:
    def _one(self, w):
        w.lock(1, LOCK_EXCLUSIVE)
        w.put(np.full(4, 5.0, np.float32), target=1)
        w.unlock(1)

    def test_cvar_write_replans(self, world, win):
        self._one(win)  # capture + freeze
        self._one(win)  # replay
        (sig, old_plan), = win._access_plans.items()
        # ANY cvar write bumps the registry generation: the frozen
        # plan is stale at the next close
        mca_var.set_value("wire_pipeline_depth", 6)
        try:
            h0 = _pv("osc_plan_cache_hits")
            self._one(win)  # re-capture under the new generation
            h1 = _pv("osc_plan_cache_hits")
            assert h1["count"] - h0["count"] == 1
            assert h1["sum"] - h0["sum"] == 0  # a capture, not a hit
            new_plan = win._access_plans[sig]
            assert new_plan is not old_plan
            assert new_plan.gen > old_plan.gen
            self._one(win)  # and replays resume
            h2 = _pv("osc_plan_cache_hits")
            assert h2["sum"] - h1["sum"] == 1
        finally:
            mca_var.VARS.unset("wire_pipeline_depth")

    def test_divergence_drops_plan_loudly(self, world, win):
        self._one(win)
        self._one(win)  # replay: plan is live with a built program
        (sig, plan), = win._access_plans.items()
        assert plan.prog is not None

        def boom(*a, **k):
            raise RuntimeError("synthetic divergence")

        plan.prog = boom
        self._one(win)  # must fall back interpreted, not raise
        np.testing.assert_array_equal(
            np.asarray(win.read())[1], np.full(4, 5.0))
        # the diverged plan was dropped; the NEXT close re-records a
        # fresh one and replays resume after it
        assert sig not in win._access_plans
        self._one(win)
        fresh = win._access_plans[sig]
        assert fresh is not plan
        h0 = _pv("osc_plan_cache_hits")
        self._one(win)
        assert _pv("osc_plan_cache_hits")["sum"] - h0["sum"] == 1

    def test_window_free_evicts_plans(self, world):
        w = win_allocate(world, (4,), jnp.float32)
        self._one(w)
        assert w._access_plans
        w.free()
        assert not w._access_plans
        assert not w._batch_templates

    def test_unplannable_user_op_without_hash_is_skipped(self, world,
                                                         win):
        # Op is a frozen dataclass (hashable) — unplannability comes
        # from unhashable payload descriptors; simulate with a raw
        # pending op carrying a list payload
        p = _PendingOp("put", 0, data=[1.0, "x"], op=ops.REPLACE)
        assert osc_plan.epoch_signature([p]) is None


# ---------------------------------------------------------------------------
# 4. wire frames: byte-identical templates
# ---------------------------------------------------------------------------


def _wire_todo():
    from ompi_release_tpu.request.request import Request

    return [
        _PendingOp("put", 1, data=jnp.arange(4, dtype=jnp.float32),
                   op=ops.REPLACE),
        _PendingOp("acc", 2, data=jnp.full((4,), 2.0, jnp.float32),
                   op=ops.SUM),
        _PendingOp("get", 1, request=Request()),
        _PendingOp("cas", 0, data=jnp.float32(9.0),
                   compare=jnp.float32(1.0), request=Request(),
                   index=2),
        _PendingOp("put", 3, data=jnp.float32(7.0), op=ops.REPLACE,
                   index=1),
    ]


class TestFrameTemplates:
    def test_template_bytes_identical_to_pack_batch(self):
        todo = _wire_todo()
        want = _pack_batch(todo)
        tpl = osc_plan.BatchTemplate(mca_var.VARS.generation, todo)
        got = tpl.render(todo)
        assert got.tobytes() == want.tobytes()  # BYTE-identical

    def test_pack_batch_is_time_deterministic(self):
        # np.savez stamps member mtimes; _savez_bytes pins the DOS
        # epoch so two packs of the same ops are identical bytes
        todo = _wire_todo()
        a = _pack_batch(todo)
        b = _pack_batch(todo)
        assert a.tobytes() == b.tobytes()

    def test_template_round_trips_through_unpack(self):
        todo = _wire_todo()
        tpl = osc_plan.BatchTemplate(mca_var.VARS.generation, todo)
        back = _unpack_batch(tpl.render(todo))
        assert [(p.kind, p.target) for p in back] == \
               [(p.kind, p.target) for p in todo]
        for p, q in zip(back, todo):
            assert (p.op.name if p.op else "") == \
                   (q.op.name if q.op else "")
            assert p.index == q.index
            assert (p.request is not None) == (q.request is not None)
            if q.data is not None:
                np.testing.assert_array_equal(
                    np.asarray(p.data), np.asarray(q.data))
            if q.compare is not None:
                np.testing.assert_array_equal(
                    np.asarray(p.compare), np.asarray(q.compare))

    def test_savez_bytes_loads_like_savez(self):
        arrays = {"a": np.arange(5, dtype=np.int32),
                  "b": np.ones((2, 3), np.float64)}
        import io

        z = np.load(io.BytesIO(_savez_bytes(arrays)),
                    allow_pickle=False)
        np.testing.assert_array_equal(z["a"], arrays["a"])
        np.testing.assert_array_equal(z["b"], arrays["b"])


# ---------------------------------------------------------------------------
# 5. hot-path cvar caching + op identity
# ---------------------------------------------------------------------------


class TestHotPathCvars:
    def test_steady_closes_hit_registry_zero_times(self, world, win,
                                                   monkeypatch):
        pay = np.full(4, 1.0, np.float32)

        def one():
            win.lock(1, LOCK_EXCLUSIVE)
            win.put(pay, target=1)
            win.unlock(1)

        for _ in range(3):
            one()  # warm: conf cached, plan frozen + replaying
        calls = []
        real_get = mca_var.get
        monkeypatch.setattr(
            mca_var, "get",
            lambda *a, **k: calls.append(a) or real_get(*a, **k))
        for _ in range(5):
            one()
        assert calls == [], (
            "steady-state RMA closes must not touch the MCA registry; "
            f"saw {calls}")

    def test_osc_tuning_snapshot_honors_wire_timeout(self):
        mca_var.set_value("osc_request_timeout_ms", 5000)
        mca_var.set_value("wire_coll_timeout_ms", 9000)
        try:
            t = OscTuning()
            # the RMA wait bound must not undercut an operator-raised
            # collective bound: max() of the two
            assert t.request_timeout_ms == 9000
            assert t.gen == mca_var.VARS.generation
        finally:
            mca_var.VARS.unset("osc_request_timeout_ms")
            mca_var.VARS.unset("wire_coll_timeout_ms")
        mca_var.set_value("osc_request_timeout_ms", 200_000)
        try:
            # above the wire default (60 s): the RMA bound wins
            assert OscTuning().request_timeout_ms == 200_000
        finally:
            mca_var.VARS.unset("osc_request_timeout_ms")

    def test_same_named_user_op_gets_its_own_plan(self, world):
        """Op keying is by OBJECT, not name: a user op named "sum"
        must neither reuse SUM's frozen program locally nor ship over
        the wire as if it were SUM."""
        clobber = Op("sum", lambda a, b: a * 0 + 99.0,
                     commutative=True)
        w = win_allocate(world, (4,), jnp.float32)
        try:
            def run(op):
                w.fence()
                w.accumulate(np.full(4, 2.0, np.float32), target=1,
                             op=op)
                w.fence_end()
                return np.asarray(w.read())[1]

            run(ops.SUM); run(ops.SUM)  # freeze + replay SUM's plan
            np.testing.assert_array_equal(run(clobber),
                                          np.full(4, 99.0))
            sigs = list(w._access_plans)
            assert len(sigs) == 2, "same-named op aliased SUM's plan"
            # and back: SUM still replays ITS program, not clobber's
            np.testing.assert_array_equal(
                run(ops.SUM), np.full(4, 101.0))
        finally:
            w.free()

    def test_same_named_user_op_refused_on_the_wire(self):
        clobber = Op("sum", lambda a, b: a * 0 + 99.0,
                     commutative=True)
        todo = [_PendingOp("acc", 0,
                           data=jnp.ones((4,), jnp.float32),
                           op=clobber)]
        with pytest.raises(MPIError):
            _pack_batch(todo)

    def test_cache_stats_shape(self):
        st = osc_plan.cache_stats()
        assert set(st) == {"epoch_plans", "batch_templates",
                           "programs", "fires", "hits"}


# ---------------------------------------------------------------------------
# 6. the real 3-process job
# ---------------------------------------------------------------------------


APP_PRELUDE = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, %r)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=2"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import ompi_release_tpu as mpi
    import ompi_release_tpu.osc.plan  # register the plan pvars NOW
    from ompi_release_tpu.mca import pvar, var as mca_var

    def _pv(name):
        p = pvar.PVARS.lookup(name)
        return p.read() if p is not None else None
""" % REPO)


class TestOscPlanJob:
    def test_wire_window_replays_plans_with_parity(self, tmp_path,
                                                   capfd):
        """3-process world: every rank hammers the same lock epoch on
        a spanning window. The home side's repeated batch epochs
        freeze access plans and replay them; results stay bitwise
        equal to the first (interpreted, capturing) epoch and the
        plan pvars witness replays on at least the home rank."""
        app = tmp_path / "app.py"
        app.write_text(APP_PRELUDE + textwrap.dedent("""
            import jax.numpy as jnp
            from ompi_release_tpu import ops
            from ompi_release_tpu.osc import LOCK_EXCLUSIVE
            from ompi_release_tpu.osc.window import win_allocate
            from ompi_release_tpu.runtime.runtime import Runtime

            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            nloc = len(world.local_comm_ranks)
            # every process hammers a REMOTE rank owned by the next
            # process — each home applies one origin's repeated batch
            tgt = (off + nloc) % world.size
            pay = np.full(4, float(off + 1), np.float32)

            def one():
                w.lock(tgt, LOCK_EXCLUSIVE)
                w.put(pay, target=tgt)
                w.accumulate(pay, target=tgt, op=ops.SUM)
                g = w.get(target=tgt)
                w.unlock(tgt)
                return np.asarray(g.value)

            w = win_allocate(world, (4,), jnp.float32)
            first = one()  # capture on the home side
            np.testing.assert_array_equal(first, pay * 2)
            for _ in range(6):
                np.testing.assert_array_equal(one(), first)  # BITWISE
            world.barrier()
            # my row `off` was written by the PREVIOUS process
            prev = (off - nloc) % world.size
            np.testing.assert_array_equal(
                np.asarray(w.read())[0],
                np.full(4, (prev + 1) * 2.0, np.float32))
            st = _pv("osc_plan_cache_hits")
            # spanning allreduce: one slice per LOCAL member; member 0
            # carries this process's plan-replay count
            buf = np.zeros((nloc, 1), np.float32)
            buf[0, 0] = st["sum"] if st else 0
            fires = float(np.asarray(world.allreduce(buf))[0, 0])
            assert fires >= 6, (fires, st)
            w.free()
            print("OSC-PLAN-JOB-OK", flush=True)
            mpi.finalize()
        """))
        job = Job(3, [sys.executable, str(app)], [],
                  heartbeat_s=0.5, miss_limit=8)
        rc = job.run(timeout_s=240)
        out = capfd.readouterr()
        assert rc == 0, out.out + out.err
        assert job.job_state.visited(JobState.TERMINATED)
        assert out.out.count("OSC-PLAN-JOB-OK") == 3
