"""Unified multi-controller COMM_WORLD — the reference's core runtime
promise (``ompi_mpi_init.c:759-786``: add_procs over ALL peers; any
rank reaches any rank through one API, ``btl_tcp_component.c:883``).

Real system tests: ``tpurun -n 2`` jobs where each worker process is
forced to 4 virtual CPU devices, so COMM_WORLD spans 8 ranks across 2
OS processes. Collectives parity-check against numpy on the SAME
values a single-controller world would reduce, and p2p crosses the
process boundary through the public ``comm.send``/``comm.recv`` API
(the wire pml routing through the shm handoff under the hood — both
workers share this host).
"""

import os
import sys
import textwrap

import numpy as np
import pytest

from ompi_release_tpu.runtime.state import JobState, ProcState
from ompi_release_tpu.tools.tpurun import Job

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# NOTE: XLA_FLAGS must land before the first jax import in the WORKER
# process (the prelude runs first in the launched script)
APP_PRELUDE = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, %r)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=4"
    ).strip()
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import ompi_release_tpu as mpi
    from ompi_release_tpu.runtime.runtime import Runtime
""" % REPO)


def _write_app(tmp_path, body, name="app.py"):
    p = tmp_path / name
    p.write_text(APP_PRELUDE + textwrap.dedent(body))
    return str(p)


def _run(tmp_path, capfd, body, n=2, timeout=180):
    app = _write_app(tmp_path, body)
    job = Job(n, [sys.executable, app], [], heartbeat_s=0.5,
              miss_limit=8)
    rc = job.run(timeout_s=timeout)
    out = capfd.readouterr()
    assert rc == 0, out.out + out.err
    assert job.job_state.visited(JobState.TERMINATED)
    return out.out


class TestUnifiedWorld:
    def test_world_spans_processes_with_allreduce_parity(self, tmp_path,
                                                         capfd):
        """2 processes x 4 devices = ONE 8-rank world; allreduce over
        deterministic per-rank values must equal the numpy total a
        single-controller 8-rank world would produce — bitwise for
        int32."""
        out = _run(tmp_path, capfd, """
            world = mpi.init()
            rt = Runtime.current()
            assert world.size == 8, world.size
            assert rt.local_size == 4
            off = rt.local_rank_offset
            # int32: parity must be exact
            vals = np.stack([
                np.arange(16, dtype=np.int32) * (off + i + 1)
                for i in range(4)
            ])
            got = np.asarray(world.allreduce(vals))
            want = sum(np.arange(16, dtype=np.int32) * (r + 1)
                       for r in range(8))
            assert got.shape == (4, 16), got.shape
            for i in range(4):
                np.testing.assert_array_equal(got[i], want)
            # f32 parity within tolerance (fixed combine order)
            fv = np.stack([np.full(8, 0.1, np.float32) * (off + i)
                           for i in range(4)])
            fgot = np.asarray(world.allreduce(fv))
            fwant = sum(np.full(8, 0.1, np.float32) * r for r in range(8))
            np.testing.assert_allclose(fgot[0], fwant, rtol=1e-5)
            print(f"ALLREDUCE-OK {off}")
            mpi.finalize()
        """)
        assert "ALLREDUCE-OK 0" in out and "ALLREDUCE-OK 4" in out

    def test_cross_process_send_recv_public_api(self, tmp_path, capfd):
        """comm.send from a rank in process 0 to a rank in process 1
        (and back) through the PUBLIC API — the wire pml routes it
        over the shm handoff with no caller-visible difference."""
        out = _run(tmp_path, capfd, """
            world = mpi.init()
            rt = Runtime.current()
            if rt.local_rank_offset == 0:
                # rank 1 (process 0) -> rank 5 (process 1), tag 7
                world.send(np.arange(32, dtype=np.float32) * 2, 5,
                           tag=7, rank=1)
                # and receive the reply at rank 2 from rank 6
                val, st = world.recv(source=6, tag=9, rank=2)
                assert st.source == 6 and st.tag == 9
                np.testing.assert_array_equal(
                    np.asarray(val), np.full(5, 3, np.int32))
                print("P0-OK")
            else:
                val, st = world.recv(source=1, tag=7, rank=5)
                assert st.source == 1 and st.tag == 7
                np.testing.assert_array_equal(
                    np.asarray(val), np.arange(32, dtype=np.float32) * 2)
                world.send(np.full(5, 3, np.int32), 2, tag=9, rank=6)
                print("P1-OK")
            world.barrier()
            mpi.finalize()
        """)
        assert "P0-OK" in out and "P1-OK" in out

    def test_wildcards_and_probe_across_processes(self, tmp_path, capfd):
        """ANY_SOURCE/ANY_TAG recvs and iprobe see wire arrivals."""
        out = _run(tmp_path, capfd, """
            world = mpi.init()
            rt = Runtime.current()
            if rt.local_rank_offset == 0:
                world.send(np.int32([11]), 4, tag=3, rank=0)
                world.barrier()
            else:
                import time
                st = None
                for _ in range(100):
                    st = world.iprobe(rank=4)  # ANY_SOURCE, ANY_TAG
                    if st is not None:
                        break
                    time.sleep(0.05)
                assert st is not None and st.source == 0 and st.tag == 3
                val, st2 = world.recv(rank=4)  # wildcards
                assert int(np.asarray(val)[0]) == 11
                assert st2.source == 0 and st2.tag == 3
                print("WILDCARD-OK")
                world.barrier()
            mpi.finalize()
        """)
        assert "WILDCARD-OK" in out

    def test_ssend_completes_on_remote_match(self, tmp_path, capfd):
        """Cross-process ssend: the send request completes only after
        the remote recv matches (ack over the wire)."""
        out = _run(tmp_path, capfd, """
            world = mpi.init()
            rt = Runtime.current()
            if rt.local_rank_offset == 0:
                req = world.isend(np.float32([1, 2]), 6, tag=5, rank=3,
                                  sync=True)
                done, _ = req.test()
                # receiver sleeps 0.5s before posting: almost surely
                # not yet matched (don't assert: timing)
                st = req.wait()
                print("SSEND-DONE")
            else:
                import time
                time.sleep(0.5)
                val, st = world.recv(source=3, tag=5, rank=6)
                np.testing.assert_array_equal(np.asarray(val),
                                              np.float32([1, 2]))
                print("SSEND-RECVD")
            world.barrier()
            mpi.finalize()
        """)
        assert "SSEND-DONE" in out and "SSEND-RECVD" in out

    def test_hier_collectives_parity(self, tmp_path, capfd):
        """bcast/reduce/allgather/alltoall/reduce_scatter_block/scan
        across the 8-rank 2-process world, parity vs numpy."""
        out = _run(tmp_path, capfd, """
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            n = world.size
            # every rank's slice, deterministic
            full = np.stack([np.arange(8, dtype=np.int32) + 10 * r
                             for r in range(n)])
            mine = full[off:off + 4]

            # bcast from a REMOTE root for one of the processes
            got = np.asarray(world.bcast(mine, root=5))
            for i in range(4):
                np.testing.assert_array_equal(got[i], full[5])

            # rooted reduce to rank 2 (process 0)
            red = np.asarray(world.reduce(mine, root=2))
            want_sum = full.sum(0)
            if off == 0:
                np.testing.assert_array_equal(red[2], want_sum)
                assert (np.asarray(red[[0, 1, 3]]) == 0).all()
            else:
                assert (red == 0).all()

            # allgather
            ag = np.asarray(world.allgather(mine))
            np.testing.assert_array_equal(ag[1], full.reshape(-1))

            # alltoall: rank i's chunk j = i*100 + j
            a2a_in = np.stack([
                np.asarray([ (off+i)*100 + j for j in range(n)],
                           dtype=np.int32)
                for i in range(4)])
            a2a = np.asarray(world.alltoall(a2a_in))
            for i in range(4):
                want = np.asarray([s*100 + (off+i) for s in range(n)],
                                  dtype=np.int32)
                np.testing.assert_array_equal(a2a[i], want)

            # reduce_scatter_block: 8 chunks of 2
            rs_in = np.stack([np.arange(16, dtype=np.int32) + r
                              for r in range(n)])[off:off+4]
            rs = np.asarray(world.reduce_scatter_block(rs_in))
            tot = np.stack([np.arange(16, dtype=np.int32) + r
                            for r in range(n)]).sum(0)
            for i in range(4):
                np.testing.assert_array_equal(rs[i],
                                              tot[(off+i)*2:(off+i)*2+2])

            # scan (inclusive): prefix sums in rank order
            sc = np.asarray(world.scan(mine))
            for i in range(4):
                np.testing.assert_array_equal(sc[i],
                                              full[:off+i+1].sum(0))

            # pair-op rooted reduce + reduce_scatter_block across the
            # boundary
            from ompi_release_tpu import ops as _ops
            apv = np.asarray([3., 1., 7., 2., 9., 0., 7., 4.],
                             np.float32).reshape(n, 1)
            api = np.arange(n, dtype=np.int32).reshape(n, 1)
            rv, ri = world.reduce(
                (apv[off:off+4], api[off:off+4]), _ops.MAXLOC, root=6)
            if off == 4:
                assert float(np.asarray(rv)[6 - 4, 0]) == 9.0
                assert int(np.asarray(ri)[6 - 4, 0]) == 4
            bv = np.stack([np.roll(np.arange(n, dtype=np.float32), r)
                           for r in range(n)])
            bi = np.tile(np.arange(n, dtype=np.int32).reshape(n, 1),
                         (1, n))
            cv, ci = world.reduce_scatter_block(
                (bv[off:off+4], bi[off:off+4]), _ops.MINLOC)
            for i in range(4):
                col = bv[:, off + i]
                k = int(np.argmin(col))
                assert float(np.asarray(cv)[i, 0]) == float(col[k])
                assert int(np.asarray(ci)[i, 0]) == k

            # pair-op scan (MAXLOC) across the process boundary
            pv = np.asarray([3., 1., 7., 2., 9., 0., 7., 4.],
                            np.float32).reshape(n, 1)
            pi = np.arange(n, dtype=np.int32).reshape(n, 1)
            sv, si = world.scan(
                (pv[off:off+4], pi[off:off+4]), _ops.MAXLOC)
            best, bi = -np.inf, 0
            want_v, want_i = [], []
            for k, v in enumerate(pv.ravel()):
                if v > best:
                    best, bi = v, k
                want_v.append(best)
                want_i.append(bi)
            np.testing.assert_array_equal(
                np.asarray(sv).ravel(), want_v[off:off+4])
            np.testing.assert_array_equal(
                np.asarray(si).ravel(), want_i[off:off+4])

            world.barrier()
            print(f"HIER-OK {off}")
            mpi.finalize()
        """)
        assert "HIER-OK 0" in out and "HIER-OK 4" in out

    def test_hier_vector_collectives_parity(self, tmp_path, capfd):
        """The five v-variant collectives across the 8-rank 2-process
        world: ragged buffers, zero counts included, parity vs the
        global numpy picture (the round-4 ERR_NOT_AVAILABLE gap)."""
        out = _run(tmp_path, capfd, """
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            n = world.size
            # ragged: rank r holds r+1 elements valued 100*r + k
            full = [np.asarray([100 * r + k for k in range(r + 1)],
                               np.int32) for r in range(n)]
            mine = full[off:off + 4]

            ag = np.asarray(world.allgatherv(mine))
            np.testing.assert_array_equal(ag, np.concatenate(full))

            gv = world.gatherv(mine, root=5)
            if off == 4:
                np.testing.assert_array_equal(np.asarray(gv),
                                              np.concatenate(full))
            else:
                assert gv is None

            counts = [r + 1 for r in range(n)]
            sendbuf = np.concatenate(full) if off == 0 else None
            sv = world.scatterv(sendbuf, counts, root=2)
            assert len(sv) == 4
            for i in range(4):
                np.testing.assert_array_equal(np.asarray(sv[i]),
                                              full[off + i])

            # alltoallv count matrix with zeros: c[i][j] = (i+j) % 3
            c = np.asarray([[(i + j) % 3 for j in range(n)]
                            for i in range(n)], np.int64)
            sb = [np.concatenate([np.full(c[i, j], 10 * i + j, np.int32)
                                  for j in range(n)])
                  for i in range(off, off + 4)]
            rv = world.alltoallv(sb, c)
            for pos, j in enumerate(range(off, off + 4)):
                want = np.concatenate([np.full(c[i, j], 10 * i + j,
                                               np.int32)
                                       for i in range(n)])
                np.testing.assert_array_equal(np.asarray(rv[pos]), want)

            # general reduce_scatter, uneven counts
            rc = [r + 1 for r in range(n)]
            tot = sum(rc)
            x = np.stack([np.arange(tot, dtype=np.int32) * (off + i + 1)
                          for i in range(4)])
            rs = world.reduce_scatter(x, rc)
            wantfull = sum(np.arange(tot, dtype=np.int32) * (r + 1)
                           for r in range(n))
            offs = np.concatenate([[0], np.cumsum(rc)])
            for i in range(4):
                r = off + i
                np.testing.assert_array_equal(
                    np.asarray(rs[i]), wantfull[offs[r]:offs[r] + rc[r]])

            # pair-op (MINLOC) general reduce_scatter
            from ompi_release_tpu import ops as _o
            pv = np.stack([
                np.roll(np.arange(tot, dtype=np.float32), off + i)
                for i in range(4)])
            pidx = np.full((4, tot), off, np.int32) \
                + np.arange(4, dtype=np.int32)[:, None]
            prs = world.reduce_scatter((pv, pidx), rc, _o.MINLOC)
            fullv = np.stack([np.roll(np.arange(tot, dtype=np.float32),
                                      r) for r in range(n)])
            for i in range(4):
                r = off + i
                seg = slice(offs[r], offs[r] + rc[r])
                vwant = fullv[:, seg].min(axis=0)
                iwant = fullv[:, seg].argmin(axis=0)
                np.testing.assert_array_equal(
                    np.asarray(prs[i][0]), vwant)
                np.testing.assert_array_equal(
                    np.asarray(prs[i][1]), iwant)

            world.barrier()
            print(f"VCOLL-OK {off}")
            mpi.finalize()
        """)
        assert "VCOLL-OK 0" in out and "VCOLL-OK 4" in out

    def test_dropless_moe_on_spanning_world(self, tmp_path, capfd):
        """The flagship dropless-MoE routing step (parallel/ep.py) on
        the unified multi-controller world: alltoallv-driven token
        routing with exact per-token parity — the round-4 blocker
        ('the flagship MoE cannot run on a unified world')."""
        out = _run(tmp_path, capfd, """
            from ompi_release_tpu.parallel.ep import dropless_moe
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            n = world.size
            n_experts = 16
            d = 4
            rng = np.random.RandomState(0)  # same stream everywhere
            all_tokens = [rng.randn(3 + r, d).astype(np.float32)
                          for r in range(n)]
            all_assign = [rng.randint(0, n_experts, size=(3 + r,))
                          for r in range(n)]

            def expert_fn(e, x):
                return x * (e + 1)

            outs = dropless_moe(world, all_tokens[off:off + 4],
                                all_assign[off:off + 4], expert_fn,
                                n_experts)
            for i in range(4):
                r = off + i
                want = all_tokens[r] * (all_assign[r][:, None] + 1)
                np.testing.assert_allclose(np.asarray(outs[i]), want,
                                           rtol=1e-6)
            world.barrier()
            print(f"MOE-OK {off}")
            mpi.finalize()
        """)
        assert "MOE-OK 0" in out and "MOE-OK 4" in out

    def test_split_type_shared_gives_local_comm(self, tmp_path, capfd):
        """split_type(COMM_TYPE_SHARED) on the unified world yields the
        process-local communicator, which runs the normal in-process
        coll stack (xla), while the world itself selects hier."""
        out = _run(tmp_path, capfd, """
            world = mpi.init()
            rt = Runtime.current()
            assert "hier" in world._coll_providers.get("allreduce", []), \\
                world._coll_providers
            subs = world.split_type_shared()
            # my local ranks all share one sub-comm of size 4
            off = rt.local_rank_offset
            sub = subs[off]
            assert sub is not None and sub.size == 4
            assert not sub.spans_processes
            got = np.asarray(sub.allreduce(
                np.stack([np.int32([r]) for r in range(4)])))
            assert (got == 6).all()
            print(f"SPLIT-OK {off}")
            mpi.finalize()
        """)
        assert "SPLIT-OK 0" in out and "SPLIT-OK 4" in out

    def test_hier_inter_domain_byte_reduction(self, tmp_path, capfd):
        """The two-level compose must cross the process boundary with
        PARTIALS, not per-rank buffers: for an allreduce of local_n=4
        slices of B bytes each, inter traffic per process = (P-1) * B
        sent (one combined partial per peer), a 4x reduction vs
        shipping every rank's slice — the ml/bcol aggregation the
        reference builds its hierarchy for."""
        out = _run(tmp_path, capfd, """
            from ompi_release_tpu.mca import pvar
            world = mpi.init()
            rt = Runtime.current()
            x = np.ones((4, 1024), np.float32)  # B = 4096 bytes/slice
            before = pvar.PVARS.read_all().get("hier_inter_bytes", 0)
            world.allreduce(x)
            sent = (pvar.PVARS.read_all()["hier_inter_bytes"] - before)
            # P=2: exactly one 4096-byte partial sent to the one peer
            assert sent == 4096, sent
            print("BYTES-OK", sent)
            world.barrier()
            mpi.finalize()
        """)
        assert out.count("BYTES-OK 4096") == 2

    def test_three_process_cid_sync_after_partial_split(self, tmp_path,
                                                        capfd):
        """A split whose sub-comm has NO members on one process must
        not desynchronize cid allocation: the hier shadow comm draws
        from the internal (negative) cid counter, so a LATER spanning
        communicator gets the same cid everywhere and wire messages
        route to the right comm. Also: operations on a no-local-member
        comm fail loudly, not with an AttributeError."""
        app = tmp_path / "app3.py"
        app.write_text(textwrap.dedent("""
            import os, sys
            sys.path.insert(0, %r)
            os.environ["XLA_FLAGS"] = (
                "--xla_force_host_platform_device_count=2")
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            import ompi_release_tpu as mpi
            from ompi_release_tpu.runtime.runtime import Runtime
            from ompi_release_tpu.utils.errors import MPIError

            world = mpi.init()          # 3 procs x 2 devices = 6 ranks
            rt = Runtime.current()
            off = rt.local_rank_offset
            assert world.size == 6, world.size
            # colors: ranks 0-3 (procs 0,1) together; 4,5 (proc 2) alone
            subs = world.split([0, 0, 0, 0, 1, 1])
            sub = subs[off]
            if off in (0, 2):
                assert sub.spans_processes
                got = np.asarray(sub.allreduce(
                    np.stack([np.int32([off + i]) for i in range(2)])))
                assert (got == 0 + 1 + 2 + 3).all(), got
            else:
                assert not sub.spans_processes and sub.size == 2
                # the OTHER sub-comm has no members here: ops must
                # raise a diagnosable MPIError, not AttributeError
                other = subs[0]
                try:
                    other.recv(rank=0)
                    raise SystemExit("FAIL: foreign comm recv worked")
                except MPIError:
                    pass
            # a LATER spanning comm: cids must still agree everywhere
            later = world.dup(name="later")
            if off == 0:
                later.send(np.int32([99]), 5, tag=1, rank=0)
            elif off == 4:
                val, st = later.recv(source=0, tag=1, rank=5)
                assert int(np.asarray(val)[0]) == 99 and st.source == 0
                print("CID-SYNC-OK")
            world.barrier()
            mpi.finalize()
        """ % REPO))
        job = Job(3, [sys.executable, str(app)], [], heartbeat_s=0.5,
                  miss_limit=8)
        rc = job.run(timeout_s=180)
        out = capfd.readouterr()
        assert rc == 0, out.out + out.err
        assert "CID-SYNC-OK" in out.out

    def test_cross_process_rma_fence_parity(self, tmp_path, capfd):
        """put/get/accumulate/CAS from process 0 into slices owned by
        process 1 (and back), fence epochs, parity vs the values a
        single-process window would hold — the round-4 'no
        cross-process RMA' gap (osc/wire_win.py home-process-applies
        path vs osc_rdma_data_move.c)."""
        out = _run(tmp_path, capfd, """
            from ompi_release_tpu.osc.window import win_allocate
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            n = world.size

            win = win_allocate(world, (4,), np.float32)
            win.fence()
            if off == 0:
                # put into a REMOTE slice (rank 5, process 1)
                win.put(np.full(4, 7.0, np.float32), 5)
                # accumulate into remote rank 6
                win.accumulate(np.full(4, 2.0, np.float32), 6)
                # and a local put for contrast
                win.put(np.full(4, 1.5, np.float32), 1)
            else:
                # process 1 accumulates into a REMOTE slice (rank 2)
                win.accumulate(np.full(4, 3.0, np.float32), 2)
            win.fence_end()
            local = np.asarray(win.read())
            if off == 0:
                np.testing.assert_array_equal(local[1],
                                              np.full(4, 1.5))
                np.testing.assert_array_equal(local[2], np.full(4, 3.0))
            else:
                np.testing.assert_array_equal(local[5 - 4],
                                              np.full(4, 7.0))
                np.testing.assert_array_equal(local[6 - 4],
                                              np.full(4, 2.0))

            # remote get + fetch_and_op under a passive (lock) epoch
            if off == 0:
                win.lock(5)
                req = win.get(5)
                win.unlock(5)
                np.testing.assert_array_equal(np.asarray(req.value),
                                              np.full(4, 7.0))
                win.lock(6)
                req = win.fetch_and_op(np.full(4, 1.0, np.float32), 6)
                win.flush(6)
                old = np.asarray(req.value)
                win.unlock(6)
                np.testing.assert_array_equal(old, np.full(4, 2.0))
                # request-based RMA completes at flush across the wire
                win.lock(5)
                rr = win.rput(np.full(4, 1.25, np.float32), 5)
                assert not rr.is_complete
                win.flush(5)
                assert rr.is_complete
                win.unlock(5)
            world.barrier()
            if off == 4:
                got = np.asarray(win.read())[6 - 4]
                np.testing.assert_array_equal(got, np.full(4, 3.0))

            # single-element CAS into a remote slot
            if off == 4:
                win.lock(1)
                req = win.compare_and_swap(
                    np.float32(9.0), np.float32(1.5), 1, index=2)
                win.unlock(1)
                assert float(np.asarray(req.value)) == 1.5
            world.barrier()
            if off == 0:
                got = np.asarray(win.read())[1]
                np.testing.assert_array_equal(
                    got, np.asarray([1.5, 1.5, 9.0, 1.5], np.float32))
            win.free()
            print(f"RMA-OK {off}")
            mpi.finalize()
        """)
        assert "RMA-OK 0" in out and "RMA-OK 4" in out

    def test_cross_process_pscw_epoch(self, tmp_path, capfd):
        """Generalized active target across processes: process 1 posts
        an exposure epoch for process 0's ranks; process 0
        starts/puts/completes; process 1's wait() returns only after
        the put is applied (osc/rdma's PSCW state machine at process
        granularity)."""
        out = _run(tmp_path, capfd, """
            from ompi_release_tpu.comm.group import Group
            from ompi_release_tpu.osc.window import win_allocate
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset

            win = win_allocate(world, (4,), np.float32)
            origins = Group([0, 1, 2, 3])   # process 0's ranks
            targets = Group([4, 5, 6, 7])   # process 1's ranks
            if off == 0:
                win.start(targets)
                win.put(np.full(4, 5.5, np.float32), 5)
                req = win.get(6)
                win.complete()
                # exposure side of OUR window for the reverse epoch
                win.post(targets)
                win.wait()
                got = np.asarray(win.read())[2]
                np.testing.assert_array_equal(got,
                                              np.full(4, 8.25))
            else:
                win.post(origins)
                # MPI_Win_test polls without blocking until proc 0's
                # COMPLETE notice lands, then closes like wait()
                import time as _t
                deadline = _t.monotonic() + 60
                while not win.test():
                    assert _t.monotonic() < deadline, "test() never true"
                    _t.sleep(0.01)
                got = np.asarray(win.read())[5 - 4]
                np.testing.assert_array_equal(got, np.full(4, 5.5))
                # reverse: proc 1 accesses proc 0's rank 2
                win.start(origins)
                win.accumulate(np.full(4, 8.25, np.float32), 2)
                win.complete()
            world.barrier()
            win.free()
            print(f"PSCW-OK {off}")
            mpi.finalize()
        """)
        assert "PSCW-OK 0" in out and "PSCW-OK 4" in out

    def test_cross_process_lock_exclusion(self, tmp_path, capfd):
        """Two processes contending for an exclusive lock on the same
        target serialize at the target's home: read-modify-write under
        the lock never loses an update."""
        out = _run(tmp_path, capfd, """
            import time
            from ompi_release_tpu.osc.window import win_allocate
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset

            win = win_allocate(world, (1,), np.int32)
            world.barrier()
            # both processes: 20 exclusive-lock increments of rank 0's
            # word via fetch_and_op (atomic at the home regardless) AND
            # a read-modify-write via get + put (needs the lock)
            for _ in range(10):
                win.lock(0)
                req = win.get(0)
                win.flush(0)
                cur = int(np.asarray(req.value)[0])
                win.put(np.int32([cur + 1]), 0)
                win.unlock(0)
            world.barrier()
            if off == 0:
                total = int(np.asarray(win.read())[0, 0])
                assert total == 20, total
                print("LOCK-TOTAL", total)
            win.free()
            print(f"LOCK-OK {off}")
            mpi.finalize()
        """)
        assert "LOCK-OK 0" in out and "LOCK-OK 4" in out
        assert "LOCK-TOTAL 20" in out

    def test_cross_process_shmem(self, tmp_path, capfd):
        """OSHMEM symmetric heap riding the wire window: put/get/AMOs
        between PEs in different processes, wait_until across the
        boundary, and shmem_ptr correctly refusing non-local PEs."""
        out = _run(tmp_path, capfd, """
            from ompi_release_tpu.oshmem import shmem
            from ompi_release_tpu.utils.errors import MPIError
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset

            ctx = shmem.shmem_init(world)
            sym = ctx.malloc((3,), np.float32)
            world.barrier()
            if off == 0:
                ctx.put(sym, np.asarray([1., 2., 3.], np.float32), 6)
                ctx.quiet()
                world.barrier()  # put visible
                world.barrier()  # proc 1 read it
                # fetch-add on a remote PE
                old = np.asarray(ctx.atomic_fetch_add(
                    sym, np.ones(3, np.float32), 6))
                np.testing.assert_array_equal(
                    old, np.asarray([1., 2., 3.]))
                try:
                    sym.local(6)
                    raise SystemExit("FAIL: shmem_ptr crossed processes")
                except MPIError:
                    pass
                world.barrier()  # fetch-add done
            else:
                world.barrier()  # wait for the put+quiet
                got = np.asarray(ctx.get(sym, 6))
                np.testing.assert_array_equal(
                    got, np.asarray([1., 2., 3.]))
                world.barrier()  # release proc 0's fetch-add
                world.barrier()  # fetch-add done
                got = np.asarray(ctx.get(sym, 6))
                np.testing.assert_array_equal(
                    got, np.asarray([2., 3., 4.]))
            world.barrier()
            print(f"SHMEM-OK {off}")
            mpi.finalize()
        """)
        assert "SHMEM-OK 0" in out and "SHMEM-OK 4" in out

    def test_cross_process_collective_io_two_phase(self, tmp_path, capfd):
        """write_at_all/read_at_all on the spanning world do a REAL
        two-phase exchange over the wire (io/two_phase.py vs
        fcoll_two_phase_file_write_all.c): interleaved per-rank
        extents from 2 processes must produce a file bit-identical to
        the single-process reference file, including through a holey
        vector view; nonblocking variants included."""
        ref = tmp_path / "ref.bin"
        # single-process reference: ranks 0..7 write 5 elements each,
        # rank r at element offset r*5, value 100*r + k
        import numpy as np_
        refdata = np_.concatenate([
            100 * r + np_.arange(5, dtype=np_.int32) for r in range(8)
        ])
        refdata.tofile(str(ref))
        out = _run(tmp_path, capfd, """
            from ompi_release_tpu.io.file import File, MODE_RDWR, \\
                MODE_CREATE
            from ompi_release_tpu.datatype import datatype as dt
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            path = %r

            f = File(world, path)
            f.set_view(etype=np.int32)
            # INTERLEAVED extents: local member i (comm rank off+i)
            # writes at element (off+i)*5 — pieces of both processes'
            # blocks land in both aggregators' file domains
            offs = [(off + i) * 5 for i in range(4)]
            blocks = [100 * (off + i) + np.arange(5, dtype=np.int32)
                      for i in range(4)]
            total = f.write_at_all(offs, blocks)
            assert total == 40, total

            # collective read back: every member its own extent
            got = f.read_at_all(offs, [5] * 4)
            for i in range(4):
                np.testing.assert_array_equal(got[i], blocks[i])

            # nonblocking collective variants
            req = f.iwrite_at_all(offs, blocks)
            req.wait()
            req = f.iread_at_all(offs, [5] * 4)
            req.wait()
            for i in range(4):
                np.testing.assert_array_equal(
                    np.asarray(req.value[i]), blocks[i])
            f.close()
            world.barrier()

            # holey view: 2-of-4 int32 vector tiles; member slots
            # interleave across processes
            path2 = path + ".holey"
            f2 = File(world, path2)
            ft = dt.create_vector(2, 2, 4, dt.INT32)
            f2.set_view(0, np.int32, filetype=ft)
            offs2 = [(off + i) * 4 for i in range(4)]
            blocks2 = [1000 * (off + i) + np.arange(4, dtype=np.int32)
                       for i in range(4)]
            f2.write_at_all(offs2, blocks2)
            got2 = f2.read_at_all(offs2, [4] * 4)
            for i in range(4):
                np.testing.assert_array_equal(got2[i], blocks2[i])
            f2.close()
            world.barrier()
            print(f"IO-OK {off}")
            mpi.finalize()
        """ % str(tmp_path / "out.bin"))
        assert "IO-OK 0" in out and "IO-OK 4" in out
        got = np_.fromfile(str(tmp_path / "out.bin"), dtype=np_.int32)
        np_.testing.assert_array_equal(got, refdata)

    def test_nonblocking_hier_collectives_overlap(self, tmp_path, capfd):
        """iallreduce on a spanning comm returns BEFORE the collective
        completes (round 4: the 'nonblocking' wrapper ran the OOB
        exchange to completion first). Proof of overlap: process 1
        delays its matching allreduce by 0.5s; process 0 posts
        iallreduce, executes user compute, and observes the request
        still incomplete — then wait() delivers the parity result.
        Posting order across two outstanding collectives is preserved."""
        out = _run(tmp_path, capfd, """
            import time
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            n = world.size
            x = np.stack([np.arange(4, dtype=np.int32) * (off + i + 1)
                          for i in range(4)])
            want = sum(np.arange(4, dtype=np.int32) * (r + 1)
                       for r in range(n))
            if off == 0:
                t0 = time.monotonic()
                req = world.iallreduce(x)
                post_t = time.monotonic() - t0
                assert post_t < 0.25, f"posting blocked {post_t:.2f}s"
                # user compute between post and wait
                acc = 0
                for i in range(1000):
                    acc += i * i
                done, _ = req.test()
                assert not done, "completed before the peer even posted"
                req2 = world.ibcast(x, root=0)  # second outstanding op
                st = req.wait()
                np.testing.assert_array_equal(np.asarray(req.value)[0],
                                              want)
                req2.wait()
                print("OVERLAP-OK", acc > 0)
            else:
                time.sleep(0.5)
                got = np.asarray(world.allreduce(x))
                np.testing.assert_array_equal(got[0], want)
                world.bcast(x, root=0)
            world.barrier()
            print(f"NBC-OK {off}")
            mpi.finalize()
        """)
        assert "OVERLAP-OK True" in out
        assert "NBC-OK 0" in out and "NBC-OK 4" in out

    def test_cross_process_surface_over_dcn_staging(self, tmp_path,
                                                    capfd):
        """OMPITPU_HOST_ID gives each worker a distinct shm identity,
        so every cross-process byte rides the DCN chunked-staging
        transport instead of the shm handoff — collectives, vector
        collectives, RMA, and two-phase IO all exercised over the
        multi-host wire path on one machine."""
        out = _run(tmp_path, capfd, """
            import os
            # distinct identity per worker BEFORE bootstrap: forces
            # the cross-host transport choice. The nativewire datapath
            # is pinned OFF so this test keeps covering the portable
            # DCN staging path (the graceful-degradation target the
            # native component falls back to); test_nativewire.py
            # covers the native cross-host mode
            os.environ["OMPITPU_HOST_ID"] = (
                "fakehost-" + os.environ["OMPITPU_NODE_ID"])
            os.environ["OMPITPU_NATIVEWIRE"] = "0"
            from ompi_release_tpu.mca import pvar
            from ompi_release_tpu.osc.window import win_allocate
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            n = world.size
            # transport choice is really DCN
            peer = 1 if rt.bootstrap["process_index"] == 0 else 0
            assert rt.wire._btl_for(peer).NAME == "dcn", \\
                rt.wire._btl_for(peer).NAME

            x = np.stack([np.arange(16, dtype=np.int32) * (off + i + 1)
                          for i in range(4)])
            got = np.asarray(world.allreduce(x))
            want = sum(np.arange(16, dtype=np.int32) * (r + 1)
                       for r in range(n))
            np.testing.assert_array_equal(got[0], want)

            full = [np.asarray([100 * r + k for k in range(r + 1)],
                               np.int32) for r in range(n)]
            ag = np.asarray(world.allgatherv(full[off:off + 4]))
            np.testing.assert_array_equal(ag, np.concatenate(full))

            win = win_allocate(world, (4,), np.float32)
            win.fence()
            if off == 0:
                win.put(np.full(4, 2.5, np.float32), 6)
            win.fence_end()
            if off == 4:
                np.testing.assert_array_equal(
                    np.asarray(win.read())[6 - 4], np.full(4, 2.5))
            world.barrier()
            win.free()

            from ompi_release_tpu.io.file import File
            f = File(world, %r)
            f.set_view(etype=np.int32)
            offs = [(off + i) * 3 for i in range(4)]
            blocks = [10 * (off + i) + np.arange(3, dtype=np.int32)
                      for i in range(4)]
            f.write_at_all(offs, blocks)
            back = f.read_at_all(offs, [3] * 4)
            for i in range(4):
                np.testing.assert_array_equal(back[i], blocks[i])
            f.close()

            staged = pvar.PVARS.read_all().get("btl_dcn_staged_bytes", 0)
            assert staged > 0, "no bytes rode the DCN staging path"
            world.barrier()
            print(f"DCN-OK {off} staged={staged > 0}")
            mpi.finalize()
        """ % str(tmp_path / "dcn_io.bin"))
        assert "DCN-OK 0" in out and "DCN-OK 4" in out

    def test_concurrent_cross_process_amo_no_lost_updates(self, tmp_path,
                                                          capfd):
        """Both processes shower fetch-adds at ONE remote slot under a
        standing lock_all epoch, from two threads each, concurrently —
        the home service must apply every batch atomically (op lock
        around the compiled epoch program): the final value equals the
        exact update count, and every fetch returns a distinct
        pre-value."""
        out = _run(tmp_path, capfd, """
            import threading
            from ompi_release_tpu.oshmem import shmem
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset

            ctx = shmem.shmem_init(world)
            counter = ctx.malloc((1,), np.int32)
            world.barrier()
            N = 12
            fetched = []
            flock = threading.Lock()

            def shower():
                for _ in range(N):
                    old = np.asarray(ctx.atomic_fetch_add(
                        counter, np.ones(1, np.int32), 0))
                    with flock:
                        fetched.append(int(old[0]))

            ts = [threading.Thread(target=shower) for _ in range(2)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            world.barrier()
            if off == 0:
                final = int(np.asarray(ctx.get(counter, 0))[0])
                assert final == 4 * N, final  # 2 procs x 2 threads x N
                print("AMO-TOTAL", final)
            # atomicity: this process's fetches are all distinct
            assert len(set(fetched)) == len(fetched) == 2 * N
            world.barrier()
            print(f"AMO-OK {off}")
            mpi.finalize()
        """)
        assert "AMO-OK 0" in out and "AMO-OK 4" in out
        assert "AMO-TOTAL 48" in out

    def test_three_process_vcoll_rma_pscw(self, tmp_path, capfd):
        """P=3 battery for the paths with P>2-specific structure: the
        vector collectives' per-peer sub-layouts, TWO remote origins
        contending for one exclusive lock (home waiter queue with
        remote grants), and a PSCW exposure with two accessor
        processes."""
        app = tmp_path / "app3.py"
        app.write_text(textwrap.dedent("""
            import os, sys
            sys.path.insert(0, %r)
            os.environ["XLA_FLAGS"] = (
                "--xla_force_host_platform_device_count=2")
            os.environ["JAX_PLATFORMS"] = "cpu"
            import jax
            jax.config.update("jax_platforms", "cpu")
            import numpy as np
            import ompi_release_tpu as mpi
            from ompi_release_tpu.comm.group import Group
            from ompi_release_tpu.osc.window import win_allocate
            from ompi_release_tpu.runtime.runtime import Runtime

            world = mpi.init()      # 3 procs x 2 devices = 6 ranks
            rt = Runtime.current()
            off = rt.local_rank_offset
            n = world.size
            assert n == 6, n

            # alltoallv with zeros: c[i][j] = (i + 2*j) %% 3
            c = np.asarray([[(i + 2 * j) %% 3 for j in range(n)]
                            for i in range(n)], np.int64)
            sb = [np.concatenate([np.full(c[i, j], 10 * i + j, np.int32)
                                  for j in range(n)])
                  for i in range(off, off + 2)]
            rv = world.alltoallv(sb, c)
            for pos, j in enumerate(range(off, off + 2)):
                want = np.concatenate([np.full(c[i, j], 10 * i + j,
                                               np.int32)
                                       for i in range(n)])
                np.testing.assert_array_equal(np.asarray(rv[pos]), want)

            # uneven reduce_scatter over 3 processes
            rc = [r + 1 for r in range(n)]
            tot = sum(rc)
            x = np.stack([np.arange(tot, dtype=np.int32) * (off + i + 1)
                          for i in range(2)])
            rs = world.reduce_scatter(x, rc)
            wantfull = sum(np.arange(tot, dtype=np.int32) * (r + 1)
                           for r in range(n))
            offs = np.concatenate([[0], np.cumsum(rc)])
            for i in range(2):
                r = off + i
                np.testing.assert_array_equal(
                    np.asarray(rs[i]), wantfull[offs[r]:offs[r] + rc[r]])

            # two REMOTE origins (procs 1, 2) contend for rank 0's
            # exclusive lock: read-modify-write, no lost updates
            win = win_allocate(world, (1,), np.int32)
            world.barrier()
            if off != 0:
                for _ in range(8):
                    win.lock(0)
                    req = win.get(0)
                    win.flush(0)
                    cur = int(np.asarray(req.value)[0])
                    win.put(np.int32([cur + 1]), 0)
                    win.unlock(0)
            world.barrier()
            if off == 0:
                total = int(np.asarray(win.read())[0, 0])
                assert total == 16, total
                print("LOCK3-TOTAL", total)

            # PSCW: proc 0 exposes to accessors in procs 1 AND 2;
            # wait() must collect BOTH completes
            g_origins = Group([2, 3, 4, 5])   # procs 1, 2
            g_targets = Group([0, 1])         # proc 0
            if off == 0:
                win.post(g_origins)
                win.wait()
                got = int(np.asarray(win.read())[1, 0])
                assert got == 2 + 4, got   # both accumulates landed
            else:
                win.start(g_targets)
                win.accumulate(np.int32([off]), 1)  # +2 and +4
                win.complete()
            world.barrier()
            win.free()
            print(f"P3-OK {off}")
            mpi.finalize()
        """ % REPO))
        job = Job(3, [sys.executable, str(app)], [], heartbeat_s=0.5,
                  miss_limit=8)
        rc = job.run(timeout_s=240)
        out = capfd.readouterr()
        assert rc == 0, out.out + out.err
        for o in (0, 2, 4):
            assert f"P3-OK {o}" in out.out
        assert "LOCK3-TOTAL 16" in out.out

    def test_intercomm_across_processes(self, tmp_path, capfd):
        """MPI_Intercomm_create bridging two process-local comms on the
        unified world: p2p crosses the boundary with remote-rank
        addressing through the intercomm, and Intercomm_merge yields a
        spanning intracomm whose collectives run the hier stack."""
        out = _run(tmp_path, capfd, """
            from ompi_release_tpu.comm.intercomm import intercomm_create
            world = mpi.init()
            rt = Runtime.current()
            off = rt.local_rank_offset
            n = world.size

            subs = world.split([0] * 4 + [1] * 4)
            comm_a, comm_b = subs[0], subs[4]
            ia, ib = intercomm_create(comm_a, 0, comm_b, 0)
            inter = ia if off == 0 else ib
            assert inter.remote_size == 4

            # p2p with REMOTE-group rank addressing across processes
            if off == 0:
                inter.send(np.int32([41]), dest=2, tag=3, rank=1)
                val, st = inter.recv(source=2, tag=4, rank=1)
                assert int(np.asarray(val)[0]) == 42
                assert st.source == 2  # remote-group rank, not bridge
            else:
                val, st = inter.recv(source=1, tag=3, rank=2)
                assert int(np.asarray(val)[0]) == 41
                assert st.source == 1
                inter.send(np.int32([42]), dest=1, tag=4, rank=2)

            # merge -> ONE spanning intracomm; hier collectives work
            merged = inter.merge(high=(off == 4))
            assert merged.size == n and merged.spans_processes
            x = np.stack([np.int32([off + i]) for i in range(4)])
            got = np.asarray(merged.allreduce(x))
            assert (got == sum(range(n))).all(), got
            world.barrier()
            print(f"INTER-OK {off}")
            mpi.finalize()
        """)
        assert "INTER-OK 0" in out and "INTER-OK 4" in out

    def test_unified_world_opt_out(self, tmp_path, capfd):
        """--mca runtime_unified_world false restores per-process
        local worlds (the pre-unification behavior)."""
        app = _write_app(tmp_path, """
            world = mpi.init()
            rt = Runtime.current()
            assert world.size == 4, world.size
            assert not rt.unified
            print("LOCAL-OK")
            mpi.finalize()
        """)
        job = Job(2, [sys.executable, app],
                  [("runtime_unified_world", "false")], heartbeat_s=0.5,
                  miss_limit=8)
        rc = job.run(timeout_s=180)
        out = capfd.readouterr().out
        assert rc == 0, out
        assert out.count("LOCAL-OK") == 2
