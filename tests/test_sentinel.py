"""Collective contract sentinel: cross-rank call-signature hashing,
desync forensics, and the typed ERR_COLL_MISMATCH.

Three layers under test:

- unit: chain determinism (two identical call streams fold to one
  chain value), the journal-event encode/parse round-trip, the call
  site fingerprint, the doctor's contract alignment on SYNTHETIC
  dumps (every divergence kind: mismatch, posting-order swap, missing
  participant, epoch skew, and the no-divergence case), the watchdog
  contributor, the incident-timeline rendering, the tpu_top DESYNC
  flag, and the bench-gate direction of ``sentinel_`` metrics;
- in-process: entry-point coverage — blocking, i-family, persistent
  ``start()`` — through a real (loopback-device) communicator;
- job: REAL 3-process tpurun desync injections. Inline mode
  (``obs_sentinel=2``): one rank posts a mismatched dtype and every
  process raises the typed ``ERR_COLL_MISMATCH`` within that round,
  naming the divergent process and both call sites — instead of
  hanging. Post-hoc mode (``obs_sentinel=1``): one rank swaps the
  posting order of two collectives, the job deadlocks, the watchdog
  postmortems capture the signature stream, and ``tpu-doctor
  contracts`` names the first divergent (cid, seq) and both call
  sites from the dumps alone.
"""

import json
import os
import sys

import numpy as np
import pytest

from ompi_release_tpu import obs
from ompi_release_tpu.mca import pvar as mca_pvar
from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.obs import doctor as doctor_mod
from ompi_release_tpu.obs import sentinel
from ompi_release_tpu.runtime.state import JobState
from ompi_release_tpu.tools.tpurun import Job
from ompi_release_tpu.utils.errors import ErrorCode

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture
def armed_sentinel():
    """obs + sentinel post-hoc mode on, fully restored afterwards."""
    was_obs = obs.enabled
    sentinel._reset_for_tests()
    mca_var.set_value("obs_sentinel", 1)
    obs.enable()
    sentinel.refresh(True)
    yield
    mca_var.VARS.unset("obs_sentinel")
    sentinel._reset_for_tests()
    if not was_obs:
        obs.disable()
    else:
        sentinel.refresh(True)


# ---------------------------------------------------------------------------
# unit: chains, encoding, call sites
# ---------------------------------------------------------------------------


class TestChain:
    def test_disabled_is_inert(self):
        sentinel._reset_for_tests()
        assert not sentinel.enabled and sentinel.mode() == 0
        assert sentinel.record_sig(1, "allreduce") is None
        assert sentinel.chain_of(1) == 0

    def test_determinism_and_divergence(self, armed_sentinel):
        stream = (("allreduce", "sum", "float32", 64, -1),
                  ("barrier", "-", "-", 0, -1),
                  ("bcast", "-", "int32", 8, 2))
        for cid in (11, 12):
            for fam, op_n, dt, cnt, root in stream:
                sentinel.record_sig(cid, fam, op_n, dt, cnt, root,
                                    site="x.py:1")
        assert sentinel.chain_of(11) == sentinel.chain_of(12) != 0
        # call SITES differ -> chains still agree (sites are
        # forensics, not contract)
        sentinel.record_sig(11, "allreduce", "sum", "float32", 64, -1,
                            site="a.py:10")
        sentinel.record_sig(12, "allreduce", "sum", "float32", 64, -1,
                            site="b.py:99")
        assert sentinel.chain_of(11) == sentinel.chain_of(12)
        # any contract FIELD difference splits the chain
        sentinel.record_sig(11, "allreduce", "sum", "float32", 64, -1)
        sentinel.record_sig(12, "allreduce", "max", "float32", 64, -1)
        assert sentinel.chain_of(11) != sentinel.chain_of(12)

    def test_journal_event_roundtrip(self, armed_sentinel):
        sig = sentinel.record_sig(7, "reduce", "min", "float64", 32, 1,
                                  epoch=3, site="train.py:88")
        assert sig is not None and len(sig.digest()) == 16
        span = [s for s in obs.journal.snapshot()
                if s.layer == "sentinel"][-1]
        assert span.comm_id == 7 and span.peer == sig.seq
        parsed = sentinel.parse_op(span.op)
        assert parsed == {"canon": "reduce|min|float64|32|1",
                          "family": "reduce", "epoch": 3,
                          "site": "train.py:88"}
        assert sentinel.parse_op("allreduce") is None
        assert sentinel.parse_op("a|b|c|d|e|f|g") is None  # no e<int>

    def test_call_site_is_this_file(self, armed_sentinel):
        sig = sentinel.record_sig(8, "allreduce")
        assert sig.site.startswith("test_sentinel.py:"), sig.site

    def test_ring_is_bounded(self, armed_sentinel):
        mca_var.set_value("obs_sentinel_ring", 4)
        try:
            for i in range(10):
                sentinel.record_sig(9, "allreduce", "sum", "f32", i, -1)
            snap = sentinel.chains_snapshot()["comms"]["9"]
            assert snap["next_seq"] == 10
            assert len(snap["last"]) == 4
            assert snap["last"][-1]["seq"] == 9
        finally:
            mca_var.VARS.unset("obs_sentinel_ring")

    def test_watchdog_contributor_registered(self, armed_sentinel):
        from ompi_release_tpu.obs import watchdog

        sentinel.record_sig(13, "allreduce", "sum", "float32", 4, -1)
        doc = watchdog._contributors["sentinel"]()
        assert doc["mode"] == 1
        assert doc["comms"]["13"]["next_seq"] == 1

    def test_describe_strips_local_rank_axis(self, armed_sentinel):
        class FakeComm:
            cid = 21
            spans_processes = False

        c = FakeComm()
        sig = sentinel.note(c, "allreduce",
                            (np.ones((2, 128), np.float32),), {})
        # per-rank count, not the stacked driver-mode buffer
        assert sig.canon == "allreduce|-|float32|128|-1", sig.canon

    def test_note_skips_internal_comms(self, armed_sentinel):
        class Internal:
            cid = -3
            spans_processes = False

        assert sentinel.note(Internal(), "allreduce") is None


# ---------------------------------------------------------------------------
# unit: doctor contract alignment on synthetic dumps
# ---------------------------------------------------------------------------


def _sig_span(cid, seq, canon, site, epoch=0):
    return {"seq": seq, "op": sentinel.encode_op(canon, epoch, site),
            "layer": "sentinel", "t": float(seq), "dt": 0.0,
            "bytes": 0, "peer": seq, "comm": cid}


def _dump(pidx, spans):
    return {"meta": {"pidx": pidx, "rank_offset": pidx, "local_size": 1,
                     "clock_offset_s": 0.0},
            "spans": spans}


AR = "allreduce|sum|float32|64|-1"
BC = "bcast|-|float32|64|0"
BAR = "barrier|-|-|0|-1"


class TestContractReport:
    def test_aligned_streams_report_clean(self):
        dumps = [_dump(p, [_sig_span(0, s, c, f"dp.py:{10 + s}")
                           for s, c in enumerate((AR, BAR, AR))])
                 for p in range(3)]
        text, data = doctor_mod.contract_report(dumps)
        assert data["divergences"] == 0
        assert "no divergence" in text and "DESYNC" not in text

    def test_signature_mismatch_names_rank_seq_and_sites(self):
        dumps = [
            _dump(0, [_sig_span(0, 0, AR, "dp.py:203"),
                      _sig_span(0, 1, AR, "dp.py:203")]),
            _dump(1, [_sig_span(0, 0, AR, "dp.py:203"),
                      _sig_span(0, 1, AR, "dp.py:203")]),
            _dump(2, [_sig_span(0, 0, AR, "dp.py:203"),
                      _sig_span(0, 1, BC, "train.py:88")]),
        ]
        text, data = doctor_mod.contract_report(dumps)
        div = data["comms"]["0"]["divergence"]
        assert div["kind"] == "signature_mismatch"
        assert div["seq"] == 1 and div["divergent"] == 2
        assert "DESYNC at seq 1" in text
        assert "proc 2 posted " + BC in text
        assert "train.py:88" in text and "dp.py:203" in text

    def test_mismatch_attributes_culprit_by_majority(self):
        # proc 0 ITSELF is the desynced rank: the majority canon is
        # the expected one, so the report must blame proc 0, not the
        # agreeing procs that happen to sort after it
        dumps = [
            _dump(0, [_sig_span(0, 0, BC, "train.py:88")]),
            _dump(1, [_sig_span(0, 0, AR, "dp.py:203")]),
            _dump(2, [_sig_span(0, 0, AR, "dp.py:203")]),
        ]
        text, data = doctor_mod.contract_report(dumps)
        div = data["comms"]["0"]["divergence"]
        assert div["divergent"] == 0 and div["agreeing"] == [1, 2]
        assert div["expected"]["canon"] == AR
        assert div["actual"]["canon"] == BC
        assert "proc 0 posted " + BC in text

    def test_chain_cleared_on_comm_free_and_cid_reuse(
            self, armed_sentinel):
        sentinel.record_sig(33, "allreduce", "sum", "float32", 8, -1)
        assert sentinel.chain_of(33) != 0
        sentinel.clear_chain(33)
        assert sentinel.chain_of(33) == 0
        assert "33" not in sentinel.chains_snapshot()["comms"]
        # and through the real comm lifecycle: free() closes the
        # comm's contract story
        import ompi_release_tpu as mpi

        world = mpi.init()
        sub = world.dup(name="sentinel_free_probe")
        x = np.ones((world.size, 4), np.float32)
        sub.allreduce(x)
        assert sentinel.chain_of(sub.cid) != 0
        sub.free()
        assert sentinel.chain_of(sub.cid) == 0

    def test_posting_order_swap_classified(self):
        dumps = [
            _dump(0, [_sig_span(0, 0, AR, "a.py:1"),
                      _sig_span(0, 1, BAR, "a.py:2"),
                      _sig_span(0, 2, AR, "a.py:3")]),
            _dump(1, [_sig_span(0, 0, AR, "a.py:1"),
                      _sig_span(0, 2, BAR, "b.py:9"),
                      _sig_span(0, 1, AR, "b.py:8")]),
        ]
        text, data = doctor_mod.contract_report(dumps)
        div = data["comms"]["0"]["divergence"]
        assert div["kind"] == "posting_order_swap" and div["seq"] == 1
        assert "posting-order swap" in text

    def test_missing_participant_names_last_posted(self):
        dumps = [
            _dump(0, [_sig_span(0, s, AR, "a.py:1") for s in range(4)]),
            _dump(1, [_sig_span(0, s, AR, "a.py:1") for s in range(4)]),
            _dump(2, [_sig_span(0, s, AR, "a.py:1") for s in range(2)]),
        ]
        text, data = doctor_mod.contract_report(dumps)
        div = data["comms"]["0"]["divergence"]
        assert div["kind"] == "missing_participant"
        assert div["seq"] == 2 and div["missing"] == [2]
        assert "never posted" in text

    def test_ring_wrap_is_not_a_divergence(self):
        # proc 1's journal wrapped: its window starts later — the
        # overlap agrees, so no desync may be reported
        dumps = [
            _dump(0, [_sig_span(0, s, AR, "a.py:1") for s in range(6)]),
            _dump(1, [_sig_span(0, s, AR, "a.py:1")
                      for s in range(3, 6)]),
        ]
        _, data = doctor_mod.contract_report(dumps)
        assert data["divergences"] == 0

    def test_epoch_skew_detected(self):
        dumps = [
            _dump(0, [_sig_span(0, 0, AR, "a.py:1", epoch=2)]),
            _dump(1, [_sig_span(0, 0, AR, "a.py:1", epoch=1)]),
        ]
        text, data = doctor_mod.contract_report(dumps)
        div = data["comms"]["0"]["divergence"]
        assert div["kind"] == "epoch_skew" and div["divergent"] == 1
        assert "epoch skew" in text

    def test_transient_epoch_skew_is_not_a_divergence(self):
        # FT notices propagate asynchronously: a one-round epoch lag
        # that converges at the next common seq is legal, not a desync
        dumps = [
            _dump(0, [_sig_span(0, 0, AR, "a.py:1", epoch=1),
                      _sig_span(0, 1, AR, "a.py:1", epoch=1)]),
            _dump(1, [_sig_span(0, 0, AR, "a.py:1", epoch=0),
                      _sig_span(0, 1, AR, "a.py:1", epoch=1)]),
        ]
        _, data = doctor_mod.contract_report(dumps)
        assert data["divergences"] == 0

    def test_epoch_skew_expected_comes_from_fresh_proc(self):
        # the stale proc may be the lowest-indexed one: expected must
        # still carry the FRESH side's record, never the culprit's own
        dumps = [
            _dump(0, [_sig_span(0, 0, AR, "a.py:1", epoch=1)]),
            _dump(1, [_sig_span(0, 0, AR, "b.py:2", epoch=2)]),
        ]
        _, data = doctor_mod.contract_report(dumps)
        div = data["comms"]["0"]["divergence"]
        assert div["kind"] == "epoch_skew" and div["divergent"] == 0
        assert div["expected"]["epoch"] == 2
        assert div["expected"]["site"] == "b.py:2"
        assert div["actual"]["site"] == "a.py:1"

    def test_finalize_meta_ring_feeds_alignment(self):
        # journal wrapped past every sentinel span before finalize:
        # the rings in meta["sentinel"] must still carry the desync
        def meta_dump(pidx, canon, site):
            d = _dump(pidx, [])
            d["meta"]["sentinel"] = {"mode": 1, "comms": {"0": {
                "next_seq": 1, "chain": "ab",
                "last": [{"seq": 0, "canon": canon, "epoch": 0,
                          "site": site, "sig": 1}]}}}
            return d

        dumps = [meta_dump(0, AR, "dp.py:203"),
                 meta_dump(1, BC, "train.py:88")]
        text, data = doctor_mod.contract_report(dumps)
        assert data["divergences"] == 1
        assert "train.py:88" in text and "dp.py:203" in text

    def test_postmortem_ring_feeds_alignment(self, tmp_path):
        # no journals at all: only postmortems with the sentinel
        # contributor ring — alignment still names the desync
        for p, canon, site in ((0, AR, "dp.py:203"),
                               (1, BC, "train.py:88")):
            pm = {"reason": "stall", "time_unix": 1.0,
                  "rank": {"pidx": p, "pid": 100 + p,
                           "rank_offset": p, "local_size": 1},
                  "clock": {"offset_s": 0.0},
                  "journal_tail": [],
                  "sentinel": {"mode": 1, "comms": {"0": {
                      "next_seq": 1, "chain": "ab",
                      "last": [{"seq": 0, "canon": canon, "epoch": 0,
                                "site": site, "sig": 1}]}}}}
            (tmp_path / f"postmortem-p{p}-stall-1.json").write_text(
                json.dumps(pm))
        dumps = doctor_mod.load_dir(str(tmp_path))
        text, data = doctor_mod.contract_report(
            dumps, directory=str(tmp_path))
        assert data["divergences"] == 1
        assert "train.py:88" in text and "dp.py:203" in text


# ---------------------------------------------------------------------------
# unit: incident timeline + tpu_top flag + gate direction
# ---------------------------------------------------------------------------


def test_incident_timeline_renders_ft_events():
    spans0 = [
        {"seq": 0, "op": "ft_failure", "layer": "ft", "t": 10.0,
         "dt": 0.0, "bytes": 0, "peer": 2, "comm": 1},
        {"seq": 1, "op": "ft_revoke", "layer": "ft", "t": 10.1,
         "dt": 0.0, "bytes": 0, "peer": 1, "comm": 5},
        {"seq": 2, "op": "ft_recovery", "layer": "ft", "t": 10.2,
         "dt": 0.85, "bytes": 0, "peer": 3, "comm": 524288},
        {"seq": 3, "op": "allreduce", "layer": "coll", "t": 11.0,
         "dt": 0.01, "bytes": 64, "peer": -1, "comm": 0},
    ]
    spans1 = [{"seq": 0, "op": "allreduce", "layer": "coll", "t": 11.0,
               "dt": 0.02, "bytes": 64, "peer": -1, "comm": 0}]
    dumps = [_dump(0, spans0), _dump(1, spans1)]
    evs = doctor_mod.incident_timeline(dumps)
    assert [e["op"] for e in evs] == ["ft_failure", "ft_revoke",
                                     "ft_recovery"]
    assert evs[0]["failed_pidx"] == 2 and evs[0]["epoch"] == 1
    assert evs[2]["duration_s"] == pytest.approx(0.85)
    # the report folds the timeline in as its incident section
    text, data = doctor_mod.skew_report(dumps)
    assert "incident timeline" in text
    assert "learned process 2 FAILED" in text
    assert "revoked cid 5" in text
    assert "recovered in 0.850s" in text
    assert len(data["incidents"]) == 3


def test_skew_report_without_incidents_has_no_section():
    dumps = [_dump(p, [{"seq": 0, "op": "allreduce", "layer": "coll",
                        "t": 1.0 + p, "dt": 0.01, "bytes": 4,
                        "peer": -1, "comm": 0}]) for p in range(2)]
    text, data = doctor_mod.skew_report(dumps)
    assert "incident timeline" not in text
    assert data["incidents"] == []


def test_tpu_top_desync_flag():
    from ompi_release_tpu.tools.tpu_top import render_fleet, \
        summarize_points

    pts = [{"i": 0, "t": 1.0, "cid": -1, "name": "sentinel_mismatches",
            "v": 2.0},
           {"i": 1, "t": 2.0, "cid": 0, "name": "coll_ops", "v": 5.0}]
    s = summarize_points(pts)
    assert s["desyncs"] == 2
    table = render_fleet([{"meta": {"pidx": 0, "rank_offset": 0,
                                    "local_size": 1}, "points": pts}])
    assert "DESYNC×2" in table
    # and absent when the sentinel saw nothing
    assert "DESYNC" not in render_fleet(
        [{"meta": {"pidx": 0}, "points": pts[1:]}])


def test_bench_gate_sentinel_metrics_are_lower_better():
    from ompi_release_tpu.tools.tpu_bench_gate import _direction

    assert _direction("frac_overhead",
                      "sentinel_allreduce_overhead_frac") == -1
    assert _direction("s", "sentinel_allreduce_1MiB_disabled") == -1
    # regression trips on overhead GROWTH past the fitted band
    from ompi_release_tpu.tools.tpu_bench_gate import evaluate

    hist = [[{"metric": "sentinel_allreduce_overhead_frac",
              "value": 0.01, "unit": "frac_overhead",
              "tier_label": "loopback-cpu"}] for _ in range(4)]
    bad = [{"metric": "sentinel_allreduce_overhead_frac", "value": 0.8,
            "unit": "frac_overhead", "tier_label": "loopback-cpu"}]
    assert evaluate(hist, bad)["regressions"]
    ok = [{"metric": "sentinel_allreduce_overhead_frac", "value": 0.012,
           "unit": "frac_overhead", "tier_label": "loopback-cpu"}]
    assert not evaluate(hist, ok)["regressions"]


def test_inline_frame_template_renders_byte_identical_payload():
    """The planned path's precomposed ctl frame: for any (seq, epoch)
    the template's render must be byte-for-byte what the interpreted
    inline check serializes — digest + json.dumps(descriptor()) — or
    planned and interpreted ranks would flag each other as desynced."""
    canon = sentinel.make_canon("allreduce", "sum", "float32", 512, -1)
    site = "app.py:42"
    tpl = sentinel.InlineFrameTemplate(canon, site)
    for seq, epoch, chain_prev in ((0, 0, 0), (7, 2, 12345),
                                   (2**31, 9, 2**60)):
        sig = sentinel.CallSig(3, seq, "allreduce", canon, epoch,
                               site, chain_prev)
        want = sig.digest() + json.dumps(sig.descriptor()).encode()
        assert tpl.render(sig) == want
    # a template is keyed by (canon, site): rendering a different
    # call stream through it would ship the wrong canon — the cache
    # in coll/nbc keys on exactly this pair
    assert tpl.key == (canon, site)


def test_err_coll_mismatch_is_a_distinct_class():
    assert ErrorCode.ERR_COLL_MISMATCH.value == 77
    assert ErrorCode.ERR_COLL_MISMATCH != ErrorCode.ERR_PROC_FAILED


# ---------------------------------------------------------------------------
# in-process: entry-point coverage through a real communicator
# ---------------------------------------------------------------------------


def test_entry_points_cover_blocking_ifamily_persistent(armed_sentinel):
    import ompi_release_tpu as mpi

    world = mpi.init()
    h0 = float(mca_pvar.PVARS.lookup("sentinel_ops_hashed").read())
    x = np.ones((world.size, 8), np.float32)
    world.allreduce(x)                      # blocking
    world.iallreduce(x).wait()              # i-family
    world.ibarrier().wait()                 # native async-dispatch
    req = world.allreduce_init(x)           # persistent: 2 starts
    req.start(); req.wait()
    req.start(); req.wait()
    hashed = float(
        mca_pvar.PVARS.lookup("sentinel_ops_hashed").read()) - h0
    assert hashed == 5.0, hashed
    sigs = [s for s in obs.journal.snapshot() if s.layer == "sentinel"
            and s.comm_id == world.cid]
    assert len(sigs) >= 5
    seqs = [s.peer for s in sigs[-5:]]
    assert seqs == sorted(seqs), seqs  # strict posting order
    parsed = sentinel.parse_op(sigs[-1].op)
    assert parsed["canon"] == "allreduce|sum|float32|8|-1"
    assert parsed["site"].startswith("test_sentinel.py:")


# ---------------------------------------------------------------------------
# job: REAL 3-process desync injections
# ---------------------------------------------------------------------------

_INLINE_APP = r'''
import os, sys
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import ompi_release_tpu as mpi
from ompi_release_tpu.runtime.runtime import Runtime
from ompi_release_tpu import obs
from ompi_release_tpu.obs import sentinel
from ompi_release_tpu.utils.errors import ErrorCode, MPIError

world = mpi.init()
rt = Runtime.current()
me = rt.bootstrap["process_index"]
assert obs.enabled and sentinel.enabled and sentinel.mode() == 2

x32 = np.ones((1, 64), np.float32)
x64 = np.ones((1, 64), np.float64)

# a matching round first: the contract holds, nothing raises
got = np.asarray(world.allreduce(x32))
np.testing.assert_allclose(got[0], 3.0)

try:
    if me == 1:
        world.allreduce(x64)   # the desync: float64 where others f32
    else:
        world.allreduce(x32)
    print(f"SENTINEL-NO-RAISE {me}", flush=True)
except MPIError as e:
    assert e.code == ErrorCode.ERR_COLL_MISMATCH, e
    print(f"SENTINEL-MISMATCH-OK {me} :: {e}", flush=True)

# the typed error fired BEFORE any payload traffic: the comm is still
# coherent, and the next round's signatures line up again
got = np.asarray(world.allreduce(x32))
np.testing.assert_allclose(got[0], 3.0)
world.barrier()
print(f"SENTINEL-APP-DONE {me}", flush=True)
mpi.finalize()
'''


def test_inline_mismatch_raises_typed_error_in_round(tmp_path, capfd):
    """obs_sentinel=2: rank 1 posts a float64 allreduce where ranks
    0/2 posted float32 — EVERY process raises ERR_COLL_MISMATCH
    within that round (no hang, no watchdog needed), the message
    names the divergent process and both call sites, and the comm
    stays usable for the next (matching) round."""
    app = tmp_path / "mismatch_app.py"
    app.write_text(_INLINE_APP % {"repo": REPO})
    job = Job(3, [sys.executable, str(app)],
              [("obs_enable", "1"), ("obs_sentinel", "2")],
              heartbeat_s=0.5, miss_limit=10)
    rc = job.run(timeout_s=180)
    out = capfd.readouterr()
    assert rc == 0, out.out + out.err
    assert job.job_state.visited(JobState.TERMINATED)
    for me in (0, 1, 2):
        assert f"SENTINEL-MISMATCH-OK {me}" in out.out, out.out
        assert f"SENTINEL-APP-DONE {me}" in out.out
    assert "SENTINEL-NO-RAISE" not in out.out
    # the typed error names the contract fields and both call sites
    mis = [ln for ln in out.out.splitlines()
           if "SENTINEL-MISMATCH-OK 0" in ln]
    assert mis and "ERR_COLL_MISMATCH" in mis[0]
    assert "process 1" in mis[0]
    assert "float64" in mis[0] and "float32" in mis[0]
    assert mis[0].count("mismatch_app.py:") == 2, mis[0]


_SWAP_APP = r'''
import os, sys, threading, time
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import ompi_release_tpu as mpi
from ompi_release_tpu.runtime.runtime import Runtime
from ompi_release_tpu import obs
from ompi_release_tpu.obs import sentinel
from ompi_release_tpu.obs import watchdog as wd

world = mpi.init()
rt = Runtime.current()
me = rt.bootstrap["process_index"]
assert obs.enabled and wd.enabled and sentinel.mode() == 1

def bail():
    # long enough for every rank's stall watchdog to dump, then die:
    # the desynced job can never finish on its own
    time.sleep(%(bail_s)s)
    print(f"SWAP-APP-BAIL {me}", flush=True)
    os._exit(9)

threading.Thread(target=bail, daemon=True).start()

x = np.ones((1, 32), np.float32)
world.allreduce(x)            # a healthy aligned round first

if me == 2:
    r1 = world.iallreduce(x)  # the swap: allreduce posted BEFORE the
    r2 = world.ibarrier()     # barrier ranks 0/1 posted first
else:
    r1 = world.ibarrier()
    r2 = world.iallreduce(x)
r1.wait()                     # deadlock: barrier (ctl) vs allreduce
r2.wait()                     # (coll channel) can never pair up
print(f"SWAP-APP-UNEXPECTED-FINISH {me}", flush=True)
mpi.finalize()
'''


def test_posting_order_swap_postmortem_contracts(tmp_path, capfd):
    """obs_sentinel=1 on a hung mismatched run: rank 2 swaps the
    posting order of an ibarrier/iallreduce pair, the job deadlocks,
    the watchdog postmortems capture each rank's signature stream,
    and ``tpu-doctor contracts`` over the postmortem dir alone names
    the first divergent (cid, seq), classifies the swap, and shows
    both call sites."""
    pm_dir = tmp_path / "pm"
    app = tmp_path / "swap_app.py"
    app.write_text(_SWAP_APP % {"repo": REPO, "bail_s": 8.0})
    job = Job(3, [sys.executable, str(app)],
              [("obs_enable", "1"), ("obs_sentinel", "1"),
               ("obs_stall_timeout", "1.5"),
               ("obs_postmortem_dir", str(pm_dir))],
              heartbeat_s=0.5, miss_limit=20)
    rc = job.run(timeout_s=180)
    out = capfd.readouterr()
    assert rc != 0, "a desynced job must not exit clean"
    assert "SWAP-APP-UNEXPECTED-FINISH" not in out.out
    pms = sorted(pm_dir.glob("postmortem-*.json"))
    assert pms, f"no postmortems in {pm_dir}: {out.out}"

    dumps = doctor_mod.load_dir(str(pm_dir))
    text, data = doctor_mod.contract_report(dumps,
                                            directory=str(pm_dir))
    assert data["divergences"] >= 1, text
    div = next(c["divergence"] for c in data["comms"].values()
               if c["divergence"])
    assert div["kind"] == "posting_order_swap", (div, text)
    assert div["divergent"] == 2
    assert "DESYNC at seq" in text
    assert "posting-order swap" in text
    # both call sites, straight out of the postmortem dumps
    assert text.count("swap_app.py:") == 2, text
    exp, act = div["expected"], div["actual"]
    assert exp["canon"].startswith("barrier|")
    assert act["canon"].startswith("allreduce|")
    assert exp["site"] != act["site"]

    # the CLI subcommand exits 3 on divergence
    from ompi_release_tpu.tools.tpu_doctor import main as doctor_main

    assert doctor_main(["contracts", str(pm_dir)]) == 3
    cli_out = capfd.readouterr().out
    assert "posting-order swap" in cli_out
