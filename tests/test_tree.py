"""Pytree-native planned collectives (``parallel/tree``).

Four layers:

1. The rules interface: regex partition rules -> PartitionSpec pytree
   (first match wins, scalar leaves unpartitioned per the fmengine
   rule, unmatched leaves fail loudly), and the flagship model's
   ``PARTITION_RULES`` reproducing its hand-written spec table.
2. Plan units: per-(dtype) bucketing through the shared fusion
   planner, signature-keyed caching (``tree_plan_cache_hits``), and
   the tuned bucket-size resolution chain (``tree_buckets`` dynamic
   rules > ``tree_bucket_bytes`` > ``dp_bucket_bytes``).
3. The BITWISE PARITY MATRIX: every planned SPMD pass (allreduce /
   reduce_scatter / allgather) against the per-leaf reference path
   (``bucket_bytes=0``) over mixed-dtype trees with scalar leaves,
   the ZeRO shard/unshard round-trip, and the host-driver
   :class:`TreeSync` families against their per-leaf blocking
   collectives (plus the HostPipeline schedule against its blocking
   leg and the compiled ``pp.pipeline`` reference).
4. A real 3-process ``tpurun`` job: the overlapped whole-tree pass
   under the progress thread hides comm (``nbc_hidden_seconds`` and
   ``tree_hidden_seconds`` both > 0) with bitwise parity, and the
   HostPipeline boundary transfers run nonblocking with identical
   results.
"""

import os
import sys
import textwrap

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

import ompi_release_tpu as mpi
from ompi_release_tpu.mca import pvar
from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.parallel import dp, pp, tree, zero
from ompi_release_tpu.runtime.state import JobState
from ompi_release_tpu.tools.tpurun import Job

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def world():
    return mpi.init()


def mesh1d(n, name):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def smap(fn, mesh, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
    )


def _pv(name):
    p = pvar.PVARS.lookup(name)
    v = p.read() if p is not None else 0.0
    return v if isinstance(v, dict) else float(v)


# ---------------------------------------------------------------------------
# rules -> PartitionSpec plan
# ---------------------------------------------------------------------------

class TestPartitionRules:
    RULES = (
        (r"embed", P("tp", None)),
        (r"layers/w.*", P("pp", None, "tp")),
        (r"layers/ln\d", P("pp", None)),
    )

    def test_regex_rules_match_paths_first_wins(self):
        tree_ = {
            "embed": np.zeros((8, 4)),
            "layers": {"w1": np.zeros((2, 4, 4)),
                       "ln1": np.zeros((2, 4))},
        }
        specs = tree.match_partition_rules(self.RULES, tree_)
        assert specs["embed"] == P("tp", None)
        assert specs["layers"]["w1"] == P("pp", None, "tp")
        # "layers/ln1" also matches neither w-rule; the ln rule wins
        assert specs["layers"]["ln1"] == P("pp", None)

    def test_scalar_leaves_unpartitioned(self):
        """The fmengine rule: 0-d and single-element leaves get P()
        regardless of what the rules say."""
        tree_ = {"embed": np.zeros(()), "layers": {"w1": np.zeros((1,))}}
        specs = tree.match_partition_rules(self.RULES, tree_)
        assert specs["embed"] == P()
        assert specs["layers"]["w1"] == P()

    def test_unmatched_leaf_raises(self):
        with pytest.raises(ValueError, match="orphan"):
            tree.match_partition_rules(self.RULES,
                                       {"orphan": np.zeros((3, 3))})

    def test_named_tree_map_paths(self):
        names = []
        tree.named_tree_map(
            lambda name, x: names.append(name),
            {"a": {"b": [np.zeros(2), np.zeros(3)]}, "c": np.zeros(1)})
        assert sorted(names) == ["a/b/0", "a/b/1", "c"]

    def test_model_partition_rules_match_literal_table(self):
        """The flagship model's regex rules reproduce the hand-written
        spec tree for both dense and MoE configs."""
        from ompi_release_tpu.models import transformer as tfm

        for n_experts in (0, 8):
            cfg = tfm.ModelConfig(n_experts=n_experts)
            specs = tfm.param_specs(cfg)
            layers = {"ln1": P("pp", None), "wq": P("pp", None, "tp"),
                      "wk": P("pp", None, "tp"),
                      "wv": P("pp", None, "tp"),
                      "wo": P("pp", "tp", None), "ln2": P("pp", None)}
            if n_experts:
                layers.update(router=P("pp", None, None),
                              we1=P("pp", "ep", None, None),
                              we2=P("pp", "ep", None, None))
            else:
                layers.update(w1=P("pp", None, "tp"),
                              w2=P("pp", "tp", None))
            assert specs == {"embed": P("tp", None), "ln_f": P(),
                             "layers": layers}


# ---------------------------------------------------------------------------
# plan units: bucketing, caching, tuned resolution
# ---------------------------------------------------------------------------

class TestPlan:
    def test_buckets_group_by_dtype_and_capacity(self):
        plan = tree.plan_from_meta(
            [((100,), "float32"),   # 400 B
             ((100,), "float32"),   # 400 B -> same bucket
             ((10,), "int32"),      # dtype break
             ((1000,), "float32"),  # 4000 B >= capacity -> big
             ((2,), "float32")],
            1024)
        assert plan.big == [3]
        assert plan.buckets == [[0, 1], [2], [4]]
        assert plan.n_transfers() == 4

    def test_zero_capacity_is_per_leaf(self):
        plan = tree.plan_from_meta([((4,), "float32")] * 3, 0)
        assert plan.big == [0, 1, 2] and plan.buckets == []

    def test_plan_cache_hits(self):
        sig = [((41,), "float32"), ((13,), "int32")]
        before = _pv("tree_plan_cache_hits")
        p1 = tree.plan_from_meta(sig, 3331)
        assert tree.plan_from_meta(sig, 3331) is p1
        assert tree.plan_from_meta(sig, 3332) is not p1
        after = _pv("tree_plan_cache_hits")
        assert after["count"] - before["count"] == 3
        assert after["sum"] - before["sum"] == 1  # exactly one hit

    def test_resolution_chain(self, tmp_path, world):
        # (world: the coll_tuned_* gating cvars register at init)
        # cvar layer
        mca_var.set_value("tree_bucket_bytes", 12345)
        try:
            assert tree.resolve_bucket_bytes(8, 1 << 20) == 12345
        finally:
            mca_var.VARS.unset("tree_bucket_bytes")
        # dp fallback
        assert tree.resolve_bucket_bytes(8, 1 << 20) == int(
            mca_var.get("dp_bucket_bytes", 4 * 1024 * 1024))
        # dynamic-rule layer outranks both: fused capacity + per_leaf
        rules = tmp_path / "rules.conf"
        rules.write_text(
            "tree_buckets  0  0        fused  65536\n"
            "tree_buckets  0  1048576  per_leaf\n")
        mca_var.set_value("coll_tuned_use_dynamic_rules", True)
        mca_var.set_value("coll_tuned_dynamic_rules_filename",
                          str(rules))
        try:
            assert tree.resolve_bucket_bytes(8, 1024) == 65536
            assert tree.resolve_bucket_bytes(8, 2 << 20) == 0
        finally:
            mca_var.VARS.unset("coll_tuned_use_dynamic_rules")
            mca_var.VARS.unset("coll_tuned_dynamic_rules_filename")


# ---------------------------------------------------------------------------
# SPMD bitwise parity matrix: planned pass vs per-leaf reference
# ---------------------------------------------------------------------------

def _mixed_tree(n, rng):
    """Mixed dtypes, sizes straddling every bucket boundary, plus a
    single-element leaf (lead axis n for the dp-sharded passes)."""
    return {
        "a": rng.randn(n, 3, 4).astype(np.float32),
        "b": rng.randn(n, 7).astype(np.float32),
        "big": rng.randn(n, 2000).astype(np.float32),
        "i": (rng.randn(n, 5) * 100).astype(np.int32),
        "h": rng.randn(n, 11).astype(np.float16),
        "s": rng.randn(n, 1).astype(np.float32),  # scalar-per-rank
    }


class TestSpmdBitwiseParity:
    @pytest.mark.parametrize("mean", [False, True])
    @pytest.mark.parametrize("bucket", [64, 4096, 1 << 20])
    def test_tree_allreduce_bitwise(self, mean, bucket):
        n = 8
        mesh = mesh1d(n, "dp")
        grads = _mixed_tree(n, np.random.RandomState(0))

        def run(bb):
            return smap(
                lambda g: tree.tree_allreduce(g, "dp", mean=mean,
                                              bucket_bytes=bb),
                mesh, (P("dp"),), P("dp"))(grads)

        planned, perleaf = run(bucket), run(0)
        for k in grads:
            np.testing.assert_array_equal(np.asarray(planned[k]),
                                          np.asarray(perleaf[k]))

    @pytest.mark.parametrize("mean", [False, True])
    def test_tree_reduce_scatter_bitwise(self, mean):
        n = 8
        mesh = mesh1d(n, "dp")
        grads = _mixed_tree(n, np.random.RandomState(1))

        def run(bb):
            return smap(
                lambda g: tree.tree_reduce_scatter(g, "dp", mean=mean,
                                                   bucket_bytes=bb),
                mesh, (P("dp"),), P("dp"))(grads)

        planned, perleaf = run(512), run(0)
        for k in grads:
            np.testing.assert_array_equal(np.asarray(planned[k]),
                                          np.asarray(perleaf[k]))

    def test_tree_allgather_bitwise_roundtrip(self):
        """ZeRO shard/unshard round-trip: shard_like -> planned
        unshard returns the EXACT original leaves (pure data
        movement), identical between planned and per-leaf paths."""
        n = 4
        mesh = mesh1d(n, "dp")
        rng = np.random.RandomState(2)
        params = {"w": rng.randn(6, 3).astype(np.float32),  # pad path
                  "v": rng.randn(16).astype(np.float32),
                  "i": (rng.randn(5) * 9).astype(np.int32)}

        def run(bb):
            def body(p):
                shards = zero.shard_like(p, "dp")
                shapes = jax.tree.map(lambda x: x.shape, p)
                return zero.unshard_params(shards, shapes, "dp",
                                           bucket_bytes=bb)
            return smap(body, mesh, (P(),), P())(params)

        planned, perleaf = run(128), run(0)
        for k in params:
            np.testing.assert_array_equal(np.asarray(planned[k]),
                                          np.asarray(perleaf[k]))
            np.testing.assert_array_equal(np.asarray(planned[k]),
                                          params[k])

    def test_zero_grad_shard_roundtrip_bitwise(self):
        """shard_gradients -> unshard over the planned path equals the
        per-leaf path bitwise (the reduce does real float sums, so the
        two paths must fold identically, not just closely)."""
        n = 8
        mesh = mesh1d(n, "dp")
        rng = np.random.RandomState(3)
        grads = {"w": rng.randn(6, 3).astype(np.float32),
                 "v": rng.randn(15).astype(np.float32)}

        def run(bb):
            def body(g):
                sh = zero.shard_gradients(g, "dp", mean=False,
                                          bucket_bytes=bb)
                shapes = jax.tree.map(lambda x: x.shape, g)
                return zero.unshard_params(sh, shapes, "dp",
                                           bucket_bytes=bb)
            return smap(body, mesh, (P(),), P())(grads)

        planned, perleaf = run(64), run(0)
        for k in grads:
            np.testing.assert_array_equal(np.asarray(planned[k]),
                                          np.asarray(perleaf[k]))

    def test_zero_step_still_matches_dense_sgd(self):
        """The refactored zero_step (planned passes underneath) keeps
        the numerical contract of the original per-leaf version."""
        n = 4
        mesh = mesh1d(n, "dp")
        rng = np.random.RandomState(7)
        params = {"w": rng.randn(6, 3).astype(np.float32)}
        grads = rng.randn(n, 6, 3).astype(np.float32)
        lr = 0.1

        def opt_update(gs, state, ps):
            return jax.tree.map(lambda g: -lr * g, gs), state

        def body(p, g):
            new_p, _ = zero.zero_step(p, {"w": g}, None, opt_update,
                                      "dp", bucket_bytes=128)
            return new_p

        out = smap(body, mesh, (P(), P("dp")), P())(params, grads)
        ref = params["w"] - lr * grads.mean(0)
        np.testing.assert_allclose(np.asarray(out["w"]), ref,
                                   rtol=1e-5, atol=1e-6)

    def test_dp_allreduce_gradients_rides_tree_pass(self):
        """dp.allreduce_gradients is now a thin wrapper: same result,
        and the plan-cache aggregate proves the planned path traced
        (SPMD bodies run at trace time, so the plan events — not
        tree_passes, a driver-only counter — are the witness)."""
        n = 8
        mesh = mesh1d(n, "dp")
        rng = np.random.RandomState(5)
        grads = {"a": rng.randn(n, 9).astype(np.float32)}
        before = _pv("tree_plan_cache_hits")["count"]
        out = smap(
            lambda g: dp.allreduce_gradients(g, "dp", mean=True,
                                             bucket_bytes=64),
            mesh, (P("dp"),), P("dp"))(grads)
        ref = np.broadcast_to(grads["a"].mean(0, keepdims=True),
                              grads["a"].shape)
        np.testing.assert_allclose(np.asarray(out["a"]), ref, rtol=1e-5)
        assert _pv("tree_plan_cache_hits")["count"] > before


# ---------------------------------------------------------------------------
# host-driver TreeSync: overlapped families vs blocking per-leaf
# ---------------------------------------------------------------------------

class TestTreeSyncDriver:
    def test_allreduce_bitwise_vs_blocking(self, world):
        n = world.size
        rng = np.random.RandomState(0)
        grads = {"a": rng.randn(n, 40).astype(np.float32),
                 "b": rng.randn(n, 7).astype(np.float32),
                 "i": (rng.randn(n, 5) * 10).astype(np.int32),
                 "big": rng.randn(n, 3000).astype(np.float32)}
        sync = tree.TreeSync(world, mean=False, bucket_bytes=256)
        out = sync.issue(grads).wait()
        for k in grads:
            np.testing.assert_array_equal(
                np.asarray(out[k]), np.asarray(world.allreduce(grads[k])))

    def test_allreduce_mean(self, world):
        n = world.size
        x = {"a": np.ones((n, 4), np.float32) * 3}
        out = tree.TreeSync(world, mean=True).issue(x).wait()
        np.testing.assert_allclose(np.asarray(out["a"]),
                                   np.ones((n, 4)) * 3)

    def test_reduce_scatter_bitwise_vs_blocking(self, world):
        n = world.size
        rng = np.random.RandomState(1)
        grads = {"a": rng.randn(n, 40).astype(np.float32),
                 "b": rng.randn(n, 7).astype(np.float32)}
        sync = tree.TreeSync(world, mean=False, bucket_bytes=512)
        out = sync.issue_reduce_scatter(grads).wait()
        for k, g in grads.items():
            pad = (-g.shape[1]) % n
            gp = np.concatenate(
                [g, np.zeros((n, pad), g.dtype)], axis=1) if pad else g
            ref = np.asarray(world.reduce_scatter_block(gp))
            np.testing.assert_array_equal(np.asarray(out[k]), ref)

    def test_allgather_roundtrip_bitwise(self, world):
        n = world.size
        rng = np.random.RandomState(2)
        grads = {"a": rng.randn(n, 40).astype(np.float32),
                 "b": rng.randn(n, 7).astype(np.float32)}
        sync = tree.TreeSync(world, mean=False, bucket_bytes=512)
        shards = sync.issue_reduce_scatter(grads).wait()
        shapes = {k: (v.shape[1],) for k, v in grads.items()}
        full = sync.issue_allgather(shards, shapes).wait()
        for k in grads:
            c = np.asarray(shards[k]).shape[1]
            ref = np.asarray(world.allgather(np.asarray(shards[k])))
            np.testing.assert_array_equal(
                np.asarray(full[k]), ref[:, :shapes[k][0]])

    def test_scalar_leaf_rejected(self, world):
        with pytest.raises(ValueError, match="leading"):
            tree.TreeSync(world).issue({"s": np.float32(1.0)})

    def test_mismatched_lead_rejected(self, world):
        n = world.size
        with pytest.raises(ValueError, match="leading"):
            tree.TreeSync(world).issue(
                {"a": np.ones((n, 2), np.float32),
                 "b": np.ones((n + 1, 2), np.float32)})

    def test_gradient_sync_is_tree_sync(self, world):
        """dp.GradientSync kept its API as the allreduce
        specialization (mean defaults on)."""
        assert issubclass(dp.GradientSync, tree.TreeSync)
        n = world.size
        g = {"a": np.ones((n, 6), np.float32)}
        out = dp.GradientSync(world, bucket_bytes=64).issue(g).wait()
        np.testing.assert_allclose(np.asarray(out["a"]),
                                   np.ones((n, 6), np.float32))


# ---------------------------------------------------------------------------
# HostPipeline (driver-mode single controller runs stages in sequence)
# ---------------------------------------------------------------------------

class TestHostPipeline:
    def _run_all_stages(self, comm, weights, mbs, nonblocking):
        outs = None
        for s in range(comm.size):
            w = weights[s]
            r = pp.HostPipeline(
                comm, lambda x, w=w: np.tanh(np.asarray(x) @ w),
                stage=s, nonblocking=nonblocking).run(mbs)
            if s == comm.size - 1:
                outs = r
        return outs

    def test_matches_sequential_and_blocking_leg(self, world):
        n = world.size
        rng = np.random.RandomState(8)
        weights = [rng.randn(6, 6).astype(np.float32) * 0.3
                   for _ in range(n)]
        mbs = [rng.randn(2, 6).astype(np.float32) for _ in range(5)]
        nb = self._run_all_stages(world, weights, mbs, True)
        bl = self._run_all_stages(world, weights, mbs, False)
        ref = mbs
        for s in range(n):
            ref = [np.tanh(x @ weights[s]) for x in ref]
        for a, b, r in zip(nb, bl, ref):
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
            np.testing.assert_allclose(np.asarray(a), r, rtol=1e-6)

    def test_matches_compiled_pipeline(self):
        """The host schedule computes the same function as the
        compiled shard_map ppermute pipeline."""
        n, m = 4, 6
        mesh = mesh1d(n, "pp")
        rng = np.random.RandomState(9)
        ws = rng.randn(n, 6, 6).astype(np.float32) * 0.3
        x = rng.randn(m, 2, 6).astype(np.float32)

        def stage_fn(w, xb):
            return jnp.tanh(xb @ w)

        out = smap(
            lambda w, xb: pp.pipeline(stage_fn, w[0], xb,
                                      axis_name="pp")[None],
            mesh, (P("pp"), P()), P("pp"))(ws, x)
        compiled = np.asarray(out)[n - 1]

        # chain n single-stage host schedules with the same weights
        # (stage s's outputs feed stage s+1's microbatch stream)
        outs = list(x)
        for s in range(n):
            outs = pp.HostPipeline(
                _SoloComm(), lambda xb, w=ws[s]: np.asarray(
                    jnp.tanh(jnp.asarray(xb) @ w)),
                stage=0, nonblocking=True).run(outs)
        host = np.stack(outs)
        np.testing.assert_allclose(host, compiled, rtol=1e-5,
                                   atol=1e-6)

    def test_boundary_pvars_count(self, world):
        before = _pv("pp_boundary_msgs")
        weights = [np.eye(3, dtype=np.float32)] * world.size
        mbs = [np.ones((2, 3), np.float32)] * 4
        self._run_all_stages(world, weights, mbs, True)
        # every non-final stage sends one activation per microbatch
        assert _pv("pp_boundary_msgs") - before == (world.size - 1) * 4


class _SoloComm:
    """1-stage comm stub: HostPipeline degenerates to a map() — lets
    the compiled-pipeline parity test apply stages functionally."""
    size = 1
    local_comm_ranks = [0]


# ---------------------------------------------------------------------------
# real 3-process job: overlap witnessed by the hidden-seconds pvars
# ---------------------------------------------------------------------------

_JOB_APP = textwrap.dedent("""
    import os, sys, time
    sys.path.insert(0, %r)
    os.environ["XLA_FLAGS"] = (
        os.environ.get("XLA_FLAGS", "")
        + " --xla_force_host_platform_device_count=1"
    ).strip()  # 1 device/process: member ranks == stages for the pp leg
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["OMPITPU_HOST_ID"] = (
        "treejob-" + os.environ["OMPITPU_NODE_ID"])
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np
    import ompi_release_tpu as mpi
    from ompi_release_tpu.mca import pvar, var as mca_var
    from ompi_release_tpu.parallel import pp as pp_mod, tree as tree_mod
    from ompi_release_tpu.runtime.runtime import Runtime

    def _pv(name):
        p = pvar.PVARS.lookup(name)
        return float(p.read()) if p is not None else 0.0

    world = mpi.init()
    rt = Runtime.current()
    me = rt.bootstrap["process_index"]
    ln = len(world.local_comm_ranks)
    off = rt.local_rank_offset
    n = world.size

    grads = {"w%%d" %% k: np.stack(
                 [np.arange(12000, dtype=np.float32) * (off + i + k + 1)
                  for i in range(ln)])
             for k in range(6)}
    sync = tree_mod.TreeSync(world, mean=False, bucket_bytes=1 << 20)
    sync.issue(grads).wait()  # warm programs + plan + channels

    mca_var.set_value("progress_thread", True)
    world.barrier()
    h0 = _pv("nbc_hidden_seconds")
    t0 = _pv("tree_hidden_seconds")
    pending = sync.issue(grads)
    t_end = time.perf_counter() + 0.5
    a = np.ones((64, 64), np.float32)
    while time.perf_counter() < t_end:
        a = a @ a * 1e-4  # caller compute while the engine moves bytes
    out = pending.wait()
    hidden_nbc = _pv("nbc_hidden_seconds") - h0
    hidden_tree = _pv("tree_hidden_seconds") - t0
    mca_var.VARS.unset("progress_thread")

    # overlap witness: the engine itself accounted comm time as hidden
    assert hidden_nbc > 0, hidden_nbc
    assert hidden_tree > 0, hidden_tree
    # bitwise parity with the per-leaf blocking path
    for k in sorted(grads):
        ref = np.asarray(world.allreduce(grads[k]))
        np.testing.assert_array_equal(np.asarray(out[k]), ref)

    # HostPipeline across REAL process boundaries: nonblocking
    # boundary transfers, identical results to the blocking leg
    W = np.eye(32, dtype=np.float32) * 0.5
    mbs = [np.ones((8, 32), np.float32) * (k + 1) for k in range(5)]
    outs = {}
    for leg, nb in (("nb", True), ("bl", False)):
        pipe = pp_mod.HostPipeline(world, lambda x: np.asarray(x) @ W,
                                   stage=me, nonblocking=nb)
        world.barrier()
        outs[leg] = pipe.run(mbs)
        world.barrier()
    if me == n - 1:
        assert len(outs["nb"]) == 5
        for a_, b_ in zip(outs["nb"], outs["bl"]):
            np.testing.assert_array_equal(np.asarray(a_),
                                          np.asarray(b_))
        for k, a_ in enumerate(outs["nb"]):
            ref = np.ones((8, 32), np.float32) * (k + 1)
            for _ in range(n):
                ref = ref @ W
            np.testing.assert_array_equal(np.asarray(a_), ref)
    print("TREE-JOB-OK %%d hidden=%%.4f" %% (me, hidden_nbc))
    world.barrier()
    mpi.finalize()
""" % REPO)


class TestTreeJob:
    def test_overlapped_tree_pass_job(self, tmp_path, capfd):
        """3 processes: the planned whole-tree pass overlaps comm
        under the progress thread (both hidden-seconds pvars > 0),
        bitwise parity holds, and HostPipeline boundary transfers run
        nonblocking across real process boundaries."""
        app = tmp_path / "tree_job.py"
        app.write_text(_JOB_APP)
        job = Job(3, [sys.executable, str(app)], [],
                  heartbeat_s=0.5, miss_limit=8)
        rc = job.run(timeout_s=240)
        out = capfd.readouterr()
        assert rc == 0, out.out + out.err
        assert job.job_state.visited(JobState.TERMINATED)
        for pidx in range(3):
            assert f"TREE-JOB-OK {pidx}" in out.out


# ---------------------------------------------------------------------------
# bench-gate direction for the new suite's lines
# ---------------------------------------------------------------------------

def test_gate_directions_for_tree_lines():
    from ompi_release_tpu.tools import tpu_bench_gate as gate

    assert gate._direction("frac_hidden", "tree_allreduce_hidden_frac") == 1
    assert gate._direction("x_vs_blocking", "tree_planned_pass_speedup") == 1
    assert gate._direction(None, "tree_pp_overlap_speedup") == 1
    assert gate.gateable({"metric": "tree_overlap_speedup",
                          "value": 4.2, "unit": "x_vs_blocking"})
