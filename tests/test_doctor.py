"""Cross-rank distributed tracing, hang diagnosis, and the tpu-doctor
flight recorder.

Three layers under test:

- unit: deterministic flow ids, journal flow round-trip, the doctor's
  merge/clock-offset math and skew report on SYNTHETIC journals (known
  offsets, known flows — the arithmetic is checked exactly);
- in-process: the stall watchdog (arm -> timeout -> postmortem naming
  the stuck op), SIGUSR1 dumps, the OOB clock-offset estimator against
  a live HNP responder, and the tpu-server journal RPC;
- job: a REAL 3-process tpurun job with one DELAYED rank — the
  watchdog's postmortem must name the stuck collective and the ranks
  that had not arrived, and the per-rank journal dumps must merge into
  ONE Perfetto trace with clock-corrected timestamps and at least one
  cross-rank send->recv flow arrow (the acceptance criterion).
"""

import json
import os
import sys
import time

import numpy as np
import pytest

from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.obs import doctor as doctor_mod
from ompi_release_tpu.obs.journal import flow_id
from ompi_release_tpu.runtime.state import JobState
from ompi_release_tpu.tools.tpurun import Job
from ompi_release_tpu.utils.errors import ErrorCode, MPIError

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# unit: flow ids + merge/clock math on synthetic journals
# ---------------------------------------------------------------------------

def _span(op, layer, t, dt, **kw):
    d = {"seq": kw.pop("seq", 0), "op": op, "layer": layer, "t": t,
         "dt": dt, "bytes": kw.pop("bytes", 0),
         "peer": kw.pop("peer", -1), "comm": kw.pop("comm", -1)}
    d.update(kw)
    return d


def _dump(pidx, offset, spans, rank_offset=None, local_size=2):
    return {"meta": {"pidx": pidx, "pid": 1000 + pidx,
                     "rank_offset": (rank_offset if rank_offset
                                     is not None else pidx * local_size),
                     "local_size": local_size,
                     "clock_offset_s": offset, "clock_rtt_s": 1e-4},
            "spans": spans}


class TestFlowIds:
    def test_deterministic_and_distinct(self):
        a = flow_id("hier", 7, 3, 0, 1, 0)
        assert a == flow_id("hier", 7, 3, 0, 1, 0)
        assert a != flow_id("hier", 7, 3, 1, 0, 0)
        assert a != flow_id("hier", 7, 4, 0, 1, 0)
        assert flow_id("p2p", 0, 1) != flow_id("win", 0, 1)
        assert all(flow_id(x) > 0 for x in range(64))

    def test_journal_carries_flow(self):
        from ompi_release_tpu.obs.journal import Journal

        j = Journal(8)
        fid = flow_id("t", 1)
        j.record("send", "wire", 0.0, 1e-3, flow=fid, flow_side="s")
        sp = j.snapshot()[-1]
        assert sp.flow == fid and sp.flow_side == "s"
        d = sp.asdict()
        assert d["flow"] == fid and d["fs"] == "s"
        # flowless spans stay compact
        j.record("x", "wire", 0.0, 0.0)
        assert "flow" not in j.snapshot()[-1].asdict()


class TestMerge:
    def _two_rank_dumps(self):
        fid = flow_id("p2p", 0, 42)
        # p0's clock reads 10.0 at the moment p1's clock reads 12.0:
        # offsets map both into the HNP timebase (p0 +0.5, p1 -1.5)
        d0 = _dump(0, 0.5, [
            _span("wire_send", "wire", 10.0, 0.010, peer=2, comm=1,
                  flow=fid, fs="s", bytes=4096),
            _span("allreduce", "coll", 10.0, 0.050, comm=1),
        ])
        d1 = _dump(1, -1.5, [
            _span("wire_recv", "wire", 12.1, 0.005, peer=0, comm=1,
                  flow=fid, fs="t", bytes=4096),
            _span("allreduce", "coll", 12.2, 0.050, comm=1),
        ])
        return d0, d1

    def test_clock_offsets_applied(self):
        d0, d1 = self._two_rank_dumps()
        trace = doctor_mod.merge([d0, d1])
        evs = [e for e in trace["traceEvents"] if e.get("ph") == "X"]
        send = next(e for e in evs if e["name"] == "wire_send")
        recv = next(e for e in evs if e["name"] == "wire_recv")
        # corrected: send at (10.0 + 0.5) s, recv at (12.1 - 1.5) s
        assert send["ts"] == pytest.approx(10.5e6)
        assert recv["ts"] == pytest.approx(10.6e6)
        # and the recv lands AFTER the send in the merged timebase —
        # the whole point of the offset correction
        assert recv["ts"] > send["ts"]

    def test_cross_rank_flow_events(self):
        d0, d1 = self._two_rank_dumps()
        pairs = doctor_mod.flow_pairs([d0, d1])
        assert len(pairs) == 1
        p = pairs[0]
        assert p["cross_process"] and p["src_pidx"] == 0 \
            and p["dst_pidx"] == 1
        # recv starts at 12.1 - 1.5 = 10.6; send ends at 10.5 + 0.01
        assert p["latency_s"] == pytest.approx(0.090, abs=1e-9)
        trace = doctor_mod.merge([d0, d1])
        flows = [e for e in trace["traceEvents"]
                 if e.get("cat") == "flow"]
        assert {e["ph"] for e in flows} == {"s", "f"}
        s = next(e for e in flows if e["ph"] == "s")
        f = next(e for e in flows if e["ph"] == "f")
        assert s["id"] == f["id"]
        assert s["pid"] == 0 and f["pid"] == 1
        assert trace["otherData"]["cross_process_flows"] == 1

    def test_unmatched_flow_is_not_paired(self):
        d0, d1 = self._two_rank_dumps()
        d1["spans"][0]["flow"] = flow_id("other")  # break the match
        assert doctor_mod.flow_pairs([d0, d1]) == []

    def test_skew_report_names_slowest_rank(self):
        # two allreduce rounds; p1 arrives late in both (by 0.1/0.3 s
        # AFTER offset correction — raw timestamps alone would blame
        # the wrong rank in round 1)
        d0 = _dump(0, 0.0, [
            _span("allreduce", "coll", 1.0, 0.01, comm=1),
            _span("allreduce", "coll", 2.0, 0.01, comm=1),
        ])
        d1 = _dump(1, -2.0, [
            _span("allreduce", "coll", 3.1, 0.01, comm=1),
            _span("allreduce", "coll", 4.3, 0.01, comm=1),
        ])
        text, data = doctor_mod.skew_report([d0, d1])
        rounds = data["rounds"]
        assert len(rounds) == 2
        assert all(r["slowest_pidx"] == 1 for r in rounds)
        assert rounds[0]["spread_s"] == pytest.approx(0.1)
        assert rounds[1]["spread_s"] == pytest.approx(0.3)
        assert data["critical_path"] == {1: 2}
        assert "proc 1" in text and "ranks 2..3" in text

    def test_load_dir_reads_postmortem_tails(self, tmp_path):
        pm = {"reason": "stall",
              "rank": {"pidx": 3, "rank_offset": 6, "local_size": 2,
                       "pid": 99},
              "clock": {"offset_s": 0.25, "rtt_s": 1e-4},
              "journal_tail": [_span("x", "wire", 1.0, 0.0)]}
        (tmp_path / "postmortem-p3-99-stall-1.json").write_text(
            json.dumps(pm))
        dumps = doctor_mod.load_dir(str(tmp_path))
        assert len(dumps) == 1
        assert dumps[0]["meta"]["pidx"] == 3
        assert dumps[0]["meta"]["clock_offset_s"] == 0.25
        with pytest.raises(FileNotFoundError):
            doctor_mod.load_dir(str(tmp_path / "nope"))

    def test_load_dir_keeps_newest_postmortem_per_rank(self, tmp_path):
        """A hung rank writes SEVERAL postmortems (one per stalled
        wait + SIGUSR1 pokes) with overlapping journal tails — only
        the newest per pidx may enter the merge, or that rank's spans
        render twice and the skew alignment desyncs."""
        def pm(pidx, t_unix, tail_len):
            return {"reason": "stall", "time_unix": t_unix,
                    "rank": {"pidx": pidx, "rank_offset": pidx,
                             "local_size": 1, "pid": 100 + pidx},
                    "clock": {"offset_s": 0.0, "rtt_s": 1e-4},
                    "journal_tail": [
                        _span("x", "wire", 1.0 + i, 0.0, seq=i)
                        for i in range(tail_len)]}
        (tmp_path / "postmortem-p0-100-stall-1.json").write_text(
            json.dumps(pm(0, 1000.0, 2)))
        (tmp_path / "postmortem-p0-100-sigusr1-2.json").write_text(
            json.dumps(pm(0, 2000.0, 5)))
        (tmp_path / "postmortem-p1-101-stall-1.json").write_text(
            json.dumps(pm(1, 1500.0, 3)))
        dumps = doctor_mod.load_dir(str(tmp_path))
        assert [d["meta"]["pidx"] for d in dumps] == [0, 1]
        assert len(dumps[0]["spans"]) == 5  # the newer p0 dump won
        assert len(dumps[1]["spans"]) == 3

    def test_load_dir_merges_postmortems_for_unfinalized_ranks(
            self, tmp_path):
        """Mixed directory — healthy ranks finalized (journal-p*.json)
        while the hung rank was killed leaving only postmortems: the
        merge must include the hung rank's tail (it is exactly the
        rank the operator is diagnosing), and a finalize journal must
        supersede that rank's own postmortem tails."""
        (tmp_path / "journal-p0.json").write_text(json.dumps(
            _dump(0, 0.0, [_span("a", "wire", 1.0, 0.0)])))
        pm = {"reason": "stall", "time_unix": 5.0,
              "rank": {"pidx": 1, "rank_offset": 1, "local_size": 1,
                       "pid": 101},
              "clock": {"offset_s": 0.1, "rtt_s": 1e-4},
              "journal_tail": [_span("b", "wire", 2.0, 0.0),
                               _span("c", "wire", 3.0, 0.0)]}
        (tmp_path / "postmortem-p1-101-stall-1.json").write_text(
            json.dumps(pm))
        # p0 also dumped a postmortem earlier: superseded by journal
        pm0 = dict(pm, rank={"pidx": 0, "rank_offset": 0,
                             "local_size": 1, "pid": 100})
        (tmp_path / "postmortem-p0-100-stall-1.json").write_text(
            json.dumps(pm0))
        dumps = doctor_mod.load_dir(str(tmp_path))
        assert [d["meta"]["pidx"] for d in dumps] == [0, 1]
        assert len(dumps[0]["spans"]) == 1  # journal, not the tail
        assert len(dumps[1]["spans"]) == 2  # the hung rank's tail
        assert dumps[1]["meta"]["clock_offset_s"] == 0.1


# ---------------------------------------------------------------------------
# in-process: watchdog, SIGUSR1, clock estimator, journal RPC
# ---------------------------------------------------------------------------

@pytest.fixture
def obs_on(tmp_path):
    """obs + watchdog enabled with a short stall timeout, postmortems
    into tmp_path; fully restored afterwards."""
    import ompi_release_tpu.obs as obs
    from ompi_release_tpu.obs import watchdog as wd

    mca_var.set_value("obs_postmortem_dir", str(tmp_path))
    mca_var.set_value("obs_stall_timeout", "0.4")
    obs.enable()
    try:
        yield wd
    finally:
        obs.disable()
        mca_var.VARS.unset("obs_stall_timeout")
        mca_var.VARS.unset("obs_postmortem_dir")


def _wait_for(predicate, timeout=10.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        got = predicate()
        if got:
            return got
        time.sleep(0.05)
    return None


class TestWatchdog:
    def _postmortems(self, d):
        return sorted(
            os.path.join(d, f) for f in os.listdir(d)
            if f.startswith("postmortem-") and f.endswith(".json")
        )

    def test_stall_dumps_postmortem_naming_the_wait(self, obs_on,
                                                    tmp_path):
        wd = obs_on
        assert wd.enabled
        tok = wd.arm("unit_allreduce", comm_id=5,
                     info=lambda: {"awaiting_procs": [1],
                                   "awaiting_ranks": [2, 3]})
        try:
            path = _wait_for(lambda: self._postmortems(str(tmp_path)))
        finally:
            wd.disarm(tok)
        assert path, "watchdog never fired within the timeout"
        pm = json.load(open(path[0]))
        assert pm["reason"] == "stall"
        st = pm["stalled"][0]
        assert st["op"] == "unit_allreduce" and st["comm"] == 5
        assert st["waited_s"] >= 0.4
        assert st["info"]["awaiting_ranks"] == [2, 3]
        # the recorder carries the debugger's queue dump + pvars +
        # per-thread stacks (faulthandler)
        assert isinstance(pm["msg_queues"], list)
        assert isinstance(pm["pvars"], dict)
        assert any("test_doctor" in ln or "Thread" in ln
                   for ln in pm["thread_stacks"])

    def test_disarm_prevents_dump(self, obs_on, tmp_path):
        wd = obs_on
        tok = wd.arm("quick_wait")
        wd.disarm(tok)
        time.sleep(0.8)
        assert not self._postmortems(str(tmp_path))

    def test_off_cost_is_one_attr_check(self):
        from ompi_release_tpu.obs import watchdog as wd

        # with obs disabled the gate is False and no token table work
        # happens at the call sites (they check .enabled first)
        assert wd.enabled is False

    def test_sigusr1_dumps(self, obs_on, tmp_path):
        import signal

        wd = obs_on
        prev = signal.getsignal(signal.SIGUSR1)
        app_calls = []
        try:
            # an application handler installed BEFORE obs: it must
            # still run after the dump (chained, not clobbered)
            signal.signal(signal.SIGUSR1,
                          lambda s, f: app_calls.append(s))
            wd._signals_installed = False
            wd.install_signal_handlers()
            os.kill(os.getpid(), signal.SIGUSR1)
            path = _wait_for(
                lambda: [p for p in self._postmortems(str(tmp_path))
                         if "sigusr1" in p])
            assert path, "SIGUSR1 produced no postmortem"
            pm = json.load(open(path[0]))
            assert pm["reason"] == "sigusr1"
            assert isinstance(pm["journal_tail"], list)
            assert app_calls == [signal.SIGUSR1]
        finally:
            signal.signal(signal.SIGUSR1, prev)
            wd._signals_installed = False


class TestClockSync:
    def test_estimator_against_live_responder(self):
        from ompi_release_tpu.runtime.coordinator import (
            HnpCoordinator, WorkerAgent)

        hnp = HnpCoordinator(2)
        agent = None
        try:
            hnp.start_clock_responder()
            agent = WorkerAgent(1, "127.0.0.1", hnp.port)
            off, rtt = agent.clock_sync(rounds=4)
            # same process, same perf_counter: the true offset is ~0
            # and must be bounded by the observed round trip
            assert rtt > 0
            assert abs(off) <= max(rtt, 0.05)
        finally:
            if agent is not None:
                agent.ep.close()
            hnp.shutdown()

    def test_estimator_raises_without_responder(self):
        from ompi_release_tpu.runtime.coordinator import (
            HnpCoordinator, WorkerAgent)

        hnp = HnpCoordinator(2)
        agent = None
        try:
            agent = WorkerAgent(1, "127.0.0.1", hnp.port)
            with pytest.raises(MPIError):
                agent.clock_sync(rounds=1, timeout_ms=300)
        finally:
            if agent is not None:
                agent.ep.close()
            hnp.shutdown()


class TestJournalRpc:
    def test_tpu_server_serves_rank_dump(self):
        from ompi_release_tpu.tools.tpu_server import (NameClient,
                                                       NameServer)

        srv = NameServer()
        client = None
        try:
            client = NameClient("127.0.0.1", srv.port)
            dump = client.journal()
            assert "meta" in dump and "spans" in dump
            assert isinstance(dump["spans"], list)
            # and the metrics RPC still answers on the same table
            assert "ompitpu_" in client.metrics()
        finally:
            if client is not None:
                client.close()
            srv.shutdown()


# ---------------------------------------------------------------------------
# satellites riding this PR
# ---------------------------------------------------------------------------

class _SpanningComm:
    name = "fake_spanning"
    spans_processes = True


class _LocalComm:
    name = "fake_local"
    spans_processes = False


class TestSatellites:
    def test_shared_pointer_refused_on_spanning_comm(self, tmp_path):
        from ompi_release_tpu.io.file import File

        f = File(_SpanningComm(), str(tmp_path / "shared.bin"))
        try:
            for call in (lambda: f.write_shared(
                             np.arange(4, dtype=np.uint8)),
                         lambda: f.read_shared(4),
                         lambda: f.write_ordered([np.arange(4)]),
                         lambda: f.read_ordered([2, 2])):
                with pytest.raises(MPIError) as ei:
                    call()
                assert ei.value.code == ErrorCode.ERR_NOT_AVAILABLE
                assert "spans" in str(ei.value)
        finally:
            f.close()

    def test_shared_pointer_still_works_locally(self, tmp_path):
        from ompi_release_tpu.io.file import File

        f = File(_LocalComm(), str(tmp_path / "local.bin"))
        try:
            f.set_view(etype=np.int32)
            f.write_ordered([np.arange(3, dtype=np.int32),
                             np.arange(3, 6, dtype=np.int32)])
            f._shared_ptr = 0  # rewind the shared pointer
            parts = f.read_ordered([3, 3])
            np.testing.assert_array_equal(parts[0], [0, 1, 2])
            np.testing.assert_array_equal(parts[1], [3, 4, 5])
            assert f.write_shared(np.arange(2, dtype=np.int32)) == 2
        finally:
            f.close()

    def test_checkpointer_refuses_spanning_comm(self, tmp_path):
        from ompi_release_tpu.ft.checkpoint import Checkpointer

        with pytest.raises(MPIError) as ei:
            Checkpointer(str(tmp_path / "ck"), comm=_SpanningComm())
        assert ei.value.code == ErrorCode.ERR_NOT_AVAILABLE
        assert "spans controller processes" in str(ei.value)
        # no comm / local comm still constructs, and a spanning comm
        # with an explicitly-declared per-process directory (the one
        # safe shape — the recovery tests' rank{pidx} layout) is let
        # through
        Checkpointer(str(tmp_path / "ck2"))
        Checkpointer(str(tmp_path / "ck3"), comm=_LocalComm())
        Checkpointer(str(tmp_path / "ck4-rank0"), comm=_SpanningComm(),
                     private_dir=True)

    def test_request_rma_wait_completes_via_flush(self):
        """MPI 3.1: wait() ALONE completes request-based RMA inside a
        passive epoch (was: 'wait() would deadlock')."""
        import ompi_release_tpu as mpi
        from ompi_release_tpu.osc.window import win_allocate

        world = mpi.init()
        win = win_allocate(world, (4,), np.float32)
        try:
            win.lock(2)
            r1 = win.rput(np.full(4, 2.0, np.float32), 2)
            assert not r1.is_complete
            r1.wait()  # no flush(), no unlock: wait alone completes
            assert r1.is_complete
            r2 = win.raccumulate(np.full(4, 0.5, np.float32), 2)
            r2.wait()
            g = win.rget(2)
            g.wait()
            np.testing.assert_array_equal(np.asarray(g.value),
                                          np.full(4, 2.5))
            win.unlock(2)
        finally:
            win.free()


# ---------------------------------------------------------------------------
# the real thing: a 3-process tpurun job with one delayed rank
# ---------------------------------------------------------------------------

_HANG_APP = r'''
import os, sys, time
sys.path.insert(0, %(repo)r)
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
os.environ["JAX_PLATFORMS"] = "cpu"
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
import ompi_release_tpu as mpi
from ompi_release_tpu.runtime.runtime import Runtime
from ompi_release_tpu import obs
from ompi_release_tpu.obs import watchdog as wd

world = mpi.init()          # 3 procs x 2 devices
rt = Runtime.current()
me = rt.bootstrap["process_index"]
assert obs.enabled and wd.enabled, (obs.enabled, wd.enabled)

if me == 1:
    time.sleep(%(delay)s)   # the straggler every other rank waits on

x = np.stack([np.arange(256, dtype=np.int32) * (rt.local_rank_offset
                                                + i + 1)
              for i in range(2)])
got = np.asarray(world.allreduce(x))
want = sum(np.arange(256, dtype=np.int32) * (r + 1)
           for r in range(world.size))
np.testing.assert_array_equal(got[0], want)

# one cross-process p2p pair so the wire-level (envelope-seq) flow is
# exercised alongside the hier round flows
if me == 0:
    world.send(np.arange(512, dtype=np.float32), 3, tag=7, rank=1)
elif me == 1:
    v, st = world.recv(source=1, tag=7, rank=3)
    np.testing.assert_array_equal(np.asarray(v),
                                  np.arange(512, dtype=np.float32))
world.barrier()
print(f"HANG-APP-OK {me}")
mpi.finalize()              # journal dump (obs_dump_dir) happens here
'''


@pytest.mark.parametrize("delay", [4.0])
def test_hang_injection_postmortem_and_merged_flow_trace(tmp_path,
                                                         capfd, delay):
    """The acceptance run: rank-span p1 sleeps before the allreduce;
    the watchdog on p0/p2 must dump a postmortem naming the stuck
    collective and the absent ranks WHILE the job is still hung, the
    job must then complete cleanly, and tpu-doctor must merge the
    three finalize-time journals into one Perfetto trace with
    clock-offset metadata and cross-rank send->recv flow arrows."""
    pm_dir = tmp_path / "pm"
    dump_dir = tmp_path / "dumps"
    app = tmp_path / "hang_app.py"
    app.write_text(_HANG_APP % {"repo": REPO, "delay": delay})
    job = Job(3, [sys.executable, str(app)],
              [("obs_enable", "1"),
               ("obs_stall_timeout", "1.2"),
               ("obs_postmortem_dir", str(pm_dir)),
               ("obs_dump_dir", str(dump_dir))],
              heartbeat_s=0.5, miss_limit=10)
    rc = job.run(timeout_s=180)
    out = capfd.readouterr()
    assert rc == 0, out.out + out.err
    assert job.job_state.visited(JobState.TERMINATED)
    for me in (0, 1, 2):
        assert f"HANG-APP-OK {me}" in out.out

    # -- postmortem: the hang left an artifact naming the wait --------
    pms = sorted(pm_dir.glob("postmortem-*-stall-*.json"))
    assert pms, f"no stall postmortem in {pm_dir}"
    named_stuck = False
    for p in pms:
        pm = json.loads(p.read_text())
        stalled_ops = [s["op"] for s in pm.get("stalled", [])]
        infos = [s.get("info") or {} for s in pm.get("stalled", [])]
        awaiting = [i for i in infos
                    if 1 in (i.get("awaiting_procs") or [])
                    or {2, 3} & set(i.get("awaiting_ranks") or [])]
        if "allreduce" in stalled_ops and awaiting:
            named_stuck = True
            # the hier round table tells the same story
            rounds = pm.get("hier_rounds", {})
            assert any(st.get("op") == "allreduce"
                       for st in rounds.values()), rounds
    assert named_stuck, (
        "no postmortem named the stuck allreduce + absent ranks: "
        + "; ".join(str(json.loads(p.read_text()).get("stalled"))
                    for p in pms))

    # -- merged trace: >= 2 ranks, clock offsets, cross-rank flows ----
    dumps = doctor_mod.load_dir(str(dump_dir))
    assert len(dumps) == 3
    for d in dumps:
        assert d["meta"]["clock_offset_s"] is not None, d["meta"]
        assert d["spans"], f"rank {d['meta']['pidx']} journal is empty"
    pairs = doctor_mod.flow_pairs(dumps)
    cross = [p for p in pairs if p["cross_process"]]
    assert cross, "no cross-rank flow pair in the merged journals"
    # the p2p send->recv pair specifically (wire envelope seq flow)
    wire = [p for p in cross if p["src"]["op"] == "wire_send"
            and p["dst"]["op"] == "wire_recv"]
    assert wire, f"no wire send->recv flow among {len(cross)} flows"
    trace = doctor_mod.merge(dumps)
    od = trace["otherData"]
    assert od["processes"] == 3 and od["cross_process_flows"] >= 1
    flow_evs = [e for e in trace["traceEvents"]
                if e.get("cat") == "flow"]
    assert {e["ph"] for e in flow_evs} >= {"s", "f"}
    # flow endpoints sit on DIFFERENT pids (the cross-rank arrow)
    by_id = {}
    for e in flow_evs:
        by_id.setdefault(e["id"], set()).add(e["pid"])
    assert any(len(pids) == 2 for pids in by_id.values())

    # -- skew report: the delayed rank is the critical path -----------
    text, data = doctor_mod.skew_report(dumps)
    ar = [r for r in data["rounds"] if r["op"] == "allreduce"]
    assert ar, f"no allreduce round in report: {text}"
    assert ar[0]["slowest_pidx"] == 1, (text, ar)
    assert ar[0]["spread_s"] > delay / 2
    assert "proc 1" in text
