"""Error management policies — the ``orte/mca/errmgr`` analogue.

The reference installs a per-role policy component reacting to error
states posted on the state machine (``errmgr_default_orted.c:118-121``);
the TPU-native response to an unsurvivable failure is job-level
restart-from-checkpoint (SURVEY §5: ICI failures are not survivable
in-place), which ``run_with_restart`` implements: run the step loop,
checkpoint on cadence, and on failure restore the last committed
checkpoint and continue.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ..mca import pvar
from ..utils import output
from .checkpoint import Checkpointer
from .sensor import InjectedFault

_log = output.stream("errmgr")
_restarts = pvar.counter("errmgr_restarts", "restart-from-checkpoint events")


class ErrMgr:
    """Callback registry per error class (policy component analogue)."""

    def __init__(self) -> None:
        self._handlers: Dict[type, List[Callable]] = {}

    def register(self, exc_type: type, handler: Callable) -> None:
        self._handlers.setdefault(exc_type, []).append(handler)

    def handle(self, exc: BaseException) -> bool:
        """Run matching handlers; True if any claimed the error."""
        claimed = False
        for t, hs in self._handlers.items():
            if isinstance(exc, t):
                for h in hs:
                    h(exc)
                    claimed = True
        return claimed


def run_with_restart(
    step_fn: Callable[[int, Any], Any],
    init_state: Any,
    *,
    num_steps: int,
    checkpointer: Checkpointer,
    checkpoint_every: int = 10,
    max_restarts: int = 5,
    recoverable: Tuple[type, ...] = (InjectedFault,),
) -> Tuple[Any, Dict]:
    """Drive ``state = step_fn(step, state)`` for num_steps with
    checkpoint/restart fault tolerance.

    On a recoverable failure: restore the last committed checkpoint
    and resume from its step (deterministic replay of the collective
    schedule — SURVEY §5's recovery model). Non-recoverable exceptions
    propagate.
    """
    stats = {"restarts": 0, "failures": []}
    start = 0
    latest = checkpointer.latest_step()
    state = init_state
    if latest is not None:
        state = checkpointer.restore(init_state, latest)
        start = latest + 1
        _log.verbose(1, f"resuming from checkpoint step {latest}")

    step = start
    while step < num_steps:
        try:
            state = step_fn(step, state)
            if step % checkpoint_every == 0:
                checkpointer.save(step, state)
            step += 1
        except recoverable as e:
            stats["restarts"] += 1
            stats["failures"].append((step, repr(e)))
            _restarts.add()
            if stats["restarts"] > max_restarts:
                raise
            checkpointer.abort()  # in-flight snapshot is suspect
            latest = checkpointer.latest_step()
            if latest is None:
                state = init_state
                step = 0
            else:
                state = checkpointer.restore(init_state, latest)
                step = latest + 1
            _log.verbose(
                1, f"restarted after failure at step {stats['failures'][-1][0]}"
                   f" -> resume at {step}"
            )
    checkpointer.wait()
    return state, stats
