"""Failure detection + fault injection — the ``orte/mca/sensor``
analogue.

- Heartbeat: periodic beats with a miss limit; missing beats fires the
  failure callback (``sensor_heartbeat.c:61,78`` check_heartbeat).
- FtTester: probabilistic fault injection for exercising errmgr paths
  (``sensor_ft_tester.c:67-106`` random kills, here raised as
  InjectedFault so tests/restart loops can exercise recovery).
- resource_usage: /proc vmsize/rss sampling (``pstat_linux_module``).
"""

from __future__ import annotations

import os
import random
import threading
import time
from typing import Callable, Dict, Optional

from ..mca import var as mca_var
from ..utils import output

_log = output.stream("sensor")


class InjectedFault(RuntimeError):
    """Raised by FtTester to simulate a process failure."""


class Heartbeat:
    """Monitor thread: the watched party calls beat(); if more than
    ``miss_limit`` intervals pass without one, ``on_failure`` fires."""

    def __init__(self, interval_s: float = 1.0, miss_limit: int = 3,
                 on_failure: Optional[Callable[[], None]] = None) -> None:
        self.interval_s = interval_s
        self.miss_limit = miss_limit
        self.on_failure = on_failure
        self._last = time.monotonic()
        self._stop = threading.Event()
        self._failed = False
        self._thread: Optional[threading.Thread] = None

    def beat(self) -> None:
        self._last = time.monotonic()

    @property
    def failed(self) -> bool:
        return self._failed

    def _run(self) -> None:
        while not self._stop.wait(self.interval_s / 2):
            silent = time.monotonic() - self._last
            if silent > self.interval_s * self.miss_limit:
                self._failed = True
                _log.verbose(
                    1, f"heartbeat missed for {silent:.2f}s -> failure"
                )
                if self.on_failure is not None:
                    self.on_failure()
                return

    def start(self) -> "Heartbeat":
        # the clock starts when monitoring starts — construction-to-
        # start delay must not count as missed beats
        self._last = time.monotonic()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=2)


class FtTester:
    """Random fault injector (``sensor/ft_tester``): call maybe_fail()
    at interesting points; with probability ``fail_prob`` it raises."""

    def __init__(self, fail_prob: Optional[float] = None,
                 seed: Optional[int] = None) -> None:
        if fail_prob is None:
            fail_prob = float(mca_var.get("sensor_ft_tester_prob", 0.0))
        self.fail_prob = fail_prob
        self._rng = random.Random(seed)
        self.injected = 0

    def maybe_fail(self, where: str = "") -> None:
        if self._rng.random() < self.fail_prob:
            self.injected += 1
            _log.verbose(1, f"ft_tester: injecting fault at {where}")
            raise InjectedFault(f"injected fault at {where or 'unknown'}")


def register_vars() -> None:
    mca_var.register(
        "sensor_ft_tester_prob", "float", 0.0,
        "Probability of injected failure per maybe_fail() call "
        "(sensor_ft_tester.c analogue)",
    )
    mca_var.register(
        "sensor_heartbeat_interval", "float", 1.0,
        "Heartbeat period in seconds",
    )


def resource_usage() -> Dict[str, int]:
    """vmsize/rss in bytes from /proc/self/status (pstat/linux)."""
    out = {"vmsize": 0, "rss": 0}
    try:
        with open("/proc/self/status") as f:
            for line in f:
                if line.startswith("VmSize:"):
                    out["vmsize"] = int(line.split()[1]) * 1024
                elif line.startswith("VmRSS:"):
                    out["rss"] = int(line.split()[1]) * 1024
    except OSError:
        pass
    return out
