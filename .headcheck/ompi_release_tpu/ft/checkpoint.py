"""Checkpoint/restart — drain-then-snapshot sharded checkpoints.

The reference stack maps as (SURVEY §5 checkpoint/resume):
- crcp/bkmrk "drain in-flight messages" -> quiesce(): barrier + flush
  outstanding PML sends and RMA epochs, then block on async dispatch.
- crs image capture -> sharded pytree save (io.sharded), async so the
  next step's compute overlaps the write.
- snapc/sstore orchestration/storage -> step-numbered checkpoint dirs
  with a committed marker (a checkpoint is only valid once its marker
  lands, so a crash mid-write is never resumed from), keep-last-N GC.
"""

from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional

import jax

from ..io import sharded
from ..mca import pvar
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("ft")
_ckpt_count = pvar.counter("ft_checkpoints_taken", "checkpoints committed")


class Checkpointer:
    def __init__(self, directory: str, *, keep: int = 3,
                 comm=None) -> None:
        self.directory = directory
        self.keep = keep
        self.comm = comm
        self._pending: List = []
        os.makedirs(directory, exist_ok=True)

    # -- quiescence (crcp/bkmrk analogue) ----------------------------------
    def quiesce(self) -> None:
        """Drain communication before snapshotting: no in-flight sends,
        closed RMA epochs, device queues flushed."""
        if self.comm is not None:
            pml = getattr(self.comm, "_pml", None)
            if pml is not None:
                unex, posted = pml.pending_counts()
                if posted or unex:
                    raise MPIError(
                        ErrorCode.ERR_PENDING,
                        f"checkpoint with in-flight p2p state "
                        f"({unex} undelivered sends, {posted} posted "
                        "receives) — drain or cancel them first; host "
                        "queues are not part of the snapshot",
                    )
            self.comm.barrier()

    # -- snapshot ----------------------------------------------------------
    def _step_dir(self, step: int) -> str:
        return os.path.join(self.directory, f"step_{step:010d}")

    def save(self, step: int, state: Any, *, async_: bool = True,
             extra_meta: Optional[Dict] = None) -> None:
        """Snapshot ``state`` (pytree) for ``step``."""
        from ..utils import memchecker

        self.wait()  # one checkpoint in flight at a time
        self.quiesce()
        # a snapshot must not contain donated/consumed buffers — the
        # memchecker liveness walk catches use-after-donation HERE,
        # with provenance, instead of deep inside serialization
        memchecker.assert_all_alive(state, what="checkpoint state")
        d = self._step_dir(step)
        tmp = d + ".tmp"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        meta = {"step": step, "time": time.time()}
        meta.update(extra_meta or {})
        with open(os.path.join(tmp, "meta.json"), "w") as f:
            json.dump(meta, f)
        futs = sharded.save_pytree(tmp, state, async_=True) or []

        def commit() -> None:
            if os.path.exists(d):
                shutil.rmtree(d)
            os.rename(tmp, d)
            with open(os.path.join(d, "COMMITTED"), "w") as f:
                f.write(str(step))
            _ckpt_count.add()
            _log.verbose(1, f"checkpoint step {step} committed -> {d}")
            self._gc()

        if async_:
            self._pending = [(futs, commit)]
        else:
            for fu in futs:
                fu.result()
            commit()

    def wait(self) -> None:
        """Block until the in-flight async checkpoint has committed."""
        for futs, commit in self._pending:
            for fu in futs:
                fu.result()
            commit()
        self._pending = []

    def abort(self) -> None:
        """Discard the in-flight checkpoint WITHOUT committing: cancel
        what hasn't started, join what has (so no orphan writer races a
        replayed save into the same tmp dir), and sweep stale tmp
        directories. Used by restart paths where the snapshot taken
        around a failure is suspect."""
        for futs, _commit in self._pending:
            for fu in futs:
                fu.cancel()
            for fu in futs:
                try:
                    fu.result()
                except Exception:
                    pass
        self._pending = []
        for name in os.listdir(self.directory):
            if name.endswith(".tmp"):
                shutil.rmtree(os.path.join(self.directory, name),
                              ignore_errors=True)

    # -- restore -----------------------------------------------------------
    def steps(self) -> List[int]:
        out = []
        for name in os.listdir(self.directory):
            d = os.path.join(self.directory, name)
            if (name.startswith("step_")
                    and os.path.exists(os.path.join(d, "COMMITTED"))):
                out.append(int(name[5:]))
        return sorted(out)

    def latest_step(self) -> Optional[int]:
        s = self.steps()
        return s[-1] if s else None

    def restore(self, like: Any, step: Optional[int] = None) -> Any:
        """Load the checkpoint for ``step`` (default: latest) into the
        structure of ``like``."""
        if step is None:
            step = self.latest_step()
        if step is None:
            raise MPIError(ErrorCode.ERR_FILE,
                           f"no committed checkpoint in {self.directory}")
        return sharded.load_pytree(self._step_dir(step), like)

    def meta(self, step: int) -> Dict:
        with open(os.path.join(self._step_dir(step), "meta.json")) as f:
            return json.load(f)

    # -- retention (sstore GC) ---------------------------------------------
    def _gc(self) -> None:
        steps = self.steps()
        for s in steps[:-self.keep] if self.keep else []:
            shutil.rmtree(self._step_dir(s), ignore_errors=True)
