"""One-sided communication (RMA) — the ``ompi/mca/osc`` analogue."""

from .window import (  # noqa: F401
    DynamicWindow, Window, win_create, win_allocate,
    win_allocate_shared, win_create_dynamic,
    LOCK_EXCLUSIVE, LOCK_SHARED,
)
