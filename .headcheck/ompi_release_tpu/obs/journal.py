"""Ring-buffer event journal — the observability plane's spine.

A fixed-size, lock-protected ring of :class:`Span` records ``(op,
layer, t_start, dt, nbytes, peer, comm_id, seq)`` written by emit
points inside the framework (coll driver, vcoll edge, pml, btl,
request wait, sharded IO, peruse bridge, PMPI tracer). Oldest spans
are overwritten; ``seq`` is process-monotonic so exporters and tools
can detect wrap/loss. Recording never allocates beyond the span
object and never blocks on IO — exporters (``obs/export.py``) read a
snapshot and format offline.

Timestamps are ``time.perf_counter()`` seconds. For XLA-dispatched
work ``dt`` is *dispatch-side* time (jax dispatch is async; blocking
for device completion inside an emit point would change program
behavior); peruse-bridge spans carry the event's element count in the
``nbytes`` slot, as fired.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional

DEFAULT_SIZE = 4096


class Span:
    __slots__ = ("seq", "op", "layer", "t_start", "dt", "nbytes",
                 "peer", "comm_id")

    def __init__(self, seq: int, op: str, layer: str, t_start: float,
                 dt: float, nbytes: int = 0, peer: int = -1,
                 comm_id: int = -1) -> None:
        self.seq = seq
        self.op = op
        self.layer = layer
        self.t_start = t_start
        self.dt = dt
        self.nbytes = nbytes
        self.peer = peer
        self.comm_id = comm_id

    def asdict(self) -> Dict[str, Any]:
        return {"seq": self.seq, "op": self.op, "layer": self.layer,
                "t": self.t_start, "dt": self.dt, "bytes": self.nbytes,
                "peer": self.peer, "comm": self.comm_id}

    def __repr__(self) -> str:
        return (f"Span(#{self.seq} {self.layer}/{self.op} "
                f"dt={self.dt:.3e}s bytes={self.nbytes})")


class Journal:
    def __init__(self, size: int = DEFAULT_SIZE) -> None:
        self._lock = threading.Lock()
        self._size = max(1, int(size))
        self._buf: List[Optional[Span]] = [None] * self._size
        self._next_seq = 0
        self._wrapped = 0  # spans overwritten or squeezed out by resize

    @property
    def size(self) -> int:
        return self._size

    @property
    def total_recorded(self) -> int:
        """Spans ever recorded (monotonic across wraps and clears)."""
        with self._lock:
            return self._next_seq

    @property
    def dropped(self) -> int:
        """Spans lost to CAPACITY — ring wrap or a shrinking resize.
        Spans removed by an explicit clear() are not losses and do not
        count (the obs_journal_dropped pvar tells operators to raise
        obs_journal_size; clear() must not trigger that advice)."""
        with self._lock:
            return self._wrapped

    def record(self, op: str, layer: str, t_start: float, dt: float,
               nbytes: int = 0, peer: int = -1, comm_id: int = -1) -> Span:
        with self._lock:
            seq = self._next_seq
            self._next_seq = seq + 1
            sp = Span(seq, op, layer, t_start, dt, nbytes, peer, comm_id)
            slot = seq % self._size
            if self._buf[slot] is not None:
                self._wrapped += 1
            self._buf[slot] = sp
            return sp

    def _snapshot_locked(self) -> List[Span]:
        spans = [s for s in self._buf if s is not None]
        spans.sort(key=lambda s: s.seq)
        return spans

    def snapshot(self) -> List[Span]:
        """Buffered spans, oldest first."""
        with self._lock:
            return self._snapshot_locked()

    def __len__(self) -> int:
        with self._lock:
            return sum(1 for s in self._buf if s is not None)

    def clear(self) -> None:
        """Drop buffered spans; seq keeps counting (monotonic)."""
        with self._lock:
            self._buf = [None] * self._size

    def resize(self, size: int) -> None:
        """Change capacity in place, keeping the newest spans."""
        with self._lock:
            spans = self._snapshot_locked()
            keep = spans[-max(1, int(size)):]
            self._wrapped += len(spans) - len(keep)  # squeezed out
            self._size = max(1, int(size))
            self._buf = [None] * self._size
            for sp in keep:
                self._buf[sp.seq % self._size] = sp


#: process-global journal every emit point writes into
JOURNAL = Journal()
