"""Rank-skew metrics — per-collective arrival-spread estimation.

The imbalance signal a production trainer needs: how long a collective
sat between *arriving* at the dispatcher and its body actually
launching, versus the body itself. The coll driver marks three points
per call — arrive (dispatcher entry), body (compiled program launch,
after cache lookup / compile / validation / host staging), end — and
this module turns them into pvars:

  coll_<op>_skew_seconds   AGGREGATE  wait before the body launched
  coll_<op>_latency        HISTOGRAM  log2 buckets of body seconds
  coll_<op>_msg_bytes      HISTOGRAM  log2 buckets of payload sizes

plus one journal span per call covering arrive→end. In
single-controller driver mode every rank's arrival is the same host
call, so the spread estimate degenerates to the host-side wait; on a
spanning (multi-controller) communicator the wait includes genuine
cross-rank arrival spread — the body cannot start until the last
rank's frames arrive. Pvars are looked up through the registry on
every ``end`` (lock + dict hit) rather than cached: skew only runs
when obs is enabled, and registry-identity staleness across test
fixtures is worse than the lookup.
"""

from __future__ import annotations

import time

from ..mca import pvar as _pvar
from .journal import JOURNAL as _JOURNAL


class CollTimer:
    __slots__ = ("op", "comm_id", "t_arrive", "t_body")

    def __init__(self, op: str, comm_id: int) -> None:
        self.op = op
        self.comm_id = comm_id
        self.t_arrive = time.perf_counter()
        self.t_body = self.t_arrive


def begin(op: str, comm_id: int = -1) -> CollTimer:
    """Mark a collective's arrival at the dispatcher."""
    return CollTimer(op, comm_id)


def body(tok: CollTimer) -> None:
    """Mark the op body's launch; wait = now - arrival."""
    tok.t_body = time.perf_counter()


def end(tok: CollTimer, nbytes: int = 0) -> None:
    """Close the span: update skew/latency/size pvars + the journal."""
    now = time.perf_counter()
    op = tok.op
    _pvar.aggregate(
        f"coll_{op}_skew_seconds",
        f"wait before the {op} body launched (arrival-spread estimate)",
    ).observe(tok.t_body - tok.t_arrive)
    _pvar.histogram(
        f"coll_{op}_latency",
        f"{op} body seconds (dispatch-side), log2 buckets",
    ).observe(now - tok.t_body)
    _pvar.histogram(
        f"coll_{op}_msg_bytes",
        f"{op} payload bytes, log2 buckets",
    ).observe(nbytes)
    _JOURNAL.record(op, "coll", tok.t_arrive, now - tok.t_arrive,
                    nbytes=nbytes, comm_id=tok.comm_id)
