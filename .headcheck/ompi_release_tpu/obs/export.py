"""Exporters — journal and pvars in standard tool formats.

Three consumers, three formats, one data source:

  - :func:`chrome_trace` / :func:`dump_chrome_trace`: Chrome/Perfetto
    ``trace_event`` JSON (load in chrome://tracing or ui.perfetto.dev).
    One pseudo-thread per layer (named via ``thread_name`` metadata
    events); spans with dt > 0 are complete events ("X"), instant
    emit points are thread-scoped instants ("i").
  - :func:`dump_jsonl`: one JSON object per span (the tracer sink's
    line format), for ad-hoc grep/pandas analysis.
  - :func:`prometheus_text`: text exposition of every registered pvar
    (``ompitpu_<name>``), served by the ``tpu_server`` metrics RPC and
    rendered live by ``tpu_top --metrics``. HISTOGRAM pvars become
    real Prometheus histograms (cumulative ``_bucket{le=...}`` +
    ``_sum``/``_count``), AGGREGATE pvars a gauge family.
"""

from __future__ import annotations

import json
import re
from typing import Any, Dict, List, Optional, Sequence

from ..mca import pvar as _pvar
from .journal import JOURNAL as _JOURNAL
from .journal import Span

# ---------------------------------------------------------------------------
# Chrome/Perfetto trace_event
# ---------------------------------------------------------------------------


def chrome_trace(spans: Optional[Sequence[Span]] = None) -> Dict[str, Any]:
    """The journal as a ``trace_event`` JSON document (dict form)."""
    if spans is None:
        spans = _JOURNAL.snapshot()
    tids: Dict[str, int] = {}
    events: List[Dict[str, Any]] = []
    for s in spans:
        tid = tids.setdefault(s.layer, len(tids) + 1)
        ev: Dict[str, Any] = {
            "name": s.op, "cat": s.layer, "pid": 0, "tid": tid,
            "ts": s.t_start * 1e6,  # trace_event wants microseconds
            "args": {"bytes": s.nbytes, "peer": s.peer,
                     "comm": s.comm_id, "seq": s.seq},
        }
        if s.dt > 0:
            ev["ph"] = "X"
            ev["dur"] = s.dt * 1e6
        else:
            ev["ph"] = "i"
            ev["s"] = "t"  # thread-scoped instant
        events.append(ev)
    meta = [
        {"name": "process_name", "ph": "M", "pid": 0,
         "args": {"name": "ompi_release_tpu"}},
    ] + [
        {"name": "thread_name", "ph": "M", "pid": 0, "tid": tid,
         "args": {"name": layer}}
        for layer, tid in sorted(tids.items(), key=lambda kv: kv[1])
    ]
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def dump_chrome_trace(path: str,
                      spans: Optional[Sequence[Span]] = None) -> str:
    with open(path, "w") as f:
        json.dump(chrome_trace(spans), f)
    return path


def dump_jsonl(path: str, spans: Optional[Sequence[Span]] = None) -> str:
    if spans is None:
        spans = _JOURNAL.snapshot()
    with open(path, "w") as f:
        for s in spans:
            f.write(json.dumps(s.asdict()) + "\n")
    return path


# ---------------------------------------------------------------------------
# Prometheus text exposition
# ---------------------------------------------------------------------------

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")


def _metric_name(name: str) -> str:
    n = _NAME_BAD.sub("_", name)
    if n and n[0].isdigit():
        n = "_" + n
    return "ompitpu_" + n


def _help_line(m: str, help: str) -> str:
    return f"# HELP {m} " + " ".join(str(help).split())


def prometheus_text(registry: Optional[_pvar.PvarRegistry] = None) -> str:
    """Every registered pvar as Prometheus text exposition format."""
    reg = registry if registry is not None else _pvar.PVARS
    out: List[str] = []
    for d in reg.describe_all():
        name, pclass, value = d["name"], d["class"], d["value"]
        m = _metric_name(name)
        if pclass == "histogram" and isinstance(value, dict):
            out.append(_help_line(m, d["help"]))
            out.append(f"# TYPE {m} histogram")
            cum = 0
            for le in sorted(value.get("buckets", {})):
                cum += value["buckets"][le]
                out.append(f'{m}_bucket{{le="{float(le):g}"}} {cum}')
            out.append(f'{m}_bucket{{le="+Inf"}} {value["count"]}')
            out.append(f"{m}_sum {float(value['sum']):g}")
            out.append(f"{m}_count {value['count']}")
        elif pclass == "aggregate" and isinstance(value, dict):
            out.append(_help_line(m, d["help"]))
            for suffix in ("count", "sum", "min", "max"):
                out.append(f"# TYPE {m}_{suffix} gauge")
                out.append(f"{m}_{suffix} {float(value[suffix]):g}")
        else:
            try:
                fv = float(value)
            except (TypeError, ValueError):
                continue  # non-numeric getter pvar: not exposable
            ptype = "counter" if pclass in ("counter", "timer") else "gauge"
            out.append(_help_line(m, d["help"]))
            out.append(f"# TYPE {m} {ptype}")
            out.append(f"{m} {fv:g}")
    return "\n".join(out) + "\n"
