"""Pack/unpack convertors: the ``opal_convertor`` analogue on XLA.

The reference walks byte state machines supporting partial buffers and
checksums (``opal/datatype/opal_convertor.c:707``,
``opal_datatype_pack.c``). Here pack = one XLA gather, unpack = one XLA
scatter, both jittable and fusable; partial (segmented) pack/unpack for
pipelined protocols slices the static index map — offsets are computed
at trace time, so segmentation stays compiler-friendly (static shapes).

Checksums (``opal_datatype_checksum.h`` analogue) are an optional CRC
over the packed payload for wire-corruption detection on DCN paths.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from .datatype import Datatype
from ..mca import pvar

_pack_count = pvar.counter(
    "datatype_pack_count", "number of convertor pack operations"
)
_unpack_count = pvar.counter(
    "datatype_unpack_count", "number of convertor unpack operations"
)


class Convertor:
    """Packs/unpacks ``count`` items of ``dtype`` against a flat buffer.

    The origin buffer is a 1-D jax array in element units of the
    datatype's base dtype (HBM-resident; no host staging).
    """

    def __init__(self, dtype: Datatype, count: int = 1) -> None:
        self.dtype = dtype
        self.count = count
        # identity map when items are contiguous and (for count>1)
        # back-to-back; only then can pack be a plain slice
        back_to_back = count == 1 or dtype.get_extent() == dtype.span
        self._offsets: Optional[np.ndarray] = (
            None if dtype.is_contiguous and back_to_back
            else dtype.offsets(count)
        )

    # -- totals ------------------------------------------------------------
    @property
    def packed_elements(self) -> int:
        return self.dtype.count * self.count

    @property
    def packed_bytes(self) -> int:
        return self.packed_elements * self.dtype.base_dtype.itemsize

    def required_span(self) -> int:
        """Minimum origin-buffer length in elements."""
        if self._offsets is None:
            return self.packed_elements
        return int(self._offsets.max()) + 1 if len(self._offsets) else 0

    def _check_span(self, flat: jax.Array) -> None:
        """Raise ERR_TRUNCATE if the origin buffer can't hold the type.

        Buffer shapes are static under jit, so this is a trace-time
        check — the analogue of MPI_ERR_TRUNCATE, instead of XLA's
        silent out-of-bounds gather semantics.
        """
        need = self.required_span()
        if flat.shape[0] < need:
            from ..utils.errors import ErrorCode, MPIError

            raise MPIError(
                ErrorCode.ERR_TRUNCATE,
                f"buffer has {flat.shape[0]} elements but datatype "
                f"{self.dtype.name!r} x{self.count} spans {need}",
            )

    # -- full pack/unpack --------------------------------------------------
    def pack(self, buffer: jax.Array) -> jax.Array:
        """Gather the described elements into a dense 1-D payload."""
        _pack_count.add()
        flat = buffer.reshape(-1)
        self._check_span(flat)
        if self._offsets is None:
            return flat[: self.packed_elements]
        return jnp.take(flat, jnp.asarray(self._offsets), axis=0)

    def unpack(self, payload: jax.Array, buffer: jax.Array) -> jax.Array:
        """Scatter a dense payload back into (a copy of) ``buffer``."""
        _unpack_count.add()
        flat = buffer.reshape(-1)
        self._check_span(flat)
        payload = payload.reshape(-1).astype(flat.dtype)
        if self._offsets is None:
            out = flat.at[: self.packed_elements].set(payload)
        else:
            out = flat.at[jnp.asarray(self._offsets)].set(payload)
        return out.reshape(buffer.shape)

    # -- external32 (MPI_Pack_external, "external32" representation) -------
    def pack_external(self, buffer: jax.Array) -> np.ndarray:
        """MPI_Pack_external: the canonical BIG-ENDIAN byte stream of
        the described elements (``ompi/mpi/c/pack_external.c`` /
        ``opal_datatype_external32``). The wire element type is the
        DATATYPE's base dtype (a float64 buffer through a FLOAT
        datatype goes out as 4-byte floats — the datatype defines the
        representation, like the reference's convertor). A
        serialization API, not a hot path — runs at the host edge,
        returns uint8 bytes any endianness (or other MPI) can
        consume."""
        wire = self.dtype.base_dtype
        payload = np.asarray(self.pack(buffer)).astype(wire)
        be = payload.astype(wire.newbyteorder(">"), copy=False)
        return np.frombuffer(be.tobytes(), dtype=np.uint8)

    def unpack_external(self, raw, buffer: jax.Array) -> jax.Array:
        """MPI_Unpack_external: decode a big-endian external32 stream
        (bytes, bytearray, or a uint8 array) back into (a copy of)
        ``buffer``."""
        want = self.packed_bytes
        if isinstance(raw, (bytes, bytearray, memoryview)):
            raw = np.frombuffer(raw, dtype=np.uint8)
        else:
            raw = np.asarray(raw, dtype=np.uint8).reshape(-1)
        if raw.size != want:
            from ..utils.errors import ErrorCode, MPIError

            raise MPIError(
                ErrorCode.ERR_TRUNCATE,
                f"external32 stream is {raw.size} B, datatype "
                f"describes {want} B",
            )
        wire = self.dtype.base_dtype
        native = np.frombuffer(raw.tobytes(),
                               dtype=wire.newbyteorder(">")).astype(wire)
        return self.unpack(jnp.asarray(native), buffer)

    # -- partial (segmented) ----------------------------------------------
    def pack_partial(self, buffer: jax.Array, position: int,
                     max_elements: int) -> Tuple[jax.Array, int]:
        """Pack up to ``max_elements`` packed elements starting at
        ``position`` (the ``opal_convertor_set_position`` analogue used
        by pipelined/segmented protocols). Returns (payload, new_pos)."""
        end = min(position + max_elements, self.packed_elements)
        flat = buffer.reshape(-1)
        self._check_span(flat)
        if self._offsets is None:
            seg = flat[position:end]
        else:
            seg = jnp.take(
                flat, jnp.asarray(self._offsets[position:end]), axis=0
            )
        _pack_count.add()
        return seg, end

    def unpack_partial(self, payload: jax.Array, buffer: jax.Array,
                       position: int) -> Tuple[jax.Array, int]:
        flat = buffer.reshape(-1)
        self._check_span(flat)
        n = payload.reshape(-1).shape[0]
        end = position + n
        payload = payload.reshape(-1).astype(flat.dtype)
        if self._offsets is None:
            out = flat.at[position:end].set(payload)
        else:
            out = flat.at[jnp.asarray(self._offsets[position:end])].set(payload)
        _unpack_count.add()
        return out.reshape(buffer.shape), end

    # -- checksum ----------------------------------------------------------
    @staticmethod
    def checksum(payload: jax.Array) -> jax.Array:
        """Cheap on-device payload checksum (wire-corruption guard).

        Reference: ``opal/datatype/opal_datatype_checksum.h``. A
        bit-exact integer sum over the byte view, computable on device.
        """
        b = jax.lax.bitcast_convert_type(
            payload.reshape(-1), jnp.uint8
        ).reshape(-1)
        return jnp.sum(b.astype(jnp.uint32) * (jnp.arange(b.shape[0], dtype=jnp.uint32) % 251 + 1), dtype=jnp.uint32)
