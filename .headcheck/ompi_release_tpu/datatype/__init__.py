"""Datatype engine: predefined + derived datatypes and convertors."""

from .datatype import (
    BFLOAT16, BOOL, BYTE, COMPLEX64, DOUBLE, FLOAT, INT8, INT16, INT32,
    INT64, UINT8, UINT16, UINT32, UINT64, Datatype, PREDEFINED,
    DARG_DEFAULT, DIST_BLOCK, DIST_CYCLIC, DIST_NONE,
    create_contiguous, create_darray, create_hindexed,
    create_indexed_block, create_struct,
    create_subarray, create_vector, from_jax_dtype,
)
from .convertor import Convertor

__all__ = [
    "Datatype", "Convertor", "PREDEFINED", "from_jax_dtype",
    "create_contiguous", "create_vector", "create_hindexed",
    "create_indexed_block", "create_struct", "create_subarray",
    "create_darray", "DIST_BLOCK", "DIST_CYCLIC", "DIST_NONE",
    "DARG_DEFAULT",
    "FLOAT", "DOUBLE", "BFLOAT16", "INT8", "INT16", "INT32", "INT64",
    "UINT8", "UINT16", "UINT32", "UINT64", "BYTE", "BOOL", "COMPLEX64",
]
