"""MPI-style datatypes re-designed for XLA.

The reference describes a type as a list of (primitive, offset) pairs
walked by byte-oriented pack/unpack state machines
(``opal/datatype/opal_convertor.c``, ``opal_datatype_pack.c``;
constructors ``ompi/datatype/ompi_datatype_create_*.c``). On TPU the
idiomatic representation is an **element index map**: every derived type
flattens to a static numpy int32 array of element offsets into the
origin buffer, and pack/unpack become a single XLA ``gather`` /
``scatter`` — one fused device op instead of a byte state machine, with
no host copy on the hot path.

Supported constructor parity: contiguous, vector/hvector, indexed/
hindexed, indexed_block, struct (homogeneous-dtype), subarray; dup and
resized (extent override) are trivial fields.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

try:  # jax only needed for bfloat16; numpy handles the rest
    import jax.numpy as jnp

    _BFLOAT16 = jnp.bfloat16
except Exception:  # pragma: no cover
    _BFLOAT16 = None


@dataclasses.dataclass(frozen=True)
class Datatype:
    """An MPI datatype: an element dtype + an index map over elements.

    ``index_map`` is None for predefined/contiguous-from-zero types
    (identity map of length ``count``); otherwise an int32 array of
    element offsets (in units of ``base_dtype`` elements, not bytes —
    byte addressing is meaningless inside HBM tensors).

    ``extent`` is in elements: how far successive items of this type
    advance in the origin buffer (MPI_Type_get_extent analogue, allows
    resized types for strided sends).
    """

    name: str
    base_dtype: np.dtype  # element dtype (what the wire carries)
    count: int  # number of base elements per item (MPI size analogue)
    index_map: Optional[np.ndarray] = None
    extent: Optional[int] = None  # in elements; defaults to span
    committed: bool = True

    def __post_init__(self):
        if self.index_map is not None:
            object.__setattr__(
                self, "index_map", np.asarray(self.index_map, dtype=np.int32)
            )
            assert self.index_map.ndim == 1
            assert len(self.index_map) == self.count

    # -- queries ----------------------------------------------------------
    @property
    def size_bytes(self) -> int:
        """Bytes of actual data per item (MPI_Type_size)."""
        return self.count * self.base_dtype.itemsize

    @property
    def span(self) -> int:
        """Elements from first to one-past-last touched offset."""
        if self.index_map is None:
            return self.count
        if len(self.index_map) == 0:
            return 0
        return int(self.index_map.max()) + 1

    @property
    def true_extent(self) -> int:
        return self.span

    def get_extent(self) -> int:
        return self.extent if self.extent is not None else self.span

    @property
    def is_contiguous(self) -> bool:
        if self.index_map is None:
            return True
        return bool(
            np.array_equal(self.index_map, np.arange(self.count, dtype=np.int32))
        )

    # -- offsets ----------------------------------------------------------
    def offsets(self, n_items: int = 1) -> np.ndarray:
        """Element offsets for ``n_items`` consecutive items."""
        base = (
            np.arange(self.count, dtype=np.int32)
            if self.index_map is None
            else self.index_map
        )
        if n_items == 1:
            return base
        ext = self.get_extent()
        starts = (np.arange(n_items, dtype=np.int32) * ext)[:, None]
        return (starts + base[None, :]).reshape(-1)

    # -- constructors (MPI_Type_* analogues) -------------------------------
    def dup(self, name: Optional[str] = None) -> "Datatype":
        return dataclasses.replace(self, name=name or f"dup({self.name})")

    def resized(self, extent: int) -> "Datatype":
        """MPI_Type_create_resized: override the extent."""
        return dataclasses.replace(
            self, extent=extent, name=f"resized({self.name},{extent})"
        )

    def __repr__(self) -> str:  # keep test output readable
        return (
            f"Datatype({self.name}, {self.base_dtype}, count={self.count}, "
            f"extent={self.get_extent()}, contig={self.is_contiguous})"
        )


def _predef(name: str, np_dtype) -> Datatype:
    return Datatype(name=name, base_dtype=np.dtype(np_dtype), count=1)


FLOAT = _predef("float", np.float32)
DOUBLE = _predef("double", np.float64)  # maps to f32 on real TPU via jax x64 off
BFLOAT16 = _predef("bfloat16", _BFLOAT16 if _BFLOAT16 else np.float16)
INT8 = _predef("int8", np.int8)
INT16 = _predef("int16", np.int16)
INT32 = _predef("int32", np.int32)
INT64 = _predef("int64", np.int64)
UINT8 = _predef("uint8", np.uint8)
UINT16 = _predef("uint16", np.uint16)
UINT32 = _predef("uint32", np.uint32)
UINT64 = _predef("uint64", np.uint64)
BYTE = _predef("byte", np.uint8)
BOOL = _predef("bool", np.bool_)
COMPLEX64 = _predef("complex64", np.complex64)

PREDEFINED = {
    t.name: t
    for t in [
        FLOAT, DOUBLE, BFLOAT16, INT8, INT16, INT32, INT64, UINT8, UINT16,
        UINT32, UINT64, BYTE, BOOL, COMPLEX64,
    ]
}


def from_jax_dtype(dtype) -> Datatype:
    """Map a jax/numpy dtype to the matching predefined Datatype."""
    if str(dtype) == "bfloat16":
        return BFLOAT16
    d = np.dtype(dtype)
    for t in PREDEFINED.values():
        if t.base_dtype == d:
            return t
    raise ValueError(f"no predefined datatype for {dtype!r}")


def create_contiguous(count: int, base: Datatype) -> Datatype:
    """MPI_Type_contiguous (``ompi_datatype_create_contiguous.c``)."""
    offs = base.offsets(count)
    contiguous = bool(
        np.array_equal(offs, np.arange(len(offs), dtype=np.int32))
    )
    return Datatype(
        name=f"contig({count},{base.name})",
        base_dtype=base.base_dtype,
        count=base.count * count,
        index_map=None if contiguous else offs,
        extent=base.get_extent() * count,
    )


def create_vector(count: int, blocklength: int, stride: int,
                  base: Datatype) -> Datatype:
    """MPI_Type_vector: ``count`` blocks of ``blocklength`` items, start
    offsets ``stride`` items apart (``ompi_datatype_create_vector.c``)."""
    ext = base.get_extent()
    block = base.offsets(blocklength)  # offsets within one block
    starts = (np.arange(count, dtype=np.int32) * stride * ext)[:, None]
    offs = (starts + block[None, :]).reshape(-1)
    return Datatype(
        name=f"vector({count},{blocklength},{stride},{base.name})",
        base_dtype=base.base_dtype,
        count=len(offs),
        index_map=offs,
        extent=((count - 1) * stride + blocklength) * ext if count else 0,
    )


def create_hindexed(blocklengths: Sequence[int], displacements: Sequence[int],
                    base: Datatype) -> Datatype:
    """MPI_Type_create_hindexed (displacements in elements, not bytes)."""
    assert len(blocklengths) == len(displacements)
    parts: List[np.ndarray] = []
    for bl, disp in zip(blocklengths, displacements):
        parts.append(disp + base.offsets(bl))
    offs = (
        np.concatenate(parts).astype(np.int32)
        if parts
        else np.zeros(0, np.int32)
    )
    return Datatype(
        name=f"hindexed({list(blocklengths)},{list(displacements)},{base.name})",
        base_dtype=base.base_dtype,
        count=len(offs),
        index_map=offs,
    )


def create_indexed_block(blocklength: int, displacements: Sequence[int],
                         base: Datatype) -> Datatype:
    return create_hindexed(
        [blocklength] * len(displacements), displacements, base
    )


def create_struct(blocklengths: Sequence[int], displacements: Sequence[int],
                  types: Sequence[Datatype]) -> Datatype:
    """MPI_Type_create_struct, homogeneous element dtype.

    The reference supports heterogeneous structs via byte-walking; on
    TPU a buffer has one dtype, so struct members must share the base
    dtype (heterogeneous structs are handled above this layer by
    splitting into one message per dtype, the same strategy the
    reference's heterogeneous-arch path uses for conversions).
    """
    if not (len(blocklengths) == len(displacements) == len(types)):
        raise ValueError(
            f"struct argument lengths differ: {len(blocklengths)} "
            f"blocklengths, {len(displacements)} displacements, "
            f"{len(types)} types"
        )
    dtypes = {t.base_dtype for t in types}
    if len(dtypes) != 1:
        raise ValueError(
            "TPU struct datatypes must be homogeneous; split per-dtype "
            f"(got {sorted(str(d) for d in dtypes)})"
        )
    parts = []
    for bl, disp, t in zip(blocklengths, displacements, types):
        for i in range(bl):
            parts.append(disp + i * t.get_extent() + t.offsets(1))
    offs = (
        np.concatenate(parts).astype(np.int32)
        if parts
        else np.zeros(0, np.int32)
    )
    return Datatype(
        name="struct",
        base_dtype=types[0].base_dtype,
        count=len(offs),
        index_map=offs,
    )


def create_subarray(sizes: Sequence[int], subsizes: Sequence[int],
                    starts: Sequence[int], base: Datatype) -> Datatype:
    """MPI_Type_create_subarray (C order), the MPI-IO workhorse."""
    assert len(sizes) == len(subsizes) == len(starts)
    grids = np.meshgrid(
        *[np.arange(st, st + ss) for st, ss in zip(starts, subsizes)],
        indexing="ij",
    )
    flat = np.ravel_multi_index([g.reshape(-1) for g in grids], dims=sizes)
    offs = np.sort(flat).astype(np.int32)
    if base.count != 1:
        offs = (offs[:, None] * base.get_extent() + base.offsets(1)[None, :]).reshape(-1)
    return Datatype(
        name=f"subarray({list(sizes)},{list(subsizes)},{list(starts)})",
        base_dtype=base.base_dtype,
        count=len(offs),
        index_map=offs,
        extent=int(np.prod(sizes)) * base.get_extent(),
    )


# MPI_Type_create_darray distribution constants
DIST_BLOCK = "block"
DIST_CYCLIC = "cyclic"
DIST_NONE = "none"
DARG_DEFAULT = -1  # MPI_DISTRIBUTE_DFLT_DARG


def _dim_indices(gsize: int, dist: str, darg: int, nprocs: int,
                 coord: int) -> np.ndarray:
    """Global indices along one dim owned by process ``coord``."""
    if dist == DIST_NONE:
        if nprocs != 1:
            raise ValueError(
                "DIST_NONE requires 1 process on that dimension"
            )
        return np.arange(gsize)
    if dist == DIST_BLOCK:
        # MPI: default block size = ceil(gsize / nprocs); an explicit
        # darg must cover the array (darg * nprocs >= gsize)
        bsize = -(-gsize // nprocs) if darg == DARG_DEFAULT else darg
        if bsize * nprocs < gsize:
            raise ValueError(
                f"block darg {bsize} too small: {bsize}*{nprocs} < "
                f"{gsize}"
            )
        lo = coord * bsize
        return np.arange(lo, min(lo + bsize, gsize))
    if dist == DIST_CYCLIC:
        bsize = 1 if darg == DARG_DEFAULT else darg
        if bsize < 1:
            # symmetric with the block check: a non-positive block
            # size would silently select NOTHING (empty range) — an
            # MPI-IO write with that type is silent data loss
            raise ValueError(
                f"cyclic darg must be >= 1, got {bsize}"
            )
        idx = []
        start = coord * bsize
        stride = nprocs * bsize
        for base_i in range(start, gsize, stride):
            idx.extend(range(base_i, min(base_i + bsize, gsize)))
        return np.asarray(idx, dtype=np.int64)
    raise ValueError(f"unknown distribution '{dist}'")


def create_darray(size: int, rank: int, gsizes: Sequence[int],
                  distribs: Sequence[str], dargs: Sequence[int],
                  psizes: Sequence[int], base: Datatype) -> Datatype:
    """MPI_Type_create_darray (C order): the datatype selecting rank's
    portion of a block/cyclic-distributed global array — the HPF-style
    decomposition MPI-IO uses for parallel array files
    (``ompi/datatype/ompi_datatype_create_darray.c`` role).

    ``size``/``rank``: process grid population and this process's
    rank (row-major over ``psizes``). Each dim: distribution
    ``block``/``cyclic``/``none`` with ``dargs[i]`` (DARG_DEFAULT for
    the MPI default block size).
    """
    ndims = len(gsizes)
    if not (len(distribs) == len(dargs) == len(psizes) == ndims):
        raise ValueError("darray argument lengths differ")
    if int(np.prod(psizes)) != size:
        raise ValueError(
            f"process grid {list(psizes)} does not cover {size} procs"
        )
    if not 0 <= rank < size:
        raise ValueError(f"rank {rank} outside process grid of {size}")
    # rank -> process-grid coordinates, row-major (MPI order)
    coords = []
    r = rank
    for p in reversed(psizes):
        coords.append(r % p)
        r //= p
    coords = list(reversed(coords))

    per_dim = [
        _dim_indices(g, d, a, p, c)
        for g, d, a, p, c in zip(gsizes, distribs, dargs, psizes, coords)
    ]
    grids = np.meshgrid(*per_dim, indexing="ij")
    flat = np.ravel_multi_index(
        [g.reshape(-1) for g in grids], dims=gsizes
    )
    offs = np.sort(flat).astype(np.int32)
    if base.count != 1:
        offs = (offs[:, None] * base.get_extent()
                + base.offsets(1)[None, :]).reshape(-1)
    return Datatype(
        name=f"darray(r{rank}/{size},{list(gsizes)})",
        base_dtype=base.base_dtype,
        count=len(offs),
        index_map=offs,
        extent=int(np.prod(gsizes)) * base.get_extent(),
    )
