"""Sequence parallelism — Ulysses head<->sequence resharding.

The alltoall pattern (``coll_tuned_alltoall.c``; DeepSpeed-Ulysses):
attention needs full sequence per head, the rest of the model wants the
sequence sharded. One ``lax.all_to_all`` flips between the two layouts,
moving each (seq-block, head-block) tile exactly once over ICI.
"""

from __future__ import annotations

import jax
from jax import lax


def seq_to_heads(x: jax.Array, *, axis_name: str = "sp",
                 seq_axis: int = 0, head_axis: int = 1) -> jax.Array:
    """(S/n, H, ...) per rank -> (S, H/n, ...): gather the sequence,
    shard the heads. H must be divisible by the sp axis size."""
    return lax.all_to_all(
        x, axis_name, split_axis=head_axis, concat_axis=seq_axis, tiled=True
    )


def heads_to_seq(x: jax.Array, *, axis_name: str = "sp",
                 seq_axis: int = 0, head_axis: int = 1) -> jax.Array:
    """Inverse reshard: (S, H/n, ...) -> (S/n, H, ...)."""
    return lax.all_to_all(
        x, axis_name, split_axis=seq_axis, concat_axis=head_axis, tiled=True
    )


def ulysses_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                      attn_fn, *, axis_name: str = "sp") -> jax.Array:
    """Full Ulysses round trip: reshard q/k/v to head-sharded full
    sequence, run ``attn_fn(q, k, v)`` (any local attention), reshard
    the output back to sequence-sharded full heads.

    q/k/v: (S/n, H, D) per rank.
    """
    qh = seq_to_heads(q, axis_name=axis_name)
    kh = seq_to_heads(k, axis_name=axis_name)
    vh = seq_to_heads(v, axis_name=axis_name)
    oh = attn_fn(qh, kh, vh)  # (S, H/n, D)
    return heads_to_seq(oh, axis_name=axis_name)
