"""Tensor parallelism: Megatron-style sharded linear layers + embedding.

Built on the allreduce/allgather/reduce_scatter patterns of the
reference (``coll_tuned_allgather.c``, ``coll_tuned_reduce_scatter.c``):
a column-parallel matmul shards the output features (no communication),
a row-parallel matmul shards the input features and psums partial
products — the classic f/g conjugate pair. Matmuls accumulate in f32
on the MXU (``preferred_element_type``) with bf16 storage.
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax


@partial(jax.custom_jvp, nondiff_argnums=(1,))
def _pmax_nograd(x, axis_name):
    """pmax with a defined (zero) tangent: used for flash-softmax max
    shifts, whose gradient cancels exactly; lax.pmax itself has no
    differentiation rule."""
    return lax.pmax(x, axis_name)


@_pmax_nograd.defjvp
def _pmax_nograd_jvp(axis_name, primals, tangents):
    out = lax.pmax(primals[0], axis_name)
    return out, jnp.zeros_like(out)


def column_parallel(x: jax.Array, w_shard: jax.Array,
                    b_shard: Optional[jax.Array] = None, *,
                    axis_name: str = "tp",
                    gather_output: bool = False) -> jax.Array:
    """y_shard = x @ w_shard (+ b_shard).

    x: (..., D) replicated over tp; w_shard: (D, F/n) this rank's output
    columns. With ``gather_output`` the full (..., F) is all_gathered
    (MPI_Allgather over the tp axis).
    """
    y = jnp.matmul(x, w_shard, preferred_element_type=jnp.float32)
    y = y.astype(x.dtype)
    if b_shard is not None:
        y = y + b_shard
    if gather_output:
        y = lax.all_gather(y, axis_name, axis=y.ndim - 1, tiled=True)
    return y


def row_parallel(x_shard: jax.Array, w_shard: jax.Array,
                 b: Optional[jax.Array] = None, *,
                 axis_name: str = "tp",
                 scatter_output: bool = False) -> jax.Array:
    """y = psum_tp(x_shard @ w_shard) (+ b).

    x_shard: (..., F/n) — exactly what column_parallel produced;
    w_shard: (F/n, D) this rank's input rows. The psum is the MPI
    allreduce of partial products; with ``scatter_output`` it becomes a
    reduce_scatter over the leading dim (sequence) instead — the
    ZeRO/sequence-parallel fusion that halves ICI traffic.
    """
    part = jnp.matmul(x_shard, w_shard, preferred_element_type=jnp.float32)
    part = part.astype(x_shard.dtype)
    if scatter_output:
        y = lax.psum_scatter(part, axis_name, scatter_dimension=0, tiled=True)
    else:
        y = lax.psum(part, axis_name)
    if b is not None:
        y = y + b
    return y


def vocab_parallel_embedding(ids: jax.Array, table_shard: jax.Array, *,
                             axis_name: str = "tp") -> jax.Array:
    """Embedding with the vocab dimension sharded over tp.

    table_shard: (V/n, D). Each rank looks up only ids in its vocab
    range (out-of-range rows contribute zeros) and the psum assembles
    the full lookup — one fused collective instead of a host gather.
    """
    n = lax.psum(1, axis_name)
    vshard = table_shard.shape[0]
    start = lax.axis_index(axis_name) * vshard
    local = ids - start
    in_range = (local >= 0) & (local < vshard)
    rows = jnp.take(table_shard, jnp.clip(local, 0, vshard - 1), axis=0)
    rows = jnp.where(in_range[..., None], rows, jnp.zeros_like(rows))
    return lax.psum(rows, axis_name) if n > 1 else rows


def vocab_parallel_logits(h: jax.Array, table_shard: jax.Array, *,
                          axis_name: str = "tp",
                          gather: bool = True) -> jax.Array:
    """Tied-embedding LM head: logits over the sharded vocab."""
    logits = jnp.matmul(h, table_shard.T, preferred_element_type=jnp.float32)
    if gather:
        logits = lax.all_gather(
            logits, axis_name, axis=logits.ndim - 1, tiled=True
        )
    return logits


def vocab_parallel_xent(h: jax.Array, table_shard: jax.Array,
                        targets: jax.Array, *,
                        axis_name: str = "tp") -> jax.Array:
    """Cross-entropy over a vocab-sharded LM head WITHOUT materializing
    the full (..., V) logits: per-shard max/sum-exp + target-row dot are
    each one psum/pmax — the flash-softmax of the loss layer.
    """
    logits = jnp.matmul(h, table_shard.T, preferred_element_type=jnp.float32)
    # the max shift is for numerical stability only — its gradient
    # cancels exactly, so it carries a zero tangent
    m = _pmax_nograd(jnp.max(logits, axis=-1), axis_name)
    se = lax.psum(jnp.sum(jnp.exp(logits - m[..., None]), axis=-1), axis_name)

    vshard = table_shard.shape[0]
    start = lax.axis_index(axis_name) * vshard
    local = targets - start
    in_range = (local >= 0) & (local < vshard)
    tgt_logit = jnp.take_along_axis(
        logits, jnp.clip(local, 0, vshard - 1)[..., None], axis=-1
    )[..., 0]
    tgt_logit = lax.psum(jnp.where(in_range, tgt_logit, 0.0), axis_name)
    return m + jnp.log(se) - tgt_logit  # -log p(target)
