"""ZeRO-style sharded optimizer state over the dp axis.

The reduce_scatter_block pattern (``coll_tuned_reduce_scatter.c``;
BASELINE.json config #4 "ZeRO-style gradient shard"): instead of every
dp replica allreducing and holding full gradients + optimizer state,
gradients are reduce_scattered so each replica owns 1/n of them,
updates its shard, and all_gathers fresh params — same total ICI bytes
as allreduce (reduce_scatter + allgather IS the ring allreduce), but
optimizer memory drops by n.
"""

from __future__ import annotations

from typing import Any, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _pad_len(size: int, n: int) -> int:
    return (-size) % n


def shard_gradients(grads: Any, axis_name: str, *, mean: bool = True) -> Any:
    """reduce_scatter every leaf over dp: returns rank's flat shard pytree
    (leaf i -> 1-D array of ceil(size/n) elements)."""
    n = lax.psum(1, axis_name)

    def rs(g):
        flat = g.reshape(-1)
        pad = _pad_len(flat.size, n)
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), g.dtype)])
        out = lax.psum_scatter(flat, axis_name, scatter_dimension=0,
                               tiled=True)
        return out / n if mean and jnp.issubdtype(g.dtype, jnp.inexact) else out

    return jax.tree.map(rs, grads)


def unshard_params(param_shards: Any, shapes: Any, axis_name: str) -> Any:
    """all_gather each flat shard back to the full (reshaped) leaf."""
    def ag(shard, shape):
        full = lax.all_gather(shard, axis_name, axis=0, tiled=True)
        size = 1
        for d in shape:
            size *= d
        return full[:size].reshape(shape)

    return jax.tree.map(ag, param_shards, shapes)


def shard_like(params: Any, axis_name: str) -> Any:
    """Slice each leaf to this rank's flat shard (for building sharded
    optimizer state at init)."""
    n = lax.psum(1, axis_name)
    idx = lax.axis_index(axis_name)

    def sl(p):
        flat = p.reshape(-1)
        pad = _pad_len(flat.size, n)
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros((pad,), p.dtype)])
        chunk = flat.size // n
        return lax.dynamic_slice_in_dim(flat, idx * chunk, chunk)

    return jax.tree.map(sl, params)


def zero_step(params: Any, grads: Any, opt_state_shards: Any, opt_update,
              axis_name: str) -> Tuple[Any, Any]:
    """One ZeRO-1 step: shard grads, update the owned shard, regather.

    ``opt_update(grad_shard_tree, state_shards, param_shard_tree)`` must
    follow optax's transform signature over the flat-shard pytrees.
    """
    gshards = shard_gradients(grads, axis_name)
    pshards = shard_like(params, axis_name)
    updates, new_state = opt_update(gshards, opt_state_shards, pshards)
    new_pshards = jax.tree.map(lambda p, u: p + u, pshards, updates)
    shapes = jax.tree.map(lambda p: p.shape, params)
    return unshard_params(new_pshards, shapes, axis_name), new_state
