"""Expert parallelism — capacity-bounded token routing over all-to-all.

The alltoallv pattern (``coll_tuned_alltoallv.c``) made static-shape
for XLA: top-1 (switch) routing with a fixed per-expert capacity so the
dispatch/combine tensors have compile-time shapes; the two
``lax.all_to_all`` calls move each token to its expert's rank and back.
Tokens over capacity are dropped (standard switch-transformer
semantics) and their outputs fall back to zero (residual carries them).
"""

from __future__ import annotations

from typing import Callable, Tuple

import jax
import jax.numpy as jnp
from jax import lax


def _one_hot_dispatch(logits: jax.Array, n_experts: int, capacity: int
                      ) -> Tuple[jax.Array, jax.Array]:
    """Build (dispatch, combine) for top-1 routing.

    logits: (T, E). dispatch: (T, E, C) one-hot slot assignment;
    combine: (T, E, C) = dispatch * gate prob.
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)  # (T,)
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]
    eh = jax.nn.one_hot(expert, n_experts, dtype=jnp.int32)  # (T, E)
    # position of each token within its expert's queue
    pos = jnp.cumsum(eh, axis=0) * eh - eh  # (T, E), valid where eh==1
    keep = (pos < capacity) & (eh == 1)
    slot = jnp.where(keep, pos, 0)
    dispatch = (
        jax.nn.one_hot(slot, capacity, dtype=jnp.float32)
        * keep[..., None]
    )  # (T, E, C)
    combine = dispatch * gate[:, None, None]
    return dispatch, combine


def moe_layer(x: jax.Array, router_w: jax.Array, expert_fn: Callable,
              expert_params, *, axis_name: str = "ep",
              capacity_factor: float = 1.25) -> Tuple[jax.Array, jax.Array]:
    """Switch-MoE layer under shard_map over the ep axis.

    x: (T, D) this rank's tokens; router_w: (D, E_global) replicated;
    expert_params: this rank's local experts' params with leading axis
    E_local; ``expert_fn(params_e, tokens) -> tokens`` applied per local
    expert via vmap. Returns (output (T, D), aux_loss scalar).
    """
    n = lax.psum(1, axis_name)
    t, dmodel = x.shape
    e_global = router_w.shape[1]
    if e_global % n:
        raise ValueError(f"{e_global} experts not divisible by ep={n}")
    e_local = e_global // n
    capacity = max(1, int(capacity_factor * t / e_global))

    logits = jnp.matmul(x, router_w, preferred_element_type=jnp.float32)
    dispatch, combine = _one_hot_dispatch(logits, e_global, capacity)

    # load-balancing aux loss (Switch eq. 4): E * sum_e f_e * p_e.
    # f_e counts router argmax assignments BEFORE capacity dropping —
    # using the post-drop dispatch would clamp an overloaded expert's
    # fraction at capacity, weakening the balancing gradient exactly
    # when that expert overflows.
    probs = jax.nn.softmax(logits, axis=-1)
    pre_drop = jax.nn.one_hot(jnp.argmax(logits, axis=-1), e_global,
                              dtype=jnp.float32)
    frac_tokens = jnp.mean(pre_drop, axis=0)  # (E,)
    frac_probs = jnp.mean(probs, axis=0)
    aux = e_global * jnp.sum(frac_tokens * frac_probs)
    aux = lax.pmean(aux, axis_name)

    # local tokens -> (E, C, D) expert queues
    sent = jnp.einsum("tec,td->ecd", dispatch, x.astype(jnp.float32))
    # route: (E, C, D) -> (n, E_local, C, D): each rank keeps its experts'
    # queues from every peer
    sent = sent.reshape(n, e_local, capacity, dmodel)
    recv = lax.all_to_all(sent, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)  # (n, E_local, C, D)
    # run local experts over all peers' tokens
    per_expert = recv.transpose(1, 0, 2, 3).reshape(
        e_local, n * capacity, dmodel
    ).astype(x.dtype)
    done = jax.vmap(expert_fn)(expert_params, per_expert)
    done = done.reshape(e_local, n, capacity, dmodel).transpose(1, 0, 2, 3)
    # route back
    back = lax.all_to_all(done.astype(jnp.float32), axis_name,
                          split_axis=0, concat_axis=0, tiled=False)
    back = back.reshape(e_global, capacity, dmodel)
    out = jnp.einsum("tec,ecd->td", combine, back)
    return out.astype(x.dtype), aux


def dropless_moe(comm, tokens, assignments, expert_fn, n_experts: int):
    """Dropless expert routing over alltoallv — uneven capacities.

    The in-jit :func:`moe_layer` pays for static shapes with token
    dropping; this driver-mode path is the exact-count alternative: the
    per-(rank, rank) token counts become an alltoallv count matrix
    (``coll_tuned_alltoallv.c``'s own use case, SURVEY §2.4 EP row), so
    no token is ever dropped. (The compiled kernel under alltoallv
    still pads each chunk to the max count — XLA needs static shapes —
    so a heavily skewed load pays padding bandwidth; what this path
    buys over moe_layer is exactness, not wire volume.)

    tokens[i]: (T_i, D) rank i's tokens (ragged T_i); assignments[i]:
    (T_i,) global expert ids; expert ``e`` lives on rank
    ``e // (n_experts // n)``. ``expert_fn(e, x)`` applies expert e to
    (K, D) tokens. Returns per-rank (T_i, D) outputs in original token
    order.

    On a communicator SPANNING controller processes (the unified
    ``tpurun`` world) each process acts only as its LOCAL member
    ranks: pass one tokens/assignments entry per local member (the
    hier v-collective convention) and the count matrix is completed
    with an allgather before routing.
    """
    import numpy as np

    n = comm.size
    if n_experts % n:
        raise ValueError(f"{n_experts} experts not divisible by {n} ranks")
    e_local = n_experts // n
    acting = (list(comm.local_comm_ranks)
              if getattr(comm, "spans_processes", False) else list(range(n)))
    if len(tokens) != len(acting) or len(assignments) != len(acting):
        raise ValueError(
            f"dropless_moe: need one tokens and assignments entry per "
            f"acting rank ({len(acting)}), got {len(tokens)} tokens / "
            f"{len(assignments)} assignments"
        )
    toks = [np.asarray(t) for t in tokens]
    # int32: expert ids are tiny, and 64-bit buffers do not traverse
    # the collectives under x64-off (the narrowing refusal)
    assign = [np.asarray(a).astype(np.int32) for a in assignments]
    d = toks[0].shape[1] if toks[0].ndim == 2 else 1

    # sort each acting rank's tokens by destination rank (stable keeps
    # order within a destination — needed to invert the permutation)
    owners = [a // e_local for a in assign]
    order = [np.argsort(o, kind="stable") for o in owners]
    local_counts = np.zeros((len(acting), n), dtype=np.int64)
    for pos in range(len(acting)):
        for j, k in zip(*np.unique(owners[pos], return_counts=True)):
            local_counts[pos, int(j)] = int(k)
    if len(acting) == n:
        counts = local_counts
    else:
        # complete the (n, n) matrix: every process contributes its
        # members' rows in comm-rank order (int32 on the wire — token
        # counts fit comfortably, and the hier path refuses int64
        # under x64-off rather than narrowing silently)
        counts = np.asarray(
            comm.allgather(local_counts.astype(np.int32))
        )[0].reshape(n, n).astype(np.int64)

    sendbufs = [toks[pos][order[pos]].reshape(-1)
                for pos in range(len(acting))]
    recv = comm.alltoallv(sendbufs, counts * d)
    # forward the expert ids alongside (same counts, 1 elem per token)
    recv_ids = comm.alltoallv(
        [assign[pos][order[pos]] for pos in range(len(acting))], counts
    )

    # each acting rank runs its local experts on the exact token set
    processed = []
    for pos, j in enumerate(acting):
        rt = np.asarray(recv[pos]).reshape(-1, d)
        ids = np.asarray(recv_ids[pos])
        out = np.empty_like(rt)
        for e in range(j * e_local, (j + 1) * e_local):
            sel = ids == e
            if sel.any():
                out[sel] = np.asarray(expert_fn(e, rt[sel]))
        processed.append(out.reshape(-1))

    # route back: the return counts matrix is the transpose
    back = comm.alltoallv(processed, counts.T * d)
    outputs = []
    for pos in range(len(acting)):
        sorted_out = np.asarray(back[pos]).reshape(-1, d)
        inv = np.empty_like(order[pos])
        inv[order[pos]] = np.arange(order[pos].shape[0])
        outputs.append(jnp.asarray(sorted_out[inv]))
    return outputs
