"""Canonical mesh-axis conventions for the parallelism strategies.

The reference maps ranks onto nodes/slots via rmaps (SURVEY §2.2); here
the mapping is a named multi-axis ``jax.sharding.Mesh`` over the ICI
torus. Axis order is chosen so the most bandwidth-hungry axis (tp) is
innermost — contiguous device ranges share ICI links, so tp collectives
ride the shortest paths, then sp/cp, then pp, then dp outermost (dp
gradients tolerate the longest routes / DCN).
"""

from __future__ import annotations

from typing import Optional, Sequence

import numpy as np

import jax
from jax.sharding import Mesh

from ..utils import jaxcompat as _jaxcompat

_jaxcompat.install()  # jax.shard_map/typeof on 0.4.x jaxlibs

AXIS_DP = "dp"  # data parallel (gradient psum)
AXIS_PP = "pp"  # pipeline stages (ppermute ring)
AXIS_SP = "sp"  # sequence/context parallel (alltoall / K-V ring)
AXIS_EP = "ep"  # expert parallel (token-routing all-to-all)
AXIS_TP = "tp"  # tensor parallel (psum/all_gather, innermost)

#: outermost -> innermost
CANONICAL_ORDER = (AXIS_DP, AXIS_PP, AXIS_SP, AXIS_EP, AXIS_TP)


def build_parallel_mesh(
    dp: int = 1, pp: int = 1, sp: int = 1, ep: int = 1, tp: int = 1,
    devices: Optional[Sequence] = None,
) -> Mesh:
    """Mesh with all five canonical axes (size-1 axes kept so PartitionSpecs
    are uniform regardless of which strategies are active)."""
    if devices is None:
        devices = jax.devices()
    shape = (dp, pp, sp, ep, tp)
    n = int(np.prod(shape))
    if n != len(devices):
        raise ValueError(
            f"dp*pp*sp*ep*tp = {n} but {len(devices)} devices available"
        )
    arr = np.asarray(devices, dtype=object).reshape(shape)
    return Mesh(arr, CANONICAL_ORDER)


def vary_like(x, ref):
    """Mark ``x`` varying over every manual axis ``ref`` varies over.

    shard_map's replication tracking (vma) types freshly-created
    constants as replicated; scan carries that will be overwritten with
    communicated data need their initial value cast to the same
    varying type or the carry types mismatch.
    """
    import jax as _jax
    from jax import lax as _lax

    want = getattr(_jax.typeof(ref), "vma", frozenset())
    have = getattr(_jax.typeof(x), "vma", frozenset())
    missing = tuple(sorted(want - have))
    return _lax.pcast(x, missing, to="varying") if missing else x


def vary_over(x, axes):
    """Mark ``x`` varying over the named manual axes (no-op for axes it
    already varies over, or outside shard_map)."""
    import jax as _jax
    from jax import lax as _lax

    have = getattr(_jax.typeof(x), "vma", frozenset())
    missing = tuple(a for a in axes if a not in have)
    return _lax.pcast(x, missing, to="varying") if missing else x


def axis_size_or_1(axis_name: str) -> int:
    """Axis size under trace; 1 when the axis is not in scope (so layer
    code can be written once and run with any subset of axes bound)."""
    from jax import lax

    try:
        return lax.psum(1, axis_name)
    except NameError:
        return 1
