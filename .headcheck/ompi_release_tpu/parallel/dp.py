"""Data parallelism: bucketed gradient allreduce.

The Horovod-style pattern the reference's ring allreduce serves
(``ompi/mca/coll/tuned/coll_tuned_allreduce.c:361``): every dp replica
holds a full gradient pytree; replicas psum (or mean) them. Bucketing
mirrors the reference's segmentation decision rules
(``coll_tuned_decision_fixed.c:70-80``) — small leaves are fused into
one flat collective so per-collective latency is amortized, exactly why
tuned switches algorithms by message size. Under XLA one psum per
bucket compiles to one fused ICI collective.

The fusion decision itself (greedy in-order same-dtype packing up to a
byte capacity) is :func:`coll.fusion.plan_buckets` — ONE definition
shared with the host-driver fusion buffer (``comm.fusion_buffer()``),
so the SPMD gradient path and the driver path coalesce identically.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..mca import var as mca_var


def register_vars() -> None:
    mca_var.register(
        "dp_bucket_bytes", "int", 4 * 1024 * 1024,
        "Gradient-allreduce bucket size in bytes (small leaves are "
        "flattened+concatenated up to this size per collective)",
    )


def allreduce_gradients(grads: Any, axis_name: str, *, mean: bool = True,
                        bucket_bytes: Optional[int] = None) -> Any:
    """Allreduce a gradient pytree over the dp axis.

    Leaves smaller than ``bucket_bytes`` (default: the dp_bucket_bytes
    config variable) are packed into flat buckets so each bucket is ONE
    psum; large leaves go through psum individually (XLA already
    tiles/pipelines a single large collective well).
    """
    if bucket_bytes is None:
        bucket_bytes = mca_var.get("dp_bucket_bytes", 4 * 1024 * 1024)
    leaves, treedef = jax.tree.flatten(grads)
    n = lax.psum(1, axis_name)

    big, small = [], []  # (index, leaf)
    for i, leaf in enumerate(leaves):
        (big if leaf.size * leaf.dtype.itemsize >= bucket_bytes
         else small).append((i, leaf))

    out = [None] * len(leaves)
    for i, leaf in big:
        r = lax.psum(leaf, axis_name)
        out[i] = r / n if mean and jnp.issubdtype(leaf.dtype, jnp.inexact) else r

    # pack small leaves into flat buckets, one psum per bucket — the
    # bucket plan comes from the shared fusion planner
    from ..coll.fusion import plan_buckets

    buckets = plan_buckets(
        (((i, leaf), leaf.size * leaf.dtype.itemsize, leaf.dtype)
         for i, leaf in small),
        bucket_bytes,
    )
    for bucket in buckets:
        flat = jnp.concatenate([l.reshape(-1) for _, l in bucket])
        red = lax.psum(flat, axis_name)
        off = 0
        for i, l in bucket:
            piece = red[off:off + l.size].reshape(l.shape)
            if mean and jnp.issubdtype(l.dtype, jnp.inexact):
                piece = piece / n
            out[i] = piece
            off += l.size

    return jax.tree.unflatten(treedef, out)


def replicate_check(x: jax.Array, axis_name: str) -> jax.Array:
    """Debug guard: max |x - bcast(x from rank0)| across the dp axis —
    the memchecker-style replica-divergence detector (SURVEY §5 race
    detection); 0 when replicas agree."""
    rank = lax.axis_index(axis_name)
    root = lax.psum(jnp.where(rank == 0, x, jnp.zeros_like(x)), axis_name)
    return lax.pmax(jnp.max(jnp.abs(x - root)), axis_name)
