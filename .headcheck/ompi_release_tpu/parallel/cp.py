"""Context parallelism — ring attention over a ppermute K/V ring.

The reference's ring-allreduce pass structure
(``coll_tuned_allreduce.c:297-361``) applied to attention: each rank
holds one block of the sequence; K/V blocks rotate around the ring
while every rank accumulates its queries' attention against each
passing block with an online (flash-style) softmax — numerically exact,
memory O(block), and the ppermute overlaps with the block matmuls
inside one compiled program.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

NEG_INF = -1e30


def _block_attn(q, k, v, mask):
    """One Q-block x K/V-block partial attention.

    q: (Hq, Sq, D), k/v: (Hkv, Sk, D); mask: (Sq, Sk) bool or None.
    Returns (out_unnorm, row_max, row_sumexp) for online combination.
    """
    scores = jnp.einsum(
        "hqd,hkd->hqk", q, k, preferred_element_type=jnp.float32
    ) / jnp.sqrt(q.shape[-1]).astype(jnp.float32)
    if mask is not None:
        scores = jnp.where(mask[None], scores, NEG_INF)
    m = jnp.max(scores, axis=-1)  # (H, Sq)
    # rows that are fully masked contribute nothing (exp underflows to 0)
    p = jnp.exp(scores - jnp.maximum(m, NEG_INF / 2)[..., None])
    l = jnp.sum(p, axis=-1)
    out = jnp.einsum("hqk,hkd->hqd", p.astype(v.dtype), v,
                     preferred_element_type=jnp.float32)
    return out, m, l


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                   axis_name: str = "sp", causal: bool = False) -> jax.Array:
    """Exact blockwise attention with K/V rotating over the ring.

    q/k/v: (H, S/n, D) per rank — rank i holds global positions
    [i*Sb, (i+1)*Sb). Returns (H, S/n, D) in q.dtype.
    """
    n = lax.psum(1, axis_name)
    rank = lax.axis_index(axis_name)
    h, sb, d = q.shape
    # send backwards so at step s the resident block originated at rank+s
    back = [(i, (i - 1) % n) for i in range(n)]

    qpos = rank * sb + jnp.arange(sb)

    from .mesh_axes import vary_like

    acc = vary_like(jnp.zeros((h, sb, d), jnp.float32), q)
    row_m = vary_like(jnp.full((h, sb), NEG_INF, jnp.float32), q)
    row_l = vary_like(jnp.zeros((h, sb), jnp.float32), q)

    def step(carry, s):
        acc, row_m, row_l, kc, vc = carry
        src = (rank + s) % n  # owner of the resident K/V block
        kpos = src * sb + jnp.arange(sb)
        mask = (qpos[:, None] >= kpos[None, :]) if causal else None
        out, m, l = _block_attn(q, kc, vc, mask)
        new_m = jnp.maximum(row_m, m)
        alpha = jnp.exp(row_m - new_m)  # rescale old accumulator
        beta = jnp.exp(m - new_m)  # rescale incoming block
        acc = acc * alpha[..., None] + out * beta[..., None]
        row_l = row_l * alpha + l * beta
        if n > 1:
            kc = lax.ppermute(kc, axis_name, back)
            vc = lax.ppermute(vc, axis_name, back)
        return (acc, new_m, row_l, kc, vc), None

    (acc, _, row_l, _, _), _ = lax.scan(
        step, (acc, row_m, row_l, k, v), jnp.arange(n)
    )
    # fully-masked rows (none under causal self-attn) would have l==0
    out = acc / jnp.maximum(row_l, 1e-30)[..., None]
    return out.astype(q.dtype)


def local_flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                          causal: bool = False,
                          block: Optional[int] = None) -> jax.Array:
    """Single-device blockwise-exact attention (the n=1 reference for
    ring_attention parity tests and the inner attn for Ulysses).

    q/k/v: (H, S, D).
    """
    h, s, d = q.shape
    scores = jnp.einsum("hqd,hkd->hqk", q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(d).astype(jnp.float32)
    if causal:
        i = jnp.arange(s)
        scores = jnp.where(i[:, None] >= i[None, :], scores, NEG_INF)
    p = jax.nn.softmax(scores, axis=-1)
    return jnp.einsum("hqk,hkd->hqd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32).astype(q.dtype)
