"""Model family built on the framework's parallelism strategies.

The reference ships no model layer (SURVEY §2.4); these models are the
validation workloads its communication patterns exist to serve, and the
flagship (``transformer.TpuLM``) exercises every strategy at once:
dp/ep-sharded batch, pp-pipelined trunk, sp ring attention, tp-sharded
matmuls and vocab, ep-routed experts.
"""

from .transformer import (  # noqa: F401
    ModelConfig, init_params, param_specs, forward_loss, make_train_step,
    make_forward,
)
