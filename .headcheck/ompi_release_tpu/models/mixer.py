"""TpuMixer — a second model family over the same parallel substrate.

MLP-Mixer (token-mixing MLP across patches + channel-mixing MLP across
features) is the all-matmul counterpoint to the attention-based
flagship: no softmax, no sequence ring — pure MXU work, which is
exactly the shape the substrate's TP/DP components were built for:

  - channel-mixing MLPs shard over ``tp`` with the same
    column-parallel/row-parallel pair the transformer's FFN uses
    (``parallel.tp`` — one psum per block, coll_tuned_allreduce's
    role inserted by shard_map's transpose);
  - the batch shards over ``dp``; replicated-parameter gradients are
    psummed by the same replication-tracking transpose as the
    flagship;
  - token mixing operates on the (small) patch axis and stays
    replicated across tp — sharding it would trade one transpose for
    an all-to-all with no arithmetic win at Mixer's patch counts.

Same functional conventions as ``models.transformer``: plain-dict
params, ``param_specs`` PartitionSpecs, ``make_forward`` /
``make_train_step`` jitted entry points over a mesh from
``parallel.mesh_axes.build_parallel_mesh``.
"""

from __future__ import annotations

import dataclasses
import math
from functools import partial
from typing import Any, Dict

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..utils import jaxcompat as _jaxcompat

_jaxcompat.install()  # jax.shard_map/typeof on 0.4.x jaxlibs

from ..parallel import tp as tp_mod


@dataclasses.dataclass(frozen=True)
class MixerConfig:
    n_patches: int = 64
    d_model: int = 128
    d_token: int = 64     # token-mixing hidden dim
    d_channel: int = 512  # channel-mixing hidden dim (tp-sharded)
    n_layers: int = 4
    n_classes: int = 10
    dtype: Any = jnp.bfloat16

    def validate(self, mesh: Mesh) -> None:
        ax = dict(mesh.shape)
        if self.d_channel % ax.get("tp", 1):
            raise ValueError("d_channel must divide by tp")
        for name in ("pp", "sp", "ep"):
            if ax.get(name, 1) != 1:
                raise ValueError(
                    f"TpuMixer parallelizes over dp/tp only; axis "
                    f"'{name}' must be 1 (got {ax[name]})"
                )


def init_params(rng: jax.Array, cfg: MixerConfig) -> Dict:
    k = jax.random.split(rng, 5)
    dt = cfg.dtype

    def norm(key, *shape):
        scale = 1.0 / math.sqrt(shape[-2])
        return (jax.random.normal(key, shape, jnp.float32)
                * scale).astype(dt)

    l = cfg.n_layers
    return {
        "layers": {
            "ln1": jnp.ones((l, cfg.d_model), jnp.float32),
            # token mixing: operates on the patch axis (replicated)
            "wt1": norm(k[0], l, cfg.n_patches, cfg.d_token),
            "wt2": norm(k[1], l, cfg.d_token, cfg.n_patches),
            "ln2": jnp.ones((l, cfg.d_model), jnp.float32),
            # channel mixing: the FFN pair, tp-sharded
            "wc1": norm(k[2], l, cfg.d_model, cfg.d_channel),
            "wc2": norm(k[3], l, cfg.d_channel, cfg.d_model),
        },
        "ln_f": jnp.ones((cfg.d_model,), jnp.float32),
        "head": norm(k[4], cfg.d_model, cfg.n_classes),
    }


def param_specs(cfg: MixerConfig) -> Dict:
    return {
        "layers": {
            "ln1": P(None, None),
            "wt1": P(None, None, None),
            "wt2": P(None, None, None),
            "ln2": P(None, None),
            "wc1": P(None, None, "tp"),   # column parallel
            "wc2": P(None, "tp", None),   # row parallel
        },
        "ln_f": P(),
        "head": P(None, None),
    }


def batch_spec() -> P:
    return P("dp")


def _layernorm(x: jax.Array, g: jax.Array) -> jax.Array:
    x32 = x.astype(jnp.float32)
    mu = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean((x32 - mu) ** 2, axis=-1, keepdims=True)
    return ((x32 - mu) * lax.rsqrt(var + 1e-6) * g).astype(x.dtype)


def _layer(lp: Dict, x: jax.Array) -> jax.Array:
    """One mixer block. x: (B_loc, P, D)."""
    # token mixing across patches (replicated weights)
    h = _layernorm(x, lp["ln1"])
    h = jnp.swapaxes(h, 1, 2)  # (B, D, P)
    h = jnp.einsum("bdp,pt->bdt", h, lp["wt1"],
                   preferred_element_type=jnp.float32)
    h = jax.nn.gelu(h)
    h = jnp.einsum("bdt,tp->bdp", h, lp["wt2"],
                   preferred_element_type=jnp.float32)
    x = x + jnp.swapaxes(h, 1, 2).astype(x.dtype)

    # channel mixing: the tp-sharded FFN pair (one psum, in row_parallel)
    h = _layernorm(x, lp["ln2"])
    h = tp_mod.column_parallel(h, lp["wc1"], axis_name="tp")
    h = jax.nn.gelu(h)
    h = tp_mod.row_parallel(h, lp["wc2"], axis_name="tp")
    return x + h.astype(x.dtype)


def forward_loss(cfg: MixerConfig, params: Dict, patches: jax.Array,
                 labels: jax.Array) -> jax.Array:
    """patches: (B_loc, P, D) pre-embedded patch features;
    labels: (B_loc,) int32. Returns the global mean xent."""
    x = patches.astype(cfg.dtype)

    def body(x, lp):
        return _layer(lp, x), None

    x, _ = lax.scan(body, x, params["layers"])
    x = _layernorm(x, params["ln_f"])
    pooled = jnp.mean(x.astype(jnp.float32), axis=1)  # (B, D)
    logits = pooled @ params["head"].astype(jnp.float32)
    logp = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
    dp_n = lax.psum(1, "dp")
    total = nll.shape[0] * dp_n
    return lax.psum(jnp.sum(nll) / total, "dp")


def _loss_spmd(cfg: MixerConfig, mesh: Mesh):
    return jax.shard_map(
        partial(forward_loss, cfg),
        mesh=mesh,
        in_specs=(param_specs(cfg), batch_spec(), batch_spec()),
        out_specs=P(),
    )


def shard_params(params: Dict, cfg: MixerConfig, mesh: Mesh) -> Dict:
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)),
        params, param_specs(cfg),
    )


def make_batch_sharding(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, batch_spec())


def make_forward(cfg: MixerConfig, mesh: Mesh):
    cfg.validate(mesh)
    return jax.jit(_loss_spmd(cfg, mesh))


def make_train_step(cfg: MixerConfig, mesh: Mesh, optimizer):
    cfg.validate(mesh)
    loss_fn = _loss_spmd(cfg, mesh)

    @jax.jit
    def step(params, opt_state, patches, labels):
        loss, grads = jax.value_and_grad(loss_fn)(params, patches, labels)
        updates, opt_state = optimizer.update(grads, opt_state, params)
        params = jax.tree.map(lambda p, u: p + u.astype(p.dtype),
                              params, updates)
        return params, opt_state, loss

    return step
