"""Performance variables (pvars): runtime counters exposed for tools.

Analogue of ``opal/mca/base/mca_base_pvar.c`` + the MPI_T performance
variable interface (``ompi/mpi/tool/``): components register named
counters/timers/levels; tools (``tpu_info``, tracing layer) read and reset
them without recompiling anything.
"""

from __future__ import annotations

import enum
import math
import threading
import time
from typing import Any, Callable, Dict, List, Optional


class PvarClass(enum.Enum):
    COUNTER = "counter"        # monotonically increasing
    LEVEL = "level"            # current utilization level
    HIGHWATERMARK = "highwatermark"
    TIMER = "timer"            # accumulated seconds
    STATE = "state"            # discrete state value
    HISTOGRAM = "histogram"    # log2-bucketed distribution
    AGGREGATE = "aggregate"    # count/sum/min/max summary


class Pvar:
    def __init__(self, name: str, pclass: PvarClass, help: str = "",
                 getter: Optional[Callable[[], Any]] = None) -> None:
        self.name = name
        self.pclass = pclass
        self.help = help
        self._value: float = 0
        self._getter = getter
        self._lock = threading.Lock()

    def add(self, delta: float = 1) -> None:
        with self._lock:
            self._value += delta

    def set(self, value: float) -> None:
        with self._lock:
            if self.pclass is PvarClass.HIGHWATERMARK:
                self._value = max(self._value, value)
            else:
                self._value = value

    def read(self) -> Any:
        if self._getter is not None:
            return self._getter()
        with self._lock:
            return self._value

    def reset(self) -> None:
        with self._lock:
            self._value = 0

    class _TimerCtx:
        def __init__(self, pvar: "Pvar") -> None:
            self._pvar = pvar

        def __enter__(self):
            self._t0 = time.perf_counter()
            return self

        def __exit__(self, *exc):
            self._pvar.add(time.perf_counter() - self._t0)
            return False

    def timing(self) -> "_TimerCtx":
        assert self.pclass is PvarClass.TIMER
        return Pvar._TimerCtx(self)


class Aggregate(Pvar):
    """count/sum/min/max summary pvar (the MPI_T aggregate class).

    The ``*_locked`` helpers let :class:`Histogram` extend the summary
    under ONE lock acquisition (``self._lock`` is not reentrant).
    """

    def __init__(self, name: str, help: str = "",
                 pclass: PvarClass = PvarClass.AGGREGATE) -> None:
        super().__init__(name, pclass, help)
        self._count = 0
        self._sum = 0.0
        self._min: Optional[float] = None
        self._max: Optional[float] = None

    def _observe_locked(self, v: float) -> None:
        self._count += 1
        self._sum += v
        self._min = v if self._min is None else min(self._min, v)
        self._max = v if self._max is None else max(self._max, v)

    def _read_locked(self) -> Dict[str, Any]:
        return {
            "count": self._count, "sum": self._sum,
            "min": 0.0 if self._min is None else self._min,
            "max": 0.0 if self._max is None else self._max,
        }

    def _reset_locked(self) -> None:
        self._count = 0
        self._sum = 0.0
        self._min = None
        self._max = None

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._observe_locked(v)

    # generic bump (pvar-agnostic call sites) records an observation
    def add(self, delta: float = 1) -> None:
        self.observe(delta)

    def read(self) -> Dict[str, Any]:
        with self._lock:
            return self._read_locked()

    def reset(self) -> None:
        with self._lock:
            self._reset_locked()


class Histogram(Aggregate):
    """Log2-bucketed distribution pvar (latencies, message sizes).

    ``observe(v)`` files v > 0 under the bucket whose upper bound is
    the smallest power of two >= v (exponent via ``frexp`` — no float
    log rounding at the boundaries); v <= 0 counts under the 0-bound
    bucket. ``read()`` returns the Aggregate summary plus ``buckets``
    mapping each upper bound to its *per-bucket* (non-cumulative)
    count; the Prometheus exporter cumulates at exposition time.
    """

    def __init__(self, name: str, help: str = "") -> None:
        super().__init__(name, help, PvarClass.HISTOGRAM)
        self._exp: Dict[int, int] = {}  # e -> count of v in (2^(e-1), 2^e]
        self._zero = 0

    def observe(self, value: float) -> None:
        v = float(value)
        with self._lock:
            self._observe_locked(v)
            if v <= 0:
                self._zero += 1
                return
            m, e = math.frexp(v)  # v = m * 2**e with 0.5 <= m < 1
            if m == 0.5:  # exact power of two belongs to the bucket below
                e -= 1
            self._exp[e] = self._exp.get(e, 0) + 1

    def read(self) -> Dict[str, Any]:
        with self._lock:
            out = self._read_locked()
            buckets: Dict[float, int] = {}
            if self._zero:
                buckets[0.0] = self._zero
            for e in sorted(self._exp):
                buckets[float(2.0 ** e)] = self._exp[e]
            out["buckets"] = buckets
            return out

    def reset(self) -> None:
        with self._lock:
            self._reset_locked()
            self._exp.clear()
            self._zero = 0


class PvarRegistry:
    def __init__(self) -> None:
        self._pvars: Dict[str, Pvar] = {}
        self._lock = threading.Lock()

    def register(self, name: str, pclass: PvarClass = PvarClass.COUNTER,
                 help: str = "", getter: Optional[Callable[[], Any]] = None) -> Pvar:
        with self._lock:
            if name in self._pvars:
                return self._pvars[name]
            if pclass is PvarClass.HISTOGRAM:
                pv: Pvar = Histogram(name, help)
            elif pclass is PvarClass.AGGREGATE:
                pv = Aggregate(name, help)
            else:
                pv = Pvar(name, pclass, help, getter)
            self._pvars[name] = pv
            return pv

    def lookup(self, name: str) -> Optional[Pvar]:
        with self._lock:
            return self._pvars.get(name)

    def read_all(self) -> Dict[str, Any]:
        with self._lock:
            return {n: p.read() for n, p in sorted(self._pvars.items())}

    def describe_all(self) -> List[Dict[str, Any]]:
        with self._lock:
            return [
                {"name": p.name, "class": p.pclass.value, "help": p.help,
                 "value": p.read()}
                for p in sorted(self._pvars.values(), key=lambda p: p.name)
            ]

    def reset_all(self) -> None:
        with self._lock:
            for p in self._pvars.values():
                p.reset()

    def _reset_for_tests(self) -> None:
        with self._lock:
            self._pvars.clear()


PVARS = PvarRegistry()


def counter(name: str, help: str = "") -> Pvar:
    return PVARS.register(name, PvarClass.COUNTER, help)


def timer(name: str, help: str = "") -> Pvar:
    return PVARS.register(name, PvarClass.TIMER, help)


def highwatermark(name: str, help: str = "") -> Pvar:
    return PVARS.register(name, PvarClass.HIGHWATERMARK, help)


def histogram(name: str, help: str = "") -> Histogram:
    pv = PVARS.register(name, PvarClass.HISTOGRAM, help)
    assert isinstance(pv, Histogram), f"{name} registered as {pv.pclass}"
    return pv


def aggregate(name: str, help: str = "") -> Aggregate:
    pv = PVARS.register(name, PvarClass.AGGREGATE, help)
    assert isinstance(pv, Aggregate), f"{name} registered as {pv.pclass}"
    return pv
