"""MPI_T tool interface — handle-based introspection of control and
performance variables.

The reference's ``ompi/mpi/tool`` exposes ``mca_base_var``/``pvar``
through MPI_T_cvar_* / MPI_T_pvar_* handles and sessions; tools bind a
handle to a variable, then read/write/reset through it. Same contract
over this framework's registries: indices are stable within a session,
cvar writes go through the registry's override layer (source=TOOL
wins like an API set), pvar sessions snapshot at start so reads can be
session-relative (the MPI_T pvar session semantic).
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from ..utils.errors import ErrorCode, MPIError
from . import pvar as pvar_mod
from . import var as var_mod


class CvarHandle:
    def __init__(self, var) -> None:
        self._var = var

    @property
    def name(self) -> str:
        return self._var.name

    def read(self) -> Any:
        return var_mod.VARS.get(self._var.name)

    def write(self, value: Any) -> None:
        var_mod.VARS.set_value(self._var.name, value)

    def info(self) -> Dict[str, Any]:
        return self._var.describe()


def _session_delta(cur: Any, base: Any) -> Any:
    """Session-relative value since the handle's start snapshot.

    Scalars subtract; structured reads (HISTOGRAM/AGGREGATE) subtract
    elementwise — counts, sums, and per-bucket counts are cumulative so
    deltas are meaningful, while extrema ("min"/"max") are not
    invertible over a window and pass through as current values.
    """
    if isinstance(cur, dict):
        bd = base if isinstance(base, dict) else {}
        return {
            k: (v if k in ("min", "max") else _session_delta(v, bd.get(k, 0)))
            for k, v in cur.items()
        }
    if isinstance(cur, (int, float)) and isinstance(base, (int, float)):
        return float(cur) - float(base)
    return cur


class PvarHandle:
    def __init__(self, session: "PvarSession", pv) -> None:
        self._session = session
        self._pv = pv
        self._base: Any = 0.0
        self._started = False

    @property
    def name(self) -> str:
        return self._pv.name

    def start(self) -> None:
        self._base = self._pv.read()
        self._started = True

    def stop(self) -> None:
        self._started = False

    def read(self) -> Any:
        """Session-relative when started (delta since start); scalar
        pvars read as float, HISTOGRAM/AGGREGATE as their dict form."""
        v = self._pv.read()
        if self._started:
            return _session_delta(v, self._base)
        return float(v) if isinstance(v, (int, float)) else v

    def reset(self) -> None:
        self._base = self._pv.read()


class PvarSession:
    """MPI_T_pvar_session: scopes handle lifetimes."""

    def __init__(self) -> None:
        self._handles: List[PvarHandle] = []
        self._open = True

    def handle(self, name: str) -> PvarHandle:
        if not self._open:
            raise MPIError(ErrorCode.ERR_ARG, "pvar session closed")
        pv = pvar_mod.PVARS.lookup(name)
        if pv is None:
            raise MPIError(ErrorCode.ERR_ARG, f"unknown pvar {name!r}")
        h = PvarHandle(self, pv)
        self._handles.append(h)
        return h

    def free(self) -> None:
        self._handles.clear()
        self._open = False


class Mpit:
    """MPI_T_init_thread analogue: the tool-facing session object."""

    def __init__(self) -> None:
        self._cvar_names = var_mod.VARS.names()

    # -- control variables -------------------------------------------------
    def cvar_get_num(self) -> int:
        self._cvar_names = var_mod.VARS.names()
        return len(self._cvar_names)

    def cvar_get_info(self, index: int) -> Dict[str, Any]:
        name = self._cvar_names[index]
        return var_mod.VARS.lookup(name).describe()

    def cvar_handle(self, name_or_index) -> CvarHandle:
        if isinstance(name_or_index, int):
            name_or_index = self._cvar_names[name_or_index]
        v = var_mod.VARS.lookup(name_or_index)
        if v is None:
            raise MPIError(ErrorCode.ERR_ARG,
                           f"unknown cvar {name_or_index!r}")
        return CvarHandle(v)

    # -- performance variables ---------------------------------------------
    def pvar_get_num(self) -> int:
        return len(pvar_mod.PVARS.read_all())

    def pvar_names(self) -> List[str]:
        return sorted(pvar_mod.PVARS.read_all())

    def pvar_session(self) -> PvarSession:
        return PvarSession()
