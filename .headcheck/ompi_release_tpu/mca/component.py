"""Framework / component / module lifecycle — the MCA analogue.

The reference's single most important architectural idea (SURVEY §1):
every subsystem is a *framework* (fixed interface) with N *components*
(plugins) that produce *modules* (instances), selected at runtime by
integer priority and user include/exclude lists. Lifecycle implemented
once here, mirroring ``opal/mca/base/mca_base_framework.c``,
``mca_base_components_open.c`` and ``mca_base_components_select.c``.

Selection syntax follows the reference: the MCA variable named after the
framework holds a comma list of components to include, or ``^a,b`` to
exclude (``mca_base_components_filter``). Priority query mirrors
``mca_base_select.c``: each opened component is asked for (priority,
module); highest priority wins; ``select_all`` returns every available
module sorted by priority (the per-communicator coll selection pattern,
``ompi/mca/coll/base/coll_base_comm_select.c:66-88``).
"""

from __future__ import annotations

import enum
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from . import var as mca_var
from ..utils import output


class ComponentState(enum.Enum):
    REGISTERED = "registered"
    OPENED = "opened"
    CLOSED = "closed"


class Component:
    """Base class for all components (plugins).

    Subclasses set ``NAME`` and ``PRIORITY`` and override ``query``;
    ``register_vars`` is called once at framework open so the component
    can register its MCA variables.
    """

    NAME: str = "base"
    PRIORITY: int = 0
    VERSION: Tuple[int, int, int] = (1, 0, 0)

    def __init__(self) -> None:
        self.framework: Optional["Framework"] = None
        self.state = ComponentState.REGISTERED

    # lifecycle ----------------------------------------------------------
    def register_vars(self) -> None:
        """Register this component's config variables (override)."""

    def open(self) -> bool:
        """Return False if the component cannot run in this environment."""
        return True

    def close(self) -> None:
        pass

    def query(self, ctx: Any = None) -> Optional[Tuple[int, Any]]:
        """Return (priority, module) if usable for ``ctx``, else None.

        Default: usable everywhere at the component's static priority,
        module is the component itself.
        """
        return (self.priority, self)

    # helpers ------------------------------------------------------------
    @property
    def priority(self) -> int:
        """Effective priority — overridable via ``<fw>_<name>_priority``."""
        if self.framework is not None:
            return mca_var.get(self._prefix() + "_priority", self.PRIORITY)
        return self.PRIORITY

    def _prefix(self) -> str:
        fw = self.framework.name if self.framework else "unknown"
        return f"{fw}_{self.NAME}"

    def register_priority_var(self) -> None:
        mca_var.register(
            self._prefix() + "_priority", "int", self.PRIORITY,
            f"Selection priority of the {self.NAME} component of the "
            f"{self.framework.name if self.framework else '?'} framework",
        )


class Framework:
    """One framework: a fixed interface + a set of registered components."""

    def __init__(self, name: str, description: str = "") -> None:
        self.name = name
        self.description = description
        self._components: Dict[str, Component] = {}
        self._opened = False
        # stream name == framework name so the registered
        # ``<name>_verbose`` variable is the one the stream reads
        self._log = output.stream(name)
        mca_var.register(
            name, "str", "",
            f"Comma list of {name} components to include "
            f"(prefix with ^ to exclude instead)",
        )
        mca_var.register(
            f"{name}_verbose", "int", 0,
            f"Verbosity level of the {name} framework",
        )

    # registration -------------------------------------------------------
    def register(self, component: Component) -> Component:
        if component.NAME in self._components:
            return self._components[component.NAME]
        component.framework = self
        self._components[component.NAME] = component
        # if the framework is already open, the new component is opened
        # lazily by available() (respecting the include/exclude filter)
        return component

    def components(self) -> List[Component]:
        return sorted(self._components.values(), key=lambda c: c.NAME)

    def lookup(self, name: str) -> Optional[Component]:
        return self._components.get(name)

    # open/close ---------------------------------------------------------
    def _open_one(self, comp: Component) -> None:
        comp.register_priority_var()
        comp.register_vars()
        try:
            ok = comp.open()
        except Exception as exc:  # a broken plugin must not kill the job
            self._log.verbose(1, f"component {comp.NAME} failed open: {exc}")
            ok = False
        comp.state = ComponentState.OPENED if ok else ComponentState.CLOSED

    def open(self) -> None:
        # only open components passing the include/exclude filter — an
        # excluded component's open() must never run (the user may have
        # excluded it precisely because its open misbehaves). If the
        # selection variable changes later, available() lazily opens
        # newly-included components on demand.
        if self._opened:
            return
        self._opened = True
        for comp in self._filtered():
            self._open_one(comp)

    def close(self) -> None:
        for comp in self._components.values():
            if comp.state is ComponentState.OPENED:
                comp.close()
                comp.state = ComponentState.CLOSED
        self._opened = False

    # selection ----------------------------------------------------------
    def _filtered(self) -> List[Component]:
        """Apply the include/exclude list from the framework variable."""
        spec = (mca_var.get(self.name) or "").strip()
        comps = list(self._components.values())
        if not spec:
            return comps
        if spec.startswith("^"):
            excluded = {s.strip() for s in spec[1:].split(",") if s.strip()}
            return [c for c in comps if c.NAME not in excluded]
        included = [s.strip() for s in spec.split(",") if s.strip()]
        by_name = {c.NAME: c for c in comps}
        missing = [n for n in included if n not in by_name]
        if missing:
            output.show_help(
                "mca", "component-not-found",
                framework=self.name, names=", ".join(missing),
                available=", ".join(sorted(by_name)),
            )
        return [by_name[n] for n in included if n in by_name]

    def available(self, ctx: Any = None) -> List[Tuple[int, Component, Any]]:
        """All opened components whose query succeeds, best first."""
        if not self._opened:
            self.open()
        out: List[Tuple[int, Component, Any]] = []
        for comp in self._filtered():
            if comp.state is ComponentState.REGISTERED:
                self._open_one(comp)  # included after a selection change
            if comp.state is not ComponentState.OPENED:
                continue
            res = comp.query(ctx)
            if res is None:
                continue
            prio, module = res
            out.append((prio, comp, module))
        out.sort(key=lambda t: (-t[0], t[1].NAME))
        return out

    def select(self, ctx: Any = None) -> Any:
        """Highest-priority usable module, or raise (no component found)."""
        avail = self.available(ctx)
        if not avail:
            output.show_help("mca", "no-component", framework=self.name)
            raise RuntimeError(
                f"no usable component in framework {self.name!r}"
            )
        prio, comp, module = avail[0]
        self._log.verbose(
            1, f"selected component {comp.NAME} (priority {prio})"
        )
        return module

    def select_all(self, ctx: Any = None) -> List[Any]:
        return [m for _, _, m in self.available(ctx)]


class _FrameworkRegistry:
    """Process-global framework table (for tpu_info introspection)."""

    def __init__(self) -> None:
        self._frameworks: Dict[str, Framework] = {}
        self._lock = threading.Lock()

    def framework(self, name: str, description: str = "") -> Framework:
        with self._lock:
            fw = self._frameworks.get(name)
            if fw is None:
                fw = Framework(name, description)
                self._frameworks[name] = fw
            return fw

    def all(self) -> List[Framework]:
        with self._lock:
            return [self._frameworks[n] for n in sorted(self._frameworks)]

    def _reset_for_tests(self) -> None:
        with self._lock:
            for fw in self._frameworks.values():
                fw.close()
            self._frameworks.clear()


FRAMEWORKS = _FrameworkRegistry()


def framework(name: str, description: str = "") -> Framework:
    return FRAMEWORKS.framework(name, description)


output.register_help(
    "mca",
    {
        "component-not-found": (
            "Requested {framework} component(s) not found: {names}\n"
            "Available components: {available}"
        ),
        "no-component": (
            "No usable component found for framework {framework!r}; the "
            "job cannot continue."
        ),
    },
)
