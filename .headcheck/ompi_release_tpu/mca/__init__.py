"""MCA analogue: typed config variables, pvars, framework/component system."""

from .var import VARS, VarLevel, VarScope, VarSource, get, parse_size, register, set_value
from .pvar import PVARS, Pvar, PvarClass, counter, highwatermark, timer
from .component import FRAMEWORKS, Component, Framework, framework

__all__ = [
    "VARS", "VarLevel", "VarScope", "VarSource", "get", "parse_size",
    "register", "set_value",
    "PVARS", "Pvar", "PvarClass", "counter", "highwatermark", "timer",
    "FRAMEWORKS", "Component", "Framework", "framework",
]
