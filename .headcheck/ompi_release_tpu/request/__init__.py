"""Request/completion engine — the ``ompi/request`` analogue."""

from .request import (  # noqa: F401
    Request, GeneralizedRequest, Status, RequestState,
    test, test_all, test_any, wait, wait_all, wait_any, wait_some,
)
