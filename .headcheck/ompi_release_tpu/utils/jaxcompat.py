"""Version-compat shims for the jax surface this framework uses.

The framework targets the modern jax API (``jax.shard_map``,
``jax.typeof``); 0.4.x jaxlibs ship the same machinery under
``jax.experimental.shard_map`` / core avals. :func:`install` aliases
the missing names onto the jax module once, so every call site (library
and test suite alike) uses the one modern spelling. No-op on jax
versions that already export them.
"""

from __future__ import annotations


def install() -> None:
    import jax

    if not hasattr(jax, "shard_map"):
        try:
            from jax.experimental.shard_map import shard_map
        except ImportError:
            return  # neither spelling: let call sites raise their own

        def _compat_shard_map(f, *args, **kwargs):
            # 0.4.x's check_rep cannot type code written for the
            # modern varying-axes (vma) system (scan carries cast via
            # pvary read as mismatched) — it is validation only, so
            # drop the knob and disable it
            kwargs.pop("check_vma", None)
            kwargs["check_rep"] = False
            return shard_map(f, *args, **kwargs)

        jax.shard_map = _compat_shard_map
    if not hasattr(jax, "typeof"):
        try:
            # call sites only getattr(.vma, default) off the result, so
            # the 0.4.x aval (no vma tracking) is a faithful stand-in
            jax.typeof = jax.core.get_aval
        except AttributeError:
            pass
    from jax import lax

    if not hasattr(lax, "pvary"):
        # no varying-axes type system on 0.4.x: pvary/pcast are identities
        lax.pvary = lambda x, axis_name: x
    if not hasattr(lax, "pcast"):
        lax.pcast = lambda x, axes, to=None, **kw: x

    import inspect

    try:
        params = inspect.signature(jax.ShapeDtypeStruct.__init__).parameters
    except (TypeError, ValueError):
        params = {}
    if params and "vma" not in params and not any(
        p.kind is inspect.Parameter.VAR_KEYWORD for p in params.values()
    ):
        _Orig = jax.ShapeDtypeStruct

        class _ShapeDtypeStruct(_Orig):  # type: ignore[misc,valid-type]
            """Accepts-and-drops ``vma=`` (no varying-axes tracking on
            0.4.x, so the annotation is vacuous there)."""

            def __init__(self, shape, dtype, *args, vma=None, **kwargs):
                super().__init__(shape, dtype, *args, **kwargs)

        jax.ShapeDtypeStruct = _ShapeDtypeStruct
