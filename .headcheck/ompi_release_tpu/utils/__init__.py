"""Utility layer: logging streams, help catalogs, error codes."""

from . import output
from .errors import Errhandler, MPIError, ErrorCode

__all__ = ["output", "Errhandler", "MPIError", "ErrorCode"]
