"""Verbosity-stream logging + tagged help catalogs.

Re-imagination of ``opal/util/output.c`` (numbered verbosity streams per
subsystem, routable to stderr/file) and ``opal/util/show_help.c``
(tag-indexed user-facing message catalogs, de-duplicated). Stream
verbosity is controlled by the ``<name>_verbose`` MCA variable so every
subsystem gets a debug knob for free, exactly as in the reference.
"""

from __future__ import annotations

import os
import sys
import threading
import time
from typing import Any, Dict, Optional, TextIO

_lock = threading.RLock()
_streams: Dict[str, "Stream"] = {}
_help_catalogs: Dict[str, Dict[str, str]] = {}
_help_seen: set = set()
_sink: Optional[TextIO] = None  # default: stderr; tests may redirect


def set_sink(fh: Optional[TextIO]) -> None:
    global _sink
    with _lock:
        _sink = fh


def _out() -> TextIO:
    return _sink if _sink is not None else sys.stderr


class Stream:
    """One named, leveled output stream (``opal_output_open`` analogue)."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._var_name = name.replace(".", "_").replace("/", "_") + "_verbose"

    @property
    def verbosity(self) -> int:
        # late import to avoid an import cycle with mca.var
        from ..mca import var as mca_var

        v = mca_var.get(self._var_name)
        if v is None:
            v = os.environ.get(mca_var.ENV_PREFIX + self._var_name)
        try:
            return int(v) if v is not None else 0
        except (TypeError, ValueError):
            # logging must never crash the caller on a garbage env value
            return 0

    def _emit(self, prefix: str, msg: str) -> None:
        pid = os.getpid()
        line = f"[{time.strftime('%H:%M:%S')}] [{pid}] {prefix}{self.name}: {msg}\n"
        with _lock:
            _out().write(line)
            _out().flush()

    def verbose(self, level: int, msg: str) -> None:
        if self.verbosity >= level:
            self._emit("", msg)

    def info(self, msg: str) -> None:
        self._emit("", msg)

    def warn(self, msg: str) -> None:
        self._emit("WARNING: ", msg)

    def error(self, msg: str) -> None:
        self._emit("ERROR: ", msg)


def stream(name: str) -> Stream:
    with _lock:
        st = _streams.get(name)
        if st is None:
            st = Stream(name)
            _streams[name] = st
        return st


def register_help(catalog: str, messages: Dict[str, str]) -> None:
    """Register a tag→template catalog (the ``help-*.txt`` analogue)."""
    with _lock:
        _help_catalogs.setdefault(catalog, {}).update(messages)


def show_help(catalog: str, tag: str, *, once: bool = True, **kwargs: Any) -> str:
    """Emit a formatted user-facing message; de-duplicated per tag.

    The reference aggregates identical help messages across ranks
    (``opal/util/show_help.c``); in-process we de-duplicate per
    (catalog, tag) unless ``once=False``.
    """
    with _lock:
        template = _help_catalogs.get(catalog, {}).get(tag)
        if template is None:
            text = f"[help {catalog}:{tag}] (no catalog entry) {kwargs}"
        else:
            try:
                text = template.format(**kwargs)
            except Exception:
                text = template + f" {kwargs}"
        key = (catalog, tag)
        if once and key in _help_seen:
            return text
        _help_seen.add(key)
        banner = "-" * 60
        _out().write(f"{banner}\n{text}\n{banner}\n")
        _out().flush()
        return text


def _reset_for_tests() -> None:
    with _lock:
        _help_seen.clear()
