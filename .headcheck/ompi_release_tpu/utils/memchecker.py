"""Donated-buffer liveness checking — the memchecker analogue.

The reference's ``opal/mca/memchecker/valgrind`` marks user buffers
inaccessible while the library owns them and defined again at delivery
(``memchecker_valgrind_module.c:98-151``; ob1 annotates recv buffers
across their lifetime, ``pml_ob1_recvreq.c:87,509``), catching
read-before-arrival and buffer-reuse races in user code.

The TPU-native ownership transfer is **buffer donation**: an array
passed through ``jax.jit(..., donate_argnums=...)`` is consumed — its
HBM is reused for the output and any later access is a bug. jax does
raise on such access, but its error carries no provenance (*which*
operation consumed the buffer, *where*). This module is the
provenance layer:

* :func:`mark_donated` — record that an operation took ownership
  (the MAKE_MEM_NOACCESS annotation).
* :func:`check` / :func:`assert_all_alive` — validate liveness before
  use; a donated/deleted array raises with the recorded owner, not a
  bare "Array has been deleted".
* :func:`donating_jit` — ``jax.jit`` with ``donate_argnums`` whose
  call-time wrapper auto-marks every donated input.

Enabled unconditionally: the bookkeeping is O(1) dict ops per
donation, nothing touches the hot compiled path.
"""

from __future__ import annotations

import threading
import weakref
from typing import Any, Dict, Optional, Sequence, Tuple

from ..mca import pvar
from .errors import ErrorCode, MPIError

_donations = pvar.counter(
    "memchecker_donations", "buffers marked donated (ownership taken)"
)
_violations = pvar.counter(
    "memchecker_violations", "accesses to donated/deleted buffers caught"
)

_lock = threading.Lock()
#: id(array) -> (owner description, weakref) — weakrefs let entries
#: vanish with the array (ids are reused; a live entry whose weakref
#: died is stale and ignored)
_owners: Dict[int, Tuple[str, Any]] = {}


def _is_deleted(arr) -> bool:
    fn = getattr(arr, "is_deleted", None)
    if fn is None:
        return False
    try:
        return bool(fn())
    except Exception:
        return False


def mark_donated(arr, owner: str) -> None:
    """Record that ``owner`` (an operation name/site) took ownership
    of ``arr``'s buffer. Later :func:`check` failures name it."""
    _donations.add()
    key = id(arr)
    try:
        # the weakref's callback removes the entry when the array is
        # garbage-collected — without it the registry grows one entry
        # per donated buffer for the life of the process
        ref = weakref.ref(
            arr, lambda _r, _k=key: _owners.pop(_k, None)
        )
    except TypeError:
        ref = None
    with _lock:
        _owners[key] = (owner, ref)


def owner_of(arr) -> Optional[str]:
    """The recorded owner that consumed ``arr``, if any."""
    with _lock:
        entry = _owners.get(id(arr))
    if entry is None:
        return None
    owner, ref = entry
    if ref is not None and ref() is not arr:
        return None  # stale id reuse
    return owner


def check(arr, what: str = "buffer"):
    """Validate ``arr`` is live; returns it. A donated/deleted array
    raises ERR_BUFFER naming the operation that consumed it — the
    read-before-arrival / buffer-reuse diagnostic."""
    if _is_deleted(arr):
        _violations.add()
        owner = owner_of(arr)
        raise MPIError(
            ErrorCode.ERR_BUFFER,
            f"{what} was donated"
            + (f" to {owner}" if owner else "")
            + " and its memory has been reused — using it again is a "
            "buffer-liveness violation (memchecker)",
        )
    return arr


def assert_all_alive(tree, what: str = "pytree") -> None:
    """Walk a pytree and :func:`check` every array leaf (the
    quiesce-before-checkpoint validation: a snapshot must not contain
    consumed buffers)."""
    import jax

    for i, leaf in enumerate(jax.tree.leaves(tree)):
        if hasattr(leaf, "dtype"):
            check(leaf, what=f"{what} leaf {i}")


def donating_jit(fn, donate_argnums: Sequence[int], owner: str, **jit_kw):
    """``jax.jit`` with donation + automatic liveness provenance: every
    donated input is marked at call time, so a later use raises with
    ``owner`` in the message instead of jax's bare deletion error."""
    import jax

    donate_argnums = tuple(donate_argnums)
    jitted = jax.jit(fn, donate_argnums=donate_argnums, **jit_kw)

    def call(*args, **kw):
        # reject already-consumed inputs BEFORE dispatch (clearer than
        # the runtime's use-after-delete at lowering time); walk the
        # LEAVES — the argument may be a pytree whose container has no
        # liveness of its own
        for i in donate_argnums:
            if i < len(args):
                for leaf in jax.tree.leaves(args[i]):
                    if hasattr(leaf, "dtype"):
                        check(leaf, what=f"{owner} argument {i}")
        out = jitted(*args, **kw)
        for i in donate_argnums:
            if i < len(args):
                for leaf in jax.tree.leaves(args[i]):
                    if hasattr(leaf, "dtype"):
                        mark_donated(leaf, owner)
        return out

    call.__wrapped__ = jitted
    return call
