"""Process liveness helpers shared by the job tools (tpu-ps/top,
tpu-clean, tpu-migrate discovery)."""

from __future__ import annotations

import os


def pid_alive(pid: int) -> bool:
    """True if ``pid`` plausibly names a LIVE process.

    ``pid <= 0`` is never alive — ``os.kill(0, ...)`` / ``kill(-1,
    ...)`` signal whole process groups and "succeed", which would
    classify a malformed contact file as an immortal job. Booleans
    are rejected for the same reason: JSON ``true`` satisfies
    ``isinstance(x, int)`` and would probe pid 1 (init — always
    alive). ``PermissionError`` means alive-but-not-ours: the owner's
    debris is not ours to reap."""
    if isinstance(pid, bool) or pid <= 0:
        return False
    try:
        os.kill(pid, 0)
        return True
    except ProcessLookupError:
        return False
    except PermissionError:
        return True
    except OSError:
        return False
