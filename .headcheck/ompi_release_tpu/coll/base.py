"""coll framework base: per-communicator, per-operation selection.

Mirror of ``ompi/mca/coll/base/coll_base_comm_select.c:66-88``: at
communicator creation every coll component is queried with the comm;
each returned module contributes implementations for the operations it
supports, and for every operation the highest-priority provider wins —
so ``xla`` can own allreduce while ``tuned`` provides scan, exactly how
the reference mixes tuned/basic/libnbc per comm.
"""

from __future__ import annotations

from typing import Callable, Dict

from ..mca import component as mca_component
from ..mca import var as mca_var
from ..utils import output

_log = output.stream("coll")

#: operations every communicator must end up with (coll.h fn table)
OP_NAMES = (
    "allreduce", "reduce", "bcast", "allgather", "gather", "scatter",
    "reduce_scatter_block", "alltoall", "scan", "exscan", "barrier",
    # v-variants (per-rank counts; coll_tuned_alltoallv.c etc.)
    "alltoallv", "allgatherv", "gatherv", "scatterv", "reduce_scatter",
)

COLL_FRAMEWORK = mca_component.framework(
    "coll", "collective operations (ompi/mca/coll analogue)"
)

#: a provider returns this to mean "handled, result is None" (e.g.
#: hier gatherv off the root's process: MPI leaves the recv buffer
#: undefined off-root) — plain None would read as a decline and fall
#: through to the next provider
NO_RESULT = object()


def comm_select(comm) -> Dict[str, Callable]:
    """Install the per-comm collective table (the ``c_coll`` analogue)."""
    # import components so they self-register before first selection
    from . import components as _components  # noqa: F401

    # chain per op: highest priority first; a provider may decline at
    # call time by returning None (e.g. tuned's reduce_scatter_block
    # declines non-commutative ops; xla's scan declines past its
    # gather-size limit), and the next provider takes over — the
    # runtime analogue of the reference re-querying on NOT_AVAILABLE
    chains: Dict[str, list] = {}
    providers: Dict[str, list] = {}
    for prio, comp, module in COLL_FRAMEWORK.available(comm):
        for op_name, fn in module.fns().items():
            chains.setdefault(op_name, []).append(fn)
            providers.setdefault(op_name, []).append(comp.NAME)

    def _dispatcher(op_name: str, chain) -> Callable:
        def call(comm_, *args, **kw):
            for fn in chain:
                res = fn(comm_, *args, **kw)
                if res is not None or op_name == "barrier":
                    return None if res is NO_RESULT else res
            from ..utils.errors import ErrorCode, MPIError

            raise MPIError(
                ErrorCode.ERR_NOT_AVAILABLE,
                f"every {op_name} provider declined on {comm_.name}",
            )

        return call

    table: Dict[str, Callable] = {
        name: _dispatcher(name, chain) for name, chain in chains.items()
    }
    missing = [o for o in OP_NAMES if o not in table]
    if missing:
        output.show_help(
            "coll", "missing-ops", comm=comm.name, ops=", ".join(missing)
        )
    _log.verbose(
        2, f"{comm.name}: coll providers {providers}"
    )
    comm._coll_providers = providers
    return table


output.register_help(
    "coll",
    {
        "missing-ops": (
            "Communicator {comm} has no implementation for collective "
            "operation(s): {ops}. They will raise if invoked."
        ),
    },
)
