"""Collectives: SPMD kernels, host driver, framework + components,
pipelined segmentation (:mod:`coll.pipeline`), and small-message
fusion (:mod:`coll.fusion`)."""

import importlib

from . import spmd
from .base import COLL_FRAMEWORK, OP_NAMES, comm_select

__all__ = ["spmd", "COLL_FRAMEWORK", "OP_NAMES", "comm_select",
           "pipeline", "fusion"]


def __getattr__(name):  # lazy: pipeline/fusion pull the jax-heavy driver
    if name in ("pipeline", "fusion"):
        return importlib.import_module(f".{name}", __name__)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
