"""SPMD collective algorithm kernels — the data plane.

These are pure jax functions meant to run *inside* ``shard_map`` over a
1-D mesh axis: each function sees one rank's block and communicates via
``lax.ppermute``/``lax.psum``/... over the axis. They serve both users
(call them inside your own pjit/shard_map programs — the performance
path) and the host driver API (``coll/driver.py`` wraps them per
communicator — the MPI-semantic path).

Algorithm parity with the reference's tuned component
(``ompi/mca/coll/tuned/coll_tuned_allreduce.c:46-54`` enum):
ring + recursive_doubling + segmented_ring for allreduce, binomial
bcast/reduce (``coll_tuned_bcast.c``), ring/recursive-doubling
allgather, pairwise alltoall, recursive-doubling scan/barrier. Each
hand-written algorithm is expressed as static-shape ppermute rounds —
the TPU-native equivalent of tuned's isend/irecv schedules
(``coll_tuned_util.c:50-59``) — so XLA can overlap compute with ICI
transfers inside one compiled program.

All step counts/permutations are static (mesh size known at trace
time); only data is traced. No data-dependent Python control flow.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Callable, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.op import Op


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _axis_size(axis_name: str) -> int:
    return lax.psum(1, axis_name)  # static under trace


def _ring_perm(n: int):
    return [(i, (i + 1) % n) for i in range(n)]


def _pad_to(x: jax.Array, total: int, fill) -> jax.Array:
    flat = x.reshape(-1)
    pad = total - flat.shape[0]
    if pad == 0:
        return flat
    return jnp.concatenate(
        [flat, jnp.full((pad,), fill, dtype=flat.dtype)]
    )


# ---------------------------------------------------------------------------
# allreduce family
# ---------------------------------------------------------------------------

def allreduce_lax(x: jax.Array, op: Op, axis_name: str) -> jax.Array:
    """XLA-native allreduce: the compiler emits its own ICI schedule.

    SUM/MAX/MIN map to fused psum/pmax/pmin; everything else gathers
    and reduces locally (still one fused program).
    """
    if op.lax_collective == "psum":
        return lax.psum(x, axis_name)
    if op.lax_collective == "pmax":
        return lax.pmax(x, axis_name)
    if op.lax_collective == "pmin":
        return lax.pmin(x, axis_name)
    g = lax.all_gather(x, axis_name, axis=0)  # (n, ...)
    return _tree_reduce_axis0(g, op)


def allreduce_pair_lax(vals: jax.Array, idxs: jax.Array, op: Op,
                       axis_name: str) -> tuple:
    """MINLOC/MAXLOC allreduce over (value, index) arrays."""
    gv = lax.all_gather(vals, axis_name, axis=0)
    gi = lax.all_gather(idxs, axis_name, axis=0)
    accv, acci = gv[0], gi[0]
    for i in range(1, gv.shape[0]):
        accv, acci = op((accv, acci), (gv[i], gi[i]))
    return accv, acci


def _tree_reduce_axis0(g: jax.Array, op: Op) -> jax.Array:
    """Fixed-order pairwise tree reduce over leading axis (deterministic)."""
    n = g.shape[0]
    while n > 1:
        half = n // 2
        even = g[: 2 * half : 2]
        odd = g[1 : 2 * half : 2]
        merged = op(even, odd)
        if n % 2:
            merged = jnp.concatenate([merged, g[2 * half : n]], axis=0)
        g = merged
        n = g.shape[0]
    return g[0]


def allreduce_recursive_doubling(x: jax.Array, op: Op,
                                 axis_name: str, n: int) -> jax.Array:
    """Recursive doubling (coll_tuned_allreduce.c:144), any n.

    Non-power-of-two handled with the standard fold/unfold: the first
    ``2*rem`` ranks pair up so ``p2`` effective ranks run the doubling,
    then results unfold back. Every round is one static ppermute.
    """
    rank = lax.axis_index(axis_name)
    shape, dtype = x.shape, x.dtype
    xf = x.reshape(-1)

    def combine(mine, theirs, their_rank_is_lower):
        """Non-commutative ops need lower-rank operand on the left
        (matches the reference rd's ordering guarantee)."""
        if op.commutative:
            return op(mine, theirs)
        return jnp.where(
            their_rank_is_lower, op(theirs, mine), op(mine, theirs)
        )

    p2 = 1 << (n.bit_length() - 1)
    if p2 == n:
        for d in (2 ** k for k in range(int(math.log2(n)))):
            perm = [(i, i ^ d) for i in range(n)]
            recv = lax.ppermute(xf, axis_name, perm)
            xf = combine(xf, recv, (rank & d) != 0)
        return xf.reshape(shape).astype(dtype)

    rem = n - p2
    # fold: even rank r < 2*rem sends to r+1 (sender is the lower rank)
    perm = [(2 * i, 2 * i + 1) for i in range(rem)]
    recv = lax.ppermute(xf, axis_name, perm)
    is_odd_low = (rank < 2 * rem) & (rank % 2 == 1)
    xf = jnp.where(is_odd_low, combine(xf, recv, True), xf)

    # effective rank for the doubling phase (-1 = idle even-low rank)
    def eff(r: int) -> int:
        if r < 2 * rem:
            return r // 2 if r % 2 == 1 else -1
        return r - rem

    def actual(e: int) -> int:
        return 2 * e + 1 if e < rem else e + rem

    participating = (rank >= 2 * rem) | (rank % 2 == 1)
    my_eff = jnp.where(rank < 2 * rem, rank // 2, rank - rem)
    for d in (2 ** k for k in range(int(math.log2(p2)))):
        perm = []
        for r in range(n):
            e = eff(r)
            if e >= 0:
                perm.append((r, actual(e ^ d)))
        recv = lax.ppermute(xf, axis_name, perm)
        xf = jnp.where(
            participating, combine(xf, recv, (my_eff & d) != 0), xf
        )

    # unfold: odd rank r < 2*rem sends result to r-1
    perm = [(2 * i + 1, 2 * i) for i in range(rem)]
    recv = lax.ppermute(xf, axis_name, perm)
    is_even_low = (rank < 2 * rem) & (rank % 2 == 0)
    xf = jnp.where(is_even_low, recv, xf)
    return xf.reshape(shape).astype(dtype)


def _ring_passes(chunks: jax.Array, op: Op, axis_name: str,
                 n: int) -> jax.Array:
    """The two ring passes (reduce-scatter + allgather) over a
    pre-chunked ``(n, ...)`` buffer. A chunk row's accumulation order
    is fixed by its row index alone — which is what lets the pipelined
    wrapper (``coll/pipeline.py``) segment WITHIN rows and stay
    bitwise-identical to the monolithic ring."""
    rank = lax.axis_index(axis_name)
    perm = _ring_perm(n)

    # reduce-scatter: after n-1 steps, chunk (rank+1) mod n is complete
    def rs_step(chunks, k):
        send_idx = (rank - k) % n
        send = jnp.take(chunks, send_idx, axis=0)
        recv = lax.ppermute(send, axis_name, perm)
        recv_idx = (rank - k - 1) % n
        cur = jnp.take(chunks, recv_idx, axis=0)
        return lax.dynamic_update_index_in_dim(
            chunks, op(cur, recv), recv_idx, 0
        ), None

    chunks, _ = lax.scan(rs_step, chunks, jnp.arange(n - 1))

    # allgather: circulate completed chunks around the ring
    def ag_step(chunks, k):
        send_idx = (rank - k + 1) % n
        send = jnp.take(chunks, send_idx, axis=0)
        recv = lax.ppermute(send, axis_name, perm)
        recv_idx = (rank - k) % n
        return lax.dynamic_update_index_in_dim(chunks, recv, recv_idx, 0), None

    chunks, _ = lax.scan(ag_step, chunks, jnp.arange(n - 1))
    return chunks


def allreduce_ring(x: jax.Array, op: Op, axis_name: str, n: int) -> jax.Array:
    """Ring allreduce: reduce-scatter pass + allgather pass
    (coll_tuned_allreduce.c:361). Bandwidth-optimal: 2(n-1)/n · size
    over the ICI ring.
    """
    if n == 1:
        return x
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    total = flat.shape[0]
    chunk = -(-total // n)  # ceil
    ident = op.identity_for(dtype)
    chunks = _pad_to(flat, chunk * n, ident).reshape(n, chunk)
    chunks = _ring_passes(chunks, op, axis_name, n)
    return chunks.reshape(-1)[:total].reshape(shape).astype(dtype)


def allreduce_segmented_ring(x: jax.Array, op: Op, axis_name: str, n: int,
                             segsize_elems: int) -> jax.Array:
    """Segmented ring (coll_tuned_allreduce.c:636): the ring pipelined
    over ~1 MiB segments, bounding the per-step working set (VMEM
    pressure) for very large buffers.

    Reduction-order note: each segment is ring-reduced independently,
    so an element's summation order is fixed by its chunk index
    *within its segment*. That order is deterministic and pinned by
    ``tests/test_bitwise_parity.py`` — but it is NOT bitwise-identical
    to plain ring (whose chunk index derives from the whole buffer)
    except when the whole buffer fits one segment; a ring chunk's
    accumulation order inherently depends on its chunk index, so no
    segmentation can preserve plain-ring bit patterns.
    """
    if n == 1:
        return x
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    total = flat.shape[0]
    seg = max(segsize_elems, n)
    nseg = -(-total // seg)
    if nseg <= 1:
        return allreduce_ring(x, op, axis_name, n)
    ident = op.identity_for(dtype)
    padded = _pad_to(flat, nseg * seg, ident).reshape(nseg, seg)
    out = lax.map(
        lambda s: allreduce_ring(s, op, axis_name, n), padded
    )
    return out.reshape(-1)[:total].reshape(shape).astype(dtype)


def allreduce_basic_linear(x: jax.Array, op: Op, axis_name: str,
                           n: int) -> jax.Array:
    """Reference linear algorithm (coll/basic): gather-to-all + local
    sequential reduce in rank order — the parity yardstick: its
    reduction order is the canonical rank order."""
    g = lax.all_gather(x, axis_name, axis=0)
    acc = g[0]
    for i in range(1, n):
        acc = op(acc, g[i])
    return acc


def allreduce_nonoverlapping(x: jax.Array, op: Op, axis_name: str,
                             n: int, root: int = 0) -> jax.Array:
    """Reduce-to-root then bcast (tuned's nonoverlapping,
    coll_tuned_allreduce.c): the fallback for non-commutative ops at
    sizes where recursive doubling is too chatty."""
    red = reduce_binomial(x, op, axis_name, n, root)
    return bcast_binomial(red, axis_name, n, root)


# ---------------------------------------------------------------------------
# bcast / reduce
# ---------------------------------------------------------------------------

def bcast_binomial(x: jax.Array, axis_name: str, n: int,
                   root: int = 0) -> jax.Array:
    """Binomial-tree broadcast (coll_tuned_bcast.c): ceil(log2 n) rounds."""
    if n == 1:
        return x
    rank = lax.axis_index(axis_name)
    rank_of = lambda v: (v + root) % n
    v = (rank - root) % n  # virtual rank: root -> 0
    rounds = (n - 1).bit_length()
    for k in range(rounds):
        d = 1 << k
        perm = [
            (rank_of(vs), rank_of(vs + d)) for vs in range(min(d, n - d))
        ]
        recv = lax.ppermute(x, axis_name, perm)
        is_receiver = (v >= d) & (v < 2 * d)
        x = jnp.where(is_receiver, recv, x)
    return x


def bcast_binary_tree(x: jax.Array, axis_name: str, n: int,
                      root: int = 0) -> jax.Array:
    """Balanced-binary-tree broadcast (``coll_tuned_bcast.c``
    ``bcast_intra_bintree``; stands in for the intermediate-size
    split_bintree pick too — the split-halves+exchange trick
    optimizes bidirectional link use, which the XLA scheduler already
    owns on a compiled torus program, so the plain binary tree is the
    faithful structure here).  Depth ceil(log2(n+1)) levels; each
    level is two static ppermutes (left edges, right edges — one
    parent feeds two children, which a single permutation cannot
    express)."""
    if n == 1:
        return x
    rank = lax.axis_index(axis_name)
    rank_of = lambda vv: (vv + root) % n
    v = (rank - root) % n
    depth = n.bit_length()  # heap levels 0..depth-1
    for lvl in range(depth):
        for side in (1, 2):  # left child 2v+1, right child 2v+2
            perm = [
                (rank_of(vs), rank_of(2 * vs + side))
                for vs in range(n)
                if (vs + 1).bit_length() - 1 == lvl
                and 2 * vs + side < n
            ]
            if not perm:
                continue
            recv = lax.ppermute(x, axis_name, perm)
            # receivers: children of this level's parents — parity
            # identifies the side (left children odd, right even>0),
            # the static level bounds identify the depth
            child_par = (v % 2 == 1) if side == 1 else \
                (v % 2 == 0) & (v > 0)
            child_lvl = (v + 1 >= (1 << (lvl + 1))) & \
                (v + 1 < (1 << (lvl + 2)))
            x = jnp.where(child_par & child_lvl, recv, x)
    return x


def bcast_chain(x: jax.Array, axis_name: str, n: int,
                root: int = 0) -> jax.Array:
    """Chain broadcast (``coll_tuned_bcast.c`` chain fanout=1): the
    value forwards rank-to-rank, n-1 hops."""
    if n == 1:
        return x
    rank = lax.axis_index(axis_name)
    rank_of = lambda v: (v + root) % n
    v = (rank - root) % n
    for hop in range(n - 1):
        perm = [(rank_of(hop), rank_of(hop + 1))]
        recv = lax.ppermute(x, axis_name, perm)
        x = jnp.where(v == hop + 1, recv, x)
    return x


def bcast_pipeline(x: jax.Array, axis_name: str, n: int, root: int,
                   seg_elems: int) -> jax.Array:
    """Pipelined (segmented chain) broadcast (``coll_tuned_bcast.c``
    ``bcast_intra_pipeline``): the flat buffer splits into S segments
    that stream down the rank chain, one hop per tick — S + n - 2
    ticks total, the GPipe schedule shape (parallel/pp.py uses the
    same loop).  Segment s reaches vrank v at tick s + v; every tick
    is ONE static ppermute of a segment-sized buffer plus traced
    dynamic slicing."""
    if n == 1:
        return x
    flat = x.reshape(-1)
    total = flat.shape[0]
    S = max(1, -(-total // max(1, seg_elems)))
    pad = S * seg_elems - total
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    segs = flat.reshape(S, seg_elems)
    rank = lax.axis_index(axis_name)
    v = (rank - root) % n
    perm = [((i + root) % n, (i + 1 + root) % n) for i in range(n - 1)]

    def tick(t, buf):
        # each rank forwards the segment it received at tick t-1:
        # rank v sends segment t - v (if it holds it)
        sidx = jnp.clip(t - v, 0, S - 1)
        outgoing = jnp.take(buf, sidx, axis=0)
        recv = lax.ppermute(outgoing, axis_name, perm)
        # receiver v stores segment t - (v - 1) at that index
        ridx = jnp.clip(t - (v - 1), 0, S - 1)
        valid = (t - (v - 1) >= 0) & (t - (v - 1) < S) & (v > 0)
        cur = jnp.take(buf, ridx, axis=0)
        new = jnp.where(valid, recv, cur)
        return lax.dynamic_update_index_in_dim(buf, new, ridx, 0)

    segs = lax.fori_loop(0, S + n - 2, tick, segs)
    out = segs.reshape(-1)[:total]
    return out.reshape(x.shape)


def bcast_masked_psum(x: jax.Array, op_dtype, axis_name: str,
                      root: int = 0) -> jax.Array:
    """One-collective bcast: zero all non-root contributions and psum.

    Integer-exact; float-exact too (adding zeros), except it does not
    preserve -0.0 vs +0.0 distinctions. Used by the xla component where
    a single fused collective beats log-round trees.
    """
    rank = lax.axis_index(axis_name)
    contrib = jnp.where(rank == root, x, jnp.zeros_like(x))
    if jnp.issubdtype(x.dtype, jnp.floating) or jnp.issubdtype(
        x.dtype, jnp.complexfloating
    ) or jnp.issubdtype(x.dtype, jnp.integer):
        return lax.psum(contrib, axis_name)
    # bool etc: max works as OR-select
    return lax.pmax(contrib.astype(jnp.int32), axis_name).astype(x.dtype)


def reduce_binomial(x: jax.Array, op: Op, axis_name: str, n: int,
                    root: int = 0) -> jax.Array:
    """Binomial-tree reduce toward root; non-root ranks end with
    partial values (MPI leaves their recv buffers undefined)."""
    if n == 1:
        return x
    rank = lax.axis_index(axis_name)
    vrank_of = lambda r: (r - root) % n
    rank_of = lambda v: (v + root) % n
    rounds = (n - 1).bit_length()
    v = vrank_of(rank)
    for k in range(rounds):
        d = 1 << k
        # senders: v where v mod 2d == d ; receivers: v - d
        perm = []
        for vs in range(d, n, 2 * d):
            perm.append((rank_of(vs), rank_of(vs - d)))
        recv = lax.ppermute(x, axis_name, perm)
        is_receiver = (v % (2 * d) == 0) & (v + d < n)
        x = jnp.where(is_receiver, op(x, recv), x)
    return x


def reduce_in_order_binary(x: jax.Array, op: Op, axis_name: str,
                           n: int, root: int = 0) -> jax.Array:
    """In-order binary-tree reduce (``coll_tuned_reduce.c``
    ``reduce_intra_in_order_binary``): the noncommutative-safe rooted
    reduce.  Unlike :func:`reduce_binomial` (whose root-relative
    vranks ROTATE the operand order when root != 0), this tree merges
    contiguous TRUE-rank ranges — every combine is
    ``op(lower range, upper range)``, so operands keep strict rank
    order 0..n-1; only the grouping is balanced (allowed: MPI requires
    associativity, never commutation).  The result lands on rank 0
    and takes one final hop to a non-zero root."""
    if n == 1:
        return x
    rank = lax.axis_index(axis_name)
    # at root 0, reduce_binomial's vranks ARE true ranks and its
    # op(lower, upper) combines are already contiguous-range in-order
    # merges — reuse that schedule, then hop to a non-zero root
    x = reduce_binomial(x, op, axis_name, n, root=0)
    if root != 0:
        moved = lax.ppermute(x, axis_name, [(0, root)])
        x = jnp.where(rank == root, moved, x)
    return jnp.where(rank == root, x, jnp.zeros_like(x))


def reduce_linear(x: jax.Array, op: Op, axis_name: str, n: int,
                  root: int = 0) -> jax.Array:
    """Linear reduce (``reduce_intra_basic_linear``): the canonical
    rank-order left fold of :func:`allreduce_basic_linear`, kept at
    root only — ONE definition of the strict sequential order."""
    acc = allreduce_basic_linear(x, op, axis_name, n)
    rank = lax.axis_index(axis_name)
    return jnp.where(rank == root, acc, jnp.zeros_like(acc))


# ---------------------------------------------------------------------------
# allgather / gather / scatter
# ---------------------------------------------------------------------------

def gather_linear(x: jax.Array, axis_name: str, n: int,
                  root: int = 0) -> jax.Array:
    """Linear gather (``coll_tuned_gather.c`` basic_linear; also the
    xla component's body): one fused allgather, root keeps it."""
    g = lax.all_gather(x, axis_name, axis=0)
    g = g.reshape((-1,) + g.shape[2:])
    rank = lax.axis_index(axis_name)
    return jnp.where(rank == root, g, jnp.zeros_like(g))


def scatter_linear(x: jax.Array, axis_name: str, n: int,
                   root: int = 0) -> jax.Array:
    """Linear scatter (basic_linear; also the xla component's body):
    bcast root's buffer, take the own chunk."""
    full = bcast_masked_psum(x, x.dtype, axis_name, root)
    chunks = full.reshape((n, -1) + full.shape[1:])
    rank = lax.axis_index(axis_name)
    return jnp.take(chunks, rank, axis=0)


def gather_binomial(x: jax.Array, axis_name: str, n: int,
                    root: int = 0) -> jax.Array:
    """Binomial-tree gather (``coll_tuned_gather.c``
    ``gather_intra_binomial``): log2(n) rounds; at round k the ranks
    whose root-relative vrank has LOWEST set bit k forward their
    accumulated k-block range to vrank - k.  Each round moves exactly
    k blocks (STATIC slice size at a traced, clamped base — true
    binomial volume, not a full-buffer echo); clamped window entries
    outside the sender's own range are masked to zero and receivers
    merge additively into a read-modify-write of the same window, so
    non-power-of-two edge ranks stay correct.  Non-root ranks end
    masked to zeros (MPI leaves them undefined).  Returns (n*block,)
    on root's slice, rank order."""
    rank = lax.axis_index(axis_name)
    v = (rank - root) % n
    out = jnp.zeros((n,) + x.shape, x.dtype)
    out = lax.dynamic_update_index_in_dim(out, x, v, 0)
    k = 1
    while k < n:
        is_sender = (v & (2 * k - 1)) == k  # lowest set bit == k
        s_send = jnp.minimum(v, n - k)      # clamped own-range base
        window = lax.dynamic_slice_in_dim(out, s_send, k, 0)
        valid = ((s_send + jnp.arange(k)) >= v).reshape(
            (k,) + (1,) * (out.ndim - 1))
        contrib = jnp.where(is_sender & valid, window,
                            jnp.zeros_like(window))
        # only the true sender set is on the wire (the sender set is
        # static in vrank space): non-listed ranks ship NOTHING and
        # non-targets receive zeros — k blocks per edge, (n/2k) edges,
        # the real binomial volume
        rank_of = lambda vv: (vv + root) % n
        perm = [(rank_of(vs), rank_of(vs - k))
                for vs in range(n) if (vs & (2 * k - 1)) == k]
        recv = lax.ppermute(contrib, axis_name, perm)
        # the child's base min(v_child, n-k) = min(v + k, n - k)
        s_recv = jnp.minimum(v + k, n - k)
        cur = lax.dynamic_slice_in_dim(out, s_recv, k, 0)
        out = lax.dynamic_update_slice_in_dim(out, cur + recv,
                                              s_recv, 0)
        k *= 2
    # vrank-space -> rank order: result[i] = out[(i - root) % n];
    # root is STATIC, so this is a static roll
    out = jnp.roll(out, shift=root, axis=0)
    flat = out.reshape((-1,) + x.shape[1:])
    return jnp.where(rank == root, flat, jnp.zeros_like(flat))


def scatter_binomial(x: jax.Array, axis_name: str, n: int,
                     root: int = 0) -> jax.Array:
    """Binomial-tree scatter (``coll_tuned_scatter.c``
    ``scatter_intra_binomial``): the mirror of binomial gather —
    root starts with all n blocks; at round k (descending) every
    range holder passes its upper-half k blocks to vrank + k, again
    as a STATIC-size slice at a clamped traced base with masked
    overlap and additive merge (k blocks per round, true binomial
    volume).  ``x`` is the root's (n*block,) buffer; returns own
    block."""
    rank = lax.axis_index(axis_name)
    v = (rank - root) % n
    blocks = x.reshape((n,) + (x.shape[0] // n,) + x.shape[1:])
    # vrank-index the buffer (static roll by -root) and zero non-root
    buf = jnp.where(rank == root,
                    jnp.roll(blocks, shift=-root, axis=0),
                    jnp.zeros_like(blocks))
    k = 1 << max(0, (n - 1).bit_length() - 1)
    while k >= 1:
        # the child vrank v + k must exist (non-power-of-two n)
        is_sender = ((v % (2 * k)) == 0) & (v + k < n)
        s_send = jnp.minimum(v + k, n - k)  # upper-half base, clamped
        window = lax.dynamic_slice_in_dim(buf, s_send, k, 0)
        valid = ((s_send + jnp.arange(k)) >= v + k).reshape(
            (k,) + (1,) * (buf.ndim - 1))
        contrib = jnp.where(is_sender & valid, window,
                            jnp.zeros_like(window))
        # static sender set only (see gather_binomial): true binomial
        # wire volume
        rank_of = lambda vv: (vv + root) % n
        perm = [(rank_of(vs), rank_of(vs + k))
                for vs in range(n)
                if vs % (2 * k) == 0 and vs + k < n]
        recv = lax.ppermute(contrib, axis_name, perm)
        # own-range base: the parent's upper half IS [v, v + k)
        s_recv = jnp.minimum(v, n - k)
        cur = lax.dynamic_slice_in_dim(buf, s_recv, k, 0)
        buf = lax.dynamic_update_slice_in_dim(buf, cur + recv,
                                              s_recv, 0)
        k //= 2
    return jnp.take(buf, v, axis=0)


def allgather_lax(x: jax.Array, axis_name: str) -> jax.Array:
    return lax.all_gather(x, axis_name, axis=0)


def allgather_bruck(x: jax.Array, axis_name: str, n: int) -> jax.Array:
    """Bruck allgather (``coll_tuned_allgather.c``
    ``allgather_intra_bruck``): ceil(log2 n) doubling rounds for ANY
    n, then a final rotation.

    Local position i holds block (rank + i) mod n throughout; round k
    appends ``min(cnt, n - cnt)`` blocks received from rank + cnt, so
    every round's slice sizes are STATIC (the python loop unrolls into
    the compiled program) while the final re-index by rank is the only
    traced-value gather."""
    rank = lax.axis_index(axis_name)
    out = jnp.zeros((n,) + x.shape, x.dtype)
    out = lax.dynamic_update_index_in_dim(out, x, 0, 0)
    cnt = 1
    while cnt < n:
        send_cnt = min(cnt, n - cnt)
        # data flows r -> r - cnt (mod n): each rank receives the
        # leading send_cnt blocks of rank + cnt, which are that
        # rank's blocks (rank + cnt + j) = our blocks cnt + j
        perm = [(i, (i - cnt) % n) for i in range(n)]
        recv = lax.ppermute(out[:send_cnt], axis_name, perm)
        out = lax.dynamic_update_slice_in_dim(out, recv, cnt, axis=0)
        cnt += send_cnt
    # local order is (rank, rank+1, ...): rotate to index order
    idx = (jnp.arange(n) - rank) % n
    return jnp.take(out, idx, axis=0)


def allgather_recursive_doubling(x: jax.Array, axis_name: str,
                                 n: int) -> jax.Array:
    """Recursive-doubling allgather (``coll_tuned_allgather.c``
    ``allgather_intra_recursivedoubling``): power-of-two n only, like
    the reference (callers decline otherwise). After round k every
    rank holds its 2^(k+1)-aligned group's blocks at their NATURAL
    indices, so no final rotation is needed; the per-round exchanged
    region has static size 2^k at a traced (rank-aligned) base."""
    if n & (n - 1):
        raise ValueError(f"recursive-doubling allgather needs "
                         f"power-of-two ranks, got {n}")
    rank = lax.axis_index(axis_name)
    out = jnp.zeros((n,) + x.shape, x.dtype)
    out = lax.dynamic_update_index_in_dim(out, x, rank, 0)
    k = 1
    while k < n:
        base = (rank // k) * k  # start of our filled k-block group
        mine = lax.dynamic_slice_in_dim(out, base, k, axis=0)
        perm = [(i, i ^ k) for i in range(n)]
        recv = lax.ppermute(mine, axis_name, perm)
        # partner's group sits at the bit-k mirrored base
        out = lax.dynamic_update_slice_in_dim(out, recv, base ^ k,
                                              axis=0)
        k *= 2
    return out


def allgather_ring(x: jax.Array, axis_name: str, n: int) -> jax.Array:
    """Neighbor-exchange ring allgather (coll_tuned_allgather.c ring)."""
    rank = lax.axis_index(axis_name)
    out = jnp.zeros((n,) + x.shape, x.dtype)
    out = lax.dynamic_update_index_in_dim(out, x, rank, 0)
    perm = _ring_perm(n)

    def step(carry, k):
        out, cur = carry
        recv = lax.ppermute(cur, axis_name, perm)
        idx = (rank - k - 1) % n
        out = lax.dynamic_update_index_in_dim(out, recv, idx, 0)
        return (out, recv), None

    (out, _), _ = lax.scan(step, (out, x), jnp.arange(n - 1))
    return out


def reduce_scatter_lax(x: jax.Array, op: Op, axis_name: str,
                       n: int) -> jax.Array:
    """reduce_scatter_block: x is (n*chunk,) per rank; rank i gets the
    reduced i-th chunk. SUM uses the fused psum_scatter."""
    chunk = x.shape[0] // n
    blocks = x.reshape((n, chunk) + x.shape[1:])
    if op.lax_collective == "psum":
        return lax.psum_scatter(blocks, axis_name, scatter_dimension=0,
                                tiled=False)
    # generic: allreduce then take own chunk
    red = allreduce_lax(blocks, op, axis_name)
    rank = lax.axis_index(axis_name)
    return jnp.take(red, rank, axis=0)


def reduce_scatter_ring(x: jax.Array, op: Op, axis_name: str,
                        n: int) -> jax.Array:
    """Ring reduce-scatter (the first phase of ring allreduce)."""
    if n == 1:
        return x
    rank = lax.axis_index(axis_name)
    chunk = x.shape[0] // n
    chunks = x.reshape((n, chunk) + x.shape[1:])
    perm = _ring_perm(n)

    def rs_step(chunks, k):
        # indices chosen so chunk c completes exactly at rank c
        send_idx = (rank - k - 1) % n
        send = jnp.take(chunks, send_idx, axis=0)
        recv = lax.ppermute(send, axis_name, perm)
        recv_idx = (rank - k - 2) % n
        cur = jnp.take(chunks, recv_idx, axis=0)
        return lax.dynamic_update_index_in_dim(
            chunks, op(cur, recv), recv_idx, 0
        ), None

    chunks, _ = lax.scan(rs_step, chunks, jnp.arange(n - 1))
    return jnp.take(chunks, rank, axis=0)


# ---------------------------------------------------------------------------
# alltoall
# ---------------------------------------------------------------------------

def alltoall_lax(x: jax.Array, axis_name: str, n: int) -> jax.Array:
    """x: (n, chunk...) per rank; out[j] = what rank j sent me."""
    return lax.all_to_all(x, axis_name, split_axis=0, concat_axis=0,
                          tiled=False)


def alltoall_bruck(blocks: jax.Array, axis_name: str, n: int) -> jax.Array:
    """Bruck alltoall (``coll_tuned_alltoall.c``
    ``alltoall_intra_bruck``): log2(n) store-and-forward phases moving
    n/2 blocks each — latency-optimal for small blocks at large n,
    at the cost of forwarding.

    Invariant: after the initial rotation, position j at rank r holds
    a block destined to rank r + j; phase k moves every position
    whose index has bit k set FORWARD by k ranks (stored at the same
    position), so a block starting at offset j arrives after its
    set-bit hops exactly at its destination, at position j.  The
    phase masks are STATIC (python loop, static index lists); only
    the first/last rotations index by the traced rank."""
    rank = lax.axis_index(axis_name)
    idx = (rank + jnp.arange(n)) % n
    local = jnp.take(blocks, idx, axis=0)  # local[j] -> dest rank+j
    k = 1
    while k < n:
        idxs = [j for j in range(n) if j & k]
        sel = local[jnp.array(idxs)]
        perm = [(i, (i + k) % n) for i in range(n)]
        recv = lax.ppermute(sel, axis_name, perm)
        local = local.at[jnp.array(idxs)].set(recv)
        k *= 2
    # position j now holds the block FROM rank - j (destined here)
    out_idx = (rank - jnp.arange(n)) % n
    return jnp.take(local, out_idx, axis=0)


def alltoall_pairwise(x: jax.Array, axis_name: str, n: int) -> jax.Array:
    """Pairwise-exchange alltoall (coll_tuned_alltoall.c pairwise):
    n-1 rounds; round k exchanges with rank±k."""
    rank = lax.axis_index(axis_name)
    out = jnp.zeros_like(x)
    # own block stays
    own = jnp.take(x, rank, axis=0)
    out = lax.dynamic_update_index_in_dim(out, own, rank, 0)
    for k in range(1, n):
        dst = [(i, (i + k) % n) for i in range(n)]
        # send the block destined for rank+k
        send = jnp.take(x, (rank + k) % n, axis=0)
        recv = lax.ppermute(send, axis_name, dst)
        src = (rank - k) % n
        out = lax.dynamic_update_index_in_dim(out, recv, src, 0)
    return out


# ---------------------------------------------------------------------------
# scan / barrier
# ---------------------------------------------------------------------------

def scan_recursive_doubling(x: jax.Array, op: Op, axis_name: str,
                            n: int, exclusive: bool = False) -> jax.Array:
    """Inclusive/exclusive prefix reduction over ranks (MPI_Scan/Exscan),
    log2-round recursive doubling (libnbc's iscan schedule shape)."""
    rank = lax.axis_index(axis_name)
    acc = x
    d = 1
    while d < n:
        perm = [(i, i + d) for i in range(n - d)]
        recv = lax.ppermute(acc, axis_name, perm)
        use = rank >= d
        acc = jnp.where(use, op(recv, acc), acc)
        d *= 2
    if not exclusive:
        return acc
    # exscan: shift inclusive results up by one rank; rank 0 undefined -> 0
    perm = [(i, i + 1) for i in range(n - 1)]
    shifted = lax.ppermute(acc, axis_name, perm)
    return jnp.where(rank == 0, jnp.zeros_like(acc), shifted)


def allreduce_two_level(x: jax.Array, op: Op, intra_axis: str,
                        inter_axis: str, intra_n: int) -> jax.Array:
    """Hierarchical allreduce (coll/ml + bcol + sbgp analogue,
    SURVEY §2.3): reduce-scatter inside the fast domain (ICI slice /
    shared-memory node), allreduce the owned chunk across the slow
    domain (DCN / inter-node), allgather back inside.

    Inter-domain traffic drops to 1/intra_n of the payload — exactly
    why the reference builds ml on top of per-level bcol primitives.
    """
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    total = flat.shape[0]
    chunk = -(-total // intra_n)
    ident = op.identity_for(dtype)
    padded = _pad_to(flat, chunk * intra_n, ident)

    # level 1: reduce-scatter within the fast domain (takes the flat
    # buffer and blocks it internally)
    mine = reduce_scatter_ring(padded, op, intra_axis, intra_n)
    # level 2: allreduce owned chunks across the slow domain
    mine = allreduce_lax(mine, op, inter_axis)
    # level 3: allgather within the fast domain
    out = lax.all_gather(mine, intra_axis, axis=0, tiled=True)
    return out[:total].reshape(shape).astype(dtype)


def bcast_two_level(x: jax.Array, intra_axis: str, inter_axis: str,
                    root: int, intra_n: int) -> jax.Array:
    """Hierarchical bcast: root -> its inter peers (one per fast
    domain) -> everyone inside each fast domain."""
    root_node, root_local = divmod(root, intra_n)
    # select root's value, then one fused two-level masked reduction
    rank_local = lax.axis_index(intra_axis)
    rank_node = lax.axis_index(inter_axis)
    is_root = (rank_node == root_node) & (rank_local == root_local)
    contrib = jnp.where(is_root, x, jnp.zeros_like(x))
    # one fused reduction over both axes delivers the bcast
    return lax.psum(lax.psum(contrib, intra_axis), inter_axis)


def reduce_two_level(x: jax.Array, op: Op, intra_axis: str,
                     inter_axis: str, root: int, intra_n: int
                     ) -> jax.Array:
    """Hierarchical rooted reduce: the two-level allreduce (which
    already cuts inter-domain traffic to 1/intra_n) masked to the
    root's position — the ml compose of bcol reduce primitives."""
    red = allreduce_two_level(x, op, intra_axis, inter_axis, intra_n)
    root_node, root_local = divmod(root, intra_n)
    is_root = ((lax.axis_index(inter_axis) == root_node)
               & (lax.axis_index(intra_axis) == root_local))
    return jnp.where(is_root, red, jnp.zeros_like(red))


def allgather_two_level(x: jax.Array, intra_axis: str, inter_axis: str
                        ) -> jax.Array:
    """Hierarchical allgather: gather inside the fast domain first,
    then exchange the per-domain aggregates across the slow domain —
    inter-domain messages carry whole-domain blocks (intra_n ranks per
    message instead of one), the recursive-doubling-on-aggregates
    shape of ml's allgather. Returns (n, chunk...) in rank order
    (rank = node * intra_n + local, node-major like run_sharded2d)."""
    g_local = lax.all_gather(x, intra_axis, axis=0)   # (intra_n, ...)
    g = lax.all_gather(g_local, inter_axis, axis=0)   # (inter_n, intra_n, ...)
    return g.reshape((-1,) + g.shape[2:])


def reduce_scatter_two_level(x: jax.Array, op: Op, intra_axis: str,
                             inter_axis: str, intra_n: int, n: int
                             ) -> jax.Array:
    """Hierarchical reduce_scatter_block: two-level allreduce, then
    each rank keeps its own chunk. Inter traffic = the allreduce's
    1/intra_n-reduced volume."""
    red = allreduce_two_level(x, op, intra_axis, inter_axis, intra_n)
    rank = (lax.axis_index(inter_axis) * intra_n
            + lax.axis_index(intra_axis))
    chunks = red.reshape((n, -1) + red.shape[1:])
    return jnp.take(chunks, rank, axis=0)


def alltoall_two_level(blocks: jax.Array, intra_axis: str,
                       inter_axis: str, intra_n: int, inter_n: int
                       ) -> jax.Array:
    """Hierarchical alltoall: factor the all-pairs exchange into an
    inter-domain alltoall of whole-domain super-blocks followed by an
    intra-domain alltoall — each slow-domain message aggregates
    intra_n**2 rank-pair blocks (the xhc/ml aggregation idea).

    ``blocks``: (n, chunk...) — row j is this rank's block for comm
    rank j (node-major rank order). Returns (n, chunk...) with row i =
    the block rank i sent to this rank.
    """
    b = blocks.reshape((inter_n, intra_n) + blocks.shape[1:])
    # exchange super-blocks across nodes: dim0 becomes SOURCE node
    b = lax.all_to_all(b, inter_axis, split_axis=0, concat_axis=0)
    # exchange within the fast domain: dim1 becomes SOURCE local rank
    b = lax.all_to_all(b, intra_axis, split_axis=1, concat_axis=1)
    return b.reshape(blocks.shape)


def barrier_psum(axis_name: str) -> jax.Array:
    """Barrier = 0-byte allreduce; completion of the program is the sync."""
    return lax.psum(jnp.zeros((), jnp.int32), axis_name)
