"""Pipelined segmented collectives — the large-message throughput
engine of the tuned component.

The reference's tuned component takes most of its large-message wins
from *segmentation* (``coll_tuned_<op>_segmentsize``): a collective is
split into segments whose transfer and reduce phases overlap, keeping
every link busy while bounding the working set. This module is the
compiled-program analogue: messages above the ``coll_pipeline_segsize``
cvar (or a dynamic rule's ``segsize`` column — see
:mod:`coll.dynamic_rules`) split into K segments that run as unrolled
chains inside ONE jitted ``shard_map`` program, **double-buffered**:
segment s carries an ``optimization_barrier`` dependency on segment
s-2, so at most two segments are in flight — segment s+1's transfers
overlap segment s's combines, and the live working set stays at two
segments, the double-buffer schedule of the reference's segmented
algorithms (``coll_tuned_allreduce.c:636``,
``coll_tuned_bcast.c`` pipeline).

Bitwise parity with the monolithic kernels is a design invariant, not
an accident (pinned by ``tests/test_coll_pipeline.py``):

- ring allreduce segments WITHIN ring-chunk rows: the buffer is chunked
  exactly like the monolithic ring first, then each row splits into
  column segments, so every element keeps its chunk index — and a ring
  element's accumulation order is a function of its chunk index alone.
  (Contrast ``spmd.allreduce_segmented_ring``, which re-chunks each
  segment and therefore pins its OWN order.)
- binomial bcast/reduce segment the flat buffer: the tree schedule —
  hence each element's combine order — never depends on the element's
  position.

Programs land in the driver's per-comm plan cache with the segment
count appended to the key (:func:`run_pipelined`): a changed segsize
compiles a new program, an unchanged one never retraces.
"""

from __future__ import annotations

import time
from typing import Callable, List, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .. import obs as _obs
from ..mca import pvar
from ..mca import var as mca_var
from ..ops.op import Op
from . import dynamic_rules, spmd
from . import driver as _driver

#: collectives the pipeline wrapper can segment, with the algorithm it
#: wraps (consumed by the tuned pickers and tpu_tune's segsize sweep)
PIPELINE_CAPABLE = {
    "allreduce": "ring",
    "bcast": "binomial",
    "reduce": "binomial",
}

# per-dispatch segment counts: count = pipelined calls, sum/max expose
# the segment counts a rules file or cvar actually produced (the
# acceptance signal for segsize tuning) — a module-level pvar bump,
# the same zero-cost class as the driver's invocation counter
_segments = pvar.aggregate(
    "coll_pipeline_segments",
    "segments per pipelined collective dispatch (count = pipelined "
    "calls, sum/max = segment counts in effect)",
)


def register_vars() -> None:
    mca_var.register(
        "coll_pipeline_segsize", "size", 1 << 20,
        "Per-rank bytes per pipeline segment: messages above this "
        "split into double-buffered segments inside one compiled "
        "program (coll_tuned_<op>_segmentsize analogue); 0 disables "
        "pipelining; a dynamic rule's segsize column overrides this",
    )
    mca_var.register(
        "coll_pipeline_max_segments", "int", 64,
        "Upper bound on segments per pipelined collective (each "
        "segment unrolls its own schedule into the compiled program)",
    )


register_vars()  # idempotent; cvars must exist before first dispatch


# ---------------------------------------------------------------------------
# segment-count policy (rules > cvar, mirroring tuned's precedence)
# ---------------------------------------------------------------------------

def pick_segsize(coll: str, comm_size: int, msg_bytes: int) -> int:
    """Segment size in bytes for this call: the dynamic rule file's
    ``segsize`` column when one matches (tuning wins), else the
    ``coll_pipeline_segsize`` cvar. 0 = pipelining off."""
    seg = dynamic_rules.lookup_segsize(coll, comm_size, msg_bytes)
    if seg is None:
        seg = int(mca_var.get("coll_pipeline_segsize", 1 << 20))
    return seg


def segment_count(coll: str, comm_size: int, msg_bytes: int) -> int:
    """How many segments this message splits into (1 = monolithic)."""
    seg = pick_segsize(coll, comm_size, msg_bytes)
    if seg <= 0 or msg_bytes <= seg:
        return 1
    cap = max(1, int(mca_var.get("coll_pipeline_max_segments", 64)))
    return min(-(-msg_bytes // seg), cap)


# ---------------------------------------------------------------------------
# double-buffered segment schedule
# ---------------------------------------------------------------------------

def _double_buffered(blocks: List[jax.Array],
                     run_one: Callable[[jax.Array], jax.Array]
                     ) -> List[jax.Array]:
    """Run ``run_one`` over segments with at most TWO in flight:
    segment s gains an ``optimization_barrier`` data dependency on
    segment s-2's output, so s and s+1 overlap freely (s+1's first
    transfer is independent of s's combines) while s+2 cannot start
    until s retires — the double-buffer working-set bound, enforced in
    the compiled program itself rather than hoped for from the
    scheduler."""
    outs: List[jax.Array] = []
    for s, blk in enumerate(blocks):
        if s >= 2:
            blk, _ = lax.optimization_barrier((blk, outs[s - 2]))
        outs.append(run_one(blk))
    return outs


def allreduce_ring_pipelined(x: jax.Array, op: Op, axis_name: str,
                             n: int, nseg: int) -> jax.Array:
    """Ring allreduce pipelined over ``nseg`` column segments of the
    ring-chunk matrix. Chunking matches :func:`spmd.allreduce_ring`
    exactly (rows = ring chunks), then each row splits into ``nseg``
    column segments — every element keeps its chunk index, so the
    per-element accumulation order (a function of the chunk index
    alone) is bitwise-identical to the monolithic ring's."""
    if n == 1:
        return x
    if nseg <= 1:
        return spmd.allreduce_ring(x, op, axis_name, n)
    shape, dtype = x.shape, x.dtype
    flat = x.reshape(-1)
    total = flat.shape[0]
    chunk = -(-total // n)  # ceil — same row assignment as the mono ring
    ident = op.identity_for(dtype)
    chunks = spmd._pad_to(flat, chunk * n, ident).reshape(n, chunk)
    seg = -(-chunk // nseg)
    pad = nseg * seg - chunk
    if pad:
        chunks = jnp.concatenate(
            [chunks, jnp.full((n, pad), ident, dtype)], axis=1
        )
    segs = chunks.reshape(n, nseg, seg).transpose(1, 0, 2)  # (nseg, n, seg)
    outs = _double_buffered(
        [segs[s] for s in range(nseg)],
        lambda blk: spmd._ring_passes(blk, op, axis_name, n),
    )
    out = jnp.stack(outs, axis=0).transpose(1, 0, 2).reshape(n, nseg * seg)
    return out[:, :chunk].reshape(-1)[:total].reshape(shape).astype(dtype)


def _flat_segments(x: jax.Array, nseg: int, fill) -> Tuple[List[jax.Array],
                                                           int]:
    """Split a buffer into ``nseg`` equal flat segments (last one
    padded with ``fill``); returns (segments, total_elems)."""
    flat = x.reshape(-1)
    total = flat.shape[0]
    seg = -(-total // nseg)
    padded = spmd._pad_to(flat, nseg * seg, fill).reshape(nseg, seg)
    return [padded[s] for s in range(nseg)], total


def bcast_binomial_pipelined(x: jax.Array, axis_name: str, n: int,
                             root: int, nseg: int) -> jax.Array:
    """Binomial-tree bcast over double-buffered flat segments. No
    reduction happens, so any segmentation is trivially bitwise-equal
    to the monolithic tree; the win is the pipeline: segment s+1
    streams down the tree while segment s is still in flight."""
    if n == 1 or nseg <= 1:
        return spmd.bcast_binomial(x, axis_name, n, root)
    segs, total = _flat_segments(x, nseg, jnp.zeros((), x.dtype))
    outs = _double_buffered(
        segs, lambda blk: spmd.bcast_binomial(blk, axis_name, n, root)
    )
    return jnp.concatenate(outs)[:total].reshape(x.shape)


def reduce_binomial_pipelined(x: jax.Array, op: Op, axis_name: str,
                              n: int, root: int, nseg: int) -> jax.Array:
    """Binomial-tree reduce over double-buffered flat segments. The
    tree's combine order per element depends only on the rank pairing,
    never on the element's position, so the segmented result is
    bitwise-identical to the monolithic :func:`spmd.reduce_binomial`.
    Like the monolithic kernel, non-root ranks end with partials —
    the caller applies the root mask."""
    if n == 1 or nseg <= 1:
        return spmd.reduce_binomial(x, op, axis_name, n, root)
    segs, total = _flat_segments(x, nseg, jnp.zeros((), x.dtype))
    outs = _double_buffered(
        segs, lambda blk: spmd.reduce_binomial(blk, op, axis_name, n, root)
    )
    return jnp.concatenate(outs)[:total].reshape(x.shape)


# ---------------------------------------------------------------------------
# dispatch: plan-cache key extended by the segment count
# ---------------------------------------------------------------------------

def run_pipelined(comm, key: Tuple, body: Callable, x, *, nseg: int,
                  nbytes: int = 0, opname: str = "") -> jax.Array:
    """Dispatch a pipelined body through the driver with the segment
    count appended to the plan-cache key: a changed segsize compiles a
    new program, an unchanged one re-runs the cached plan with no
    retrace."""
    _segments.observe(nseg)  # zero-cost pvar site (module-level)
    full_key = key + ("pipelined", nseg)
    if not _obs.enabled:
        return _driver.run_sharded(comm, full_key, body, x)
    label = opname or _driver._op_name(key)
    t0 = time.perf_counter()
    out = _driver.run_sharded(comm, full_key, body, x)
    _obs.record(label, "pipeline", t0, time.perf_counter() - t0,
                nbytes=nbytes, comm_id=getattr(comm, "cid", -1))
    return out
