"""coll/hier — collectives for communicators that SPAN controller
processes (the unified COMM_WORLD of ``tpurun -n P``).

Two-level compose, the ``coll/ml`` shape (``ompi/mca/coll/ml`` with
bcol/sbgp subgrouping) re-cast for the TPU runtime:

  intra  this process's members: ONE compiled XLA collective over the
         local submesh (a shadow communicator reuses the whole normal
         coll stack — xla/tuned selection, persistent programs);
  inter  the process-combine step over the wire router — shm segment
         handoffs on one host, chunked DCN staging across hosts
         (``runtime/wire.py``), never a fake device_put.

Driver-mode contract on a spanning communicator: buffers carry one
leading-axis slice per LOCAL member (this process's members of the
comm, in comm-rank order) — the per-process shard of the single-
controller convention. Results keep that local leading axis;
"identical on every rank" results are replicated across it.

Reduction order: local partials use the selected local algorithm's
order; the inter step combines partials in process-index order — the
same fixed-order tree discipline the parity harness pins for the
in-process algorithms.

The inter step is linear (every process exchanges with every peer):
honest O(P^2) messaging that is fine at realistic controller counts;
the pvar ``hier_inter_bytes`` counts exactly what crossed a process
boundary so the two-level byte reduction vs flat is measurable.

Exchange overlap (``wire_overlap_exchange``, default on): every round
posts ALL its sends first — striped across peers in pipelined fragment
bursts by ``WireRouter.coll_send_all`` — then reaps receives in
ARRIVAL order (``coll_recv_any``), so one slow peer no longer blocks
the reap of peers whose data already landed, the failure mode of the
old fixed-process-order ``self._recv(p)`` loops. Per-peer FIFO order
still holds (the OOB guarantees it), so multi-message rounds keep
their member ordering.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import jax.numpy as jnp
import numpy as np

from ..mca import component as mca_component
from ..mca import pvar
from ..mca import var as mca_var
from ..ops.op import Op
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("coll")

_inter_bytes = pvar.counter(
    "hier_inter_bytes",
    "bytes crossing a controller-process boundary in hier collectives",
)
_inter_msgs = pvar.counter(
    "hier_inter_msgs", "inter-process messages in hier collectives"
)


class _HierModule:
    """Two-level collectives over (process, local-member) subgroups."""

    def __init__(self, comm) -> None:
        from ..comm.communicator import Communicator
        from ..comm.group import Group

        self.comm = comm
        rt = comm.runtime
        from ..runtime.wire import proc_topology

        t = proc_topology(comm)  # the one shared layout derivation
        self.router = t.router
        self.my_pidx = t.my_pidx
        self.owner = t.owner
        self.procs = t.procs
        self.members_of = t.members_of
        self.local_ranks = t.local_ranks
        self.local_n = t.local_n
        # shadow communicator over the LOCAL members: the intra level,
        # with the full normal coll stack (the bcol analogue).
        # internal=True: shadow creation happens only on processes with
        # local members, so it must not consume a global cid — that
        # counter has to stay SPMD-synchronized for wire addressing
        self.shadow = Communicator(
            rt, Group([comm.group.world_rank(i) for i in self.local_ranks]),
            name=f"{comm.name}.local", internal=True,
        )
        # the shadow lives exactly as long as its owner: freeing the
        # spanning comm frees it (no registry leak per create/free)
        comm._on_free = tuple(getattr(comm, "_on_free", ())) + (
            self.shadow.free,
        )

    # -- plumbing ----------------------------------------------------------
    @property
    def peers(self) -> List[int]:
        return [p for p in self.procs if p != self.my_pidx]

    @staticmethod
    def _overlap() -> bool:
        return bool(mca_var.get("wire_overlap_exchange", True))

    def _send(self, peer: int, arr) -> None:
        arr = np.asarray(arr)
        self.router.coll_send(self.comm, peer, arr)
        _inter_msgs.add()
        _inter_bytes.add(int(arr.nbytes))

    def _recv(self, peer: int):
        out = np.asarray(self.router.coll_recv(self.comm, peer))
        _inter_msgs.add()
        return out

    def _send_all(self, sends: Dict[int, list]) -> None:
        """Post one round's sends to every peer, striped across
        destinations in pipelined fragment bursts (same pvar
        accounting as per-peer :meth:`_send`)."""
        self.router.coll_send_all(self.comm, sends)
        for arrs in sends.values():
            for a in arrs:
                _inter_msgs.add()
                _inter_bytes.add(int(a.nbytes))

    def _reap(self, pending: Dict[int, int],
              on_arrival: Callable[[int, np.ndarray], None]) -> None:
        """Reap ``pending[p]`` messages per peer in ARRIVAL order —
        a slow peer never blocks the reap of one whose data already
        landed (the posted-sends overlap the module docstring pins)."""
        left = sum(pending.values())
        while left:
            src, arr = self.router.coll_recv_any(self.comm, pending)
            _inter_msgs.add()
            pending[src] -= 1
            left -= 1
            on_arrival(src, np.asarray(arr))

    def _exchange(self, arrs_for: Dict[int, list]) -> Dict[int, list]:
        """Linear inter-process exchange: send every peer its arrays,
        then receive the same count back from each peer (all sends
        land before any recv parks — deadlock-free for the linear
        pattern). Receives reap in arrival order unless
        ``wire_overlap_exchange`` pins the sequential baseline."""
        sends = {p: [np.asarray(a) for a in arrs_for.get(p, [])]
                 for p in self.peers}
        if not self._overlap():
            for p in self.peers:
                for a in sends[p]:
                    self._send(p, a)
            got_seq: Dict[int, list] = {}
            for p in self.peers:
                got_seq[p] = [self._recv(p)
                              for _ in range(len(sends[p]))]
            return got_seq
        self._send_all(sends)
        got: Dict[int, list] = {p: [] for p in self.peers}
        self._reap({p: len(sends[p]) for p in self.peers},
                   lambda src, arr: got[src].append(arr))
        return got

    def _check_local_axis(self, x, what: str) -> None:
        if not hasattr(x, "shape") or x.ndim == 0 \
                or x.shape[0] != self.local_n:
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"{what} on spanning {self.comm.name}: buffers carry "
                f"one slice per LOCAL member ({self.local_n}), got "
                f"shape {getattr(x, 'shape', None)}",
            )
        # same refusal as the compiled driver edge: hier's local
        # partials and jnp conversions would otherwise silently narrow
        # 64-bit buffers with x64 off — and behavior would even differ
        # by process layout (a 1-member process skips the shadow comm)
        from .driver import _check_no_narrowing

        _check_no_narrowing(x)

    def _local_partial(self, x, op: Op):
        """Reduce this process's member slices to one partial."""
        if op.is_pair_op:
            vals, idxs = x
            self._check_local_axis(vals, "pair allreduce")
            if self.local_n == 1:
                return (jnp.asarray(vals[0]), jnp.asarray(idxs[0]))
            out_v, out_i = self.shadow.allreduce((vals, idxs), op)
            return (out_v[0], out_i[0])
        self._check_local_axis(x, "reduce")
        if self.local_n == 1:
            return jnp.asarray(x[0])
        return self.shadow.allreduce(x, op)[0]

    def _combine_with_peers(self, partial, op: Op):
        """Exchange partials with every peer; combine in process-index
        order (fixed order: every process computes the identical
        sequence, so results are bitwise-identical across processes)."""
        if op.is_pair_op:
            pv, pi = partial
            sends = {p: [np.asarray(pv), np.asarray(pi)]
                     for p in self.peers}
            got = self._exchange(sends)
            parts = {self.my_pidx: (jnp.asarray(pv), jnp.asarray(pi))}
            for p in self.peers:
                parts[p] = (jnp.asarray(got[p][0]), jnp.asarray(got[p][1]))
        else:
            got = self._exchange({p: [np.asarray(partial)]
                                  for p in self.peers})
            parts = {self.my_pidx: jnp.asarray(partial)}
            for p in self.peers:
                parts[p] = jnp.asarray(got[p][0])
        ordered = [parts[p] for p in self.procs]
        acc = ordered[0]
        for nxt in ordered[1:]:
            acc = op(acc, nxt)
        return acc

    def _bcast_local_axis(self, value):
        value = jnp.asarray(value)
        return jnp.broadcast_to(
            value[None], (self.local_n,) + value.shape
        )

    @staticmethod
    def _cat(parts: list) -> np.ndarray:
        """Concatenate per-rank slices the way all_gather+reshape does
        (0-d slices stack into a vector)."""
        parts = [np.asarray(p) for p in parts]
        if parts[0].ndim == 0:
            return np.stack(parts)
        return np.concatenate(parts, axis=0)

    # -- operation table ---------------------------------------------------
    def fns(self) -> Dict[str, Callable]:
        return {
            "allreduce": self.allreduce,
            "reduce": self.reduce,
            "bcast": self.bcast,
            "allgather": self.allgather,
            "gather": self.gather,
            "scatter": self.scatter,
            "reduce_scatter_block": self.reduce_scatter_block,
            "alltoall": self.alltoall,
            "scan": self.scan,
            "exscan": self.exscan,
            "barrier": self.barrier,
            "alltoallv": self.alltoallv,
            "allgatherv": self.allgatherv,
            "gatherv": self.gatherv,
            "scatterv": self.scatterv,
            "reduce_scatter": self.reduce_scatter,
        }

    # -- reductions --------------------------------------------------------
    def allreduce(self, comm, x, op: Op):
        total = self._combine_with_peers(self._local_partial(x, op), op)
        if op.is_pair_op:
            tv, ti = total
            return (self._bcast_local_axis(tv),
                    self._bcast_local_axis(ti))
        return self._bcast_local_axis(total)

    def reduce(self, comm, x, op: Op, root: int):
        # combine like allreduce, then mask to the root's slice (the
        # xla component's rooted-reduce convention: zeros elsewhere)
        total = self._combine_with_peers(self._local_partial(x, op), op)

        def place(t):
            out = np.zeros((self.local_n,) + np.asarray(t).shape,
                           np.asarray(t).dtype)
            if root in self.local_ranks:
                out[self.local_ranks.index(root)] = np.asarray(t)
            return jnp.asarray(out)

        if op.is_pair_op:
            return (place(total[0]), place(total[1]))
        return place(total)

    def reduce_scatter_block(self, comm, x, op: Op):
        n = comm.size

        def chunked(total: np.ndarray) -> np.ndarray:
            if total.shape[0] % n:
                raise MPIError(
                    ErrorCode.ERR_COUNT,
                    f"reduce_scatter_block buffer length "
                    f"{total.shape[0]} not divisible by comm size {n}",
                )
            chunks = total.reshape((n, -1) + total.shape[1:])
            out = np.stack([chunks[r] for r in self.local_ranks])
            return out.reshape((self.local_n, -1) + total.shape[1:])

        total = self._combine_with_peers(self._local_partial(x, op), op)
        if op.is_pair_op:
            tv, ti = total
            return (jnp.asarray(chunked(np.asarray(tv))),
                    jnp.asarray(chunked(np.asarray(ti))))
        return jnp.asarray(chunked(np.asarray(total)))

    # -- data movement -----------------------------------------------------
    def bcast(self, comm, x, root: int):
        owner = self.owner[root]
        if owner == self.my_pidx:
            self._check_local_axis(x, "bcast")
            val = np.asarray(x[self.local_ranks.index(root)])
            if self._overlap():
                self._send_all({p: [val] for p in self.peers})
            else:
                for p in self.peers:
                    self._send(p, val)
        else:
            val = self._recv(owner)
        return self._bcast_local_axis(val)

    def allgather(self, comm, x):
        self._check_local_axis(x, "allgather")
        block = np.asarray(x)  # (local_n, chunk...)
        got = self._exchange({p: [block] for p in self.peers})
        rows: Dict[int, np.ndarray] = {}
        for p in self.procs:
            pblock = block if p == self.my_pidx else got[p][0]
            for pos, r in enumerate(self.members_of[p]):
                rows[r] = pblock[pos]
        full = self._cat([rows[r] for r in range(comm.size)])
        return self._bcast_local_axis(full)

    def gather(self, comm, x, root: int):
        self._check_local_axis(x, "gather")
        owner = self.owner[root]
        block = np.asarray(x)
        full_shape = (comm.size * block.shape[1],) + block.shape[2:] \
            if block.ndim > 1 else (comm.size,)
        if owner != self.my_pidx:
            self._send(owner, block)
            return jnp.zeros((self.local_n,) + full_shape, block.dtype)
        rows: Dict[int, np.ndarray] = {}
        for pos, r in enumerate(self.members_of[self.my_pidx]):
            rows[r] = block[pos]

        def place(p: int, pblock: np.ndarray) -> None:
            for pos, r in enumerate(self.members_of[p]):
                rows[r] = pblock[pos]

        if self._overlap():
            self._reap({p: 1 for p in self.peers}, place)
        else:
            for p in self.peers:
                place(p, self._recv(p))
        full = self._cat([rows[r] for r in range(comm.size)])
        out = np.zeros((self.local_n,) + full.shape, full.dtype)
        out[self.local_ranks.index(root)] = full
        return jnp.asarray(out)

    def scatter(self, comm, x, root: int):
        n = comm.size
        owner = self.owner[root]
        if owner == self.my_pidx:
            self._check_local_axis(x, "scatter")
            full = np.asarray(x[self.local_ranks.index(root)])
            if full.shape[0] % n:
                raise MPIError(
                    ErrorCode.ERR_COUNT,
                    f"scatter buffer length {full.shape[0]} not "
                    f"divisible by comm size {n}",
                )
            chunks = full.reshape((n, -1) + full.shape[1:])
            if self._overlap():
                self._send_all({p: [chunks[self.members_of[p]]]
                                for p in self.peers})
            else:
                for p in self.peers:
                    self._send(p, chunks[self.members_of[p]])
            mine = chunks[self.members_of[self.my_pidx]]
        else:
            mine = self._recv(owner)  # (local_n, chunk...)
        return jnp.asarray(mine)

    def alltoall(self, comm, x):
        self._check_local_axis(x, "alltoall")
        n = comm.size
        block = np.asarray(x)
        if block.shape[1] % n:
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"alltoall buffer length {block.shape[1]} not divisible "
                f"by comm size {n}",
            )
        c = block.shape[1] // n
        # chunks[a, j]: local member a's chunk destined to comm rank j
        chunks = block.reshape((self.local_n, n, c) + block.shape[2:])
        sends = {p: [chunks[:, self.members_of[p]]] for p in self.peers}
        got = self._exchange(sends)
        out = np.empty_like(chunks)
        # local block: out[b, i] = in[a, j] for local members i->j
        for a, i in enumerate(self.local_ranks):
            for b, j in enumerate(self.local_ranks):
                out[b, i] = chunks[a, j]
        for p in self.peers:
            r = got[p][0]  # [a, b]: p's member a -> my member b
            for a, i in enumerate(self.members_of[p]):
                for b in range(self.local_n):
                    out[b, i] = r[a, b]
        return jnp.asarray(out.reshape(block.shape))

    # -- v-variant collectives (ragged; lists indexed by LOCAL member) -----
    # Spanning-comm analogue of coll/vcoll.py's driver-mode convention:
    # rank-dependent inputs/outputs are Python lists with one entry per
    # LOCAL member in comm-rank order; identical-everywhere results are
    # returned once. Counts arguments are GLOBAL (the full matrix /
    # per-rank vector on every process), matching MPI's requirement
    # that every caller supplies the complete picture.

    def _ragged_local(self, bufs, what: str) -> List[np.ndarray]:
        if len(bufs) != self.local_n:
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"{what} on spanning {self.comm.name}: pass one buffer "
                f"per LOCAL member ({self.local_n}), got {len(bufs)}",
            )
        out = [np.asarray(b).reshape(-1) for b in bufs]
        dtypes = {a.dtype for a in out}
        if len(dtypes) != 1:
            raise MPIError(
                ErrorCode.ERR_TYPE,
                f"{what} buffers must share one dtype, got "
                f"{sorted(map(str, dtypes))}",
            )
        from .driver import _check_no_narrowing

        if out:
            _check_no_narrowing(out[0])
        return out

    def alltoallv(self, comm, sendbufs, sendcounts):
        """Pairwise exchange, process-aggregated
        (``coll_tuned_alltoallv.c:148`` sends rank-pairwise over the
        PML; here every process sends ONE aggregated message per peer
        process — its members' chunks for that peer's members — since
        both sides derive the sub-layout from the shared count
        matrix). ``sendcounts`` is the full (n, n) matrix; returns
        ``recv[b]`` = source-order concatenation for local member b."""
        n = comm.size
        c = np.asarray(sendcounts, dtype=np.int64)
        if c.shape != (n, n) or (c < 0).any():
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"alltoallv needs a non-negative ({n},{n}) count "
                f"matrix, got {getattr(c, 'shape', None)}",
            )
        bufs = self._ragged_local(sendbufs, "alltoallv")
        dtype = bufs[0].dtype
        offs = np.concatenate(
            [np.zeros((n, 1), np.int64), np.cumsum(c, axis=1)], axis=1
        )
        for pos, i in enumerate(self.local_ranks):
            if bufs[pos].shape[0] != int(c[i].sum()):
                raise MPIError(
                    ErrorCode.ERR_COUNT,
                    f"alltoallv rank {i}: buffer has "
                    f"{bufs[pos].shape[0]} elements, counts sum to "
                    f"{int(c[i].sum())}",
                )

        def chunk(pos: int, i: int, j: int) -> np.ndarray:
            return bufs[pos][offs[i, j]:offs[i, j] + int(c[i, j])]

        sends = {}
        for p in self.peers:
            parts = [chunk(pos, i, j)
                     for pos, i in enumerate(self.local_ranks)
                     for j in self.members_of[p]]
            sends[p] = [np.concatenate(parts) if parts
                        else np.zeros((0,), dtype)]
        got = self._exchange(sends)
        from_peer: Dict[tuple, np.ndarray] = {}
        for p in self.peers:
            msg = np.asarray(got[p][0])
            off = 0
            for i in self.members_of[p]:
                for j in self.local_ranks:
                    k = int(c[i, j])
                    from_peer[(i, j)] = msg[off:off + k]
                    off += k
            if off != msg.shape[0]:
                raise MPIError(
                    ErrorCode.ERR_TRUNCATE,
                    f"alltoallv message from process {p} has "
                    f"{msg.shape[0]} elements, count matrix implies "
                    f"{off} — mismatched sendcounts across processes?",
                )
        recv = []
        for pos, j in enumerate(self.local_ranks):
            parts = [
                chunk(self.local_ranks.index(i), i, j)
                if self.owner[i] == self.my_pidx else from_peer[(i, j)]
                for i in range(n)
            ]
            recv.append(jnp.asarray(np.concatenate(parts) if parts
                                    else np.zeros((0,), dtype)))
        return recv

    def _gather_rows(self, bufs: List[np.ndarray]) -> Dict[int, np.ndarray]:
        """Every rank's ragged buffer: send each LOCAL member's buffer
        as its own message (shapes ride the wire, so no count
        pre-exchange), receive each peer's members' in comm-rank
        order (per-peer FIFO keeps member order under arrival-order
        reaping)."""
        rows: Dict[int, np.ndarray] = {
            r: bufs[pos] for pos, r in enumerate(self.local_ranks)
        }
        if self._overlap():
            self._send_all({p: list(bufs) for p in self.peers})
            slots = {p: list(self.members_of[p]) for p in self.peers}

            def place(p: int, arr: np.ndarray) -> None:
                rows[slots[p].pop(0)] = arr

            self._reap({p: len(self.members_of[p])
                        for p in self.peers}, place)
            return rows
        for p in self.peers:
            for b in bufs:
                self._send(p, b)
        for p in self.peers:
            for r in self.members_of[p]:
                rows[r] = self._recv(p)
        return rows

    def allgatherv(self, comm, sendbufs):
        """Rank-order concatenation of ragged buffers; identical on
        every rank, returned once (the vcoll convention)."""
        bufs = self._ragged_local(sendbufs, "allgatherv")
        rows = self._gather_rows(bufs)
        return jnp.asarray(
            np.concatenate([rows[r] for r in range(comm.size)])
        )

    def gatherv(self, comm, sendbufs, root: int):
        """Linear gather to the root's owner process
        (``coll_base_gatherv`` linear variant): non-owner processes
        send their members' buffers and return None (MPI leaves the
        recv buffer undefined off-root); the owner returns the
        rank-order concatenation."""
        n = comm.size
        if not 0 <= root < n:
            raise MPIError(ErrorCode.ERR_ROOT, f"bad root {root}")
        bufs = self._ragged_local(sendbufs, "gatherv")
        owner = self.owner[root]
        if owner != self.my_pidx:
            for b in bufs:
                self._send(owner, b)
            from .base import NO_RESULT

            return NO_RESULT  # recv buffer undefined off-root
        rows: Dict[int, np.ndarray] = {
            r: bufs[pos] for pos, r in enumerate(self.local_ranks)
        }
        if self._overlap():
            slots = {p: list(self.members_of[p]) for p in self.peers}
            self._reap(
                {p: len(self.members_of[p]) for p in self.peers},
                lambda p, arr: rows.__setitem__(slots[p].pop(0), arr),
            )
        else:
            for p in self.peers:
                for r in self.members_of[p]:
                    rows[r] = self._recv(p)
        return jnp.asarray(np.concatenate([rows[r] for r in range(n)]))

    def scatterv(self, comm, sendbuf, counts, root: int):
        """Root's owner splits ``sendbuf`` by ``counts`` and ships each
        remote rank's chunk to its owner; returns one array per LOCAL
        member. ``sendbuf`` is read only on the owner process."""
        n = comm.size
        if not 0 <= root < n:
            raise MPIError(ErrorCode.ERR_ROOT, f"bad root {root}")
        counts = [int(k) for k in counts]
        if len(counts) != n or any(k < 0 for k in counts):
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"scatterv needs {n} non-negative counts, got {counts}",
            )
        owner = self.owner[root]
        if owner != self.my_pidx:
            return [jnp.asarray(self._recv(owner))
                    for _ in self.local_ranks]
        buf = np.asarray(sendbuf).reshape(-1)
        from .driver import _check_no_narrowing

        _check_no_narrowing(buf)
        if buf.shape[0] != sum(counts):
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"scatterv root buffer has {buf.shape[0]} elements, "
                f"counts sum to {sum(counts)}",
            )
        offs = np.concatenate([[0], np.cumsum(counts)])
        chunks = [buf[offs[j]:offs[j] + counts[j]] for j in range(n)]
        if self._overlap():
            self._send_all({p: [chunks[j] for j in self.members_of[p]]
                            for p in self.peers})
        else:
            for p in self.peers:
                for j in self.members_of[p]:
                    self._send(p, chunks[j])
        return [jnp.asarray(chunks[j]) for j in self.local_ranks]

    def reduce_scatter(self, comm, x, recvcounts, op: Op):
        """General MPI_Reduce_scatter: combine (local partial, then
        process-index-order inter combine — the allreduce discipline),
        each rank keeps its ``recvcounts[i]``-length segment. ``x`` is
        (local_n, total); returns one array per LOCAL member."""
        n = comm.size
        recvcounts = [int(k) for k in recvcounts]
        if len(recvcounts) != n or any(k < 0 for k in recvcounts):
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"reduce_scatter needs {n} non-negative counts",
            )
        total = sum(recvcounts)
        if op.is_pair_op:
            vals, idxs = x
            self._check_local_axis(vals, "reduce_scatter")
            vals = np.asarray(vals)
            if vals.reshape(self.local_n, -1).shape[1] != total:
                raise MPIError(
                    ErrorCode.ERR_COUNT,
                    f"reduce_scatter needs values shaped "
                    f"({self.local_n}, {total}), got {vals.shape}",
                )
            tv, ti = self._combine_with_peers(
                self._local_partial((vals, idxs), op), op
            )
            tv, ti = np.asarray(tv).reshape(-1), np.asarray(ti).reshape(-1)
            offs = np.concatenate([[0], np.cumsum(recvcounts)])
            return [
                (jnp.asarray(tv[offs[r]:offs[r] + recvcounts[r]]),
                 jnp.asarray(ti[offs[r]:offs[r] + recvcounts[r]]))
                for r in self.local_ranks
            ]
        x = np.asarray(x)
        from .driver import _check_no_narrowing

        _check_no_narrowing(x)  # BEFORE the jnp conversion below
        if x.shape[0] != self.local_n \
                or x.reshape(self.local_n, -1).shape[1] != total:
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"reduce_scatter needs x shaped ({self.local_n}, "
                f"{total}), got {x.shape}",
            )
        x = x.reshape(self.local_n, total)
        red = np.asarray(self._combine_with_peers(
            self._local_partial(jnp.asarray(x), op), op
        ))
        offs = np.concatenate([[0], np.cumsum(recvcounts)])
        return [jnp.asarray(red[offs[r]:offs[r] + recvcounts[r]])
                for r in self.local_ranks]

    # -- prefix scans ------------------------------------------------------
    def _full_rows(self, x) -> Dict[int, np.ndarray]:
        """Every rank's slice, via an allgather-style block exchange."""
        block = np.asarray(x)
        got = self._exchange({p: [block] for p in self.peers})
        rows: Dict[int, np.ndarray] = {}
        for p in self.procs:
            pblock = block if p == self.my_pidx else got[p][0]
            for pos, r in enumerate(self.members_of[p]):
                rows[r] = pblock[pos]
        return rows

    def _scan_impl(self, comm, x, op: Op, exclusive: bool):
        if op.is_pair_op:
            # MINLOC/MAXLOC scans: fold the gathered (value, index)
            # rows with the pair combiner in rank order; the rank-0
            # exscan slice is zeros (MPI leaves it undefined)
            vals, idxs = x
            self._check_local_axis(vals, "scan")
            vrows = self._full_rows(vals)
            irows = self._full_rows(idxs)
            outv, outi = [], []
            for r in self.local_ranks:
                end = r if exclusive else r + 1
                if end == 0:
                    outv.append(np.zeros_like(vrows[0]))
                    outi.append(np.zeros_like(irows[0]))
                    continue
                acc = (jnp.asarray(vrows[0]), jnp.asarray(irows[0]))
                for j in range(1, end):
                    acc = op(acc, (jnp.asarray(vrows[j]),
                                   jnp.asarray(irows[j])))
                outv.append(np.asarray(acc[0]))
                outi.append(np.asarray(acc[1]))
            return (jnp.asarray(np.stack(outv)),
                    jnp.asarray(np.stack(outi)))
        self._check_local_axis(x, "scan")
        rows = self._full_rows(x)
        out = []
        for r in self.local_ranks:
            if exclusive:
                if r == 0:
                    out.append(np.zeros_like(rows[0]))
                    continue
                acc = jnp.asarray(rows[0])
                for j in range(1, r):
                    acc = op(acc, jnp.asarray(rows[j]))
            else:
                acc = jnp.asarray(rows[0])
                for j in range(1, r + 1):
                    acc = op(acc, jnp.asarray(rows[j]))
            out.append(np.asarray(acc))
        return jnp.asarray(np.stack(out))

    def scan(self, comm, x, op: Op):
        return self._scan_impl(comm, x, op, exclusive=False)

    def exscan(self, comm, x, op: Op):
        return self._scan_impl(comm, x, op, exclusive=True)

    # -- synchronization ---------------------------------------------------
    def barrier(self, comm):
        if self.local_n > 1:
            self.shadow.barrier()
        self.router.proc_barrier(self.comm, self.procs)


class HierCollComponent(mca_component.Component):
    """Claims exactly the communicators no in-process component can
    serve: those spanning controller processes."""

    NAME = "hier"
    PRIORITY = 150

    def query(self, ctx=None):
        if ctx is None:
            return (self.priority, self)
        if not getattr(ctx, "spans_processes", False):
            return None
        if getattr(ctx.runtime, "wire", None) is None:
            return None  # no router: nothing can serve this comm
        return (self.priority, _HierModule(ctx))
