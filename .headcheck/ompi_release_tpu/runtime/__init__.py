"""Runtime layer (ORTE analogue): bootstrap, mesh, state machine, modex."""

from .state import JobState, ProcState, StateMachine
from .mesh import Endpoint, build_mesh, factorize_torus, run_modex
from .runtime import Runtime, finalize, init
from .ess import ESS_FRAMEWORK

__all__ = [
    "JobState", "ProcState", "StateMachine",
    "Endpoint", "build_mesh", "factorize_torus", "run_modex",
    "Runtime", "init", "finalize", "ESS_FRAMEWORK",
]
