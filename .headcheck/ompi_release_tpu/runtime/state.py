"""Event-driven job/proc state machine — the ``orte/mca/state`` analogue.

The reference drives every lifecycle transition through an explicit FSM
(``orte/mca/state/state.h:87,148,242``; state codes
``orte/mca/plm/plm_types.h:47-130``): states are *activated*, which
posts callbacks registered for that state. We keep the explicit-states
idea for observability (and its test value: the fault injector and
errmgr hook in via states) while replacing libevent with synchronous
in-order dispatch plus an optional thread-pool for async callbacks —
the control plane is host Python; the data plane never goes through
here.
"""

from __future__ import annotations

import enum
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..utils import output

_log = output.stream("state")


class JobState(enum.IntEnum):
    """Job lifecycle states (mirrors ORTE_JOB_STATE_*, plm_types.h:113-151)."""

    UNDEF = 0
    INIT = 1
    ALLOCATE = 2
    MAP = 3
    SETUP = 4
    LAUNCH_DAEMONS = 5
    DAEMONS_REPORTED = 6
    VM_READY = 7
    LAUNCH_APPS = 8
    RUNNING = 9
    REGISTERED = 10  # all procs completed modex
    TERMINATED = 11
    ABORTED = 12
    FAILED_TO_START = 13
    RESTART = 14


class ProcState(enum.IntEnum):
    """Process/participant states (mirrors ORTE_PROC_STATE_*, plm_types.h:47-91)."""

    UNDEF = 0
    INIT = 1
    RUNNING = 2
    REGISTERED = 3
    IOF_COMPLETE = 4
    WAITPID_FIRED = 5
    TERMINATED = 6
    ABORTED = 7
    FAILED_TO_START = 8
    COMM_FAILED = 9
    SENSOR_BOUND_EXCEEDED = 10
    HEARTBEAT_FAILED = 11
    LIFELINE_LOST = 12
    UNABLE_TO_SEND_MSG = 13


Callback = Callable[[Any], None]


class StateMachine:
    """Ordered-callback state machine with transition history.

    ``register(state, cb, priority)`` mirrors ``orte_state.add_job_state``;
    ``activate(state, data)`` mirrors ``ORTE_ACTIVATE_JOB_STATE``.
    Callbacks run highest-priority first, synchronously, in activation
    order (the reference posts to an event base; we are single-threaded
    on the control path and keep strict ordering for determinism).
    """

    def __init__(self, name: str) -> None:
        self.name = name
        self._callbacks: Dict[int, List[Tuple[int, Callback]]] = {}
        self._history: List[Tuple[float, int, Any]] = []
        self._current: Optional[int] = None
        self._lock = threading.RLock()
        self._error_states: set = set()

    def register(self, state: int, cb: Callback, priority: int = 0) -> None:
        with self._lock:
            self._callbacks.setdefault(int(state), []).append((priority, cb))
            self._callbacks[int(state)].sort(key=lambda t: -t[0])

    def mark_error_state(self, state: int) -> None:
        """States routed to the errmgr (errmgr registers for them)."""
        with self._lock:
            self._error_states.add(int(state))

    def activate(self, state: int, data: Any = None) -> None:
        with self._lock:
            self._current = int(state)
            self._history.append((time.time(), int(state), data))
            cbs = list(self._callbacks.get(int(state), ()))
        _log.verbose(2, f"{self.name}: activate {self._fmt(state)}")
        for _, cb in cbs:
            cb(data)

    def _fmt(self, state: int) -> str:
        for E in (JobState, ProcState):
            try:
                return E(int(state)).name
            except ValueError:
                continue
        return str(state)

    @property
    def current(self) -> Optional[int]:
        return self._current

    def history(self) -> List[Tuple[float, int, Any]]:
        with self._lock:
            return list(self._history)

    def visited(self, state: int) -> bool:
        return any(s == int(state) for _, s, _ in self.history())
