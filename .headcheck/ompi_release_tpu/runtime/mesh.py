"""Device mesh construction + modex — the ORTE wire-up analogue.

The reference's ESS/RAS/RMAPS pipeline discovers the allocation, maps
procs onto nodes, and exchanges contact info (the *modex*,
``orte/mca/grpcomm/base/grpcomm_base_modex.c:67,201``). On TPU the
"allocation" is the device set jax exposes, "mapping" is laying ranks
onto a ``jax.sharding.Mesh`` whose axes ride the physical ICI torus,
and the modex is an allgather of per-device endpoint records
{rank, host process, coords, platform} — device coordinates replace
TCP business cards.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax
from jax.sharding import Mesh

from ..mca import var as mca_var
from ..utils import output

_log = output.stream("mesh")


@dataclasses.dataclass(frozen=True)
class Endpoint:
    """One participant's modex record (the business-card analogue)."""

    rank: int
    device_id: int
    process_index: int  # host process (multi-host: one per host)
    platform: str
    device_kind: str
    coords: Tuple[int, ...]  # physical coords if exposed, else mesh coords
    slice_index: int = 0
    host: str = ""  # machine identity: same-host cross-process peers
    #                 can hand buffers off through shared memory

    def describe(self) -> Dict:
        return dataclasses.asdict(self)


def factorize_torus(n: int, ndims: int) -> Tuple[int, ...]:
    """Balanced factorization of ``n`` into ``ndims`` dims (MPI_Dims_create).

    Mirrors the reference's dims_create semantics: dims as close to each
    other as possible, sorted non-increasing.
    """
    if ndims <= 0:
        raise ValueError("ndims must be >= 1")
    dims = [1] * ndims
    # greedy: repeatedly assign the largest prime factor to the smallest dim
    factors: List[int] = []
    m = n
    p = 2
    while p * p <= m:
        while m % p == 0:
            factors.append(p)
            m //= p
        p += 1
    if m > 1:
        factors.append(m)
    for f in sorted(factors, reverse=True):
        dims[dims.index(min(dims))] *= f
    return tuple(sorted(dims, reverse=True))


def register_vars() -> None:
    mca_var.register(
        "rmaps_mesh_shape", "str", "",
        "Explicit mesh shape as comma list (e.g. '4,2'); empty = auto 1D",
    )
    mca_var.register(
        "rmaps_mesh_axes", "str", "world",
        "Comma list of mesh axis names matching rmaps_mesh_shape",
    )
    # NOTE: no oversubscription variable — a jax Mesh requires unique
    # devices, so ranks-per-device wrapping (mpirun oversubscription)
    # has no TPU analogue; the simulator backend (forced host device
    # count) covers the reference's oversubscribed-test use case.


def device_coords(dev) -> Tuple[int, ...]:
    """Physical coords when the platform exposes them (TPU does)."""
    c = getattr(dev, "coords", None)
    if c is not None:
        try:
            return tuple(int(x) for x in c)
        except TypeError:
            pass
    return (int(dev.id),)


def build_mesh(
    devices: Optional[Sequence] = None,
    shape: Optional[Sequence[int]] = None,
    axis_names: Optional[Sequence[str]] = None,
) -> Mesh:
    """Build the world mesh.

    Defaults: all visible devices on a 1-D ``world`` axis. An explicit
    shape (from args or the ``rmaps_mesh_shape`` variable) lays the same
    devices out as a torus; jax device order already follows the
    physical ICI torus for TPU slices, so contiguous reshapes keep
    neighbors physically adjacent.
    """
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    n = len(devices)

    if shape is None:
        spec = (mca_var.get("rmaps_mesh_shape") or "").strip()
        if spec:
            shape = tuple(int(s) for s in spec.split(","))
    if shape is None:
        shape = (n,)
    shape = tuple(int(s) for s in shape)
    if math.prod(shape) != n:
        raise ValueError(
            f"mesh shape {shape} does not cover {n} devices"
        )

    if axis_names is None:
        spec = (mca_var.get("rmaps_mesh_axes") or "world").strip()
        names = [s.strip() for s in spec.split(",") if s.strip()]
        if len(names) != len(shape):
            names = (
                ["world"]
                if len(shape) == 1
                else [f"axis{i}" for i in range(len(shape))]
            )
        axis_names = names

    dev_array = np.asarray(devices, dtype=object).reshape(shape)
    mesh = Mesh(dev_array, tuple(axis_names))
    _log.verbose(
        1,
        f"built mesh shape={shape} axes={tuple(axis_names)} "
        f"platform={devices[0].platform}",
    )
    return mesh


def build_submesh(world_mesh: Mesh, world_ranks: Sequence[int]) -> Mesh:
    """1-D sub-mesh over the given world ranks, in group order.

    Group order defines the collective's rank order (MPI semantics);
    jax's device order inside the sub-mesh array defines how XLA routes
    the collective over ICI. Keeping group order here preserves MPI
    rank numbering; XLA still picks ICI-optimal routes for the ring.
    """
    flat = list(world_mesh.devices.reshape(-1))
    devs = np.asarray([flat[r] for r in world_ranks], dtype=object)
    return Mesh(devs, ("rank",))


def run_modex(mesh: Mesh) -> List[Endpoint]:
    """Allgather endpoint records for every mesh position.

    Single-controller: all device handles are visible in-process, so
    the allgather is a local enumeration (multi-host jax runs this
    after ``jax.distributed.initialize`` where ``jax.devices()`` is
    already the global view — the allgather the reference does over
    its daemon tree is done by the jax runtime during init).
    """
    import socket

    flat = list(mesh.devices.reshape(-1))
    hostname = socket.gethostname()
    my_process = jax.process_index()
    endpoints = []
    for rank, dev in enumerate(flat):
        pidx = int(getattr(dev, "process_index", 0))
        endpoints.append(
            Endpoint(
                rank=rank,
                device_id=int(dev.id),
                process_index=pidx,
                platform=str(dev.platform),
                device_kind=str(getattr(dev, "device_kind", "unknown")),
                coords=device_coords(dev),
                slice_index=int(getattr(dev, "slice_index", 0) or 0),
                # only claim OUR host for our own process's devices; a
                # peer process's hostname comes from its modex card
                # (coordinator wire-up), never assumed
                host=hostname if pidx == my_process else "",
            )
        )
    return endpoints
