"""tpu_info — the ``ompi_info`` analogue.

Dumps every framework, component, config variable (with value +
source), and performance variable, plus the device/mesh view — the
introspection contract of ``ompi/tools/ompi_info`` (SURVEY §5
observability: "dumps every framework/component/variable").

Usage:
    python -m ompi_release_tpu.tools.tpu_info            # summary
    python -m ompi_release_tpu.tools.tpu_info --all      # + all vars
    python -m ompi_release_tpu.tools.tpu_info --param pml coll
    python -m ompi_release_tpu.tools.tpu_info --json
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List


def _import_everything() -> None:
    """Import every subsystem so all frameworks/vars self-register
    (ompi_info opens every framework the same way)."""
    from .. import coll, comm, datatype, ops, runtime  # noqa: F401
    from ..btl import components as _b  # noqa: F401
    from ..coll import components as _c  # noqa: F401
    from ..ops import pallas_op as _po  # noqa: F401
    from ..p2p import pml as _p  # noqa: F401
    from ..io import sharded as _s  # noqa: F401
    from ..ft import sensor as _f  # noqa: F401
    from ..parallel import dp as _dp  # noqa: F401
    from ..runtime import ess as _e  # noqa: F401
    from ..runtime import mesh as _m

    _m.register_vars()
    _p.register_vars()
    _s.register_vars()
    _f.register_vars()
    from ..parallel.dp import register_vars as _dpr

    _dpr()


def gather(include_vars: bool = True) -> Dict[str, Any]:
    import jax

    from ..mca import pvar as pvar_mod
    from ..mca import var as var_mod
    from ..mca.component import FRAMEWORKS

    _import_everything()

    info: Dict[str, Any] = {
        "package": "ompi_release_tpu",
        "devices": [
            {
                "id": int(d.id),
                "platform": str(d.platform),
                "kind": str(getattr(d, "device_kind", "?")),
                "process": int(getattr(d, "process_index", 0)),
            }
            for d in jax.devices()
        ],
        "frameworks": [
            {
                "name": fw.name,
                "description": fw.description,
                "components": [
                    {"name": c.NAME, "priority": c.priority}
                    for c in fw.components()
                ],
            }
            for fw in FRAMEWORKS.all()
        ],
    }
    if include_vars:
        info["variables"] = var_mod.VARS.describe_all()
        info["pvars"] = pvar_mod.PVARS.read_all()
    return info


def render_text(info: Dict[str, Any], show_vars: bool) -> str:
    lines: List[str] = []
    lines.append(f"Package: {info['package']}")
    lines.append("Devices:")
    for d in info["devices"]:
        lines.append(
            f"  [{d['id']}] {d['platform']}/{d['kind']} "
            f"(process {d['process']})"
        )
    lines.append("Frameworks:")
    for fw in info["frameworks"]:
        comps = ", ".join(
            f"{c['name']}(prio={c['priority']})" for c in fw["components"]
        ) or "(none registered)"
        lines.append(f"  {fw['name']:<12} {comps}")
        if fw["description"]:
            lines.append(f"    {fw['description']}")
    if show_vars and "variables" in info:
        lines.append("Config variables (MCA):")
        for v in info["variables"]:
            lines.append(
                f"  {v['name']:<36} {v['type']:<6} "
                f"value={v['value']!r} source={v['source']}"
            )
            if v.get("help"):
                lines.append(f"    {v['help']}")
        lines.append("Performance variables:")
        for name, val in sorted(info.get("pvars", {}).items()):
            lines.append(f"  {name:<36} {val}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="tpu_info")
    ap.add_argument("--all", "-a", action="store_true",
                    help="show all config + perf variables")
    ap.add_argument("--param", nargs="*", default=None,
                    help="show variables whose name contains any prefix")
    ap.add_argument("--json", action="store_true", help="JSON output")
    args = ap.parse_args(argv)

    show_vars = bool(args.all or args.param)
    info = gather(include_vars=True)
    if args.param:
        info["variables"] = [
            v for v in info["variables"]
            if any(p in v["name"] for p in args.param)
        ]
        info["pvars"] = {
            k: v for k, v in info["pvars"].items()
            if any(p in k for p in args.param)
        }
    if args.json:
        print(json.dumps(info, indent=2, default=str))
    else:
        print(render_text(info, show_vars))
    return 0


if __name__ == "__main__":
    sys.exit(main())
