"""Message-queue debugger dump — the ``ompi/debuggers`` analogue.

The reference ships a message-queue DLL so TotalView/DDT can walk
pending sends/recvs (``ompi_debuggers.c:127,219``). Here the same
information is a function call: every live communicator's PML queues,
plus RMA pending ops, rendered for humans or returned structured.
"""

from __future__ import annotations

from typing import Dict, List


def dump_all() -> List[Dict]:
    """Structured dump across every live communicator."""
    from ..comm.communicator import _comm_registry

    out = []
    for comm in list(_comm_registry.values()):
        pml = getattr(comm, "_pml", None)
        entry = {"comm": comm.name, "cid": comm.cid, "size": comm.size}
        if pml is not None:
            entry.update(pml.dump_queues())
        else:
            entry.update({"unexpected": [], "posted": []})
        out.append(entry)
    return out


def render() -> str:
    lines = []
    for c in dump_all():
        lines.append(
            f"{c['comm']} (cid={c['cid']}, size={c['size']}): "
            f"{len(c['unexpected'])} unexpected, "
            f"{len(c['posted'])} posted"
        )
        for s in c["unexpected"]:
            lines.append(
                f"  UNEX  src={s['src']} -> dst={s['dst']} "
                f"tag={s['tag']} bytes={s['bytes']} ({s['protocol']})"
            )
        for r in c["posted"]:
            lines.append(
                f"  POSTED dst={r['dst']} source={r['source']} "
                f"tag={r['tag']}"
            )
    return "\n".join(lines) or "(no live communicators)"
