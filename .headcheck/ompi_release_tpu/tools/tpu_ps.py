"""tpu-ps / tpu-top — live job monitoring (the ``orte-ps`` /
``orte-top`` analogue, ``orte/tools/orte-ps/orte-ps.c`` and
``orte/tools/orte-top/orte-top.c``).

Discovery follows the reference's session-dir mechanism: every running
``tpurun`` writes a contact file under the per-user session directory
(``tpurun.SESSION_DIR``); ``tpu-ps`` lists those jobs (skipping stale
files whose launcher pid is gone) and queries each HNP's TAG_PS
responder for the live snapshot — per-rank pid, proc state, vmsize/rss
(piggybacked on heartbeats by ``sensor_resusage``-style sampling), and
heartbeat age. ``tpu-top`` is the same query on a refresh loop.

Usage::

    python -m ompi_release_tpu.tools.tpu_ps            # all local jobs
    python -m ompi_release_tpu.tools.tpu_ps --hnp H:P  # one job direct
    python -m ompi_release_tpu.tools.tpu_top [-d SECS]
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time
from typing import Dict, List, Optional

from ..native import OobEndpoint
from ..runtime.coordinator import TAG_PS
from ..utils.errors import ErrorCode, MPIError
from ..utils.procutil import pid_alive as _pid_alive


class PsClient:
    """One-shot snapshot query against a job's HNP (high random client
    id, like the cross-job NameClient — ps clients from any job must
    not collide with worker ids)."""

    def __init__(self, host: str, port: int,
                 secret: Optional[str] = None) -> None:
        self.ep = OobEndpoint(
            random.randrange(1 << 20, 1 << 30),
            secret=secret.encode() if secret else None,
        )
        self.ep.connect(0, host, int(port))

    def query(self, timeout_ms: int = 5_000) -> Dict:
        self.ep.send(0, TAG_PS, b"")
        _, _, raw = self.ep.recv(tag=TAG_PS, timeout_ms=timeout_ms)
        return json.loads(raw)

    def close(self) -> None:
        self.ep.close()


def discover_jobs() -> List[Dict]:
    """Live jobs from the session contact files (stale files — dead
    launcher pids — are reaped here, the orte-clean-lite duty)."""
    from .tpurun import SESSION_DIR

    jobs = []
    if not os.path.isdir(SESSION_DIR):
        return jobs
    for name in sorted(os.listdir(SESSION_DIR)):
        if not name.endswith(".json"):
            continue
        path = os.path.join(SESSION_DIR, name)
        try:
            with open(path) as f:
                info = json.load(f)
        except (OSError, ValueError):
            continue
        pid = info.get("pid") if isinstance(info, dict) else None
        # bool rejection lives in pid_alive (JSON true is an int)
        if not isinstance(pid, int) or not _pid_alive(pid):
            try:
                os.unlink(path)  # stale: launcher is gone
            except OSError:
                pass
            continue
        jobs.append(info)
    return jobs


def _fmt_bytes(n) -> str:
    if not n:
        return "-"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if n < 1024:
            return f"{n:.0f}{unit}"
        n /= 1024
    return f"{n:.1f}TiB"


def render_job(info: Dict, snap: Optional[Dict]) -> str:
    lines = [
        f"Job (tpurun pid {info.get('pid', '?')}) "
        f"n={info.get('n', '?')} "
        f"cmd={' '.join(info.get('argv', []))[:60]}"
    ]
    if snap is None:
        lines.append("  (HNP did not answer the snapshot query)")
        return "\n".join(lines)
    states = snap.get("proc_states", {})
    lines.append(
        f"  {'rank':>4} {'pid':>8} {'state':<16} {'rss':>9} "
        f"{'vmsize':>9} {'beat-age':>8}"
    )
    for nid_s, w in sorted(snap.get("workers", {}).items(),
                           key=lambda kv: int(kv[0])):
        nid = int(nid_s)
        age = w.get("beat_age_s")
        lines.append(
            f"  {nid - 1:>4} {w.get('pid', '-')!s:>8} "
            f"{states.get(nid_s, '?'):<16} "
            f"{_fmt_bytes(w.get('rss')):>9} "
            f"{_fmt_bytes(w.get('vmsize')):>9} "
            f"{(f'{age:.1f}s' if age is not None else '-'):>8}"
        )
    return "\n".join(lines)


def snapshot_all(hnp: Optional[str] = None,
                 secret_file: Optional[str] = None) -> List[str]:
    """Rendered snapshots of every target job."""
    out = []
    if hnp:
        host, port = hnp.rsplit(":", 1)
        target = {"host": host, "port": int(port), "pid": "?",
                  "argv": [], "n": "?"}
        if secret_file:
            with open(secret_file) as f:
                target["secret"] = f.read().strip()
        targets = [target]
    else:
        targets = discover_jobs()
    for info in targets:
        client = None
        snap = None
        try:
            client = PsClient(info["host"], info["port"],
                              secret=info.get("secret"))
            snap = client.query()
        except (MPIError, OSError):
            snap = None
        finally:
            if client is not None:
                client.close()
        out.append(render_job(info, snap))
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-ps",
        description="List live tpurun jobs and their per-rank state "
                    "(orte-ps analogue)")
    ap.add_argument("--hnp", default=None,
                    help="query one job directly at host:port instead "
                         "of discovering via the session dir (the "
                         "job's control plane is authenticated: supply "
                         "its secret via --secret-file or the "
                         "OMPITPU_JOB_SECRET env var)")
    ap.add_argument("--secret-file", default=None,
                    help="file holding the target job's control-plane "
                         "secret (for --hnp; session-dir discovery "
                         "reads it from the contact file)")
    args = ap.parse_args(argv)
    snaps = snapshot_all(args.hnp, secret_file=args.secret_file)
    if not snaps:
        print("no live tpurun jobs found")
        return 0
    print("\n\n".join(snaps))
    return 0


def main_top(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-top",
        description="Continuously display live tpurun jobs "
                    "(orte-top analogue)")
    ap.add_argument("--hnp", default=None)
    ap.add_argument("-d", "--delay", type=float, default=2.0,
                    help="refresh interval in seconds")
    ap.add_argument("--iterations", type=int, default=0,
                    help="stop after N refreshes (0 = until SIGINT)")
    args = ap.parse_args(argv)
    i = 0
    try:
        while True:
            snaps = snapshot_all(args.hnp)
            sys.stdout.write("\x1b[2J\x1b[H" if sys.stdout.isatty()
                             else "")
            print(time.strftime("tpu-top  %H:%M:%S"))
            print("\n\n".join(snaps) if snaps
                  else "no live tpurun jobs found")
            sys.stdout.flush()
            i += 1
            if args.iterations and i >= args.iterations:
                return 0
            time.sleep(args.delay)
    except KeyboardInterrupt:
        return 0


if __name__ == "__main__":
    sys.exit(main())
