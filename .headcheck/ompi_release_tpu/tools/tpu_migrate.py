"""tpu-migrate — proactive process migration (the ``orte-migrate``
analogue, ``orte/tools/orte-migrate/orte-migrate.c``).

Asks a RUNNING job's HNP to evacuate a host: every rank mapped there
is terminated, remapped to a surviving slot (the host stays excluded
for future failure-respawns too), and respawned; each moved app
resumes from its last committed checkpoint via its own
``ft.run_with_restart`` / ``Checkpointer`` logic — the same
restart-from-checkpoint contract failure recovery uses.  Where the
reference pairs orte-migrate with an on-demand snapc global snapshot,
this framework's apps checkpoint on their own cadence, so migration
recomputes work since the last commit (stated, not hidden).

Usage::

    python -m ompi_release_tpu.tools.tpu_migrate --off HOSTNAME \
        [--hnp H:P | --pid LAUNCHER_PID]

Without ``--hnp``/``--pid`` the session directory must hold exactly
one live job (same discovery as tpu-ps).
"""

from __future__ import annotations

import argparse
import json
import random
import sys
from typing import Dict, List, Optional

from ..native import OobEndpoint
from ..runtime.coordinator import TAG_MIGRATE
from ..utils.errors import MPIError


def request_migration(host: str, port: int, off: str,
                      timeout_ms: int = 30_000,
                      secret: Optional[str] = None) -> Dict:
    """One-shot TAG_MIGRATE round trip (high random client id — same
    collision discipline as the ps/name-server clients)."""
    ep = OobEndpoint(random.randrange(1 << 20, 1 << 30),
                     secret=secret.encode() if secret else None)
    try:
        ep.connect(0, host, int(port))
        ep.send(0, TAG_MIGRATE, json.dumps({"off": off}).encode())
        _, _, raw = ep.recv(tag=TAG_MIGRATE, timeout_ms=timeout_ms)
        return json.loads(raw)
    finally:
        ep.close()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-migrate",
        description="Evacuate a host of a live tpurun job "
                    "(orte-migrate analogue)")
    ap.add_argument("--off", required=True,
                    help="hostname to evacuate (as it appears in the "
                         "job's allocation)")
    ap.add_argument("--hnp", default=None,
                    help="target job's HNP at host:port (supply its "
                         "control-plane secret via --secret-file or "
                         "the OMPITPU_JOB_SECRET env var)")
    ap.add_argument("--secret-file", default=None,
                    help="file holding the target job's control-plane "
                         "secret (for --hnp; session-dir discovery "
                         "reads it from the contact file)")
    ap.add_argument("--pid", type=int, default=None,
                    help="target job by launcher pid (session-dir "
                         "discovery)")
    args = ap.parse_args(argv)

    secret = None
    if args.hnp:
        host, port = args.hnp.rsplit(":", 1)
        port = int(port)
        if args.secret_file:
            with open(args.secret_file) as f:
                secret = f.read().strip()
    else:
        from .tpu_ps import discover_jobs

        jobs = discover_jobs()
        if args.pid is not None:
            jobs = [j for j in jobs if int(j.get("pid", -1)) == args.pid]
        if not jobs:
            print("no matching live tpurun job found", file=sys.stderr)
            return 1
        if len(jobs) > 1:
            print(f"{len(jobs)} live jobs; pick one with --pid or "
                  "--hnp:", file=sys.stderr)
            for j in jobs:
                print(f"  pid {j['pid']}  {j['host']}:{j['port']}  "
                      f"n={j['n']}", file=sys.stderr)
            return 1
        host, port = jobs[0]["host"], int(jobs[0]["port"])
        secret = jobs[0].get("secret")

    try:
        reply = request_migration(host, port, args.off, secret=secret)
    except (MPIError, OSError) as e:
        print(f"migration request failed: {e}", file=sys.stderr)
        return 1
    if reply.get("ok"):
        ranks = ", ".join(map(str, reply.get("ranks", [])))
        print(f"migrating rank(s) {ranks} off {reply.get('off')}")
        if reply.get("skipped"):
            sk = ", ".join(map(str, reply["skipped"]))
            print(f"warning: rank(s) {sk} skipped ({reply.get('note')})",
                  file=sys.stderr)
        return 0
    print(f"migration refused: {reply.get('error')}", file=sys.stderr)
    return 1


if __name__ == "__main__":
    sys.exit(main())
