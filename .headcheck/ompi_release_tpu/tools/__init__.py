"""CLI tools — the ``ompi/tools`` + ``orte/tools`` analogue."""
