"""tpu-checkpoint — the ``orte-checkpoint``/``orte-restart`` tool role.

The reference ships operator CLIs over its checkpoint stack
(``orte/tools/orte-checkpoint``, ``orte-restart``; storage under
``orte/mca/sstore``). This is the same operator surface over
``ft/checkpoint.py``'s sharded snapshots:

    python -m ompi_release_tpu.tools.tpu_checkpoint list DIR
    python -m ompi_release_tpu.tools.tpu_checkpoint show DIR [--step N]
    python -m ompi_release_tpu.tools.tpu_checkpoint verify DIR [--step N]
    python -m ompi_release_tpu.tools.tpu_checkpoint gc DIR --keep K

``verify`` re-reads every shard of a committed step (the sharded
loader validates the per-shard CRCs), catching bit-rot before a
restart depends on the snapshot. ``gc`` applies the sstore retention
policy by hand.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
from typing import List, Optional


def _ckpt(directory: str):
    from ..ft.checkpoint import Checkpointer

    if not os.path.isdir(directory):
        raise SystemExit(f"tpu-checkpoint: no such directory: {directory}")
    return Checkpointer(directory)


def cmd_list(args) -> int:
    ck = _ckpt(args.directory)
    steps = ck.steps()
    if not steps:
        print("no committed checkpoints")
        return 1
    for s in steps:
        meta = ck.meta(s)
        d = os.path.join(args.directory, f"step_{s:010d}")
        nbytes = sum(
            os.path.getsize(os.path.join(d, f)) for f in os.listdir(d)
        )
        extras = {k: v for k, v in meta.items()
                  if k not in ("step", "time")}
        print(f"step {s:>8}  {nbytes / 1e6:9.2f} MB  "
              f"t={meta.get('time', 0):.0f}"
              + (f"  {extras}" if extras else ""))
    return 0


def cmd_show(args) -> int:
    ck = _ckpt(args.directory)
    step = args.step if args.step is not None else ck.latest_step()
    if step is None:
        print("no committed checkpoints")
        return 1
    print(json.dumps(ck.meta(step), indent=2))
    d = os.path.join(args.directory, f"step_{step:010d}")
    for name in sorted(os.listdir(d)):
        print(f"  {name}  {os.path.getsize(os.path.join(d, name))} B")
    return 0


def cmd_verify(args) -> int:
    """Re-read every shard of the step: the sharded loader's CRC
    validation runs on each — bit-rot surfaces here, not at restart."""
    from ..io import sharded

    ck = _ckpt(args.directory)
    step = args.step if args.step is not None else ck.latest_step()
    if step is None:
        print("no committed checkpoints")
        return 1
    d = os.path.join(args.directory, f"step_{step:010d}")
    manifest = os.path.join(d, "pytree.json")
    if not os.path.exists(manifest):
        print(f"step {step}: missing pytree manifest")
        return 1
    with open(manifest) as f:
        n_leaves = json.load(f)["num_leaves"]
    bad = 0
    for i in range(n_leaves):
        name = f"leaf{i:04d}"
        try:
            sharded.load_sharded(d, name=name)
        except Exception as e:
            print(f"step {step}: leaf '{name}' FAILED: {e}")
            bad += 1
    if bad:
        print(f"step {step}: {bad}/{n_leaves} leaves corrupt")
        return 1
    print(f"step {step}: {n_leaves} leaves verified OK")
    return 0


def cmd_gc(args) -> int:
    ck = _ckpt(args.directory)
    steps = ck.steps()
    doomed = steps[:-args.keep] if args.keep else steps
    for s in doomed:
        shutil.rmtree(os.path.join(args.directory, f"step_{s:010d}"),
                      ignore_errors=True)
        print(f"removed step {s}")
    print(f"kept {len(steps) - len(doomed)} of {len(steps)}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-checkpoint",
        description="Inspect/verify/GC sharded checkpoints "
                    "(orte-checkpoint tool role)")
    sub = ap.add_subparsers(dest="cmd", required=True)
    for name, fn in (("list", cmd_list), ("show", cmd_show),
                     ("verify", cmd_verify), ("gc", cmd_gc)):
        p = sub.add_parser(name)
        p.add_argument("directory")
        if name in ("show", "verify"):
            p.add_argument("--step", type=int, default=None)
        if name == "gc":
            p.add_argument("--keep", type=int, required=True)
        p.set_defaults(fn=fn)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
