"""tpu-clean — remove leftover job debris (the ``orte-clean``
analogue, ``orte/tools/orte-clean/orte-clean.c``).

What the reference's orte-clean removes — stale session directories
and orphaned daemons of dead jobs — maps here to:

* **session contact files** under ``tpurun.SESSION_DIR`` whose
  launcher pid is dead (or whose contents are unparseable debris);
* **orphaned shm handoff segments**: ShmBtl names every segment
  ``ompitpu-<creator pid>-<uuid>`` precisely so this tool can unlink
  segments whose creator died without the receiver ever mapping them
  (the sender-side TTL reaper only runs while the sender lives).
  Only regular files matching that exact name pattern are candidates;
  anything else under /dev/shm — including the session directory
  itself when ``TMPDIR=/dev/shm`` — is never touched.

Segment reaping is double-gated: creator dead AND segment older than
``--min-age`` (default 60 s). The age gate exists because ShmBtl
transfers OWNERSHIP to the receiver at announce — a sender may exit
cleanly while a live receiver is milliseconds from mapping the
segment, and creator-death alone would tear that transfer down. A
receiver that has not mapped a segment after min-age has hit its own
recv timeout long since.

Live launchers are never touched, and debris owned by OTHER users
(PermissionError on the liveness probe) is left alone.

Usage::

    python -m ompi_release_tpu.tools.tpu_clean [--dry-run] [-v]
        [--min-age SECONDS]
"""

from __future__ import annotations

import argparse
import json
import os
import stat as stat_mod
import sys
import time
from typing import List, Optional, Tuple

from ..utils.procutil import pid_alive

SHM_DIR = "/dev/shm"
SHM_PREFIX = "ompitpu-"


def stale_sessions() -> List[str]:
    """Contact files whose launcher pid is dead plus unparseable
    debris (anything that cannot yield a positive int pid)."""
    from .tpurun import SESSION_DIR

    out = []
    if not os.path.isdir(SESSION_DIR):
        return out
    for name in sorted(os.listdir(SESSION_DIR)):
        if not name.endswith(".json"):
            continue
        path = os.path.join(SESSION_DIR, name)
        try:
            with open(path) as f:
                pid = json.load(f).get("pid")
        except (OSError, ValueError, AttributeError):
            out.append(path)  # unreadable / not JSON / not a dict
            continue
        if not isinstance(pid, int) or pid <= 0 or not pid_alive(pid):
            out.append(path)
    return out


def orphaned_segments(min_age_s: float = 60.0,
                      shm_prefix: Optional[str] = None
                      ) -> List[Tuple[str, int]]:
    """(segment name, creator pid) for shm segments with a dead
    creator that are at least ``min_age_s`` old.

    Only names matching the exact ShmBtl pattern
    ``<prefix><digits>-...`` on REGULAR files are candidates —
    anything else under /dev/shm is skipped, never reaped. (The
    per-user session dir itself lands in /dev/shm when
    ``TMPDIR=/dev/shm``, and its ``ompitpu-sessions-<uid>`` name
    would otherwise read as 'unparseable debris'.)"""
    prefix = SHM_PREFIX if shm_prefix is None else shm_prefix
    out = []
    if not os.path.isdir(SHM_DIR):
        return out
    now = time.time()
    for name in sorted(os.listdir(SHM_DIR)):
        if not name.startswith(prefix):
            continue
        try:
            st = os.stat(os.path.join(SHM_DIR, name))
        except OSError:
            continue  # vanished mid-scan
        if not stat_mod.S_ISREG(st.st_mode):
            continue
        if now - st.st_mtime < min_age_s:
            continue
        rest = name[len(prefix):]
        pid_s = rest.split("-", 1)[0]
        if not pid_s.isdigit():
            continue  # not a ShmBtl segment: not ours to touch
        pid = int(pid_s)
        if not pid_alive(pid):
            out.append((name, pid))
    return out


def clean(dry_run: bool = False, verbose: bool = False,
          min_age_s: float = 60.0, shm_prefix: Optional[str] = None,
          out=sys.stdout) -> Tuple[int, int]:
    """Remove stale sessions + orphaned segments; returns counts of
    entries actually removed (dry-run: entries that would be tried)."""
    from multiprocessing import shared_memory

    n_sessions = 0
    for path in stale_sessions():
        if verbose or dry_run:
            print(f"{'would remove' if dry_run else 'removing'} stale "
                  f"session file {path}", file=out)
        if not dry_run:
            try:
                os.unlink(path)
            except OSError as e:
                print(f"tpu-clean: cannot remove {path}: {e}",
                      file=sys.stderr)
                continue
        n_sessions += 1
    n_segs = 0
    for name, pid in orphaned_segments(min_age_s, shm_prefix):
        if verbose or dry_run:
            print(f"{'would remove' if dry_run else 'removing'} "
                  f"orphaned shm segment {name} (pid {pid} dead)",
                  file=out)
        if not dry_run:
            try:
                seg = shared_memory.SharedMemory(name=name)
                seg.close()
                seg.unlink()
            except FileNotFoundError:
                continue  # raced its own receiver/reaper: fine
            except OSError as e:
                print(f"tpu-clean: cannot remove segment {name}: {e}",
                      file=sys.stderr)
                continue
        n_segs += 1
    return n_sessions, n_segs


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpu-clean",
        description="Remove stale session files and orphaned shm "
                    "segments of dead jobs (orte-clean analogue)")
    ap.add_argument("--dry-run", action="store_true",
                    help="report what would be removed, remove nothing")
    ap.add_argument("--min-age", type=float, default=60.0,
                    help="only reap shm segments older than this many "
                         "seconds (guards in-flight ownership "
                         "handoffs)")
    ap.add_argument("-v", "--verbose", action="store_true")
    args = ap.parse_args(argv)
    n_sessions, n_segs = clean(dry_run=args.dry_run,
                               verbose=args.verbose,
                               min_age_s=args.min_age)
    verb = "would remove" if args.dry_run else "removed"
    print(f"tpu-clean: {verb} {n_sessions} stale session file(s), "
          f"{n_segs} orphaned shm segment(s)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
