"""tpu-top — refresh-loop entry point (``orte-top`` analogue).

Default mode is tpu_ps's snapshot machinery on a loop
(``python -m ompi_release_tpu.tools.tpu_top [-d SECS]``). With
``--metrics HOST:PORT`` it instead polls a ``tpu_server``'s metrics
RPC and renders the live Prometheus pvar page — the observability
plane's terminal UI.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import List, Optional


def _metrics_loop(target: str, delay: float, iterations: int) -> int:
    from ..utils.errors import MPIError
    from .tpu_server import NameClient

    try:
        host, port_s = target.rsplit(":", 1)
        port = int(port_s)
    except ValueError:
        print(f"tpu-top: --metrics wants HOST:PORT, got {target!r}",
              file=sys.stderr)
        return 2
    try:
        client = NameClient(host, port)
    except (MPIError, OSError) as e:
        print(f"tpu-top: cannot reach tpu-server at {target}: {e}",
              file=sys.stderr)
        return 1
    i = 0
    try:
        while True:
            page = client.metrics()
            sys.stdout.write("\x1b[2J\x1b[H" if sys.stdout.isatty()
                             else "")
            # target stays out of the strftime format: a '%' in it
            # (IPv6 zone-id hosts) would expand or raise
            print("tpu-top pvars @ " + target + "  "
                  + time.strftime("%H:%M:%S"))
            print(page, end="" if page.endswith("\n") else "\n")
            sys.stdout.flush()
            i += 1
            if iterations and i >= iterations:
                return 0
            time.sleep(delay)
    except KeyboardInterrupt:
        return 0
    except (MPIError, OSError) as e:
        print(f"tpu-top: metrics query to {target} failed: {e}",
              file=sys.stderr)
        return 1
    finally:
        client.close()


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(prog="tpu-top", add_help=False)
    ap.add_argument("--metrics", default=None,
                    help="render a tpu-server's live pvar page "
                         "(host:port) instead of job snapshots")
    args, rest = ap.parse_known_args(argv)
    if args.metrics is None:
        from .tpu_ps import main_top

        return main_top(rest)
    mp = argparse.ArgumentParser(prog="tpu-top --metrics HOST:PORT")
    mp.add_argument("-d", "--delay", type=float, default=2.0,
                    help="refresh interval in seconds")
    mp.add_argument("--iterations", type=int, default=0,
                    help="stop after N refreshes (0 = until SIGINT)")
    ma = mp.parse_args(rest)
    return _metrics_loop(args.metrics, ma.delay, ma.iterations)


if __name__ == "__main__":
    sys.exit(main())
