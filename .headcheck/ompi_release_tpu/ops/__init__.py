"""Reduction ops (MPI_Op analogue) + op framework for overrides."""

from .op import (
    BAND, BOR, BXOR, LAND, LOR, LXOR, MAX, MAXLOC, MIN, MINLOC, NO_OP,
    OP_FRAMEWORK, PREDEFINED_OPS, PROD, REPLACE, SUM, Op, reduce_local,
    resolve, user_op,
)
from .pallas_op import PallasOpComponent

OP_FRAMEWORK.register(PallasOpComponent())

__all__ = [
    "Op", "user_op", "PREDEFINED_OPS", "OP_FRAMEWORK", "resolve",
    "reduce_local",
    "SUM", "PROD", "MAX", "MIN", "LAND", "LOR", "LXOR", "BAND", "BOR",
    "BXOR", "MAXLOC", "MINLOC", "REPLACE", "NO_OP",
]
