"""Pallas flash-attention kernel — the hand-scheduled hot op.

The one place XLA's automatic fusion loses to hand scheduling in this
framework's model stack is attention: materializing (S, S) scores is
HBM-bound, while a blocked kernel keeps the working set in VMEM and
streams K/V blocks through the MXU with an online softmax. This is the
``op`` framework's accelerated-component story (SURVEY §2.3: "op MCA
framework exists for accelerated overrides") applied where it matters.

Layout: q/k/v are (H, S, D). Grid = (H, S/block_q); each program owns
one query block, loops over key blocks with running (max, sumexp)
statistics in f32 and emits the per-row logsumexp (LSE) alongside the
output. Backward is fully blocked too (the flash recompute strategy):
two Pallas kernels — dq over q-blocks, dk/dv over k-blocks — re-derive
each probability block from q/k and the saved LSE, so no (S, S)
tensor is ever materialized in either direction.

``interpret=True`` runs the same kernels on CPU for CI (the simulator
backend strategy of SURVEY §4).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from ..utils import jaxcompat as _jaxcompat

_jaxcompat.install()  # jax.typeof/ShapeDtypeStruct-vma on 0.4.x jaxlibs

NEG_INF = -1e30


def _attn_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *, block_k: int,
                 seq_k: int, causal: bool, block_q: int):
    """One (head, q-block) program: stream K/V blocks, online softmax.
    Also emits the per-row logsumexp of the scaled scores — the (m, l)
    statistic the blocked backward recomputes probabilities from."""
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)  # (block_q, D)
    d = q.shape[-1]
    scale = jax.lax.rsqrt(jnp.float32(d))
    q = q * scale

    nk = pl.cdiv(seq_k, block_k)
    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(jk, carry):
        acc, row_m, row_l = carry
        k_blk = k_ref[0, pl.ds(jk * block_k, block_k), :].astype(
            jnp.float32
        )
        v_blk = v_ref[0, pl.ds(jk * block_k, block_k), :].astype(
            jnp.float32
        )
        s = jnp.dot(q, k_blk.T, preferred_element_type=jnp.float32)
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = k_pos < seq_k  # tail padding
        if causal:
            mask = mask & (q_pos >= k_pos)
        s = jnp.where(mask, s, NEG_INF)
        m = jnp.maximum(row_m, jnp.max(s, axis=-1))
        p = jnp.exp(s - m[:, None])
        alpha = jnp.exp(row_m - m)
        acc = acc * alpha[:, None] + jnp.dot(
            p, v_blk, preferred_element_type=jnp.float32
        )
        row_l = row_l * alpha + jnp.sum(p, axis=-1)
        return acc, m, row_l

    acc0 = jnp.zeros((block_q, d), jnp.float32)
    m0 = jnp.full((block_q,), NEG_INF, jnp.float32)
    l0 = jnp.zeros((block_q,), jnp.float32)
    acc, row_m, row_l = jax.lax.fori_loop(0, nk, body, (acc0, m0, l0))
    out = acc / jnp.maximum(row_l, 1e-30)[:, None]
    o_ref[0] = out.astype(o_ref.dtype)
    # LSE stays finite for fully-masked (padding) rows: m is the
    # finite NEG_INF sentinel and l is clamped, so the backward's
    # exp(s - lse) cannot produce inf*0 NaNs on masked entries
    lse_ref[0, :, 0] = row_m + jnp.log(jnp.maximum(row_l, 1e-30))


def _flash_forward(q, k, v, *, causal: bool, block_q: int, block_k: int,
                   interpret: bool):
    h, s, d = q.shape
    bq = min(block_q, s)
    bk = min(block_k, s)
    nq = pl.cdiv(s, bq)
    nk = pl.cdiv(s, bk)
    # pad both sequence axes to whole blocks: a dynamic slice whose
    # start exceeds the buffer gets CLAMPED, which would silently read
    # the wrong K/V rows on the final partial block
    pad_q = nq * bq - s
    if pad_q:
        q = jnp.pad(q, ((0, 0), (0, pad_q), (0, 0)))
    pad_k = nk * bk - s
    if pad_k:
        k = jnp.pad(k, ((0, 0), (0, pad_k), (0, 0)))
        v = jnp.pad(v, ((0, 0), (0, pad_k), (0, 0)))
    sk = s + pad_k

    kernel = functools.partial(
        _attn_kernel, block_k=bk, seq_k=s, causal=causal, block_q=bq,
    )
    vma = getattr(jax.typeof(q), "vma", frozenset())
    out, lse = pl.pallas_call(
        kernel,
        grid=(h, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda ih, iq: (ih, iq, 0)),
            pl.BlockSpec((1, sk, d), lambda ih, iq: (ih, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda ih, iq: (ih, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bq, d), lambda ih, iq: (ih, iq, 0)),
            # LSE rides as (H, S, 1): Mosaic requires the last two
            # block dims be (8k, 128k)-divisible or full, which a
            # (1, bq) block of an (H, S) array cannot satisfy
            pl.BlockSpec((1, bq, 1), lambda ih, iq: (ih, iq, 0)),
        ],
        # under shard_map's replication tracking the kernel output
        # varies over the same manual axes as its inputs
        out_shape=[
            jax.ShapeDtypeStruct((h, nq * bq, d), q.dtype, vma=vma),
            jax.ShapeDtypeStruct((h, nq * bq, 1), jnp.float32, vma=vma),
        ],
        interpret=interpret,
    )(q, k, v)
    return out[:, :s, :], lse[:, :s, 0]


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref, dq_ref,
                   *, block_k: int, seq_q: int, seq_k: int, causal: bool,
                   block_q: int):
    """dq for one (head, q-block): stream K/V blocks, recompute each
    probability block P = exp(S - LSE) from the saved statistic —
    never an (S, S) tensor, exactly the forward's blocking."""
    iq = pl.program_id(1)
    q = q_ref[0].astype(jnp.float32)
    do = do_ref[0].astype(jnp.float32)
    lse = lse_ref[0, :, 0]
    dlt = dlt_ref[0, :, 0]
    d = q.shape[-1]
    scale = jax.lax.rsqrt(jnp.float32(d))
    qs = q * scale

    nk = pl.cdiv(seq_k, block_k)
    q_pos = iq * block_q + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 0
    )

    def body(jk, dq):
        k_blk = k_ref[0, pl.ds(jk * block_k, block_k), :].astype(
            jnp.float32
        )
        v_blk = v_ref[0, pl.ds(jk * block_k, block_k), :].astype(
            jnp.float32
        )
        s = jnp.dot(qs, k_blk.T, preferred_element_type=jnp.float32)
        k_pos = jk * block_k + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 1
        )
        mask = (q_pos < seq_q) & (k_pos < seq_k)
        if causal:
            mask = mask & (q_pos >= k_pos)
        p = jnp.where(mask, jnp.exp(s - lse[:, None]), 0.0)
        dp = jnp.dot(do, v_blk.T, preferred_element_type=jnp.float32)
        ds = p * (dp - dlt[:, None])
        return dq + jnp.dot(ds, k_blk, preferred_element_type=jnp.float32)

    dq = jax.lax.fori_loop(
        0, nk, body, jnp.zeros((block_q, d), jnp.float32)
    )
    dq_ref[0] = (dq * scale).astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, dlt_ref,
                    dk_ref, dv_ref, *, block_q: int, seq_q: int,
                    seq_k: int, causal: bool, block_k: int):
    """dk/dv for one (head, k-block): stream q/dO/LSE blocks."""
    jk = pl.program_id(1)
    k_blk = k_ref[0].astype(jnp.float32)
    v_blk = v_ref[0].astype(jnp.float32)
    d = k_blk.shape[-1]
    scale = jax.lax.rsqrt(jnp.float32(d))

    nq = pl.cdiv(seq_q, block_q)
    k_pos = jk * block_k + jax.lax.broadcasted_iota(
        jnp.int32, (block_q, block_k), 1
    )

    def body(iq, carry):
        dk, dv = carry
        q_blk = q_ref[0, pl.ds(iq * block_q, block_q), :].astype(
            jnp.float32
        )
        do_blk = do_ref[0, pl.ds(iq * block_q, block_q), :].astype(
            jnp.float32
        )
        lse_blk = lse_ref[0, pl.ds(iq * block_q, block_q), 0]
        dlt_blk = dlt_ref[0, pl.ds(iq * block_q, block_q), 0]
        qs = q_blk * scale
        s = jnp.dot(qs, k_blk.T, preferred_element_type=jnp.float32)
        q_pos = iq * block_q + jax.lax.broadcasted_iota(
            jnp.int32, (block_q, block_k), 0
        )
        mask = (q_pos < seq_q) & (k_pos < seq_k)
        if causal:
            mask = mask & (q_pos >= k_pos)
        p = jnp.where(mask, jnp.exp(s - lse_blk[:, None]), 0.0)
        dv = dv + jnp.dot(p.T, do_blk,
                          preferred_element_type=jnp.float32)
        dp = jnp.dot(do_blk, v_blk.T,
                     preferred_element_type=jnp.float32)
        ds = p * (dp - dlt_blk[:, None])
        # dk_j = sum_i ds_ij * scale * q_i  (qs already carries scale)
        dk = dk + jnp.dot(ds.T, qs, preferred_element_type=jnp.float32)
        return dk, dv

    dk0 = jnp.zeros((block_k, d), jnp.float32)
    dv0 = jnp.zeros((block_k, d), jnp.float32)
    dk, dv = jax.lax.fori_loop(0, nq, body, (dk0, dv0))
    dk_ref[0] = dk.astype(dk_ref.dtype)
    dv_ref[0] = dv.astype(dv_ref.dtype)


def _flash_backward(q, k, v, out, lse, g, *, causal: bool, block_q: int,
                    block_k: int, interpret: bool):
    """Blocked flash backward: delta = rowsum(dO*O) host-side (O(S·D)
    elementwise), then one Pallas sweep per gradient side."""
    h, s, d = q.shape
    bq = min(block_q, s)
    bk = min(block_k, s)
    nq = pl.cdiv(s, bq)
    nk = pl.cdiv(s, bk)
    delta = jnp.sum(
        g.astype(jnp.float32) * out.astype(jnp.float32), axis=-1
    )  # (h, s)

    def pad_seq(x, n_blocks, blk):
        padding = n_blocks * blk - s
        if padding == 0:
            return x
        cfg = ((0, 0), (0, padding)) + ((0, 0),) * (x.ndim - 2)
        return jnp.pad(x, cfg)

    qp = pad_seq(q, nq, bq)
    dop = pad_seq(g, nq, bq)
    lsep = pad_seq(lse, nq, bq)[..., None]   # (h, sq, 1): see forward
    dltp = pad_seq(delta, nq, bq)[..., None]
    kp = pad_seq(k, nk, bk)
    vp = pad_seq(v, nk, bk)
    sq, sk = nq * bq, nk * bk
    vma = getattr(jax.typeof(q), "vma", frozenset())

    dq = pl.pallas_call(
        functools.partial(
            _bwd_dq_kernel, block_k=bk, seq_q=s, seq_k=s,
            causal=causal, block_q=bq,
        ),
        grid=(h, nq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda ih, iq: (ih, iq, 0)),
            pl.BlockSpec((1, sk, d), lambda ih, iq: (ih, 0, 0)),
            pl.BlockSpec((1, sk, d), lambda ih, iq: (ih, 0, 0)),
            pl.BlockSpec((1, bq, d), lambda ih, iq: (ih, iq, 0)),
            pl.BlockSpec((1, bq, 1), lambda ih, iq: (ih, iq, 0)),
            pl.BlockSpec((1, bq, 1), lambda ih, iq: (ih, iq, 0)),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda ih, iq: (ih, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((h, sq, d), q.dtype, vma=vma),
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, dltp)

    dk, dv = pl.pallas_call(
        functools.partial(
            _bwd_dkv_kernel, block_q=bq, seq_q=s, seq_k=s,
            causal=causal, block_k=bk,
        ),
        grid=(h, nk),
        in_specs=[
            pl.BlockSpec((1, sq, d), lambda ih, jk: (ih, 0, 0)),
            pl.BlockSpec((1, bk, d), lambda ih, jk: (ih, jk, 0)),
            pl.BlockSpec((1, bk, d), lambda ih, jk: (ih, jk, 0)),
            pl.BlockSpec((1, sq, d), lambda ih, jk: (ih, 0, 0)),
            pl.BlockSpec((1, sq, 1), lambda ih, jk: (ih, 0, 0)),
            pl.BlockSpec((1, sq, 1), lambda ih, jk: (ih, 0, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, bk, d), lambda ih, jk: (ih, jk, 0)),
            pl.BlockSpec((1, bk, d), lambda ih, jk: (ih, jk, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((h, sk, d), k.dtype, vma=vma),
            jax.ShapeDtypeStruct((h, sk, d), v.dtype, vma=vma),
        ],
        interpret=interpret,
    )(qp, kp, vp, dop, lsep, dltp)
    return dq[:, :s, :], dk[:, :s, :], dv[:, :s, :]


def _reference(q, k, v, causal: bool):
    d = q.shape[-1]
    s = jnp.einsum("hqd,hkd->hqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * jax.lax.rsqrt(jnp.float32(d))
    if causal:
        n = q.shape[1]
        i = jnp.arange(n)
        s = jnp.where(i[:, None] >= i[None, :], s, NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("hqk,hkd->hqd", p,
                      v.astype(jnp.float32)).astype(q.dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def flash_attention(q, k, v, causal: bool = False, block_q: int = 128,
                    block_k: int = 128,
                    interpret: Optional[bool] = None):
    """Blocked attention. q/k/v: (H, S, D); returns (H, S, D).

    ``interpret=None`` auto-selects: compiled on TPU, interpreter
    elsewhere (CI parity runs).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, _ = _flash_forward(q, k, v, causal=causal, block_q=block_q,
                            block_k=block_k, interpret=interpret)
    return out


def _fwd(q, k, v, causal, block_q, block_k, interpret):
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    out, lse = _flash_forward(q, k, v, causal=causal, block_q=block_q,
                              block_k=block_k, interpret=interpret)
    # residuals: inputs + output + per-row LSE — O(S·D), never (S, S)
    return out, (q, k, v, out, lse, interpret)


def _bwd(causal, block_q, block_k, interpret, res, g):
    q, k, v, out, lse, interp = res
    return _flash_backward(
        q, k, v, out, lse, g, causal=causal, block_q=block_q,
        block_k=block_k, interpret=interp,
    )


flash_attention.defvjp(_fwd, _bwd)
