"""Predefined reduction operations — the ``ompi/op`` analogue.

The reference implements every (op × dtype) kernel as a C loop in
``ompi/mca/op/base/op_base_functions.c`` (1544 LoC) with an ``op`` MCA
framework for accelerated overrides. On TPU each op is one XLA
elementwise combiner executed on the VPU, fused by the compiler into the
surrounding collective — there is nothing to hand-roll per dtype.

Each op carries the metadata the collective decision rules need:
commutativity (tuned picks ring only for commutative ops,
``coll_tuned_decision_fixed.c:71``) and an identity element per dtype
(for padded/segmented algorithms).

MINLOC/MAXLOC operate on a (value, index) pair carried as two arrays,
matching MPI's pair-type semantics without byte-packed structs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

import jax.numpy as jnp
import numpy as np

from ..mca import component as mca_component


@dataclasses.dataclass(frozen=True)
class Op:
    """A reduction operator usable by collectives and RMA accumulate."""

    name: str
    fn: Callable[[Any, Any], Any]  # elementwise combiner a⊕b
    commutative: bool = True
    identity: Optional[Callable[[Any], Any]] = None  # dtype -> identity scalar
    # lax reduce primitive name when XLA has a fused collective for it
    # (psum/pmax/pmin); None -> reduce via generic combinator tree
    lax_collective: Optional[str] = None
    is_pair_op: bool = False  # MINLOC/MAXLOC operate on (value, index)

    def identity_for(self, dtype) -> Any:
        if self.identity is None:
            raise ValueError(f"op {self.name} has no identity element")
        return self.identity(np.dtype(dtype) if str(dtype) != "bfloat16" else dtype)

    def __call__(self, a, b):
        return self.fn(a, b)

    def __repr__(self) -> str:
        return f"Op({self.name}, commutative={self.commutative})"


def _min_identity(dtype):
    d = jnp.dtype(dtype)
    if d == jnp.bool_:
        return True
    if jnp.issubdtype(d, jnp.integer):
        return jnp.iinfo(d).max
    return jnp.array(jnp.inf, d)


def _max_identity(dtype):
    d = jnp.dtype(dtype)
    if d == jnp.bool_:
        return False
    if jnp.issubdtype(d, jnp.integer):
        return jnp.iinfo(d).min
    return jnp.array(-jnp.inf, d)


def _band_identity(dtype):
    d = jnp.dtype(dtype)
    if d == jnp.bool_:
        return True
    return d.type(np.iinfo(d).max) if d.kind == "u" else d.type(-1)  # all bits set


SUM = Op("sum", lambda a, b: a + b, True, lambda d: 0, "psum")
PROD = Op("prod", lambda a, b: a * b, True, lambda d: 1)
MAX = Op("max", jnp.maximum, True, _max_identity, "pmax")
MIN = Op("min", jnp.minimum, True, _min_identity, "pmin")
LAND = Op("land", jnp.logical_and, True, lambda d: True)
LOR = Op("lor", jnp.logical_or, True, lambda d: False)
LXOR = Op("lxor", jnp.logical_xor, True, lambda d: False)
BAND = Op("band", lambda a, b: a & b, True, _band_identity)
BOR = Op("bor", lambda a, b: a | b, True, lambda d: 0)
BXOR = Op("bxor", lambda a, b: a ^ b, True, lambda d: 0)
REPLACE = Op("replace", lambda a, b: b, False)  # MPI_REPLACE (RMA)
NO_OP = Op("no_op", lambda a, b: a, False)  # MPI_NO_OP (RMA get-accumulate)


def _maxloc_fn(a, b):
    """a, b are (value, index) tuples; ties pick the lower index (MPI)."""
    av, ai = a
    bv, bi = b
    take_a = (av > bv) | ((av == bv) & (ai <= bi))
    return jnp.where(take_a, av, bv), jnp.where(take_a, ai, bi)


def _minloc_fn(a, b):
    av, ai = a
    bv, bi = b
    take_a = (av < bv) | ((av == bv) & (ai <= bi))
    return jnp.where(take_a, av, bv), jnp.where(take_a, ai, bi)


MAXLOC = Op("maxloc", _maxloc_fn, True, is_pair_op=True)
MINLOC = Op("minloc", _minloc_fn, True, is_pair_op=True)

PREDEFINED_OPS: Dict[str, Op] = {
    op.name: op
    for op in [SUM, PROD, MAX, MIN, LAND, LOR, LXOR, BAND, BOR, BXOR,
               MAXLOC, MINLOC, REPLACE, NO_OP]
}


def user_op(name: str, fn: Callable, commute: bool = True,
            identity: Optional[Callable] = None) -> Op:
    """MPI_Op_create analogue: wrap a user combiner (must be jax-traceable)."""
    return Op(name, fn, commutative=commute, identity=identity)


class XlaOpComponent(mca_component.Component):
    """Default op component: XLA elementwise combiners (always available).

    The ``op`` framework mirrors ``ompi/mca/op``: accelerated components
    (the Pallas streaming-reduce component in ``pallas_op.py``) register
    with higher priority and claim the (op, dtype, size) shapes their
    kernels beat the compiler on — ``resolve`` walks the components in
    priority order exactly like ``ompi_op_base_op_select``.
    """

    NAME = "xla"
    PRIORITY = 10

    def lookup(self, name: str, dtype=None, nbytes: int = 0
               ) -> Optional[Op]:
        return PREDEFINED_OPS.get(name)


OP_FRAMEWORK = mca_component.framework(
    "op", "reduction operator kernels (ompi/mca/op analogue)"
)
OP_FRAMEWORK.register(XlaOpComponent())


def reduce_local(inbuf, inoutbuf, op: Op):
    """MPI_Reduce_local (``ompi/mpi/c/reduce_local.c``): combine two
    local buffers, ``inout = in OP inout`` — no communication.  Pair
    ops take/return ``(values, indices)`` tuples.  Routed through the
    op framework, so an accelerated component (pallas) claims the
    shapes its kernels win on, exactly like the collectives' local
    reduction steps."""
    import jax.numpy as jnp

    if op.is_pair_op:
        (va, ia), (vb, ib) = inbuf, inoutbuf
        return op((jnp.asarray(va), jnp.asarray(ia)),
                  (jnp.asarray(vb), jnp.asarray(ib)))
    a = jnp.asarray(inbuf)
    b = jnp.asarray(inoutbuf)
    resolved = resolve(op, a.dtype, a.size * a.dtype.itemsize)
    return resolved(a, b)


def resolve(op: Op, dtype=None, nbytes: int = 0) -> Op:
    """Accelerated-kernel resolution (``ompi/mca/op`` select): query
    components highest-priority first with the reduction's shape
    context; the first claim wins. Ops no component knows (user ops)
    pass through unchanged. Callers that bake the combiner into a
    compiled program must include the resolved op's name in their
    program cache key — accelerated ops carry distinct names
    (e.g. ``sum[pallas]``) precisely so those keys differ. The
    framework include/exclude variable applies (``--mca op ^pallas``
    turns the accelerated component off job-wide)."""
    for _prio, _comp, module in OP_FRAMEWORK.available():
        found = module.lookup(op.name, dtype=dtype, nbytes=int(nbytes))
        if found is not None:
            return found
    return op
