"""OpenSHMEM layer — the ``oshmem/`` analogue."""

from .shmem import (  # noqa: F401
    ShmemCtx, SymmetricArray, shmem_init, shmem_finalize,
)
