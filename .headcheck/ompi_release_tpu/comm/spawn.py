"""MPI_Comm_spawn — the dpm spawn path, honestly scoped.

The reference's ``MPI_Comm_spawn`` (``ompi/mca/dpm/dpm_orte``) asks the
runtime to launch ``maxprocs`` new processes and returns an
intercommunicator to them. Here the runtime launch is real — a full
:class:`~..tools.tpurun.Job` (fork or ssh, modex, heartbeats, state
machine) driven from a background thread — and the parent<->children
channel is the job's own OOB: the spawning process IS the HNP, so it
holds a lifeline link to every child and exchanges tagged frames with
them directly (``WorkerAgent``'s ``ep`` on the child side).

Scope note (design honesty): the children are separate CONTROLLERS, so
a device-data intercommunicator across the boundary would be a lie in
this runtime — cross-controller device payloads ride the transports
built for that (``DcnBtl.send_staged`` / ``ShmBtl.send_shm`` over this
same OOB). What MPI_Comm_spawn's intercomm is USED for — addressing
the children, messaging them, learning their fate — is all here.
"""

from __future__ import annotations

import threading
from typing import List, Optional, Tuple

from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("dpm")

from ..native import USER_TAG_BASE as TAG_USER_BASE  # noqa: E402
#: user payload tags must stay clear of the coordinator's control tags
#: (shared OOB tag-space constant)


class SpawnedJob:
    """Handle to a spawned child job (the intercomm's working parts:
    remote size, addressing, messaging, completion)."""

    def __init__(self, argv: List[str], maxprocs: int, *,
                 mca: Optional[List[tuple]] = None,
                 hosts=None, timeout_s: float = 300.0) -> None:
        from ..tools.tpurun import Job

        if maxprocs < 1:
            raise MPIError(ErrorCode.ERR_SPAWN, "maxprocs must be >= 1")
        self.maxprocs = maxprocs
        self.job = Job(maxprocs, argv, mca or [], hosts=hosts,
                       heartbeat_s=0.5)
        self._rc: Optional[int] = None
        self._error: Optional[BaseException] = None
        self._timeout_s = timeout_s
        self._thread = threading.Thread(
            target=self._run, args=(timeout_s,), daemon=True
        )
        self._thread.start()

    def _run(self, timeout_s: float) -> None:
        try:
            self._rc = self.job.run(timeout_s=timeout_s)
        except BaseException as exc:  # surfaced by wait()/messaging
            self._error = exc

    def wait_running(self, timeout_s: Optional[float] = None) -> None:
        """Block until the children completed wire-up (job RUNNING) —
        the point from which send/recv are valid."""
        import time

        from ..runtime.state import JobState

        if timeout_s is None:
            timeout_s = self._timeout_s  # the job's own launch budget
        deadline = time.monotonic() + timeout_s
        while time.monotonic() < deadline:
            if self._error is not None:
                raise MPIError(ErrorCode.ERR_SPAWN,
                               f"spawn failed: {self._error}")
            if self.job.job_state.visited(JobState.RUNNING):
                return
            if not self._thread.is_alive():
                raise MPIError(
                    ErrorCode.ERR_SPAWN,
                    f"spawned job exited before wire-up "
                    f"(rc={self._rc})",
                )
            time.sleep(0.02)
        raise MPIError(ErrorCode.ERR_SPAWN,
                       "spawned job did not reach RUNNING in time")

    def _check_live(self) -> None:
        """Messaging a finished job is an error, not a segfault: the
        run thread shuts the HNP endpoint down at job end (the native
        guard also raises on a closed endpoint, belt and braces)."""
        if not self._thread.is_alive():
            raise MPIError(
                ErrorCode.ERR_SPAWN,
                f"spawned job already finished (rc={self._rc}); "
                "late send/recv has no peer",
            )

    # -- the intercomm-ish surface -----------------------------------------
    @property
    def remote_size(self) -> int:
        return self.maxprocs

    def send(self, child_rank: int, tag: int, payload: bytes) -> None:
        """Tagged frame to child ``child_rank`` over its lifeline."""
        if not 0 <= child_rank < self.maxprocs:
            raise MPIError(ErrorCode.ERR_RANK,
                           f"child rank {child_rank} out of range")
        if tag < TAG_USER_BASE:
            raise MPIError(
                ErrorCode.ERR_TAG,
                f"spawn message tags start at {TAG_USER_BASE} "
                "(below is the coordinator control plane)",
            )
        self.wait_running()  # hnp exists only after launch starts
        self._check_live()
        self.job.hnp.ep.send(child_rank + 1, tag, payload)

    def recv(self, tag: int, *, timeout_ms: int = 30_000
             ) -> Tuple[int, bytes]:
        """One frame from any child; returns (child_rank, payload)."""
        if tag < TAG_USER_BASE:
            raise MPIError(ErrorCode.ERR_TAG,
                           f"spawn message tags start at {TAG_USER_BASE}")
        self.wait_running()
        self._check_live()
        src, _, raw = self.job.hnp.ep.recv(tag=tag, timeout_ms=timeout_ms)
        return src - 1, raw

    def wait(self, timeout_s: float = 300.0) -> int:
        """Join the job; returns its aggregate exit code. A launch
        that DIED (exception in the run thread) raises ERR_SPAWN with
        the underlying error instead of masking it."""
        self._thread.join(timeout=timeout_s)
        if self._thread.is_alive():
            raise MPIError(ErrorCode.ERR_PENDING,
                           "spawned job still running")
        if self._error is not None or self._rc is None:
            raise MPIError(ErrorCode.ERR_SPAWN,
                           f"spawned job launch failed: {self._error}")
        return int(self._rc)

    @property
    def running(self) -> bool:
        return self._thread.is_alive()

    def terminate(self) -> None:
        self.job.abort("parent terminated the spawn")


def comm_spawn(command: List[str], maxprocs: int, *,
               mca: Optional[List[tuple]] = None, hosts=None,
               timeout_s: float = 300.0) -> SpawnedJob:
    """``MPI_Comm_spawn`` analogue: launch ``maxprocs`` children and
    return the handle. Children initialize through the normal tpurun
    wire-up (``mpi.init()`` inside the child sees the coordinator) and
    reach the parent at node 0 via ``Runtime.current().agent``."""
    return SpawnedJob(command, maxprocs, mca=mca, hosts=hosts,
                      timeout_s=timeout_s)
