"""MPI_Info — the ``ompi/info`` analogue.

The reference implements info objects as ordered key/value lists with
bounded key/value lengths and a set of reserved keys surfaced through
``MPI_INFO_ENV`` (``ompi/info/info.c``). Same surface here: create /
set / get / delete / dup / nkeys / nthkey, insertion-ordered (MPI
requires MPI_Info_get_nthkey to enumerate in a consistent order),
plus ``INFO_ENV`` pre-populated from the runtime environment the way
``MPI_INFO_ENV`` carries command/argv/maxprocs/soft etc.
"""

from __future__ import annotations

import os
import sys
from typing import Dict, Iterator, Optional

from ..utils.errors import ErrorCode, MPIError

MAX_KEY_LEN = 255    # MPI_MAX_INFO_KEY
MAX_VALUE_LEN = 1024  # MPI_MAX_INFO_VAL


class Info:
    """Insertion-ordered string->string map with MPI's validation."""

    def __init__(self, initial: Optional[Dict[str, str]] = None) -> None:
        self._kv: Dict[str, str] = {}
        if initial:
            for k, v in initial.items():
                self.set(k, v)

    @staticmethod
    def _check_key(key: str) -> None:
        if not isinstance(key, str) or not key:
            raise MPIError(ErrorCode.ERR_ARG, "info key must be non-empty")
        if len(key) > MAX_KEY_LEN:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"info key exceeds MPI_MAX_INFO_KEY ({MAX_KEY_LEN})",
            )

    def set(self, key: str, value: str) -> None:
        """MPI_Info_set (overwrites like the reference)."""
        self._check_key(key)
        value = str(value)
        if len(value) > MAX_VALUE_LEN:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"info value exceeds MPI_MAX_INFO_VAL ({MAX_VALUE_LEN})",
            )
        self._kv[key] = value  # dict preserves insertion order

    def get(self, key: str) -> Optional[str]:
        """MPI_Info_get: value or None when unset (flag=false)."""
        self._check_key(key)
        return self._kv.get(key)

    def delete(self, key: str) -> None:
        """MPI_Info_delete: ERR on missing key (MPI_ERR_INFO_NOKEY)."""
        self._check_key(key)
        if key not in self._kv:
            raise MPIError(ErrorCode.ERR_ARG,
                           f"info key '{key}' not set (MPI_ERR_INFO_NOKEY)")
        del self._kv[key]

    def dup(self) -> "Info":
        """MPI_Info_dup: independent deep copy."""
        return Info(dict(self._kv))

    @property
    def nkeys(self) -> int:
        return len(self._kv)

    def nthkey(self, n: int) -> str:
        """MPI_Info_get_nthkey: insertion order, range-checked."""
        if not 0 <= n < len(self._kv):
            raise MPIError(ErrorCode.ERR_ARG,
                           f"info has {len(self._kv)} keys, asked for {n}")
        return list(self._kv)[n]

    def keys(self) -> Iterator[str]:
        return iter(self._kv)

    def items(self):
        return self._kv.items()

    def as_dict(self) -> Dict[str, str]:
        return dict(self._kv)

    def __contains__(self, key: str) -> bool:
        return key in self._kv

    def __len__(self) -> int:
        return len(self._kv)

    def __repr__(self) -> str:
        return f"Info({self._kv})"


def _build_info_env() -> Info:
    """MPI_INFO_ENV: the reserved startup keys the reference fills
    from the launch environment (``ompi/runtime/ompi_mpi_init.c``
    MPI_INFO_ENV setup)."""
    info = Info()
    info.set("command", sys.argv[0] if sys.argv else "")
    info.set("argv", " ".join(sys.argv[1:])[:MAX_VALUE_LEN])
    if os.environ.get("OMPITPU_NUM_NODES"):
        info.set("maxprocs", os.environ["OMPITPU_NUM_NODES"])
    info.set("soft", "")
    info.set("host", os.environ.get("OMPITPU_HOST", ""))
    info.set("arch", sys.platform)
    info.set("wdir", os.getcwd()[:MAX_VALUE_LEN])
    info.set("thread_level", "MPI_THREAD_MULTIPLE")
    return info


INFO_ENV = _build_info_env()
INFO_NULL = None  # MPI_INFO_NULL: the absence of an info object
