"""Intercommunicators — ``MPI_Intercomm_create/merge`` + ``coll/inter``.

Reference analogues: intercommunicator construction and merge in
``ompi/communicator/comm.c`` (ompi_comm_create with remote group,
ompi_intercomm_merge), inter-collective semantics in
``ompi/mca/coll/inter/coll_inter.c``.

An intercommunicator binds a *local* group and a disjoint *remote*
group; collectives have cross-group semantics (data always flows
between the groups, never within one). The reference implements
inter-collectives by composing intra-collectives with a leader
exchange (coll_inter's gather-to-leader / leader-exchange /
bcast-from-leader pattern). TPU-native, the same composition appears
as compiled collectives over each group's sub-mesh — the "leader
exchange" is a device-to-device array handoff that XLA routes over
ICI, free of host staging, and the rooted ops are single bcast/gather
programs over the receiving group's sub-mesh.

Driver-mode conventions match :class:`Communicator`: one controller
plays every rank, so cross-group ops take both sides' buffers
(leading axis = that group's size) and results are reported from the
handle's perspective (what *local* ranks receive). Every
intercommunicator is created as a mirrored pair sharing one merged
sub-mesh; ``mirror`` is the remote side's handle.
"""

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np

from ..utils import output
from ..utils.errors import ErrorCode, MPIError
from .communicator import Communicator
from .group import Group

_log = output.stream("comm")


class Intercommunicator(Communicator):
    """One side's handle of an intercommunicator pair.

    ``group`` (inherited) is the LOCAL group; ``remote_group`` is the
    other side. ``size``/``rank_of`` follow MPI: they describe the
    local group; ``remote_size`` describes the remote group.
    """

    is_inter = True

    def __init__(self, runtime, local_group: Group, remote_group: Group,
                 *, name: str = "", parent: Optional[Communicator] = None,
                 _bridge: Optional[Communicator] = None) -> None:
        overlap = set(local_group.world_ranks) & set(remote_group.world_ranks)
        if overlap:
            raise MPIError(
                ErrorCode.ERR_GROUP,
                f"intercomm groups must be disjoint; overlap={sorted(overlap)}",
            )
        if local_group.size == 0 or remote_group.size == 0:
            raise MPIError(ErrorCode.ERR_GROUP,
                           "intercomm groups must be non-empty")
        self.remote_group = remote_group
        super().__init__(runtime, local_group, name=name, parent=parent)
        # the bridge is an ordinary intra-communicator over
        # local+remote in that order — the compiled union mesh both
        # perspectives share (the coll/inter "merged" substrate)
        if _bridge is None:
            _bridge = Communicator(
                runtime,
                Group(local_group.world_ranks + remote_group.world_ranks),
                name=f"bridge({self.name})", parent=parent,
            )
        self._bridge = _bridge
        self.mirror: Optional["Intercommunicator"] = None  # set by create

    # -- queries ----------------------------------------------------------
    @property
    def remote_size(self) -> int:
        return self.remote_group.size

    # -- construction -----------------------------------------------------
    @classmethod
    def create(cls, runtime, group_a: Group, group_b: Group, *,
               name: str = "", parent: Optional[Communicator] = None,
               ) -> Tuple["Intercommunicator", "Intercommunicator"]:
        """Build the mirrored pair (side A's handle, side B's handle)."""
        ia = cls(runtime, group_a, group_b, name=name or "intercomm",
                 parent=parent)
        ib = cls(runtime, group_b, group_a, name=f"{ia.name}~mirror",
                 parent=parent, _bridge=ia._bridge)
        ia.mirror, ib.mirror = ib, ia
        return ia, ib

    def merge(self, high: bool = False) -> Communicator:
        """``MPI_Intercomm_merge``: intra-communicator over the union.

        ``high`` is this side's vote: the low group's ranks come first
        (``comm.c`` ompi_intercomm_merge ordering). Mirrored handles
        created by :meth:`create` are merged from either side.
        """
        self._check_alive()
        first, second = (
            (self.remote_group, self.group) if high
            else (self.group, self.remote_group)
        )
        return Communicator(
            self.runtime,
            Group(first.world_ranks + second.world_ranks),
            name=f"merge({self.name})", parent=self,
        )

    # -- inter collectives (coll/inter analogue) --------------------------
    # All take driver-mode buffers: *_local has leading axis = local
    # size, *_remote leading axis = remote size. Results are what the
    # LOCAL side receives.
    def _local_comm(self) -> Communicator:
        c = getattr(self, "_local_intra", None)
        if c is None:
            c = Communicator(self.runtime, self.group,
                             name=f"local({self.name})", parent=self)
            self._local_intra = c
        return c

    def _remote_comm(self) -> Communicator:
        # the mirror's local comm, so compiled programs are shared
        if self.mirror is not None:
            return self.mirror._local_comm()
        c = getattr(self, "_remote_intra", None)
        if c is None:
            c = Communicator(self.runtime, self.remote_group,
                             name=f"remote({self.name})", parent=self)
            self._remote_intra = c
        return c

    def _check_counts(self, bufs, n: int, what: str) -> None:
        if len(bufs) != n:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"{what} needs {n} per-rank buffers, got {len(bufs)}",
            )

    def barrier(self) -> None:
        """Inter-barrier: no rank leaves until every rank of BOTH
        groups arrived — the bridge's barrier is exactly that."""
        self._check_alive()
        self._bridge.barrier()

    def ibarrier(self):
        """Nonblocking inter-barrier rides the BRIDGE (both groups),
        not the inherited local-group dispatch — an ibarrier that
        completes without the remote group arriving would be a
        semantic lie."""
        self._check_alive()
        return self._bridge.ibarrier()

    # nonblocking inter variants: the blocking inter ops already
    # dispatch asynchronously (XLA arrays are futures), so each i-op
    # is its blocking form wrapped in a readiness Request. The
    # inherited intra i-variants would misbind onto the inter
    # signatures (send_local/send_remote), so every one is overridden.
    def iallgather(self, send_local, send_remote):
        return self._async(self.allgather(send_local, send_remote))

    def iallreduce(self, send_local, send_remote, op=None):
        return self._async(self.allreduce(send_local, send_remote, op))

    def ibcast(self, x, root: int):
        return self._async(self.bcast(x, root))

    def ireduce(self, send_remote, op=None, root: int = 0):
        return self._async(self.reduce(send_remote, op, root))

    def igather(self, send_remote, root: int = 0):
        return self._async(self.gather(send_remote, root))

    def iscatter(self, sendbuf, root: int):
        return self._async(self.scatter(sendbuf, root))

    def ialltoall(self, send_local, send_remote):
        return self._async(self.alltoall(send_local, send_remote))

    def allgather(self, send_local, send_remote):
        """Each local rank receives the remote group's buffers
        concatenated in remote rank order (identical across local
        ranks — returned once, driver convention)."""
        self._check_alive()
        self._check_counts(send_local, self.size, "allgather local")
        self._check_counts(send_remote, self.remote_size, "allgather remote")
        # coll_inter_allgather: intra-gather in the remote group, then
        # deliver across. The intra-allgather runs on the remote
        # sub-mesh; the handoff to our ranks is a device array the
        # bridge mesh already spans. Identical on every local rank,
        # so returned once (driver convention for uniform results).
        return self._remote_comm().allgather(np.asarray(send_remote))[0]

    def allreduce(self, send_local, send_remote, op=None):
        """Local ranks receive the reduction of the REMOTE group's
        contributions (MPI inter-allreduce semantics). ``send_local``
        is what OUR ranks contribute to the remote side's result; it
        is validated here (both handles must be well-formed on either
        side of the intercomm) and consumed by the remote group's own
        call."""
        self._check_alive()
        from .. import ops as ops_mod

        self._check_counts(send_local, self.size, "allreduce local")
        self._check_counts(send_remote, self.remote_size, "allreduce remote")
        return self._remote_comm().allreduce(
            np.asarray(send_remote), op or ops_mod.SUM
        )[0]

    def bcast(self, x, root: int):
        """Root is a rank in the REMOTE group (the MPI_ROOT side);
        local ranks receive its buffer. The rooted broadcast is a
        bridge bcast from the remote root's bridge rank across the
        union mesh."""
        self._check_alive()
        if not 0 <= root < self.remote_size:
            raise MPIError(ErrorCode.ERR_ROOT,
                           f"root {root} not in remote group")
        bridge_root = self._bridge.group.rank_of(
            self.remote_group.world_rank(root)
        )
        x = np.asarray(x)
        placed = np.broadcast_to(x, (self._bridge.size,) + x.shape)
        return self._bridge.bcast(placed, root=bridge_root)[0]

    def reduce(self, send_remote, op=None, root: int = 0):
        """Reduce the REMOTE group's contributions to local rank
        ``root`` (this side is the root group).

        Driver convention — root-agnostic result: with one controller
        playing every local rank there is no per-rank delivery, so the
        reduction is computed once (as a remote-group allreduce — the
        reduction order is that allreduce's order, not a rooted-tree
        order) and returned to the caller, who IS every local rank
        including the root. ``root`` is range-validated so erroneous
        programs fail identically to the reference."""
        self._check_alive()
        from .. import ops as ops_mod

        if not 0 <= root < self.size:
            raise MPIError(ErrorCode.ERR_ROOT,
                           f"root {root} not in local group")
        self._check_counts(send_remote, self.remote_size, "reduce remote")
        return self._remote_comm().allreduce(
            np.asarray(send_remote), op or ops_mod.SUM
        )[0]

    def gather(self, send_remote, root: int = 0):
        """Local rank ``root`` receives the remote group's buffers in
        remote rank order (root-group perspective). Root-agnostic
        driver convention as in :meth:`reduce`: the gathered buffer is
        returned once to the caller (who plays every local rank);
        ``root`` is range-validated only."""
        self._check_alive()
        if not 0 <= root < self.size:
            raise MPIError(ErrorCode.ERR_ROOT,
                           f"root {root} not in local group")
        self._check_counts(send_remote, self.remote_size, "gather remote")
        return self._remote_comm().allgather(np.asarray(send_remote))[0]

    def scatter(self, sendbuf, root: int):
        """Remote rank ``root`` scatters; local ranks receive one
        chunk each (leading axis of ``sendbuf`` = local size)."""
        self._check_alive()
        if not 0 <= root < self.remote_size:
            raise MPIError(ErrorCode.ERR_ROOT,
                           f"root {root} not in remote group")
        sendbuf = np.asarray(sendbuf)
        if sendbuf.shape[0] != self.size:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"scatter sendbuf leading axis {sendbuf.shape[0]} != "
                f"local size {self.size}",
            )
        # the rooted delivery runs as the local sub-mesh's compiled
        # scatter (coll_inter's bcast-then-intra pattern; the remote
        # root's buffer is host-visible under one controller). The
        # result stays a device array so iscatter keeps real overlap.
        import jax.numpy as jnp

        n = self.size
        flat = sendbuf.reshape(n, -1)
        arr = np.broadcast_to(flat.reshape(-1), (n, flat.size))
        out = self._local_comm().scatter(arr, root=0)
        return jnp.reshape(out, sendbuf.shape)

    def alltoall(self, send_local, send_remote):
        """Inter-alltoall: local rank i sends ``send_local[i][j]`` to
        remote rank j; returns what local ranks receive —
        ``recv[i][j] = send_remote[j][i]``.

        Runs as the BRIDGE's compiled intra-alltoall with the
        off-diagonal block pattern (local rows only populate remote
        destinations and vice versa): one program over the union mesh,
        so the result lands sharded on the union mesh like every other
        inter op — not as a host-side transpose."""
        self._check_alive()
        send_local = np.asarray(send_local)
        send_remote = np.asarray(send_remote)
        nl, nr = self.size, self.remote_size
        if send_local.shape[:2] != (nl, nr):
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"send_local must be (local={nl}, remote={nr}, ...), "
                f"got {send_local.shape}",
            )
        if send_remote.shape[:2] != (nr, nl):
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"send_remote must be (remote={nr}, local={nl}, ...), "
                f"got {send_remote.shape}",
            )
        if send_local.shape[2:] != send_remote.shape[2:]:
            raise MPIError(
                ErrorCode.ERR_ARG,
                "send_local/send_remote chunk shapes differ: "
                f"{send_local.shape[2:]} vs {send_remote.shape[2:]}",
            )
        n = nl + nr
        trail = send_local.shape[2:]
        full = np.zeros((n, n) + trail, send_local.dtype)
        full[:nl, nl:] = send_local          # local rows -> remote dests
        full[nl:, :nl] = send_remote         # remote rows -> local dests
        # bridge alltoall convention: per-rank slice holds n chunks
        # back to back along the leading axis. Reshape/slice stay jnp
        # (device-side, async dispatch) so ialltoall keeps overlap.
        import jax.numpy as jnp

        out = self._bridge.alltoall(full.reshape((n, -1) + trail[1:])
                                    if trail else full.reshape(n, n))
        out = jnp.reshape(out, (n, n) + trail)
        # local rank i's received remote chunks: out[i][nl:]
        return out[:nl, nl:]

    # -- point-to-point (MPI intercomm addressing) -------------------------
    # On an intercommunicator, dest/source are ranks in the REMOTE
    # group (MPI-2 semantics). The inherited Communicator p2p would
    # silently deliver within the local group — wrong recipient, no
    # error — so every p2p op translates through the bridge comm's
    # PML: local rank -> bridge rank [0, nl), remote rank -> bridge
    # rank [nl, nl+nr).
    def _bridge_local(self, r: int) -> int:
        if not 0 <= r < self.size:
            raise MPIError(ErrorCode.ERR_RANK,
                           f"local rank {r} out of range")
        return self._bridge.group.rank_of(self.group.world_rank(r))

    def _bridge_remote(self, r: int) -> int:
        if not 0 <= r < self.remote_size:
            raise MPIError(ErrorCode.ERR_RANK,
                           f"remote rank {r} out of range")
        return self._bridge.group.rank_of(self.remote_group.world_rank(r))

    def isend(self, data, dest: int, tag: int = 0, *, rank: int, **kw):
        return self._bridge.isend(
            data, self._bridge_remote(dest), tag,
            rank=self._bridge_local(rank), **kw,
        )

    def send(self, data, dest: int, tag: int = 0, *, rank: int, **kw):
        return self._bridge.send(
            data, self._bridge_remote(dest), tag,
            rank=self._bridge_local(rank), **kw,
        )

    def _status_to_remote(self, status):
        """Translate a Status carrying a bridge source rank into the
        REMOTE-group rank MPI intercomm semantics report (a server
        replying to status.source would otherwise address the wrong
        process — or a nonexistent one)."""
        if status is not None and status.source >= 0:
            world = self._bridge.group.world_rank(status.source)
            status.source = self.remote_group.rank_of(world)
        return status

    def irecv(self, source: int = -1, tag: int = -1, *, rank: int):
        src = -1 if source == -1 else self._bridge_remote(source)
        req = self._bridge.irecv(src, tag, rank=self._bridge_local(rank))
        req.on_complete(lambda r: self._status_to_remote(r.status))
        return req

    def recv(self, source: int = -1, tag: int = -1, *, rank: int):
        src = -1 if source == -1 else self._bridge_remote(source)
        value, status = self._bridge.recv(
            src, tag, rank=self._bridge_local(rank)
        )
        return value, self._status_to_remote(status)

    def iprobe(self, source: int = -1, tag: int = -1, *, rank: int):
        src = -1 if source == -1 else self._bridge_remote(source)
        status = self._bridge.iprobe(
            src, tag, rank=self._bridge_local(rank)
        )
        return self._status_to_remote(status)

    def sendrecv(self, *a, **kw):
        raise MPIError(
            ErrorCode.ERR_COMM,
            "sendrecv has no inter-communicator implementation here "
            "(use isend/recv with remote-rank addressing)",
        )

    # intra-only operations are ERR_COMM on an intercommunicator,
    # matching MPI (scan/exscan/split et al. require an intracomm);
    # inter variants not yet implemented raise rather than silently
    # running with intra semantics over the local group
    def _intra_only(self, what: str):
        raise MPIError(ErrorCode.ERR_COMM,
                       f"{what} is intra-communicator only")

    def scan(self, *a, **kw):
        self._intra_only("scan")

    def exscan(self, *a, **kw):
        self._intra_only("exscan")

    def iscan(self, *a, **kw):
        self._intra_only("iscan")

    def iexscan(self, *a, **kw):
        self._intra_only("iexscan")

    def split(self, *a, **kw):
        raise MPIError(
            ErrorCode.ERR_COMM,
            "split on intercommunicators is not supported (use merge)",
        )

    # -- inter v-variants (ragged; results land in the group
    # complementary to the contributors, MPI inter semantics) -------------
    def allgatherv(self, send_local, send_remote):
        """Local ranks receive the REMOTE group's ragged buffers
        concatenated in remote rank order (returned once — the driver
        convention for uniform results). ``send_local`` feeds the
        mirrored call and is validated here."""
        self._check_alive()
        self._check_counts(send_local, self.size, "allgatherv local")
        self._check_counts(send_remote, self.remote_size,
                           "allgatherv remote")
        return self._remote_comm().allgatherv(list(send_remote))

    def gatherv(self, send_remote, root: int = 0):
        """Local rank ``root`` receives the remote group's ragged
        concatenation (root-agnostic driver convention, see
        :meth:`reduce`)."""
        self._check_alive()
        if not 0 <= root < self.size:
            raise MPIError(ErrorCode.ERR_ROOT,
                           f"root {root} not in local group")
        self._check_counts(send_remote, self.remote_size,
                           "gatherv remote")
        return self._remote_comm().allgatherv(list(send_remote))

    def scatterv(self, sendbuf, counts, root: int = 0):
        """Remote rank ``root`` scatters ``counts[i]`` elements to
        local rank i (ragged chunks; one array per local rank)."""
        self._check_alive()
        if not 0 <= root < self.remote_size:
            raise MPIError(ErrorCode.ERR_ROOT,
                           f"root {root} not in remote group")
        return self._local_comm().scatterv(
            np.asarray(sendbuf).reshape(-1), counts, root=0
        )

    def reduce_scatter_block(self, send_remote, op=None):
        """The remote group's contributions reduced elementwise, the
        result split in equal blocks over the local ranks (leading
        local axis, like the intra form)."""
        self._check_alive()
        import jax.numpy as jnp

        from .. import ops as ops_mod

        self._check_counts(send_remote, self.remote_size, "rsb remote")
        red = np.asarray(self._remote_comm().allreduce(
            np.asarray(send_remote), op or ops_mod.SUM
        )[0])
        n = self.size
        if red.shape[0] % n:
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"reduce_scatter_block length {red.shape[0]} not "
                f"divisible by local size {n}",
            )
        return jnp.asarray(red.reshape((n, -1) + red.shape[1:]))

    def reduce_scatter(self, send_remote, recvcounts, op=None):
        """General inter reduce_scatter: local rank i keeps the
        ``recvcounts[i]``-long segment of the remote group's
        reduction. Returns one array per local rank."""
        self._check_alive()
        import jax.numpy as jnp

        from .. import ops as ops_mod

        recvcounts = [int(c) for c in recvcounts]
        if len(recvcounts) != self.size or any(c < 0 for c in recvcounts):
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"reduce_scatter needs {self.size} non-negative counts",
            )
        self._check_counts(send_remote, self.remote_size, "rs remote")
        red = np.asarray(self._remote_comm().allreduce(
            np.asarray(send_remote), op or ops_mod.SUM
        )[0]).reshape(-1)
        if red.shape[0] != sum(recvcounts):
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"reduce_scatter buffer length {red.shape[0]} != "
                f"counts sum {sum(recvcounts)}",
            )
        offs = np.concatenate([[0], np.cumsum(recvcounts)])
        return [jnp.asarray(red[offs[i]:offs[i] + recvcounts[i]])
                for i in range(self.size)]

    def alltoallv(self, send_local, counts_local, send_remote,
                  counts_remote):
        """Inter alltoallv: local rank i sends ``counts_local[i][j]``
        elements to remote rank j and receives remote rank j's chunk
        for it. Returns ``recv[i]`` per local rank in remote-rank
        order. Pure ragged edge slicing under one controller (the
        compiled equal-block path is :meth:`alltoall`)."""
        self._check_alive()
        import jax.numpy as jnp

        nl, nr = self.size, self.remote_size
        self._check_counts(send_local, nl, "alltoallv local")
        self._check_counts(send_remote, nr, "alltoallv remote")
        cl = np.asarray(counts_local, np.int64).reshape(nl, nr)
        cr = np.asarray(counts_remote, np.int64).reshape(nr, nl)
        if (cl < 0).any() or (cr < 0).any():
            raise MPIError(ErrorCode.ERR_COUNT,
                           "alltoallv counts must be >= 0")
        bufs_r = [np.asarray(b).reshape(-1) for b in send_remote]
        for j in range(nr):
            if bufs_r[j].shape[0] != int(cr[j].sum()):
                raise MPIError(
                    ErrorCode.ERR_COUNT,
                    f"alltoallv remote rank {j}: buffer has "
                    f"{bufs_r[j].shape[0]} elements, counts sum to "
                    f"{int(cr[j].sum())}",
                )
        offs = np.concatenate(
            [np.zeros((nr, 1), np.int64), np.cumsum(cr, axis=1)], axis=1
        )
        # no blocking barrier here: the sibling v-variants complete
        # through their device results, and a barrier inside the
        # blocking body would make ialltoallv synchronous
        return [
            jnp.asarray(np.concatenate(
                [bufs_r[j][offs[j, i]:offs[j, i] + int(cr[j, i])]
                 for j in range(nr)]
            ) if nr else np.zeros(0))
            for i in range(nl)
        ]

    def iallgatherv(self, send_local, send_remote):
        return self._async(self.allgatherv(send_local, send_remote))

    def igatherv(self, send_remote, root: int = 0):
        return self._async(self.gatherv(send_remote, root))

    def iscatterv(self, sendbuf, counts, root: int = 0):
        return self._async(self.scatterv(sendbuf, counts, root))

    def ireduce_scatter_block(self, send_remote, op=None):
        return self._async(self.reduce_scatter_block(send_remote, op))

    def ireduce_scatter(self, send_remote, recvcounts, op=None):
        return self._async(
            self.reduce_scatter(send_remote, recvcounts, op))

    def ialltoallv(self, send_local, counts_local, send_remote,
                   counts_remote):
        return self._async(self.alltoallv(
            send_local, counts_local, send_remote, counts_remote))

    def __repr__(self) -> str:
        return (
            f"Intercommunicator({self.name}, cid={self.cid}, "
            f"local={self.size}, remote={self.remote_size})"
        )


def intercomm_create(
    local_comm: Communicator, local_leader: int,
    peer_comm: Communicator, remote_leader: int, tag: int = 0,
) -> Tuple[Intercommunicator, Intercommunicator]:
    """``MPI_Intercomm_create``: bridge two disjoint intra-comms.

    ``local_leader``/``remote_leader`` are ranks within each comm whose
    peer link carries the group exchange in the reference
    (``comm.c`` ompi_intercomm_create's leader handshake over
    ``peer_comm``); under one controller the handshake is immediate
    but the leaders are still validated. Returns the mirrored pair
    (local side's handle first).
    """
    if not 0 <= local_leader < local_comm.size:
        raise MPIError(ErrorCode.ERR_RANK,
                       f"local_leader {local_leader} out of range")
    if not 0 <= remote_leader < peer_comm.size:
        raise MPIError(ErrorCode.ERR_RANK,
                       f"remote_leader {remote_leader} out of range")
    return Intercommunicator.create(
        local_comm.runtime, local_comm.group, peer_comm.group,
        name=f"intercomm({local_comm.name},{peer_comm.name})",
        parent=local_comm,
    )
