"""WORLD/SELF communicator creation (``ompi_comm_init`` analogue)."""

from __future__ import annotations

from typing import Tuple

from .communicator import Communicator
from .group import Group


def create_world(runtime) -> Tuple[Communicator, Communicator]:
    world_group = Group(range(runtime.world_size))
    world = Communicator(runtime, world_group, name="MPI_COMM_WORLD")
    # COMM_SELF is per-rank; in driver mode one size-1 comm stands in
    # for it — under a unified multi-controller world it must hold a
    # LOCAL rank (this process's first), not world rank 0
    self_group = Group([getattr(runtime, "local_rank_offset", 0)])
    comm_self = Communicator(runtime, self_group, name="MPI_COMM_SELF")
    return world, comm_self
