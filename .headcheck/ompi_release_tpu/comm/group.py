"""Process groups — the ``ompi/group`` analogue.

A group is an ordered set of world ranks (mesh flat positions). All of
MPI's group calculus is here: incl/excl/range variants, set operations,
rank translation, comparison. Groups are immutable value objects;
communicators are created *from* groups (``MPI_Comm_create``).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from ..utils.errors import ErrorCode, MPIError

# comparison results (MPI_IDENT/SIMILAR/UNEQUAL)
IDENT = 0
SIMILAR = 1
UNEQUAL = 2

UNDEFINED = -1  # MPI_UNDEFINED


class Group:
    __slots__ = ("_ranks", "_index")

    def __init__(self, ranks: Sequence[int]) -> None:
        ranks = tuple(int(r) for r in ranks)
        if len(set(ranks)) != len(ranks):
            raise MPIError(ErrorCode.ERR_GROUP, f"duplicate ranks: {ranks}")
        self._ranks = ranks
        self._index = {r: i for i, r in enumerate(ranks)}

    # -- queries -----------------------------------------------------------
    @property
    def size(self) -> int:
        return len(self._ranks)

    @property
    def world_ranks(self) -> Tuple[int, ...]:
        return self._ranks

    def rank_of(self, world_rank: int) -> int:
        """Local rank of a world rank, or UNDEFINED."""
        return self._index.get(int(world_rank), UNDEFINED)

    def world_rank(self, local_rank: int) -> int:
        if not 0 <= local_rank < self.size:
            raise MPIError(ErrorCode.ERR_RANK, f"rank {local_rank} not in group")
        return self._ranks[local_rank]

    def translate_ranks(self, ranks: Sequence[int],
                        other: "Group") -> List[int]:
        """MPI_Group_translate_ranks: my local ranks -> other's locals."""
        return [other.rank_of(self.world_rank(r)) for r in ranks]

    def compare(self, other: "Group") -> int:
        if self._ranks == other._ranks:
            return IDENT
        if set(self._ranks) == set(other._ranks):
            return SIMILAR
        return UNEQUAL

    # -- constructors ------------------------------------------------------
    def incl(self, ranks: Sequence[int]) -> "Group":
        return Group([self.world_rank(r) for r in ranks])

    def excl(self, ranks: Sequence[int]) -> "Group":
        drop = {self.world_rank(r) for r in ranks}
        return Group([r for r in self._ranks if r not in drop])

    def range_incl(self, ranges: Sequence[Tuple[int, int, int]]) -> "Group":
        """ranges = [(first, last, stride), ...], inclusive like MPI."""
        picked: List[int] = []
        for first, last, stride in ranges:
            if stride == 0:
                raise MPIError(ErrorCode.ERR_ARG, "zero stride")
            r = first
            if stride > 0:
                while r <= last:
                    picked.append(r)
                    r += stride
            else:
                while r >= last:
                    picked.append(r)
                    r += stride
        return self.incl(picked)

    def range_excl(self, ranges: Sequence[Tuple[int, int, int]]) -> "Group":
        inc = self.range_incl(ranges)
        drop = set(inc._ranks)
        return Group([r for r in self._ranks if r not in drop])

    def union(self, other: "Group") -> "Group":
        extra = [r for r in other._ranks if r not in self._index]
        return Group(self._ranks + tuple(extra))

    def intersection(self, other: "Group") -> "Group":
        return Group([r for r in self._ranks if other.rank_of(r) != UNDEFINED])

    def difference(self, other: "Group") -> "Group":
        return Group([r for r in self._ranks if other.rank_of(r) == UNDEFINED])

    def __len__(self) -> int:
        return self.size

    def __eq__(self, other) -> bool:
        return isinstance(other, Group) and self._ranks == other._ranks

    def __hash__(self) -> int:
        return hash(self._ranks)

    def __repr__(self) -> str:
        return f"Group({list(self._ranks)})"


EMPTY = Group(())
