"""Communicator/group layer (ompi/communicator + ompi/group analogue)."""

from .group import EMPTY, IDENT, SIMILAR, UNDEFINED, UNEQUAL, Group
from .communicator import (
    Communicator, Keyval, clear_comm_registry, create_keyval, free_keyval,
)
from .info import INFO_ENV, INFO_NULL, Info
from .intercomm import Intercommunicator, intercomm_create
from .dpm import (
    open_port, close_port, publish_name, unpublish_name, lookup_name,
    comm_accept, comm_connect,
)
from .spawn import SpawnedJob, comm_spawn
from .world import create_world

__all__ = [
    "Group", "EMPTY", "IDENT", "SIMILAR", "UNEQUAL", "UNDEFINED",
    "Communicator", "Keyval", "create_keyval", "free_keyval",
    "clear_comm_registry", "create_world",
    "Intercommunicator", "intercomm_create",
    "Info", "INFO_ENV", "INFO_NULL",
    "SpawnedJob", "comm_spawn",
    "open_port", "close_port", "publish_name", "unpublish_name",
    "lookup_name", "comm_accept", "comm_connect",
]
