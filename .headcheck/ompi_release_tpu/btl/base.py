"""btl framework — the byte-transfer-layer analogue, device-native.

The reference's data-plane pluggability lives in the BTL interface
(``ompi/mca/btl/btl.h:795-838``): each module exposes transfer entry
points plus *attributes* — eager/rndv/max-send sizes (``btl.h:799-804``)
and a latency/bandwidth ranking (``btl.h:806-807``) — and the BML "r2"
multiplexer sorts each peer's eligible BTLs into ``btl_eager`` /
``btl_send`` / ``btl_rdma`` lists and stripes large transfers across
rails (``ompi/mca/bml/bml.h:71,229``).

TPU-native reinterpretation: a "transfer" is a device-to-device array
move. The wire protocols (sockets, verbs QPs, shared-memory FIFOs)
collapse into *which fabric the runtime routes the copy over* —
intra-slice ICI, inter-slice/host DCN, or an explicit host-memory
staging bounce — so a component here is a (reachability predicate,
move function, size/ranking attributes) triple, and the BML's job —
pick the transfer path per peer and per message size, stripe segments
across rails — survives unchanged.

Module attributes (all MCA-variable overridable, per component):
  eager_limit    bytes moved in one shot at send time (btl.h:799)
  max_send_size  single-segment ceiling; beyond it transfers are
                 segmented/pipelined (btl.h:802 rdma pipeline)
  latency        relative cost to start a transfer (lower = better)
  bandwidth      MB/s ranking input for rail striping (higher = better)
  exclusivity    peers reachable by a higher-exclusivity btl drop
                 lower ones from their lists (btl.h:797 analogue)
"""

from __future__ import annotations

import math
import time as _time
from typing import Any, Dict, List, Optional, Sequence, Tuple

from .. import obs as _obs
from ..mca import component as mca_component
from ..mca import pvar
from ..mca import var as mca_var
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("btl")

BTL_FRAMEWORK = mca_component.framework(
    "btl", "byte/buffer transfer layer (ompi/mca/btl analogue)"
)

_striped_moves = pvar.counter(
    "bml_striped_moves", "pipelined transfers striped across >1 rail"
)


class BtlModule:
    """One transfer path instance (the ``mca_btl_base_module_t``).

    Subclasses implement :meth:`reachable` (can this module move
    between the two endpoints?) and :meth:`move_segment` (one
    contiguous transfer). Size/ranking attributes are read through the
    MCA variable system so every one is user-tunable exactly like the
    reference's ``btl_<name>_<attr>`` parameters.
    """

    #: class defaults; instances read the MCA variables registered by
    #: the owning component (see Component.register_vars)
    NAME = "base"
    EAGER_LIMIT = 64 * 1024
    MAX_SEND_SIZE = 16 * 1024 * 1024
    LATENCY = 50
    BANDWIDTH = 1000
    EXCLUSIVITY = 0
    #: False for out-of-band transports (shm handoff) whose transfer
    #: entry points are not move_segment — the BML keeps them out of
    #: its in-band move lists so selection cannot route a device move
    #: onto a module that cannot perform one
    SUPPORTS_MOVE = True

    def _var(self, attr: str, default: int) -> int:
        return int(mca_var.get(f"btl_{self.NAME}_{attr}", default))

    @property
    def eager_limit(self) -> int:
        return self._var("eager_limit", self.EAGER_LIMIT)

    @property
    def max_send_size(self) -> int:
        return self._var("max_send_size", self.MAX_SEND_SIZE)

    @property
    def latency(self) -> int:
        return self._var("latency", self.LATENCY)

    @property
    def bandwidth(self) -> int:
        return self._var("bandwidth", self.BANDWIDTH)

    @property
    def exclusivity(self) -> int:
        return self._var("exclusivity", self.EXCLUSIVITY)

    # -- interface ---------------------------------------------------------
    def reachable(self, src_ep, dst_ep) -> bool:
        """Can this module carry src_ep -> dst_ep? (add_procs analogue)"""
        raise NotImplementedError

    def move_segment(self, data, dst_device):
        """Move one contiguous array to ``dst_device``; returns the
        moved array (a future — jax dispatch is async)."""
        raise NotImplementedError

    # -- accounting --------------------------------------------------------
    def _cached_counter(self, attr: str, name: str, doc: str):
        """Lazily-registered, instance-cached pvar (hot paths call
        .add() per chunk — no registry lookup per call)."""
        c = getattr(self, attr, None)
        if c is None:
            c = pvar.counter(name, doc)
            setattr(self, attr, c)
        return c

    @property
    def bytes_pvar(self):
        return self._cached_counter(
            "_bytes_pvar", f"btl_{self.NAME}_bytes",
            f"bytes moved through the {self.NAME} btl",
        )

    @property
    def move_hist(self):
        """Per-BTL log2 size distribution (obs plane), lazily cached
        like the byte counter."""
        h = getattr(self, "_move_hist", None)
        if h is None:
            h = pvar.histogram(
                f"btl_{self.NAME}_move_bytes",
                f"per-move payload bytes through the {self.NAME} btl, "
                "log2 buckets",
            )
            self._move_hist = h
        return h

    def move(self, data, dst_device):
        nbytes = int(data.size * data.dtype.itemsize)
        self.bytes_pvar.add(nbytes)
        if not _obs.enabled:
            return self.move_segment(data, dst_device)
        t0 = _time.perf_counter()
        out = self.move_segment(data, dst_device)
        self.move_hist.observe(nbytes)
        _obs.record(f"move[{self.NAME}]", "btl", t0,
                    _time.perf_counter() - t0, nbytes=nbytes)
        return out


def register_module_vars(mod_cls) -> None:
    """Register the standard per-module attribute variables."""
    n = mod_cls.NAME
    for attr, default, doc in (
        ("eager_limit", mod_cls.EAGER_LIMIT,
         "bytes moved in one eager shot (btl.h:799)"),
        ("max_send_size", mod_cls.MAX_SEND_SIZE,
         "single-segment ceiling; larger transfers pipeline (btl.h:802)"),
        ("latency", mod_cls.LATENCY,
         "relative transfer-start cost, lower preferred (btl.h:806)"),
        ("bandwidth", mod_cls.BANDWIDTH,
         "MB/s ranking input for rail striping (btl.h:807)"),
        ("exclusivity", mod_cls.EXCLUSIVITY,
         "peers reachable at higher exclusivity drop lower btls"),
    ):
        mca_var.register(
            f"btl_{n}_{attr}", "size" if "limit" in attr or "size" in attr
            else "int", default, f"{n}: {doc}",
        )


class BmlEndpoint:
    """Per-peer transfer plan — the ``mca_bml_base_endpoint_t`` (bml.h:71).

    Holds this (src, dst) pair's eligible modules sorted into the three
    reference lists:
      btl_eager  lowest latency first — small messages
      btl_send   lowest latency first — mid-size single-segment
      btl_rdma   highest bandwidth first — pipelined rails, striped
    """

    __slots__ = ("src_ep", "dst_ep", "dst_device", "btl_eager", "btl_send",
                 "btl_rdma")

    def __init__(self, src_ep, dst_ep, dst_device,
                 modules: Sequence[BtlModule]) -> None:
        self.src_ep = src_ep
        self.dst_ep = dst_ep
        self.dst_device = dst_device
        reach = [m for m in modules if m.reachable(src_ep, dst_ep)]
        # out-of-band transports (shm handoff) are reachable but have
        # no in-band move entry point: the move lists hold movers only
        movers = [m for m in reach if m.SUPPORTS_MOVE]
        if not movers:
            raise MPIError(
                ErrorCode.ERR_UNREACH,
                f"no btl reaches rank {dst_ep.rank} from {src_ep.rank}",
            )
        # exclusivity: keep only the highest tier (btl.h:797 — e.g. the
        # loopback btl owns self-sends outright, as btl/self does)
        top = max(m.exclusivity for m in movers)
        tier = [m for m in movers if m.exclusivity == top]
        self.btl_eager = sorted(tier, key=lambda m: (m.latency, m.NAME))
        self.btl_send = list(self.btl_eager)
        self.btl_rdma = sorted(
            tier, key=lambda m: (-m.bandwidth, m.NAME)
        )

    # -- size-driven path selection (ob1's protocol switch points) ---------
    @property
    def eager_limit(self) -> int:
        return self.btl_eager[0].eager_limit

    @property
    def max_send_size(self) -> int:
        return self.btl_send[0].max_send_size

    def move(self, data, *, max_send: Optional[int] = None,
             on_pipeline=None):
        """Move ``data`` to this peer, choosing path + segmentation by
        size exactly as ob1 chooses start_copy/start_prepare/start_rdma
        (``pml_ob1_sendreq.c:480,610,667``). ``max_send`` overrides the
        btl's segment ceiling (the pml pipeline-size knob);
        ``on_pipeline`` is invoked iff the transfer actually segments
        (so callers' counters match reality)."""
        import jax.numpy as jnp

        nbytes = int(data.size * data.dtype.itemsize)
        seg = max_send or self.btl_rdma[0].max_send_size
        if data.ndim == 0 or nbytes <= seg:
            btl = (self.btl_eager if nbytes <= self.eager_limit
                   else self.btl_send)[0]
            return btl.move(data, self.dst_device)
        # pipelined: stripe max_send-sized segments across the rdma
        # rails, share per rail proportional to bandwidth (bml r2
        # round-robin weighted striping, bml.h:229)
        if on_pipeline is not None:
            on_pipeline()
        flat = data.reshape(-1)
        seg_elems = max(1, seg // data.dtype.itemsize)
        nseg = math.ceil(flat.shape[0] / seg_elems)
        rails = self._rail_schedule(nseg)
        if len(set(rails)) > 1:
            _striped_moves.add()
        out = []
        for i in range(nseg):
            chunk = flat[i * seg_elems:(i + 1) * seg_elems]
            out.append(self.btl_rdma[rails[i]].move(chunk, self.dst_device))
        return jnp.concatenate(out).reshape(data.shape)

    def _rail_schedule(self, nseg: int) -> List[int]:
        """Assign each segment a rail index, weighted by bandwidth."""
        # clamp: bandwidth is a user-settable var; a 0 would starve the
        # rail and stall the scheduler below
        bws = [max(1, m.bandwidth) for m in self.btl_rdma]
        total = sum(bws)
        # largest-remainder apportionment, then interleave
        counts = [nseg * b // total for b in bws]
        rema = sorted(
            range(len(bws)),
            key=lambda i: -(nseg * bws[i] - counts[i] * total),
        )
        for i in rema[: nseg - sum(counts)]:
            counts[i] += 1
        sched: List[int] = []
        pending = list(counts)
        while len(sched) < nseg:
            for r in range(len(bws)):
                if pending[r] > 0:
                    sched.append(r)
                    pending[r] -= 1
        return sched

    def describe(self) -> Dict[str, Any]:
        return {
            "dst": self.dst_ep.rank,
            "eager": [m.NAME for m in self.btl_eager],
            "send": [m.NAME for m in self.btl_send],
            "rdma": [m.NAME for m in self.btl_rdma],
            "eager_limit": self.eager_limit,
            "max_send_size": self.max_send_size,
        }


class BmlR2:
    """Per-communicator BTL multiplexer (the bml/r2 component).

    Opens the btl framework, queries every component against the
    communicator (add_procs analogue) and builds per-peer endpoints
    lazily.
    """

    def __init__(self, comm) -> None:
        self.comm = comm
        # comm rank -> LOCAL device; under a unified multi-controller
        # world only this process's members have devices here — cross-
        # process pairs never get a BML endpoint (the wire pml routes
        # them through the shm/dcn staged transports instead)
        flat = list(comm.submesh.devices.reshape(-1))
        local = getattr(comm, "local_comm_ranks", None)
        if local is None:
            local = range(comm.size)
        self._devices = {r: flat[i] for i, r in enumerate(local)}
        eps = {e.rank: e for e in comm.runtime.endpoints}
        self._eps = [
            eps[comm.group.world_rank(i)] for i in range(comm.size)
        ]
        self._modules: List[BtlModule] = [
            m for _, _, m in BTL_FRAMEWORK.available(comm)
        ]
        if not self._modules:
            raise MPIError(
                ErrorCode.ERR_NOT_AVAILABLE, "no btl component available"
            )
        self._endpoints: Dict[Tuple[int, int], BmlEndpoint] = {}
        _log.verbose(
            2,
            f"{comm.name}: btl modules "
            f"{[m.NAME for m in self._modules]}",
        )

    def endpoint(self, src_rank: int, dst_rank: int) -> BmlEndpoint:
        key = (src_rank, dst_rank)
        ep = self._endpoints.get(key)
        if ep is None:
            dst_device = self._devices.get(dst_rank)
            if dst_device is None:
                raise MPIError(
                    ErrorCode.ERR_UNREACH,
                    f"rank {dst_rank} belongs to another controller "
                    "process — in-band BML moves cannot reach it; "
                    "cross-process pairs route through the wire pml",
                )
            ep = BmlEndpoint(
                self._eps[src_rank], self._eps[dst_rank],
                dst_device, self._modules,
            )
            self._endpoints[key] = ep
        return ep
