"""Point-to-point engine — the pml/bml/btl stack reimagined for TPU.

Two paths, mirroring SURVEY §2.4 item 1:

- ``spmd``: static ppermute schedules compiled into XLA programs — the
  performance path for fixed communication patterns (rings, halos,
  pipeline stages).
- ``pml``: MPI dynamic semantics — (rank, tag, comm) matching with
  wildcards, unexpected-message queue, eager/rendezvous/pipelined
  transfer scheduling — executed as host-orchestrated device-to-device
  transfers (the ``btl/tpu`` data mover).
"""

from . import pml, spmd  # noqa: F401
from .pml import (  # noqa: F401
    ANY_SOURCE, ANY_TAG, PmlEngine,
)
