"""PERUSE — event callbacks on P2P internals.

The reference's PERUSE spec (``ompi/peruse/peruse.h:24,55``) lets
tools observe request lifecycle events inside the ob1 engine:
activation, matching, transfer begin/end. Same events here, fired by
the PML at the equivalent points. Registration is per communicator and
per event; the hooks cost one dict lookup when no subscriber exists.
"""

from __future__ import annotations

import time as _time
from typing import Callable, Dict, List

from .. import obs as _obs

# event names (the PERUSE_COMM_* set that maps onto this engine)
REQ_ACTIVATE = "req_activate"        # send/recv posted
REQ_MATCH_UNEX = "req_match_unex"    # recv matched an unexpected send
MSG_UNEX_INSERT = "msg_unex_insert"  # send queued unexpected
REQ_XFER_BEGIN = "req_xfer_begin"    # payload movement started
REQ_XFER_END = "req_xfer_end"        # payload delivered
REQ_COMPLETE = "req_complete"

EVENTS = (REQ_ACTIVATE, REQ_MATCH_UNEX, MSG_UNEX_INSERT,
          REQ_XFER_BEGIN, REQ_XFER_END, REQ_COMPLETE)

_subscribers: Dict[int, Dict[str, List[Callable]]] = {}


def subscribe(comm, event: str, fn: Callable) -> None:
    """fn(event, **info) is called at each occurrence on this comm."""
    if event not in EVENTS:
        raise ValueError(f"unknown PERUSE event {event!r}")
    _subscribers.setdefault(comm.cid, {}).setdefault(event, []).append(fn)


def unsubscribe_all(comm) -> None:
    _subscribers.pop(comm.cid, None)


def fire(comm, event: str, **info) -> None:
    if _obs.enabled:
        # PERUSE and the journal are one stream: every fired event is
        # also an instant span (nbytes carries the event's element
        # count, as fired)
        dst = info.get("dst")
        _obs.record(event, "peruse", _time.perf_counter(), 0.0,
                    nbytes=int(info.get("count", 0) or 0),
                    peer=dst if isinstance(dst, int) else -1,
                    comm_id=comm.cid)
    subs = _subscribers.get(comm.cid)
    if not subs:
        return
    for fn in subs.get(event, ()):
        fn(event, **info)


def has_subscribers(comm) -> bool:
    return bool(_subscribers.get(comm.cid))
