"""Pessimistic message logging — the vprotocol/pessimist analogue.

The reference's pessimistic FT
(``ompi/mca/vprotocol/pessimist/vprotocol_pessimist.h:19-35``) keeps
two things: a sender-based payload log, and the receiver-side
*determinants* — for every nondeterministic event (a wildcard recv's
actual match) the outcome is logged so a restarted process replays the
exact same delivery order. Driver-mode recast:

* every send is recorded with its immutable payload handle (the log IS
  the sender-based payload log — jax arrays cannot be mutated under
  the logger's feet);
* every recv POSTING is recorded in order, and on completion the
  matched (source, tag) is filled in — the determinant;
* ``replay`` re-issues the whole event sequence in posting order
  against a fresh engine, with each wildcard recv pinned to its
  recorded match, so the restarted consumer sees byte-identical
  deliveries in the original order even when the first run matched
  racy ANY_SOURCE recvs.
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, Optional

from ..mca import pvar
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("vprotocol")
_logged = pvar.counter("vprotocol_logged_sends", "sends captured in the log")
_logged_recvs = pvar.counter(
    "vprotocol_logged_recvs", "recv postings captured in the log"
)


@dataclasses.dataclass
class LoggedSend:
    seq: int
    src: int
    dst: int
    tag: int
    data: Any
    sync: bool


@dataclasses.dataclass
class LoggedRecv:
    seq: int
    dst: int
    source: int          # as posted (may be ANY_SOURCE = -1)
    tag: int             # as posted (may be ANY_TAG = -1)
    matched_src: Optional[int] = None   # determinant, set on completion
    matched_tag: Optional[int] = None
    cancelled: bool = False  # MPI_Cancel'd: skipped on replay


class MessageLog:
    def __init__(self) -> None:
        self.events: List[Any] = []  # LoggedSend | LoggedRecv, in order

    # -- engine-side hooks -------------------------------------------------
    def record(self, src: int, dst: int, tag: int, data, sync: bool
               ) -> None:
        _logged.add()
        self.events.append(
            LoggedSend(len(self.events), src, dst, tag, data, sync)
        )

    def record_recv_post(self, dst: int, source: int, tag: int,
                         req) -> None:
        """Log a recv posting; the determinant (which message matched)
        is filled in when the request completes."""
        _logged_recvs.add()
        ev = LoggedRecv(len(self.events), dst, source, tag)
        self.events.append(ev)

        def on_done(r) -> None:
            if r.status.cancelled:
                # a cancelled recv consumed nothing: replaying it as
                # a live wildcard would steal a later recv's message
                ev.cancelled = True
                return
            ev.matched_src = int(r.status.source)
            ev.matched_tag = int(r.status.tag)

        req.on_complete(on_done)

    def record_matched_recv(self, dst: int, source: int, tag: int,
                            matched_src: int, matched_tag: int) -> None:
        """Log an improbe/mrecv delivery: the match decision is made
        at probe time, so the determinant is complete immediately."""
        _logged_recvs.add()
        self.events.append(LoggedRecv(
            len(self.events), dst, source, tag,
            matched_src=int(matched_src), matched_tag=int(matched_tag),
        ))

    # -- restart side ------------------------------------------------------
    def replay(self, pml) -> List[Any]:
        """Re-issue the logged event sequence in posting order on a
        fresh engine. Wildcard recvs are pinned to their recorded
        determinants, so delivery order is reproduced exactly. Returns
        the re-delivered recv payloads in original posting order (what
        the restarted consumer consumes)."""
        reqs = []
        for ev in self.events:
            if isinstance(ev, LoggedSend):
                pml.isend(ev.data, ev.dst, ev.tag, src=ev.src, sync=False)
            else:
                if ev.cancelled:
                    continue  # consumed nothing; nothing to replay
                if ev.matched_src is None:
                    raise MPIError(
                        ErrorCode.ERR_PENDING,
                        f"recv event {ev.seq} has no determinant: the "
                        "original recv never completed — drain before "
                        "checkpointing the log",
                    )
                # the determinant replaces the wildcard: the fresh
                # engine MUST match the same message
                reqs.append(pml.irecv(
                    ev.matched_src, ev.matched_tag, dst=ev.dst
                ))
        values = []
        for r in reqs:
            r.wait()
            values.append(r.value)
        return values

    def clear(self) -> None:
        self.events.clear()


def attach(comm) -> MessageLog:
    """Enable pessimistic send+recv logging on this comm's PML."""
    log = MessageLog()
    comm.pml._logger = log
    return log


def detach(comm) -> None:
    pml = getattr(comm, "_pml", None)
    if pml is not None:
        pml._logger = None
