"""Static P2P schedules — compiled ppermute data plane.

The TPU-native form of a fixed send/recv pattern is a permutation
compiled into the surrounding XLA program (the reference's isend/irecv
schedule in ``coll_tuned_util.c:50-59`` becomes one ppermute); use
these inside shard_map. The host PML (``pml.py``) is for dynamic
patterns only.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import jax
from jax import lax


def sendrecv(x: jax.Array, perm: Sequence[Tuple[int, int]],
             axis_name: str) -> jax.Array:
    """MPI_Sendrecv over a static pattern: each (src, dst) pair is one
    edge; ranks not receiving get zeros (ppermute semantics)."""
    return lax.ppermute(x, axis_name, list(perm))


def ring_shift(x: jax.Array, axis_name: str, shift: int = 1) -> jax.Array:
    """Rotate values around the ring by ``shift`` (ring_c.c pattern)."""
    n = lax.psum(1, axis_name)
    return lax.ppermute(
        x, axis_name, [(i, (i + shift) % n) for i in range(n)]
    )


def halo_exchange(x: jax.Array, axis_name: str) -> Tuple[jax.Array, jax.Array]:
    """Neighbor exchange: returns (from_left, from_right) for a 1-D
    non-periodic decomposition; boundary ranks receive zeros."""
    n = lax.psum(1, axis_name)
    from_left = lax.ppermute(
        x, axis_name, [(i, i + 1) for i in range(n - 1)]
    )
    from_right = lax.ppermute(
        x, axis_name, [(i + 1, i) for i in range(n - 1)]
    )
    return from_left, from_right
