"""Cartesian / graph / dist-graph topologies + neighborhood collectives.

The reference's ``topo/basic`` component (``ompi/mca/topo``, SURVEY
§2.3) provides rank<->coordinate math and neighbor queries attached to
a communicator; neighborhood collectives live in coll. On TPU the cart
topology is doubly load-bearing: laying a cart communicator onto the
mesh in device order keeps grid neighbors physically adjacent on the
ICI torus, and the static neighbor lists compile into single ppermute
programs (one per direction) for the neighborhood collectives.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

import jax.numpy as jnp

from ..runtime.mesh import factorize_torus
from ..utils.errors import ErrorCode, MPIError


def dims_create(nnodes: int, ndims: int,
                dims: Optional[Sequence[int]] = None) -> Tuple[int, ...]:
    """MPI_Dims_create: fill zero entries of ``dims`` with a balanced
    factorization."""
    if dims is None or not any(dims):
        return factorize_torus(nnodes, ndims)
    dims = list(dims)
    fixed = int(np.prod([d for d in dims if d > 0])) if any(
        d > 0 for d in dims
    ) else 1
    if nnodes % fixed:
        raise MPIError(
            ErrorCode.ERR_DIMS,
            f"cannot fill dims {dims} for {nnodes} nodes",
        )
    free = [i for i, d in enumerate(dims) if d <= 0]
    if not free:
        if fixed != nnodes:
            raise MPIError(
                ErrorCode.ERR_DIMS,
                f"fully-specified dims {dims} have product {fixed} != "
                f"{nnodes} nodes",
            )
        return tuple(dims)
    fills = factorize_torus(nnodes // fixed, len(free))
    for i, f in zip(free, fills):
        dims[i] = f
    return tuple(dims)



class _NonblockingNeighborsMixin:
    """ineighbor_* (libnbc's nbc_ineighbor_* analogue): XLA dispatch
    is asynchronous, so the compiled schedule's results are futures
    wrapped in a Request — the same contract as comm.iallreduce.
    Mixed into every topology class (each provides the blocking
    neighbor_* pair and a ``comm``)."""

    def ineighbor_allgather(self, x):
        return self.comm._async(self.neighbor_allgather(x))

    def ineighbor_alltoall(self, x):
        return self.comm._async(self.neighbor_alltoall(x))


class CartTopo(_NonblockingNeighborsMixin):
    """Cartesian topology attached to a communicator."""

    def __init__(self, comm, dims: Sequence[int],
                 periods: Sequence[bool]) -> None:
        self.comm = comm
        self.dims = tuple(int(d) for d in dims)
        self.periods = tuple(bool(p) for p in periods)
        if int(np.prod(self.dims)) != comm.size:
            raise MPIError(
                ErrorCode.ERR_DIMS,
                f"cart dims {self.dims} != comm size {comm.size}",
            )

    @property
    def ndims(self) -> int:
        return len(self.dims)

    def coords(self, rank: int) -> Tuple[int, ...]:
        """MPI_Cart_coords (row-major, like the reference)."""
        c = []
        for d in reversed(self.dims):
            c.append(rank % d)
            rank //= d
        return tuple(reversed(c))

    def rank(self, coords: Sequence[int]) -> int:
        """MPI_Cart_rank; periodic dims wrap, others must be in range."""
        r = 0
        for d, p, c in zip(self.dims, self.periods, coords):
            if p:
                c %= d
            elif not 0 <= c < d:
                return -1  # MPI_PROC_NULL
            r = r * d + c
        return r

    def shift(self, dim: int, disp: int, rank: int) -> Tuple[int, int]:
        """MPI_Cart_shift -> (source, dest); -1 = MPI_PROC_NULL."""
        c = list(self.coords(rank))
        cd = list(c)
        cd[dim] += disp
        cs = list(c)
        cs[dim] -= disp
        return self.rank(cs), self.rank(cd)

    def _neighbor_at(self, rank: int, dim: int, delta: int) -> int:
        c = list(self.coords(rank))
        c[dim] += delta
        return self.rank(c)

    def neighbors(self, rank: int) -> List[int]:
        """Neighborhood order per MPI: for each dim, -1 then +1."""
        return [
            self._neighbor_at(rank, dim, delta)
            for dim in range(self.ndims)
            for delta in (-1, 1)
        ]

    def sub(self, remain_dims: Sequence[bool]):
        """MPI_Cart_sub: partition into sub-grids over the kept dims.
        Driver mode: returns the per-rank list of (subcomm, subtopo)."""
        keep = [i for i, k in enumerate(remain_dims) if k]
        drop = [i for i, k in enumerate(remain_dims) if not k]
        colors = []
        for r in range(self.comm.size):
            c = self.coords(r)
            color = 0
            for i in drop:
                color = color * self.dims[i] + c[i]
            colors.append(color)
        subs = self.comm.split(colors)
        sub_dims = tuple(self.dims[i] for i in keep)
        sub_periods = tuple(self.periods[i] for i in keep)
        out = []
        seen: Dict[int, CartTopo] = {}
        for r, sc in enumerate(subs):
            if sc is None:
                out.append(None)
                continue
            if sc.cid not in seen:
                topo = CartTopo(sc, sub_dims, sub_periods)
                sc.topo = topo
                seen[sc.cid] = topo
            out.append((sc, seen[sc.cid]))
        return out

    # -- neighborhood collectives (static ppermute programs) --------------
    def neighbor_perms(self) -> List[List[Tuple[int, int]]]:
        """One static (src, dst) edge list per neighbor slot, in the
        MPI neighbor order — each compiles to one ppermute."""
        perms: List[List[Tuple[int, int]]] = []
        for dim in range(self.ndims):
            for delta in (-1, 1):
                edges = []
                for r in range(self.comm.size):
                    nbr = self._neighbor_at(r, dim, delta)
                    if nbr >= 0:
                        edges.append((nbr, r))
                perms.append(edges)
        return perms

    def neighbor_allgather(self, x):
        """MPI_Neighbor_allgather, driver mode: x has a leading rank
        axis; returns (size, n_neighbors, ...) — slot order matches
        ``neighbors()``; missing neighbors (non-periodic edge) yield
        zeros."""
        from jax import lax

        from ..coll.driver import run_sharded

        perms = self.neighbor_perms()

        def body(xb):
            outs = [
                lax.ppermute(xb, "rank", p) for p in perms
            ]
            return jnp.stack(outs, axis=0)

        return run_sharded(
            self.comm, ("topo", "neighbor_allgather", len(perms)), body, x
        )

    def neighbor_alltoall(self, x):
        """MPI_Neighbor_alltoall: x is (size, n_neighbors, ...) — block
        j goes to neighbor slot j; received blocks keep slot order."""
        from jax import lax

        from ..coll.driver import run_sharded

        perms = self.neighbor_perms()
        nn = len(perms)
        if x.shape[1] != nn:
            raise MPIError(
                ErrorCode.ERR_COUNT,
                f"neighbor_alltoall needs {nn} blocks per rank",
            )
        # slot j (dim, disp) sends to the OPPOSITE slot at the neighbor:
        # what I send "left" arrives at my left neighbor's "right" slot
        def body(xb):
            outs = []
            for j, p in enumerate(perms):
                opp = j ^ 1  # (-1 <-> +1) within the same dim
                send = xb[opp]
                outs.append(lax.ppermute(send, "rank", p))
            return jnp.stack(outs, axis=0)

        return run_sharded(
            self.comm, ("topo", "neighbor_alltoall", nn), body, x
        )


# ---------------------------------------------------------------------------
# ragged neighborhoods (graph / dist-graph): static ppermute rounds
# ---------------------------------------------------------------------------
#
# A cart topology's neighbor slots are uniform, so each slot is one
# ppermute. Graph/dist-graph adjacency is ragged: the edge set is
# greedily edge-colored into ROUNDS where every rank sends at most one
# block and receives at most one block — each round is then a legal
# (partial) permutation, and the whole collective compiles to one
# program of len(rounds) static ppermutes with constant slot tables
# (the libnbc round-schedule idea, nbc_ineighbor_allgather.c, with the
# schedule baked into the XLA program instead of replayed by a
# progress engine).


class _NeighborSchedule:
    """Edge-colored schedule for one (in_neighbors, out_neighbors)."""

    def __init__(self, in_neighbors: List[List[int]],
                 out_neighbors: List[List[int]]) -> None:
        n = len(in_neighbors)
        self.n = n
        self.in_neighbors = [list(x) for x in in_neighbors]
        self.out_neighbors = [list(x) for x in out_neighbors]
        self.max_in = max((len(x) for x in in_neighbors), default=0)
        self.max_out = max((len(x) for x in out_neighbors), default=0)

        # edge list with slot indices matched by occurrence order
        # (duplicate edges pair up first-to-first, MPI buffer order)
        out_cursor: Dict[Tuple[int, int], int] = {}
        edges = []  # (src, dst, send_slot, recv_slot)
        for dst in range(n):
            for recv_slot, src in enumerate(self.in_neighbors[dst]):
                k = out_cursor.get((src, dst), 0)
                # find the (k+1)-th occurrence of dst in src's out list
                seen = -1
                send_slot = -1
                for j, d in enumerate(self.out_neighbors[src]):
                    if d == dst:
                        seen += 1
                        if seen == k:
                            send_slot = j
                            break
                if send_slot < 0:
                    raise MPIError(
                        ErrorCode.ERR_TOPOLOGY,
                        f"edge {src}->{dst} in rank {dst}'s sources has "
                        f"no matching entry in rank {src}'s destinations",
                    )
                out_cursor[(src, dst)] = k + 1
                edges.append((src, dst, send_slot, recv_slot))
        for src in range(n):
            for dst in self.out_neighbors[src]:
                if out_cursor.get((src, dst), 0) != \
                        self.out_neighbors[src].count(dst) or \
                        self.in_neighbors[dst].count(src) != \
                        self.out_neighbors[src].count(dst):
                    raise MPIError(
                        ErrorCode.ERR_TOPOLOGY,
                        f"edge {src}->{dst} in destinations has no "
                        "matching entry in the target's sources",
                    )

        # greedy edge coloring: each round is a partial permutation
        self.rounds: List[List[Tuple[int, int]]] = []
        self.send_slots: List[List[int]] = []  # per round: [n] (-1 none)
        self.recv_slots: List[List[int]] = []
        remaining = edges
        while remaining:
            used_src, used_dst = set(), set()
            this, rest = [], []
            for e in remaining:
                if e[0] not in used_src and e[1] not in used_dst:
                    this.append(e)
                    used_src.add(e[0])
                    used_dst.add(e[1])
                else:
                    rest.append(e)
            self.rounds.append([(e[0], e[1]) for e in this])
            ss = [-1] * n
            rs = [-1] * n
            for src, dst, send_slot, recv_slot in this:
                ss[src] = send_slot
                rs[dst] = recv_slot
            self.send_slots.append(ss)
            self.recv_slots.append(rs)
            remaining = rest

    def key(self) -> Tuple:
        return (
            tuple(tuple(x) for x in self.in_neighbors),
            tuple(tuple(x) for x in self.out_neighbors),
        )


def _neighbor_allgather_ragged(comm, sched: _NeighborSchedule, x):
    """Each rank's single block delivered to all its out-neighbors;
    rank r receives into slot i the block from in_neighbors[r][i].
    Returns (size, max_in, ...) with zeros in unused slots."""
    from jax import lax

    from ..coll.driver import run_sharded

    max_in = max(sched.max_in, 1)
    recv_tables = np.asarray(sched.recv_slots, np.int32)  # (rounds, n)
    rounds = sched.rounds

    def body(xb):
        rank = lax.axis_index("rank")
        out = jnp.zeros((max_in,) + xb.shape, xb.dtype)
        for i, perm in enumerate(rounds):
            recv = lax.ppermute(xb, "rank", perm)
            slot = jnp.asarray(recv_tables[i])[rank]
            onehot = (
                jnp.arange(max_in) == slot
            ).reshape((max_in,) + (1,) * xb.ndim)
            out = jnp.where(onehot, recv[None], out)
        return out

    return run_sharded(
        comm, ("topo", "graph_neighbor_allgather", sched.key()), body, x
    )


def _neighbor_alltoall_ragged(comm, sched: _NeighborSchedule, x):
    """x: (size, max_out, ...) — rank r's block j goes to
    out_neighbors[r][j]. Returns (size, max_in, ...)."""
    from jax import lax

    from ..coll.driver import run_sharded

    max_in = max(sched.max_in, 1)
    max_out = max(sched.max_out, 1)
    if getattr(x, "ndim", 0) < 2 or x.shape[1] != max_out:
        raise MPIError(
            ErrorCode.ERR_COUNT,
            f"neighbor_alltoall needs (size, {max_out}, ...) — "
            f"{max_out} send blocks per rank (max out-degree), got "
            f"shape {getattr(x, 'shape', None)}",
        )
    send_tables = np.asarray(sched.send_slots, np.int32)
    recv_tables = np.asarray(sched.recv_slots, np.int32)
    rounds = sched.rounds

    def body(xb):  # xb: (max_out, ...)
        rank = lax.axis_index("rank")
        out = jnp.zeros((max_in,) + xb.shape[1:], xb.dtype)
        for i, perm in enumerate(rounds):
            sslot = jnp.asarray(send_tables[i])[rank]
            send = jnp.take(xb, jnp.maximum(sslot, 0), axis=0)
            recv = lax.ppermute(send, "rank", perm)
            rslot = jnp.asarray(recv_tables[i])[rank]
            onehot = (
                jnp.arange(max_in) == rslot
            ).reshape((max_in,) + (1,) * (xb.ndim - 1))
            out = jnp.where(onehot, recv[None], out)
        return out

    return run_sharded(
        comm, ("topo", "graph_neighbor_alltoall", sched.key()), body, x
    )


class GraphTopo(_NonblockingNeighborsMixin):
    """MPI_Graph_create analogue (index/edges arrays) WITH neighborhood
    collectives over the ragged adjacency (the reference supports
    neighborhood collectives on all three topology kinds,
    ``nbc_ineighbor_allgather.c``)."""

    def __init__(self, comm, index: Sequence[int],
                 edges: Sequence[int]) -> None:
        self.comm = comm
        self.index = tuple(index)
        self.edges = tuple(edges)
        if len(index) != comm.size:
            raise MPIError(
                ErrorCode.ERR_TOPOLOGY,
                f"graph index length {len(index)} != comm size",
            )
        self._sched: Optional[_NeighborSchedule] = None

    def neighbors(self, rank: int) -> List[int]:
        lo = self.index[rank - 1] if rank else 0
        return list(self.edges[lo:self.index[rank]])

    @property
    def max_degree(self) -> int:
        return self._schedule().max_in

    def _schedule(self) -> _NeighborSchedule:
        # MPI graph neighborhoods send to and receive from the same
        # neighbor list (the graph must be symmetric for the
        # collectives to be well-defined — validated by the schedule's
        # edge matching)
        if self._sched is None:
            adj = [self.neighbors(r) for r in range(self.comm.size)]
            self._sched = _NeighborSchedule(adj, adj)
        return self._sched

    def neighbor_allgather(self, x):
        """Driver mode: x (size, ...) -> (size, max_degree, ...);
        rank r's slot i holds the block from neighbors(r)[i]."""
        return _neighbor_allgather_ragged(self.comm, self._schedule(), x)

    def neighbor_alltoall(self, x):
        """x (size, max_degree, ...): rank r's block j goes to
        neighbors(r)[j]; slot i of the result came from
        neighbors(r)[i]."""
        return _neighbor_alltoall_ragged(self.comm, self._schedule(), x)



class DistGraphTopo(_NonblockingNeighborsMixin):
    """MPI_Dist_graph_create_adjacent analogue (driver mode: per-rank
    adjacency, one controller playing every rank) with neighborhood
    collectives over the directed ragged edge set."""

    def __init__(self, comm, sources: Sequence[Sequence[int]],
                 destinations: Sequence[Sequence[int]]) -> None:
        self.comm = comm
        if len(sources) != comm.size or len(destinations) != comm.size:
            raise MPIError(
                ErrorCode.ERR_TOPOLOGY,
                "dist graph needs per-rank sources/destinations lists "
                f"of length {comm.size}",
            )
        self.sources = tuple(tuple(int(s) for s in x) for x in sources)
        self.destinations = tuple(
            tuple(int(d) for d in x) for x in destinations
        )
        # validates that every source entry has a matching destination
        self._sched = _NeighborSchedule(
            [list(x) for x in self.sources],
            [list(x) for x in self.destinations],
        )

    def in_neighbors(self, rank: int) -> List[int]:
        return list(self.sources[rank])

    def out_neighbors(self, rank: int) -> List[int]:
        return list(self.destinations[rank])

    @property
    def max_in_degree(self) -> int:
        return self._sched.max_in

    @property
    def max_out_degree(self) -> int:
        return self._sched.max_out

    def neighbor_allgather(self, x):
        """x (size, ...) -> (size, max_in_degree, ...): rank r's slot
        i holds the block from sources[r][i]."""
        return _neighbor_allgather_ragged(self.comm, self._sched, x)

    def neighbor_alltoall(self, x):
        """x (size, max_out_degree, ...): rank r's block j goes to
        destinations[r][j]; result slot i came from sources[r][i]."""
        return _neighbor_alltoall_ragged(self.comm, self._sched, x)



def cart_create(comm, dims: Sequence[int],
                periods: Optional[Sequence[bool]] = None,
                reorder: bool = True):
    """MPI_Cart_create: dup the comm, attach a cart topology.

    ``reorder=True`` keeps device order (ranks stay mesh-contiguous so
    grid neighbors sit on adjacent ICI links — on TPU reordering INTO
    device order is always the right answer).
    """
    dims = dims_create(comm.size, len(dims), dims)
    if periods is None:
        periods = [False] * len(dims)
    c = comm.dup(name=f"cart{tuple(dims)}")
    topo = CartTopo(c, dims, periods)
    c.topo = topo
    return c, topo


def graph_create(comm, index: Sequence[int], edges: Sequence[int]):
    c = comm.dup(name="graph")
    topo = GraphTopo(c, index, edges)
    c.topo = topo
    return c, topo


def dist_graph_create_adjacent(comm, sources: Sequence[Sequence[int]],
                               destinations: Sequence[Sequence[int]]):
    c = comm.dup(name="dist_graph")
    topo = DistGraphTopo(c, sources, destinations)
    c.topo = topo
    return c, topo
