"""Communicator topologies — the ``ompi/mca/topo`` analogue."""

from .topo import (  # noqa: F401
    CartTopo, GraphTopo, DistGraphTopo, cart_create, graph_create,
    dist_graph_create_adjacent, dims_create,
)
