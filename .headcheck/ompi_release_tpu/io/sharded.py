"""Sharded array save/load — the ompio/fcoll two-phase path, TPU form.

The reference's ompio decomposes MPI-IO into fs (open/close), fbtl
(individual read/write), fcoll (collective two-phase aggregation:
``fcoll/two_phase``) and sharedfp. The TPU-native equivalent of
two-phase collective I/O is tensorstore-style sharded array storage
(SURVEY §2.4 item 11): each rank's block is written as its own object
in parallel (phase 1 = the data is ALREADY aggregated per device;
phase 2 = N concurrent contiguous writes), with a manifest describing
shard layout for reassembly. Writes run on a thread pool so device
compute overlaps file I/O (async checkpoint requirement of §5).
"""

from __future__ import annotations

import gzip
import io as _io
import json
import os
import threading
import time as _time
import zlib
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Any, Dict, List, Optional

import numpy as np

import jax

from .. import obs as _obs
from ..mca import pvar
from ..mca import var as mca_var
from ..utils import output
from ..utils.errors import ErrorCode, MPIError

_log = output.stream("io")
_bytes_written = pvar.counter("io_bytes_written", "sharded-IO bytes written")
_bytes_read = pvar.counter("io_bytes_read", "sharded-IO bytes read")

_pool: Optional[ThreadPoolExecutor] = None
_pool_lock = threading.Lock()


def register_vars() -> None:
    mca_var.register(
        "io_num_aggregators", "int", 8,
        "Concurrent shard writers (fcoll two_phase aggregator count)",
    )
    mca_var.register(
        "io_compress", "enum", "none",
        "Shard compression (opal/mca/compress analogue)",
        choices=("none", "gzip"),
    )
    mca_var.register(
        "io_checksum", "bool", True,
        "CRC32 per shard, verified on load (opal datatype-checksum "
        "analogue: catches storage corruption)",
    )
    mca_var.register(
        "io_target_shard_bytes", "size", 64 * 1024 * 1024,
        "Target bytes per shard for flat-layout saves (pytree leaves): "
        "a leaf splits into ceil(nbytes/target) contiguous chunks",
    )


register_vars()  # idempotent; io vars must exist before any save/load
# reads them (an unregistered var silently reads as its default)


def _executor() -> ThreadPoolExecutor:
    global _pool
    with _pool_lock:
        if _pool is None:
            _pool = ThreadPoolExecutor(
                max_workers=int(mca_var.get("io_num_aggregators", 8)),
                thread_name_prefix="ompitpu-io",
            )
        return _pool


def save_sharded(path: str, x, *, name: str = "array",
                 async_: bool = False, layout: str = "axis0",
                 num_shards: Optional[int] = None):
    """Write an array as N .npy shards + a manifest.

    layout="axis0": one shard per leading-axis slice (driver-mode rank
    axis — each rank's block is its own object). layout="flat": the
    array is flattened and split into ``num_shards`` contiguous chunks
    (default: ceil(nbytes / io_target_shard_bytes)) — the right layout
    for model parameters, where axis 0 (e.g. a 32k vocab) would
    otherwise produce one tiny file per row.

    Device shards are pulled per-shard so at most one shard is
    host-resident at a time. Returns a Future list when ``async_``
    (wait with ``[f.result() for f in futs]``), else writes
    synchronously.
    """
    os.makedirs(path, exist_ok=True)
    if layout == "flat":
        nbytes = int(x.size) * np.dtype(
            "float32" if str(x.dtype) == "bfloat16" else x.dtype
        ).itemsize
        if num_shards is None:
            target = int(mca_var.get("io_target_shard_bytes",
                                     64 * 1024 * 1024))
            num_shards = max(1, -(-nbytes // max(1, target)))
        n = min(int(num_shards), max(1, int(x.size)))
        bounds = np.linspace(0, int(x.size), n + 1).astype(np.int64)
    elif layout == "axis0":
        n = int(x.shape[0])
        bounds = None
    else:
        raise MPIError(ErrorCode.ERR_ARG, f"unknown layout {layout!r}")
    compress = str(mca_var.get("io_compress", "none"))
    checksum = bool(mca_var.get("io_checksum", True))
    manifest = {
        "name": name,
        "dtype": str(np.dtype(x.dtype) if str(x.dtype) != "bfloat16"
                     else "bfloat16"),
        "shape": list(x.shape),
        "num_shards": n,
        "compress": compress,
        "layout": layout,
        "version": 3,
    }
    crcs: List[Optional[int]] = [None] * n
    if layout == "flat":
        xflat = x.reshape(-1)

    def write_one(i: int) -> int:
        rec = _obs.enabled  # capture once: flag may flip mid-write
        t0 = _time.perf_counter() if rec else 0.0
        src = (xflat[bounds[i]:bounds[i + 1]] if layout == "flat"
               else x[i])
        block = np.asarray(
            src if str(x.dtype) != "bfloat16" else src.astype("float32")
        )
        buf = _io.BytesIO()
        np.save(buf, block)
        raw = buf.getvalue()
        if checksum:
            crcs[i] = zlib.crc32(raw)
        fn = os.path.join(path, f"{name}.shard{i:05d}.npy")
        opener = gzip.open if compress == "gzip" else open
        with opener(fn, "wb") as f:
            f.write(raw)
        _bytes_written.add(block.nbytes)
        if rec:  # per-shard write incl. device pull + disk
            _obs.record("shard_write", "io", t0,
                        _time.perf_counter() - t0, nbytes=block.nbytes,
                        peer=i)
        return block.nbytes

    ex = _executor()
    futs = [ex.submit(write_one, i) for i in range(n)]

    def finish() -> None:
        if checksum:
            manifest["crc32"] = crcs
        with open(os.path.join(path, f"{name}.manifest.json"), "w") as f:
            json.dump(manifest, f)

    if async_:
        writers = list(futs)

        def wait_then_finish() -> int:
            # FIFO pool: writers were submitted first, so this task
            # only runs after a worker frees up — no self-deadlock
            for f in writers:
                f.result()
            finish()
            return 0

        futs.append(ex.submit(wait_then_finish))
        return futs
    for f in futs:
        f.result()
    finish()
    return None


def load_sharded(path: str, *, name: str = "array"):
    """Reassemble a sharded array (parallel shard reads)."""
    mf = os.path.join(path, f"{name}.manifest.json")
    if not os.path.exists(mf):
        raise MPIError(ErrorCode.ERR_FILE, f"no manifest at {mf}")
    with open(mf) as f:
        manifest = json.load(f)
    n = manifest["num_shards"]
    compress = manifest.get("compress", "none")
    crcs = manifest.get("crc32")

    def read_one(i: int) -> np.ndarray:
        rec = _obs.enabled
        t0 = _time.perf_counter() if rec else 0.0
        fn = os.path.join(path, f"{manifest['name']}.shard{i:05d}.npy")
        opener = gzip.open if compress == "gzip" else open
        with opener(fn, "rb") as f:
            raw = f.read()
        if crcs is not None and crcs[i] is not None:
            got = zlib.crc32(raw)
            if got != crcs[i]:
                raise MPIError(
                    ErrorCode.ERR_IO,
                    f"checksum mismatch on {fn}: stored {crcs[i]:#x}, "
                    f"read {got:#x} (corrupt shard)",
                )
        block = np.load(_io.BytesIO(raw))
        _bytes_read.add(block.nbytes)
        if rec:
            _obs.record("shard_read", "io", t0,
                        _time.perf_counter() - t0, nbytes=block.nbytes,
                        peer=i)
        return block

    ex = _executor()
    blocks = list(ex.map(read_one, range(n)))
    if manifest.get("layout", "axis0") == "flat":
        out = np.concatenate([b.reshape(-1) for b in blocks]).reshape(
            manifest["shape"]
        )
    else:
        out = np.stack(blocks, axis=0)
    if manifest["dtype"] == "bfloat16":
        import jax.numpy as jnp

        return jnp.asarray(out, jnp.bfloat16)
    return out.astype(manifest["dtype"])


def save_pytree(path: str, tree: Any, *, async_: bool = False):
    """Save a pytree of arrays (one sharded entry per leaf)."""
    os.makedirs(path, exist_ok=True)
    leaves, treedef = jax.tree.flatten(tree)
    meta = {"treedef": str(treedef), "num_leaves": len(leaves),
            "version": 1}
    with open(os.path.join(path, "pytree.json"), "w") as f:
        json.dump(meta, f)
    futs: List[Future] = []
    for i, leaf in enumerate(leaves):
        import jax.numpy as jnp

        arr = jnp.asarray(leaf)
        if arr.ndim == 0:
            arr = arr[None]
        # flat layout: shard count scales with leaf BYTES, not axis 0 —
        # a (32000, d) embed table must not become 32000 row files
        r = save_sharded(path, arr, name=f"leaf{i:04d}", async_=async_,
                         layout="flat")
        if r:
            futs.extend(r)
    return futs if async_ else None


def load_pytree(path: str, like: Any) -> Any:
    """Load a pytree saved by save_pytree; ``like`` supplies the tree
    structure (and scalar-ness) to restore into."""
    leaves, treedef = jax.tree.flatten(like)
    out = []
    for i, leaf in enumerate(leaves):
        arr = load_sharded(path, name=f"leaf{i:04d}")
        import jax.numpy as jnp

        a = jnp.asarray(arr)
        if getattr(leaf, "ndim", 0) == 0 and a.ndim == 1:
            a = a[0]
        out.append(a.astype(leaf.dtype) if hasattr(leaf, "dtype") else a)
    return jax.tree.unflatten(treedef, out)
