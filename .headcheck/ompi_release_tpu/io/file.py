"""MPI_File API over host files — the ompio surface.

The surface of ``ompi/mca/io`` (open/close/read_at/write_at/
read_all/write_all/shared pointer/set_view) with ompio's component
split honored in miniature: fs = python file open/close per rank
handle, fbtl = individual pread/pwrite at explicit offsets, fcoll =
collective write_all/read_all where every rank's block lands at its
view offset (the two-phase exchange is unnecessary when each "rank"
writes a disjoint contiguous extent — the driver already holds the
aggregated blocks), sharedfp = an ordered shared file pointer.

Views: ``set_view(disp, etype, filetype)`` accepts a full
:class:`~..datatype.datatype.Datatype` filetype WITH holes
(``io/romio`` file views; the fcoll/two_phase case exists because
interleaved views from different ranks tile the same extents — here
each rank's strided runs are written/read directly per contiguous
run). Nonblocking ops (``iwrite_at``/``iread_at``/``iwrite_at_all``/
``iread_at_all``) run on a per-file thread pool and return Requests
(``MPI_File_iwrite_at`` family; ompio drives these through libnbc's
progress — here the pool thread is the progress engine and the
Request's completion is the future's).
"""

from __future__ import annotations

import os
import threading
from concurrent.futures import Future, ThreadPoolExecutor
from typing import Optional, Tuple

import numpy as np

from ..request.request import Request, Status
from ..utils.errors import ErrorCode, MPIError

MODE_RDONLY = os.O_RDONLY
MODE_WRONLY = os.O_WRONLY
MODE_RDWR = os.O_RDWR
MODE_CREATE = os.O_CREAT


class File:
    """MPI_File analogue bound to a communicator."""

    def __init__(self, comm, path: str,
                 mode: int = MODE_RDWR | MODE_CREATE) -> None:
        self.comm = comm
        self.path = path
        try:
            self._fd = os.open(path, mode, 0o644)
        except OSError as e:
            raise MPIError(ErrorCode.ERR_FILE, f"open {path}: {e}")
        self._lock = threading.Lock()
        self._shared_ptr = 0  # sharedfp analogue
        # view: (displacement bytes, elementary dtype, filetype)
        self._disp = 0
        self._etype = np.dtype(np.uint8)
        self._filetype = None
        self._ft_runs: Optional[np.ndarray] = None  # (start, len) pairs
        self._ft_size = 0    # visible elements per tile
        self._ft_extent = 0  # tile extent in etype elements
        self._closed = False
        self._pool: Optional[ThreadPoolExecutor] = None

    # -- view (MPI_File_set_view) -----------------------------------------
    def set_view(self, disp: int = 0, etype=np.uint8,
                 filetype=None) -> None:
        """Install the view: from ``disp`` bytes on, the file is a
        tiling of ``filetype`` (a :class:`Datatype`, possibly with
        holes); only the filetype's data regions are addressable and
        element offsets count VISIBLE etype elements (the ROMIO view
        contract). ``filetype=None`` = contiguous etype stream."""
        self._disp = int(disp)
        self._etype = np.dtype(etype)
        self._filetype = filetype
        if filetype is None:
            self._ft_runs = None
            return
        offs = np.asarray(filetype.offsets(1), dtype=np.int64)
        if offs.size == 0:
            raise MPIError(ErrorCode.ERR_TYPE,
                           "filetype has no data elements")
        base_size = getattr(filetype, "base_dtype", None)
        if base_size is not None and \
                np.dtype(base_size).itemsize != self._etype.itemsize:
            raise MPIError(
                ErrorCode.ERR_TYPE,
                f"filetype base ({np.dtype(base_size)}) and etype "
                f"({self._etype}) sizes differ — MPI requires the "
                "filetype be constructed from the etype",
            )
        # contiguous runs within one tile: (start_elem, run_len)
        runs = []
        start = prev = int(offs[0])
        for o in offs[1:]:
            o = int(o)
            if o == prev + 1:
                prev = o
                continue
            runs.append((start, prev - start + 1))
            start = prev = o
        runs.append((start, prev - start + 1))
        self._ft_runs = np.asarray(runs, dtype=np.int64)
        self._ft_size = int(offs.size)
        self._ft_extent = int(filetype.get_extent())

    def _byte_offset(self, offset_elems: int) -> int:
        return self._disp + offset_elems * self._etype.itemsize

    def _view_ranges(self, start_elem: int, count: int):
        """Yield (byte_offset, elem_count) contiguous file runs for
        ``count`` visible elements from view position ``start_elem``
        (identity when no filetype is installed)."""
        if self._ft_runs is None:
            yield self._byte_offset(start_elem), count
            return
        pos = start_elem
        remaining = count
        while remaining > 0:
            tile, idx = divmod(pos, self._ft_size)
            # find the run containing visible index idx
            seen = 0
            for rstart, rlen in self._ft_runs:
                if idx < seen + rlen:
                    within = idx - seen
                    take = min(int(rlen) - within, remaining)
                    file_elem = (tile * self._ft_extent + int(rstart)
                                 + within)
                    yield self._byte_offset(file_elem), take
                    pos += take
                    remaining -= take
                    break
                seen += int(rlen)

    def _check(self) -> None:
        if self._closed:
            raise MPIError(ErrorCode.ERR_FILE, f"{self.path} closed")

    # -- individual (fbtl) -------------------------------------------------
    def write_at(self, offset: int, data) -> int:
        """pwrite at a visible-element offset in the current view
        (with a holey filetype this scatters per contiguous run)."""
        self._check()
        buf = np.ascontiguousarray(np.asarray(data, self._etype)
                                   ).reshape(-1)
        isz = self._etype.itemsize
        raw = buf.tobytes()
        done = 0
        written = 0
        for byte_off, n_elems in self._view_ranges(offset, buf.size):
            written += os.pwrite(
                self._fd, raw[done * isz:(done + n_elems) * isz],
                byte_off,
            )
            done += n_elems
        return written // isz

    def read_at(self, offset: int, count: int) -> np.ndarray:
        self._check()
        isz = self._etype.itemsize
        parts = []
        for byte_off, n_elems in self._view_ranges(offset, count):
            raw = os.pread(self._fd, n_elems * isz, byte_off)
            parts.append(np.frombuffer(raw, self._etype))
            if len(raw) < n_elems * isz:
                break  # EOF inside a run: later runs are past it too
        if not parts:
            return np.empty(0, self._etype)
        return (parts[0].copy() if len(parts) == 1
                else np.concatenate(parts))

    # -- collective (fcoll) ------------------------------------------------
    def write_at_all(self, offsets, blocks) -> int:
        """Collective write: rank i's block at element offset i
        (driver mode: per-rank lists). Disjoint contiguous extents per
        rank = the post-aggregation phase of fcoll/two_phase. The
        per-rank pwrites are issued concurrently (os.pwrite releases
        the GIL), matching the aggregators-write-in-parallel phase.

        On a communicator spanning controller processes the lists
        carry one entry per LOCAL member and the real two-phase
        exchange runs over the wire (io/two_phase.py)."""
        self._check()
        if getattr(self.comm, "spans_processes", False):
            from . import two_phase

            # through the comm's one collective worker: the exchange
            # shares the comm's wire channel with every other
            # collective, so posting order must be execution order
            return self.comm._run_serialized(
                two_phase.write_at_all, self, offsets, blocks)
        if len(offsets) != self.comm.size or len(blocks) != self.comm.size:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"need {self.comm.size} offsets/blocks (one per rank)",
            )
        with ThreadPoolExecutor(
            max_workers=min(self.comm.size, 16)
        ) as pool:
            total = sum(pool.map(
                lambda ob: self.write_at(ob[0], ob[1]),
                zip(offsets, blocks),
            ))
        self.comm.barrier()
        return total

    def read_at_all(self, offsets, counts):
        self._check()
        if getattr(self.comm, "spans_processes", False):
            from . import two_phase

            return self.comm._run_serialized(
                two_phase.read_at_all, self, offsets, counts)
        if len(offsets) != self.comm.size or len(counts) != self.comm.size:
            raise MPIError(
                ErrorCode.ERR_ARG,
                f"need {self.comm.size} offsets/counts (one per rank)",
            )
        with ThreadPoolExecutor(
            max_workers=min(self.comm.size, 16)
        ) as pool:
            out = list(pool.map(
                lambda oc: self.read_at(oc[0], oc[1]),
                zip(offsets, counts),
            ))
        self.comm.barrier()
        return out

    # -- nonblocking (MPI_File_iwrite_at family) ---------------------------
    def _io_pool(self) -> ThreadPoolExecutor:
        with self._lock:  # two first-op threads must share ONE pool
            if self._pool is None:
                self._pool = ThreadPoolExecutor(
                    max_workers=4,
                    thread_name_prefix=f"io-{os.path.basename(self.path)}",
                )
            return self._pool

    @staticmethod
    def _future_request(fut: Future) -> Request:
        """The generic future wrapper plus IO's element-count Status
        (``MPI_Get_count`` on a file request)."""
        from ..request.request import from_future

        req = from_future(fut)

        def _count(r: Request) -> None:
            v = r.value
            r.status.count = (int(v) if isinstance(v, int)
                              else int(getattr(v, "size", 0)))

        req.on_complete(_count)
        return req

    def iwrite_at(self, offset: int, data) -> Request:
        """Nonblocking write_at: returns a Request whose value is the
        element count written."""
        self._check()
        buf = np.ascontiguousarray(np.asarray(data, self._etype))
        return self._future_request(
            self._io_pool().submit(self.write_at, offset, buf)
        )

    def iread_at(self, offset: int, count: int) -> Request:
        """Nonblocking read_at: the Request's value is the array."""
        self._check()
        return self._future_request(
            self._io_pool().submit(self.read_at, offset, count)
        )

    def iwrite_at_all(self, offsets, blocks) -> Request:
        """Nonblocking collective write (MPI_File_iwrite_at_all): the
        whole fcoll exchange runs on the pool thread; collective
        ordering across the communicator is the caller's duty, as in
        MPI. On a spanning comm it submits straight to the comm's ONE
        collective worker (the 4-worker io pool would reorder two
        outstanding collectives between posting and execution)."""
        self._check()
        blocks = [np.ascontiguousarray(np.asarray(b, self._etype))
                  for b in blocks]
        if getattr(self.comm, "spans_processes", False):
            from . import two_phase

            return self.comm._submit_serialized(
                two_phase.write_at_all, self, offsets, blocks)
        return self._future_request(
            self._io_pool().submit(self.write_at_all, offsets, blocks)
        )

    def iread_at_all(self, offsets, counts) -> Request:
        self._check()
        if getattr(self.comm, "spans_processes", False):
            from . import two_phase

            return self.comm._submit_serialized(
                two_phase.read_at_all, self, offsets, counts)
        return self._future_request(
            self._io_pool().submit(self.read_at_all, offsets, counts)
        )

    # -- shared file pointer (sharedfp) ------------------------------------
    def write_ordered(self, blocks) -> None:
        """Rank-ordered append at the shared pointer (sharedfp
        'ordered' semantics)."""
        self._check()
        with self._lock:
            for blk in blocks:
                buf = np.ascontiguousarray(np.asarray(blk, self._etype))
                os.pwrite(self._fd, buf.tobytes(),
                          self._byte_offset(self._shared_ptr))
                self._shared_ptr += buf.size

    def write_shared(self, data) -> int:
        """Append one buffer at the shared pointer (sharedfp
        non-ordered write: first-come placement) — one rank's
        write_ordered, sharing the placement logic."""
        buf = np.asarray(data, self._etype)
        self.write_ordered([buf])
        return int(buf.size)  # not a pointer diff: races with other
        #                       shared-pointer writers would misreport

    def read_shared(self, count: int) -> np.ndarray:
        self._check()
        with self._lock:
            out = self.read_at(self._shared_ptr, count)
            self._shared_ptr += count
        return out

    # -- admin -------------------------------------------------------------
    def size(self) -> int:
        self._check()
        return os.fstat(self._fd).st_size

    def preallocate(self, nbytes: int) -> None:
        self._check()
        os.ftruncate(self._fd, nbytes)

    def sync(self) -> None:
        self._check()
        os.fsync(self._fd)

    def close(self) -> None:
        if not self._closed:
            if self._pool is not None:
                # MPI_File_close completes outstanding nonblocking ops
                self._pool.shutdown(wait=True)
                self._pool = None
            os.close(self._fd)
            self._closed = True

    @staticmethod
    def delete(path: str) -> None:
        try:
            os.unlink(path)
        except FileNotFoundError:
            pass

    def __enter__(self) -> "File":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
