"""Parallel I/O — the MPI-IO (romio/ompio) analogue."""

from .file import File, MODE_RDONLY, MODE_WRONLY, MODE_RDWR, MODE_CREATE
from .sharded import (  # noqa: F401
    save_sharded, load_sharded, save_pytree, load_pytree,
)

__all__ = [
    "File", "MODE_RDONLY", "MODE_WRONLY", "MODE_RDWR", "MODE_CREATE",
    "save_sharded", "load_sharded", "save_pytree", "load_pytree",
]
