"""Cross-process collective IO — real two-phase exchange-then-write.

The reference's ``fcoll/two_phase``
(``ompi/mca/fcoll/two_phase/fcoll_two_phase_file_write_all.c``)
partitions the file's touched range into contiguous *file domains*,
one per aggregator; every rank ships the pieces of its blocks that
fall in aggregator p's domain to p, and each aggregator writes its
domain with large coalesced IOs. Under the unified ``tpurun`` world
the single-controller fast path (``io/file.py``: the driver already
holds every block) no longer applies — each process holds only its
LOCAL members' blocks — so this module does the actual exchange over
the wire's per-communicator collective channels:

  phase 0  allgather the global (offset, count) table (every process
           learns the touched range and every rank's extent);
  phase 1  split local blocks by file domain; linear exchange — one
           segment-table message + one data message per peer (the
           hier coll discipline: all sends land before any recv
           parks);
  phase 2  each aggregator coalesces its domain's segments (sorted,
           adjacent runs merged) and writes them through the view
           (``File.write_at`` maps visible elements to file bytes,
           holes included).

Reads run the phases in reverse: aggregators read their domain's
segments and ship them back to the requesting member's process.

Offsets/counts are VISIBLE-element positions in the current view, so
interleaved holey views from different processes tile the same file
extents exactly as ``io/romio``'s aggregated case.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from ..runtime.wire import ProcTopology as _Topology
from ..runtime.wire import proc_topology as _topology
from ..utils.errors import ErrorCode, MPIError


def _global_table(comm, topo: _Topology, offsets, counts) -> np.ndarray:
    """(n, 2) int64 rows of (offset, count) per comm rank, exchanged as
    raw numpy over the wire channel (the hier allgather's jnp path
    cannot carry int64 with x64 off, and file element offsets must not
    truncate at 2^31)."""
    local = np.asarray(
        [[int(o), int(c)] for o, c in zip(offsets, counts)], np.int64
    ).reshape(topo.local_n, 2)
    for p in topo.peers:
        topo.router.coll_send(comm, p, local)
    rows: Dict[int, np.ndarray] = {topo.my_pidx: local}
    for p in topo.peers:
        rows[p] = np.asarray(topo.router.coll_recv(comm, p))
    table = np.zeros((comm.size, 2), np.int64)
    for p in topo.procs:
        for pos, r in enumerate(topo.members_of[p]):
            table[r] = rows[p][pos]
    return table


def _domains(table: np.ndarray, procs: List[int]
             ) -> Dict[int, Tuple[int, int]]:
    """Contiguous per-aggregator file domains covering the touched
    visible-element range (two_phase's fd_start/fd_end)."""
    active = table[table[:, 1] > 0]
    if active.size == 0:
        return {p: (0, 0) for p in procs}
    lo = int(active[:, 0].min())
    hi = int((active[:, 0] + active[:, 1]).max())
    span = hi - lo
    k = len(procs)
    chunk = -(-span // k) if span else 0
    return {
        p: (lo + i * chunk, min(lo + (i + 1) * chunk, hi))
        for i, p in enumerate(procs)
    }


def _split_segments(topo: _Topology, doms: Dict[int, Tuple[int, int]],
                    offsets, blocks, etype) -> Dict[int, list]:
    """Cut each local member's block by file domain:
    {aggregator: [(seg_offset, seg_array)]}."""
    segs: Dict[int, list] = {p: [] for p in topo.procs}
    for o, blk in zip(offsets, blocks):
        o = int(o)
        arr = np.ascontiguousarray(np.asarray(blk, etype)).reshape(-1)
        for p, (dlo, dhi) in doms.items():
            s = max(o, dlo)
            e = min(o + arr.size, dhi)
            if s < e:
                segs[p].append((s, arr[s - o:e - o]))
    return segs


def _pack_segs(segs: list, etype) -> Tuple[np.ndarray, np.ndarray]:
    """(m, 2) int64 table of (offset, len) + concatenated data."""
    table = np.asarray([[s, a.size] for s, a in segs],
                       np.int64).reshape(len(segs), 2)
    data = (np.concatenate([a for _, a in segs])
            if segs else np.empty(0, etype))
    return table, data


def _coalesce(segs: list) -> list:
    """Sort by offset and merge adjacent runs (two_phase writes each
    domain with few large IOs, not one per incoming piece). Overlaps
    (undefined in MPI) resolve last-writer-wins via apply order."""
    if not segs:
        return []
    segs = sorted(segs, key=lambda s: s[0])
    out = [segs[0]]
    for o, a in segs[1:]:
        po, pa = out[-1]
        if o == po + pa.size:
            out[-1] = (po, np.concatenate([pa, a]))
        else:
            out.append((o, a))
    return out


def write_at_all(file, offsets, blocks) -> int:
    """Spanning-comm MPI_File_write_at_all: ``offsets``/``blocks``
    carry one entry per LOCAL member (visible-element offsets in the
    current view). Returns the GLOBAL element count written."""
    comm = file.comm
    topo = _topology(comm)
    if len(offsets) != topo.local_n or len(blocks) != topo.local_n:
        raise MPIError(
            ErrorCode.ERR_ARG,
            f"spanning write_at_all needs one offset/block per LOCAL "
            f"member ({topo.local_n}), got {len(offsets)}/{len(blocks)}",
        )
    etype = file._etype
    arrs = [np.ascontiguousarray(np.asarray(b, etype)).reshape(-1)
            for b in blocks]
    table = _global_table(comm, topo, offsets,
                          [a.size for a in arrs])
    doms = _domains(table, topo.procs)
    segs = _split_segments(topo, doms, offsets, arrs, etype)

    # linear exchange: segment table + data to every peer, then
    # receive the same pair from each (all sends first — deadlock-free
    # for the linear pattern, as in coll/hier._exchange)
    for p in topo.peers:
        t, d = _pack_segs(segs[p], etype)
        topo.router.coll_send(comm, p, t)
        topo.router.coll_send(comm, p, d)
    mine = list(segs[topo.my_pidx])
    for p in topo.peers:
        t = np.asarray(topo.router.coll_recv(comm, p))
        d = np.asarray(topo.router.coll_recv(comm, p)).astype(
            etype, copy=False)
        off = 0
        for s, ln in t.reshape(-1, 2):
            mine.append((int(s), d[off:off + int(ln)]))
            off += int(ln)

    for o, a in _coalesce(mine):
        file.write_at(o, a)
    comm.barrier()  # collective completion (fcoll's end-of-phase sync)
    return int(table[:, 1].sum())


def read_at_all(file, offsets, counts) -> List[np.ndarray]:
    """Spanning-comm MPI_File_read_at_all: aggregators read their
    file domain once and ship each member's pieces back. Returns one
    array per LOCAL member."""
    comm = file.comm
    topo = _topology(comm)
    if len(offsets) != topo.local_n or len(counts) != topo.local_n:
        raise MPIError(
            ErrorCode.ERR_ARG,
            f"spanning read_at_all needs one offset/count per LOCAL "
            f"member ({topo.local_n}), got {len(offsets)}/{len(counts)}",
        )
    etype = file._etype
    counts = [int(c) for c in counts]
    table = _global_table(comm, topo, offsets, counts)
    doms = _domains(table, topo.procs)

    # which segments each process wants from each aggregator (derived
    # from the global table — no request messages needed; both sides
    # compute the identical plan, the two_phase offset-list exchange
    # collapsed into shared arithmetic)
    def wanted(proc: int) -> Dict[int, list]:
        """{aggregator: [(member_pos, seg_offset, seg_len)]} for
        ``proc``'s members, in deterministic order."""
        want: Dict[int, list] = {p: [] for p in topo.procs}
        for pos, r in enumerate(topo.members_of[proc]):
            o, c = int(table[r, 0]), int(table[r, 1])
            for p, (dlo, dhi) in doms.items():
                s = max(o, dlo)
                e = min(o + c, dhi)
                if s < e:
                    want[p].append((pos, s, e - s))
        return want

    # read ONLY the wanted extents of my domain (merged where they
    # overlap/touch): a sparse request pattern must not amplify into
    # reading the whole contiguous domain span
    import bisect

    spans = sorted(
        (s, ln) for p in topo.procs
        for _, s, ln in wanted(p)[topo.my_pidx]
    )
    runs: List[list] = []
    for s, ln in spans:
        if runs and s <= runs[-1][0] + runs[-1][1]:
            runs[-1][1] = max(runs[-1][1], s + ln - runs[-1][0])
        else:
            runs.append([s, ln])
    run_data: Dict[int, np.ndarray] = {}
    for s, ln in runs:
        arr = np.asarray(file.read_at(s, ln))
        if arr.size < ln:
            raise MPIError(
                ErrorCode.ERR_FILE,
                f"read_at_all: file ends inside requested extent "
                f"[{s}, {s + ln}) ({arr.size} of {ln} elements)",
            )
        run_data[s] = arr
    run_starts = [s for s, _ in runs]

    def piece(s: int, ln: int) -> np.ndarray:
        rs = run_starts[bisect.bisect_right(run_starts, s) - 1]
        return run_data[rs][s - rs:s - rs + ln]

    # serve every peer's pieces from my domain (deterministic order),
    # then collect my members' pieces from each aggregator
    for p in topo.peers:
        pieces = [piece(s, ln) for _, s, ln in wanted(p)[topo.my_pidx]]
        topo.router.coll_send(
            comm, p,
            np.concatenate(pieces) if pieces else np.empty(0, etype),
        )
    my_want = wanted(topo.my_pidx)
    out = [np.empty(c, etype) for c in counts]
    for pos, s, ln in my_want[topo.my_pidx]:  # my own domain's pieces
        o = int(table[topo.local_ranks[pos], 0])
        out[pos][s - o:s - o + ln] = piece(s, ln)
    for p in topo.peers:
        d = np.asarray(topo.router.coll_recv(comm, p)).astype(
            etype, copy=False)
        off = 0
        for pos, s, ln in my_want[p]:
            o = int(table[topo.local_ranks[pos], 0])
            out[pos][s - o:s - o + ln] = d[off:off + ln]
            off += ln
    comm.barrier()
    return [np.asarray(a) for a in out]
