"""P2P engine tests: matching semantics + the ring_c acceptance test.

``examples/ring_c.c:19-61`` is BASELINE.json config #1: rank 0 seeds a
counter, each rank passes it to (rank+1)%n, rank 0 decrements per lap,
everyone forwards until it reaches 0.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import ompi_release_tpu as mpi
from ompi_release_tpu.p2p import ANY_SOURCE, ANY_TAG
from ompi_release_tpu.p2p import spmd as p2p_spmd
from ompi_release_tpu import request as req_mod
from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.utils.errors import MPIError


@pytest.fixture(scope="module")
def world():
    yield mpi.init()


class TestRing:
    def test_ring_c_parity(self, world):
        """Driver-mode replay of examples/ring_c.c with 4 virtual ranks:
        every rank loops recv-from-prev / forward-to-next (rank 0
        decrements per lap), exits after forwarding a 0, and rank 0
        drains the final 0 off the ring."""
        n = 4
        sub = world.create(world.group.incl(list(range(n))), name="ring4")
        laps = 3
        sub.send(np.int32(laps), dest=1, tag=201, rank=0)  # rank 0 seeds
        done = [False] * n
        recvs = 0
        for _ in range(10 * n * (laps + 2)):  # bounded: fail, don't hang
            if all(done):
                break
            for r in range(n):
                if done[r]:
                    continue
                if sub.iprobe(source=(r - 1) % n, tag=201, rank=r) is None:
                    continue
                value, _ = sub.recv(source=(r - 1) % n, tag=201, rank=r)
                recvs += 1
                value = int(value)
                if r == 0:
                    value -= 1
                sub.send(np.int32(value), dest=(r + 1) % n, tag=201, rank=r)
                if value == 0:
                    done[r] = True
        assert all(done), f"ring stalled: done={done}"
        # the final 0 circles back to rank 0 (ring_c's trailing recv)
        v, _ = sub.recv(source=n - 1, tag=201, rank=0)
        recvs += 1
        assert int(v) == 0
        assert recvs == n * (laps + 1)
        sub.free()

    def test_spmd_ring_shift(self, world):
        """The compiled path: ring_c's pattern as one XLA program."""
        from jax.sharding import Mesh, PartitionSpec as P

        n = world.size
        mesh = world.submesh
        x = np.arange(n, dtype=np.int32)

        out = jax.jit(
            jax.shard_map(
                lambda b: p2p_spmd.ring_shift(b, "rank", 1),
                mesh=mesh, in_specs=P("rank"), out_specs=P("rank"),
            )
        )(x)
        np.testing.assert_array_equal(np.asarray(out), np.roll(x, 1))


class TestMatching:
    def test_send_before_recv_unexpected_queue(self, world):
        world.send(np.float32(1.5), dest=2, tag=7, rank=0)
        v, st = world.recv(source=0, tag=7, rank=2)
        assert float(v) == 1.5
        assert st.source == 0 and st.tag == 7

    def test_recv_before_send(self, world):
        r = world.irecv(source=3, tag=9, rank=1)
        assert not r.is_complete
        world.send(np.arange(4.0), dest=1, tag=9, rank=3)
        st = r.wait()
        np.testing.assert_array_equal(np.asarray(r.value), np.arange(4.0))
        assert st.count == 4

    def test_any_source_any_tag(self, world):
        world.send(np.int32(42), dest=5, tag=33, rank=4)
        v, st = world.recv(source=ANY_SOURCE, tag=ANY_TAG, rank=5)
        assert int(v) == 42 and st.source == 4 and st.tag == 33

    def test_mpi_ordering_same_src_tag(self, world):
        """Two sends same (src, tag): must arrive in order."""
        world.send(np.int32(1), dest=6, tag=1, rank=0)
        world.send(np.int32(2), dest=6, tag=1, rank=0)
        a, _ = world.recv(source=0, tag=1, rank=6)
        b, _ = world.recv(source=0, tag=1, rank=6)
        assert (int(a), int(b)) == (1, 2)

    def test_tag_selectivity(self, world):
        world.send(np.int32(10), dest=7, tag=100, rank=1)
        world.send(np.int32(20), dest=7, tag=200, rank=1)
        v, _ = world.recv(source=1, tag=200, rank=7)
        assert int(v) == 20  # skipped over the tag-100 message
        v, _ = world.recv(source=1, tag=100, rank=7)
        assert int(v) == 10

    def test_probe_does_not_consume(self, world):
        world.send(np.int32(5), dest=3, tag=55, rank=2)
        st1 = world.iprobe(source=2, tag=55, rank=3)
        st2 = world.iprobe(source=2, tag=55, rank=3)
        assert st1 is not None and st2 is not None and st1.count == 1
        v, _ = world.recv(source=2, tag=55, rank=3)
        assert int(v) == 5
        assert world.iprobe(source=2, tag=55, rank=3) is None

    def test_bad_rank_raises(self, world):
        with pytest.raises(MPIError):
            world.send(np.int32(0), dest=world.size + 3, rank=0)


class TestProtocols:
    def test_eager_completes_before_match(self, world):
        req = world.isend(np.zeros(8, np.float32), dest=1, tag=71, rank=0)
        assert req.is_complete  # under eager limit: sender done at once
        world.recv(source=0, tag=71, rank=1)

    def test_rendezvous_defers_completion(self, world):
        mca_var.set_value("pml_eager_limit", 16)
        try:
            req = world.isend(np.zeros(100, np.float32), dest=1, tag=72,
                              rank=0)
            assert not req.is_complete  # rendezvous: waits for the recv
            v, _ = world.recv(source=0, tag=72, rank=1)
            assert req.is_complete
            assert np.asarray(v).shape == (100,)
        finally:
            mca_var.VARS.unset("pml_eager_limit")

    def test_pipelined_large_message_content(self, world):
        mca_var.set_value("pml_max_send_size", 256)  # force segmentation
        try:
            data = np.random.RandomState(0).randn(1000).astype(np.float32)
            world.send(data, dest=2, tag=73, rank=1)
            v, _ = world.recv(source=1, tag=73, rank=2)
            np.testing.assert_array_equal(np.asarray(v), data)
            from ompi_release_tpu.mca import pvar

            assert pvar.PVARS.lookup("pml_pipelined_sends").read() > 0
        finally:
            mca_var.VARS.unset("pml_max_send_size")

    def test_ssend_completes_only_on_match(self, world):
        req = world.isend(np.int32(1), dest=4, tag=74, rank=3, sync=True)
        assert not req.is_complete
        world.recv(source=3, tag=74, rank=4)
        assert req.is_complete

    def test_rsend_requires_posted_recv(self, world):
        with pytest.raises(MPIError):
            world.isend(np.int32(1), dest=5, tag=75, rank=4, ready=True)
        r = world.irecv(source=4, tag=76, rank=5)
        world.isend(np.int32(9), dest=5, tag=76, rank=4, ready=True)
        assert int(r.value) == 9

    def test_sendrecv(self, world):
        # everyone rotates a value to rank+1 via one vectorized sendrecv
        n = world.size
        values, statuses = world.sendrecv(
            [np.int32(r) for r in range(n)],
            [(r + 1) % n for r in range(n)],
            sendtag=77,
            sources=[(r - 1) % n for r in range(n)],
            recvtag=77,
        )
        assert [int(v) for v in values] == [(r - 1) % n for r in range(n)]
        assert [s.source for s in statuses] == [
            (r - 1) % n for r in range(n)
        ]


class TestRequests:
    def test_waitall_testall(self, world):
        rs = [world.irecv(source=0, tag=80 + i, rank=1) for i in range(3)]
        done, _ = req_mod.test_all(rs)
        assert not done
        for i in range(3):
            world.send(np.int32(i), dest=1, tag=80 + i, rank=0)
        sts = req_mod.wait_all(rs)
        assert [int(r.value) for r in rs] == [0, 1, 2]
        assert [s.tag for s in sts] == [80, 81, 82]

    def test_waitany(self, world):
        rs = [world.irecv(source=0, tag=90 + i, rank=2) for i in range(2)]
        world.send(np.int32(7), dest=2, tag=91, rank=0)
        i, st = req_mod.wait_any(rs)
        assert i == 1 and int(rs[1].value) == 7
        world.send(np.int32(8), dest=2, tag=90, rank=0)
        rs[0].wait()

    def test_persistent_requests(self, world):
        sreq = world.pml.send_init(np.int32(3), 1, tag=95, src=0)
        rreq = world.pml.recv_init(source=0, tag=95, dst=1)
        for _ in range(3):
            rreq.start()
            sreq.start()
            st = rreq.wait()
            assert int(rreq.value) == 3 and st.source == 0

    def test_wait_without_match_raises_not_hangs(self, world):
        r = world.irecv(source=0, tag=999, rank=3)
        with pytest.raises(MPIError):
            r.wait()

    def test_cancel_completes_and_does_not_consume(self, world):
        """MPI_Cancel: the request completes with cancelled status, and
        a cancelled recv must NOT swallow a later matching send."""
        r = world.irecv(source=0, tag=500, rank=1)
        r.cancel()
        st = r.wait()  # must succeed, not raise
        assert st.cancelled and r.is_cancelled
        # the message goes to a real recv, not the cancelled one
        world.send(np.int32(77), dest=1, tag=500, rank=0)
        v, st2 = world.recv(source=0, tag=500, rank=1)
        assert int(v) == 77 and not st2.cancelled

    def test_wait_any_prefers_blockable_request(self, world):
        from ompi_release_tpu import ops

        dead = world.irecv(source=0, tag=501, rank=2)  # never matched
        live = world.iallreduce(
            np.ones((world.size, 16), np.float32), ops.SUM
        )
        i, st = req_mod.wait_any([dead, live])
        assert i == 1
        dead.cancel()

    def test_dp_bucket_bytes_var_is_live(self, world):
        from jax.sharding import PartitionSpec as P

        from ompi_release_tpu.parallel import dp as dp_mod

        mca_var.set_value("dp_bucket_bytes", 8)  # 2 f32 per bucket
        try:
            g = {"a": np.ones((world.size, 3), np.float32),
                 "b": np.ones((world.size, 5), np.float32)}
            out = jax.jit(
                jax.shard_map(
                    lambda t: dp_mod.allreduce_gradients(t, "rank",
                                                         mean=False),
                    mesh=world.submesh, in_specs=(P("rank"),),
                    out_specs=P("rank"),
                )
            )(g)
            for k in g:
                np.testing.assert_allclose(
                    np.asarray(out[k])[0], g[k].sum(0), rtol=1e-6
                )
        finally:
            mca_var.VARS.unset("dp_bucket_bytes")

    def test_generalized_request(self, world):
        from ompi_release_tpu.request.request import (
            GeneralizedRequest, Status,
        )

        q = GeneralizedRequest(
            query_fn=lambda s: Status(count=s["n"]), extra_state={"n": 4}
        )
        assert not q.is_complete
        q.complete()
        assert q.wait().count == 4


class TestNonblockingCollectives:
    def test_iallreduce(self, world):
        from ompi_release_tpu import ops

        x = np.random.RandomState(5).randn(world.size, 64).astype(np.float32)
        req = world.iallreduce(x, ops.SUM)
        st = req.wait()
        np.testing.assert_allclose(
            np.asarray(req.value)[0], x.sum(0), rtol=2e-5, atol=1e-5
        )

    def test_ibcast_ibarrier_waitall(self, world):
        x = np.random.RandomState(6).randn(world.size, 8).astype(np.float32)
        r1 = world.ibcast(x, root=2)
        r2 = world.ibarrier()
        req_mod.wait_all([r1, r2])
        np.testing.assert_array_equal(np.asarray(r1.value)[0], x[2])

    def test_overlap_compute_with_collective(self, world):
        """The point of nonblocking: dispatch, compute, then wait."""
        from ompi_release_tpu import ops

        x = np.ones((world.size, 1 << 16), np.float32)
        req = world.iallreduce(x, ops.SUM)
        local = np.arange(10).sum()  # overlapped host work
        req.wait()
        assert local == 45
        assert req.is_complete


class TestVprotocolPessimist:
    """Pessimistic message logging (vprotocol_pessimist.h:19-35):
    sender payload log + receiver determinants, consumer restart."""

    def test_consumer_restart_replays_wildcard_order(self, world):
        """The core pessimist property: the original run matches
        WILDCARD recvs (nondeterministic under racy senders); the
        restarted consumer must see byte-identical deliveries in the
        same order, reproduced by pinning each recv to its logged
        determinant."""
        from ompi_release_tpu.p2p import vprotocol

        sub = world.create(world.group.incl([0, 1, 2, 3]), name="vp")
        log = vprotocol.attach(sub)

        # three producers (ranks 1-3) send two rounds to the consumer
        # (rank 0) on ONE tag; consumer drains with wildcard recvs
        payloads = {}
        for rnd in range(2):
            for src in (1, 2, 3):
                data = np.full(4, 10 * src + rnd, np.float32)
                payloads[(src, rnd)] = data
                sub.isend(data, dest=0, tag=5, rank=src)
        original = []
        determinants = []
        for _ in range(6):
            v, st = sub.recv(source=-1, tag=5, rank=0)
            original.append(np.asarray(v))
            determinants.append(st.source)
        assert len(log.events) == 12  # 6 sends + 6 recv postings

        # "restart": a FRESH engine (new comm dup => new pml), replay
        vprotocol.detach(sub)
        fresh = sub.dup(name="vp_restarted")
        redelivered = log.replay(fresh.pml)
        assert len(redelivered) == 6
        for a, b in zip(original, redelivered):
            np.testing.assert_array_equal(a, np.asarray(b))
        fresh.free()
        sub.free()

    def test_replay_without_determinant_raises(self, world):
        from ompi_release_tpu.p2p import vprotocol

        sub = world.create(world.group.incl([0, 1]), name="vp2")
        log = vprotocol.attach(sub)
        sub.irecv(source=-1, tag=9, rank=0)  # never completes
        fresh = sub.dup(name="vp2_restart")
        with pytest.raises(MPIError):
            log.replay(fresh.pml)
        vprotocol.detach(sub)
        fresh.free()
        sub.free()

    def test_cancelled_recv_not_replayed(self, world):
        """A cancelled recv consumed nothing; replaying it as a live
        wildcard would steal a later recv's message."""
        from ompi_release_tpu.p2p import vprotocol

        sub = world.create(world.group.incl([0, 1]), name="vp3")
        log = vprotocol.attach(sub)
        r = sub.irecv(source=-1, tag=3, rank=0)
        r.cancel()
        data = np.arange(3, dtype=np.float32)
        sub.isend(data, dest=0, tag=3, rank=1)
        v, _ = sub.recv(source=-1, tag=3, rank=0)
        vprotocol.detach(sub)
        fresh = sub.dup(name="vp3_restart")
        redelivered = log.replay(fresh.pml)
        assert len(redelivered) == 1  # the cancelled posting is skipped
        np.testing.assert_array_equal(np.asarray(redelivered[0]), data)
        fresh.free()
        sub.free()

    def test_mprobe_delivery_logged(self, world):
        """improbe+mrecv is the nondeterministic match event: the log
        must capture it or restart silently diverges."""
        from ompi_release_tpu.p2p import vprotocol

        sub = world.create(world.group.incl([0, 1]), name="vp4")
        log = vprotocol.attach(sub)
        data = np.arange(5, dtype=np.float32) * 2
        sub.isend(data, dest=0, tag=6, rank=1)
        msg = sub.pml.improbe(source=-1, tag=6, dst=0)
        assert msg is not None
        v, _ = sub.pml.mrecv(msg, dst=0)
        np.testing.assert_array_equal(np.asarray(v), data)
        vprotocol.detach(sub)
        fresh = sub.dup(name="vp4_restart")
        redelivered = log.replay(fresh.pml)
        assert len(redelivered) == 1
        np.testing.assert_array_equal(np.asarray(redelivered[0]), data)
        fresh.free()
        sub.free()
