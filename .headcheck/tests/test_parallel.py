"""Parallelism-strategy tests on the virtual 8-device CPU mesh.

Every strategy is validated against its single-device dense reference
(the test style of SURVEY §4: simulator-backend multi-"device" runs
checked for exact/close parity).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from ompi_release_tpu.parallel import cp, dp, ep, pp, sp, tp, zero
from ompi_release_tpu.parallel.mesh_axes import build_parallel_mesh


def mesh1d(n, name):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def smap(fn, mesh, in_specs, out_specs):
    return jax.jit(
        jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_vma=False)
    )


# -- tp ---------------------------------------------------------------------

class TestTensorParallel:
    def test_column_row_pair_matches_dense(self):
        n = 4
        mesh = mesh1d(n, "tp")
        rng = np.random.RandomState(0)
        x = rng.randn(6, 16).astype(np.float32)
        w1 = rng.randn(16, 32).astype(np.float32)
        w2 = rng.randn(32, 16).astype(np.float32)
        b2 = rng.randn(16).astype(np.float32)

        def body(x, w1s, w2s, b2):
            h = tp.column_parallel(x, w1s, axis_name="tp")
            h = jax.nn.relu(h)
            return tp.row_parallel(h, w2s, b2, axis_name="tp")

        out = smap(
            body, mesh,
            (P(), P(None, "tp"), P("tp", None), P()),
            P(),
        )(x, w1, w2, b2)
        ref = np.maximum(x @ w1, 0) @ w2 + b2
        np.testing.assert_allclose(np.asarray(out), ref, rtol=2e-5, atol=2e-5)

    def test_column_gather_output(self):
        n = 4
        mesh = mesh1d(n, "tp")
        x = np.random.RandomState(1).randn(3, 8).astype(np.float32)
        w = np.random.RandomState(2).randn(8, 12).astype(np.float32)
        out = smap(
            lambda x, w: tp.column_parallel(
                x, w, axis_name="tp", gather_output=True
            ),
            mesh, (P(), P(None, "tp")), P(),
        )(x, w)
        np.testing.assert_allclose(np.asarray(out), x @ w, rtol=2e-5,
                                   atol=2e-5)

    def test_vocab_parallel_embedding(self):
        n = 4
        mesh = mesh1d(n, "tp")
        table = np.random.RandomState(3).randn(20, 8).astype(np.float32)
        ids = np.array([0, 5, 7, 19, 12], np.int32)
        out = smap(
            lambda i, t: tp.vocab_parallel_embedding(i, t, axis_name="tp"),
            mesh, (P(), P("tp", None)), P(),
        )(ids, table)
        np.testing.assert_allclose(np.asarray(out), table[ids], rtol=1e-6)

    def test_vocab_parallel_xent_matches_dense(self):
        n = 4
        mesh = mesh1d(n, "tp")
        rng = np.random.RandomState(4)
        h = rng.randn(5, 8).astype(np.float32)
        table = rng.randn(16, 8).astype(np.float32)
        tgt = np.array([1, 15, 7, 0, 9], np.int32)
        out = smap(
            lambda h, t, y: tp.vocab_parallel_xent(h, t, y, axis_name="tp"),
            mesh, (P(), P("tp", None), P()), P(),
        )(h, table, tgt)
        logits = h @ table.T
        ref = (np.log(np.exp(logits - logits.max(-1, keepdims=True))
                      .sum(-1)) + logits.max(-1)
               - logits[np.arange(5), tgt])
        np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-5, atol=1e-5)


# -- dp / zero --------------------------------------------------------------

class TestDataParallel:
    def test_bucketed_allreduce_matches_psum(self):
        n = 8
        mesh = mesh1d(n, "dp")
        rng = np.random.RandomState(5)
        grads = {
            "a": rng.randn(n, 3, 4).astype(np.float32),
            "b": rng.randn(n, 7).astype(np.float32),
            "c": rng.randn(n, 2000).astype(np.float32),  # > bucket
            "d": rng.randn(n, 5).astype(np.int32).astype(np.float32),
        }
        out = smap(
            lambda g: dp.allreduce_gradients(
                g, "dp", mean=True, bucket_bytes=64
            ),
            mesh, (P("dp"),), P("dp"),
        )({k: v for k, v in grads.items()})
        for k in grads:
            ref = np.broadcast_to(
                grads[k].mean(0, keepdims=True), grads[k].shape
            )
            np.testing.assert_allclose(np.asarray(out[k]), ref, rtol=1e-5,
                                       atol=1e-5)

    def test_replicate_check_detects_divergence(self):
        n = 4
        mesh = mesh1d(n, "dp")
        same = np.ones((n, 3), np.float32)
        div = same.copy()
        div[2] += 0.5
        f = smap(lambda x: dp.replicate_check(x, "dp")[None],
                 mesh, (P("dp"),), P("dp"))
        assert np.asarray(f(same)).max() == 0
        assert np.asarray(f(div)).max() == pytest.approx(0.5)


class TestZero:
    def test_shard_unshard_roundtrip(self):
        n = 4
        mesh = mesh1d(n, "dp")
        rng = np.random.RandomState(6)
        p = rng.randn(3, 5).astype(np.float32)  # 15 elems: pad path

        def body(p):
            shard = zero.shard_like(p, "dp")
            return zero.unshard_params(shard, p.shape, "dp")

        out = smap(body, mesh, (P(),), P())(p)
        np.testing.assert_allclose(np.asarray(out), p, rtol=1e-6)

    def test_zero_sgd_step_matches_dense_sgd(self):
        n = 4
        mesh = mesh1d(n, "dp")
        rng = np.random.RandomState(7)
        params = {"w": rng.randn(6, 3).astype(np.float32)}
        # per-replica grads differ; dense ref uses their mean
        grads = rng.randn(n, 6, 3).astype(np.float32)
        lr = 0.1

        def opt_update(gs, state, ps):
            return jax.tree.map(lambda g: -lr * g, gs), state

        def body(p, g):
            new_p, _ = zero.zero_step(p, {"w": g}, None, opt_update, "dp")
            return new_p

        out = smap(body, mesh, (P(), P("dp")), P())(
            params, grads.reshape(n, 6, 3)
        )
        ref = params["w"] - lr * grads.mean(0)
        np.testing.assert_allclose(np.asarray(out["w"]), ref, rtol=1e-5,
                                   atol=1e-6)


# -- pp ---------------------------------------------------------------------

class TestPipeline:
    def test_pipeline_matches_sequential(self):
        n = 4
        m = 8  # microbatches
        mesh = mesh1d(n, "pp")
        rng = np.random.RandomState(8)
        # stage s: x -> tanh(x @ w[s]); same shape in/out
        ws = rng.randn(n, 6, 6).astype(np.float32) * 0.3
        x = rng.randn(m, 2, 6).astype(np.float32)

        def stage_fn(w, x):
            return jnp.tanh(x @ w)

        out = smap(
            lambda w, x: pp.pipeline(stage_fn, w[0], x, axis_name="pp")[None],
            mesh, (P("pp"), P()), P("pp"),
        )(ws, x)
        # result lives on the last stage
        got = np.asarray(out)[n - 1]  # out has leading pp axis of size n
        ref = x
        for s in range(n):
            ref = np.tanh(ref @ ws[s])
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-5)

    def test_pipeline_loss_grads_flow_to_all_stages(self):
        n = 4
        m = 4
        mesh = mesh1d(n, "pp")
        rng = np.random.RandomState(9)
        ws = rng.randn(n, 4, 4).astype(np.float32) * 0.3
        x = rng.randn(m, 2, 4).astype(np.float32)
        y = rng.randn(m, 2, 4).astype(np.float32)

        def stage_fn(w, x):
            return jnp.tanh(x @ w)

        def loss_of(w_stage, x, y):
            return pp.pipeline_loss(
                stage_fn, lambda out, t: jnp.mean((out - t) ** 2),
                w_stage, x, y, axis_name="pp",
            )

        def body(w, x, y):
            loss, g = jax.value_and_grad(loss_of)(w[0], x, y)
            return loss[None], g[None]

        loss, g = smap(body, mesh, (P("pp"), P(), P()),
                       (P("pp"), P("pp")))(ws, x, y)
        # every stage got a nonzero gradient for its own weights
        g = np.asarray(g)
        assert g.shape == (n, 4, 4)
        for s in range(n):
            assert np.abs(g[s]).max() > 0
        # loss identical on all stages (it was broadcast)
        np.testing.assert_allclose(np.asarray(loss), np.asarray(loss)[0])

        # parity with the dense sequential loss/grad
        def dense_loss(ws_all):
            h = x
            for s in range(n):
                h = jnp.tanh(h @ ws_all[s])
            return jnp.mean((h - y) ** 2)

        ref_loss = dense_loss(ws)
        ref_g = jax.grad(dense_loss)(ws)
        np.testing.assert_allclose(np.asarray(loss)[0], ref_loss, rtol=1e-5)
        np.testing.assert_allclose(g, np.asarray(ref_g), rtol=1e-4,
                                   atol=1e-5)


# -- sp / cp ----------------------------------------------------------------

class TestSequenceParallel:
    def test_reshard_roundtrip(self):
        n = 4
        mesh = mesh1d(n, "sp")
        x = np.random.RandomState(10).randn(16, 8, 4).astype(np.float32)

        def body(x):
            h = sp.seq_to_heads(x, axis_name="sp")
            return sp.heads_to_seq(h, axis_name="sp")

        out = smap(body, mesh, (P("sp"),), P("sp"))(x)
        np.testing.assert_allclose(np.asarray(out), x, rtol=1e-6)

    def test_ulysses_matches_local_attention(self):
        n = 4
        s, h, d = 16, 8, 4
        mesh = mesh1d(n, "sp")
        rng = np.random.RandomState(11)
        q = rng.randn(s, h, d).astype(np.float32)
        k = rng.randn(s, h, d).astype(np.float32)
        v = rng.randn(s, h, d).astype(np.float32)

        def attn(q, k, v):  # (S, H', D) -> transpose to (H', S, D)
            o = cp.local_flash_attention(
                q.transpose(1, 0, 2), k.transpose(1, 0, 2),
                v.transpose(1, 0, 2),
            )
            return o.transpose(1, 0, 2)

        out = smap(
            lambda q, k, v: sp.ulysses_attention(
                q, k, v, attn, axis_name="sp"
            ),
            mesh, (P("sp"), P("sp"), P("sp")), P("sp"),
        )(q, k, v)
        ref = attn(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_ring_matches_local(self, causal):
        n = 4
        h, s, d = 2, 16, 8
        mesh = mesh1d(n, "sp")
        rng = np.random.RandomState(12)
        q = rng.randn(h, s, d).astype(np.float32)
        k = rng.randn(h, s, d).astype(np.float32)
        v = rng.randn(h, s, d).astype(np.float32)

        def body(q, k, v):
            # shard the sequence axis: (h, s/n, d) per rank
            return cp.ring_attention(q, k, v, axis_name="sp", causal=causal)

        out = smap(body, mesh, (P(None, "sp"), P(None, "sp"), P(None, "sp")),
                   P(None, "sp"))(q, k, v)
        ref = cp.local_flash_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=causal
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)


# -- ep ---------------------------------------------------------------------

class TestExpertParallel:
    def test_moe_matches_dense_when_capacity_ample(self):
        n = 4
        t, dmodel, e = 8, 6, 8
        mesh = mesh1d(n, "ep")
        rng = np.random.RandomState(13)
        x = rng.randn(n, t, dmodel).astype(np.float32)
        router = rng.randn(dmodel, e).astype(np.float32)
        # expert e: x -> x * scale_e (leading axis = global experts,
        # sharded over ep -> e_local per rank)
        scales = (np.arange(1, e + 1, dtype=np.float32))[:, None]

        def expert_fn(scale, tokens):
            return tokens * scale

        def run(x, r, s):
            o, a = ep.moe_layer(
                x[0], r, expert_fn, s, axis_name="ep",
                capacity_factor=float(e),  # ample: nothing dropped
            )
            return o, a[None]

        out, aux = smap(
            run,
            mesh,
            (P("ep"), P(), P("ep")),
            (P("ep"), P("ep")),
        )(x, router, scales.reshape(e, 1))
        out = np.asarray(out).reshape(n, t, dmodel)

        # dense reference: each token scaled by its argmax expert's scale
        for r in range(n):
            logits = x[r] @ router
            probs = np.exp(logits - logits.max(-1, keepdims=True))
            probs /= probs.sum(-1, keepdims=True)
            pick = probs.argmax(-1)
            ref = x[r] * scales[pick, 0][:, None] * probs[
                np.arange(t), pick][:, None]
            np.testing.assert_allclose(out[r], ref, rtol=1e-4, atol=1e-4)

    def test_moe_capacity_drops_tokens_to_zero(self):
        n = 2
        t, dmodel, e = 8, 4, 2
        mesh = mesh1d(n, "ep")
        rng = np.random.RandomState(14)
        x = rng.randn(n, t, dmodel).astype(np.float32)
        # router forces everyone onto expert 0
        router = np.zeros((dmodel, e), np.float32)
        router[:, 0] = 10.0 / dmodel
        x_pos = np.abs(x) + 1.0  # make expert-0 logit dominate
        def run(x, r, s):
            o, a = ep.moe_layer(
                x[0], r, lambda s_, t_: t_, s, axis_name="ep",
                capacity_factor=0.5,  # capacity = 2 of 8 tokens
            )
            return o, a[None]

        out, _ = smap(
            run,
            mesh, (P("ep"), P(), P("ep")), (P("ep"), P("ep")),
        )(x_pos, router, np.zeros((e, 1), np.float32))
        out = np.asarray(out).reshape(n, t, dmodel)
        # some tokens were dropped (zero rows), some survived
        zero_rows = (np.abs(out) < 1e-12).all(-1)
        assert zero_rows.any() and not zero_rows.all()


# -- mesh builder -----------------------------------------------------------

def test_build_parallel_mesh_axes():
    mesh = build_parallel_mesh(dp=2, tp=4)
    assert mesh.shape == {"dp": 2, "pp": 1, "sp": 1, "ep": 1, "tp": 4}
    with pytest.raises(ValueError):
        build_parallel_mesh(dp=3, tp=4)


class TestPipelineRemat:
    def test_remat_grads_match_plain(self):
        """remat=True trades recompute for activation memory; the
        gradients must be numerically identical to the plain path
        (same math, different schedule)."""
        n, m = 4, 4
        mesh = mesh1d(n, "pp")
        rng = np.random.RandomState(10)
        ws = rng.randn(n, 4, 4).astype(np.float32) * 0.3
        x = rng.randn(m, 2, 4).astype(np.float32)
        y = rng.randn(m, 2, 4).astype(np.float32)

        def stage_fn(w, x):
            return jnp.tanh(x @ w)

        def loss_of(remat):
            def f(w_stage, x, y):
                return pp.pipeline_loss(
                    stage_fn, lambda out, t: jnp.mean((out - t) ** 2),
                    w_stage, x, y, axis_name="pp", remat=remat,
                )
            return f

        def body(remat):
            def run(w, x, y):
                loss, g = jax.value_and_grad(loss_of(remat))(w[0], x, y)
                return loss[None], g[None]
            return run

        loss_a, g_a = smap(body(False), mesh, (P("pp"), P(), P()),
                           (P("pp"), P("pp")))(ws, x, y)
        loss_b, g_b = smap(body(True), mesh, (P("pp"), P(), P()),
                           (P("pp"), P("pp")))(ws, x, y)
        np.testing.assert_allclose(np.asarray(loss_a), np.asarray(loss_b),
                                   rtol=1e-6)
        np.testing.assert_allclose(np.asarray(g_a), np.asarray(g_b),
                                   rtol=1e-5, atol=1e-6)
