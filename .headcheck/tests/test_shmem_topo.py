"""OSHMEM symmetric heap + topology tests."""

import numpy as np
import pytest

import jax.numpy as jnp

import ompi_release_tpu as mpi
from ompi_release_tpu.oshmem import ShmemCtx, shmem_init
from ompi_release_tpu.topo import (
    cart_create, dims_create, graph_create,
)
from ompi_release_tpu.utils.errors import MPIError


@pytest.fixture(scope="module")
def world():
    yield mpi.init()


@pytest.fixture(scope="module")
def shmem(world):
    ctx = ShmemCtx(world)
    yield ctx
    ctx.finalize()


class TestShmem:
    def test_put_get_roundtrip(self, shmem):
        sym = shmem.malloc((4,), jnp.float32)
        shmem.put(sym, np.full(4, 3.5, np.float32), pe=2)
        shmem.quiet()
        np.testing.assert_array_equal(
            np.asarray(shmem.get(sym, pe=2)), np.full(4, 3.5)
        )
        # untouched PE stays zero
        np.testing.assert_array_equal(
            np.asarray(shmem.get(sym, pe=1)), np.zeros(4)
        )
        sym.free()

    def test_atomic_add_and_fetch(self, shmem):
        sym = shmem.malloc((2,), jnp.float32)
        for _ in range(3):
            shmem.atomic_add(sym, np.ones(2, np.float32), pe=0)
        old = shmem.atomic_fetch_add(sym, np.ones(2, np.float32), pe=0)
        np.testing.assert_array_equal(np.asarray(old), np.full(2, 3.0))
        np.testing.assert_array_equal(
            np.asarray(shmem.get(sym, pe=0)), np.full(2, 4.0)
        )
        sym.free()

    def test_atomic_swap_cswap(self, shmem):
        sym = shmem.malloc((1,), jnp.int32)
        old = shmem.atomic_swap(sym, np.array([5], np.int32), pe=3)
        assert int(old[0]) == 0
        old = shmem.atomic_compare_swap(
            sym, cond=np.array([5], np.int32),
            value=np.array([9], np.int32), pe=3,
        )
        assert int(old[0]) == 5
        assert int(shmem.get(sym, pe=3)[0]) == 9
        # failed CAS leaves value
        shmem.atomic_compare_swap(
            sym, cond=np.array([5], np.int32),
            value=np.array([1], np.int32), pe=3,
        )
        assert int(shmem.get(sym, pe=3)[0]) == 9
        sym.free()

    def test_barrier_all_flushes_puts(self, shmem):
        sym = shmem.malloc((3,), jnp.float32)
        for pe in range(shmem.n_pes):
            shmem.put(sym, np.full(3, float(pe), np.float32), pe=pe)
        shmem.barrier_all()
        for pe in range(shmem.n_pes):
            assert float(sym.local(pe)[0]) == float(pe)
        sym.free()

    def test_scoll_delegates(self, shmem, world):
        x = np.random.RandomState(0).randn(world.size, 8).astype(np.float32)
        s = shmem.sum_to_all(x)
        np.testing.assert_allclose(
            np.asarray(s)[0], x.sum(0), rtol=2e-5, atol=1e-5
        )
        f = shmem.fcollect(x[:, :2])
        assert np.asarray(f).shape == (world.size, world.size * 2)


class TestShmemLocks:
    """shmem_set_lock/clear_lock/test_lock (shmem.h.in:167) over the
    AMO-backed home-PE lock word."""

    def test_acquire_release_cycle(self, shmem):
        lk = shmem.lock_create()
        shmem.set_lock(lk, pe=1)
        assert not shmem.test_lock(lk, pe=2)  # held: attempt fails
        shmem.clear_lock(lk, pe=1)
        assert shmem.test_lock(lk, pe=2)      # free: attempt acquires
        shmem.clear_lock(lk, pe=2)

    def test_wrong_holder_clear_raises(self, shmem):
        from ompi_release_tpu.utils.errors import MPIError

        lk = shmem.lock_create()
        shmem.set_lock(lk, pe=0)
        with pytest.raises(MPIError):
            shmem.clear_lock(lk, pe=3)
        with pytest.raises(MPIError):
            shmem.set_lock(lk, pe=0)  # non-recursive
        shmem.clear_lock(lk, pe=0)
        with pytest.raises(MPIError):
            shmem.clear_lock(lk, pe=0)  # already free

    def test_contention_mutual_exclusion(self, shmem):
        """N contending PEs (threads) do lost-update-prone
        read-modify-writes on a shared word under the lock: the final
        count proves mutual exclusion (without the lock this test
        reliably loses updates)."""
        import threading

        lk = shmem.lock_create()
        counter = shmem.malloc((1,), jnp.int32)
        n_pes, iters = 4, 25
        errs = []

        def contender(pe):
            try:
                for _ in range(iters):
                    shmem.set_lock(lk, pe=pe)
                    try:
                        v = int(np.asarray(
                            shmem.atomic_fetch(counter, pe=0)
                        ).reshape(-1)[0])
                        shmem.atomic_set(counter, v + 1, pe=0)
                    finally:
                        shmem.clear_lock(lk, pe=pe)
            except Exception as e:  # surfaced after join
                errs.append(e)

        threads = [threading.Thread(target=contender, args=(pe,))
                   for pe in range(n_pes)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert not errs, errs
        final = int(np.asarray(
            shmem.atomic_fetch(counter, pe=0)).reshape(-1)[0])
        assert final == n_pes * iters, final


class TestDims:
    def test_dims_create_balanced(self):
        assert dims_create(8, 3) == (2, 2, 2)
        assert dims_create(12, 2) == (4, 3)

    def test_dims_create_partial(self):
        assert dims_create(8, 2, [2, 0]) == (2, 4)
        with pytest.raises(MPIError):
            dims_create(7, 2, [2, 0])


class TestCart:
    def test_coords_rank_roundtrip(self, world):
        c, topo = cart_create(world, [2, 4], periods=[True, False])
        for r in range(world.size):
            assert topo.rank(topo.coords(r)) == r
        assert topo.coords(0) == (0, 0)
        assert topo.coords(7) == (1, 3)
        c.free()

    def test_shift_periodic_and_edge(self, world):
        c, topo = cart_create(world, [2, 4], periods=[True, False])
        src, dst = topo.shift(0, 1, 0)  # periodic dim of size 2
        assert (src, dst) == (4, 4)
        src, dst = topo.shift(1, 1, 3)  # non-periodic edge: (1,3)+1 -> NULL
        assert src == 2 and dst == -1
        c.free()

    def test_neighbor_allgather_2d_torus(self, world):
        c, topo = cart_create(world, [2, 4], periods=[True, True])
        x = np.arange(world.size, dtype=np.float32)[:, None]
        out = np.asarray(topo.neighbor_allgather(x))
        # out: (size, 4 neighbors, 1)
        assert out.shape == (world.size, 4, 1)
        for r in range(world.size):
            nbrs = topo.neighbors(r)
            np.testing.assert_array_equal(
                out[r, :, 0], np.array(nbrs, np.float32)
            )
        c.free()

    def test_neighbor_alltoall_exchanges_blocks(self, world):
        c, topo = cart_create(world, [2, 4], periods=[True, True])
        nn = 4
        # block value encodes (sender, slot)
        x = np.zeros((world.size, nn, 1), np.float32)
        for r in range(world.size):
            for j in range(nn):
                x[r, j, 0] = 100 * r + j
        out = np.asarray(topo.neighbor_alltoall(x))
        for r in range(world.size):
            nbrs = topo.neighbors(r)
            for j in range(nn):
                # slot j holds neighbor j's block aimed at me (their j^1)
                assert out[r, j, 0] == 100 * nbrs[j] + (j ^ 1)
        c.free()

    def test_cart_sub_splits_rows(self, world):
        c, topo = cart_create(world, [2, 4], periods=[False, False])
        subs = topo.sub([False, True])  # keep columns: 2 row-comms of 4
        assert all(s is not None for s in subs)
        sc0, st0 = subs[0]
        assert sc0.size == 4 and st0.dims == (4,)
        # ranks 0-3 share a subcomm; 4-7 share another
        assert subs[0][0].cid == subs[3][0].cid
        assert subs[0][0].cid != subs[4][0].cid
        c.free()

    def test_graph_topo(self, world):
        # ring graph over 4 ranks inside an 8-comm is invalid; build on all 8
        index, edges = [], []
        acc = 0
        for r in range(world.size):
            nbrs = [(r - 1) % world.size, (r + 1) % world.size]
            acc += len(nbrs)
            index.append(acc)
            edges.extend(nbrs)
        g, topo = graph_create(world, index, edges)
        assert topo.neighbors(0) == [world.size - 1, 1]
        assert topo.neighbors(3) == [2, 4]
        g.free()


class TestRaggedNeighborhoods:
    """Graph/dist-graph neighborhood collectives (VERDICT r2 #8): the
    ragged edge set is edge-colored into static ppermute rounds —
    the libnbc round schedule baked into one compiled program
    (nbc_ineighbor_allgather.c / nbc_ineighbor_alltoall.c)."""

    def _ring_graph(self, world):
        index, edges = [], []
        acc = 0
        for r in range(world.size):
            nbrs = [(r - 1) % world.size, (r + 1) % world.size]
            acc += len(nbrs)
            index.append(acc)
            edges.extend(nbrs)
        return graph_create(world, index, edges)

    def test_graph_neighbor_allgather(self, world):
        g, topo = self._ring_graph(world)
        n = world.size
        x = np.arange(n * 3, dtype=np.float32).reshape(n, 3)
        out = np.asarray(topo.neighbor_allgather(x))
        assert out.shape == (n, 2, 3)
        for r in range(n):
            for i, nbr in enumerate(topo.neighbors(r)):
                np.testing.assert_array_equal(out[r, i], x[nbr])
        g.free()

    def test_graph_neighbor_alltoall(self, world):
        g, topo = self._ring_graph(world)
        n = world.size
        x = np.arange(n * 2 * 2, dtype=np.float32).reshape(n, 2, 2)
        out = np.asarray(topo.neighbor_alltoall(x))
        # block j of rank r goes to neighbors(r)[j]; at the receiver
        # it lands in the slot whose source is r
        for r in range(n):
            for i, src in enumerate(topo.neighbors(r)):
                j = topo.neighbors(src).index(r)
                np.testing.assert_array_equal(out[r, i], x[src, j])
        g.free()

    def test_dist_graph_irregular(self, world):
        """Asymmetric, ragged dist-graph: a star + a chord."""
        from ompi_release_tpu.topo import dist_graph_create_adjacent

        n = world.size
        # rank 0 broadcasts to everyone; rank 3 also feeds rank 1
        destinations = [[r for r in range(1, n)]] + [[] for _ in range(n - 1)]
        destinations[3] = [1]
        sources = [[] for _ in range(n)]
        for r in range(1, n):
            sources[r] = [0]
        sources[1] = [0, 3]
        dg, topo = dist_graph_create_adjacent(world, sources, destinations)
        assert topo.max_in_degree == 2
        assert topo.max_out_degree == n - 1
        x = 10.0 + np.arange(n, dtype=np.float32).reshape(n, 1)
        out = np.asarray(topo.neighbor_allgather(x))
        assert out.shape == (n, 2, 1)
        for r in range(1, n):
            np.testing.assert_array_equal(out[r, 0], x[0])
        np.testing.assert_array_equal(out[1, 1], x[3])
        np.testing.assert_array_equal(out[0], np.zeros((2, 1)))
        # alltoall: rank 0 sends a DISTINCT block to each destination
        xa = np.arange(n * (n - 1) * 1, dtype=np.float32).reshape(
            n, n - 1, 1
        )
        outa = np.asarray(topo.neighbor_alltoall(xa))
        for r in range(1, n):
            np.testing.assert_array_equal(outa[r, 0], xa[0, r - 1])
        np.testing.assert_array_equal(outa[1, 1], xa[3, 0])
        dg.free()

    def test_dist_graph_mismatched_edges_rejected(self, world):
        from ompi_release_tpu.topo import dist_graph_create_adjacent

        n = world.size
        sources = [[] for _ in range(n)]
        destinations = [[] for _ in range(n)]
        destinations[0] = [1]  # 0 sends to 1, but 1 lists no source
        with pytest.raises(Exception):
            dist_graph_create_adjacent(world, sources, destinations)


class TestShmemExtendedApi:
    """shmem breadth: inc/set/fetch AMOs, wait_until/test sync,
    collect + logical/prod reductions (oshmem/include/shmem.h.in)."""

    def test_inc_set_fetch(self, world):
        from ompi_release_tpu.oshmem import shmem

        ctx = shmem.shmem_init(world)
        s = ctx.malloc((2,), jnp.float32)
        ctx.atomic_set(s, np.array([5.0, 7.0], np.float32), pe=1)
        ctx.atomic_inc(s, pe=1)
        got = np.asarray(ctx.atomic_fetch(s, pe=1))
        np.testing.assert_array_equal(got, [6.0, 8.0])
        prev = np.asarray(ctx.atomic_fetch_inc(s, pe=1))
        np.testing.assert_array_equal(prev, [6.0, 8.0])
        np.testing.assert_array_equal(
            np.asarray(ctx.get(s, pe=1)), [7.0, 9.0])
        ctx.finalize()
        shmem._ctx = None

    def test_wait_until_and_test(self, world):
        import threading

        from ompi_release_tpu.oshmem import shmem

        ctx = shmem.shmem_init(world)
        flag = ctx.malloc((1,), jnp.float32)
        assert ctx.test(flag, "ge", 1.0, pe=2) is False

        def producer():
            import time
            time.sleep(0.2)
            ctx.atomic_add(flag, np.ones(1, np.float32), pe=2)
            ctx.quiet()

        t = threading.Thread(target=producer)
        t.start()
        got = np.asarray(ctx.wait_until(flag, "ge", 1.0, pe=2,
                                        timeout_s=10))
        t.join()
        assert got[0] >= 1.0
        with pytest.raises(Exception):
            ctx.wait_until(flag, "lt", 0.0, pe=2, timeout_s=0.2)
        with pytest.raises(Exception):
            ctx.wait_until(flag, "approximately", 1.0, pe=2)
        ctx.finalize()
        shmem._ctx = None

    def test_collect_and_reductions(self, world):
        from ompi_release_tpu.oshmem import shmem

        ctx = shmem.shmem_init(world)
        n = world.size
        ragged = [np.arange(i + 1, dtype=np.float32) for i in range(n)]
        got = np.asarray(ctx.collect(ragged))
        np.testing.assert_array_equal(got, np.concatenate(ragged))
        x = np.full((n, 4), 2.0, np.float32)
        np.testing.assert_allclose(
            np.asarray(ctx.prod_to_all(x))[0], 2.0 ** n)
        xi = np.arange(n * 2, dtype=np.int32).reshape(n, 2)
        import functools
        np.testing.assert_array_equal(
            np.asarray(ctx.xor_to_all(xi))[0],
            functools.reduce(np.bitwise_xor, [xi[r] for r in range(n)]),
        )
        np.testing.assert_array_equal(
            np.asarray(ctx.or_to_all(xi))[3],
            functools.reduce(np.bitwise_or, [xi[r] for r in range(n)]),
        )
        ctx.finalize()
        shmem._ctx = None


class TestNonblockingNeighborhoods:
    """ineighbor_* (libnbc nbc_ineighbor_*): the compiled schedule is
    dispatched asynchronously; the Request completes to the same
    result the blocking call returns."""

    def test_cart_ineighbor_allgather(self, world):
        c, topo = cart_create(world, [2, 4], periods=[True, True])
        x = np.arange(world.size, dtype=np.float32)[:, None]
        req = topo.ineighbor_allgather(x)
        req.wait()
        out = np.asarray(req.value)
        np.testing.assert_array_equal(
            out, np.asarray(topo.neighbor_allgather(x)))
        c.free()

    def test_graph_ineighbor_alltoall_matches_blocking(self, world):
        index, edges = [], []
        acc = 0
        for r in range(world.size):
            nbrs = [(r - 1) % world.size, (r + 1) % world.size]
            acc += len(nbrs)
            index.append(acc)
            edges.extend(nbrs)
        g, topo = graph_create(world, index, edges)
        n = world.size
        x = np.random.RandomState(3).randn(n, 2, 3).astype(np.float32)
        req = topo.ineighbor_alltoall(x)
        assert hasattr(req, "test") or hasattr(req, "wait")
        req.wait()
        np.testing.assert_array_equal(
            np.asarray(req.value),
            np.asarray(topo.neighbor_alltoall(x)))
        g.free()
