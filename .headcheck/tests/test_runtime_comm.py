"""Runtime bring-up + communicator/group tests (8-device CPU mesh)."""

import numpy as np
import pytest

import ompi_release_tpu as mpi
from ompi_release_tpu.comm import Group, IDENT, SIMILAR, UNDEFINED, UNEQUAL
from ompi_release_tpu.runtime import JobState, factorize_torus
from ompi_release_tpu.runtime.runtime import Runtime, _parse_mca_cli


@pytest.fixture(scope="module")
def world():
    w = mpi.init()
    yield w


def test_init_world(world):
    assert world.size == 8
    assert world.name == "MPI_COMM_WORLD"
    rt = Runtime.current()
    assert rt.job_state.visited(JobState.VM_READY)
    assert rt.job_state.visited(JobState.REGISTERED)
    assert len(rt.endpoints) == 8
    assert rt.endpoints[3].rank == 3


def test_second_init_returns_same(world):
    assert mpi.init() is world


def test_group_calculus():
    g = Group(range(8))
    sub = g.incl([1, 3, 5])
    assert sub.size == 3
    assert sub.world_rank(1) == 3
    assert sub.rank_of(5) == 2
    assert sub.rank_of(0) == UNDEFINED
    assert g.excl([0, 1, 2, 3, 4]).world_ranks == (5, 6, 7)
    assert g.range_incl([(0, 6, 2)]).world_ranks == (0, 2, 4, 6)
    assert g.range_excl([(0, 6, 2)]).world_ranks == (1, 3, 5, 7)
    a, b = g.incl([0, 1, 2]), g.incl([2, 3])
    assert a.union(b).world_ranks == (0, 1, 2, 3)
    assert a.intersection(b).world_ranks == (2,)
    assert a.difference(b).world_ranks == (0, 1)
    assert a.compare(g.incl([0, 1, 2])) == IDENT
    assert a.compare(g.incl([2, 1, 0])) == SIMILAR
    assert a.compare(b) == UNEQUAL
    assert a.translate_ranks([0, 2], b) == [UNDEFINED, 0]


def test_group_duplicate_ranks_rejected():
    with pytest.raises(Exception):
        Group([1, 1, 2])


def test_comm_create_dup_free(world):
    sub = world.create(world.group.incl([0, 2, 4, 6]), name="evens")
    assert sub.size == 4
    d = sub.dup()
    assert d.size == 4 and d.cid != sub.cid
    d.free()
    sub.free()
    with pytest.raises(Exception):
        sub.dup()


def test_comm_split(world):
    colors = [i % 2 for i in range(8)]
    keys = [-i for i in range(8)]  # reverse order within each color
    comms = world.split(colors, keys)
    assert len(comms) == 8
    evens = comms[0]
    # rank order within color: sorted by key => descending world rank
    assert evens.group.world_ranks == (6, 4, 2, 0)
    # ranks sharing a color share the communicator object
    assert comms[0] is comms[2] is comms[4] is comms[6]
    assert comms[1] is comms[3]
    for c in {id(c): c for c in comms}.values():
        c.free()


def test_comm_split_undefined(world):
    colors = [0, UNDEFINED, 0, UNDEFINED, 0, UNDEFINED, 0, UNDEFINED]
    comms = world.split(colors)
    assert comms[1] is None
    assert comms[0].size == 4
    comms[0].free()


def test_keyvals(world):
    from ompi_release_tpu.comm import create_keyval, free_keyval

    copies = []
    kv = create_keyval(
        copy_fn=lambda c, k, v, s: (copies.append(v) or (True, v * 2)),
        delete_fn=lambda c, k, v, s: None,
    )
    world.set_attr(kv, 21)
    found, val = world.get_attr(kv)
    assert found and val == 21
    d = world.dup()
    found, val = d.get_attr(kv)
    assert found and val == 42  # copy callback doubled it
    d.free()
    world.delete_attr(kv)
    assert world.get_attr(kv) == (False, None)
    free_keyval(kv)


def test_factorize_torus():
    assert factorize_torus(8, 1) == (8,)
    assert factorize_torus(8, 2) == (4, 2)
    assert factorize_torus(8, 3) == (2, 2, 2)
    assert factorize_torus(12, 2) == (4, 3)
    assert factorize_torus(7, 2) == (7, 1)
    assert factorize_torus(1, 2) == (1, 1)


def test_parse_mca_cli():
    argv = ["prog", "--mca", "coll", "tuned", "-x", "--mca", "a_b", "3"]
    assert _parse_mca_cli(argv) == [("coll", "tuned"), ("a_b", "3")]
    assert _parse_mca_cli(["--mca", "dangling"]) == []


def test_submesh_device_order(world):
    sub = world.create(world.group.incl([5, 1, 3]), name="scrambled")
    devs = list(sub.submesh.devices.reshape(-1))
    assert [d.id for d in devs] == [5, 1, 3]  # group order preserved
    sub.free()


def test_split_type_shared(world):
    comms = world.split_type_shared()
    # single host: everyone lands in one shared comm
    assert comms[0].size == 8
    comms[0].free()


class TestInfo:
    """MPI_Info object (ompi/info analogue) — closes the 'MPI_Info
    beyond a dict' L3 gap."""

    def test_set_get_delete_order(self):
        from ompi_release_tpu.comm import Info

        info = Info()
        info.set("alpha", "1")
        info.set("beta", "2")
        info.set("alpha", "3")  # overwrite keeps position
        assert info.nkeys == 2
        assert info.get("alpha") == "3"
        assert info.get("missing") is None  # flag=false, not an error
        assert [info.nthkey(i) for i in range(2)] == ["alpha", "beta"]
        info.delete("alpha")
        with pytest.raises(Exception):
            info.delete("alpha")  # MPI_ERR_INFO_NOKEY
        with pytest.raises(Exception):
            info.nthkey(5)
        with pytest.raises(Exception):
            info.set("", "x")
        with pytest.raises(Exception):
            info.set("k" * 300, "x")  # > MPI_MAX_INFO_KEY

    def test_dup_is_independent(self):
        from ompi_release_tpu.comm import Info

        a = Info({"k": "v"})
        b = a.dup()
        b.set("k", "w")
        assert a.get("k") == "v" and b.get("k") == "w"

    def test_info_env_reserved_keys(self):
        from ompi_release_tpu.comm import INFO_ENV

        for key in ("command", "argv", "wdir", "thread_level"):
            assert key in INFO_ENV

    def test_comm_info_dup_semantics(self, world):
        c = world.dup(name="info_parent")
        c.info.set("io_hint", "collective")
        d = c.dup(name="info_child")
        assert d.info.get("io_hint") == "collective"
        d.info.set("io_hint", "independent")
        assert c.info.get("io_hint") == "collective"  # deep copy
        d.free()
        c.free()


def test_env_utility_surface(world):
    """MPI_Initialized/Wtime/Wtick/Get_version/Error_string."""
    assert mpi.initialized() is True
    assert mpi.finalized() is False
    t0 = mpi.wtime()
    assert mpi.wtime() >= t0
    assert 0 < mpi.wtick() < 1
    ver, level = mpi.get_version()
    assert ver and "1.8.5" in level
    from ompi_release_tpu.utils.errors import ErrorCode
    assert mpi.error_string(ErrorCode.ERR_RANK) == "ERR_RANK"
    assert mpi.error_string(6) == "ERR_RANK"
    assert "unknown" in mpi.error_string(99999)


def test_init_timing_report():
    """The ompi_timing analogue: with runtime_timing set, init prints
    per-stage durations from the job state machine's timestamped
    history (ompi_mpi_init.c:366-371,617-625)."""
    import os
    import subprocess
    import sys

    from conftest import subprocess_env

    env = subprocess_env(OMPITPU_MCA_runtime_timing="1")
    r = subprocess.run(
        [sys.executable, "-c",
         "import ompi_release_tpu as mpi; mpi.init(); mpi.finalize()"],
        cwd="/root/repo", env=env, capture_output=True, text=True,
        timeout=300,
    )
    assert r.returncode == 0, r.stderr
    err = r.stderr
    assert "init timing (total" in err, err
    for stage in ("INIT", "ALLOCATE", "MAP", "VM_READY", "RUNNING"):
        assert stage in err, err
