"""tpu_info CLI + tracing interposition tests."""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

import ompi_release_tpu as mpi
from ompi_release_tpu import ops
from ompi_release_tpu.tools import tpu_info, trace
from ompi_release_tpu.utils.errors import MPIError


@pytest.fixture(scope="module")
def world():
    yield mpi.init()


class TestTpuInfo:
    def test_gather_structure(self, world):
        info = tpu_info.gather()
        names = [f["name"] for f in info["frameworks"]]
        assert "coll" in names and "pml" in names and "op" in names
        coll = next(f for f in info["frameworks"] if f["name"] == "coll")
        comp_names = [c["name"] for c in coll["components"]]
        assert "tuned" in comp_names and "xla" in comp_names
        assert any(v["name"] == "pml_eager_limit"
                   for v in info["variables"])
        assert len(info["devices"]) >= 1

    def test_render_text(self, world):
        info = tpu_info.gather()
        text = tpu_info.render_text(info, show_vars=True)
        assert "Frameworks:" in text and "pml_eager_limit" in text

    def test_cli_json_subprocess(self):
        out = subprocess.run(
            [sys.executable, "-m", "ompi_release_tpu.tools.tpu_info",
             "--json", "--param", "coll"],
            capture_output=True, text=True, timeout=120, cwd="/root/repo",
            env={"PATH": "/usr/bin:/bin", "JAX_PLATFORMS": "cpu",
                 "HOME": "/root"},
        )
        assert out.returncode == 0, out.stderr
        info = json.loads(out.stdout)
        assert all("coll" in v["name"] for v in info["variables"])


class TestTracing:
    def test_interposition_records_events(self, world, tmp_path):
        sink = str(tmp_path / "trace.jsonl")
        tc = trace.wrap(world, sink)
        x = np.ones((world.size, 100), np.float32)
        tc.allreduce(x, ops.SUM)
        tc.bcast(x, root=0)
        tc.barrier()
        tc.send(np.int32(1), dest=1, tag=600, rank=0)
        tc.recv(source=0, tag=600, rank=1)
        s = tc.summary()
        assert s["allreduce"]["calls"] == 1
        assert s["allreduce"]["bytes"] == x.nbytes
        assert s["barrier"]["calls"] == 1 and s["recv"]["calls"] == 1
        tc.close()
        lines = [json.loads(l) for l in open(sink)]
        assert len(lines) == 5
        assert lines[0]["op"] == "allreduce" and lines[0]["dt"] >= 0

    def test_passthrough_untraced(self, world):
        tc = trace.wrap(world)
        assert tc.size == world.size  # attribute passthrough
        sub = tc.dup("traced_dup")  # untraced method passthrough
        sub.free()


class TestTpuServer:
    """Standalone orte-server analogue: name exchange between
    INDEPENDENT jobs (no shared HNP)."""

    def test_cross_job_publish_lookup(self):
        from ompi_release_tpu.tools.tpu_server import (
            NameClient, NameServer,
        )

        srv = NameServer()
        a = NameClient("127.0.0.1", srv.port)  # "job A"
        b = NameClient("127.0.0.1", srv.port)  # "job B"
        try:
            assert a.client_id != b.client_id
            a.publish("cross-job-svc", "tpu-port:99")
            assert b.lookup("cross-job-svc") == "tpu-port:99"
            # parked lookup answered by a later publish
            import threading

            got = {}
            t = threading.Thread(
                target=lambda: got.update(
                    v=b.lookup("late-svc", timeout_ms=15000))
            )
            t.start()
            import time
            time.sleep(0.3)
            a.publish("late-svc", "tpu-port:7")
            t.join(timeout=15)
            assert got["v"] == "tpu-port:7"
            a.unpublish("cross-job-svc")
            with pytest.raises(MPIError):
                b.lookup("cross-job-svc", timeout_ms=300)
        finally:
            a.close()
            b.close()
            srv.shutdown()

    def test_concurrent_rpcs_do_not_serialize(self):
        """A publish issued from another thread of the SAME client
        endpoint while a lookup is parked server-side completes
        immediately and unparks that lookup — the reply demultiplexer
        means concurrent RPCs never wait out each other's timeouts."""
        import threading
        import time as _time

        from ompi_release_tpu.tools.tpu_server import (
            NameClient, NameServer,
        )

        srv = NameServer()
        client = NameClient("127.0.0.1", srv.port)
        try:
            got = {}

            def looker():
                t0 = _time.monotonic()
                got["value"] = client.lookup("late-svc",
                                             timeout_ms=20_000)
                got["elapsed"] = _time.monotonic() - t0

            t = threading.Thread(target=looker, daemon=True)
            t.start()
            _time.sleep(0.3)  # lookup is parked server-side now
            t0 = _time.monotonic()
            client.publish("late-svc", "9191")  # same endpoint!
            publish_took = _time.monotonic() - t0
            t.join(timeout=10)
            assert not t.is_alive()
            assert got["value"] == "9191"
            # the publish must not have waited behind the parked
            # lookup's 20s budget, and the lookup unparked promptly
            assert publish_took < 5, publish_took
            assert got["elapsed"] < 10, got["elapsed"]
        finally:
            client.close()
            srv.shutdown()

    def test_cli_prints_uri(self):
        import subprocess
        import sys

        p = subprocess.Popen(
            [sys.executable, "-m", "ompi_release_tpu.tools.tpu_server"],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        try:
            line = p.stdout.readline()
            assert line.startswith("tpu-server URI: ")
            host_port = line.split(": ", 1)[1].strip()
            host, port = host_port.rsplit(":", 1)
            assert int(port) > 0
        finally:
            p.terminate()
            p.wait(timeout=10)


class TestExamples:
    """The reference's examples/ are its acceptance programs
    (SURVEY §4 item 4); ours must run the same way."""

    @pytest.mark.parametrize("name", [
        "ring_tpu.py", "connectivity_tpu.py", "allreduce_tpu.py",
        "hello_oshmem_tpu.py", "ring_oshmem_tpu.py",
        "oshmem_reduction_tpu.py", "unified_world_tpu.py",
    ])
    def test_example_runs_driver_mode(self, name):
        import os
        import subprocess

        from conftest import subprocess_env

        # without the axon filter the examples silently ran
        # single-device on the real chip instead of the 8-device mesh
        env = subprocess_env(
            XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                       + " --xla_force_host_platform_device_count=8"))
        r = subprocess.run(
            [sys.executable, f"examples/{name}"], cwd="/root/repo",
            env=env, capture_output=True, text=True, timeout=300,
        )
        assert r.returncode == 0, r.stderr
        assert "OK" in r.stdout or "complete" in r.stdout

    def test_unified_world_example_under_tpurun(self):
        """The cross-process acceptance example: 2 processes x 4
        virtual devices, collectives + p2p + RMA across the boundary
        through the public API."""
        import os
        import subprocess

        from conftest import subprocess_env

        env = subprocess_env(
            XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                       + " --xla_force_host_platform_device_count=4"))
        r = subprocess.run(
            [sys.executable, "-m", "ompi_release_tpu.tools.tpurun",
             "-n", "2", sys.executable,
             "examples/unified_world_tpu.py"],
            cwd="/root/repo", env=env, capture_output=True, text=True,
            timeout=300,
        )
        assert r.returncode == 0, r.stderr + r.stdout
        assert "unified world OK (ranks 0..3 of 8)" in r.stdout
        assert "unified world OK (ranks 4..7 of 8)" in r.stdout

    def test_hello_under_tpurun(self):
        import subprocess

        from conftest import subprocess_env

        # 3 workers contending for the one tunneled chip hang whenever
        # another tenant holds it — this launch test is about tpurun
        env = subprocess_env()
        r = subprocess.run(
            [sys.executable, "-m", "ompi_release_tpu.tools.tpurun",
             "-n", "3", sys.executable, "examples/hello_tpu.py"],
            cwd="/root/repo", env=env, capture_output=True, text=True,
            timeout=300,
        )
        assert r.returncode == 0, r.stderr + r.stdout
        for rank in range(3):
            assert f"I am process {rank} of 3" in r.stdout


class TestTpuClean:
    """tpu-clean (orte-clean analogue): stale sessions + orphaned shm
    segments of dead jobs are removed; live ones are never touched."""

    def test_clean_reaps_only_dead_owners(self, tmp_path, monkeypatch):
        import io
        import json
        from multiprocessing import shared_memory

        from ompi_release_tpu.tools import tpu_clean, tpurun

        sess = tmp_path / "sessions"
        sess.mkdir()
        monkeypatch.setattr(tpurun, "SESSION_DIR", str(sess))
        # dead-pid file, live file, malformed-but-valid-JSON debris
        # ({"pid": null} and a JSON list both count), non-JSON debris
        (sess / "111.json").write_text(json.dumps({"pid": 2 ** 22 + 17}))
        (sess / "live.json").write_text(json.dumps({"pid": os.getpid()}))
        (sess / "junk.json").write_text("{not json")
        (sess / "nullpid.json").write_text('{"pid": null}')
        (sess / "list.json").write_text("[1, 2]")

        # a per-test prefix isolates the scan from any real ompitpu-*
        # debris on this machine (and keeps the real clean() pass from
        # touching segments the test did not create)
        prefix = f"omtst{os.getpid()}-"
        dead_seg = shared_memory.SharedMemory(
            create=True, size=64, name=f"{prefix}{2 ** 22 + 19}-dead")
        live_seg = shared_memory.SharedMemory(
            create=True, size=64, name=f"{prefix}{os.getpid()}-live")
        fresh_dead = shared_memory.SharedMemory(
            create=True, size=64, name=f"{prefix}{2 ** 22 + 23}-fresh")
        try:
            kw = dict(min_age_s=0.0, shm_prefix=prefix)
            # dry run removes nothing
            buf = io.StringIO()
            ns, ng = tpu_clean.clean(dry_run=True, verbose=True,
                                     out=buf, **kw)
            assert ns == 4 and ng == 2, buf.getvalue()
            assert (sess / "111.json").exists()
            # the min-age gate protects in-flight ownership handoffs
            # (sender exited, receiver about to map)
            _, ng_aged = tpu_clean.clean(
                dry_run=True, min_age_s=3600.0, shm_prefix=prefix,
                out=buf)
            assert ng_aged == 0
            ns, ng = tpu_clean.clean(verbose=True, out=buf, **kw)
            assert ns == 4 and ng == 2, buf.getvalue()
            for gone in ("111.json", "junk.json", "nullpid.json",
                         "list.json"):
                assert not (sess / gone).exists(), gone
            assert (sess / "live.json").exists()
            # dead-creator segments are gone, the live one intact
            for seg in (dead_seg, fresh_dead):
                with pytest.raises(FileNotFoundError):
                    shared_memory.SharedMemory(name=seg.name)
            shared_memory.SharedMemory(name=live_seg.name).close()
        finally:
            for seg in (live_seg, dead_seg, fresh_dead):
                seg.close()
                try:
                    seg.unlink()
                except FileNotFoundError:
                    pass

    def test_cli_reports_counts(self, tmp_path, monkeypatch):
        import subprocess

        r = subprocess.run(
            [sys.executable, "-m", "ompi_release_tpu.tools.tpu_clean",
             "--dry-run"],
            cwd="/root/repo", capture_output=True, text=True, timeout=60,
        )
        assert r.returncode == 0, r.stderr
        assert "tpu-clean: would remove" in r.stdout
