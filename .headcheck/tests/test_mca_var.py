"""Unit tests for the MCA variable system (mca/var.py).

Mirrors the reference's precedence contract: override > env > file >
default (``opal/mca/base/mca_base_var.c``).
"""

import os

import pytest

from ompi_release_tpu.mca import var as mca_var
from ompi_release_tpu.mca.var import ENV_PREFIX, VarScope, VarSource, parse_size


def test_register_and_default(fresh_mca):
    v = fresh_mca.register("btl_tpu_eager_limit", "size", "64K",
                           "eager/rendezvous switch point")
    assert v.value == 64 * 1024
    assert v.source is VarSource.DEFAULT
    assert fresh_mca.get("btl_tpu_eager_limit") == 65536


def test_types(fresh_mca):
    assert fresh_mca.register("a_int", "int", "42").value == 42
    assert fresh_mca.register("a_float", "float", "2.5").value == 2.5
    assert fresh_mca.register("a_bool", "bool", "yes").value is True
    assert fresh_mca.register("a_str", "str", 7).value == "7"
    assert fresh_mca.register("a_list", "list", "tcp, self").value == ["tcp", "self"]
    v = fresh_mca.register("a_enum", "enum", "ring",
                           choices=["ring", "recursive_doubling"])
    assert v.value == "ring"
    with pytest.raises(ValueError):
        fresh_mca.register("bad_enum", "enum", "nope", choices=["a", "b"])


def test_parse_size():
    assert parse_size("8") == 8
    assert parse_size("4k") == 4096
    assert parse_size("64K") == 65536
    assert parse_size("1M") == 1 << 20
    assert parse_size("2GB") == 2 << 30
    with pytest.raises(ValueError):
        parse_size("lots")


def test_env_precedence(fresh_mca, monkeypatch):
    monkeypatch.setenv(ENV_PREFIX + "coll_tuned_algorithm", "ring")
    v = fresh_mca.register("coll_tuned_algorithm", "str", "auto")
    assert v.value == "ring"
    assert v.source is VarSource.ENV


def test_file_and_override_precedence(fresh_mca, monkeypatch, tmp_path):
    p = tmp_path / "params.conf"
    p.write_text("# comment\nfoo_bar = 10\nbaz = hello # trailing\n")
    assert fresh_mca.load_param_file(str(p)) == 2
    v = fresh_mca.register("foo_bar", "int", 1)
    assert v.value == 10 and v.source is VarSource.FILE

    # env beats file
    monkeypatch.setenv(ENV_PREFIX + "foo_bar", "20")
    fresh_mca.refresh_from_env()
    assert v.value == 20 and v.source is VarSource.ENV

    # override beats env
    fresh_mca.set_value("foo_bar", 30)
    assert v.value == 30 and v.source is VarSource.OVERRIDE

    fresh_mca.unset("foo_bar")
    assert v.value == 20 and v.source is VarSource.ENV


def test_cli_pairs(fresh_mca):
    v = fresh_mca.register("pml_tpu_pipeline_depth", "int", 2)
    fresh_mca.apply_cli([("pml_tpu_pipeline_depth", "8")])
    assert v.value == 8 and v.source is VarSource.OVERRIDE


def test_readonly_scope(fresh_mca):
    fresh_mca.register("const_thing", "int", 5, scope=VarScope.READONLY)
    with pytest.raises(PermissionError):
        fresh_mca.set_value("const_thing", 6)


def test_reregistration_idempotent(fresh_mca):
    a = fresh_mca.register("dup", "int", 1)
    b = fresh_mca.register("dup", "int", 99)
    assert a is b and b.value == 1
    with pytest.raises(ValueError):
        fresh_mca.register("dup", "str", "x")


def test_synonyms(fresh_mca, monkeypatch):
    monkeypatch.setenv(ENV_PREFIX + "old_name", "7")
    v = fresh_mca.register("new_name", "int", 0, synonyms=["old_name"])
    assert v.value == 7


def test_describe_all(fresh_mca):
    fresh_mca.register("zz", "int", 1, "help text")
    descs = fresh_mca.describe_all()
    assert any(d["name"] == "zz" and d["help"] == "help text" for d in descs)


def test_readonly_not_leaked_via_refresh(fresh_mca):
    """A rejected set_value must not apply on a later resolve."""
    v = fresh_mca.register("ro_var", "int", 5, scope=VarScope.READONLY)
    with pytest.raises(PermissionError):
        fresh_mca.set_value("ro_var", 6)
    fresh_mca.refresh_from_env()
    assert v.value == 5


def test_invalid_env_does_not_half_register(fresh_mca, monkeypatch):
    monkeypatch.setenv(ENV_PREFIX + "half_reg", "garbage")
    with pytest.raises(ValueError):
        fresh_mca.register("half_reg", "int", 5)
    assert fresh_mca.lookup("half_reg") is None
    monkeypatch.delenv(ENV_PREFIX + "half_reg")
    assert fresh_mca.register("half_reg", "int", 5).value == 5


def test_apply_cli_skips_readonly(fresh_mca):
    v = fresh_mca.register("ro2", "int", 5, scope=VarScope.READONLY)
    w = fresh_mca.register("rw2", "int", 1)
    fresh_mca.apply_cli([("ro2", "9"), ("rw2", "2")])
    assert v.value == 5 and w.value == 2


def test_readonly_launch_time_override_applies(fresh_mca):
    """CLI/env overrides recorded BEFORE registration are launch-time
    config and legitimately set READONLY vars (reference semantics);
    only post-registration writes are rejected."""
    fresh_mca.apply_cli([("early_ro", "9")])
    v = fresh_mca.register("early_ro", "int", 5, scope=VarScope.READONLY)
    assert v.value == 9
    with pytest.raises(PermissionError):
        fresh_mca.set_value("early_ro", 10)


def test_rejected_set_value_does_not_poison_registry(fresh_mca):
    """A set_value rejected by enum validation must roll back: the
    stored bad override would otherwise make every later get() raise
    (observed as cross-test contamination before the fix)."""
    import pytest

    from ompi_release_tpu.mca import var as mca_var

    mca_var.register("poison_probe", "enum", "a",
                     "rollback probe", choices=("a", "b"))
    mca_var.set_value("poison_probe", "b")
    with pytest.raises(ValueError, match="not in enum"):
        mca_var.set_value("poison_probe", "zz")
    # prior override survives the rejected set
    assert mca_var.get("poison_probe") == "b"
    mca_var.VARS.unset("poison_probe")
    with pytest.raises(ValueError):
        mca_var.set_value("poison_probe", "zz")
    assert mca_var.get("poison_probe") == "a"  # default restored
    # TypeError path (int([1,2])) must roll back too
    mca_var.register("poison_int", "int", 5, "rollback probe 2")
    with pytest.raises((TypeError, ValueError)):
        mca_var.set_value("poison_int", [1, 2])
    assert mca_var.get("poison_int") == 5
