"""Parallel I/O + checkpoint/restart + failure detection tests."""

import os
import time

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import ompi_release_tpu as mpi
from ompi_release_tpu.ft import (
    Checkpointer, ErrMgr, FtTester, Heartbeat, run_with_restart,
    resource_usage,
)
from ompi_release_tpu.ft.sensor import InjectedFault
from ompi_release_tpu.io import File, MODE_CREATE, MODE_RDWR
from ompi_release_tpu.io.sharded import (
    load_pytree, load_sharded, save_pytree, save_sharded,
)
from ompi_release_tpu.utils.errors import MPIError


@pytest.fixture(scope="module")
def world():
    yield mpi.init()


class TestShardedIO:
    def test_roundtrip(self, tmp_path):
        x = np.random.RandomState(0).randn(8, 16, 4).astype(np.float32)
        save_sharded(str(tmp_path), x, name="w")
        y = load_sharded(str(tmp_path), name="w")
        np.testing.assert_array_equal(x, y)
        # one object per shard on disk
        assert len([f for f in os.listdir(tmp_path)
                    if f.endswith(".npy")]) == 8

    def test_async_write(self, tmp_path):
        x = np.ones((4, 1000), np.float32)
        futs = save_sharded(str(tmp_path), x, name="a", async_=True)
        for f in futs:
            f.result()
        np.testing.assert_array_equal(
            load_sharded(str(tmp_path), name="a"), x
        )

    def test_bfloat16_roundtrip(self, tmp_path):
        x = jnp.asarray(np.random.RandomState(1).randn(4, 8),
                        jnp.bfloat16)
        save_sharded(str(tmp_path), x, name="b")
        y = load_sharded(str(tmp_path), name="b")
        assert y.dtype == jnp.bfloat16
        np.testing.assert_array_equal(
            np.asarray(x, np.float32), np.asarray(y, np.float32)
        )

    def test_pytree_roundtrip(self, tmp_path):
        tree = {
            "w": np.random.RandomState(2).randn(4, 3).astype(np.float32),
            "b": np.float32(2.5),  # scalar leaf
            "nested": {"i": np.arange(6, dtype=np.int32)},
        }
        save_pytree(str(tmp_path), tree)
        out = load_pytree(str(tmp_path), tree)
        np.testing.assert_array_equal(out["w"], tree["w"])
        assert float(out["b"]) == 2.5
        np.testing.assert_array_equal(out["nested"]["i"],
                                      tree["nested"]["i"])

    def test_missing_manifest_raises(self, tmp_path):
        with pytest.raises(MPIError):
            load_sharded(str(tmp_path), name="nope")


class TestFileAPI:
    def test_write_read_at_with_view(self, world, tmp_path):
        p = str(tmp_path / "f.bin")
        with File(world, p, MODE_RDWR | MODE_CREATE) as f:
            f.set_view(disp=8, etype=np.float32)
            f.write_at(2, np.array([1.5, 2.5], np.float32))
            out = f.read_at(2, 2)
        np.testing.assert_array_equal(out, [1.5, 2.5])
        assert os.stat(p).st_size == 8 + 4 * 4  # disp + 4 elements

    def test_collective_write_all(self, world, tmp_path):
        p = str(tmp_path / "c.bin")
        n = world.size
        blocks = [np.full(4, r, np.float32) for r in range(n)]
        with File(world, p) as f:
            f.set_view(etype=np.float32)
            f.write_at_all([r * 4 for r in range(n)], blocks)
            whole = f.read_at(0, 4 * n)
        np.testing.assert_array_equal(
            whole.reshape(n, 4), np.stack(blocks)
        )

    def test_shared_pointer_ordered(self, world, tmp_path):
        p = str(tmp_path / "s.bin")
        with File(world, p) as f:
            f.set_view(etype=np.int32)
            f.write_ordered([np.array([r], np.int32)
                             for r in range(world.size)])
            f._shared_ptr = 0
            out = f.read_shared(world.size)
        np.testing.assert_array_equal(out, np.arange(world.size))


class TestFiletypeViews:
    """ROMIO-style file views with holes (``io/romio`` README:3): the
    filetype tiles the file; only its data regions are addressable."""

    def test_vector_view_skips_holes(self, world, tmp_path):
        from ompi_release_tpu.datatype import datatype as dt

        path = str(tmp_path / "v.bin")
        with File(world, path) as f:
            # background pattern so holes are observable
            f.write_at(0, np.full(32, 0xEE, np.uint8))
        with File(world, path) as f:
            ft = dt.create_vector(4, 2, 4, dt.INT32)  # 2 data, 2 hole
            f.set_view(0, np.int32, filetype=ft)
            f.write_at(0, np.arange(8, dtype=np.int32))
            got = f.read_at(0, 8)
            np.testing.assert_array_equal(got,
                                          np.arange(8, dtype=np.int32))
        # raw file: data at int32 positions {0,1, 4,5, 8,9, 12,13}
        raw = np.fromfile(path, np.int32)
        np.testing.assert_array_equal(raw[[0, 1, 4, 5]], [0, 1, 2, 3])
        hole = np.frombuffer(np.asarray(raw[[2, 3]]).tobytes(), np.uint8)
        assert (hole == 0xEE).all()  # holes untouched

    def test_view_spans_multiple_tiles(self, world, tmp_path):
        from ompi_release_tpu.datatype import datatype as dt

        path = str(tmp_path / "t.bin")
        with File(world, path) as f:
            ft = dt.create_vector(2, 1, 2, dt.FLOAT)
            f.set_view(8, np.float32, filetype=ft)
            # 7 elements from view position 3: crosses tile boundaries
            f.write_at(3, np.arange(3, 10, dtype=np.float32))
            got = f.read_at(3, 7)
        np.testing.assert_array_equal(got,
                                      np.arange(3, 10, dtype=np.float32))

    def test_etype_filetype_size_mismatch_raises(self, world, tmp_path):
        from ompi_release_tpu.datatype import datatype as dt

        with File(world, str(tmp_path / "m.bin")) as f:
            ft = dt.create_vector(2, 1, 2, dt.INT64)
            with pytest.raises(MPIError):
                f.set_view(0, np.int32, filetype=ft)


class TestNonblockingIO:
    """MPI_File_iwrite_at/iread_at (+ _all): Requests on the file's
    thread pool; MPI_File_close completes outstanding ops."""

    def test_iwrite_iread_roundtrip(self, world, tmp_path):
        with File(world, str(tmp_path / "nb.bin")) as f:
            f.set_view(0, np.float32)
            wreq = f.iwrite_at(2, np.arange(16, dtype=np.float32))
            st = wreq.wait()
            assert st.count == 16 and wreq.value == 16
            rreq = f.iread_at(2, 16)
            rreq.wait()
            np.testing.assert_array_equal(
                np.asarray(rreq.value), np.arange(16, dtype=np.float32))

    def test_interleaved_view_written_nonblockingly(self, world,
                                                    tmp_path):
        """The two-phase case: two ranks' views interleave element-wise
        (rank 0 writes even int32 slots, rank 1 odd slots), both
        written through iwrite_at, then round-tripped through each
        view AND verified interleaved in the raw file."""
        from ompi_release_tpu.datatype import datatype as dt

        path = str(tmp_path / "ileave.bin")
        n = 8
        ft = dt.create_vector(n, 1, 2, dt.INT32)  # every 2nd slot
        with File(world, path) as f:
            f.set_view(0, np.int32, filetype=ft)          # rank 0 view
            r0 = f.iwrite_at(0, np.arange(n, dtype=np.int32))
            f2 = File(world, path)
            f2.set_view(4, np.int32, filetype=ft)         # rank 1 view
            r1 = f2.iwrite_at(0, 100 + np.arange(n, dtype=np.int32))
            assert r0.wait().count == n
            assert r1.wait().count == n
            # round-trip through each rank's view (nonblocking read)
            rr = f.iread_at(0, n)
            rr.wait()
            np.testing.assert_array_equal(np.asarray(rr.value),
                                          np.arange(n, dtype=np.int32))
            np.testing.assert_array_equal(
                f2.read_at(0, n), 100 + np.arange(n, dtype=np.int32))
            f2.close()
        raw = np.fromfile(path, np.int32)
        np.testing.assert_array_equal(raw[0::2],
                                      np.arange(n, dtype=np.int32))
        np.testing.assert_array_equal(raw[1::2],
                                      100 + np.arange(n, dtype=np.int32))

    def test_iwrite_at_all_collective(self, world, tmp_path):
        n = world.size
        with File(world, str(tmp_path / "call.bin")) as f:
            f.set_view(0, np.int32)
            offsets = [r * 4 for r in range(n)]
            blocks = [np.full(4, r, np.int32) for r in range(n)]
            req = f.iwrite_at_all(offsets, blocks)
            req.wait()
            got = f.read_at(0, 4 * n)
        want = np.repeat(np.arange(n, dtype=np.int32), 4)
        np.testing.assert_array_equal(got, want)

    def test_error_surfaces_at_wait(self, world, tmp_path):
        f = File(world, str(tmp_path / "err.bin"))
        f.set_view(0, np.float32)
        f.close()
        # closed before submit: immediate raise
        with pytest.raises(MPIError):
            f.iwrite_at(0, np.ones(4, np.float32))

    def test_close_completes_outstanding(self, world, tmp_path):
        f = File(world, str(tmp_path / "drain.bin"))
        f.set_view(0, np.uint8)
        reqs = [f.iwrite_at(i * 1000, np.full(1000, i, np.uint8))
                for i in range(8)]
        f.close()  # must drain the pool
        assert os.path.getsize(str(tmp_path / "drain.bin")) == 8000
        for r in reqs:
            assert r.wait().count == 1000


class TestCheckpoint:
    def test_save_restore(self, world, tmp_path):
        ck = Checkpointer(str(tmp_path), comm=world)
        state = {"p": np.random.RandomState(3).randn(4, 4).astype(
            np.float32), "step": np.int32(7)}
        ck.save(7, state, async_=False)
        assert ck.steps() == [7]
        out = ck.restore(state)
        np.testing.assert_array_equal(out["p"], state["p"])
        assert int(out["step"]) == 7

    def test_async_commit_and_gc(self, world, tmp_path):
        ck = Checkpointer(str(tmp_path), keep=2, comm=world)
        s = {"x": np.ones(8, np.float32)}
        for step in (1, 2, 3, 4):
            ck.save(step, {"x": s["x"] * step})
        ck.wait()
        assert ck.steps() == [3, 4]  # keep=2
        out = ck.restore(s, 3)
        np.testing.assert_array_equal(out["x"], np.full(8, 3.0))

    def test_uncommitted_tmp_not_restored(self, world, tmp_path):
        ck = Checkpointer(str(tmp_path), comm=world)
        ck.save(1, {"x": np.ones(2, np.float32)}, async_=False)
        # simulate crash mid-write of step 2: tmp dir, no marker
        os.makedirs(str(tmp_path / "step_0000000002.tmp"))
        assert ck.latest_step() == 1

    def test_quiesce_rejects_posted_recvs(self, world, tmp_path):
        ck = Checkpointer(str(tmp_path), comm=world)
        r = world.irecv(source=0, tag=4242, rank=1)
        with pytest.raises(MPIError):
            ck.save(1, {"x": np.zeros(2, np.float32)})
        r.cancel()
        ck.save(1, {"x": np.zeros(2, np.float32)}, async_=False)


class TestSensors:
    def test_heartbeat_detects_silence(self):
        fired = []
        hb = Heartbeat(interval_s=0.05, miss_limit=2,
                       on_failure=lambda: fired.append(1)).start()
        hb.beat()
        time.sleep(0.3)
        hb.stop()
        assert hb.failed and fired

    def test_heartbeat_stays_alive_with_beats(self):
        hb = Heartbeat(interval_s=0.05, miss_limit=3).start()
        for _ in range(10):
            hb.beat()
            time.sleep(0.02)
        assert not hb.failed
        hb.stop()

    def test_ft_tester_deterministic(self):
        t = FtTester(fail_prob=1.0, seed=0)
        with pytest.raises(InjectedFault):
            t.maybe_fail("here")
        t2 = FtTester(fail_prob=0.0, seed=0)
        for _ in range(100):
            t2.maybe_fail()
        assert t2.injected == 0

    def test_resource_usage(self):
        ru = resource_usage()
        assert ru["rss"] > 0 and ru["vmsize"] >= ru["rss"]


class TestErrMgr:
    def test_handler_registry(self):
        em = ErrMgr()
        seen = []
        em.register(ValueError, lambda e: seen.append(repr(e)))
        assert em.handle(ValueError("x"))
        assert not em.handle(KeyError("y"))
        assert len(seen) == 1

    def test_run_with_restart_recovers(self, world, tmp_path):
        """Fault injection mid-training: training must complete with
        the same result as a fault-free run (deterministic replay)."""
        ck = Checkpointer(str(tmp_path), comm=world)
        tester = FtTester(seed=7)
        fail_at = {13, 27}  # inject at these steps, once each

        def step_fn(step, state):
            if step in fail_at:
                fail_at.discard(step)
                raise InjectedFault(f"boom@{step}")
            return {"acc": state["acc"] + step}

        init = {"acc": np.float32(0.0)}
        final, stats = run_with_restart(
            step_fn, init, num_steps=30, checkpointer=ck,
            checkpoint_every=5,
        )
        assert stats["restarts"] == 2
        assert float(final["acc"]) == float(sum(range(30)))

    def test_run_with_restart_gives_up(self, world, tmp_path):
        ck = Checkpointer(str(tmp_path / "b"), comm=world)

        def always_fail(step, state):
            raise InjectedFault("always")

        with pytest.raises(InjectedFault):
            run_with_restart(
                always_fail, {"x": np.float32(0)}, num_steps=5,
                checkpointer=ck, checkpoint_every=1, max_restarts=2,
            )


class TestFlatLayout:
    def test_flat_shard_count_scales_with_bytes_not_axis0(self, tmp_path):
        """ADVICE r1 (medium): a (4096, 8) leaf must produce a handful
        of size-targeted shards, never one file per row."""
        from ompi_release_tpu.mca import var as mca_var

        x = np.arange(4096 * 8, dtype=np.float32).reshape(4096, 8)
        mca_var.set_value("io_target_shard_bytes", 32 * 1024)
        try:
            save_sharded(str(tmp_path), x, name="flat", layout="flat")
        finally:
            mca_var.VARS.unset("io_target_shard_bytes")
        shards = [f for f in os.listdir(tmp_path)
                  if f.startswith("flat.shard")]
        assert len(shards) == 4  # 128 KiB / 32 KiB
        y = load_sharded(str(tmp_path), name="flat")
        np.testing.assert_array_equal(y, x)

    def test_pytree_uses_flat_layout(self, tmp_path):
        tree = {"embed": np.random.RandomState(0).randn(512, 4)
                .astype(np.float32),
                "scale": np.float32(2.5)}
        save_pytree(str(tmp_path), tree)
        # one shard for the small embed table (well under target), one
        # for the scalar — NOT 512 row files
        shards = [f for f in os.listdir(tmp_path) if ".shard" in f]
        assert len(shards) == 2, shards
        out = load_pytree(str(tmp_path), tree)
        np.testing.assert_array_equal(out["embed"], tree["embed"])
        assert float(out["scale"]) == 2.5


class TestMemchecker:
    """Donated-buffer liveness (memchecker/valgrind analogue,
    memchecker_valgrind_module.c:98-151) — closes the A2
    'no donated-buffer liveness' gap."""

    def test_donating_jit_marks_and_catches_reuse(self):
        import jax
        import jax.numpy as jnp

        from ompi_release_tpu.utils import memchecker
        from ompi_release_tpu.utils.errors import MPIError

        step = memchecker.donating_jit(
            lambda acc, g: acc + g, donate_argnums=(0,),
            owner="grad_accumulate",
        )
        acc = jnp.ones((256, 256), jnp.float32)
        g = jnp.full((256, 256), 2.0, jnp.float32)
        out = step(acc, g)
        np.testing.assert_allclose(np.asarray(out)[0, 0], 3.0)
        if not acc.is_deleted():
            pytest.skip("backend did not donate (no aliasing on this "
                        "platform/config)")
        with pytest.raises(MPIError) as ei:
            memchecker.check(acc)
        assert "grad_accumulate" in str(ei.value)
        # double-donation of a consumed buffer is caught BEFORE dispatch
        with pytest.raises(MPIError):
            step(acc, g)

    def test_assert_all_alive_names_the_leaf(self):
        import jax.numpy as jnp

        from ompi_release_tpu.utils import memchecker
        from ompi_release_tpu.utils.errors import MPIError

        good = {"w": jnp.ones(4), "b": jnp.zeros(2)}
        memchecker.assert_all_alive(good)  # no raise

        class FakeDeleted:
            dtype = np.float32

            def is_deleted(self):
                return True

        memchecker.mark_donated(FakeDeleted(), "optimizer_update")
        bad = {"w": jnp.ones(4), "dead": FakeDeleted()}
        with pytest.raises(MPIError):
            memchecker.assert_all_alive(bad, what="params")

    def test_checkpoint_rejects_donated_state(self, tmp_path):
        import jax.numpy as jnp

        from ompi_release_tpu.ft.checkpoint import Checkpointer
        from ompi_release_tpu.utils import memchecker
        from ompi_release_tpu.utils.errors import MPIError

        step = memchecker.donating_jit(
            lambda x: x * 2, donate_argnums=(0,), owner="train_step",
        )
        x = jnp.ones((128, 128), jnp.float32)
        _ = step(x)
        if not x.is_deleted():
            pytest.skip("backend did not donate")
        ck = Checkpointer(str(tmp_path / "ckpt"))
        with pytest.raises(MPIError) as ei:
            ck.save(1, {"params": x}, async_=False)
        assert "train_step" in str(ei.value)


def test_write_shared_pointer_advances(tmp_path, world):
    """sharedfp non-ordered append: each write lands at the current
    shared pointer and advances it."""
    from ompi_release_tpu.io.file import File

    path = str(tmp_path / "sharedfp.bin")
    with File(world, path) as f:
        f.set_view(0, np.float32)
        assert f.write_shared(np.arange(3, dtype=np.float32)) == 3
        assert f.write_shared(np.full(2, 9.0, np.float32)) == 2
        got = f.read_at(0, 5)
        np.testing.assert_array_equal(got, [0, 1, 2, 9, 9])


def test_donating_jit_pytree_arg_provenance():
    """Pytree donated args: the pre-dispatch liveness check walks the
    LEAVES, so reuse of a consumed state dict raises with provenance."""
    import jax.numpy as jnp

    from ompi_release_tpu.utils import memchecker
    from ompi_release_tpu.utils.errors import MPIError

    step = memchecker.donating_jit(
        lambda st, g: {"w": st["w"] + g}, donate_argnums=(0,),
        owner="tree_step",
    )
    st = {"w": jnp.ones((64, 64), jnp.float32)}
    g = jnp.ones((64, 64), jnp.float32)
    out = step(st, g)
    if not st["w"].is_deleted():
        pytest.skip("backend did not donate")
    with pytest.raises(MPIError) as ei:
        step(st, g)  # consumed pytree caught BEFORE dispatch
    assert "tree_step" in str(ei.value)


class TestCheckpointCli:
    """tpu-checkpoint CLI (orte-checkpoint/orte-restart tool role)."""

    def _make(self, tmp_path, steps=(3, 7)):
        import jax.numpy as jnp

        from ompi_release_tpu.ft.checkpoint import Checkpointer

        ck = Checkpointer(str(tmp_path), keep=0)
        state = {"w": jnp.arange(1000, dtype=jnp.float32),
                 "b": jnp.ones((4,), jnp.float32)}
        for s in steps:
            ck.save(s, state, async_=False, extra_meta={"loss": 1.0 / s})
        return ck

    def test_list_show_verify_gc(self, tmp_path, capsys):
        from ompi_release_tpu.tools import tpu_checkpoint as cli

        self._make(tmp_path)
        assert cli.main(["list", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "step        3" in out and "step        7" in out
        assert cli.main(["show", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert '"step": 7' in out
        assert cli.main(["verify", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "verified OK" in out
        assert cli.main(["gc", str(tmp_path), "--keep", "1"]) == 0
        out = capsys.readouterr().out
        assert "removed step 3" in out
        assert cli.main(["list", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "step        3" not in out

    def test_verify_detects_corruption(self, tmp_path, capsys):
        import glob
        import os

        from ompi_release_tpu.tools import tpu_checkpoint as cli

        self._make(tmp_path, steps=(1,))
        shards = glob.glob(str(tmp_path / "step_*" / "leaf0000*"))
        data_files = [p for p in shards if not p.endswith(".json")]
        assert data_files
        with open(data_files[0], "r+b") as f:
            f.seek(16)
            byte = f.read(1)
            f.seek(16)
            f.write(bytes([byte[0] ^ 0xFF]))
        assert cli.main(["verify", str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "FAILED" in out or "corrupt" in out
