"""tpurun launcher tests — the orterun/orted system-test analogue.

Real multi-process jobs over localhost: wire-up through the OOB
coordinator during MPI init, stdio forwarding, exit-code aggregation,
and failure detection (abnormal exit + heartbeat loss) driving the job
state machine into the error states (``plm_types.h:113-151``).
"""

import os
import subprocess
import sys
import textwrap

import pytest

from ompi_release_tpu.runtime.state import JobState, ProcState
from ompi_release_tpu.tools.tpurun import Job

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

APP_PRELUDE = textwrap.dedent("""
    import os, sys
    sys.path.insert(0, %r)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import ompi_release_tpu as mpi
    from ompi_release_tpu.runtime.runtime import Runtime
""" % REPO)


def _write_app(tmp_path, body, name="app.py"):
    p = tmp_path / name
    p.write_text(APP_PRELUDE + textwrap.dedent(body))
    return str(p)


class TestEndToEnd:
    def test_four_process_job(self, tmp_path, capfd):
        """tpurun -n 4: every worker inits through the coordinator,
        sees the right identity, and exits 0."""
        app = _write_app(tmp_path, """
            world = mpi.init()
            rt = Runtime.current()
            pi = rt.bootstrap["process_index"]
            pc = rt.bootstrap["process_count"]
            peers = rt.bootstrap["peer_cards"]
            assert pc == 4 and 0 <= pi < 4
            assert len(peers) == 4
            assert peers[pi]["pid"] == os.getpid()
            print(f"hello from {pi}/{pc}")
            mpi.finalize()
        """)
        job = Job(4, [sys.executable, app], [], heartbeat_s=0.3)
        rc = job.run(timeout_s=120)
        out = capfd.readouterr().out
        assert rc == 0, out
        for r in range(4):
            assert f"[rank {r}] hello from {r}/4" in out
        assert job.job_state.visited(JobState.RUNNING)
        assert job.job_state.visited(JobState.TERMINATED)
        assert all(s == ProcState.TERMINATED
                   for s in job.proc_state.values())

    def test_xcast_reaches_all_workers(self, tmp_path, capfd):
        """An HNP tree xcast after wire-up reaches every worker via
        binomial relay (grpcomm xcast, not a star loop)."""
        app = _write_app(tmp_path, """
            world = mpi.init()
            rt = Runtime.current()
            payload = rt.agent.recv_xcast(timeout_ms=30000)
            print("got:" + payload.decode())
            mpi.finalize()
        """)
        job = Job(5, [sys.executable, app], [], heartbeat_s=0.3)

        # inject the xcast once the job reports RUNNING
        import threading

        def cast_when_running():
            import time

            for _ in range(600):
                if job.job_state.visited(JobState.RUNNING):
                    job.hnp.xcast(b"tree-payload")
                    return
                time.sleep(0.05)

        t = threading.Thread(target=cast_when_running, daemon=True)
        t.start()
        rc = job.run(timeout_s=120)
        out = capfd.readouterr().out
        assert rc == 0, out
        assert out.count("got:tree-payload") == 5

    def test_mca_vars_propagate(self, tmp_path, capfd):
        app = _write_app(tmp_path, """
            world = mpi.init()
            _ = world.pml   # registers the pml vars (env applies then)
            from ompi_release_tpu.mca import var as mca_var
            print("val=" + str(mca_var.get("pml_eager_limit", 0)))
            mpi.finalize()
        """)
        job = Job(2, [sys.executable, app],
                  [("pml_eager_limit", "12345")], heartbeat_s=0.3)
        rc = job.run(timeout_s=120)
        out = capfd.readouterr().out
        assert rc == 0, out
        assert out.count("val=12345") == 2


class TestPubsub:
    def test_publish_lookup_inside_job(self, tmp_path, capfd):
        """MPI_Publish_name/Lookup_name inside a live tpurun job: the
        launcher's HNP serves the name table (orte-server role), so
        one worker's publish is visible to the others' lookups —
        including a lookup issued BEFORE the publish (parked)."""
        app = _write_app(tmp_path, """
            world = mpi.init()
            rt = Runtime.current()
            pi = rt.bootstrap["process_index"]
            if pi == 0:
                import time
                time.sleep(0.4)  # let the others' lookups park first
                rt.agent.publish_name("job-svc", "tpu-port:7")
                port = rt.agent.lookup_name("job-svc")
            else:
                port = rt.agent.lookup_name("job-svc", timeout_ms=20000)
            print("found:" + port)
            mpi.finalize()
        """)
        job = Job(3, [sys.executable, app], [], heartbeat_s=0.3)
        rc = job.run(timeout_s=120)
        out = capfd.readouterr().out
        assert rc == 0, out
        assert out.count("found:tpu-port:7") == 3


class TestPubsubPublicApi:
    def test_comm_publish_lookup_bridges_to_hnp(self, tmp_path, capfd):
        """The PUBLIC comm.publish_name/lookup_name API must reach the
        JOB-global name table under tpurun (not each process's local
        dict, which no other worker can see)."""
        app = _write_app(tmp_path, """
            from ompi_release_tpu.comm import publish_name, lookup_name
            world = mpi.init()
            rt = Runtime.current()
            pi = rt.bootstrap["process_index"]
            if pi == 0:
                publish_name("pub-api-svc", "tpu-port:5")
            port = lookup_name("pub-api-svc", timeout_s=20)
            print("found:" + port)
            mpi.finalize()
        """)
        job = Job(2, [sys.executable, app], [], heartbeat_s=0.3)
        rc = job.run(timeout_s=120)
        out = capfd.readouterr().out
        assert rc == 0, out
        assert out.count("found:tpu-port:5") == 2


class TestFailureDetection:
    def test_tpu_ps_snapshots_live_job(self, tmp_path, capfd):
        """tpu-ps against a LIVE job: session-dir discovery finds the
        contact file, the HNP's TAG_PS responder returns per-rank
        pid/state/rss/vmsize piggybacked from heartbeats, and the
        rendered table carries them (orte-ps + sensor_resusage)."""
        import threading
        import time as _time

        from ompi_release_tpu.tools import tpu_ps

        app = _write_app(tmp_path, """
            import time
            world = mpi.init()
            time.sleep(2.5)   # stay alive across several beats
            mpi.finalize()
        """)
        job = Job(2, [sys.executable, app], [], heartbeat_s=0.3)
        results = {}

        def probe_when_running():
            for _ in range(600):
                if job.job_state.visited(JobState.RUNNING):
                    break
                _time.sleep(0.05)
            _time.sleep(1.0)  # let a resusage-bearing beat land
            jobs = tpu_ps.discover_jobs()
            results["discovered"] = [
                j for j in jobs if j["pid"] == os.getpid()
            ]
            client = tpu_ps.PsClient("127.0.0.1", job.hnp.port)
            try:
                results["snap"] = client.query()
            finally:
                client.close()

        t = threading.Thread(target=probe_when_running, daemon=True)
        t.start()
        rc = job.run(timeout_s=120)
        t.join(timeout=10)
        assert rc == 0
        # discovery: this launcher's contact file was found and live
        assert results.get("discovered"), results
        assert results["discovered"][0]["n"] == 2
        snap = results.get("snap")
        assert snap and snap["num_workers"] == 2, snap
        for nid in ("1", "2"):
            w = snap["workers"][nid]
            assert w["pid"] > 0          # piggybacked sample arrived
            assert w["rss"] > 0 and w["vmsize"] > 0
            assert w["beat_age_s"] is not None
            assert snap["proc_states"][nid] == "RUNNING"
        # rendering includes rank rows with byte-formatted columns
        text = tpu_ps.render_job(results["discovered"][0], snap)
        assert "rank" in text and "RUNNING" in text
        # contact file removed after the job ends
        assert not [j for j in tpu_ps.discover_jobs()
                    if j["pid"] == os.getpid()]

    def test_resilient_restart_resumes_from_checkpoint(self, tmp_path,
                                                       capfd):
        """rmaps/resilient + errmgr recovery: a worker KILLED mid-job
        is respawned on a surviving slot (same rank identity, fresh
        wire-up through the rejoin service) and resumes from its last
        committed checkpoint; the job completes rc=0."""
        ckdir = tmp_path / "ck"
        ckdir.mkdir()
        app = _write_app(tmp_path, """
            import os, signal
            from ompi_release_tpu.ft import Checkpointer
            world = mpi.init()
            rt = Runtime.current()
            pi = rt.bootstrap["process_index"]
            ck = Checkpointer(%r + f"/rank{pi}", comm=world)
            state = {"step": jax.numpy.zeros((), jax.numpy.int32)}
            latest = ck.latest_step()
            restored = latest is not None
            start = 0
            if restored:
                state = ck.restore(state, step=latest)
                start = int(state["step"])
                print(f"RESUMED {pi} from {start}")
            for step in range(start, 10):
                state["step"] = jax.numpy.asarray(step + 1)
                if step == 4 and not restored:
                    ck.save(step + 1, state)
                    ck.wait()
                    if pi == 1:
                        os.kill(os.getpid(), signal.SIGKILL)
            print(f"DONE {pi} step=10")
            mpi.finalize()
        """ % str(ckdir))
        job = Job(2, [sys.executable, app], [], heartbeat_s=0.3,
                  on_failure="restart", max_restarts=2)
        rc = job.run(timeout_s=120)
        out = capfd.readouterr().out
        assert rc == 0, out
        assert "RESUMED 1 from 5" in out
        assert "DONE 0 step=10" in out and "DONE 1 step=10" in out
        assert job._restarts.get(2) == 1  # exactly one respawn, rank 1
        assert not job.job_state.visited(JobState.ABORTED)
        assert job.job_state.visited(JobState.TERMINATED)

    def test_restart_budget_exhaustion_aborts(self, tmp_path, capfd):
        """A rank that keeps dying exhausts max_restarts and the job
        aborts (the resilient policy never loops forever)."""
        app = _write_app(tmp_path, """
            import os, signal
            world = mpi.init()
            rt = Runtime.current()
            if rt.bootstrap["process_index"] == 0:
                os.kill(os.getpid(), signal.SIGKILL)
            import time
            time.sleep(30)
        """)
        job = Job(2, [sys.executable, app], [], heartbeat_s=0.3,
                  on_failure="restart", max_restarts=1)
        rc = job.run(timeout_s=60)
        assert rc != 0
        assert job._restarts.get(1) == 1
        assert job.job_state.visited(JobState.ABORTED)

    def test_abnormal_exit_aborts_job(self, tmp_path, capfd):
        """One worker exits 3 mid-job: the job reaches ABORTED, the
        others are torn down, exit code propagates."""
        app = _write_app(tmp_path, """
            import time
            world = mpi.init()
            pi = Runtime.current().bootstrap["process_index"]
            if pi == 1:
                time.sleep(0.5)
                os._exit(3)
            time.sleep(600)   # would hang forever without teardown
        """)
        job = Job(3, [sys.executable, app], [], heartbeat_s=0.3)
        rc = job.run(timeout_s=120)
        assert rc == 3
        assert job.job_state.visited(JobState.ABORTED)
        assert job.proc_state[2] == ProcState.ABORTED  # node 2 = rank 1

    def test_heartbeat_loss_detected(self, tmp_path, capfd):
        """A worker that stops beating (but stays alive) is detected by
        the HNP monitor: HEARTBEAT_FAILED -> job ABORTED -> teardown
        (sensor_heartbeat.c:61,78 + errmgr policy)."""
        app = _write_app(tmp_path, """
            import time
            world = mpi.init()
            rt = Runtime.current()
            pi = rt.bootstrap["process_index"]
            if pi == 0:
                rt.agent.stop_heartbeats()   # go silent, stay alive
            time.sleep(600)
        """)
        job = Job(2, [sys.executable, app], [],
                  heartbeat_s=0.3, miss_limit=3)
        rc = job.run(timeout_s=120)
        assert rc != 0
        assert job.job_state.visited(JobState.ABORTED)
        assert job.proc_state[1] == ProcState.HEARTBEAT_FAILED

    def test_worker_crash_before_wireup(self, tmp_path, capfd):
        """A worker dying before the modex completes fails the start
        (FAILED_TO_START or ABORTED, never a hang)."""
        app = _write_app(tmp_path, """
            pi = int(os.environ["OMPITPU_NODE_ID"])
            if pi == 2:
                os._exit(7)
            world = mpi.init()
            import time; time.sleep(600)
        """)
        job = Job(2, [sys.executable, app], [], heartbeat_s=0.3)
        rc = job.run(timeout_s=120)
        assert rc == 7
        assert (job.job_state.visited(JobState.ABORTED)
                or job.job_state.visited(JobState.FAILED_TO_START))


class TestCli:
    def test_module_cli(self, tmp_path):
        """python -m ompi_release_tpu.tools.tpurun -n 2 ... end to end."""
        app = _write_app(tmp_path, """
            world = mpi.init()
            print("cli-ok", Runtime.current().bootstrap["process_index"])
            mpi.finalize()
        """)
        env = dict(os.environ)
        env["PYTHONPATH"] = REPO
        r = subprocess.run(
            [sys.executable, "-m", "ompi_release_tpu.tools.tpurun",
             "-n", "2", "--timeout", "120", sys.executable, app],
            capture_output=True, text=True, env=env, timeout=180,
        )
        assert r.returncode == 0, r.stderr
        assert "[rank 0] cli-ok 0" in r.stdout
        assert "[rank 1] cli-ok 1" in r.stdout


class TestMultiHost:
    """Multi-host launch (VERDICT r2 #4): rmaps-lite rank->host
    mapping, rsh launch path, real addresses in modex cards."""

    def test_hostfile_and_map_policies(self, tmp_path):
        from ompi_release_tpu.tools.tpurun import (
            HostSpec, map_ranks, parse_host_list, parse_hostfile,
        )

        hf = tmp_path / "hosts"
        hf.write_text("# allocation\nnodeA slots=2\nnodeB slots=3\n")
        hosts = parse_hostfile(str(hf))
        assert [(h.name, h.slots) for h in hosts] == [
            ("nodeA", 2), ("nodeB", 3)]
        assert [(h.name, h.slots) for h in parse_host_list("x:2,y")] == [
            ("x", 2), ("y", 1)]
        # by-slot: fill nodeA before nodeB (rmaps_rr byslot)
        names = [h.name for h in map_ranks(hosts, 4, "slot")]
        assert names == ["nodeA", "nodeA", "nodeB", "nodeB"]
        # by-node: round robin one per host per pass
        names = [h.name for h in map_ranks(hosts, 4, "node")]
        assert names == ["nodeA", "nodeB", "nodeA", "nodeB"]
        # third pass only nodeB has a slot left
        names = [h.name for h in map_ranks(hosts, 5, "node")]
        assert names == ["nodeA", "nodeB", "nodeA", "nodeB", "nodeB"]
        import pytest as _pytest

        from ompi_release_tpu.utils.errors import MPIError

        with _pytest.raises(MPIError):
            map_ranks(hosts, 6, "slot")  # oversubscription rejected

    def test_ppr_and_seq_mappers(self, tmp_path):
        """rmaps/ppr and rmaps/seq analogues: exact N per node in
        allocation order; one rank per allocation LINE."""
        import pytest as _pytest

        from ompi_release_tpu.tools.tpurun import map_ranks, parse_hostfile
        from ompi_release_tpu.utils.errors import MPIError

        hf = tmp_path / "hosts"
        hf.write_text("nodeA slots=4\nnodeB slots=4\nnodeC slots=4\n")
        hosts = parse_hostfile(str(hf))
        names = [h.name for h in map_ranks(hosts, 5, "ppr:2:node")]
        assert names == ["nodeA", "nodeA", "nodeB", "nodeB", "nodeC"]
        with _pytest.raises(MPIError, match="places only"):
            map_ranks(hosts, 7, "ppr:2:node")  # 2*3 hosts < 7
        with _pytest.raises(MPIError, match="exceeds"):
            map_ranks(hosts, 4, "ppr:5:node")  # > slots, no oversub
        with _pytest.raises(MPIError, match="ppr"):
            map_ranks(hosts, 2, "ppr:2:socket")  # only :node exists

        # seq: file ORDER, duplicates allowed, slots ignored
        sf = tmp_path / "seqhosts"
        sf.write_text("nodeB\nnodeA\nnodeB\n")
        seq_hosts = parse_hostfile(str(sf))
        names = [h.name for h in map_ranks(seq_hosts, 3, "seq")]
        assert names == ["nodeB", "nodeA", "nodeB"]
        with _pytest.raises(MPIError, match="allocation lines"):
            map_ranks(seq_hosts, 4, "seq")

    def test_rankfile_mapping(self, tmp_path):
        """rmaps/rank_file analogue: explicit placement wins over the
        policy mapper, with full-coverage and allocation checks."""
        import pytest as _pytest

        from ompi_release_tpu.tools.tpurun import (
            HostSpec, Job, parse_rankfile,
        )
        from ompi_release_tpu.utils.errors import MPIError

        alloc = [HostSpec("nodeA", 2), HostSpec("nodeB", 2)]
        rf = tmp_path / "ranks"
        rf.write_text(
            "# explicit placement\n"
            "rank 0=nodeB slot=0\n"
            "rank 2=nodeA\n"
            "rank 1=nodeB slot=1\n"
        )
        names = [h.name for h in parse_rankfile(str(rf), 3, alloc)]
        assert names == ["nodeB", "nodeB", "nodeA"]

        # Job honors the rankfile over --map-by
        job = Job(3, ["true"], [], hosts=alloc, map_by="slot",
                  rankfile=str(rf))
        assert [h.name for h in job.rank_hosts] == \
            ["nodeB", "nodeB", "nodeA"]

        rf.write_text("rank 0=nodeA\n")  # rank 1 unmapped
        with _pytest.raises(MPIError, match="unmapped"):
            parse_rankfile(str(rf), 2, alloc)
        rf.write_text("rank 0=nodeA\nrank 0=nodeB\nrank 1=nodeA\n")
        with _pytest.raises(MPIError, match="twice"):
            parse_rankfile(str(rf), 2, alloc)
        rf.write_text("rank 0=nodeZ\nrank 1=nodeA\n")
        with _pytest.raises(MPIError, match="not in"):
            parse_rankfile(str(rf), 2, alloc)
        rf.write_text("rank 0=nodeA\nrank 1=nodeA\nrank 2=nodeA\n")
        with _pytest.raises(MPIError, match="exceed"):
            parse_rankfile(str(rf), 3, alloc)  # 3 ranks, 2 slots
        rf.write_text("rank 0=nodeA slot=7\nrank 1=nodeB\n")
        with _pytest.raises(MPIError, match="slot 7"):
            parse_rankfile(str(rf), 2, alloc)
        rf.write_text("banana\n")
        with _pytest.raises(MPIError, match="unparseable"):
            parse_rankfile(str(rf), 1, alloc)
        # no allocation: named hosts form their own — and the Job's
        # allocation (self.hosts) must be rebuilt from them so the
        # remapper/migrator host-load bookkeeping (keyed by identity
        # over self.hosts) covers every placed rank
        rf.write_text("rank 0=alpha\nrank 1=alpha\n")
        names = [h.name for h in parse_rankfile(str(rf), 2, None)]
        assert names == ["alpha", "alpha"]
        job2 = Job(2, ["true"], [], rankfile=str(rf))
        assert [(h.name, h.slots) for h in job2.hosts] == [("alpha", 2)]
        assert all(h is job2.hosts[0] for h in job2.rank_hosts)

    def test_fake_ssh_two_host_job(self, tmp_path, capfd):
        """End-to-end 2-'host' job through the rsh launch path: a fake
        ssh agent records each target host then execs locally (the
        standard clusterless PLM test), the OMPITPU_* contract rides
        the remote command line, and every rank wires up + exits 0."""
        log = tmp_path / "ssh_targets.log"
        agent = tmp_path / "fakessh"
        # faithful ssh fake: join the args into ONE string and give it
        # to a shell, exactly like real ssh hands the remote command
        # line to the login shell (this is what makes the launcher's
        # shlex quoting load-bearing rather than untested)
        agent.write_text(
            "#!/bin/sh\n"
            f'echo "$1" >> {log}\n'
            "shift\n"
            'exec sh -c "$*"\n'
        )
        agent.chmod(0o755)
        app = _write_app(tmp_path, """
            world = mpi.init()
            rt = Runtime.current()
            pi = rt.bootstrap["process_index"]
            print(f"host={os.environ['OMPITPU_HOST']} rank={pi}")
            print("mca=" + os.environ["OMPITPU_MCA_quoting_probe"])
            mpi.finalize()
        """)
        from ompi_release_tpu.tools.tpurun import HostSpec

        # the mca value carries spaces and shell metachars: it must
        # survive the ssh join + remote-shell re-parse intact
        job = Job(
            4, [sys.executable, app],
            [("quoting_probe", "two words; $(rm -rf /) `x`")],
            heartbeat_s=0.3,
            hosts=[HostSpec("nodeA", 2), HostSpec("nodeB", 2)],
            launch_agent=str(agent),
        )
        rc = job.run(timeout_s=120)
        out = capfd.readouterr().out
        assert rc == 0, out
        targets = sorted(log.read_text().split())
        assert targets == ["nodeA", "nodeA", "nodeB", "nodeB"]
        assert "host=nodeA rank=0" in out
        assert "host=nodeB rank=2" in out
        assert out.count("mca=two words; $(rm -rf /) `x`") == 4
        assert job.job_state.visited(JobState.TERMINATED)

    def test_nonloopback_wireup_and_card_addresses(self):
        """Distinct listen interface: the HNP binds 0.0.0.0, the
        worker dials the machine's real (non-loopback) address, and
        its modex card advertises that address — not 127.0.0.1."""
        from ompi_release_tpu.runtime.coordinator import (
            HnpCoordinator, WorkerAgent, local_addr_toward,
        )

        ip = local_addr_toward("192.0.2.1")  # TEST-NET: no packet sent
        if ip.startswith("127."):
            pytest.skip("no non-loopback interface available")
        import threading

        hnp = HnpCoordinator(2, bind_addr="0.0.0.0")
        agent = None
        try:
            t = threading.Thread(target=lambda: hnp.run_modex(None))
            t.start()
            agent = WorkerAgent(1, ip, hnp.port)
            worker_cards = agent.run_modex({"pid": os.getpid()})
            t.join(timeout=10)
            assert worker_cards[0]["oob_host"] == ip
            assert not worker_cards[0]["oob_host"].startswith("127.")
        finally:
            if agent is not None:
                agent.close()
            hnp.shutdown()


class TestMigration:
    """tpu-migrate (orte-migrate analogue): proactively evacuate a
    host of a live job through the HNP's TAG_MIGRATE responder."""

    def test_migrate_off_host_resumes_elsewhere(self, tmp_path, capfd):
        """A 2-'host' fake-ssh job is asked to evacuate nodeB: the
        rank there is terminated, remapped to nodeA (which stays
        excluded for later respawns), respawned, and resumes from its
        last committed checkpoint; the job completes rc=0 and the
        failure-restart budget is untouched."""
        import threading
        import time as _time

        from ompi_release_tpu.tools.tpu_migrate import request_migration
        from ompi_release_tpu.tools.tpurun import HostSpec

        log = tmp_path / "ssh_targets.log"
        agent = tmp_path / "fakessh"
        agent.write_text(
            "#!/bin/sh\n"
            f'echo "$1" >> {log}\n'
            "shift\n"
            'exec sh -c "$*"\n'
        )
        agent.chmod(0o755)
        ckdir = tmp_path / "ck"
        ckdir.mkdir()
        app = _write_app(tmp_path, """
            import time
            from ompi_release_tpu.ft import Checkpointer
            world = mpi.init()
            rt = Runtime.current()
            pi = rt.bootstrap["process_index"]
            ck = Checkpointer(%r + f"/rank{pi}", comm=world)
            state = {"step": jax.numpy.zeros((), jax.numpy.int32)}
            latest = ck.latest_step()
            start = 0
            if latest is not None:
                state = ck.restore(state, step=latest)
                start = int(state["step"])
                print(f"RESUMED {pi} from {start}", flush=True)
            for step in range(start, 16):
                state["step"] = jax.numpy.asarray(step + 1)
                ck.save(step + 1, state)
                ck.wait()
                time.sleep(0.25)
            print(f"DONE {pi}", flush=True)
            mpi.finalize()
        """ % str(ckdir))
        job = Job(2, [sys.executable, app], [], heartbeat_s=0.3,
                  hosts=[HostSpec("nodeA", 2), HostSpec("nodeB", 2)],
                  map_by="node", launch_agent=str(agent),
                  on_failure="restart", max_restarts=2)
        results = {}

        def migrate_when_running():
            for _ in range(600):
                if job.job_state.visited(JobState.RUNNING):
                    break
                _time.sleep(0.05)
            _time.sleep(1.2)  # let the app commit a few checkpoints
            results["reply"] = request_migration(
                "127.0.0.1", job.hnp.port, "nodeB")

        t = threading.Thread(target=migrate_when_running, daemon=True)
        t.start()
        rc = job.run(timeout_s=120)
        t.join(timeout=10)
        out = capfd.readouterr().out
        assert rc == 0, out
        reply = results.get("reply")
        assert reply and reply.get("ok"), (reply, out)
        assert reply["ranks"] == [1]
        # rank 1 now lives on nodeA; nodeB stays excluded
        assert job.rank_hosts[1].name == "nodeA"
        assert "nodeB" in job._excluded_hosts
        # the moved app resumed from a committed step and finished —
        # and the OLD incarnation actually died (TAG_DIE through the
        # control plane: killing only the local fake-ssh client would
        # orphan it to run to completion, printing DONE 1 twice)
        assert "RESUMED 1 from" in out
        assert "DONE 0" in out and "DONE 1" in out
        assert out.count("DONE 1") == 1, out
        assert out.count("RESUMED 1") == 1, out
        # an operator move is not a failure: budget untouched
        assert not job._restarts.get(2)
        assert not job.job_state.visited(JobState.ABORTED)
        assert job.job_state.visited(JobState.TERMINATED)
        # the respawn actually went through the launch agent to nodeA
        targets = log.read_text().split()
        assert targets.count("nodeA") == 2 and targets.count("nodeB") == 1

    def test_migrate_refused_without_capacity(self, tmp_path, capfd):
        """Evacuating the only host with free slots is refused whole —
        no rank is killed on a request that cannot complete."""
        import threading
        import time as _time

        from ompi_release_tpu.tools.tpu_migrate import request_migration

        app = _write_app(tmp_path, """
            import time
            world = mpi.init()
            time.sleep(3.0)
            mpi.finalize()
        """)
        # default single-host allocation: localhost with exactly n slots
        job = Job(2, [sys.executable, app], [], heartbeat_s=0.3,
                  on_failure="restart")
        results = {}

        def probe():
            for _ in range(600):
                if job.job_state.visited(JobState.RUNNING):
                    break
                _time.sleep(0.05)
            results["reply"] = request_migration(
                "127.0.0.1", job.hnp.port, "localhost")
            results["bogus"] = request_migration(
                "127.0.0.1", job.hnp.port, "no-such-host")

        t = threading.Thread(target=probe, daemon=True)
        t.start()
        rc = job.run(timeout_s=60)
        t.join(timeout=10)
        assert rc == 0
        reply = results.get("reply")
        assert reply and not reply.get("ok")
        assert "cannot evacuate" in reply["error"]
        assert "localhost" not in job._excluded_hosts  # rolled back
        bogus = results.get("bogus")
        assert bogus and not bogus.get("ok")
        assert "no ranks mapped" in bogus["error"]


class TestCommSpawn:
    def test_spawn_exchange_and_wait(self, tmp_path, capfd):
        """MPI_Comm_spawn analogue: parent launches 2 children, sends
        each a tagged frame over the job OOB, receives replies, and
        joins a clean exit."""
        from ompi_release_tpu.comm import comm_spawn
        from ompi_release_tpu.utils.errors import MPIError

        app = _write_app(tmp_path, """
            world = mpi.init()
            rt = Runtime.current()
            pi = rt.bootstrap["process_index"]
            src, tag, payload = rt.agent.ep.recv(tag=101,
                                                 timeout_ms=30000)
            rt.agent.ep.send(0, 102,
                             payload + f"+child{pi}".encode())
            mpi.finalize()
        """)
        job = comm_spawn([sys.executable, app], 2, timeout_s=120)
        assert job.remote_size == 2
        # wait for wire-up before messaging (children recv after init)
        from ompi_release_tpu.runtime.state import JobState as JS
        import time
        for _ in range(600):
            if job.job.job_state.visited(JS.RUNNING):
                break
            time.sleep(0.05)
        job.send(0, 101, b"hello")
        job.send(1, 101, b"hello")
        replies = {}
        for _ in range(2):
            rank, payload = job.recv(102, timeout_ms=30000)
            replies[rank] = payload
        assert replies == {0: b"hello+child0", 1: b"hello+child1"}
        assert job.wait(timeout_s=60) == 0
        with pytest.raises(MPIError):
            job.send(5, 101, b"x")
        with pytest.raises(MPIError):
            job.send(0, 3, b"x")  # control-plane tags protected

    def test_messaging_after_job_end_errors_cleanly(self, tmp_path,
                                                    capfd):
        """Late send/recv on a finished spawn must raise ERR_SPAWN —
        this used to SEGFAULT (NULL native handle after shutdown)."""
        from ompi_release_tpu.comm import comm_spawn
        from ompi_release_tpu.utils.errors import MPIError

        app = _write_app(tmp_path, """
            world = mpi.init()
            mpi.finalize()
        """)
        job = comm_spawn([sys.executable, app], 1, timeout_s=120)
        assert job.wait(timeout_s=60) == 0
        with pytest.raises(MPIError):
            job.send(0, 101, b"late")
        with pytest.raises(MPIError):
            job.recv(102, timeout_ms=100)
