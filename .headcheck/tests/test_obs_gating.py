"""Static hot-path observability discipline for the new coll engines
and the wire transport.

``coll/pipeline.py``, ``coll/fusion.py``, and ``runtime/wire.py`` sit
on hot paths (the wire router is EVERY cross-process byte); PR 1's
contract is that observability costs ONE attribute check
(``_obs.enabled``) when off. This test enforces it statically, without
importing jax: every emit site (journal ``record``, skew
``begin/body/end``, per-call pvar registry lookups) must be gated on
``_obs.enabled``, and every pvar bump (``.add``/``.observe``) must
target a MODULE-LEVEL pre-registered pvar (the zero-cost-counter
class the driver already uses) or itself be gated.
``btl/components.py`` carries wire pvars but no journal emits, so it
is checked for gating violations only.

Gating shapes recognized:

- ``if _obs.enabled: <emit>``   (including ``and``-compounds)
- ``if not _obs.enabled: return`` followed by the emit (early-return)
"""

import ast
import os

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CHECKED = ("ompi_release_tpu/coll/pipeline.py",
           "ompi_release_tpu/coll/fusion.py",
           "ompi_release_tpu/runtime/wire.py")
#: gating violations checked, but no journal-emit-site requirement
#: (module-level wire pvars only — no _obs import)
PVAR_ONLY = ("ompi_release_tpu/btl/components.py",)

#: attribute calls that ARE emit sites when ungated
EMIT_ATTRS = {"record", "begin", "body", "end"}
#: per-call pvar registry lookups (allocate/lock per call — never on
#: an ungated hot path; module scope is where registration belongs)
REGISTRY_ATTRS = {"counter", "aggregate", "histogram", "timer",
                  "highwatermark"}
#: bumps allowed ungated ONLY on module-level pvars
BUMP_ATTRS = {"add", "observe"}


def _mentions_enabled(node) -> bool:
    return any(
        (isinstance(n, ast.Attribute) and n.attr == "enabled")
        or (isinstance(n, ast.Name) and n.id == "enabled")
        for n in ast.walk(node)
    )


def _terminates(stmts) -> bool:
    return bool(stmts) and isinstance(
        stmts[-1], (ast.Return, ast.Raise, ast.Continue, ast.Break))


def _module_pvars(tree) -> set:
    """Names bound at module level to pvar registrations."""
    out = set()
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign) and isinstance(stmt.value, ast.Call):
            f = stmt.value.func
            attr = f.attr if isinstance(f, ast.Attribute) else \
                (f.id if isinstance(f, ast.Name) else None)
            if attr in REGISTRY_ATTRS:
                out.update(t.id for t in stmt.targets
                           if isinstance(t, ast.Name))
    return out


def _check_calls(node, gated, pvars, violations, path):
    """Check every Call in an expression subtree (no statements here)."""
    for n in ast.walk(node):
        if not isinstance(n, ast.Call):
            continue
        f = n.func
        if not isinstance(f, ast.Attribute):
            continue
        where = f"{path}:{n.lineno}"
        if f.attr in EMIT_ATTRS and not gated:
            # record/begin/body/end on obs-ish receivers; skip
            # unrelated receivers (e.g. dict methods named the same)
            base = f.value
            base_name = (base.id if isinstance(base, ast.Name) else
                         base.attr if isinstance(base, ast.Attribute)
                         else "")
            if any(t in base_name for t in ("obs", "skew", "journal",
                                            "JOURNAL")):
                violations.append(
                    f"{where}: ungated emit {base_name}.{f.attr}()")
        if f.attr in REGISTRY_ATTRS and not gated:
            base = f.value
            if isinstance(base, ast.Name) and base.id in ("pvar",
                                                          "_pvar"):
                violations.append(
                    f"{where}: per-call pvar registry lookup "
                    f"{base.id}.{f.attr}() on the hot path")
        if f.attr in BUMP_ATTRS and not gated:
            base = f.value
            if isinstance(base, ast.Name) and base.id not in pvars:
                violations.append(
                    f"{where}: {base.id}.{f.attr}() bumps a "
                    f"non-module-level pvar ungated")


def _scan_stmts(stmts, gated, pvars, violations, path):
    for stmt in stmts:
        if isinstance(stmt, ast.If) and _mentions_enabled(stmt.test):
            neg = (isinstance(stmt.test, ast.UnaryOp)
                   and isinstance(stmt.test.op, ast.Not))
            _check_calls(stmt.test, gated, pvars, violations, path)
            if neg:
                _scan_stmts(stmt.body, gated, pvars, violations, path)
                _scan_stmts(stmt.orelse, True, pvars, violations, path)
                if _terminates(stmt.body):
                    gated = True  # `if not enabled: return` early-out
            else:
                _scan_stmts(stmt.body, True, pvars, violations, path)
                _scan_stmts(stmt.orelse, gated, pvars, violations, path)
            continue
        # other statements: recurse into child statement lists with the
        # same gating, check the non-statement (expression) children
        for field, value in ast.iter_fields(stmt):
            if isinstance(value, list) and value \
                    and isinstance(value[0], ast.stmt):
                _scan_stmts(value, gated, pvars, violations, path)
            elif isinstance(value, list):
                for v in value:
                    if isinstance(v, ast.excepthandler):
                        _scan_stmts(v.body, gated, pvars, violations,
                                    path)
                    elif isinstance(v, ast.AST):
                        _check_calls(v, gated, pvars, violations, path)
            elif isinstance(value, ast.AST):
                _check_calls(value, gated, pvars, violations, path)


def test_pvar_only_files_have_no_ungated_sites():
    for rel in PVAR_ONLY:
        path = os.path.join(REPO, rel)
        tree = ast.parse(open(path).read(), filename=rel)
        pvars = _module_pvars(tree)
        assert pvars, f"{rel}: expected module-level pvar registrations"
        violations = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_stmts(node.body, False, pvars, violations, rel)
        assert not violations, "\n".join(violations)


def test_pipeline_and_fusion_emit_sites_are_gated():
    checked_any_gate = 0
    for rel in CHECKED:
        path = os.path.join(REPO, rel)
        tree = ast.parse(open(path).read(), filename=rel)
        pvars = _module_pvars(tree)
        assert pvars, f"{rel}: expected module-level pvar registrations"
        violations = []
        # scan only function bodies (module scope runs once at import)
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                _scan_stmts(node.body, False, pvars, violations, rel)
        assert not violations, "\n".join(violations)
        # non-vacuous: each file must actually contain a gated emit
        src = open(path).read()
        assert "_obs.enabled" in src and "_obs.record" in src, (
            f"{rel}: expected at least one _obs.enabled-gated "
            f"_obs.record emit site")
        checked_any_gate += 1
    assert checked_any_gate == len(CHECKED)


def test_gating_checker_catches_violations():
    """The checker itself must reject an ungated emit (guards against
    the static test rotting into a rubber stamp)."""
    bad = (
        "import time\n"
        "from .. import obs as _obs\n"
        "from ..mca import pvar\n"
        "_ok = pvar.counter('x')\n"
        "def hot(journal):\n"
        "    _ok.add()\n"                      # fine: module-level pvar
        "    journal.record('op', 'l', 0, 0)\n"  # VIOLATION: ungated
        "    local = pvar.counter('y')\n"        # VIOLATION: per-call
        "    local.add()\n"                      # VIOLATION: non-module
    )
    tree = ast.parse(bad)
    pvars = _module_pvars(tree)
    violations = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            _scan_stmts(node.body, False, pvars, violations, "bad.py")
    assert len(violations) == 3, violations

    good = (
        "from .. import obs as _obs\n"
        "from ..mca import pvar\n"
        "_ok = pvar.counter('x')\n"
        "def hot(journal):\n"
        "    _ok.add()\n"
        "    if _obs.enabled:\n"
        "        journal.record('op', 'l', 0, 0)\n"
        "def hot2(journal):\n"
        "    if not _obs.enabled:\n"
        "        return 1\n"
        "    journal.record('op', 'l', 0, 0)\n"
    )
    tree = ast.parse(good)
    violations = []
    for node in ast.walk(tree):
        if isinstance(node, ast.FunctionDef):
            _scan_stmts(node.body, False, _module_pvars(tree),
                        violations, "good.py")
    assert not violations, violations
