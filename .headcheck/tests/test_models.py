"""Flagship model tests: sharded-vs-single-device parity.

The simulator-backend strategy of SURVEY §4: the same SPMD program runs
on a 1-device mesh (every axis size 1 — the dense reference) and on
real multi-device layouts; losses and post-step losses must agree.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from ompi_release_tpu.models import transformer as tfm
from ompi_release_tpu.parallel.mesh_axes import build_parallel_mesh

CFG = dict(
    vocab=32, d_model=16, n_layers=2, n_heads=4, head_dim=4, d_ff=32,
    max_seq=16, dtype=jnp.float32,
)


def make_batch(rng, b, s, vocab):
    tokens = rng.randint(0, vocab, size=(b, s)).astype(np.int32)
    targets = np.roll(tokens, -1, axis=1)
    return tokens, targets


def run_loss(cfg, mesh, params, tokens, targets):
    fwd = tfm.make_forward(cfg, mesh)
    p = tfm.shard_params(params, cfg, mesh)
    sh = tfm.make_batch_sharding(mesh)
    return float(fwd(p, jax.device_put(tokens, sh),
                     jax.device_put(targets, sh)))


def run_step(cfg, mesh, params, tokens, targets, lr=0.1):
    opt = optax.sgd(lr)
    step = tfm.make_train_step(cfg, mesh, opt)
    p = tfm.shard_params(params, cfg, mesh)
    opt_state = jax.jit(opt.init)(p)
    sh = tfm.make_batch_sharding(mesh)
    tok = jax.device_put(tokens, sh)
    tgt = jax.device_put(targets, sh)
    p, opt_state, loss0 = step(p, opt_state, tok, tgt)
    _, _, loss1 = step(p, opt_state, tok, tgt)
    return float(loss0), float(loss1)


@pytest.fixture(scope="module")
def setup():
    cfg = tfm.ModelConfig(**CFG)
    params = jax.device_get(
        tfm.init_params(jax.random.PRNGKey(0), cfg)
    )
    rng = np.random.RandomState(0)
    tokens, targets = make_batch(rng, 8, 16, cfg.vocab)
    mesh1 = build_parallel_mesh(devices=jax.devices()[:1])
    ref_loss = run_loss(cfg, mesh1, params, tokens, targets)
    return cfg, params, tokens, targets, mesh1, ref_loss


def test_loss_is_finite_and_reasonable(setup):
    cfg, params, tokens, targets, mesh1, ref = setup
    assert np.isfinite(ref)
    # random init ~ uniform over vocab
    assert abs(ref - np.log(cfg.vocab)) < 1.0


@pytest.mark.parametrize(
    "axes",
    [
        dict(dp=2), dict(tp=2), dict(sp=2), dict(dp=2, tp=2),
        dict(dp=2, sp=2, tp=2), dict(dp=2, pp=2, tp=2),
        dict(pp=2, sp=2, tp=2),
    ],
    ids=lambda a: "x".join(f"{k}{v}" for k, v in a.items()),
)
def test_sharded_loss_matches_single_device(setup, axes):
    cfg, params, tokens, targets, mesh1, ref = setup
    n = int(np.prod(list(axes.values())))
    if "pp" in axes:
        cfg = tfm.ModelConfig(**{**CFG, "microbatches": 4})
        ref = run_loss(cfg, mesh1, params, tokens, targets)
    mesh = build_parallel_mesh(devices=jax.devices()[:n], **axes)
    got = run_loss(cfg, mesh, params, tokens, targets)
    assert got == pytest.approx(ref, rel=1e-4, abs=1e-5)


def test_train_step_parity_dp_sp_tp(setup):
    cfg, params, tokens, targets, mesh1, _ = setup
    ref0, ref1 = run_step(cfg, mesh1, params, tokens, targets)
    mesh = build_parallel_mesh(devices=jax.devices(), dp=2, sp=2, tp=2)
    got0, got1 = run_step(cfg, mesh, params, tokens, targets)
    assert got0 == pytest.approx(ref0, rel=1e-4)
    assert got1 == pytest.approx(ref1, rel=1e-3, abs=1e-4)
    assert ref1 < ref0  # it actually learns


def test_train_step_parity_full_mesh_pp(setup):
    cfg, params, tokens, targets, mesh1, _ = setup
    cfg = tfm.ModelConfig(**{**CFG, "microbatches": 2})
    ref0, ref1 = run_step(cfg, mesh1, params, tokens, targets)
    mesh = build_parallel_mesh(devices=jax.devices(), dp=2, pp=2, tp=2)
    got0, got1 = run_step(cfg, mesh, params, tokens, targets)
    assert got0 == pytest.approx(ref0, rel=1e-4)
    assert got1 == pytest.approx(ref1, rel=1e-3, abs=1e-4)


class TestMoE:
    def test_moe_loss_parity_ep2(self):
        cfg = tfm.ModelConfig(**{**CFG, "n_experts": 4,
                                 "capacity_factor": 4.0})
        params = jax.device_get(
            tfm.init_params(jax.random.PRNGKey(1), cfg)
        )
        rng = np.random.RandomState(1)
        tokens, targets = make_batch(rng, 8, 16, cfg.vocab)
        mesh1 = build_parallel_mesh(devices=jax.devices()[:1])
        ref = run_loss(cfg, mesh1, params, tokens, targets)
        mesh = build_parallel_mesh(devices=jax.devices()[:4], ep=2, tp=2)
        got = run_loss(cfg, mesh, params, tokens, targets)
        assert got == pytest.approx(ref, rel=1e-4, abs=1e-5)

    def test_moe_train_step_runs(self):
        cfg = tfm.ModelConfig(**{**CFG, "n_experts": 4,
                                 "capacity_factor": 4.0})
        params = jax.device_get(
            tfm.init_params(jax.random.PRNGKey(2), cfg)
        )
        rng = np.random.RandomState(2)
        tokens, targets = make_batch(rng, 8, 16, cfg.vocab)
        mesh = build_parallel_mesh(devices=jax.devices(), dp=2, ep=2, tp=2)
        l0, l1 = run_step(cfg, mesh, params, tokens, targets)
        assert np.isfinite(l0) and np.isfinite(l1)
        assert l1 < l0


def test_flash_attention_path_matches_ring(setup):
    """Forcing the Pallas flash path must agree with ring attention.
    (Off-TPU this runs the interpret-mode kernels with the vma checker
    gated off in _loss_spmd — the jax HLO interpreter's dynamic_slice
    vma check rejects valid interpret-mode pallas; see _loss_spmd.)"""
    cfg_ring, params, tokens, targets, mesh1, ref = setup
    cfg_flash = tfm.ModelConfig(**{**CFG, "attn_impl": "flash"})
    got = run_loss(cfg_flash, mesh1, params, tokens, targets)
    assert got == pytest.approx(ref, rel=1e-4, abs=1e-5)


def test_remat_train_step_matches_plain():
    """cfg.remat=True must not change the training math (loss parity
    with the plain config on one step)."""
    cfg_a = tfm.ModelConfig(**{**CFG, "microbatches": 2})
    cfg_b = tfm.ModelConfig(**{**CFG, "microbatches": 2, "remat": True})
    params = jax.device_get(tfm.init_params(jax.random.PRNGKey(3), cfg_a))
    rng = np.random.RandomState(3)
    tokens, targets = make_batch(rng, 8, 16, cfg_a.vocab)
    mesh = build_parallel_mesh(devices=jax.devices()[:4], pp=2, tp=2)
    la = run_loss(cfg_a, mesh, params, tokens, targets)
    lb = run_loss(cfg_b, mesh, params, tokens, targets)
    assert la == pytest.approx(lb, rel=1e-5)
    l0, l1 = run_step(cfg_b, mesh, params, tokens, targets)
    assert np.isfinite(l0) and l1 < l0


class TestMixer:
    """Second model family (TpuMixer): the all-matmul MLP-Mixer over
    the same dp/tp substrate — sharded parity + learning."""

    def _setup(self):
        from ompi_release_tpu.models import mixer as mx

        cfg = mx.MixerConfig(n_patches=16, d_model=32, d_token=16,
                             d_channel=64, n_layers=2, n_classes=8,
                             dtype=jnp.float32)
        params = jax.device_get(mx.init_params(jax.random.PRNGKey(0), cfg))
        rng = np.random.RandomState(0)
        patches = rng.randn(8, 16, 32).astype(np.float32)
        labels = rng.randint(0, 8, size=(8,)).astype(np.int32)
        return mx, cfg, params, patches, labels

    def _loss(self, mx, cfg, mesh, params, patches, labels):
        fwd = mx.make_forward(cfg, mesh)
        p = mx.shard_params(params, cfg, mesh)
        sh = mx.make_batch_sharding(mesh)
        lbl_sh = jax.device_put(labels, sh)
        return float(fwd(p, jax.device_put(patches, sh), lbl_sh))

    def test_sharded_loss_matches_single_device(self):
        mx, cfg, params, patches, labels = self._setup()
        mesh1 = build_parallel_mesh(devices=jax.devices()[:1])
        ref = self._loss(mx, cfg, mesh1, params, patches, labels)
        assert abs(ref - np.log(cfg.n_classes)) < 1.0  # ~uniform init
        for axes in (dict(dp=2), dict(tp=2), dict(dp=2, tp=2),
                     dict(dp=2, tp=4)):
            n = int(np.prod(list(axes.values())))
            mesh = build_parallel_mesh(devices=jax.devices()[:n], **axes)
            got = self._loss(mx, cfg, mesh, params, patches, labels)
            assert got == pytest.approx(ref, rel=1e-4), axes

    def test_train_step_learns_and_matches(self):
        mx, cfg, params, patches, labels = self._setup()
        mesh1 = build_parallel_mesh(devices=jax.devices()[:1])
        mesh = build_parallel_mesh(devices=jax.devices()[:4], dp=2, tp=2)

        def run(mesh):
            opt = optax.sgd(0.5)
            step = mx.make_train_step(cfg, mesh, opt)
            p = mx.shard_params(params, cfg, mesh)
            opt_state = jax.jit(opt.init)(p)
            sh = mx.make_batch_sharding(mesh)
            pt = jax.device_put(patches, sh)
            lb = jax.device_put(labels, sh)
            p, opt_state, l0 = step(p, opt_state, pt, lb)
            _, _, l1 = step(p, opt_state, pt, lb)
            return float(l0), float(l1)

        ref0, ref1 = run(mesh1)
        got0, got1 = run(mesh)
        assert ref1 < ref0  # it learns
        assert got0 == pytest.approx(ref0, rel=1e-4)
        assert got1 == pytest.approx(ref1, rel=1e-3, abs=1e-4)

    def test_unsupported_axes_rejected(self):
        mx, cfg, params, patches, labels = self._setup()
        mesh = build_parallel_mesh(devices=jax.devices()[:4], pp=2, tp=2)
        with pytest.raises(ValueError):
            mx.make_forward(cfg, mesh)

    def test_default_bf16_dtype_runs(self):
        """The default (bfloat16) config trains without dtype drift:
        params keep their dtype across steps (no f32 promotion)."""
        from ompi_release_tpu.models import mixer as mx

        cfg = mx.MixerConfig(n_patches=8, d_model=16, d_token=8,
                             d_channel=32, n_layers=1, n_classes=4)
        params = mx.init_params(jax.random.PRNGKey(1), cfg)
        mesh = build_parallel_mesh(devices=jax.devices()[:2], tp=2)
        opt = optax.sgd(0.1)
        step = mx.make_train_step(cfg, mesh, opt)
        p = mx.shard_params(params, cfg, mesh)
        opt_state = jax.jit(opt.init)(p)
        rng = np.random.RandomState(1)
        patches = rng.randn(4, 8, 16).astype(np.float32)
        labels = rng.randint(0, 4, size=(4,)).astype(np.int32)
        sh = mx.make_batch_sharding(mesh)
        p2, _, loss = step(p, opt_state, jax.device_put(patches, sh),
                           jax.device_put(labels, sh))
        assert np.isfinite(float(loss))
        for a, b in zip(jax.tree.leaves(p), jax.tree.leaves(p2)):
            assert a.dtype == b.dtype  # no silent promotion
